# CI entry points — `make verify` is the PR gate (lint + tier-1 tests).
#
#   make lint         kschedlint AST rules + Level-3 program-coverage
#                     sweep over the library, tools, bench (every
#                     jit/pallas_call/shard_map site registered or
#                     waived; prints the L3 summary line)
#   make test         tier-1 pytest (ROADMAP.md command; CPU, 8-dev mesh)
#   make chaos-smoke  short fixed-seed chaos soak (fault injection +
#                     degradation ladder + restore + determinism check;
#                     docs/robustness.md)
#   make obs-smoke    short chaos soak serving live /metricsz; scrapes
#                     its own endpoint and asserts the served counters
#                     reconcile exactly with the RoundRecord totals,
#                     AND that the seeded solver faults produced a
#                     flight dump carrying the stall detector's
#                     structured reason + telemetry tail
#                     (docs/observability.md)
#   make pipeline-smoke  short double-buffered chaos soak asserting
#                     bit-identical placements across the sync,
#                     pipelined, and pipelined+device-resident service
#                     loops, including mid-flight rung degradation
#                     (docs/round_pipeline.md)
#   make tenant-smoke 16-cell multi-tenant soak: one warm batched-solver
#                     process, mixed cell sizes, chaos injected into ONE
#                     tenant — asserts per-tenant placements bit-identical
#                     to each tenant run in isolation, zero cross-tenant
#                     interference in the round trace, and reports
#                     per-tenant p50/p99 (docs/multitenancy.md)
#   make recovery-smoke  state-integrity soak: seeded device-buffer
#                     corruption + two mid-soak kill-and-restores vs a
#                     clean control run — asserts 100% corruption
#                     detection (fingerprint audits), zero false
#                     positives, warm delta-sized restores, and
#                     bit-identical placements (docs/robustness.md)
#   make shard-smoke  multi-chip rung churn soak on the virtual
#                     8-device CPU mesh: sharded placements
#                     bit-identical to the single-chip scan-CSR arm
#                     over the same layout, delta-sized sharded plan
#                     syncs after warm-up (zero layout rebuilds, zero
#                     build_sharded_plan argsorts), chaos containment
#                     via the sharded -> jax -> cpu_ref ladder
#                     (docs/sharding.md)
#   make bench-gate   check BENCH_TRAJECTORY.jsonl: fail if any config's
#                     newest p50 regressed >15% vs its previous entry,
#                     or its supersteps_p50 regressed >25% (+8 slack)
#                     for series that carry it — the churn/event path
#                     (tools/bench_compare.py; append runs with
#                     `python tools/bench_compare.py append ... --from-bench`)
#   make verify       lint, then tests, then the chaos + obs smokes
#   make baseline     re-accept current lint violations (ratchet; avoid —
#                     fix or suppress inline instead, docs/static_analysis.md)

SHELL := /bin/bash

PY ?= python
LINT_PATHS = ksched_tpu tools bench.py

.PHONY: lint test chaos-smoke obs-smoke pipeline-smoke tenant-smoke recovery-smoke shard-smoke bench-gate verify baseline

lint:
	$(PY) -m tools.kschedlint --coverage $(LINT_PATHS)

chaos-smoke:
	timeout -k 10 300 env JAX_PLATFORMS=cpu $(PY) tools/soak.py --chaos \
	  --rounds 96 --chunk 32 --seed 0 --machines 6 --slots 8 \
	  --chaos-restore-every 48 --verify-determinism

obs-smoke:
	rm -rf /tmp/ksched_obs_smoke_flight
	timeout -k 10 300 env JAX_PLATFORMS=cpu $(PY) tools/soak.py --chaos \
	  --rounds 64 --chunk 32 --seed 3 --machines 6 --slots 8 \
	  --chaos-restore-every 0 --metrics-port 0 \
	  --flight-dir /tmp/ksched_obs_smoke_flight --solver-outage-prob 0.08 \
	  --assert-stall-flight

pipeline-smoke:
	timeout -k 10 300 env JAX_PLATFORMS=cpu $(PY) tools/soak.py --chaos \
	  --rounds 64 --chunk 32 --seed 5 --machines 6 --slots 8 \
	  --chaos-restore-every 32 --verify-loop-parity

tenant-smoke:
	timeout -k 10 570 env JAX_PLATFORMS=cpu $(PY) tools/soak.py \
	  --tenants 16 --rounds 40 --seed 0 --chaos-tenant 0

recovery-smoke:
	timeout -k 10 300 env JAX_PLATFORMS=cpu $(PY) tools/soak.py --chaos \
	  --rounds 512 --chunk 128 --seed 11 --machines 6 --slots 8 \
	  --chaos-restore-every 128 --verify-recovery

shard-smoke:
	timeout -k 10 300 env JAX_PLATFORMS=cpu $(PY) tools/shard_smoke.py \
	  --machines 6 --tasks 48 --rounds 24 --warmup 4 --devices 8 --seed 7

bench-gate:
	$(PY) tools/bench_compare.py gate BENCH_TRAJECTORY.jsonl

test:
	set -o pipefail; rm -f /tmp/_t1.log; \
	timeout -k 10 1100 env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q \
	  -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
	  -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; \
	rc=$${PIPESTATUS[0]}; \
	echo DOTS_PASSED=$$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$$' /tmp/_t1.log | tr -cd . | wc -c); \
	exit $$rc

verify: lint test chaos-smoke obs-smoke pipeline-smoke tenant-smoke recovery-smoke shard-smoke

baseline:
	$(PY) -m tools.kschedlint --write-baseline $(LINT_PATHS)
