"""Long-chain soak: thousands of device-resident rounds with churn and
elastic machine membership, verifying state invariants at checkpoints.

Catches classes of bugs the short benchmark chains cannot: slow state
drift (pu_running vs actual placements), convergence decay as the class
mix wanders, and accounting leaks across enable/disable cycles.

Usage: python tools/soak.py [--rounds 4096] [--tasks 20000] [--cpu]
       python tools/soak.py --preempt --checkpoint-every 4
       python tools/soak.py --chaos --rounds 512 --seed 0
Exit code 0 = all checkpoints clean.

--preempt runs the soak in stability-aware preemption mode (hybrid
incremental + full tiered re-solves, the coco50k-preempt regime).
--checkpoint-every N additionally round-trips the cluster through
save/load_device_checkpoint every N chunks MID-SOAK — the restored
cluster must be bit-identical and the soak continues on it (restart
under churn at scale, not the unit test's toy shape; SURVEY §5
"device-side graph state reconstructible at any time").

--chaos runs the OTHER soak: the event-path SchedulerService under a
seeded fault schedule (runtime/chaos.py) — control-plane outages,
dropped binding POSTs, machine heartbeat flaps, forced solver faults
(non-convergence / backend exceptions / NaN'd costs) — with mid-soak
kill-and-restore from a service checkpoint. It asserts, every chunk:
zero scheduler crashes (any exception fails the soak), supply/binding/
capacity invariants, and at the end that every injected fault is
accounted for in the per-round RoundRecord counters. --verify-determinism
runs the whole soak twice and requires bit-identical final placements
and fault totals. `make chaos-smoke` is the short fixed-seed CI entry.
"""

import argparse
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def check_service_invariants(svc, where: str) -> None:
    """The event-path soak's state invariants: supply conservation in
    the flow graph, binding/table consistency, per-PU capacity, and no
    binding onto a machine the resource map no longer holds."""
    from ksched_tpu.data import TaskState

    sched = svc.scheduler
    assert sched.gm.sink_node.excess == -len(sched.gm.task_to_node), (
        f"supply invariant broken {where}: sink excess "
        f"{sched.gm.sink_node.excess} vs {len(sched.gm.task_to_node)} tasks"
    )
    per_pu: dict = {}
    for tid, rid in sched.task_bindings.items():
        rs = svc.resource_map.find(rid)
        assert rs is not None, f"binding onto missing resource {rid} {where}"
        td = svc.task_map.find(tid)
        assert td is not None and td.state == TaskState.RUNNING, (
            f"bound task {tid} not RUNNING {where}"
        )
        assert tid in svc.task_to_pod, f"bound task {tid} missing pod map {where}"
        per_pu[rid] = per_pu.get(rid, 0) + 1
    for rid, n in per_pu.items():
        assert n <= svc.max_tasks_per_pu, (
            f"PU {rid} over capacity ({n} > {svc.max_tasks_per_pu}) {where}"
        )
    for pod_id, tid in svc.pod_to_task.items():
        assert svc.task_to_pod.get(tid) == pod_id, (
            f"pod map asymmetry for {pod_id} {where}"
        )


def reconcile_obs(served: dict, tracer, injector) -> None:
    """Assert the LIVE Prometheus text (scraped over HTTP) agrees
    exactly with the soak's two other accounting surfaces: the
    injector's deterministic Counter and the summed RoundRecord JSONL.
    Any drift between what the registry served and what the rounds
    recorded is a bug in the publication path."""

    def served_value(name, **labels):
        return served.get((name, tuple(sorted(labels.items()))), 0.0)

    for kind, n in injector.counters.items():
        got = served_value("ksched_chaos_injected_total", kind=kind)
        assert got == n, f"served chaos_injected[{kind}]={got} != injector {n}"
    attributed: dict = {}
    for rec in tracer.records:
        for k, v in rec.faults_injected.items():
            attributed[k] = attributed.get(k, 0) + v
    for kind, n in attributed.items():
        got = served_value("ksched_faults_attributed_total", kind=kind)
        assert got == n, f"served faults_attributed[{kind}]={got} != records {n}"
    checks = {
        "ksched_retries_total": sum(r.retries for r in tracer.records),
        "ksched_round_degradations_total": sum(
            r.degradations for r in tracer.records
        ),
        "ksched_deadline_misses_total": sum(
            1 for r in tracer.records if r.deadline_miss
        ),
        "ksched_machines_lost_total": sum(r.machines_lost for r in tracer.records),
        "ksched_scheduled_tasks_total": sum(
            r.num_scheduled for r in tracer.records
        ),
    }
    for name, want in checks.items():
        got = served_value(name)
        assert got == want, f"served {name}={got} != summed records {want}"
    kinds = {
        "noop": sum(1 for r in tracer.records if r.noop_round),
        "idle": sum(
            1 for r in tracer.records if r.solver_rung == -1 and not r.noop_round
        ),
    }
    kinds["sched"] = len(tracer.records) - kinds["noop"] - kinds["idle"]
    for kind, want in kinds.items():
        got = served_value("ksched_rounds_total", kind=kind)
        assert got == want, f"served rounds_total[{kind}]={got} != {want}"


def run_chaos_soak(args, log=print) -> dict:
    """Drive the SchedulerService for args.rounds rounds under a seeded
    fault schedule, single-threaded and in logical time (1 round = 1 s
    of heartbeat clock) so the whole run is deterministic. Returns the
    final placements and fault totals for cross-run comparison.

    The run gets a PRIVATE metrics registry (scoped_registry) so its
    counters start from zero — the determinism double-run would
    otherwise accumulate in the process registry. With --metrics-port
    the registry is served live during the run and scraped back over
    HTTP at the end; reconcile_obs then asserts the served text, the
    injector totals, and the summed RoundRecords agree exactly."""
    from ksched_tpu.obs import scoped_registry

    with scoped_registry() as reg:
        return _run_chaos_soak_in_registry(args, reg, log)


def _run_chaos_soak_in_registry(args, reg, log=print) -> dict:
    from ksched_tpu.obs import DeviceProfiler, MetricsServer, set_profiler
    from ksched_tpu.utils import seed_rng

    seed_rng(args.seed)  # task/job/machine ids come from the global RNG
    set_profiler(DeviceProfiler())  # per-run solve/export accounting
    server = None
    # getattr: callers (tests) build a bare Namespace without obs flags
    metrics_port = getattr(args, "metrics_port", None)
    if metrics_port is not None:
        server = MetricsServer(port=metrics_port, registry=reg)
        log(f"metrics: {server.url}/metricsz", flush=True)
    try:
        return _chaos_soak_body(args, reg, server, log)
    finally:
        # an invariant/reconcile assertion mid-run must not leak the
        # HTTP thread or leave the module profiler pinned to this run's
        # (popped) scoped registry for later in-process callers
        set_profiler(None)
        if server is not None:
            server.stop()


def _chaos_soak_body(args, reg, server, log=print) -> dict:
    from ksched_tpu.cli import SchedulerService
    from ksched_tpu.cluster import NodeEvent, PodEvent, SyntheticClusterAPI
    from ksched_tpu.obs import dump_registry, scrape
    from ksched_tpu.runtime import (
        ChaosClusterAPI,
        ChaosPolicy,
        FaultInjector,
        RoundTracer,
    )
    from ksched_tpu.solver.select import make_backend

    corruption = bool(getattr(args, "corruption", False))
    if getattr(args, "control_clean_policy", False):
        # the recovery soak's clean control arm: zero faults of any
        # kind, same seed — the bit-identical baseline the corruption
        # arm must match after detection + repair
        policy = ChaosPolicy(seed=args.seed)
    elif corruption:
        # the recovery soak isolates the state-corruption fault domains
        # (device bit flips + WAL damage at kill points) so its clean
        # control arm is comparable bit-for-bit; the mixed-domain fault
        # schedule stays covered by chaos/obs/pipeline smokes
        policy = ChaosPolicy(
            seed=args.seed,
            device_corrupt_prob=0.25,
            wal_corrupt_prob=float(getattr(args, "wal_chaos", 0.0)),
        )
    else:
        policy = ChaosPolicy(
            seed=args.seed,
            api_outage_prob=0.04,
            api_outage_rounds=(1, 3),
            binding_drop_prob=0.08,
            machine_flap_prob=0.008,
            machine_flap_rounds=(2, 5),
            solver_fault_prob=0.06,
            solver_total_outage_prob=getattr(args, "solver_outage_prob", None)
            if getattr(args, "solver_outage_prob", None) is not None
            else 0.01,
        )
    injector = FaultInjector(policy)
    api = ChaosClusterAPI(SyntheticClusterAPI(), injector)
    tracer = RoundTracer()
    hb_timeout_s = 2.5  # a 3-round flap kills a machine; 2-round flaps survive

    # optional flight recorder (the obs smoke's stall-dump assertion):
    # NOOP rounds auto-dump the ring, and each dump embeds the soltel
    # stall ring — the structured reasons + telemetry tails the
    # degradation ladder deposited (docs/observability.md)
    flight = None
    span_tracer = None
    flight_dir = getattr(args, "flight_dir", None)
    if flight_dir:
        from ksched_tpu.obs import FlightRecorder, SpanTracer
        from ksched_tpu.obs import soltel

        soltel.reset_stalls()  # assert THIS run's stalls, not a prior run's
        flight = FlightRecorder(
            capacity=32, dump_dir=flight_dir, registry=reg,
            min_rounds_between_dumps=8,
        )
        span_tracer = SpanTracer().install()

    pipeline = getattr(args, "loop", "sync") == "pipelined"
    device_resident = bool(getattr(args, "device_resident", False))
    if getattr(args, "corruption", False):
        device_resident = True  # the poison scatter needs a device mirror

    audit_every = int(getattr(args, "audit_every", 0) or 0)
    if corruption:
        # corruption mode pins the cadence to 1: the soak's acceptance
        # (every flip detected the round it happens, divergences ==
        # injected flips) is only well-defined per-round — a sparser
        # cadence would collapse multiple flips into one detection
        audit_every = 1

    def make_service():
        return SchedulerService(
            api,
            max_tasks_per_pu=args.slots,
            backend=make_backend(args.chaos_backend),
            backend_name=args.chaos_backend,
            injector=injector,
            tracer=tracer,
            round_deadline_s=30.0,
            flight=flight,
            span_tracer=span_tracer,
            pipeline=pipeline,
            device_resident=device_resident,
            audit_every=audit_every,
        )

    svc = make_service()
    svc.enable_heartbeats(machine_timeout_s=hb_timeout_s, task_timeout_s=1e9)
    svc.init_topology(fake_machines=args.machines, pus_per_core=2)

    wrng = np.random.default_rng(np.random.SeedSequence([args.seed, 0xC0C0]))
    pod_seq = 0
    pending_rejoin: list = []  # (due_round, node_id)
    cooldown = 16  # fault-free tail so dropped bindings settle
    total_rounds = args.rounds + cooldown
    restores = 0
    warm_restores = 0
    from collections import Counter as _Counter

    integrity_totals: _Counter = _Counter()  # summed across restores
    all_latencies: list = []  # round latencies summed across restores
    awaiting_recovery = False  # assert the first post-restore SOLVED round
    restore_had_warm_solver = False
    restore_caps = (0, 0)  # pow2 buckets at restore (growth waiver)
    restore_overflows = 0  # plan overflow count at restore (rebuild waiver)
    recovery_strict = 0  # recovery rounds that held the delta-kind asserts
    recovery_latencies: list = []
    t0 = time.perf_counter()

    for r in range(total_rounds):
        now = float(r)
        if r == args.rounds:
            injector.quiesce()
        injector.begin_round(r)

        # node rejoin: machines lost to heartbeat expiry come back
        while pending_rejoin and pending_rejoin[0][0] <= r:
            _, node_id = pending_rejoin.pop(0)
            svc.add_node(NodeEvent(node_id=node_id, num_cores=1, pus_per_core=2))

        # workload: seeded pod arrivals (bounded backlog) + completions
        if r < args.rounds:
            if len(svc.pod_to_task) < args.machines * args.slots * 2:
                for _ in range(int(wrng.integers(0, 4))):
                    api.submit_pod(PodEvent(pod_id=f"pod_{pod_seq}"))
                    pod_seq += 1
            if r % 2 == 1:
                bound = sorted(
                    p for p, t in svc.pod_to_task.items()
                    if t in svc.scheduler.task_bindings
                )
                if bound:
                    k = int(wrng.integers(1, min(5, len(bound)) + 1))
                    for j in sorted(
                        int(x) for x in wrng.choice(len(bound), k, replace=False)
                    ):
                        svc.complete_pod(bound[j])

        # heartbeats: every machine beats unless the injector flaps it
        nodes_before = dict(svc.node_to_machine)
        for node_id, mid in sorted(nodes_before.items()):
            if not injector.machine_silent(mid):
                svc.monitor.record_machine_heartbeat(mid, now=now)

        # Pipelined loops post round r's bindings in round r+1's
        # dispatch window — AFTER that round's poll, which would shift
        # a dropped binding's pod resurface by one poll vs the sync
        # loop. The soak drives LOGICAL rounds and asserts cross-loop
        # placement parity, so it flushes before polling: the POST
        # sequence (and every drop draw) hits the API in the same
        # order and poll alignment as the synchronous loop. The live
        # service (cli.run) keeps the overlap window instead.
        svc.flush_pending_bindings()
        pods = api.poll_pod_batch(0.005)
        svc.run_round(pods, now=now)

        # first post-restore SOLVED round: warm restores must resume on
        # the delta-sized warm path — no full_build export, delta plan
        # sync, fresh/warm solve scope — and its latency is reported
        # alongside the p50/p99 summary (the recovery-round cost class)
        if awaiting_recovery and tracer.records and tracer.records[-1].solver_rung >= 0:
            rec = tracer.records[-1]
            sol = svc.scheduler.solver
            lat = svc.round_latencies_s[-1] if svc.round_latencies_s else 0.0
            recovery_latencies.append(lat)
            scope = kind = plan_kind = "-"
            st = sol.state
            # a pow2 bucket growth landing on this very round rebuilds
            # the mirror legitimately (it would without the kill too) —
            # the delta-kind asserts apply when the bucket held
            grew = (st.n_cap, st.m_cap) != restore_caps
            if svc.restored_warm and rec.solver_rung == 0 and not rec.noop_round:
                assert sol._started, (
                    f"post-restore round {r + 1} fell back to the cold "
                    "full_build export path"
                )
                overflowed = (
                    sol.state.plan.region_overflows > restore_overflows
                )
                if sol.resident is not None and not grew and not overflowed:
                    kind = sol.resident.last_upload_kind
                    plan_kind = sol.resident.last_plan_kind
                    assert kind == "delta", (
                        f"post-restore round {r + 1} re-uploaded the problem "
                        f"wholesale (upload kind {kind!r}, want 'delta')"
                    )
                    assert plan_kind in ("delta", "clean"), (
                        f"post-restore round {r + 1} rebuilt the CSR plan "
                        f"(plan sync {plan_kind!r}, want delta/clean)"
                    )
                    recovery_strict += 1
                from ksched_tpu.runtime.checkpoint import find_jax_solver

                jaxs = find_jax_solver(sol.backend)
                if jaxs is not None and restore_had_warm_solver:
                    scope = jaxs.last_warm_scope
                    assert scope in ("warm", "fresh"), (
                        f"post-restore round {r + 1} solved COLD "
                        f"(scope {scope!r}): the warm endpoints did not survive"
                    )
            log(
                f"recovery round {r + 1}: latency={lat * 1e3:.2f}ms "
                f"upload={kind} plan_sync={plan_kind} warm_scope={scope} "
                f"(restored_warm={svc.restored_warm})",
                flush=True,
            )
            awaiting_recovery = False

        # machines the sweep expired rejoin (as fresh registrations) later
        for node_id in sorted(set(nodes_before) - set(svc.node_to_machine)):
            pending_rejoin.append((r + 5, node_id))

        if (r + 1) % args.chunk == 0 or r == total_rounds - 1:
            check_service_invariants(svc, f"at round {r + 1}")
            rec = tracer.records[-1]
            log(
                f"round {r + 1:6d}: live_pods={len(svc.pod_to_task)} "
                f"bound={len(svc.scheduler.task_bindings)} "
                f"machines={len(svc.node_to_machine)} "
                f"noop={svc.noop_rounds} restores={restores} "
                f"faults={sum(injector.counters.values())}",
                flush=True,
            )

        # mid-soak kill-and-restore: the service process "dies" and a new
        # one resumes from the checkpoint, with cold solver state
        if (
            args.chaos_restore_every
            and r < args.rounds
            and (r + 1) % args.chaos_restore_every == 0
        ):
            # this service object dies here: bank its integrity totals
            # and its latency history (round_latencies_s resets with it)
            integrity_totals.update(svc.scheduler.solver.integrity_counts)
            all_latencies.extend(svc.round_latencies_s)
            with tempfile.TemporaryDirectory() as td:
                ckpt = os.path.join(td, "svc.ckpt")
                svc.save_checkpoint(ckpt)
                # checkpoint chaos: damage the warm manifest the way a
                # torn write / dropped / duplicated WAL record would —
                # restore must DETECT it (never load garbage) and fall
                # back to the cold event replay
                wal_fault = injector.checkpoint_corruption()
                if wal_fault is not None:
                    from ksched_tpu.runtime.integrity import corrupt_wal_file

                    kind, wal_seed = wal_fault
                    corrupt_wal_file(
                        ckpt + ".wal", kind, np.random.default_rng(wal_seed)
                    )
                before_bindings = dict(svc.scheduler.task_bindings)
                before_pods = dict(svc.pod_to_task)
                svc = SchedulerService.restore(
                    api,
                    ckpt,
                    backend=make_backend(args.chaos_backend),
                    backend_name=args.chaos_backend,
                    injector=injector,
                    tracer=tracer,
                    round_deadline_s=30.0,
                    flight=flight,
                    span_tracer=span_tracer,
                    pipeline=pipeline,
                    device_resident=device_resident,
                )
            if wal_fault is not None:
                assert not svc.restored_warm, (
                    f"restore at round {r + 1} loaded a CORRUPTED warm "
                    f"manifest ({wal_fault[0]}) instead of detecting it"
                )
            else:
                assert svc.restored_warm, (
                    f"restore at round {r + 1} fell back to cold replay "
                    "with an intact warm manifest"
                )
            svc.enable_heartbeats(machine_timeout_s=hb_timeout_s, task_timeout_s=1e9)
            assert dict(svc.scheduler.task_bindings) == before_bindings, (
                f"checkpoint restore changed bindings at round {r + 1}"
            )
            assert dict(svc.pod_to_task) == before_pods, (
                f"checkpoint restore changed pod maps at round {r + 1}"
            )
            check_service_invariants(svc, f"after restore at round {r + 1}")
            restores += 1
            if svc.restored_warm:
                warm_restores += 1
            from ksched_tpu.runtime.checkpoint import find_jax_solver

            _j = find_jax_solver(svc.scheduler.solver.backend)
            restore_had_warm_solver = _j is not None and _j._prev is not None
            st = svc.scheduler.solver.state
            restore_caps = (st.n_cap, st.m_cap)
            restore_overflows = st.plan.region_overflows
            awaiting_recovery = True

    # every injected fault must be attributed to some round's record
    attributed: dict = {}
    for rec in tracer.records:
        for k, v in rec.faults_injected.items():
            attributed[k] = attributed.get(k, 0) + v
    assert attributed == dict(injector.counters), (
        f"fault accounting mismatch: rounds say {attributed}, "
        f"injector says {dict(injector.counters)}"
    )
    noops = sum(1 for rec in tracer.records if rec.noop_round)
    degr = sum(rec.degradations for rec in tracer.records)
    integrity_totals.update(svc.scheduler.solver.integrity_counts)
    all_latencies.extend(svc.round_latencies_s)
    dt = time.perf_counter() - t0
    # a pipelined loop holds the final round's POSTs for a dispatch
    # window that will never come; flush before reading api.bindings()
    svc.flush_pending_bindings()
    placements = {
        pod: api.bindings().get(pod)
        for pod in sorted(svc.pod_to_task)
        if svc.pod_to_task[pod] in svc.scheduler.task_bindings
    }
    log(
        f"CHAOS SOAK OK: {total_rounds} rounds in {dt:.1f}s — "
        f"faults={dict(sorted(injector.counters.items()))} "
        f"degradations={degr} noop_rounds={noops} restores={restores} "
        f"(warm={warm_restores}) final_bound={len(placements)}"
    )
    if integrity_totals or recovery_latencies:
        lat_ms = sorted(x * 1e3 for x in recovery_latencies)
        lats = sorted(x * 1e3 for x in all_latencies) or [0.0]
        p50 = lats[len(lats) // 2]
        p99 = lats[min(len(lats) - 1, (99 * len(lats)) // 100)]
        log(
            f"INTEGRITY: audits "
            f"divergences={integrity_totals.get('divergences', 0)} "
            f"repairs={{"
            + ", ".join(
                f"{k[len('repair_'):]}: {v}"
                for k, v in sorted(integrity_totals.items())
                if k.startswith("repair_")
            )
            + "} "
            f"device_flips={injector.counters.get('device_bit_flip', 0)}; "
            f"recovery rounds "
            f"{[f'{x:.1f}ms' for x in lat_ms]} vs service p50={p50:.1f}ms "
            f"p99={p99:.1f}ms"
        )
    if span_tracer is not None:
        span_tracer.uninstall()
    if getattr(args, "assert_stall_flight", False):
        # the solver-interior acceptance check: a seeded nonconvergence
        # fault (ladder exhaustion → NOOP round) must have produced a
        # flight dump whose solver_stalls carry the stall detector's
        # STRUCTURED reason and the final supersteps of telemetry
        import json as _json

        assert flight is not None, "--assert-stall-flight needs --flight-dir"
        assert flight.dumps, (
            "no flight dump was written: the fault schedule produced no "
            "NOOP round — raise --solver-outage-prob or the round count"
        )
        with open(flight.dumps[-1]) as fh:
            dump = _json.load(fh)
        stalls = dump.get("solver_stalls") or []
        assert stalls, "flight dump has no solver_stalls section"
        kinds = {s.get("kind") for s in stalls}
        assert kinds & {
            "injected_fault", "superstep_budget_exhausted",
            "excess_plateau", "eps_plateau", "rejected_input",
        }, f"no structured stall reason in dump (kinds={kinds})"
        with_tail = [s for s in stalls if s.get("telemetry_tail")]
        assert with_tail, (
            "no stall event carries a telemetry tail — solver-interior "
            "telemetry was not recorded before the failure"
        )
        cols = with_tail[-1].get("telemetry_cols")
        assert cols and cols[0] == "eps", f"bad telemetry cols {cols}"
        log(
            f"STALL FLIGHT OK: {len(flight.dumps)} dump(s); last carries "
            f"{len(stalls)} structured stall reason(s) "
            f"({sorted(k for k in kinds if k)}), "
            f"{len(with_tail)} with a telemetry tail of "
            f"{len(with_tail[-1]['telemetry_tail'])} supersteps"
        )
    if server is not None:
        # scrape our own live endpoint (text format over a real socket)
        # and reconcile it against the injector + the RoundRecord sums
        # (the caller's finally stops the server)
        served = scrape(server.url + "/metricsz")
        reconcile_obs(served, tracer, injector)
        log(
            f"OBS RECONCILE OK: {len(served)} served series match the "
            "injector totals and the summed RoundRecord JSONL"
        )
    obs_out = getattr(args, "obs_out", None)
    if obs_out:
        dump_registry(reg, obs_out)
        log(f"obs: registry snapshot -> {obs_out}")
    return {
        "placements": placements,
        "all_bindings": dict(api.bindings()),
        "fault_totals": dict(injector.counters),
        "noop_rounds": noops,
        "degradations": degr,
        "rounds": len(tracer.records),
        "restores": restores,
        "warm_restores": warm_restores,
        "divergences": integrity_totals.get("divergences", 0),
        "repairs": {
            k[len("repair_"):]: v
            for k, v in integrity_totals.items()
            if k.startswith("repair_")
        },
        "device_flips": injector.counters.get("device_bit_flip", 0),
        "recovery_strict": recovery_strict,
        "recovery_latencies_s": recovery_latencies,
    }


# ---------------------------------------------------------------------------
# multi-tenant soak: N cells, one warm batched solver, chaos on one
# ---------------------------------------------------------------------------


def _drive_tenant_fleet(args, tenant_ids, chaos_on, log=print):
    """Run one multi-tenant process serving ``tenant_ids`` (mixed cell
    sizes cycling 3 classes) for args.rounds logical rounds; chaos is
    injected ONLY into ``chaos_on``'s cell. Returns per-tenant round
    records, placements, and latency summaries."""
    import numpy as np

    from ksched_tpu.cluster import PodEvent
    from ksched_tpu.obs.metrics import Registry
    from ksched_tpu.runtime.chaos import ChaosPolicy, FaultInjector
    from ksched_tpu.tenancy import MultiTenantService

    #: three cell size classes -> mixed pow2 shape buckets
    SIZES = ((3, 2, 4), (5, 2, 4), (9, 2, 8))  # (machines, pus/core, slots)
    reg = Registry()
    mts = MultiTenantService(
        registry=reg, pipeline=True, flight_dir=getattr(args, "flight_dir", None)
    )
    cells = {}
    for tid in tenant_ids:
        i = int(tid.split("_")[-1])
        machines, ppc, slots = SIZES[i % len(SIZES)]
        inj = None
        if tid == chaos_on:
            inj = FaultInjector(
                ChaosPolicy(
                    seed=args.seed + 17,
                    solver_fault_prob=0.25,
                    solver_total_outage_prob=0.1,
                )
            )
        cells[tid] = mts.add_tenant(
            tid,
            machines=machines,
            pus_per_core=ppc,
            slots=slots,
            seed=args.seed * 1000 + i,
            injector=inj,
            machine_timeout_s=1e9,  # logical-time soak: no expiry
        )
    # per-tenant seeded workloads: arrivals + completions, reproducible
    # in isolation (the parity re-runs drive the same streams)
    wrngs = {
        tid: np.random.default_rng([args.seed, int(tid.split("_")[-1])])
        for tid in tenant_ids
    }
    pod_seq = {tid: 0 for tid in tenant_ids}
    import warnings as _warnings

    with _warnings.catch_warnings():
        _warnings.simplefilter("ignore")
        for r in range(args.rounds):
            for tid, cell in cells.items():
                rng = wrngs[tid]
                if len(cell.svc.pod_to_task) < 64:
                    for _ in range(int(rng.integers(0, 3))):
                        cell.api.submit_pod(
                            PodEvent(pod_id=f"{tid}_pod_{pod_seq[tid]}")
                        )
                        pod_seq[tid] += 1
                if r % 2 == 1:
                    bound = sorted(
                        p for p, t in cell.svc.pod_to_task.items()
                        if t in cell.svc.scheduler.task_bindings
                    )
                    if bound:
                        k = int(rng.integers(1, min(3, len(bound)) + 1))
                        for j in sorted(
                            int(x) for x in rng.choice(len(bound), k, replace=False)
                        ):
                            cell.svc.complete_pod(bound[j])
            mts.run_round(now=float(r))
        mts.drain()
    out = {}
    for tid, cell in cells.items():
        recs = cell.svc.tracer.records
        out[tid] = dict(
            bindings=dict(cell.api.bindings()),
            work=[rec.solver_work for rec in recs],
            scheduled=[rec.num_scheduled for rec in recs],
            faults=sum(sum(r2.faults_injected.values()) for r2 in recs),
            degradations=sum(r2.degradations for r2 in recs),
            noops=sum(1 for r2 in recs if r2.noop_round),
            summary=cell.svc.tracer.summary(),
            tenants_seen={r2.tenant for r2 in recs},
        )
    meta = dict(
        flushes=mts.batcher.flushes,
        last_groups=mts.batcher.last_groups,
        last_lanes=mts.batcher.last_lanes,
        quarantines=reg.value("ksched_tenant_quarantines_total"),
    )
    mts.close()
    return out, meta


def run_tenant_soak(args, log=print) -> int:
    """--tenants N: the multi-tenant acceptance soak. One warm process
    serves N synthetic cells (mixed sizes across 3 cell classes) with
    chaos injected into ONE tenant, then asserts:

    - zero cross-tenant interference in the round trace: every clean
      tenant's records carry 0 faults / 0 degradations / 0 NOOPs, and
      each record is tagged with its own tenant only;
    - per-tenant placements (and per-round solver work) bit-identical
      to the same tenant run in ISOLATION — its own single-cell
      process with the same seed — for every clean tenant;
    - per-tenant p50/p99 round latency published.
    """
    import time as _time

    n = args.tenants
    tenant_ids = [f"cell_{i}" for i in range(n)]
    chaos_on = (
        tenant_ids[args.chaos_tenant]
        if 0 <= args.chaos_tenant < n
        else None
    )
    t0 = _time.perf_counter()
    multi, meta = _drive_tenant_fleet(args, tenant_ids, chaos_on, log)
    log(
        f"fleet: {n} cells x {args.rounds} rounds in "
        f"{_time.perf_counter() - t0:.1f}s — {meta['flushes']} batch "
        f"flushes, last round {meta['last_groups']} stacked program(s) "
        f"for {meta['last_lanes']} lanes, "
        f"quarantines={meta['quarantines']:.0f}"
    )
    # -- per-tenant latency + interference report -----------------------
    log(f"{'tenant':<10} {'rounds':>6} {'p50_ms':>9} {'p99_ms':>9} "
        f"{'bound':>6} {'faults':>6} {'degr':>5} {'noop':>5}")
    for tid in tenant_ids:
        m = multi[tid]
        s = m["summary"]
        log(
            f"{tid:<10} {s.get('rounds', 0):>6} "
            f"{s.get('p50_ms', 0.0):>9.2f} {s.get('p99_ms', 0.0):>9.2f} "
            f"{len(m['bindings']):>6} {m['faults']:>6} "
            f"{m['degradations']:>5} {m['noops']:>5}"
        )
    # -- zero cross-tenant interference ---------------------------------
    for tid in tenant_ids:
        m = multi[tid]
        assert m["tenants_seen"] <= {tid}, (
            f"{tid} round records carry foreign tenant tags: {m['tenants_seen']}"
        )
        if tid == chaos_on:
            continue
        assert m["faults"] == 0 and m["degradations"] == 0 and m["noops"] == 0, (
            f"cross-tenant interference: clean tenant {tid} shows "
            f"faults={m['faults']} degradations={m['degradations']} "
            f"noops={m['noops']}"
        )
    if chaos_on is not None:
        cm = multi[chaos_on]
        assert cm["faults"] > 0, (
            "chaos tenant drew no faults — raise --rounds or the fault probs"
        )
        log(
            f"chaos contained to {chaos_on}: faults={cm['faults']} "
            f"degradations={cm['degradations']} noops={cm['noops']}"
        )
    # -- isolation parity: each clean tenant vs its own solo process ----
    checked = 0
    for tid in tenant_ids:
        if tid == chaos_on:
            continue
        solo, _ = _drive_tenant_fleet(args, [tid], None, log)
        for key in ("bindings", "work", "scheduled"):
            assert solo[tid][key] == multi[tid][key], (
                f"isolation parity broken for {tid}: {key} differs "
                f"between the {n}-cell process and the solo run"
            )
        checked += 1
    log(
        f"TENANT SOAK OK: {checked} clean tenants bit-identical to their "
        f"isolated runs; zero cross-tenant interference in the round trace"
    )
    return 0


def chaos_main(args) -> int:
    import copy

    if getattr(args, "verify_recovery", False):
        # The state-integrity acceptance check (make recovery-smoke):
        # a corruption soak (seeded device bit flips, per-round audits,
        # mid-soak kill-and-restores through the warm manifest) must be
        # bit-identical to a CLEAN control run with no corruption and
        # no kills — every injected corruption detected and repaired
        # the round it happened, every restore resuming warm on the
        # delta-sized path — and the clean control run must report
        # ZERO divergence events (no false positives).
        rec_args = copy.copy(args)
        rec_args.corruption = True
        rec_args.device_resident = True
        print("--- recovery arm: corruption + kills ---", flush=True)
        recovered = run_chaos_soak(rec_args)
        ctl_args = copy.copy(args)
        ctl_args.corruption = False
        ctl_args.audit_every = 1
        ctl_args.device_resident = True
        ctl_args.chaos_restore_every = 0
        # the control must see the same (empty) fault schedule the
        # corruption policy produces on its other domains
        ctl_args.solver_outage_prob = 0.0
        ctl_args.control_clean_policy = True
        print("--- control arm: clean, no kills ---", flush=True)
        control = run_chaos_soak(ctl_args)
        assert recovered["device_flips"] > 0, (
            "corruption soak injected no device bit flips — raise "
            "--rounds or the corrupt probability"
        )
        assert recovered["divergences"] == recovered["device_flips"], (
            f"DETECTION GAP: {recovered['device_flips']} injected flips "
            f"but only {recovered['divergences']} divergences detected"
        )
        assert sum(recovered["repairs"].values()) >= recovered["divergences"], (
            f"unrepaired divergences: {recovered['repairs']} vs "
            f"{recovered['divergences']} detections"
        )
        assert recovered["restores"] >= 2 and recovered["warm_restores"] == recovered["restores"], (
            f"expected every mid-soak kill to restore WARM: "
            f"{recovered['warm_restores']}/{recovered['restores']}"
        )
        assert recovered["recovery_strict"] >= 1, (
            "no recovery round held the strict delta-sized cost-class "
            "asserts (every restore collided with a pow2 bucket growth "
            "— move --chaos-restore-every)"
        )
        assert control["divergences"] == 0, (
            f"FALSE POSITIVES: clean control run reported "
            f"{control['divergences']} divergence event(s)"
        )
        for key in ("placements", "all_bindings"):
            assert recovered[key] == control[key], (
                f"corruption+kill soak diverged from the clean control: "
                f"{key} differs"
            )
        print(
            "RECOVERY SOAK OK: "
            f"{recovered['device_flips']} corruptions all detected within "
            f"their round and repaired ({recovered['repairs']}), "
            f"{recovered['restores']} kill-and-restores all resumed warm "
            "on the delta-sized path, placements bit-identical to the "
            "clean control run, zero false positives"
        )
        return 0

    if getattr(args, "verify_loop_parity", False):
        # The pipeline-parity acceptance check: the SAME seeded chaos
        # soak through the synchronous, pipelined, and pipelined+
        # device-resident service loops must produce bit-identical
        # placements (and identical API-side bindings once the deferred
        # POSTs flush). Fault TOTALS are compared per-domain except
        # binding drops: deferring POSTs by one dispatch window can
        # shift which re-post batch a drop draw lands on — placements
        # are unaffected (drops never touch the scheduler's graph).
        runs = {}
        for label, loop, resident in (
            ("sync", "sync", False),
            ("pipelined", "pipelined", False),
            ("device-resident", "pipelined", True),
        ):
            a = copy.copy(args)
            a.loop = loop
            a.device_resident = resident
            print(f"--- loop parity arm: {label} ---", flush=True)
            runs[label] = run_chaos_soak(a)
        base = runs["sync"]
        for label in ("pipelined", "device-resident"):
            got = runs[label]
            for key in ("placements", "all_bindings"):
                assert got[key] == base[key], (
                    f"loop mode {label!r} diverged from sync: {key} differs"
                )
            for k, v in base["fault_totals"].items():
                if k == "binding_drop":
                    continue
                assert got["fault_totals"].get(k, 0) == v, (
                    f"loop mode {label!r}: fault {k} {got['fault_totals'].get(k, 0)} != {v}"
                )
            assert got["noop_rounds"] == base["noop_rounds"], (
                f"loop mode {label!r}: noop_rounds differ "
                f"({got['noop_rounds']} != {base['noop_rounds']})"
            )
        print(
            "LOOP PARITY OK: bit-identical placements and bindings across "
            "sync / pipelined / device-resident loops "
            f"({len(base['placements'])} placements, "
            f"noop_rounds={base['noop_rounds']}, "
            f"degradations={base['degradations']})"
        )
        return 0
    got = run_chaos_soak(args)
    if args.verify_determinism:
        again = run_chaos_soak(args)
        for key in ("placements", "all_bindings", "fault_totals"):
            assert got[key] == again[key], (
                f"seed {args.seed} not deterministic: {key} differs across runs"
            )
        print("DETERMINISM OK: identical placements and fault totals across two runs")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=4096)
    ap.add_argument("--tasks", type=int, default=20_000)
    ap.add_argument("--machines", type=int, default=None,
                    help="default: 500 (device soak), 10 (chaos mode)")
    ap.add_argument("--chunk", type=int, default=256)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--preempt", action="store_true",
                    help="stability-aware preemption mode (hybrid rounds)")
    ap.add_argument("--checkpoint-every", type=int, default=0, metavar="N",
                    help="save+load+verify a device checkpoint every N "
                    "chunks and continue on the RESTORED cluster")
    ap.add_argument("--chaos", action="store_true",
                    help="event-path SchedulerService soak under a seeded "
                    "fault schedule (see module docstring)")
    ap.add_argument("--tenants", type=int, default=0, metavar="N",
                    help="multi-tenant soak: serve N synthetic cells (mixed "
                    "sizes) from ONE warm batched-solver process, chaos on "
                    "--chaos-tenant only; asserts per-tenant placements "
                    "bit-identical to each tenant run in isolation and zero "
                    "cross-tenant interference in the round trace "
                    "(make tenant-smoke)")
    ap.add_argument("--chaos-tenant", type=int, default=0, metavar="I",
                    help="tenant index the multi-tenant soak injects chaos "
                    "into (-1 = no chaos)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--slots", type=int, default=16,
                    help="chaos mode: task slots per PU")
    ap.add_argument("--chaos-backend", default="jax",
                    help="chaos mode: configured solver backend (first "
                    "ladder rung)")
    ap.add_argument("--chaos-restore-every", type=int, default=128, metavar="N",
                    help="chaos mode: kill-and-restore from a service "
                    "checkpoint every N rounds (0 = never)")
    ap.add_argument("--verify-determinism", action="store_true",
                    help="chaos mode: run twice, require identical "
                    "placements + fault totals")
    ap.add_argument("--loop", choices=["sync", "pipelined"], default="sync",
                    help="chaos mode: service round structure — "
                    "'pipelined' double-buffers rounds (solve dispatch "
                    "overlaps the previous round's binding POSTs; "
                    "docs/round_pipeline.md)")
    ap.add_argument("--device-resident", action="store_true",
                    help="chaos mode: keep the flow problem device-"
                    "resident between rounds (delta-record scatter "
                    "instead of full re-uploads)")
    ap.add_argument("--corruption", action="store_true",
                    help="chaos mode: inject state-corruption faults — "
                    "seeded device-buffer bit flips via the poison "
                    "scatter (detected by the per-round fingerprint "
                    "audit and repaired by the divergence ladder; "
                    "implies --device-resident and --audit-every 1)")
    ap.add_argument("--wal-chaos", type=float, default=0.0, metavar="P",
                    help="corruption mode: probability a kill-point "
                    "checkpoint's warm manifest is damaged (dropped/"
                    "duplicated WAL record or torn write); restore must "
                    "detect it and fall back to cold replay")
    ap.add_argument("--audit-every", type=int, default=0, metavar="N",
                    help="chaos mode: device-state integrity audit "
                    "cadence (0 = off; --corruption always pins it to 1 "
                    "— its per-round detection asserts need the "
                    "every-round cadence)")
    ap.add_argument("--verify-recovery", action="store_true",
                    help="chaos mode: the state-integrity acceptance "
                    "soak — corruption faults + mid-soak kills vs a "
                    "clean control run; asserts 100%% detection, zero "
                    "false positives, warm delta-sized restores, and "
                    "bit-identical placements (make recovery-smoke)")
    ap.add_argument("--verify-loop-parity", action="store_true",
                    help="chaos mode: run the soak through the sync, "
                    "pipelined, and pipelined+device-resident loops and "
                    "require bit-identical placements across all three "
                    "(the round-pipeline acceptance check)")
    ap.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                    help="chaos mode: serve live Prometheus text on "
                    "/metricsz during the soak (0 = ephemeral port) and "
                    "reconcile the scraped text against the RoundRecord "
                    "totals at exit (the obs smoke)")
    ap.add_argument("--obs-out", metavar="PATH", default=None,
                    help="write the metrics-registry snapshot JSON at exit")
    ap.add_argument("--flight-dir", metavar="DIR", default=None,
                    help="chaos mode: attach a flight recorder (+ span "
                    "tracer); NOOP rounds auto-dump the ring with the "
                    "solver-stall events embedded")
    ap.add_argument("--assert-stall-flight", action="store_true",
                    help="chaos mode: require >=1 flight dump whose "
                    "solver_stalls carry a structured reason and a "
                    "telemetry tail (the obs smoke's solver-interior "
                    "acceptance check)")
    ap.add_argument("--solver-outage-prob", type=float, default=None,
                    metavar="P",
                    help="chaos mode: override solver_total_outage_prob "
                    "(default 0.01); the obs smoke raises it so a NOOP "
                    "round (and its flight dump) fires within the short "
                    "soak")
    args = ap.parse_args()
    if args.machines is None:  # per-mode default (device soak vs chaos)
        args.machines = 10 if args.chaos else 500

    if args.tenants:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        return run_tenant_soak(args)

    if args.chaos:
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        return chaos_main(args)

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        from ksched_tpu.utils import force_cpu_platform

        force_cpu_platform()

    import jax
    import jax.numpy as jnp

    from ksched_tpu.costmodels.device_costs import coco_device_cost_fn
    from ksched_tpu.scheduler.device_bulk import DeviceBulkCluster
    from ksched_tpu.utils import next_pow2

    rng = np.random.default_rng(0)
    pen = rng.integers(0, 40, (args.machines, 4)).astype(np.int64)
    cost_fn = coco_device_cost_fn(pen)
    preempt_kw = {}
    if args.preempt:
        preempt_kw = dict(
            preemption=True,
            continuation_discount=8,
            preempt_every=16,
            preempt_drift=max(100, args.tasks // 5),
        )
    dev = DeviceBulkCluster(
        num_machines=args.machines,
        pus_per_machine=4,
        slots_per_pu=16,
        num_jobs=16,
        num_task_classes=4,
        task_capacity=next_pow2(args.tasks + 4096),
        class_cost_fn=cost_fn,
        supersteps=1 << 17,
        unsched_cost=2500,
        ec_cost=0,
        decode_width=2048,
        **preempt_kw,
    )
    dev.add_tasks(
        args.tasks,
        rng.integers(0, 16, args.tasks).astype(np.int32),
        rng.integers(0, 4, args.tasks).astype(np.int32),
    )
    jax.block_until_ready(dev.round())
    churn_n = max(1, args.tasks // 100)

    t_start = time.perf_counter()
    rounds_done = 0
    down: list = []
    chunk_i = 0
    while rounds_done < args.rounds:
        # elastic membership: every other chunk, toggle a random slice
        # of machines out of / back into service
        if down:
            for m in down:
                dev.set_machine_enabled(int(m), True)
            down = []
        elif chunk_i % 2 == 1:
            n_down = min(max(1, args.machines // 100), args.machines - 1)
            down = rng.choice(args.machines, n_down, replace=False).tolist()
            for m in down:
                dev.set_machine_enabled(int(m), False)
        chunk_i += 1

        this_chunk = min(args.chunk, args.rounds - rounds_done)
        stats = dev.run_steady_rounds(this_chunk, 0.01, churn_n, seed=100 + chunk_i)
        got = dev.fetch_stats(stats)
        rounds_done += this_chunk

        # ---- checkpoint invariants ----
        assert got["converged"].all(), f"non-convergence by round {rounds_done}"
        st = dev.fetch_state()
        live = np.asarray(st["live"])
        pu = np.asarray(st["pu"])
        placed_mask = live & (pu >= 0)
        recount = np.bincount(pu[placed_mask], minlength=dev.num_pus)
        pr = np.asarray(st["pu_running"])
        assert (recount == pr).all(), (
            f"pu_running drift at round {rounds_done}: "
            f"max|delta|={np.abs(recount - pr).max()}"
        )
        assert (pr <= dev.S).all(), f"slot overflow at round {rounds_done}"
        enabled = np.asarray(st["machine_enabled"])
        on_disabled = placed_mask & ~np.repeat(enabled, dev.P)[
            np.clip(pu, 0, dev.num_pus - 1)
        ]
        assert not on_disabled.any(), f"task on disabled machine at {rounds_done}"
        extra = ""
        if args.preempt and "full_round" in got:
            extra = (
                f" full={int(got['full_round'].sum())}"
                f" migrated={int(got['migrated'].sum())}"
                f" preempted={int(got['preempted'].sum())}"
            )
        print(
            f"round {rounds_done:6d}: live={int(got['live'][-1])} "
            f"placed/round={got['placed'].mean():.1f} "
            f"supersteps mean={got['supersteps'].mean():.0f} "
            f"max={int(got['supersteps'].max())} "
            f"down={len(down)}" + extra,
            flush=True,
        )

        # ---- mid-soak checkpoint round-trip: the soak CONTINUES on
        # the restored cluster, so any reconstruction defect surfaces
        # as invariant drift in later chunks ----
        if args.checkpoint_every and chunk_i % args.checkpoint_every == 0:
            from ksched_tpu.runtime.checkpoint import (
                load_device_checkpoint,
                save_device_checkpoint,
            )

            with tempfile.TemporaryDirectory() as td:
                path = os.path.join(td, "soak.npz")
                save_device_checkpoint(dev, path)
                restored = load_device_checkpoint(path, class_cost_fn=cost_fn)
            before = dev.fetch_state()
            after = restored.fetch_state()
            for k in before:
                assert np.array_equal(
                    np.asarray(before[k]), np.asarray(after[k])
                ), f"checkpoint round-trip drift in {k} at round {rounds_done}"
            dev = restored
            print(f"round {rounds_done:6d}: checkpoint round-trip OK "
                  "(soak continues on the restored cluster)", flush=True)

    dt = time.perf_counter() - t_start
    print(
        f"SOAK OK: {rounds_done} rounds in {dt:.1f}s "
        f"({dt / rounds_done * 1e3:.2f} ms/round incl verification fetches)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
