"""Long-chain soak: thousands of device-resident rounds with churn and
elastic machine membership, verifying state invariants at checkpoints.

Catches classes of bugs the short benchmark chains cannot: slow state
drift (pu_running vs actual placements), convergence decay as the class
mix wanders, and accounting leaks across enable/disable cycles.

Usage: python tools/soak.py [--rounds 4096] [--tasks 20000] [--cpu]
       python tools/soak.py --preempt --checkpoint-every 4
Exit code 0 = all checkpoints clean.

--preempt runs the soak in stability-aware preemption mode (hybrid
incremental + full tiered re-solves, the coco50k-preempt regime).
--checkpoint-every N additionally round-trips the cluster through
save/load_device_checkpoint every N chunks MID-SOAK — the restored
cluster must be bit-identical and the soak continues on it (restart
under churn at scale, not the unit test's toy shape; SURVEY §5
"device-side graph state reconstructible at any time").
"""

import argparse
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=4096)
    ap.add_argument("--tasks", type=int, default=20_000)
    ap.add_argument("--machines", type=int, default=500)
    ap.add_argument("--chunk", type=int, default=256)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--preempt", action="store_true",
                    help="stability-aware preemption mode (hybrid rounds)")
    ap.add_argument("--checkpoint-every", type=int, default=0, metavar="N",
                    help="save+load+verify a device checkpoint every N "
                    "chunks and continue on the RESTORED cluster")
    args = ap.parse_args()

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        from ksched_tpu.utils import force_cpu_platform

        force_cpu_platform()

    import jax
    import jax.numpy as jnp

    from ksched_tpu.costmodels.device_costs import coco_device_cost_fn
    from ksched_tpu.scheduler.device_bulk import DeviceBulkCluster
    from ksched_tpu.utils import next_pow2

    rng = np.random.default_rng(0)
    pen = rng.integers(0, 40, (args.machines, 4)).astype(np.int64)
    cost_fn = coco_device_cost_fn(pen)
    preempt_kw = {}
    if args.preempt:
        preempt_kw = dict(
            preemption=True,
            continuation_discount=8,
            preempt_every=16,
            preempt_drift=max(100, args.tasks // 5),
        )
    dev = DeviceBulkCluster(
        num_machines=args.machines,
        pus_per_machine=4,
        slots_per_pu=16,
        num_jobs=16,
        num_task_classes=4,
        task_capacity=next_pow2(args.tasks + 4096),
        class_cost_fn=cost_fn,
        supersteps=1 << 17,
        unsched_cost=2500,
        ec_cost=0,
        decode_width=2048,
        **preempt_kw,
    )
    dev.add_tasks(
        args.tasks,
        rng.integers(0, 16, args.tasks).astype(np.int32),
        rng.integers(0, 4, args.tasks).astype(np.int32),
    )
    jax.block_until_ready(dev.round())
    churn_n = max(1, args.tasks // 100)

    t_start = time.perf_counter()
    rounds_done = 0
    down: list = []
    chunk_i = 0
    while rounds_done < args.rounds:
        # elastic membership: every other chunk, toggle a random slice
        # of machines out of / back into service
        if down:
            for m in down:
                dev.set_machine_enabled(int(m), True)
            down = []
        elif chunk_i % 2 == 1:
            n_down = min(max(1, args.machines // 100), args.machines - 1)
            down = rng.choice(args.machines, n_down, replace=False).tolist()
            for m in down:
                dev.set_machine_enabled(int(m), False)
        chunk_i += 1

        this_chunk = min(args.chunk, args.rounds - rounds_done)
        stats = dev.run_steady_rounds(this_chunk, 0.01, churn_n, seed=100 + chunk_i)
        got = dev.fetch_stats(stats)
        rounds_done += this_chunk

        # ---- checkpoint invariants ----
        assert got["converged"].all(), f"non-convergence by round {rounds_done}"
        st = dev.fetch_state()
        live = np.asarray(st["live"])
        pu = np.asarray(st["pu"])
        placed_mask = live & (pu >= 0)
        recount = np.bincount(pu[placed_mask], minlength=dev.num_pus)
        pr = np.asarray(st["pu_running"])
        assert (recount == pr).all(), (
            f"pu_running drift at round {rounds_done}: "
            f"max|delta|={np.abs(recount - pr).max()}"
        )
        assert (pr <= dev.S).all(), f"slot overflow at round {rounds_done}"
        enabled = np.asarray(st["machine_enabled"])
        on_disabled = placed_mask & ~np.repeat(enabled, dev.P)[
            np.clip(pu, 0, dev.num_pus - 1)
        ]
        assert not on_disabled.any(), f"task on disabled machine at {rounds_done}"
        extra = ""
        if args.preempt and "full_round" in got:
            extra = (
                f" full={int(got['full_round'].sum())}"
                f" migrated={int(got['migrated'].sum())}"
                f" preempted={int(got['preempted'].sum())}"
            )
        print(
            f"round {rounds_done:6d}: live={int(got['live'][-1])} "
            f"placed/round={got['placed'].mean():.1f} "
            f"supersteps mean={got['supersteps'].mean():.0f} "
            f"max={int(got['supersteps'].max())} "
            f"down={len(down)}" + extra,
            flush=True,
        )

        # ---- mid-soak checkpoint round-trip: the soak CONTINUES on
        # the restored cluster, so any reconstruction defect surfaces
        # as invariant drift in later chunks ----
        if args.checkpoint_every and chunk_i % args.checkpoint_every == 0:
            from ksched_tpu.runtime.checkpoint import (
                load_device_checkpoint,
                save_device_checkpoint,
            )

            with tempfile.TemporaryDirectory() as td:
                path = os.path.join(td, "soak.npz")
                save_device_checkpoint(dev, path)
                restored = load_device_checkpoint(path, class_cost_fn=cost_fn)
            before = dev.fetch_state()
            after = restored.fetch_state()
            for k in before:
                assert np.array_equal(
                    np.asarray(before[k]), np.asarray(after[k])
                ), f"checkpoint round-trip drift in {k} at round {rounds_done}"
            dev = restored
            print(f"round {rounds_done:6d}: checkpoint round-trip OK "
                  "(soak continues on the restored cluster)", flush=True)

    dt = time.perf_counter() - t_start
    print(
        f"SOAK OK: {rounds_done} rounds in {dt:.1f}s "
        f"({dt / rounds_done * 1e3:.2f} ms/round incl verification fetches)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
