"""Long-chain soak: thousands of device-resident rounds with churn and
elastic machine membership, verifying state invariants at checkpoints.

Catches classes of bugs the short benchmark chains cannot: slow state
drift (pu_running vs actual placements), convergence decay as the class
mix wanders, and accounting leaks across enable/disable cycles.

Usage: python tools/soak.py [--rounds 4096] [--tasks 20000] [--cpu]
Exit code 0 = all checkpoints clean.
"""

import argparse
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rounds", type=int, default=4096)
    ap.add_argument("--tasks", type=int, default=20_000)
    ap.add_argument("--machines", type=int, default=500)
    ap.add_argument("--chunk", type=int, default=256)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        from ksched_tpu.utils import force_cpu_platform

        force_cpu_platform()

    import jax
    import jax.numpy as jnp

    from ksched_tpu.costmodels.device_costs import coco_device_cost_fn
    from ksched_tpu.scheduler.device_bulk import DeviceBulkCluster
    from ksched_tpu.utils import next_pow2

    rng = np.random.default_rng(0)
    pen = rng.integers(0, 40, (args.machines, 4)).astype(np.int64)
    dev = DeviceBulkCluster(
        num_machines=args.machines,
        pus_per_machine=4,
        slots_per_pu=16,
        num_jobs=16,
        num_task_classes=4,
        task_capacity=next_pow2(args.tasks + 4096),
        class_cost_fn=coco_device_cost_fn(pen),
        supersteps=1 << 17,
        unsched_cost=2500,
        ec_cost=0,
        decode_width=2048,
    )
    dev.add_tasks(
        args.tasks,
        rng.integers(0, 16, args.tasks).astype(np.int32),
        rng.integers(0, 4, args.tasks).astype(np.int32),
    )
    jax.block_until_ready(dev.round())
    churn_n = max(1, args.tasks // 100)

    t_start = time.perf_counter()
    rounds_done = 0
    down: list = []
    chunk_i = 0
    while rounds_done < args.rounds:
        # elastic membership: every other chunk, toggle a random slice
        # of machines out of / back into service
        if down:
            for m in down:
                dev.set_machine_enabled(int(m), True)
            down = []
        elif chunk_i % 2 == 1:
            n_down = min(max(1, args.machines // 100), args.machines - 1)
            down = rng.choice(args.machines, n_down, replace=False).tolist()
            for m in down:
                dev.set_machine_enabled(int(m), False)
        chunk_i += 1

        this_chunk = min(args.chunk, args.rounds - rounds_done)
        stats = dev.run_steady_rounds(this_chunk, 0.01, churn_n, seed=100 + chunk_i)
        got = dev.fetch_stats(stats)
        rounds_done += this_chunk

        # ---- checkpoint invariants ----
        assert got["converged"].all(), f"non-convergence by round {rounds_done}"
        st = dev.fetch_state()
        live = np.asarray(st["live"])
        pu = np.asarray(st["pu"])
        placed_mask = live & (pu >= 0)
        recount = np.bincount(pu[placed_mask], minlength=dev.num_pus)
        pr = np.asarray(st["pu_running"])
        assert (recount == pr).all(), (
            f"pu_running drift at round {rounds_done}: "
            f"max|delta|={np.abs(recount - pr).max()}"
        )
        assert (pr <= dev.S).all(), f"slot overflow at round {rounds_done}"
        enabled = np.asarray(st["machine_enabled"])
        on_disabled = placed_mask & ~np.repeat(enabled, dev.P)[
            np.clip(pu, 0, dev.num_pus - 1)
        ]
        assert not on_disabled.any(), f"task on disabled machine at {rounds_done}"
        print(
            f"round {rounds_done:6d}: live={int(got['live'][-1])} "
            f"placed/round={got['placed'].mean():.1f} "
            f"supersteps mean={got['supersteps'].mean():.0f} "
            f"max={int(got['supersteps'].max())} "
            f"down={len(down)}",
            flush=True,
        )

    dt = time.perf_counter() - t_start
    print(
        f"SOAK OK: {rounds_done} rounds in {dt:.1f}s "
        f"({dt / rounds_done * 1e3:.2f} ms/round incl verification fetches)"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
