#!/usr/bin/env python
"""Microbench: the Pallas MCMF megakernel vs the scan-based general
backends (CSR / ELL) on the 10k x 1k general graph.

The number this exists to pin down: docs/ROUND5.md section 5 measured
the scan-based general-graph solve at ~60 ms (CSR and ELL tie — both
gather/scan-bound, ~6 full-entry HBM passes + 3 global scans per
superstep) and identified the VMEM-resident megakernel as the lever
(predicted >= 5x from the gather arithmetic). This tool measures all
three backends on the same instance with the same protocol as
tools/csr_tpu_bench.py: cold solves (flow zeroed, eps=1 tightened
prices — the from-scratch solve the graph path issues per round),
completion barrier via scalar fetch.

Honesty notes baked into the output record:
- on a TPU the megakernel runs COMPILED and the record carries the
  measured ratio;
- with no TPU ambient the megakernel runs under the Pallas INTERPRETER
  (CPU) — functionally identical, bit-identical flows, but the wall
  time measures the interpreter, not the kernel, so the record marks
  the device claim "unmeasured" instead of extrapolating.

Importable seam: bench.py's `--config mcmf-mega` calls `run_bench`.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def _solve_fns(problem, max_supersteps, backends):
    """Per-backend (name -> zero-arg cold-solve callable returning
    supersteps) over prebuilt plans; plan build excluded from timing.
    Only the requested backends get their plans built/uploaded."""
    import jax
    import jax.numpy as jnp

    n = problem.num_nodes
    src = problem.src.astype(np.int32)
    dst = problem.dst.astype(np.int32)
    cap = jnp.asarray(problem.cap.astype(np.int32))
    cost = jnp.asarray(problem.cost.astype(np.int32) * np.int32(n))
    supply = jnp.asarray(problem.excess.astype(np.int32))
    m = len(src)
    eps = jnp.asarray(np.int32(1))
    zero_flow = jnp.zeros(m, jnp.int32)
    fns = {}

    if "csr" in backends or "mega" in backends:
        from ksched_tpu.solver.jax_solver import build_csr_plan

        csr_plan = build_csr_plan(src, dst, n)

    if "csr" in backends:
        from ksched_tpu.solver.jax_solver import _solve_mcmf

        csr_dev = tuple(
            jnp.asarray(x)
            for x in (
                csr_plan.s_arc, csr_plan.s_sign, csr_plan.s_src,
                csr_plan.s_dst, csr_plan.s_segstart, csr_plan.s_isstart,
                csr_plan.inv_order, csr_plan.node_first,
                csr_plan.node_last, csr_plan.node_nonempty,
            )
        )

        def run_csr():
            out = _solve_mcmf(
                cap, cost, supply, zero_flow, eps, *csr_dev,
                alpha=8, max_supersteps=max_supersteps,
            )
            jax.block_until_ready(out)
            assert bool(out[3]), "csr solve did not converge"
            return int(out[2])

        fns["csr"] = run_csr

    if "ell" in backends:
        from ksched_tpu.solver.ell_solver import (
            _plan_args, _solve_mcmf_ell, build_ell_plan,
        )

        ell_dev = _plan_args(build_ell_plan(src, dst, n))

        def run_ell():
            out = _solve_mcmf_ell(
                cap, cost, supply, zero_flow, eps, *ell_dev,
                alpha=8, max_supersteps=max_supersteps,
            )
            jax.block_until_ready(out)
            assert bool(out[3]), "ell solve did not converge"
            return int(out[2])

        fns["ell"] = run_ell

    from ksched_tpu.ops.mcmf_pallas import mcmf_loop_pallas, mega_fits_vmem
    from ksched_tpu.solver.mega_solver import build_mega_plan

    if "mega" in backends and mega_fits_vmem(2 * m):
        mega_plan = build_mega_plan(csr_plan)
        mega_dev = tuple(
            jnp.asarray(x)
            for x in (
                mega_plan.e_arc, mega_plan.e_sign, mega_plan.e_src,
                mega_plan.e_hs, mega_plan.e_he, mega_plan.e_prow,
                mega_plan.e_pcol, mega_plan.fwd_pos,
            )
        )
        interpret = jax.default_backend() != "tpu"

        def run_mega():
            out = mcmf_loop_pallas(
                cap, cost, supply, zero_flow, eps, *mega_dev,
                R=mega_plan.R, L=mega_plan.L,
                alpha=8, max_supersteps=max_supersteps,
                interpret=interpret,
            )
            jax.block_until_ready(out)
            assert bool(out[2]), "mega solve did not converge"
            return int(out[1])

        run_mega.interpret = interpret
        fns["mega"] = run_mega
    return fns


def run_bench(tasks=10_000, machines=1_000, solves=8,
              max_supersteps=4096, backends=("mega", "csr", "ell")):
    """Measure ms/solve + supersteps per backend; returns the record."""
    import jax

    import __graft_entry__ as graft

    backends = tuple(b.strip() for b in backends)
    known = ("mega", "csr", "ell")
    for b in backends:
        if b not in known:
            raise SystemExit(f"unknown backend {b!r}; choose from {known}")
    problem = graft._build_problem(num_machines=machines, tasks=tasks)
    platform = jax.devices()[0].platform
    fns = _solve_fns(problem, max_supersteps, backends)
    detail = {
        "nodes": problem.num_nodes,
        "arcs": len(problem.src),
        "entries": 2 * len(problem.src),
        "solves": solves,
        "platform": platform,
    }
    per = {}
    for name in backends:
        if name not in fns:
            # only mega can be absent: the VMEM tiling gate refused it
            detail[name] = "refused (VMEM tiling budget)"
            continue
        fn = fns[name]
        steps = fn()  # warm-up / compile, excluded from timing
        walls = []
        for _ in range(solves):
            t0 = time.perf_counter()
            steps = fn()
            walls.append((time.perf_counter() - t0) * 1e3)
        per[name] = {
            "p50_ms": round(float(np.percentile(walls, 50)), 3),
            "supersteps": steps,
        }
        if name == "mega" and getattr(fn, "interpret", False):
            per[name]["mode"] = "interpret (Pallas interpreter on CPU)"
        print(f"# {name}: {per[name]}", file=sys.stderr)
    detail.update(per)
    if "mega" in per and "csr" in per:
        ratio = per["csr"]["p50_ms"] / max(per["mega"]["p50_ms"], 1e-9)
        if platform == "tpu":
            detail["mega_vs_csr_speedup"] = round(ratio, 2)
        else:
            detail["mega_vs_csr_speedup"] = (
                f"{round(ratio, 2)}x under the CPU interpreter — the "
                ">=5x device claim is UNMEASURED (no TPU ambient)"
            )
    # headline: the first measured backend in preference order (JSON
    # null when everything was refused/excluded — never a bare NaN)
    value = next(
        (per[b]["p50_ms"] for b in ("mega", "csr", "ell") if b in per),
        None,
    )
    return {
        "metric": (
            f"p50 cold-solve latency, general-graph MCMF megakernel vs "
            f"scan backends, {tasks} tasks x {machines} machines "
            f"({problem.num_nodes} nodes, {len(problem.src)} arcs), "
            f"backend=mega/{platform}"
        ),
        "value": value,
        "unit": "ms",
        "detail": detail,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tasks", type=int, default=10_000)
    ap.add_argument("--machines", type=int, default=1_000)
    ap.add_argument("--solves", type=int, default=8)
    ap.add_argument("--max-supersteps", type=int, default=4096)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument(
        "--backends", default="mega,csr,ell",
        help="comma-separated subset of mega,csr,ell",
    )
    args = ap.parse_args()
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        from ksched_tpu.utils import force_cpu_platform

        force_cpu_platform()
    out = run_bench(
        tasks=args.tasks, machines=args.machines, solves=args.solves,
        max_supersteps=args.max_supersteps,
        backends=tuple(args.backends.split(",")),
    )
    print(json.dumps(out))


if __name__ == "__main__":
    main()
