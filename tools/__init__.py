# Makes tools/ a package so `python -m tools.kschedlint` resolves from
# any sys.path configuration (namespace-package lookup is cwd-dependent).
