#!/usr/bin/env python
"""Record the general CSR push-relabel solver (solver/jax_solver.py) on
TPU hardware at the 10k x 1k graph-path shape — the number VERDICT r2
noted was missing (the graph path was only ever timed on JAX-CPU).

Protocol: the solve runs device-resident inside ONE dispatched scan of
N back-to-back solves (cold potentials each, flow zeroed — the
from-scratch solve the graph path issues per round), closed by the
scalar-fetch completion barrier, wall >= the 2 s floor bar
(docs/NOTES.md measurement discipline). Prints one JSON line.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--solves", type=int, default=64, help="solves per chunk")
    ap.add_argument("--chunks", type=int, default=3)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--max-supersteps", type=int, default=4096)
    ap.add_argument(
        "--layout", choices=("csr", "ell"), default="csr",
        help="general-solver data layout: sorted-entry CSR "
        "(jax_solver) or bucketed ELL (ell_solver)",
    )
    args = ap.parse_args()

    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        from ksched_tpu.utils import force_cpu_platform

        force_cpu_platform()

    import jax
    import jax.numpy as jnp
    from jax import lax

    import __graft_entry__ as graft

    problem = graft._build_problem()
    n = problem.num_nodes
    src = problem.src.astype(np.int32)
    dst = problem.dst.astype(np.int32)
    if args.layout == "ell":
        from ksched_tpu.solver.ell_solver import (
            _plan_args,
            _solve_mcmf_ell as _solve_mcmf,
            build_ell_plan,
        )

        plan_arrays = _plan_args(build_ell_plan(src, dst, n))
    else:
        from ksched_tpu.solver.jax_solver import _solve_mcmf, build_csr_plan

        plan = build_csr_plan(src, dst, n)
        plan_arrays = tuple(
            jnp.asarray(x)
            for x in (
                plan.s_arc, plan.s_sign, plan.s_src, plan.s_dst,
                plan.s_segstart, plan.s_isstart, plan.inv_order,
                plan.node_first, plan.node_last, plan.node_nonempty,
            )
        )
    cap = jnp.asarray(problem.cap.astype(np.int32))
    cost = jnp.asarray(problem.cost.astype(np.int32) * np.int32(n))
    supply = jnp.asarray(problem.excess.astype(np.int32))
    eps = jnp.asarray(np.int32(1))
    A = len(src)
    ms = args.max_supersteps

    def chain(num_solves, salt):
        """num_solves data-chained cold solves of the SAME instance:
        each solve's flow0 is zeroed THROUGH the previous result (flow
        * 0), so XLA cannot CSE or reorder them."""

        def body(carry, _):
            flow0, acc = carry
            flow, p, steps, converged, _ovf = _solve_mcmf(
                cap, cost, supply, flow0, eps, *plan_arrays,
                alpha=8, max_supersteps=ms,
            )
            return (flow * 0 + salt * 0, acc + steps), (steps, converged)

        (_, acc), (steps, conv) = lax.scan(
            body, (jnp.zeros(A, jnp.int32), jnp.int32(0)),
            None, length=num_solves,
        )
        return acc, steps, conv

    chain_jit = jax.jit(chain, static_argnums=(0,))
    devices = jax.devices()
    platform = devices[0].platform
    print(f"# platform={platform} nodes={n} arcs={A}", file=sys.stderr)

    # warm/compile
    out = chain_jit(2, jnp.int32(0))
    jax.block_until_ready(out)
    int(jax.device_get(out[0]))

    N = args.solves
    walls = []
    steps_all = None
    while True:
        walls = []
        for rep in range(args.chunks):
            t0 = time.perf_counter()
            acc, steps, conv = chain_jit(N, jnp.int32(rep))
            jax.block_until_ready(steps)
            int(jax.device_get(acc))  # the true completion barrier
            wall = (time.perf_counter() - t0) * 1e3
            walls.append(wall)
        steps_all = np.asarray(jax.device_get(steps))
        conv_all = np.asarray(jax.device_get(conv))
        assert conv_all.all(), "a solve did not converge"
        if platform == "cpu" or min(walls) >= 2000.0 or N >= (1 << 14):
            break
        N *= 4
        out = chain_jit(N, jnp.int32(0))  # recompile + drain
        jax.block_until_ready(out)
        int(jax.device_get(out[0]))

    per_solve = [w / N for w in walls]
    p50 = float(np.percentile(per_solve, 50))
    print(
        json.dumps(
            {
                "metric": (
                    f"p50 cold-solve latency, general CSR cost-scaling "
                    f"push-relabel, 10k tasks x 1k machines graph "
                    f"({n} nodes, {A} arcs), {N}-solve chains, "
                    f"backend={args.layout}/{platform}"
                ),
                "value": round(p50, 3),
                "unit": "ms",
                "vs_baseline": round(10.0 / p50, 3),
                "detail": {
                    "solves_per_chunk": N,
                    "chunks_wall_ms": [round(w, 1) for w in walls],
                    "supersteps_per_solve": int(steps_all[-1]),
                },
            }
        )
    )


if __name__ == "__main__":
    main()
