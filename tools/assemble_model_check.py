#!/usr/bin/env python
"""Assemble MODEL_CHECK_r05.json: measured-kappa results (model_check
runs on captured instances) against the suite artifact's fitted
per-superstep slopes, with the re-based p99 column VERDICT r4 #3 asked
for: p99_rebased = fixed_ms + kappa_measured * supersteps_p99.

Usage: python tools/assemble_model_check.py BENCH_SUITE_r05.jsonl
(the three /tmp/mc_*.json files must exist from tools/model_check.py).
"""

import json
import sys


def suite_rec(path, config):
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("config") == config:
                return rec
    return None


def main():
    suite = sys.argv[1] if len(sys.argv) > 1 else "BENCH_SUITE_r05.jsonl"
    out = {"round": 5, "suite_artifact": suite, "configs": {}}
    jobs = [
        ("coco50k-preempt", "/tmp/mc_preempt.json",
         "tiered full/scoped re-solve (transport_fori_tiered, the "
         "fired-round regime — compare per_superstep_us_full)"),
        ("whare-hetero", "/tmp/mc_whare.json",
         "plain class transport (transport_fori)"),
        ("quincy10k-multiblock", "/tmp/mc_multiblock.json",
         "grouped two-stage dispatch incl. the lax.cond fallback"),
    ]
    for config, mc_path, what in jobs:
        mc = json.load(open(mc_path))
        entry = {
            "what_was_timed": what,
            "kappa_measured_us": mc["fit"]["kappa_measured_us"],
            "t_loop_ms": mc["fit"]["t_loop_ms"],
            "instances": mc["instances"],
            "inst_file": mc["inst_file"],
        }
        rec = suite_rec(suite, config)
        if rec is not None:
            d = rec["detail"]
            lm = d["latency_model"]
            if config == "coco50k-preempt" and "per_superstep_us_full" in lm:
                km = lm["per_superstep_us_full"]
                # the fired-round regime is what the captures replay;
                # re-base the SCOPED p99 (the fired-regime tail)
                ss99 = d.get("supersteps_scoped_p99", d["supersteps_p99"])
            else:
                km = lm["per_superstep_us"]
                ss99 = d["supersteps_p99"]
            kmeas = mc["fit"]["kappa_measured_us"]
            entry["suite_fit"] = {
                "fixed_ms": lm["fixed_ms"],
                "per_superstep_us": lm["per_superstep_us"],
                **(
                    {"per_superstep_us_full": lm["per_superstep_us_full"]}
                    if "per_superstep_us_full" in lm else {}
                ),
                "p99_ms_fitted": lm["p99_ms"],
            }
            entry["comparison"] = {
                "kappa_model_us": km,
                "measured_over_model": round(kmeas / km, 3) if km else None,
                "supersteps_p99_used": ss99,
                "p99_ms_rebased_measured_kappa": round(
                    lm["fixed_ms"] + kmeas * 1e-3 * ss99, 3
                ),
                "under_10ms_bar_with_measured_kappa": bool(
                    lm["fixed_ms"] + kmeas * 1e-3 * ss99 < 10.0
                ),
            }
        out["configs"][config] = entry
    with open("MODEL_CHECK_r05.json", "w") as f:
        json.dump(out, f, indent=1)
    for c, e in out["configs"].items():
        cmp = e.get("comparison", {})
        print(c, "k_meas", e["kappa_measured_us"],
              "ratio", cmp.get("measured_over_model"),
              "p99_rebased", cmp.get("p99_ms_rebased_measured_kappa"),
              "under_bar", cmp.get("under_10ms_bar_with_measured_kappa"))


if __name__ == "__main__":
    main()
