#!/usr/bin/env python
"""shard-smoke: the multi-chip rung's fixed-seed churn soak (CI gate).

Runs the SAME seeded churn scenario through three arms on a virtual
8-device CPU mesh (`make shard-smoke`, wired into `make verify`):

1. **scan-CSR reference** — single-chip JaxSolver (slot-stable plan,
   journal-scoped warm policy), device-resident mirror;
2. **sharded** — ShardedJaxSolver over the mesh, device-resident
   mirror in SHARDED plan mode (entry tables [D, Es], per-shard
   routed record scatters). Asserts, per round, placements
   BIT-IDENTICAL to arm 1; after warm-up every plan sync must be
   delta-sized ("delta"/"clean" — zero layout rebuilds, zero
   build_sharded_plan argsorts: the legacy plan cache stays empty);
3. **chaos** — the sharded rung at the top of the degradation ladder
   (sharded -> jax -> cpu_ref) under seeded solver-fault injection:
   every round must land (faults degrade, never crash), at least one
   degradation must actually fire, and a second identically-seeded
   run must produce bit-identical placements (containment +
   determinism, the chaos-smoke convention).

Exit code 0 = all assertions held.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

# env before jax import: hermetic CPU mesh, like tests/conftest.py
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np  # noqa: E402


def _build_arm(backend, machines, tasks, *, resident_mesh=None,
               plan_shards=None, seed=7):
    from ksched_tpu.drivers import add_job, build_cluster
    from ksched_tpu.graph.device_export import DeviceResidentState
    from ksched_tpu.utils import seed_rng

    seed_rng(seed)
    sched, rmap, jmap, tmap, root = build_cluster(
        num_machines=machines, num_cores=1, pus_per_core=4,
        max_tasks_per_pu=4, backend=backend,
    )
    sched.solver.device_resident = True
    res = DeviceResidentState(sched.solver.state)
    if resident_mesh is not None:
        res.enable_sharded_plan(resident_mesh, "x")
    elif plan_shards is not None:
        # the single-chip REFERENCE arm consumes the SAME sharded-mode
        # layout the multi-chip arm maintains: every arm then sees one
        # entry order with one rebuild schedule, so the comparison is
        # pure single-chip-vs-mesh EXECUTION — layout-rebuild timing
        # (which legally re-sorts cost-tied optima) can't confound it
        sched.solver.state.plan.enable_sharding(plan_shards)
    sched.solver.resident = res
    job_id = add_job(sched, jmap, tmap, num_tasks=tasks)
    sched.schedule_all_jobs()
    return sched, jmap, tmap, job_id, res


def _drive_arm(label, backend, *, machines, tasks, rounds, warmup,
               resident_mesh=None, plan_shards=None, injector=None,
               verbose=False):
    """Run the seeded churn scenario; returns (placements per round,
    plan-kind counts post-warmup, scheduler, backend)."""
    from ksched_tpu.drivers.synthetic import add_task_to_job

    sched, jmap, tmap, job_id, res = _build_arm(
        backend, machines, tasks, resident_mesh=resident_mesh,
        plan_shards=plan_shards,
    )
    rng = np.random.default_rng(123)
    k = max(1, tasks // 12)
    placements = []
    kinds = {}
    rungs = {}
    waived = 0
    t0 = time.perf_counter()
    for r in range(rounds):
        if injector is not None:
            injector.begin_round(r)
        bound = sorted(sched.task_bindings.items())
        idx = sorted(
            int(x) for x in rng.choice(len(bound), k, replace=False)
        )
        for i in reversed(idx):
            sched.handle_task_completion(tmap.find(bound[i][0]))
        for _ in range(k):
            add_task_to_job(job_id, jmap, tmap)
        sched.add_job(jmap.find(job_id))
        gen0 = sched.solver.state.generation
        overflow0 = sched.solver.state.plan.region_overflows
        sched.schedule_all_jobs()
        placements.append({
            tmap.find(t).name: rid for t, rid in sched.task_bindings.items()
        })
        rung = getattr(backend, "last_rung_name", None)
        if rung is not None:
            rungs[rung] = rungs.get(rung, 0) + 1
        if r >= warmup:
            kind = res.last_plan_kind
            # the acceptance waives exactly the documented rebuild
            # triggers: pow2 bucket growth (generation moved) and
            # tail-pool exhaustion (region_overflows moved); any OTHER
            # rebuild after warm-up is a regression
            if kind == "rebuild":
                grew = sched.solver.state.generation != gen0
                overflowed = (
                    sched.solver.state.plan.region_overflows != overflow0
                )
                assert grew or overflowed, (
                    f"{label} round {r}: plan layout rebuilt outside "
                    "full_build / pow2 growth / pool exhaustion — "
                    "post-warm-up rounds must be delta-sized"
                )
                waived += 1
            else:
                kinds[kind] = kinds.get(kind, 0) + 1
        if verbose:
            print(
                f"# {label} round {r}: plan={res.last_plan_kind}",
                file=sys.stderr,
            )
    wall = time.perf_counter() - t0
    print(
        f"# {label}: {rounds} rounds in {wall:.1f}s, plan kinds {kinds}"
        + (f", {waived} growth-waived rebuild(s)" if waived else "")
    )
    return placements, kinds, sched, res, rungs


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--machines", type=int, default=6)
    ap.add_argument("--tasks", type=int, default=48)
    ap.add_argument("--rounds", type=int, default=24)
    ap.add_argument("--warmup", type=int, default=4)
    ap.add_argument("--devices", type=int, default=8)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()

    import warnings

    import jax
    from jax.sharding import Mesh

    from ksched_tpu.parallel.sharded_solver import ShardedJaxSolver
    from ksched_tpu.runtime.chaos import ChaosPolicy, FaultInjector
    from ksched_tpu.runtime.degrade import build_degradation_ladder
    from ksched_tpu.solver.jax_solver import JaxSolver

    devs = jax.devices()
    assert len(devs) >= args.devices, (
        f"need {args.devices} virtual devices, got {len(devs)}"
    )
    mesh = Mesh(np.array(devs[: args.devices]), ("x",))
    common = dict(
        machines=args.machines, tasks=args.tasks,
        rounds=args.rounds, warmup=args.warmup, verbose=args.verbose,
    )

    # ---- arm 1: single-chip scan-CSR reference ----
    ref_pl, _, _, _, _ = _drive_arm(
        "scan-csr", JaxSolver(slot_stable=True, restart_budget=64),
        plan_shards=args.devices, **common,
    )

    # ---- arm 2: sharded, resident sharded plan mode ----
    sharded = ShardedJaxSolver(mesh)
    sh_pl, sh_kinds, sh_sched, sh_res, _ = _drive_arm(
        "sharded", sharded, resident_mesh=mesh, **common
    )
    for r, (a, b) in enumerate(zip(ref_pl, sh_pl)):
        assert a == b, (
            f"round {r}: sharded placements diverged from the scan-CSR "
            f"reference ({len(b)} vs {len(a)} bindings)"
        )
    assert sharded.last_path == "slot_stable", sharded.last_path
    assert sharded._plan is None, (
        "the legacy build_sharded_plan path ran — slot-stable rounds "
        "must never argsort a ShardedPlan"
    )
    assert sh_kinds.get("delta", 0) > 0, sh_kinds
    sh_res.parity_check()
    sh_res.plan_parity_check()
    print(
        f"# parity: {len(ref_pl)} rounds bit-identical; sharded plan "
        f"syncs post-warm-up: {sh_kinds}"
    )

    # ---- arm 3: chaos containment on the sharded rung ----
    def chaos_run():
        injector = FaultInjector(
            ChaosPolicy(seed=args.seed, solver_fault_prob=0.25)
        )
        ladder = build_degradation_ladder(
            ShardedJaxSolver(mesh), "sharded", injector=injector
        )
        assert ladder.rung_names() == ["sharded", "jax", "cpu_ref"]
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            pl, _, _, _, rungs = _drive_arm(
                "chaos", ladder, injector=injector, **common
            )
        return pl, ladder.degradations_total, injector.snapshot(), rungs

    pl_a, degr_a, snap_a, rungs_a = chaos_run()
    pl_b, degr_b, snap_b, _rungs_b = chaos_run()
    assert degr_a > 0, "chaos arm drew no solver faults; raise the prob"
    # the containment LANDING matters, not just that degradations
    # fired: fault-free rounds land on the sharded rung, and a
    # sharded-rung fault must land on the JAX rung (a dead middle rung
    # would silently fall through to the cpu_ref oracle — the exact
    # regression a [D, Es]-shaped d_plan once caused here)
    assert rungs_a.get("sharded", 0) > 0, rungs_a
    assert rungs_a.get("jax", 0) > 0, (
        "no degraded round landed on the jax rung — the "
        "sharded -> jax containment rung is dead", rungs_a,
    )
    assert degr_a == degr_b and snap_a == snap_b, (
        "chaos runs drew different fault schedules"
    )
    for r, (a, b) in enumerate(zip(pl_a, pl_b)):
        assert a == b, f"round {r}: chaos arm not deterministic"
    print(
        f"# chaos containment: {degr_a} degradations off the sharded "
        f"rung, every round landed, twin runs bit-identical "
        f"(landing rungs: {rungs_a}; faults: {snap_a})"
    )
    print("shard-smoke OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
