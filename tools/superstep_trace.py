#!/usr/bin/env python
"""Numpy step-by-step replication of the transport superstep loop, for
inspecting the dynamics of tail rounds (what are 5000 supersteps
doing?). Mirrors solver/layered.py transport_superstep/_transport_loop
exactly; parity with the JAX solver is asserted on the final objective.

Folded into the solver-telemetry path (obs/soltel.py): the per-step
counters are recorded in the SOLTEL_COLS taxonomy — the same rows the
compiled backends emit on device — and rendered through the one shared
convergence-table view (tools/obs_report.py report_convergence), so
this tracer and the in-kernel telemetry cannot drift apart. `--out`
writes a `solver_telemetry` JSON that obs_report.py renders directly.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np

from ksched_tpu.obs.soltel import SOLTEL_COLS, SOLTEL_WIDTH

BIG = np.int64(1 << 30)
BIG_D = np.int64(1 << 28)


def excesses(supply, y, z):
    e_row = supply - y.sum(axis=1)
    e_col = y.sum(axis=0) - z
    e_sink = z.sum() - supply.sum()
    return e_row, e_col, e_sink


def tighten(wS, U, col_cap):
    live = col_cap > 0
    pm = np.where(live, 0, -BIG_D)
    has_arc = U > 0
    pr = np.max(np.where(has_arc, pm[None, :] - wS, -BIG), axis=1)
    pr = np.where(has_arc.any(axis=1), pr, 0)
    psink = np.min(np.where(live, pm, BIG))
    return pr, pm, psink


def saturate_eps(wS, U, col_cap, y, z, pr, pm, psink, eps):
    rcf = wS + pr[:, None] - pm[None, :]
    y2 = np.where(rcf < -eps, U, np.where(rcf > eps, 0, y))
    rcs = pm - psink
    z2 = np.where(rcs < -eps, col_cap, np.where(rcs > eps, 0, z))
    return y2, z2


def price_refine(wS, U, col_cap, y, z, pr, pm, psink, eps, waves):
    for _ in range(waves):
        bound_m = np.min(np.where(U - y > 0, wS + pr[:, None] + eps, BIG), axis=0)
        pm2 = np.maximum(np.minimum(pm, bound_m), -BIG_D)
        pm2 = np.minimum(pm2, np.where(z > 0, psink + eps, BIG))
        bound_r = np.min(np.where(y > 0, pm2[None, :] - wS + eps, BIG), axis=1)
        pr2 = np.maximum(np.minimum(pr, bound_r), -BIG_D)
        bound_s = np.min(np.where(col_cap - z > 0, pm2 + eps, BIG))
        psink2 = np.maximum(np.minimum(psink, bound_s), -BIG_D)
        pr, pm, psink = pr2, pm2, psink2
    return pr, pm, psink


def superstep(wS, U, supply, col_cap, y, z, pr, pm, psink, eps, rows=None):
    """One synchronous wave — the numpy twin of layered.py
    transport_superstep(with_stats=True): when `rows` is given, one
    SOLTEL_COLS-ordered counter row is appended per call."""
    e_row, e_col, e_sink = excesses(supply, y, z)
    rcf = wS + pr[:, None] - pm[None, :]

    r_fwd = U - y
    adm_f = (r_fwd > 0) & (rcf < 0)
    r_adm = np.where(adm_f, r_fwd, 0)
    excl = np.cumsum(r_adm, axis=1) - r_adm
    delta_f = np.clip(e_row[:, None] - excl, 0, r_adm)

    r_s = col_cap - z
    rc_s = pm - psink
    r_b = y
    rc_b = pm[None, :] - pr[:, None] - wS
    colA = np.concatenate(
        [np.where((r_s > 0) & (rc_s < 0), r_s, 0)[None, :],
         np.where((r_b > 0) & (rc_b < 0), r_b, 0)], axis=0)
    exclA = np.cumsum(colA, axis=0) - colA
    deltaA = np.clip(e_col[None, :] - exclA, 0, colA)
    delta_s = deltaA[0]
    delta_b = deltaA[1:]

    r_zb = z
    rc_zb = psink - pm
    zb_adm = np.where((r_zb > 0) & (rc_zb < 0), r_zb, 0)
    excl_zb = np.cumsum(zb_adm) - zb_adm
    delta_zb = np.clip(e_sink - excl_zb, 0, zb_adm)

    y2 = y + delta_f - delta_b
    z2 = z + delta_s - delta_zb

    pushed_row = delta_f.sum(axis=1)
    cand_row = np.where(r_fwd > 0, pm[None, :] - wS, -BIG)
    best_row = cand_row.max(axis=1)
    relabel_row = (e_row > 0) & (pushed_row == 0)
    pr2 = np.where(relabel_row, best_row - eps, pr)

    pushed_col = delta_s + delta_b.sum(axis=0)
    cand_col = np.maximum(
        np.max(np.where(y > 0, pr[:, None] + wS, -BIG), axis=0),
        np.where(r_s > 0, psink, -BIG))
    relabel_col = (e_col > 0) & (pushed_col == 0)
    pm2 = np.where(relabel_col, cand_col - eps, pm)

    pushed_sink = delta_zb.sum()
    cand_sink = np.max(np.where(z > 0, pm, -BIG))
    relabel_sink = (e_sink > 0) & (pushed_sink == 0)
    psink2 = np.where(relabel_sink, cand_sink - eps, psink)

    if rows is not None:
        # SOLTEL_COLS order: eps, active, excess, pushed, relabels,
        # saturated, work — exactly layered.py's with_stats counters
        rows.append([
            int(eps),
            int((e_row > 0).sum() + (e_col > 0).sum() + (e_sink > 0)),
            int(np.maximum(e_row, 0).sum() + np.maximum(e_col, 0).sum()
                + max(int(e_sink), 0)),
            int(delta_f.sum() + deltaA.sum() + delta_zb.sum()),
            int(relabel_row.sum() + relabel_col.sum() + int(relabel_sink)),
            int(((U > 0) & (y >= U)).sum()
                + ((col_cap > 0) & (z >= col_cap)).sum()),
            int((r_adm > 0).sum() + (colA > 0).sum() + (zb_adm > 0).sum()),
        ] + [0] * (SOLTEL_WIDTH - 7))
    return y2, z2, pr2, pm2, np.int64(psink2)


def run(wS, supply, col_cap, eps_sched, refine_waves=8, verbose_every=500,
        max_steps=40000):
    """Returns (y, z, rows, converged): rows is the full SOLTEL_COLS
    trace; converged is False when a PHASE blew the max_steps budget
    (the budget is per phase, matching the historical tracer — a slow
    multi-phase instance whose every phase drains is not a stall)."""
    U = np.minimum(supply[:, None], col_cap[None, :]).astype(np.int64)
    pr, pm, psink = tighten(wS, U, col_cap)
    C, Mp1 = wS.shape
    y = np.zeros((C, Mp1), np.int64)
    z = np.zeros(Mp1, np.int64)
    rows: list = []
    for phase, eps in enumerate(eps_sched):
        if refine_waves and phase > 0:
            pr, pm, psink = price_refine(wS, U, col_cap, y, z, pr, pm, psink,
                                         eps, refine_waves)
        y, z = saturate_eps(wS, U, col_cap, y, z, pr, pm, psink,
                            0 if phase == 0 else eps)
        k = 0
        while True:
            er, ec, es = excesses(supply, y, z)
            if not (er > 0).any() and not (ec > 0).any() and es <= 0:
                break
            y, z, pr, pm, psink = superstep(wS, U, supply, col_cap, y, z,
                                            pr, pm, psink, eps, rows)
            k += 1
            if verbose_every and k % verbose_every == 0:
                print(f"  eps={eps} step {k}: "
                      f"{dict(zip(SOLTEL_COLS, rows[-1]))}")
            if k > max_steps:
                print("  STALL")
                return y, z, rows, False
        if k:
            print(f"phase eps={eps}: {k} steps, "
                  f"{sum(r[3] for r in rows[-k:])} unit-pushes, "
                  "final excess drained")
    return y, z, rows, True


def rows_to_telemetry(rows, budget: int, converged: bool = True) -> dict:
    """The host tracer's rows as a `solver_telemetry` dict — the same
    shape SolveTelemetry.to_dict() produces, consumable by
    obs_report.py's convergence view."""
    return {
        "backend": "superstep_trace",
        "steps": len(rows),
        "budget": budget,
        "cap": len(rows),
        "truncated": False,
        "start_step": 0,
        "converged": converged,
        "cols": list(SOLTEL_COLS),
        "rows": [[int(v) for v in row] for row in rows],
    }


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--inst", default="/tmp/tails_whare.npz")
    ap.add_argument("--k", type=int, default=0)
    ap.add_argument("--n-scale", type=int, default=1024)
    ap.add_argument("--eps0", type=int, default=None)
    ap.add_argument("--alpha", type=int, default=8)
    ap.add_argument("--refine", type=int, default=8)
    ap.add_argument("--every", type=int, default=500)
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the trace as solver_telemetry JSON "
                    "(tools/obs_report.py renders it)")
    ap.add_argument("--table", action="store_true",
                    help="print the per-superstep convergence table "
                    "(last 64 rows) via obs_report.report_convergence")
    args = ap.parse_args()

    from ksched_tpu.solver.layered import default_eps0

    data = np.load(args.inst)
    Mp = int(data["Mp"])
    w = data[f"w_{args.k}"].astype(np.int64)
    supply = data[f"supply_{args.k}"].astype(np.int64)
    col_cap = data[f"colcap_{args.k}"].astype(np.int64)
    C, M = w.shape
    wP = np.zeros((C, Mp), np.int64)
    wP[:, :M] = w
    wS = wP * args.n_scale
    eps0 = args.eps0 if args.eps0 is not None else default_eps0(args.n_scale)
    sched = []
    e = eps0
    while True:
        sched.append(e)
        if e <= 1:
            break
        e = max(1, e // args.alpha)
    print(f"instance {args.k}: supply={supply.tolist()} "
          f"cap={int(col_cap[:M].sum())} sched={sched}")
    y, z, rows, converged = run(
        wS, supply, col_cap, sched, refine_waves=args.refine,
        verbose_every=args.every,
    )
    obj = int((y[:, :M] * wP[:, :M]).sum())
    print(f"total steps={len(rows)} obj={obj} placed={int(y[:, :M].sum())}"
          + ("" if converged else "  NOT CONVERGED (phase budget blown)"))
    tel = rows_to_telemetry(rows, budget=40000, converged=converged)
    if args.table:
        from tools.obs_report import report_convergence

        report_convergence(tel, max_rows=64)
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"solver_telemetry": tel}, f)
        print(f"telemetry -> {args.out}")
