#!/usr/bin/env python
"""Validate the bench latency model against isolated hardware timings.

bench.py's per-round p99/max numbers come from a calibrated line
(_round_latency_model: latency = t_fixed + kappa * supersteps) fit on
chunk walls. Its held-out chunk error is now checked in-band
(loo_rel_err_* / fit_suspect), but the line's SLOPE — the coefficient
that converts a superstep tail into a millisecond tail — deserves an
independent measurement: this tool times captured tail instances
(tools/tail_repro.py capture) in isolation on hardware and compares
the measured per-superstep cost against the model's kappa.

Method (the transport's ~110 ms completion-polling floor forbids
timing one solve — docs/NOTES.md): each captured instance is re-solved
`reps` times inside ONE jitted lax.scan whose body threads the
superstep count through the carry (a loop-carried dependency XLA
cannot hoist), using the SAME solve entry the production round uses
(solver/layered.py transport_fori / transport_fori_tiered with
round_core's knobs — alpha, eps0 policy, refinement). Chains are timed
under the bench discipline (scalar-fetch barrier, >= 2 s walls), and
(t_loop, kappa) fall out of least squares across instances with
different superstep counts:

    wall_k = reps_k * t_loop + kappa * total_supersteps_k

kappa_measured vs the suite artifact's per_superstep_us is the
model-vs-measured comparison VERDICT r3 #3 asked for. The loop's own
fixed cost (t_loop) is NOT comparable to the round's t_fixed — the
chain body has no census/decode/apply — so only the slope is compared.

Usage:
  python tools/tail_repro.py capture --config coco --rounds 60 \
      --threshold 0 --out /tmp/insts.npz      # 0: keep EVERY round,
                                              # cheap + tail alike
  python tools/model_check.py --inst /tmp/insts.npz \
      --suite-json BENCH_SUITE.jsonl --config coco50k
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def _load_model_kappa(suite_json: str, config: str):
    """per_superstep_us (and the full latency_model) for `config` from
    a suite artifact written by bench.py --suite."""
    with open(suite_json) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if rec.get("config") == config:
                lm = rec.get("detail", {}).get("latency_model")
                if lm is None:
                    raise SystemExit(
                        f"config {config!r} in {suite_json} has no "
                        "latency_model (closed-form config?)"
                    )
                return lm
    raise SystemExit(f"config {config!r} not found in {suite_json}")


def _build_chain_grouped(data, k: int, reps: int, alpha: int, supersteps: int):
    """Chain for GROUPED captures (quincy/multiblock, tail_repro
    capture --config multiblock): replicates the production two-stage
    dispatch — bounded stage-1 discount descent (eps0=n_scale/4,
    budget S1_BUDGET, no retry) and, under lax.cond, the refined full
    fallback when the budget is exhausted — so the measured
    per-superstep cost covers the same op mix the round pays
    (scheduler/device_bulk.py grouped dispatch). The cheap stage-2
    greedy spill is host-side in production and excluded from both
    the model's kappa and this chain."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ksched_tpu.solver.layered import choose_eps0, transport_fori

    i32 = jnp.int32
    n_scale = int(data["n_scale"])
    Mp = int(data["Mp"])
    e = data["g_e"].astype(np.int64)
    u = data["g_u"].astype(np.int64)
    pref = data["g_pref"].astype(np.int64)
    G, M = pref.shape
    route = np.broadcast_to(e[:, None], (G, M))
    w = np.minimum(route, pref) - u[:, None]
    ground = (e - u).astype(np.int64)
    supply = data[f"supply_{k}"].astype(np.int32)
    machine_free = data[f"free_{k}"].astype(np.int32)
    total = int(supply.sum())
    active_cap = int(data["active_cap"])
    act = np.nonzero(supply > 0)[0]
    if len(act) > active_cap:
        act = np.arange(G)
    wA = w[act]
    supA = supply[act]
    groundA = ground[act]
    Ga = len(act)
    col_cap = np.zeros(Mp, np.int64)
    col_cap[:M] = machine_free
    col_cap[-1] = total
    wP = np.zeros((Ga, Mp), np.int64)
    wP[:, :M] = wA
    wS = jnp.asarray((wP * n_scale).astype(np.int32))
    supJ = jnp.asarray(supA)
    capJ = jnp.asarray(col_cap.astype(np.int32))
    eps_full = int(max(1, np.abs(wP).max() * n_scale))
    D = np.maximum(groundA[:, None] - wA, 0)
    w1 = np.where(D > 0, -D, 1)
    w1P = np.zeros((Ga, Mp), np.int64)
    w1P[:, :M] = w1
    wS1 = jnp.asarray((w1P * n_scale).astype(np.int32))
    fb_eps0 = int(choose_eps0(n_scale, eps_full, total,
                              int(machine_free.sum()), short=n_scale))
    # production eligibility for the two-stage decomposition
    # (can_two_stage + the runtime guards in device_bulk's
    # grouped_solve): ineligible instances go straight to the refined
    # full solve, so the chain times the op mix the round actually pays
    two_stage_ok = (total <= int(machine_free.sum())) and bool(
        ((groundA < 0) | (supA == 0)).all()
    )
    #: stage-1 budget — MUST track device_bulk's stage1_quarter budget
    #: (2048 since r5; was 1024) or the chain re-pays fallbacks
    #: production no longer takes
    S1_BUDGET = 2048

    def solve_full_only(sup_i):
        return transport_fori(
            wS, sup_i, capJ, supersteps, alpha=2, refine_waves=8,
            eps0=fb_eps0,
        )

    def solve(sup_i):
        if not two_stage_ok:
            return solve_full_only(sup_i)
        y1, pm1, s1, conv1 = transport_fori(
            wS1, sup_i, capJ, supersteps, alpha=2, refine_waves=8,
            eps0=n_scale // 4, eps0_budget=S1_BUDGET, eps0_retry=False,
        )

        def fallback(_):
            y2, pm2, s2, _c2 = transport_fori(
                wS, sup_i, capJ, supersteps, alpha=2, refine_waves=8,
                eps0=fb_eps0,
            )
            return y2, pm2, s1 + s2, _c2

        def done(_):
            return y1, pm1, s1, conv1

        return lax.cond(conv1, done, fallback, operand=None)

    def chain(_):
        def body(carry, x):
            sup_i = supJ.at[0].add(jnp.where(x < i32(0), carry, i32(0)))
            _y, _pm, steps, conv = solve(sup_i)
            return carry + steps, (steps, conv)

        total_ss, (ss, conv) = lax.scan(
            body, i32(0), jnp.arange(reps, dtype=i32)
        )
        return total_ss, ss, jnp.all(conv)

    return jax.jit(chain)


def build_chain(data, k: int, reps: int, alpha: int, supersteps: int):
    """A jitted `reps`-solve chain of captured instance `k`, matching
    round_core's solve dispatch (scheduler/device_bulk.py:546-563 for
    class instances, :852-855 for tiered preemption instances).
    Returns fn() -> (total_ss, per_rep_ss, all_converged)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ksched_tpu.solver.layered import (
        choose_eps0,
        transport_fori,
        transport_fori_tiered,
    )

    i32 = jnp.int32
    n_scale = int(data["n_scale"])
    Mp = int(data["Mp"])
    preempt = int(data.get("preempt", 0)) == 1
    if int(data.get("grouped", 0)) == 1:
        return _build_chain_grouped(data, k, reps, alpha, supersteps)
    w = data[f"w_{k}"].astype(np.int64)
    supply = data[f"supply_{k}"].astype(np.int32)
    col_cap = data[f"colcap_{k}"].astype(np.int32)
    C, M = w.shape
    wP = np.zeros((C, Mp), np.int64)
    wP[:, :M] = w
    wS = jnp.asarray((wP * n_scale).astype(np.int32))
    supJ = jnp.asarray(supply)
    capJ = jnp.asarray(col_cap)
    eps_full = int(max(1, np.abs(wP).max() * n_scale))
    free_total = int(col_cap[:M].sum())
    total = int(supply.sum())

    if preempt:
        discount = int(data["discount"])
        R = data[f"residents_{k}"].astype(np.int64)
        RP = np.zeros((C, Mp), np.int64)
        RP[:, :M] = R
        wLoP = wP.copy()
        wLoP[:, :M] -= discount
        wLo = jnp.asarray((wLoP * n_scale).astype(np.int32))
        RJ = jnp.asarray(RP.astype(np.int32))
        # round_core_preempt: full-unit start (short=n_scale), refine on
        eps0 = int(choose_eps0(n_scale, eps_full, total, free_total,
                               short=n_scale))

        def solve(sup_i):
            return transport_fori_tiered(
                wLo, wS, RJ, sup_i, capJ, supersteps,
                alpha=alpha, eps0=eps0, refine_waves=8,
            )
    else:
        # round_core non-grouped: choose_eps0 default short (n_scale/4)
        eps0 = int(choose_eps0(n_scale, eps_full, total, free_total))

        def solve(sup_i):
            return transport_fori(
                wS, sup_i, capJ, supersteps,
                alpha=alpha, eps0=eps0, refine_waves=8,
            )

    def chain(_):
        def body(carry, x):
            # loop-carried dependency so XLA cannot hoist the
            # loop-invariant solve out of the scan: x >= 0 always, so
            # the supply is unchanged at runtime, but the predicate is
            # dynamic and the carry is loop-carried
            sup_i = supJ.at[0].add(jnp.where(x < i32(0), carry, i32(0)))
            y, _pm, steps, conv = solve(sup_i)
            return carry + steps, (steps, conv)

        total_ss, (ss, conv) = lax.scan(
            body, i32(0), jnp.arange(reps, dtype=i32)
        )
        return total_ss, ss, jnp.all(conv)

    return jax.jit(chain)


#: bench.py's floor discipline (see MIN_CHUNK_WALL_MS there)
MIN_WALL_MS = 2_000.0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--inst", required=True,
                    help="captured instances (tools/tail_repro.py capture)")
    ap.add_argument("--reps", type=int, default=64,
                    help="initial solves per chain (grown to clear the "
                    "2 s wall bar on accelerators)")
    ap.add_argument("--alpha", type=int, default=8)
    ap.add_argument("--max-instances", type=int, default=8)
    ap.add_argument("--suite-json", default=None,
                    help="bench suite artifact to compare kappa against")
    ap.add_argument("--config", default=None,
                    help="config name inside --suite-json")
    ap.add_argument("--supersteps", type=int, default=1 << 17)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()

    import jax

    data = np.load(args.inst)
    n = min(int(data["n"]), args.max_instances)
    platform = jax.devices()[0].platform
    min_wall = MIN_WALL_MS if platform != "cpu" else 0.0

    rows = []
    for k in range(n):
        reps = args.reps
        while True:
            fn = build_chain(data, k, reps, args.alpha, args.supersteps)
            # warm (compile) + drain with the scalar-fetch barrier
            out = fn(0)
            jax.block_until_ready(out)
            int(jax.device_get(out[0]))
            t0 = time.perf_counter()
            out = fn(0)
            jax.block_until_ready(out)
            total_ss = int(jax.device_get(out[0]))
            wall_ms = (time.perf_counter() - t0) * 1e3
            if wall_ms >= min_wall or reps >= (1 << 18):
                break
            grow = max(2, int(np.ceil(2.5 * min_wall / max(wall_ms, 1e-3))))
            if args.verbose:
                print(f"# inst {k}: wall {wall_ms:.0f} ms at reps={reps} "
                      f"under the {min_wall:.0f} ms bar - x{grow}",
                      file=sys.stderr)
            reps *= grow
        ss_per = np.asarray(jax.device_get(out[1]))
        assert bool(jax.device_get(out[2])), f"instance {k} did not converge"
        rows.append({
            "instance": k,
            "orig_ss": int(data[f"ss_{k}"]),
            "replay_ss": int(ss_per[0]),
            "reps": reps,
            "wall_ms": round(wall_ms, 1),
            "per_solve_ms": round(wall_ms / reps, 4),
            "total_ss": total_ss,
        })
        if args.verbose:
            print(f"# inst {k}: replay_ss={ss_per[0]} reps={reps} "
                  f"wall={wall_ms:.0f} ms -> {wall_ms / reps:.3f} ms/solve",
                  file=sys.stderr)

    out = {"instances": rows, "platform": platform,
           "alpha": args.alpha, "inst_file": args.inst}
    # least squares across chains: wall = reps * t_loop + kappa * ss
    walls = np.array([r["wall_ms"] for r in rows], np.float64)
    repss = np.array([r["reps"] for r in rows], np.float64)
    sss = np.array([r["total_ss"] for r in rows], np.float64)
    if len(rows) >= 2 and np.ptp(sss / repss) > 0:
        A = np.stack([repss, sss], axis=1)
        (t_loop, kappa), *_ = np.linalg.lstsq(A, walls, rcond=None)
        if kappa < 0 or t_loop < 0:
            kappa = float(np.sum(walls * sss) / np.sum(sss * sss))
            t_loop = 0.0
        out["fit"] = {
            "t_loop_ms": round(float(t_loop), 4),
            "kappa_measured_us": round(float(kappa) * 1e3, 4),
        }
        if args.suite_json and args.config:
            lm = _load_model_kappa(args.suite_json, args.config)
            out["model"] = lm
            # preempt captures replay the FULL tiered re-solve, so they
            # validate the mixture model's full-round slope, not the
            # incremental one
            if int(data.get("preempt", 0)) and "per_superstep_us_full" in lm:
                km = lm["per_superstep_us_full"]
            else:
                km = lm["per_superstep_us"]
            out["comparison"] = {
                "kappa_model_us": km,
                "kappa_measured_us": out["fit"]["kappa_measured_us"],
                "measured_over_model": round(
                    out["fit"]["kappa_measured_us"] / km, 3
                ) if km else None,
            }
    else:
        out["fit"] = None
        print("# need >= 2 instances with distinct superstep counts "
              "for a slope fit", file=sys.stderr)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
