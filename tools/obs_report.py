"""Render an observability dump as a phase-percentile table.

One reader for every artifact the obs subsystem writes, detected by
shape — point it at whichever file a run left behind:

- **RoundRecord JSONL** (`RoundTracer.dump`, `--round-trace`): exact
  per-phase percentiles over the recorded rounds (idle sweeps
  excluded, counted separately — runtime/trace.py summary semantics);
- **registry snapshot JSON** (`dump_registry`, `--obs-dump`/`--obs-out`
  or the live `/varz` body): percentiles *estimated* from the
  `ksched_round_phase_ms` histogram buckets (log-linear interpolation
  within a bucket), plus a counter table;
- **flight-recorder dump** (`flight_<reason>_r*.json`): the ring's
  embedded RoundRecords, exact percentiles as for JSONL — plus the
  embedded `solver_stalls` (structured stall reasons with their
  telemetry tails, rendered as convergence tables);
- **Chrome trace JSON** (`SpanTracer.dump`, `--trace-out`): per-span-
  name duration percentiles over the trace events;
- **solver telemetry JSON** (`SolveTelemetry.to_dict()`, e.g.
  `tools/superstep_trace.py --out`): the per-superstep convergence
  table — eps, active/excess, pushes, relabels, saturated arcs, work
  per executed superstep (obs/soltel.py taxonomy).

Usage: python tools/obs_report.py DUMP [--phase total]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

PCTS = (50, 90, 99)


def _row(name: str, vals) -> str:
    v = np.asarray(vals, dtype=np.float64)
    cells = [f"{np.percentile(v, p):10.3f}" for p in PCTS]
    return (
        f"{name:<24} {len(v):>7} " + " ".join(cells)
        + f" {v.mean():10.3f} {v.max():10.3f}"
    )


def _header(unit: str = "ms") -> str:
    cols = [f"p{p}_{unit}" for p in PCTS] + [f"mean_{unit}", f"max_{unit}"]
    return f"{'phase':<24} {'n':>7} " + " ".join(f"{c:>10}" for c in cols)


def report_records(records: list) -> None:
    """Exact percentiles from RoundRecord dicts (JSONL / flight ring)."""
    def is_idle(r):
        return r.get("solver_rung", 0) == -1 and not r.get("noop_round")

    idle = [r for r in records if is_idle(r)]
    active = [r for r in records if not is_idle(r)]
    print(f"rounds: {len(active)} (+{len(idle)} idle sweeps excluded)")
    noops = sum(1 for r in active if r.get("noop_round"))
    misses = sum(1 for r in active if r.get("deadline_miss"))
    if noops or misses:
        print(f"noop_rounds: {noops}  deadline_misses: {misses}")
    faults: dict = {}
    for r in records:
        for k, v in (r.get("faults_injected") or {}).items():
            faults[k] = faults.get(k, 0) + v
    if faults:
        print(f"faults: {dict(sorted(faults.items()))}")
    if not active:
        return
    phases = sorted({p for r in active for p in r.get("phases_ms", {})})
    print(_header())
    for phase in phases:
        print(_row(phase, [r["phases_ms"].get(phase, 0.0) for r in active]))
    _report_tenants(active)


def _report_tenants(active: list) -> None:
    """Per-tenant percentile view: when the records carry a multi-
    tenant service's ``tenant`` field, break the total-phase
    percentiles (plus fault/NOOP attribution) out per cell — the
    operator's one-glance check that a pathological tenant degraded
    only its own lane."""
    tenants = sorted({r.get("tenant") or "" for r in active})
    if tenants == [""]:
        return
    print("\nper-tenant (total phase):")
    print(_header())
    for tid in tenants:
        rows = [r for r in active if (r.get("tenant") or "") == tid]
        label = tid or "<untagged>"
        suffix = []
        noops = sum(1 for r in rows if r.get("noop_round"))
        faults = sum(
            sum((r.get("faults_injected") or {}).values()) for r in rows
        )
        degr = sum(r.get("degradations", 0) for r in rows)
        if faults or degr or noops:
            suffix.append(f"  [faults={faults} degr={degr} noop={noops}]")
        print(
            _row(label, [r["phases_ms"].get("total", 0.0) for r in rows])
            + "".join(suffix)
        )


def _hist_percentile(buckets: list, count: int, pct: float) -> float:
    """Estimate a percentile from cumulative-ready [bound, n] bucket
    pairs (n per-bucket, +Inf last) by interpolating within the
    landing bucket. Standard Prometheus-style estimation: exact at
    bucket bounds, log-linear inside."""
    want = count * pct / 100.0
    cum = 0.0
    lo = 0.0
    for bound, n in buckets:
        prev = cum
        cum += n
        if cum >= want and n > 0:
            if bound == "+Inf":
                return float(lo)
            b = float(bound)
            frac = (want - prev) / n
            return float(lo + (b - lo) * frac)
        if bound != "+Inf":
            lo = float(bound)
    return float(lo)


def report_snapshot(metrics: dict, phase_metric: str = "ksched_round_phase_ms") -> None:
    """Histogram-estimated percentiles + counters from a registry
    snapshot (`dump_registry` / the live `/varz` body)."""
    fam = metrics.get(phase_metric)
    if fam and fam.get("kind") == "histogram":
        print(f"{phase_metric} (histogram-estimated):")
        print(_header())
        for sample in fam["samples"]:
            name = ",".join(f"{k}={v}" for k, v in sorted(sample["labels"].items()))
            count = sample["count"]
            if not count:
                continue
            cells = [
                f"{_hist_percentile(sample['buckets'], count, p):10.3f}"
                for p in PCTS
            ]
            mean = sample["sum"] / count
            print(
                f"{name or '(all)':<24} {count:>7} " + " ".join(cells)
                + f" {mean:10.3f} {'':>10}"
            )
        print()
    print(f"{'counter/gauge':<44} {'value':>14}")
    for name, fam in sorted(metrics.items()):
        if fam.get("kind") == "histogram":
            continue
        for sample in fam["samples"]:
            lbl = ",".join(f"{k}={v}" for k, v in sorted(sample["labels"].items()))
            series = name + (f"{{{lbl}}}" if lbl else "")
            print(f"{series:<44} {sample['value']:>14g}")


def report_convergence(tel: dict, max_rows: int = 0) -> None:
    """Per-superstep convergence table from a `solver_telemetry` dict
    (obs/soltel.SolveTelemetry.to_dict(), or a stall event's
    `telemetry_tail` re-wrapped). THE one renderer for solver-interior
    rows — superstep_trace.py and the flight-dump view both call it."""
    cols = tel.get("cols") or ["eps", "active", "excess", "pushed",
                               "relabels", "saturated", "work"]
    rows = tel.get("rows") or []
    start = int(tel.get("start_step", 0))
    head = f"solver telemetry: backend={tel.get('backend', '?')} "
    if "steps" in tel:
        head += f"steps={tel['steps']}"
        if tel.get("budget"):
            head += f"/{tel['budget']} budget"
    if tel.get("truncated"):
        head += (f" TRUNCATED (ring kept the final {len(rows)} of "
                 f"{tel.get('steps', '?')} supersteps)")
    if "converged" in tel:
        head += "" if tel["converged"] else "  NOT CONVERGED"
    print(head)
    if not rows:
        print("  (no supersteps recorded)")
        return
    shown = rows if not max_rows else rows[-max_rows:]
    offset = start + (len(rows) - len(shown))
    width = max(len(c) for c in cols) + 2
    print(f"{'step':>8} " + " ".join(f"{c:>{width}}" for c in cols))
    for i, row in enumerate(shown):
        print(
            f"{offset + i:>8} "
            + " ".join(f"{int(v):>{width}}" for v in row[: len(cols)])
        )
    # phase summary: supersteps per eps value, in order
    phases = []
    for row in rows:
        e = int(row[0])
        if phases and phases[-1][0] == e:
            phases[-1][1] += 1
        else:
            phases.append([e, 1])
    if len(phases) > 1:
        print("phases: " + "  ".join(f"eps={e}: {k}" for e, k in phases))


def report_stalls(stalls: list) -> None:
    """Structured solver stall events (a flight dump's
    `solver_stalls`), each with its telemetry-tail convergence table."""
    print(f"solver stalls: {len(stalls)} event(s)")
    for i, ev in enumerate(stalls):
        line = (f"  [{i}] kind={ev.get('kind')} rung={ev.get('rung', '-')} "
                f"backend={ev.get('backend', '-')} "
                f"supersteps={ev.get('supersteps', '-')}")
        print(line)
        if ev.get("detail") or ev.get("error"):
            print(f"      {ev.get('detail') or ev.get('error')}")
        tail = ev.get("telemetry_tail")
        if tail:
            report_convergence(
                {
                    "cols": ev.get("telemetry_cols"),
                    "rows": tail,
                    "start_step": ev.get("telemetry_start_step", 0),
                    "backend": ev.get("backend", "?"),
                    "truncated": ev.get("telemetry_truncated", False),
                }
            )


def report_trace(events: list) -> None:
    """Per-span-name duration percentiles from Chrome trace events."""
    by_name: dict = {}
    for ev in events:
        if ev.get("ph") == "X":
            by_name.setdefault(ev["name"], []).append(ev.get("dur", 0.0) / 1e3)
    print(f"trace: {len(events)} events, {len(by_name)} span names")
    print(_header())
    for name in sorted(by_name):
        print(_row(name, by_name[name]))
    report_pipeline_occupancy(events)


#: host-side span names whose time inside a round's in-flight window
#: counts as overlapped work (the POSTs the pipelined loop defers into
#: the dispatch window, and the decode when a driver interleaves it)
OVERLAP_SPAN_NAMES = ("bindings_post", "decode", "deltas", "apply")


def pipeline_occupancy(events: list) -> Optional[dict]:
    """Measure the double-buffered loop's overlap from a span trace:
    for every round with a ``solve_dispatch`` → ``solve_sync`` pair,
    the in-flight window is the gap between dispatch end and sync
    start (the device is crunching); host spans (OVERLAP_SPAN_NAMES)
    falling inside that window are work the pipeline hid behind the
    solve. Returns None when the trace carries no pipelined rounds
    (nothing dispatched asynchronously)."""
    complete = [ev for ev in events if ev.get("ph") == "X"]
    rounds = [ev for ev in complete if ev["name"] in ("service_round", "round")]
    # prefer service_round (it contains the POST flush); fall back to
    # bare scheduler rounds for driver-level traces
    if any(ev["name"] == "service_round" for ev in rounds):
        rounds = [ev for ev in rounds if ev["name"] == "service_round"]
    dispatches = [ev for ev in complete if ev["name"] == "solve_dispatch"]
    syncs = [ev for ev in complete if ev["name"] == "solve_sync"]
    hosts = [ev for ev in complete if ev["name"] in OVERLAP_SPAN_NAMES]
    if not rounds or not dispatches or not syncs:
        return None
    total_round_us = 0.0
    total_window_us = 0.0
    total_overlap_us = 0.0
    windows = 0
    for rnd in rounds:
        r0, r1 = rnd["ts"], rnd["ts"] + rnd.get("dur", 0.0)

        def inside(ev):
            return ev["ts"] >= r0 and ev["ts"] + ev.get("dur", 0.0) <= r1

        ds = [ev for ev in dispatches if inside(ev)]
        ss = [ev for ev in syncs if inside(ev)]
        if not ds or not ss:
            continue
        w0 = min(ev["ts"] + ev.get("dur", 0.0) for ev in ds)
        w1 = max(ev["ts"] for ev in ss)
        if w1 <= w0:
            continue
        overlap = 0.0
        for ev in hosts:
            h0, h1 = ev["ts"], ev["ts"] + ev.get("dur", 0.0)
            overlap += max(0.0, min(h1, w1) - max(h0, w0))
        total_round_us += r1 - r0
        total_window_us += w1 - w0
        total_overlap_us += overlap
        windows += 1
    if not windows:
        return None
    return {
        "rounds_with_window": windows,
        "round_wall_ms": total_round_us / 1e3,
        "inflight_window_ms": total_window_us / 1e3,
        "overlapped_host_ms": total_overlap_us / 1e3,
        # the headline: fraction of round wall where upload/solve
        # overlapped decode/bind work on the host
        "occupancy_of_round": (
            total_overlap_us / total_round_us if total_round_us else 0.0
        ),
        "occupancy_of_window": (
            total_overlap_us / total_window_us if total_window_us else 0.0
        ),
    }


def report_pipeline_occupancy(events: list) -> None:
    occ = pipeline_occupancy(events)
    if occ is None:
        return
    print()
    print(
        f"pipeline occupancy: {occ['rounds_with_window']} round(s) with an "
        f"in-flight solve window"
    )
    print(
        f"  round wall {occ['round_wall_ms']:.2f} ms, in-flight window "
        f"{occ['inflight_window_ms']:.2f} ms, overlapped host work "
        f"{occ['overlapped_host_ms']:.2f} ms"
    )
    print(
        f"  {occ['occupancy_of_round']:.1%} of round wall overlapped the "
        f"solve ({occ['occupancy_of_window']:.1%} of the in-flight window)"
    )


def load_and_report(path: str, phase_metric: str) -> None:
    with open(path) as f:
        text = f.read()
    if not text.strip():
        print("empty dump", file=sys.stderr)
        return
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        doc = None  # multi-line JSONL: one record per line
    if isinstance(doc, dict):
        if "solver_telemetry" in doc:
            report_convergence(doc["solver_telemetry"])
            return
        if "cols" in doc and "rows" in doc:
            report_convergence(doc)  # bare SolveTelemetry.to_dict()
            return
        if "metrics" in doc:
            report_snapshot(doc["metrics"], phase_metric)
            return
        if "rounds" in doc and isinstance(doc["rounds"], list):
            print(f"flight dump: reason={doc.get('reason')} "
                  f"rounds_seen={doc.get('rounds_seen')}")
            report_records([entry["record"] for entry in doc["rounds"]])
            if doc.get("solver_stalls"):
                print()
                report_stalls(doc["solver_stalls"])
            # the ring's span slices double as a trace: surface the
            # double-buffered loop's overlap from any flight dump
            report_pipeline_occupancy(
                [ev for entry in doc["rounds"] for ev in entry.get("spans", [])]
            )
            return
        if "traceEvents" in doc:
            report_trace(doc["traceEvents"])
            return
        if doc and all(isinstance(v, dict) and "kind" in v for v in doc.values()):
            report_snapshot(doc, phase_metric)  # bare /varz body
            return
    # fall through: RoundRecord JSONL
    records = [json.loads(line) for line in text.splitlines() if line.strip()]
    report_records(records)


def main() -> int:
    ap = argparse.ArgumentParser(
        description="phase-percentile table from any obs dump"
    )
    ap.add_argument("dump", help="JSONL round trace, registry snapshot, "
                    "flight dump, or Chrome trace JSON")
    ap.add_argument("--phase-metric", default="ksched_round_phase_ms",
                    help="histogram family to tabulate from snapshots")
    args = ap.parse_args()
    try:
        load_and_report(args.dump, args.phase_metric)
    except BrokenPipeError:
        # piping into head/a pager closes stdout mid-table; that is a
        # normal way to skim the output, not an error — point the fd at
        # devnull so the interpreter's exit flush doesn't re-raise
        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
        return 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
