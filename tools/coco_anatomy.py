#!/usr/bin/env python
"""The round-floor anatomy of the coco50k steady config — the
partial-fusion (megakernel) probe VERDICT r4 #8 asked for.

Non-preempt steady rounds sit at ~2.2 ms with the solve a minority
term; whether a census+solve(+decode) Pallas megakernel is worth a
future round depends on how the OTHER ~1.7 ms decomposes. Ablations
(same protocol as bench.py's _device_bench, one variant per process
run is NOT needed — each variant builds its own cluster/scan):

  baseline    the suite's coco50k exactly
  uncontended slots doubled (occupancy ~39%): supersteps collapse, the
              residual is the census+cost+decode+bookkeeping floor
  decode-512 / decode-8192
              the [width, M] mover-ranking term, by slope

Prints one JSON line per variant plus a floor decomposition.
"""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np


def main():
    import bench
    from ksched_tpu.costmodels import coco
    from ksched_tpu.costmodels.device_costs import coco_device_cost_fn

    rng = np.random.default_rng(0)
    penalties = rng.integers(0, 40, (1_000, 4)).astype(np.int64)

    variants = [
        ("baseline", dict(slots=16, decode_width=4096)),
        ("uncontended-slots32", dict(slots=32, decode_width=4096)),
        ("decode-512", dict(slots=16, decode_width=512)),
        ("decode-8192", dict(slots=16, decode_width=8192)),
    ]
    out = {}
    for name, kw in variants:
        rec = bench._device_bench(
            tasks=50_000, machines=1_000, pus=4, jobs=20,
            churn=0.01, rounds=128, chunk=32,
            num_task_classes=4,
            class_cost_fn=coco_device_cost_fn(penalties),
            unsched_cost=coco.UNSCHEDULED_COST,
            ec_cost=0,
            supersteps=1 << 17,
            label=f"coco50k anatomy/{name}",
            verbose=False,
            **kw,
        )
        d = rec["detail"]
        lm = d.get("latency_model") or {}
        out[name] = {
            "p50_ms": rec["value"],
            "supersteps_p50": d.get("supersteps_p50"),
            "fixed_ms": lm.get("fixed_ms"),
            "per_superstep_us": lm.get("per_superstep_us"),
            "chunks_wall_ms": d.get("chunks_wall_ms"),
        }
        print(f"# {name}: p50 {rec['value']} ss_p50 "
              f"{d.get('supersteps_p50')}", file=sys.stderr)

    base = out["baseline"]["p50_ms"]
    unc = out["uncontended-slots32"]["p50_ms"]
    d512 = out["decode-512"]["p50_ms"]
    d8192 = out["decode-8192"]["p50_ms"]
    # decode slope per 1k width from the 512->8192 spread
    decode_slope = (d8192 - d512) / (8192 - 512) * 1024
    out["decomposition"] = {
        "solve_plus_contention_ms": round(base - unc, 4),
        "decode_per_1024_width_ms": round(decode_slope, 4),
        "decode_at_4096_ms_est": round(decode_slope * 4, 4),
        "residual_floor_ms_est": round(
            unc - decode_slope * 4, 4
        ),
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
