"""Latency diagnostics for the device-resident scheduling round.

Measures, on the ambient platform (real TPU by default, or
JAX_PLATFORMS=cpu):

1. the empty-scan floor — per-iteration cost of a 64-length lax.scan
   doing nothing, which bounds the measurement resolution;
2. the per-call dispatch overhead of a jitted program;
3. the sustained steady-round latency — the bench.py protocol: 64
   data-dependent churn rounds chained in one scan, wall time / 64.

Timing rides the obs span tracer (ksched_tpu/obs/spans.py): every
measured repetition is a span, the reported medians are computed from
the spans' durations, and the whole session exports as Chrome/Perfetto
trace-event JSON (--trace-out) — so the numbers printed and the trace
a human inspects are the same measurement.

Two measurement hazards this tool works around, documented because they
invalidate naive timings on this stack:

- D2H fetch poisoning: on the tunneled-TPU transport, a single
  device-to-host transfer (even `int(x[0])`) permanently degrades every
  subsequent dispatch in the process from ~30 us to ~90 ms. All forcing
  here uses jax.block_until_ready (which waits without transferring);
  nothing is fetched until after all timing.
- XLA loop hoisting: a scan body computed from loop-invariant inputs is
  hoisted out of the loop and executes once, so "repeat phase X in a
  scan" times an empty loop. Only the real round chain — where each
  round's state feeds the next — is immune, which is why this tool
  times whole rounds rather than isolated phases.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ksched_tpu.obs.spans import SpanTracer, span  # noqa: E402
from ksched_tpu.scheduler.device_bulk import DeviceBulkCluster  # noqa: E402

R = 64


def _med(fn, name: str, reps: int = 7, **args) -> float:
    """Median wall-ms of `fn` over `reps` calls, each timed as (and
    reported from) one obs span named `name`."""
    ts = []
    for i in range(reps):
        with span(name, rep=i, **args) as sp:
            fn()
        ts.append(sp.dur_s * 1e3)
    return float(np.median(ts))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--trace-out", default="profile_round_trace.json", metavar="PATH",
        help="Chrome/Perfetto trace-event JSON of the measured spans "
        "('' to skip)",
    )
    args = ap.parse_args()

    tracer = SpanTracer().install()
    M, P, S, J, T = 1000, 4, 4, 10, 10_000
    rng = np.random.default_rng(0)
    with span("setup", machines=M, tasks=T):
        dev = DeviceBulkCluster(
            num_machines=M, pus_per_machine=P, slots_per_pu=S, num_jobs=J,
            task_capacity=16384,
        )
        dev.add_tasks(T, rng.integers(0, J, T).astype(np.int32))
    with span("fill_round"):
        fill = dev.round()
        jax.block_until_ready(fill)

    # empty-scan floor + dispatch overhead
    def empty_chunk(x):
        out, _ = lax.scan(lambda c, _: (c + 1, None), x, None, length=R)
        return out

    f_empty = jax.jit(empty_chunk)
    x0 = jnp.int32(0)
    with span("empty_scan_compile"):
        jax.block_until_ready(f_empty(x0))
    empty_ms = _med(
        lambda: jax.block_until_ready(f_empty(x0)), "empty_scan_chunk"
    )

    # the real thing: data-dependent steady rounds (bench protocol)
    churn_n = max(1, T // 100)
    with span("steady_warmup", rounds=R):
        jax.block_until_ready(dev.run_steady_rounds(R, 0.01, churn_n, seed=1))
    stats = []

    def one_chunk():
        s = dev.run_steady_rounds(R, 0.01, churn_n, seed=2 + len(stats))
        jax.block_until_ready(s)
        stats.append(s)

    chunk_ms = _med(one_chunk, "steady_chunk", rounds=R)

    # clock stopped; fetch + verify
    fill_got = dev.fetch_stats(fill)
    assert bool(fill_got["converged"])
    for s in stats:
        assert dev.fetch_stats(s)["converged"].all()
    tracer.uninstall()

    print(f"geometry: T={T} Tcap={dev.Tcap} M={M} P={P} S={S} "
          f"platform={jax.devices()[0].platform}, {R}-round chains")
    print(f"empty scan floor   : {empty_ms / R * 1e3:8.2f} us/iter "
          f"({empty_ms:.3f} ms/call, incl dispatch)")
    print(f"steady round chain : {chunk_ms / R * 1e3:8.2f} us/round "
          f"({chunk_ms:.3f} ms/chunk)")
    if args.trace_out:
        tracer.dump(args.trace_out)
        print(f"trace ({tracer.mark()} spans) -> {args.trace_out}")


if __name__ == "__main__":
    main()
