#!/usr/bin/env python
"""Capture and replay superstep-tail rounds of the steady-state configs.

BENCH_SUITE_r02 recorded supersteps_max = 15687 (quincy10k) and 25324
(whare-hetero) against p50s of 12 and 753: a small minority of rounds
burn 20-30x the typical superstep budget, and at ~2.6 us/superstep they
blow the 10 ms target. This tool makes those rounds reproducible:

  capture  run the steady-state loop on JAX-CPU, one round per dispatch,
           snapshotting each round's exact transport instance (cost
           matrix, window supply, free columns) BEFORE the round runs;
           rounds whose supersteps exceed a threshold are written to an
           npz for replay.
  replay   re-solve captured instances under solver-knob sweeps
           (alpha, refine_waves, eps0 policy) and report supersteps per
           knob point — the measurement loop for killing the tail.

Usage:
  python tools/tail_repro.py capture --config whare --rounds 200 --out /tmp/tails.npz
  python tools/tail_repro.py replay --inst /tmp/tails.npz --alpha 2,8 --refine 8,32
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def build_config(name: str):
    """The bench suite's steady-state configs, scaled for CPU capture."""
    from ksched_tpu.costmodels.device_costs import (
        coco_device_cost_fn,
        whare_device_cost_fn,
    )
    from ksched_tpu.scheduler.device_bulk import DeviceBulkCluster
    from ksched_tpu.utils import next_pow2

    rng = np.random.default_rng(7)
    if name == "whare":
        tasks, machines = 20_000, 1_000
        platform_factor = rng.integers(80, 140, machines).astype(np.int64)
        dev = DeviceBulkCluster(
            num_machines=machines, pus_per_machine=4, slots_per_pu=8,
            num_jobs=20, num_task_classes=4,
            task_capacity=next_pow2(tasks + 4096),
            class_cost_fn=whare_device_cost_fn(
                slots_per_machine=32, platform_factor=platform_factor
            ),
            unsched_cost=_whare_unsched(), ec_cost=0,
            supersteps=1 << 17, decode_width=2048,
        )
    elif name == "coco":
        tasks, machines = 50_000, 1_000
        penalties = rng.integers(0, 40, (machines, 4)).astype(np.int64)
        dev = DeviceBulkCluster(
            num_machines=machines, pus_per_machine=4, slots_per_pu=16,
            num_jobs=20, num_task_classes=4,
            task_capacity=next_pow2(tasks + 4096),
            class_cost_fn=coco_device_cost_fn(penalties),
            unsched_cost=_coco_unsched(), ec_cost=0,
            supersteps=1 << 17, decode_width=4096,
        )
    elif name == "coco-preempt":
        # scaled-down preemption-on CoCo (CPU-capturable): same
        # structure as coco50k-preempt at 20k tasks
        tasks, machines = 20_000, 1_000
        penalties = rng.integers(0, 40, (machines, 4)).astype(np.int64)
        dev = DeviceBulkCluster(
            num_machines=machines, pus_per_machine=4, slots_per_pu=8,
            num_jobs=20, num_task_classes=4,
            task_capacity=next_pow2(tasks + 4096),
            class_cost_fn=coco_device_cost_fn(penalties),
            unsched_cost=_coco_unsched(), ec_cost=0,
            supersteps=1 << 17,
            preemption=True, continuation_discount=8,
        )
    elif name == "quincy":
        from ksched_tpu.costmodels.quincy_device import QuincyGroupTable

        MBv = 1 << 20
        tasks, machines, n_blocks, G = 10_000, 1_000, 480, 512
        dev = DeviceBulkCluster(
            num_machines=machines, pus_per_machine=4, slots_per_pu=4,
            num_jobs=10, task_capacity=next_pow2(tasks + 4096),
            num_groups=G, supersteps=1 << 17, decode_width=2048,
        )
        table = QuincyGroupTable(
            num_groups=G, num_machines=machines, cost_unit_mb=64
        )
        for b in range(1, n_blocks + 1):
            table.blocks.register(
                b, 512 * MBv,
                rng.choice(machines, size=3, replace=False).tolist(),
            )
        blocks = rng.integers(1, n_blocks + 1, tasks)
        groups = table.groups_for(
            np.zeros(tasks, np.int32), [[int(b)] for b in blocks]
        )
        table.sync(dev)
        dev._tail_repro_groups = (table, groups)  # capture() hooks
    elif name == "multiblock":
        # bench.py _quincy_multiblock_bench's exact setup (split quanta,
        # heavy-tailed block sizes, skewed template pool) so captured
        # tails are THAT config's tails
        from ksched_tpu.costmodels.quincy_device import QuincyGroupTable

        MBv = 1 << 20
        tasks, machines, n_blocks, G = 10_000, 1_000, 480, 1024
        n_templates = 640
        dev = DeviceBulkCluster(
            num_machines=machines, pus_per_machine=4, slots_per_pu=4,
            num_jobs=10, task_capacity=next_pow2(tasks + 4096),
            num_groups=G, supersteps=1 << 17, decode_width=2048,
            active_groups_cap=(128, 256, 512),
            two_stage_eps0="quarter",
        )
        table = QuincyGroupTable(
            num_groups=G, num_machines=machines,
            cost_unit_mb=64, sig_unit_mb=128,
        )
        rng7 = np.random.default_rng(7)
        sizes = (
            128 * MBv * np.exp(rng7.exponential(1.2, n_blocks))
        ).astype(np.int64)
        sizes = np.minimum(sizes, 4096 * MBv)
        for b in range(1, n_blocks + 1):
            table.blocks.register(
                b, int(sizes[b - 1]),
                rng7.choice(machines, size=3, replace=False).tolist(),
            )
        templates = [
            sorted(
                rng7.choice(n_blocks, size=int(rng7.integers(2, 4)),
                            replace=False) + 1
            )
            for _ in range(n_templates)
        ]
        popularity = 1.0 / np.arange(1, n_templates + 1) ** 0.8
        popularity /= popularity.sum()
        t_idx = rng7.choice(n_templates, size=tasks, p=popularity)
        groups = table.groups_for(
            np.zeros(tasks, np.int32), [templates[t] for t in t_idx]
        )
        table.sync(dev)
        dev._tail_repro_groups = (table, groups)
    else:
        raise SystemExit(f"unknown config {name!r}")
    return dev, tasks


def _whare_unsched():
    from ksched_tpu.costmodels import whare

    return whare.UNSCHEDULED_COST


def _coco_unsched():
    from ksched_tpu.costmodels import coco

    return coco.UNSCHEDULED_COST


def capture(args) -> None:
    import jax
    import jax.numpy as jnp

    dev, tasks = build_config(args.config)
    rng = np.random.default_rng(0)
    grouped_setup = getattr(dev, "_tail_repro_groups", None)
    if grouped_setup is not None:
        _table, init_groups = grouped_setup
        dev.add_tasks(
            tasks, rng.integers(0, dev.J, tasks).astype(np.int32),
            groups=init_groups,
        )
    else:
        dev.add_tasks(
            tasks,
            rng.integers(0, dev.J, tasks).astype(np.int32),
            rng.integers(0, dev.C, tasks).astype(np.int32),
        )
    jax.block_until_ready(dev.round())

    churn_n = max(1, int(tasks * 0.01))
    # Tail rounds appear only after the backlog drifts into the
    # contended regime (solver escapes accumulate over hundreds of
    # rounds); run the warmup as device-chained chunks — fast — before
    # capturing rounds one by one.
    warm_chunk = 256
    for w0 in range(0, args.warmup, warm_chunk):
        stats = dev.fetch_stats(
            dev.run_steady_rounds(
                min(warm_chunk, args.warmup - w0), 0.01, churn_n, seed=w0
            )
        )
        if args.verbose:
            ss = np.asarray(stats["supersteps"])
            print(
                f"# warmup {w0}+{len(ss)}: ss p50={np.percentile(ss, 50):.0f} "
                f"max={ss.max()}",
                file=sys.stderr,
            )
    insts = []
    ss_all = []
    for i in range(args.rounds):
        # Drive the churn from the host (complete + admit), snapshot
        # the exact pre-solve state, then run the round — so a captured
        # instance IS the instance the round solved (round() decodes
        # full-width; the steady window never binds at churn_n rows).
        st0 = dev.fetch_state()
        live = np.asarray(st0["live"])
        pu = np.asarray(st0["pu"])
        placed_rows = np.nonzero(live & (pu >= 0))[0]
        done = rng.choice(
            placed_rows, size=min(churn_n, len(placed_rows)), replace=False
        )
        dev.complete_tasks(done.astype(np.int32))
        if grouped_setup is not None:
            dev.add_tasks(
                churn_n,
                rng.integers(0, dev.J, churn_n).astype(np.int32),
                groups=rng.integers(0, dev.G, churn_n).astype(np.int32),
            )
        else:
            dev.add_tasks(
                churn_n,
                rng.integers(0, dev.J, churn_n).astype(np.int32),
                rng.integers(0, dev.C, churn_n).astype(np.int32),
            )
        st = dev.fetch_state()
        stats = dev.fetch_stats(dev.round())
        ss = int(stats["supersteps"])
        ss_all.append(ss)
        if ss >= args.threshold:
            insts.append((ss, st))
        if args.verbose and (ss >= args.threshold or i % 20 == 0):
            print(f"# round {i}: supersteps={ss}", file=sys.stderr)

    ss_all = np.array(ss_all)
    print(
        f"rounds={args.rounds} supersteps p50={np.percentile(ss_all, 50):.0f} "
        f"p90={np.percentile(ss_all, 90):.0f} p99={np.percentile(ss_all, 99):.0f} "
        f"max={ss_all.max()} tails>={args.threshold}: {len(insts)}"
    )
    if not insts:
        print("no tail rounds captured; lower --threshold")
        return
    if grouped_setup is not None:
        # grouped instance: per-group supply over the decode window +
        # machine_free; GroupSpec arrays are capture-static, saved once
        out = {}
        for k, (ss, st) in enumerate(insts):
            supply, machine_free = grouped_instance_from_state(dev, st)
            out[f"supply_{k}"] = supply
            out[f"free_{k}"] = machine_free
            out[f"ss_{k}"] = np.int64(ss)
        g = dev.groups
        out.update(
            n=np.int64(len(insts)), n_scale=np.int64(dev.n_scale),
            Mp=np.int64(dev.Mp), grouped=np.int64(1),
            g_e=np.asarray(g.e), g_u=np.asarray(g.u),
            g_pref=np.asarray(g.pref_w),
            active_cap=np.int64(dev.active_groups_cap),
        )
        np.savez_compressed(args.out, **out)
        print(f"wrote {len(insts)} grouped instances to {args.out}")
        return
    # Reconstruct each tail round's transport instance from its
    # pre-round state snapshot. The captured state is PRE-churn; the
    # exact solved instance differs by one churn step, but the captured
    # one is statistically identical (verified: replay supersteps are
    # the same magnitude) and fully reproducible.
    out = {}
    for k, (ss, st) in enumerate(insts):
        inst = instance_from_state(dev, st)
        out[f"w_{k}"] = inst[0]
        out[f"supply_{k}"] = inst[1]
        out[f"colcap_{k}"] = inst[2]
        if dev.preemption:
            out[f"residents_{k}"] = inst[3]
        out[f"ss_{k}"] = np.int64(ss)
    out["n"] = np.int64(len(insts))
    out["n_scale"] = np.int64(dev.n_scale)
    out["Mp"] = np.int64(dev.Mp)
    out["preempt"] = np.int64(int(dev.preemption))
    out["discount"] = np.int64(dev.continuation_discount)
    np.savez_compressed(args.out, **out)
    print(f"wrote {len(insts)} instances to {args.out}")


def grouped_instance_from_state(dev, st):
    """(supply[G] over the decode window, machine_free[M]) for a
    group-mode round — mirrors round_core's window census."""
    live = np.asarray(st["live"])
    pu = np.asarray(st["pu"])
    grp = np.asarray(st["grp"])
    M, P, S = dev.M, dev.P, dev.S
    num_pus = dev.num_pus

    placed = live & (pu >= 0)
    pu_running = np.zeros(num_pus, np.int64)
    np.add.at(pu_running, pu[placed], 1)
    enabled = np.asarray(st["machine_enabled"])
    pu_free = np.where(np.repeat(enabled, P), S - pu_running, 0)
    machine_free = pu_free.reshape(M, P).sum(axis=1)

    unplaced = live & (pu < 0)
    W = dev.decode_width or dev.Tcap
    rows = np.nonzero(unplaced)[0][:W]
    supply = np.bincount(grp[rows], minlength=dev.G)
    return supply.astype(np.int32), machine_free.astype(np.int32)


def replay_grouped(args) -> None:
    """Re-solve captured GROUPED instances under solver-strategy sweeps,
    replicating round_core's grouped dispatch (two-stage decomposition
    with the eps0=1 bounded attempt, active-row compaction, refined
    full fallback — scheduler/device_bulk.py) outside the jitted round
    so strategies can be compared on real blocked-contention rounds."""
    import jax.numpy as jnp

    from ksched_tpu.solver.layered import (
        choose_eps0,
        split_grants_by_class,
        transport_fori,
    )

    data = np.load(args.inst)
    n = int(data["n"])
    n_scale = int(data["n_scale"])
    Mp = int(data["Mp"])
    e = data["g_e"].astype(np.int64)
    u = data["g_u"].astype(np.int64)
    pref = data["g_pref"].astype(np.int64)
    G, M = pref.shape
    PREF_NONE = 1 << 30

    route = np.broadcast_to(e[:, None], (G, M))
    cost_eff = np.minimum(route, pref)
    w = cost_eff - u[:, None]
    ground = (e - u).astype(np.int64)  # [G]

    strategies = args.strategies.split(",")
    active_cap = int(data["active_cap"])

    for k in range(n):
        supply = data[f"supply_{k}"].astype(np.int32)
        machine_free = data[f"free_{k}"].astype(np.int32)
        orig = int(data[f"ss_{k}"])
        total = int(supply.sum())

        # active-row compaction (as the device path does)
        act = np.nonzero(supply > 0)[0]
        if len(act) > active_cap:
            act = np.arange(G)
        wA = w[act]
        supA = supply[act]
        groundA = ground[act]
        Ga = len(act)
        col_cap = np.zeros(Mp, np.int64)
        col_cap[:M] = machine_free
        col_cap[-1] = total
        wP = np.zeros((Ga, Mp), np.int64)
        wP[:, :M] = wA
        wS = jnp.asarray((wP * n_scale).astype(np.int32))
        supJ = jnp.asarray(supA)
        capJ = jnp.asarray(col_cap.astype(np.int32))
        eps_full = int(max(1, np.abs(wP).max() * n_scale))

        D = np.maximum(groundA[:, None] - wA, 0)
        w1 = np.where(D > 0, -D, 1)
        w1P = np.zeros((Ga, Mp), np.int64)
        w1P[:, :M] = w1
        wS1 = jnp.asarray((w1P * n_scale).astype(np.int32))
        two_stage_ok = (total <= int(machine_free.sum())) and bool(
            ((groundA < 0) | (supA == 0)).all()
        )

        print(
            f"inst {k}: rows={Ga} total={total} "
            f"free={int(machine_free.sum())} two_stage_ok={two_stage_ok} "
            f"orig_ss={orig}"
        )
        obj_ref = None
        for strat in strategies:
            ss_total = 0
            if strat.startswith("two"):
                # two-stage: stage-1 eps0/budget from the strategy name
                # two:<eps0>:<budget>[:<fallback-eps0>]
                # (eps0 'n4' = n_scale/4, '1' = 1; the optional 4th
                # field overrides the FULL-FALLBACK eps0 taken when the
                # stage-1 budget is exhausted — the production default
                # is choose_eps0(short=n_scale))
                parts = strat.split(":")
                _, e0name, budget = parts[:3]
                fb_name = parts[3] if len(parts) > 3 else None
                e0 = {"1": 1, "n4": n_scale // 4, "n": n_scale}[e0name]
                y1, _pm, s1, conv1 = transport_fori(
                    wS1, supJ, capJ, 1 << 17, alpha=2, refine_waves=8,
                    eps0=int(e0), eps0_budget=int(budget),
                    eps0_retry=False,  # the production honest bound
                )
                ss_total += int(s1)
                if bool(conv1):
                    y1r = np.asarray(y1, np.int64)[:, :M]
                    left = supA - y1r.sum(axis=1)
                    rem = machine_free - y1r.sum(axis=0)
                    excl = np.cumsum(rem) - rem
                    grants_m = np.clip(left.sum() - excl, 0, rem)
                    y2 = split_grants_by_class(grants_m, left)
                    y_real = y1r + y2
                else:
                    fb = {
                        None: int(choose_eps0(n_scale, eps_full, total,
                                              int(machine_free.sum()),
                                              short=n_scale)),
                        "n4": n_scale // 4, "n": n_scale,
                        "n2": n_scale // 2, "1": 1,
                    }[fb_name]
                    y_f, _pm, s2, conv2 = transport_fori(
                        wS, supJ, capJ, 1 << 17, alpha=2, refine_waves=8,
                        eps0=int(fb),
                    )
                    ss_total += int(s2)
                    assert bool(conv2)
                    y_real = np.asarray(y_f, np.int64)[:, :M]
            else:
                # direct full solve: full:<eps0name>:<alpha>
                _, e0name, alpha = strat.split(":")
                e0 = {"1": 1, "n4": n_scale // 4, "n": n_scale,
                      "full": eps_full}[e0name]
                y_f, _pm, s2, conv2 = transport_fori(
                    wS, supJ, capJ, 1 << 17, alpha=int(alpha),
                    refine_waves=8, eps0=int(e0),
                )
                ss_total += int(s2)
                assert bool(conv2)
                y_real = np.asarray(y_f, np.int64)[:, :M]
            obj = int((wA * y_real).sum())
            if obj_ref is None:
                obj_ref = obj
            flag = "" if obj == obj_ref else f"  OBJ DRIFT ({obj - obj_ref:+d})"
            print(f"  {strat:14s}: ss={ss_total}{flag}")


def instance_from_state(dev, st):
    """Rebuild (w[C,M], supply[C], col_cap[Mp]) the round core would
    solve from a fetched DeviceClusterState — mirrors round_core
    (scheduler/device_bulk.py) with a zero window offset. In preempt
    mode (round_core_preempt): supply = ALL live tasks, col_cap = total
    slots, and a 4th return carries the resident census R[C, M]."""
    import jax.numpy as jnp

    live = np.asarray(st["live"])
    pu = np.asarray(st["pu"])
    cls = np.asarray(st["cls"])
    M, P, S, C = dev.M, dev.P, dev.S, dev.C
    num_pus = dev.num_pus

    placed = live & (pu >= 0)
    machine = np.clip(pu, 0, num_pus - 1) // P
    census = np.zeros((M, C), np.int64)
    np.add.at(census, (machine[placed], cls[placed]), 1)

    pu_running = np.zeros(num_pus, np.int64)
    np.add.at(pu_running, pu[placed], 1)
    enabled = np.asarray(st["machine_enabled"])
    pu_free = np.where(np.repeat(enabled, P), S - pu_running, 0)
    machine_free = pu_free.reshape(M, P).sum(axis=1)

    cost_cm = np.asarray(dev.class_cost_fn(jnp.asarray(census))).astype(np.int64)
    w = cost_cm + dev.ec_cost - dev.unsched_cost

    col_cap = np.zeros(dev.Mp, np.int64)
    if dev.preemption:
        supply = np.bincount(cls[live], minlength=C)
        col_cap[:M] = np.where(enabled, P * S, 0)
        col_cap[-1] = supply.sum()
        R = np.zeros((C, M), np.int64)
        np.add.at(R, (cls[placed], machine[placed]), 1)
        return (w.astype(np.int32), supply.astype(np.int32),
                col_cap.astype(np.int32), R.astype(np.int32))

    unplaced = live & (pu < 0)
    W = dev.decode_width or dev.Tcap
    rows = np.nonzero(unplaced)[0][:W]
    supply = np.bincount(cls[rows], minlength=C)
    col_cap[:M] = machine_free
    col_cap[-1] = supply.sum()
    return w.astype(np.int32), supply.astype(np.int32), col_cap.astype(np.int32)


def replay(args) -> None:
    import jax.numpy as jnp

    from ksched_tpu.solver.layered import (
        _solve_transport,
        choose_eps0,
        default_eps0,
    )

    data = np.load(args.inst)
    n = int(data["n"])
    n_scale = int(data["n_scale"])
    Mp = int(data["Mp"])
    alphas = [int(a) for a in args.alpha.split(",")]
    refines = [int(r) for r in args.refine.split(",")]

    for k in range(n):
        w = data[f"w_{k}"].astype(np.int64)
        supply = data[f"supply_{k}"]
        col_cap = data[f"colcap_{k}"]
        orig = int(data[f"ss_{k}"])
        C, M = w.shape
        wP = np.zeros((C, Mp), np.int64)
        wP[:, :M] = w
        wS = jnp.asarray((wP * n_scale).astype(np.int32))
        sup = jnp.asarray(supply)
        cap = jnp.asarray(col_cap)
        eps_full = int(max(1, np.abs(wP).max() * n_scale))
        eps0 = int(
            choose_eps0(n_scale, eps_full, int(supply.sum()),
                        int(col_cap[:M].sum()))
        )
        print(f"instance {k}: C={C} M={M} supply={supply.tolist()} "
              f"cap_total={int(col_cap[:M].sum())} orig_ss={orig}")
        for alpha in alphas:
            for refine in refines:
                y, _pm, steps, conv = _solve_transport(
                    wS, sup, cap, jnp.int32(eps0), None,
                    alpha=alpha, max_supersteps=1 << 17,
                    refine_waves=refine,
                )
                obj = int(np.sum(np.asarray(y, np.int64)[:, :M] * wP[:, :M]))
                print(
                    f"  alpha={alpha} refine={refine}: "
                    f"ss={int(steps)} conv={bool(conv)} obj={obj}"
                )


def replay_tiered(args) -> None:
    """Re-solve captured PREEMPT (tiered) instances under eps0/refine
    sweeps — transport_fori_tiered outside the jitted round."""
    import jax.numpy as jnp

    from ksched_tpu.solver.layered import transport_fori_tiered

    data = np.load(args.inst)
    assert int(data["preempt"]) == 1, "not a preempt capture"
    n = int(data["n"])
    n_scale = int(data["n_scale"])
    Mp = int(data["Mp"])
    discount = int(data["discount"])
    refines = [int(r) for r in args.refine.split(",")]

    for k in range(n):
        w = data[f"w_{k}"].astype(np.int64)
        supply = data[f"supply_{k}"]
        col_cap = data[f"colcap_{k}"]
        R = data[f"residents_{k}"].astype(np.int64)
        orig = int(data[f"ss_{k}"])
        C, M = w.shape
        wHiP = np.zeros((C, Mp), np.int64)
        wHiP[:, :M] = w
        wLoP = wHiP.copy()
        wLoP[:, :M] -= discount
        RP = np.zeros((C, Mp), np.int64)
        RP[:, :M] = R
        wHi = jnp.asarray((wHiP * n_scale).astype(np.int32))
        wLo = jnp.asarray((wLoP * n_scale).astype(np.int32))
        RJ = jnp.asarray(RP.astype(np.int32))
        supJ = jnp.asarray(supply)
        capJ = jnp.asarray(col_cap)
        eps_full = int(max(1, np.abs(wHiP).max() * n_scale))
        print(f"inst {k}: C={C} total={int(supply.sum())} "
              f"residents={int(R.sum())} cap={int(col_cap[:M].sum())} "
              f"orig_ss={orig}")
        obj_ref = None
        for label, eps0 in [("full", eps_full), ("n", n_scale),
                            ("n/4", n_scale // 4), ("n/16", n_scale // 16)]:
            for rw in refines:
                y, _pm, steps, conv = transport_fori_tiered(
                    wLo, wHi, RJ, supJ, capJ, 1 << 17,
                    alpha=8, eps0=int(max(1, eps0)), refine_waves=rw,
                )
                yr = np.asarray(y, np.int64)[:, :M]
                ret = np.minimum(yr, R)
                obj = int((wHiP[:, :M] * yr).sum() - discount * ret.sum())
                if obj_ref is None:
                    obj_ref = obj
                drift = "" if obj == obj_ref else f"  OBJ {obj - obj_ref:+d}"
                print(f"  eps0={label:5s} refine={rw:2d}: ss={int(steps)} "
                      f"conv={bool(conv)}{drift}")


def main():
    ap = argparse.ArgumentParser()
    sub = ap.add_subparsers(dest="cmd", required=True)
    cap = sub.add_parser("capture")
    cap.add_argument(
        "--config", default="whare",
        choices=["whare", "coco", "quincy", "multiblock", "coco-preempt"],
    )
    cap.add_argument("--rounds", type=int, default=200)
    cap.add_argument("--warmup", type=int, default=0)
    cap.add_argument("--threshold", type=int, default=5000)
    cap.add_argument("--out", default="/tmp/tails.npz")
    cap.add_argument("--verbose", action="store_true")
    cap.set_defaults(fn=capture)
    rep = sub.add_parser("replay")
    rep.add_argument("--inst", default="/tmp/tails.npz")
    rep.add_argument("--alpha", default="2,8")
    rep.add_argument("--refine", default="8,32")
    rep.set_defaults(fn=replay)
    repg = sub.add_parser("replay-grouped")
    repg.add_argument("--inst", default="/tmp/tails_q.npz")
    repg.add_argument(
        "--strategies",
        default="two:1:256,two:n4:1024,full:n4:2,full:n:2",
        help="comma list: two:<eps0>:<budget> or full:<eps0>:<alpha>",
    )
    repg.set_defaults(fn=replay_grouped)
    rept = sub.add_parser("replay-tiered")
    rept.add_argument("--inst", default="/tmp/tails_preempt.npz")
    rept.add_argument("--refine", default="0,8")
    rept.set_defaults(fn=replay_tiered)
    args = ap.parse_args()
    args.fn(args)


if __name__ == "__main__":
    main()
