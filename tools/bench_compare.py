#!/usr/bin/env python
"""The bench-trajectory ratchet: append runs, gate regressions.

`BENCH_TRAJECTORY.jsonl` is the checked-in latency history: one JSON
line per bench run with the config, backend, p50, and superstep
detail. `append` folds a fresh bench record (the JSON line bench.py
prints, or a BENCH_*.json artifact) into it; `gate` (the `make
bench-gate` entry) fails when any config's NEWEST entry regressed
more than the tolerance vs its PREVIOUS entry — the committed
equivalent of "don't merge a p50 regression", enforceable without
re-running the bench in CI.

The gate ratchets TWO axes per (config, platform) series: wall-clock
`p50_ms`, and — for entries that carry it (the churn/event-path
series) — `supersteps_p50`, the solver-work-per-round measure that
wall clock alone can hide on a fast host (a warm-start price war that
burns 600+ supersteps still finishes in milliseconds on an idle CPU,
then detonates under load). Supersteps get a relative tolerance plus
a small absolute slack, since healthy values sit near ~10 where ±
a-few is quantization, not regression.

Cross-platform readings don't gate each other: entries compare only
within the same (config, platform, mesh_devices) series — the mesh
shape (device count) is part of the series identity, so a 2-dev CPU
sharded reading never baselines an 8-dev one — and entries stamped
`accelerator_unreachable` are never used as a baseline for device
readings.

Usage:
    python tools/bench_compare.py append TRAJ.jsonl --from-bench out.json \
        [--config NAME] [--note TEXT]
    python tools/bench_compare.py gate TRAJ.jsonl [--tolerance 0.15]
    python tools/bench_compare.py show TRAJ.jsonl
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import List, Optional

DEFAULT_TOLERANCE = 0.15
#: supersteps ratchet: relative tolerance + absolute slack (healthy
#: churn-series values are ~10; integer jitter of a few steps is
#: quantization, a jump past ~25% AND +8 is a warm-start regression)
SUPERSTEPS_TOLERANCE = 0.25
SUPERSTEPS_SLACK = 8


def _git_commit() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ).stdout.strip() or "unknown"
    except Exception:
        return "unknown"


def _platform_of(record: dict) -> str:
    if record.get("accelerator_unreachable"):
        return "cpu-fallback"
    metric = record.get("metric", "")
    if "backend=" in metric:
        return metric.rsplit("/", 1)[-1].strip()
    return "unknown"


def entry_from_record(record: dict, config: Optional[str] = None,
                      note: Optional[str] = None) -> dict:
    """Normalize one bench.py JSON record into a trajectory entry."""
    detail = record.get("detail") or {}
    entry = {
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "commit": _git_commit(),
        "config": config or record.get("config") or "10kx1k",
        "platform": _platform_of(record),
        "metric": record.get("metric", ""),
        "p50_ms": record.get("value"),
        "vs_baseline": record.get("vs_baseline"),
    }
    for key in ("supersteps_p50", "supersteps_p99", "supersteps_max"):
        if key in detail:
            entry[key] = detail[key]
    # mesh shape: multi-chip readings are their own series — a 2-dev
    # CPU reading must never baseline (or gate) an 8-dev one, the same
    # isolation rule as cross-platform entries
    mesh = detail.get("mesh_devices", record.get("mesh_devices"))
    if mesh is not None:
        entry["mesh_devices"] = int(mesh)
    # the churn (round-pipeline) config: lift the arm comparison into
    # the series so the ratchet history shows WHERE the p50 comes from
    arms = detail.get("arms")
    if isinstance(arms, dict):
        dr = arms.get("device_resident") or {}
        fr = arms.get("full_rebuild") or {}
        if dr.get("supersteps_p50") is not None:
            entry["supersteps_p50"] = dr["supersteps_p50"]
        if dr.get("h2d_delta_bytes_per_round") is not None:
            entry["h2d_delta_bytes_per_round"] = dr["h2d_delta_bytes_per_round"]
        if fr.get("p50_ms") is not None:
            entry["full_rebuild_p50_ms"] = fr["p50_ms"]
        if "p50_improvement_vs_full_rebuild" in detail:
            entry["p50_improvement_vs_full_rebuild"] = detail[
                "p50_improvement_vs_full_rebuild"
            ]
    if record.get("accelerator_unreachable"):
        entry["accelerator_unreachable"] = True
    if note:
        entry["note"] = note
    return entry


def load_trajectory(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for i, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError as e:
                raise SystemExit(f"{path}:{i + 1}: bad JSON line: {e}")
    return out


def append_cmd(args) -> int:
    with open(args.from_bench) as f:
        text = f.read().strip()
    # accept either a single JSON object or JSONL (take the last
    # bench record line, skipping suite provenance stamps)
    records = []
    try:
        doc = json.loads(text)
        records = [doc]
    except json.JSONDecodeError:
        for line in text.splitlines():
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if not rec.get("suite_stamp"):
                records.append(rec)
    if not records:
        raise SystemExit(f"no bench records in {args.from_bench}")
    wrote = 0
    with open(args.trajectory, "a") as f:
        for rec in records:
            if rec.get("value") is None:
                print(f"# skipping failed record: {rec.get('metric')}",
                      file=sys.stderr)
                continue
            entry = entry_from_record(rec, config=args.config, note=args.note)
            f.write(json.dumps(entry) + "\n")
            wrote += 1
    print(f"appended {wrote} entr{'y' if wrote == 1 else 'ies'} to "
          f"{args.trajectory}")
    return 0


def _series_key(entry: dict):
    # mesh shape (device count) is part of the series identity: sharded
    # readings taken on different mesh sizes are different experiments
    # (single-chip entries carry no mesh field and keep their series)
    return (
        entry.get("config"), entry.get("platform"), entry.get("mesh_devices")
    )


def gate_cmd(args) -> int:
    entries = load_trajectory(args.trajectory)
    if not entries:
        raise SystemExit(f"{args.trajectory} is empty; nothing to gate")
    series = {}
    for e in entries:
        if e.get("p50_ms") is None:
            continue
        series.setdefault(_series_key(e), []).append(e)
    failures = []
    checked = 0
    for (config, platform, mesh), es in sorted(
        series.items(),
        key=lambda kv: (
            str(kv[0][0]), str(kv[0][1]),
            -1 if kv[0][2] is None else int(kv[0][2]),
        ),
    ):
        if len(es) < 2:
            continue
        prev, last = es[-2], es[-1]
        # a cpu-fallback reading must not gate (or baseline) a device
        # series; same-platform by key, but double-check the stamp
        if prev.get("accelerator_unreachable") != last.get(
            "accelerator_unreachable"
        ):
            continue
        checked += 1
        p_prev, p_last = float(prev["p50_ms"]), float(last["p50_ms"])
        ratio = (p_last - p_prev) / max(p_prev, 1e-9)
        tag = f"{config} [{platform}]" + (
            f" [{mesh}dev]" if mesh is not None else ""
        )
        verdict = "OK" if ratio <= args.tolerance else "REGRESSED"
        print(
            f"{tag:<40} p50 {p_prev:9.3f} -> {p_last:9.3f} ms "
            f"({ratio:+8.1%})  {verdict}"
        )
        if ratio > args.tolerance:
            failures.append(
                f"{tag}: p50 {p_prev:.3f} -> {p_last:.3f} ms "
                f"(+{ratio:.1%} > {args.tolerance:.0%} tolerance; "
                f"{prev.get('commit')} -> {last.get('commit')})"
            )
        # supersteps ratchet: only when BOTH entries carry the field
        # (the churn/event-path series); regression requires blowing
        # the relative tolerance AND the absolute slack
        if prev.get("supersteps_p50") is not None and last.get(
            "supersteps_p50"
        ) is not None:
            s_prev = float(prev["supersteps_p50"])
            s_last = float(last["supersteps_p50"])
            s_ratio = (s_last - s_prev) / max(s_prev, 1e-9)
            bad = (
                s_ratio > args.supersteps_tolerance
                and s_last - s_prev > SUPERSTEPS_SLACK
            )
            print(
                f"{tag:<40} ss  {s_prev:9.0f} -> {s_last:9.0f}    "
                f"({s_ratio:+8.1%})  {'REGRESSED' if bad else 'OK'}"
            )
            if bad:
                failures.append(
                    f"{tag}: supersteps_p50 {s_prev:.0f} -> {s_last:.0f} "
                    f"(+{s_ratio:.1%} > {args.supersteps_tolerance:.0%} "
                    f"tolerance and +{s_last - s_prev:.0f} > "
                    f"{SUPERSTEPS_SLACK} slack; warm-start price war "
                    f"creeping back? {prev.get('commit')} -> "
                    f"{last.get('commit')})"
                )
    if not checked:
        print("gate: no series has two comparable entries yet (pass)")
        return 0
    if failures:
        print("\nBENCH GATE FAILED:", file=sys.stderr)
        for f_ in failures:
            print(f"  {f_}", file=sys.stderr)
        return 1
    print(f"bench gate OK: {checked} series within "
          f"{args.tolerance:.0%} of their previous entry")
    return 0


def show_cmd(args) -> int:
    entries = load_trajectory(args.trajectory)
    print(f"{'utc':<22} {'commit':<9} {'config':<22} {'platform':<13} "
          f"{'p50_ms':>9} {'ss_p50':>7}")
    for e in entries:
        p50 = e.get("p50_ms")
        p50_s = f"{p50:>9.3f}" if p50 is not None else f"{'—':>9}"
        print(
            f"{e.get('utc', ''):<22} {e.get('commit', ''):<9} "
            f"{e.get('config', ''):<22} {e.get('platform', ''):<13} "
            f"{p50_s} {e.get('supersteps_p50', ''):>7}"
        )
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    ap_append = sub.add_parser("append", help="fold a bench record in")
    ap_append.add_argument("trajectory")
    ap_append.add_argument("--from-bench", required=True,
                           help="bench.py output JSON (line or artifact)")
    ap_append.add_argument("--config", default=None,
                           help="override the config name")
    ap_append.add_argument("--note", default=None)
    ap_append.set_defaults(fn=append_cmd)
    ap_gate = sub.add_parser("gate", help="fail on p50 regression")
    ap_gate.add_argument("trajectory")
    ap_gate.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                         help="max allowed relative p50 increase "
                         "(default 0.15)")
    ap_gate.add_argument("--supersteps-tolerance", type=float,
                         default=SUPERSTEPS_TOLERANCE,
                         help="max allowed relative supersteps_p50 "
                         "increase for series that carry it "
                         "(default 0.25; +8 absolute slack)")
    ap_gate.set_defaults(fn=gate_cmd)
    ap_show = sub.add_parser("show", help="tabulate the trajectory")
    ap_show.add_argument("trajectory")
    ap_show.set_defaults(fn=show_cmd)
    args = ap.parse_args()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
