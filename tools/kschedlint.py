"""kschedlint: the repo's AST lint CLI (Level 1 of ksched_tpu.analysis).

Usage:
    python -m tools.kschedlint ksched_tpu tools bench.py
    python -m tools.kschedlint --write-baseline ksched_tpu tools bench.py

Exit status: 0 when every violation is suppressed inline or recorded in
the baseline; 1 when NEW violations exist (printed one per line as
`path:line:col: rule: message`); 2 on usage errors. Stale baseline
entries (fixed violations still listed) are reported as a warning —
run --write-baseline to shed them.

The jaxpr contracts (Level 2) need jax and are run by
tests/test_static_analysis.py, not this CLI, so the lint stays usable
in environments without the jax_graft toolchain.
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # `python tools/kschedlint.py` direct invocation
    sys.path.insert(0, _REPO_ROOT)

from ksched_tpu.analysis import (  # noqa: E402
    RULES,
    lint_paths,
    load_baseline,
    split_by_baseline,
    write_baseline,
)

DEFAULT_BASELINE = os.path.join("tools", "kschedlint_baseline.json")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kschedlint", description=__doc__)
    parser.add_argument("paths", nargs="*", default=["ksched_tpu", "tools", "bench.py"],
                        help="files/directories to lint (default: the library, "
                        "tools, and bench.py)")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline JSON (repo-relative)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline: every violation fails")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept current violations into the baseline and exit 0")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--root", default=_REPO_ROOT,
                        help="repo root paths are resolved against")
    args = parser.parse_args(argv)

    if args.list_rules:
        for name, fn in RULES.items():
            doc = (fn.__doc__ or "").strip().split("\n")[0]
            print(f"{name:16s} {doc}")
        return 0

    for p in args.paths:
        # os.path.join passes absolute p through untouched, so this
        # also rejects a typo'd absolute path instead of "cleanly"
        # linting zero files
        if not os.path.exists(os.path.join(args.root, p)):
            print(f"kschedlint: no such path: {p}", file=sys.stderr)
            return 2

    violations = lint_paths(args.paths, repo_root=args.root)
    baseline_path = os.path.join(args.root, args.baseline)

    if args.write_baseline:
        count = write_baseline(baseline_path, violations)
        print(f"kschedlint: baseline written with {count} entr{'y' if count == 1 else 'ies'}")
        return 0

    from collections import Counter

    baseline = Counter() if args.no_baseline else load_baseline(baseline_path)
    new, old, stale = split_by_baseline(violations, baseline)

    for v in new:
        print(v.render())
    if old:
        print(f"kschedlint: {len(old)} baselined violation(s) not shown "
              f"(ratchet debt in {args.baseline})", file=sys.stderr)
    if stale:
        print(f"kschedlint: {sum(stale.values())} stale baseline entr(y/ies) — "
              "the violations were fixed; run --write-baseline to shed them",
              file=sys.stderr)
    if new:
        print(f"kschedlint: {len(new)} new violation(s)", file=sys.stderr)
        return 1
    print(f"kschedlint: clean ({len(old)} baselined, "
          f"{len(list(RULES))} rules)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
