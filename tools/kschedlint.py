"""kschedlint: the repo's AST lint CLI (Levels 1+3 of ksched_tpu.analysis).

Usage:
    python -m tools.kschedlint ksched_tpu tools bench.py
    python -m tools.kschedlint --coverage ksched_tpu tools bench.py
    python -m tools.kschedlint --rules dtype64,unregistered-program ksched_tpu
    python -m tools.kschedlint --json ksched_tpu tools bench.py
    python -m tools.kschedlint --prune-baseline ksched_tpu tools bench.py

Exit status: 0 when every violation is suppressed inline or recorded in
the baseline AND the baseline carries no stale entries; 1 when NEW
violations exist (printed one per line as `path:line:col: rule:
message`) or when baseline entries match no current violation (the
ratchet only shrinks — run --prune-baseline to shed fixed debt);
2 on usage errors, including unknown rule names in --rules.

--coverage adds the Level-3 program-coverage report: every
jax.jit / pl.pallas_call / shard_map call site in library code must be
annotated with a registered `# kschedlint: program=<name>` or waived
with `# kschedlint: disable=unregistered-program -- rationale`, and
every registered site name must be annotated somewhere. The summary
line is printed either way.

The jaxpr contracts and the registry engine (Level 2/3 dynamic checks)
need jax and are run by tests/test_static_analysis.py, not this CLI,
so the lint stays usable in environments without the jax_graft
toolchain. The registry's declarative side (program names, site
annotations) is stdlib-only and IS checked here.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from collections import Counter

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:  # `python tools/kschedlint.py` direct invocation
    sys.path.insert(0, _REPO_ROOT)

from ksched_tpu.analysis import (  # noqa: E402
    RULES,
    fingerprint,
    lint_paths,
    load_baseline,
    program_coverage,
    split_by_baseline,
    write_baseline,
)
from ksched_tpu.analysis.program_registry import PROGRAMS  # noqa: E402

DEFAULT_BASELINE = os.path.join("tools", "kschedlint_baseline.json")


def _coverage_summary(cov) -> str:
    return (
        f"kschedlint L3: {len(PROGRAMS)} programs registered / "
        f"{cov['sites']} call sites swept / "
        f"{len(cov['waived'])} waived / "
        f"{len(cov['unaudited'])} unaudited"
    )


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="kschedlint", description=__doc__)
    parser.add_argument("paths", nargs="*", default=["ksched_tpu", "tools", "bench.py"],
                        help="files/directories to lint (default: the library, "
                        "tools, and bench.py)")
    parser.add_argument("--baseline", default=DEFAULT_BASELINE,
                        help="baseline JSON (repo-relative)")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore the baseline: every violation fails")
    parser.add_argument("--write-baseline", action="store_true",
                        help="accept current violations into the baseline and exit 0")
    parser.add_argument("--prune-baseline", action="store_true",
                        help="shed stale baseline entries (shrink-only: never "
                        "adds debt) and exit 0 if nothing new")
    parser.add_argument("--rules", default=None, metavar="R1,R2",
                        help="run only these rules (unknown names exit 2)")
    parser.add_argument("--coverage", action="store_true",
                        help="also run the Level-3 program-coverage report; "
                        "unaudited sites or unannotated registered programs fail")
    parser.add_argument("--json", dest="as_json", action="store_true",
                        help="emit one machine-readable JSON object on stdout")
    parser.add_argument("--list-rules", action="store_true")
    parser.add_argument("--root", default=_REPO_ROOT,
                        help="repo root paths are resolved against")
    args = parser.parse_args(argv)

    if args.list_rules:
        for name, fn in RULES.items():
            doc = (fn.__doc__ or "").strip().split("\n")[0]
            print(f"{name:20s} {doc}")
        return 0

    rules = None
    if args.rules is not None:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown or not rules:
            print(f"kschedlint: unknown rule(s) in --rules: {unknown or '(none given)'} "
                  f"(known: {', '.join(RULES)})", file=sys.stderr)
            return 2

    for p in args.paths:
        # os.path.join passes absolute p through untouched, so this
        # also rejects a typo'd absolute path instead of "cleanly"
        # linting zero files
        if not os.path.exists(os.path.join(args.root, p)):
            print(f"kschedlint: no such path: {p}", file=sys.stderr)
            return 2

    violations = lint_paths(args.paths, repo_root=args.root, rules=rules)
    baseline_path = os.path.join(args.root, args.baseline)

    if args.write_baseline:
        count = write_baseline(baseline_path, violations)
        print(f"kschedlint: baseline written with {count} entr{'y' if count == 1 else 'ies'}")
        return 0

    baseline = Counter() if args.no_baseline else load_baseline(baseline_path)
    new, old, stale = split_by_baseline(violations, baseline)

    if args.prune_baseline:
        # shrink-only: keep exactly the entries current violations
        # still consume; NEVER admits new debt (that is --write-baseline,
        # which demands an explicit decision)
        count = write_baseline(baseline_path, old)
        stale = Counter()

    cov = None
    coverage_problems = []
    if args.coverage:
        cov = program_coverage(args.paths, repo_root=args.root)
        for entry in cov["unaudited"]:
            coverage_problems.append(
                f"{entry['path']}:{entry['line']}: unaudited program site "
                f"`{entry['callee']}` ({entry['kind']})"
            )
        for name in cov["unannotated_registered"]:
            coverage_problems.append(
                f"registry: program site `{name}` is registered but annotated "
                "at no call site — annotate it or drop the spec"
            )

    if args.as_json:
        payload = {
            "new": [
                {"path": v.path, "line": v.line, "col": v.col,
                 "rule": v.rule, "message": v.message}
                for v in new
            ],
            "baselined": len(old),
            "stale_baseline": [
                {"path": p, "rule": r, "hash": h, "count": c}
                for (p, r, h), c in sorted(stale.items())
            ],
            "rules": list(RULES if rules is None else rules),
        }
        if cov is not None:
            payload["coverage"] = {
                "programs_registered": len(PROGRAMS),
                "sites": cov["sites"],
                "annotated": cov["annotated"],
                "waived": cov["waived"],
                "unaudited": cov["unaudited"],
                "unannotated_registered": cov["unannotated_registered"],
                "summary": _coverage_summary(cov),
            }
        payload["ok"] = not (new or stale or coverage_problems)
        print(json.dumps(payload, indent=2, sort_keys=True))
        return 0 if payload["ok"] else 1

    for v in new:
        print(v.render())
    for line in coverage_problems:
        print(line)
    if old:
        print(f"kschedlint: {len(old)} baselined violation(s) not shown "
              f"(ratchet debt in {args.baseline})", file=sys.stderr)
    if stale:
        print(f"kschedlint: {sum(stale.values())} stale baseline entr(y/ies) — "
              "the violations were fixed; run --prune-baseline to shed them",
              file=sys.stderr)
    if cov is not None:
        print(_coverage_summary(cov), file=sys.stderr)
    if new or stale or coverage_problems:
        problems = len(new) + sum(stale.values()) + len(coverage_problems)
        print(f"kschedlint: {problems} problem(s)", file=sys.stderr)
        return 1
    print(f"kschedlint: clean ({len(old)} baselined, "
          f"{len(list(RULES if rules is None else rules))} rules)", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
