#!/usr/bin/env python
"""Calibrate the TPU primitive costs that bound the general-graph
(CSR/ELL) solver: random gather, cumsum, associative scan, and dense
row reductions, at the shapes the 10k x 1k flow graph produces.

Motivation (round 5): the bucketed-ELL rewrite removed every global
scan from the push-relabel superstep and measured ... no win (59.2 vs
60.5 ms/solve). Either gathers dominate both layouts, or the cost is
somewhere else entirely. This tool measures each primitive in an
isolated data-chained loop so the 60 ms has an arithmetic explanation.

Each measurement chains REPS applications inside one jitted scan with
a REAL loop-carried dependency — the measured op's result feeds the
next iteration's operand through arithmetic XLA cannot fold away (an
earlier revision used `result * 0`, which the algebraic simplifier
folds to 0, turning the timed op loop-invariant and hoistable — the
gather/cumsum/scan rows measured launch floor, not the op). Closed by
the scalar-fetch barrier, following docs/NOTES.md measurement
discipline.
"""

import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax


def timed_chain(body, state0, reps, label, results):
    """body(state) -> state with identical structure; chains reps."""

    def chain(s0):
        def step(s, _):
            return body(s), ()

        out, _ = lax.scan(step, s0, None, length=reps)
        return out

    fn = jax.jit(chain)
    out = fn(state0)
    jax.block_until_ready(out)
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(jax.device_get(leaf)).ravel()[:1]
    t0 = time.perf_counter()
    out = fn(state0)
    jax.block_until_ready(out)
    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(jax.device_get(leaf)).ravel()[:1]
    wall_ms = (time.perf_counter() - t0) * 1e3
    per_us = wall_ms * 1e3 / reps
    results[label] = round(per_us, 2)
    print(f"  {label:34s} {per_us:9.2f} us/op  (wall {wall_ms:.0f} ms)",
          file=sys.stderr)


def main():
    reps = int(sys.argv[1]) if len(sys.argv) > 1 else 2048
    rng = np.random.default_rng(0)
    N = 32768          # nodes
    E = 131072         # doubled residual entries (CSR layout)
    ES, W = 32768, 8   # ELL small block
    results = {}
    platform = jax.devices()[0].platform
    print(f"# platform={platform} reps={reps}", file=sys.stderr)

    table = jnp.asarray(rng.integers(0, 100, N).astype(np.int32))
    idx_e = jnp.asarray(rng.integers(0, N, E).astype(np.int32))
    idx_ell = jnp.asarray(rng.integers(0, N, (ES, W)).astype(np.int32))
    vec_e = jnp.asarray(rng.integers(0, 100, E).astype(np.int32))
    mat = jnp.asarray(rng.integers(0, 100, (ES, W)).astype(np.int32))
    flags = jnp.asarray(rng.random(E) < 0.25)

    # gather: E random indices into an N-entry table (p[s_src] etc.)
    def g1_body(s):
        t, acc = s
        g = t[idx_e]
        return t + g[0], g

    timed_chain(
        g1_body, (table, jnp.zeros(E, jnp.int32)),
        reps, f"gather {E} from {N} (flat int32)", results,
    )
    # gather in ELL shape: [32768, 8] indices
    def g2_body(s):
        t, acc = s
        g = t[idx_ell]
        return t + g[0, 0], g

    timed_chain(
        g2_body, (table, jnp.zeros((ES, W), jnp.int32)),
        reps, f"gather [{ES},{W}] from {N}", results,
    )
    # the same [32768, 8] gather expressed as flat-gather + reshape —
    # measures whether the 2D-index lowering (1952 us measured) is a
    # shape artifact the solver can route around
    def g3_body(s):
        t, acc = s
        g = t[idx_ell.reshape(-1)].reshape(ES, W)
        return t + g[0, 0], g

    timed_chain(
        g3_body, (table, jnp.zeros((ES, W), jnp.int32)),
        reps, f"gather [{ES},{W}] via flat+reshape", results,
    )
    # pure flat gather at the SAME element count (2*E) — is the 2D
    # cost a per-element truth or a lowering artifact?
    idx_flat2 = jnp.asarray(rng.integers(0, N, ES * W).astype(np.int32))

    def g4_body(s):
        t, acc = s
        g = t[idx_flat2]
        return t + g[0], g

    timed_chain(
        g4_body, (table, jnp.zeros(ES * W, jnp.int32)),
        reps, f"gather {ES * W} flat", results,
    )
    # flat gather + optimization_barrier + reshape: blocks XLA from
    # fusing the reshape back into a 2D-indexed gather
    def g5_body(s):
        t, acc = s
        g = t[idx_ell.reshape(-1)]
        g = jax.lax.optimization_barrier(g)
        return t + g[0], g.reshape(ES, W)

    timed_chain(
        g5_body, (table, jnp.zeros((ES, W), jnp.int32)),
        reps, f"gather [{ES},{W}] flat+barrier+reshape", results,
    )
    # cumsum over E
    def cs_body(s):
        v, acc = s
        c = jnp.cumsum(v)
        return v + c[0], c

    timed_chain(
        cs_body, (vec_e, jnp.zeros(E, jnp.int32)),
        reps, f"cumsum {E} (int32)", results,
    )
    # segmented max via associative scan over E (the CSR relabel)
    def as_body(s):
        v, acc = s

        def combine(a, b):
            f1, v1 = a
            f2, v2 = b
            return f1 | f2, jnp.where(f2, v2, jnp.maximum(v1, v2))

        _, scanned = lax.associative_scan(combine, (flags, v))
        return v + scanned[0], scanned

    timed_chain(
        as_body, (vec_e, jnp.zeros(E, jnp.int32)),
        reps, f"assoc-scan segmax {E}", results,
    )
    # dense row reduce [32768, 8] -> [32768] (the ELL per-node combine)
    def rr_body(s):
        m, acc = s
        r = jnp.sum(m, axis=1)
        return m + r[0], r

    timed_chain(
        rr_body, (mat, jnp.zeros(ES, jnp.int32)),
        reps, f"row-sum [{ES},{W}]", results,
    )
    # elementwise pass over E (the floor: one fused map)
    def ew_body(s):
        v, acc = s
        v2 = v * 3 + 1
        return v2 - v2[0] // 2, v2

    timed_chain(
        ew_body, (vec_e, jnp.zeros(E, jnp.int32)),
        reps, f"elementwise {E}", results,
    )
    print(json.dumps({"platform": platform, "reps": reps,
                      "per_op_us": results}))


if __name__ == "__main__":
    main()
