#!/usr/bin/env python
"""Benchmark: p50 scheduling-round latency at 10k tasks x 1k machines.

The driver-set north star (BASELINE.json): <10 ms p50 round latency on a
10k-task / 1k-machine flow graph with the trivial cost model, solved by
the JAX/TPU backend. The measurement point mirrors the reference's round
timer around ScheduleAllJobs (cmd/k8sscheduler/scheduler.go:146-150):
one round = stats/capacity refresh + solve + decode + apply.

Prints ONE JSON line:
    {"metric": ..., "value": p50_ms, "unit": "ms", "vs_baseline": ...}

vs_baseline is target_ms / p50_ms (>= 1.0 means the 10 ms target is met).

Steady-state protocol: fill the cluster to ~95%, then each round
complete ~1% of running tasks and admit the same number of new ones —
the incremental re-solve regime Flowlessly's daemon mode serves in the
reference. Use --cold for full from-scratch solves instead.
"""

import argparse
import json
import math
import os
import sys
import time
from typing import Optional

import numpy as np


def _accelerator_alive(timeout_s: float = 90.0) -> bool:
    """Probe the ambient accelerator in a subprocess: a wedged TPU tunnel
    hangs backend init forever, which must not take the benchmark down."""
    import subprocess

    try:
        r = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _emit_record(out: dict, args) -> None:
    """Print one JSON record, stamping accelerator_unreachable when
    this process (or the suite parent that spawned it) fell back from
    a wedged accelerator — a CPU-host reading must be machine-
    distinguishable from a device measurement in EVERY record."""
    if getattr(args, "fell_back", False):
        out["accelerator_unreachable"] = True
    print(json.dumps(out))


def _solver_work(backend) -> int:
    """Iterations/supersteps the backend spent on its last solve."""
    return getattr(backend, "last_supersteps", None) or getattr(backend, "last_iterations", 0)


#: the tunneled-TPU completion-polling floor (docs/NOTES.md): wall-clock
#: readings of device work are only trustworthy once a timed region
#: exceeds this by a wide margin — short work reads artificially fast
#: (microseconds), so a per-round number derived from a sub-floor chunk
#: is an artifact, not a measurement.
FLOOR_MS = 110.0
#: minimum wall time of a timed chunk before its per-round quotient is
#: believed. Two artifacts set it: the completion-polling floor above,
#: and the fact that jax.block_until_ready can RETURN EARLY on this
#: transport for some executables (measured: a scanned XLA-while-loop
#: solve "blocks" in ~1 ms while the real execution surfaces only at
#: fetch). Every timed chunk therefore ends with a small scalar fetch
#: — the one operation that provably waits for the chain — and the
#: ~100-200 ms fetch round-trip plus the post-first-fetch dispatch
#: degradation (~90 ms, docs/NOTES.md) must stay a small fraction of
#: the wall: 2 s keeps the overhead under ~10%.
MIN_CHUNK_WALL_MS = 2_000.0
#: leave-one-out relative-error bar above which a latency-model fit is
#: flagged suspect (tunnel-flake chunk walls poison the lstsq fit —
#: docs/NOTES.md "tunnel flakiness"; clean fits on this transport
#: measure held-out errors well under this)
LOO_SUSPECT_REL_ERR = 0.25


def _round_latency_model(chunk_walls_ms, R, ss_per_chunk, full_per_chunk=None):
    """Per-round latency distribution from chunked measurements.

    The chunk apparatus can only time R-round chains (the transport's
    completion floor forbids per-round fetches — MIN_CHUNK_WALL_MS), so
    per-round walls are unobservable directly. But per-ROUND superstep
    counts ARE recorded, and the round cost decomposes as a fixed
    overhead plus a per-superstep cost:

        wall_chunk = R * t_fixed + kappa * sum(supersteps in chunk)

    Chunks with different superstep totals identify (t_fixed, kappa) by
    least squares; each round's latency is then t_fixed + kappa * ss_i.
    This is the calibrated stand-in for the reference's per-round timer
    (cmd/k8sscheduler/scheduler.go:146-150), which the device path
    cannot carry — and it makes the TAIL visible: a chunk mean hides a
    25k-superstep round inside 16383 cheap ones.

    Returns a dict with the fit and the p50/p99/max of the modeled
    per-round latency. Fit degeneracies (all-equal superstep totals, or
    a negative component from noise) clamp to the chunk-mean model —
    flagged via "fit" so readers know which regime produced the number.

    OUT-OF-SAMPLE CHECK (VERDICT r3 #3): with >= 3 chunks, each chunk's
    wall is predicted by a model fit on the OTHERS (leave-one-out); the
    relative errors ride along as loo_rel_err_mean/max and
    "fit_suspect" flags fits whose held-out prediction misses by more
    than LOO_SUSPECT_REL_ERR — replacing the eyeball-the-kappa
    discipline docs/NOTES.md used for poisoned (tunnel-flake) series.

    TWO-REGIME MIXTURE (stability-aware preemption): when
    full_per_chunk marks which rounds ran the full tiered re-solve,
    incremental and full rounds get separate per-superstep
    coefficients (the tiered solve's superstep is ~10x the fused
    kernel's) — wall = R*t_fixed + k_i*Σss_incr + k_f*Σss_full — and
    each round's latency maps through its own regime's line.
    """
    walls = np.asarray(chunk_walls_ms, np.float64)
    ss_cat = np.concatenate(ss_per_chunk).astype(np.float64)
    mixture = (
        full_per_chunk is not None
        and any(np.any(f) for f in full_per_chunk)
        and not all(np.all(f) for f in full_per_chunk)
    )
    if mixture:
        full_cat = np.concatenate(full_per_chunk).astype(bool)
        ss_i = np.array([
            float(np.sum(np.asarray(s)[~np.asarray(f, bool)]))
            for s, f in zip(ss_per_chunk, full_per_chunk)
        ])
        ss_f = np.array([
            float(np.sum(np.asarray(s)[np.asarray(f, bool)]))
            for s, f in zip(ss_per_chunk, full_per_chunk)
        ])
    else:
        ss_i = np.array([float(np.sum(s)) for s in ss_per_chunk])
        ss_f = np.zeros_like(ss_i)

    def _fit(w, si, sf):
        """(t_fixed, k_i, k_f, fit_kind) for chunk walls w. The
        2-regime fit needs >= 4 chunks: with exactly 3 the 3-parameter
        system is exactly determined (zero residual df) and fits noise
        — a 3-chunk suite run produced k_incr > k_full, which is
        nonsense; the merged-slope model with its LOO check is the
        honest fallback there."""
        if mixture and len(w) >= 4 and np.ptp(si) > 0 and np.ptp(sf) > 0:
            A = np.stack([np.full_like(si, R), si, sf], axis=1)
            (tf, ki, kf), *_ = np.linalg.lstsq(A, w, rcond=None)
            if tf >= 0 and ki >= 0 and kf >= 0:
                return float(tf), float(ki), float(kf), "lstsq-2regime"
            # degenerate mixture fit: fall through to the single-slope
            # model on combined supersteps
        st = si + sf
        if len(w) >= 2 and np.ptp(st) > 0:
            A = np.stack([np.full_like(st, R), st], axis=1)
            (tf, kp), *_ = np.linalg.lstsq(A, w, rcond=None)
            if kp >= 0 and tf >= 0:
                return float(tf), float(kp), float(kp), "lstsq"
            if kp >= 0:
                # tf < 0: supersteps dominate so strongly the intercept
                # went negative from noise — refit through the origin
                kp = float(np.sum(w * st) / np.sum(st * st))
                return 0.0, kp, kp, "origin"
        # all-equal superstep totals (or a single chunk): all
        # information is in the mean
        m = float(w.mean() / R)
        return m, 0.0, 0.0, "chunk-mean"

    t_fixed, k_i, k_f, fit = _fit(walls, ss_i, ss_f)
    if mixture:
        lat = t_fixed + np.where(full_cat, k_f, k_i) * ss_cat
    else:
        lat = t_fixed + k_i * ss_cat
    out = {
        "fit": fit,
        "fixed_ms": round(t_fixed, 4),
        "per_superstep_us": round(k_i * 1e3, 4),
        "p50_ms": round(float(np.percentile(lat, 50)), 4),
        "p99_ms": round(float(np.percentile(lat, 99)), 4),
        "max_ms": round(float(lat.max()), 4),
    }
    if mixture:
        out["per_superstep_us_full"] = round(k_f * 1e3, 4)
    if len(walls) >= 3:
        # a fold only counts when its subfit ran in the SAME regime as
        # the full fit — a 4-chunk mixture run's 3-chunk subfits can
        # only do the merged-slope model, and judging the 2-regime fit
        # by a merged-slope prediction would flag clean fits (hybrid
        # configs therefore measure 5 chunks: 4-chunk subfits keep the
        # 2-regime form and the LOO check stays live)
        errs = []
        for i in range(len(walls)):
            keep = np.arange(len(walls)) != i
            tf_i, ki_i, kf_i, kind_i = _fit(walls[keep], ss_i[keep], ss_f[keep])
            if kind_i != fit:
                continue
            pred = R * tf_i + ki_i * ss_i[i] + kf_i * ss_f[i]
            errs.append(abs(pred - walls[i]) / max(walls[i], 1e-9))
        if errs:
            out["loo_rel_err_mean"] = round(float(np.mean(errs)), 4)
            out["loo_rel_err_max"] = round(float(np.max(errs)), 4)
            out["fit_suspect"] = bool(np.max(errs) > LOO_SUSPECT_REL_ERR)
    return out


def _device_bench(
    *,
    tasks: int,
    machines: int,
    pus: int,
    slots: int,
    jobs: int,
    churn: float,
    rounds: int,
    chunk: int,
    num_task_classes: int = 1,
    class_cost_fn=None,
    supersteps=None,
    unsched_cost: int = 5,
    ec_cost: int = 2,
    decode_width=None,
    num_groups: int = 0,
    group_setup=None,  # (cluster, rng) -> per-task group ids for the fill
    refine_waves: int = 8,  # matches the DeviceBulkCluster default
    alpha: int = 8,
    preemption: bool = False,
    continuation_discount: int = 1,
    preempt_every: int = 1,
    preempt_drift: int = 0,
    preempt_global_every: int = 0,
    preempt_scope_tau: int = 1,
    preempt_scoped_width=None,
    preempt_incr_budget=None,
    label: str = "trivial cost model",
    verbose: bool = False,
) -> dict:
    """Measure sustained p50 round latency on the device-resident path.

    The timed region per round matches the reference's (everything
    inside ScheduleAllJobs: stats refresh, graph update, solve, decode,
    delta apply — cmd/k8sscheduler/scheduler.go:146-150); binding
    readback happens outside it, as the reference's AssignBinding does.
    Rounds within a chunk are data-dependent (round N's completions draw
    from round N-1's placements), so a chunk is R genuinely sequential
    rounds; its wall time divided by R is the sustained round latency.
    Completion of the whole chain is forced INSIDE the timed region by
    a tiny scalar fetch (jax.block_until_ready alone can return early
    on this transport — see MIN_CHUNK_WALL_MS); chunk walls are sized
    to keep the fetch round-trip and the post-first-fetch dispatch
    degradation (docs/NOTES.md) under ~10% of the reading, erring
    conservative. The bulk stats transfer is still deferred until
    after all timing; convergence of every round is asserted from the
    deferred fetches once the clock stops."""
    import jax
    from ksched_tpu.scheduler.device_bulk import DeviceBulkCluster
    from ksched_tpu.utils import next_pow2

    rng = np.random.default_rng(0)
    dev = DeviceBulkCluster(
        num_machines=machines,
        pus_per_machine=pus,
        slots_per_pu=slots,
        num_jobs=jobs,
        num_task_classes=num_task_classes,
        task_capacity=next_pow2(tasks + 4096),
        class_cost_fn=class_cost_fn,
        supersteps=supersteps,
        unsched_cost=unsched_cost,
        ec_cost=ec_cost,
        decode_width=decode_width,
        num_groups=num_groups,
        refine_waves=refine_waves,
        alpha=alpha,
        preemption=preemption,
        continuation_discount=continuation_discount,
        preempt_every=preempt_every,
        preempt_drift=preempt_drift,
        preempt_global_every=preempt_global_every,
        preempt_scope_tau=preempt_scope_tau,
        preempt_scoped_width=preempt_scoped_width,
        preempt_incr_budget=preempt_incr_budget,
    )
    devices = jax.devices()
    churn_n = max(1, int(tasks * churn))

    init_groups = None if group_setup is None else group_setup(dev, rng)
    dev.add_tasks(
        tasks,
        rng.integers(0, jobs, tasks).astype(np.int32),
        rng.integers(0, num_task_classes, tasks).astype(np.int32),
        groups=init_groups,
    )
    t0 = time.perf_counter()
    fill = dev.round()
    jax.block_until_ready(fill)
    fill_s = time.perf_counter() - t0

    # --- chunk sizing against the transport artifacts ---------------
    # A chunk of R data-dependent rounds is timed as one unit, CLOSED
    # BY A SCALAR FETCH (see MIN_CHUNK_WALL_MS: block_until_ready can
    # return early on this transport, so the fetch is the only
    # trustworthy completion barrier). The wall must clear the bar
    # before the per-round quotient is believed; sub-bar walls are
    # artifacts, so R cannot be scaled proportionally from them — it
    # grows geometrically until a probe chunk clears the bar. On the
    # CPU platform the clock is honest and chunking is amortization.
    platform = devices[0].platform
    min_wall_ms = MIN_CHUNK_WALL_MS if platform != "cpu" else 0.0

    def timed_chunk(R, seed):
        """One timed chunk: dispatch R rounds, wait via block + a tiny
        scalar fetch (the true barrier). Returns (wall_ms, stats)."""
        t0 = time.perf_counter()
        stats = dev.run_steady_rounds(R, churn, churn_n, seed=seed)
        jax.block_until_ready(stats)
        np.asarray(jax.device_get(stats["live"][-1]))
        return (time.perf_counter() - t0) * 1e3, stats

    # The probe must clear the bar with a 4x margin: round latency can
    # vary several-fold between chunks (e.g. locality rounds alternate
    # between trivial and contended solves), and a chunk whose wall
    # falls below the bar is rejected — so R is sized off the probe
    # with headroom for faster-than-probe chunks.
    R = min(chunk, rounds)
    # hybrid-preempt configs grow R gently (2x, not 8x): their p99
    # claim rides the 2-regime latency fit, and oversized chunks
    # average the per-chunk superstep totals into near-collinearity —
    # two suite-scale runs at R=16384 produced degenerate (origin)
    # fits where R=2048 identified both slopes cleanly. Smaller
    # chunks = more relative superstep variance = a conditioned fit,
    # at the price of one extra probe compile.
    hybrid_cfg = preemption and (preempt_every > 1 or preempt_drift > 0)
    grow = 2 if hybrid_cfg else 8
    while True:
        # warm the scan executable for this R (num_rounds is static)
        jax.block_until_ready(dev.run_steady_rounds(R, churn, churn_n, seed=1))
        probe_ms, _ = timed_chunk(R, seed=1)
        if probe_ms >= 4 * min_wall_ms or R >= (1 << 20):
            break
        if verbose:
            print(
                f"# probe chunk R={R}: wall {probe_ms:.1f} ms under the "
                f"{4 * min_wall_ms:.0f} ms probe bar - growing R",
                file=sys.stderr,
            )
        R *= grow
    if probe_ms < min_wall_ms:
        raise RuntimeError(
            f"chunk wall {probe_ms:.2f} ms below {min_wall_ms:.0f} ms at "
            f"R={R}: per-round latency unmeasurable over this transport"
        )

    while True:
        # a measured chunk can still undercut the bar (heavy round-to-
        # round variance, or a sub-bar reading the probe's 4x margin
        # missed): retry it once, then GROW R and restart measurement
        # rather than reporting a number the bar does not cover
        # >= 3 chunks for the p50; hybrid-preempt configs take 5 so
        # the TWO-REGIME latency fit is over-determined (3 params) AND
        # its leave-one-out folds (4-chunk subfits) can run the same
        # regime — at 3 chunks the mixture fit is exactly determined
        # and fits noise (a suite run produced k_incr > k_full);
        # 7 chunks once the gentle-growth probe keeps them small
        chunks = max(7 if hybrid_cfg else 3, -(-rounds // R))
        per_round_ms = []
        chunk_walls_ms = []
        chunk_stats = []
        grown = False
        for rep in range(chunks):
            wall_ms, stats = timed_chunk(R, seed=2 + rep)
            if wall_ms < min_wall_ms:
                wall_ms, stats = timed_chunk(R, seed=100 + rep)
            if wall_ms < min_wall_ms:
                if R >= (1 << 20):
                    raise RuntimeError(
                        f"chunk {rep} wall {wall_ms:.2f} ms below the "
                        f"{min_wall_ms:.0f} ms bar at R={R} - rejecting "
                        "the measurement"
                    )
                if verbose:
                    print(
                        f"# chunk {rep} wall {wall_ms:.1f} ms under the "
                        f"{min_wall_ms:.0f} ms bar - growing R from {R}",
                        file=sys.stderr,
                    )
                R *= 4
                # warm the new-R executable AND drain it with the same
                # scalar-fetch barrier as timed chunks: block_until_ready
                # alone can return early here, and an undrained warm-up
                # chain would bleed into the restarted rep-0 wall
                warm = dev.run_steady_rounds(R, churn, churn_n, seed=1)
                jax.block_until_ready(warm)
                np.asarray(jax.device_get(warm["live"][-1]))
                grown = True
                break
            chunk_walls_ms.append(round(wall_ms, 1))
            per_round_ms.append(wall_ms / R)
            chunk_stats.append(stats)
        if not grown:
            break

    # Clock stopped — now fetch and verify everything.
    fill_got = dev.fetch_stats(fill)
    assert bool(fill_got["converged"]), "fill round did not converge"
    if verbose:
        print(
            f"# fill: placed {int(fill_got['placed'])}/{tasks} in "
            f"{fill_s:.2f}s (incl compile), "
            f"unsched={int(fill_got['unscheduled'])}",
            file=sys.stderr,
        )
    ss_all, full_all, glob_all, placed_all, live_last = [], [], [], [], 0
    drift_all, esc_all = [], []
    for rep, stats in enumerate(chunk_stats):
        got = dev.fetch_stats(stats)
        assert got["converged"].all(), "a steady round did not converge"
        ss = got.get("supersteps")
        if ss is not None:
            ss_all.append(np.asarray(ss))
        if "full_round" in got:
            full_all.append(np.asarray(got["full_round"]))
        if "global_round" in got:
            glob_all.append(np.asarray(got["global_round"]))
        if "census_drift" in got:
            drift_all.append(np.asarray(got["census_drift"]))
        if "escalated_round" in got:
            esc_all.append(np.asarray(got["escalated_round"]))
        placed_all.append(np.asarray(got["placed"]))
        live_last = int(got["live"][-1])
        if verbose:
            print(
                f"# chunk {rep}: {per_round_ms[rep]:.3f} ms/round x {R} rounds "
                f"(wall {chunk_walls_ms[rep]:.0f} ms), "
                f"placed/round mean {got['placed'].mean():.1f}, "
                f"live {int(got['live'][-1])}"
                + (f", supersteps mean {ss.mean():.0f} max {int(ss.max())}"
                   if ss is not None else ""),
                file=sys.stderr,
            )

    p50 = float(np.percentile(per_round_ms, 50))
    target_ms = 10.0
    detail = {
        "rounds_per_chunk": R,
        "chunks_wall_ms": chunk_walls_ms,
        "floor_bar_ms": round(min_wall_ms, 1),
        "placed_per_round_mean": round(float(np.mean(placed_all)), 2),
        "live_final": live_last,
    }
    if ss_all:
        ss_cat = np.concatenate(ss_all)
        # solver-interior telemetry for --obs-out: the fused device
        # rounds expose per-round superstep counts through fetch_stats;
        # publish them AFTER the clock stopped (hot loop untouched)
        from ksched_tpu.obs import soltel

        soltel.publish_round_supersteps(ss_cat, backend=f"device/{platform}")
        detail["supersteps_p50"] = int(np.percentile(ss_cat, 50))
        detail["supersteps_p99"] = int(np.percentile(ss_cat, 99))
        detail["supersteps_max"] = int(ss_cat.max())
        detail["latency_model"] = _round_latency_model(
            np.array(chunk_walls_ms), R, ss_all,
            full_per_chunk=full_all or None,
        )
        if full_all:
            detail["full_rounds"] = int(np.concatenate(full_all).sum())
            detail["rounds_total"] = int(sum(len(f) for f in full_all))
        # forensic anchor for the max tail (VERDICT r4 #5): the top
        # rounds by superstep count, each with its tier and context,
        # so an artifact reader can see WHICH regime the monsters live
        # in without a re-run
        k = min(8, len(ss_cat))
        top = np.argsort(ss_cat)[-k:][::-1]
        fcat_t = np.concatenate(full_all).astype(bool) if full_all else None
        gcat_t = np.concatenate(glob_all).astype(bool) if glob_all else None
        dcat_t = np.concatenate(drift_all) if drift_all else None
        ecat_t = np.concatenate(esc_all).astype(bool) if esc_all else None
        detail["top_rounds"] = [
            {
                "round": int(i),
                "supersteps": int(ss_cat[i]),
                **(
                    {
                        "tier": (
                            "escalated"
                            if ecat_t is not None and ecat_t[i]
                            else "global"
                            if gcat_t is not None and gcat_t[i]
                            else "scoped" if fcat_t[i] else "incremental"
                        )
                    }
                    if fcat_t is not None else {}
                ),
                **(
                    {"census_drift": int(dcat_t[i])}
                    if dcat_t is not None else {}
                ),
            }
            for i in top
        ]
        if esc_all:
            detail["escalated_rounds"] = int(np.concatenate(esc_all).sum())
        if glob_all and preempt_global_every > 0:
            detail["global_rounds"] = int(np.concatenate(glob_all).sum())
            # scoped-regime evidence: the p99 claim rests on scoped
            # re-solves being cheap — record their superstep spread
            # separately from the rare global rounds
            gcat = np.concatenate(glob_all).astype(bool)
            fcat = np.concatenate(full_all).astype(bool)
            scat = ss_cat
            scoped = fcat & ~gcat
            if scoped.any():
                detail["supersteps_scoped_p99"] = int(
                    np.percentile(scat[scoped], 99)
                )
                detail["supersteps_scoped_max"] = int(scat[scoped].max())
            if gcat.any():
                detail["supersteps_global_max"] = int(scat[gcat].max())
    return {
        "metric": (
            f"p50 scheduling-round latency, {tasks} tasks x "
            f"{machines} machines, {label}, "
            f"{churn:.0%} churn, device-resident rounds "
            f"({R}-round chains), backend=device/{platform}"
        ),
        "value": round(p50, 4),
        "unit": "ms",
        "vs_baseline": round(target_ms / p50, 3),
        "detail": detail,
    }



def parse_overrides(pairs, allowed):
    """--override K=V pairs -> dict with int/float coercion; rejects
    unknown keys so a typo'd ablation cannot silently no-op."""
    ov = {}
    for kv in pairs or []:
        k, sep, v = kv.partition("=")
        if not sep:
            raise SystemExit(f"--override wants K=V, got {kv!r}")
        try:
            ov[k] = int(v)
        except ValueError:
            try:
                # scientific notation ("rate=1e5") and decimals land
                # here; malformed values exit cleanly, not a traceback
                ov[k] = float(v)
            except ValueError:
                raise SystemExit(
                    f"--override wants a numeric value, got {kv!r}"
                ) from None
            if not math.isfinite(ov[k]):
                raise SystemExit(
                    f"--override wants a finite value, got {kv!r}"
                )
    unknown = set(ov) - set(allowed)
    if unknown:
        raise SystemExit(f"unknown --override keys: {sorted(unknown)}")
    return ov


def run_device_bench(args) -> None:
    out = _device_bench(
        tasks=args.tasks,
        machines=args.machines,
        pus=args.pus,
        slots=args.slots,
        jobs=args.jobs,
        churn=args.churn,
        rounds=args.rounds,
        chunk=args.chunk,
        verbose=args.verbose,
    )
    if args.tasks == 10_000 and args.machines == 1_000:
        # the headline config is class-degenerate by construction (the
        # trivial model), so its rounds take the exact closed form with
        # zero solver iterations — say so, and point at the configs
        # that exercise the iterative solver (VERDICT r2 weak #6)
        out["detail"]["note"] = (
            "trivial model is class-degenerate: rounds take the exact "
            "closed form (supersteps 0); iterative-solver flagships are "
            "quincy10k / coco50k / whare-hetero in --suite"
        )
    _emit_record(out, args)


def _churn_pipeline_bench(
    tasks: int = 10_000,
    machines: int = 1_000,
    rounds: int = 24,
    churn: float = 0.01,
    restart_budget: int = 64,
    cold_control: bool = True,
    warmup: int = 6,
    verbose: bool = False,
) -> dict:
    """The steady-state churn benchmark for the device-resident round
    pipeline (event path: FlowScheduler + PlacementSolver + JaxSolver).

    Three arms run the IDENTICAL seeded scenario — same graph
    evolution, same solver policy (slot-stable plan + dirty-frontier
    price refit, budgeted restart escape as backstop), so placements
    are bit-identical BY CONSTRUCTION and the bench asserts it every
    round. The arms differ only in how the folded problem reaches the
    solver:

    - ``full_rebuild``: the r9 status-quo export — every round
      re-copies/refolds ALL host arrays (problem() cache bypassed) and
      re-uploads every one of them (fresh device_put);
    - ``delta_scatter``: the host-side delta path — the journal
      scatters into the host arrays and the problem() cache rebuilds
      only dirty groups; the device still receives full uploads;
    - ``device_resident``: persistent device buffers — only packed
      delta records cross the host/device boundary (the problem-delta
      scatter AND the plan-row scatter), warm flow + potentials stay
      device-resident.

    Two baseline measurements attribute the win: ``reference`` runs
    the full_rebuild export with the r9 solver defaults (legacy plan,
    no warm potentials, no restart escape) and ``r11_policy`` runs the
    device-resident export with the r11 policy (legacy argsort plan
    rebuilt per endpoint change, warm prices OFF, budgeted restart
    escape as the price-war band-aid) — the 407 ms/747-supersteps p50
    path this change retires. ``cold_control`` additionally measures
    the canonical cold solve (zero flow, full cost-scaling from
    eps = max|cost|·n — the complete() fallback) on the final round's
    problem, the baseline for the warm-supersteps claim.

    The arms are INTERLEAVED round-robin, one round each per logical
    round: ambient machine drift (the dominant noise on CPU, measured
    ~±25% over a multi-minute sequential run) then hits every arm
    equally, so the cross-arm comparison is paired rather than
    confounded by whichever arm ran during a slow window.
    """
    import jax

    from ksched_tpu.drivers import add_job, build_cluster
    from ksched_tpu.drivers.synthetic import add_task_to_job
    from ksched_tpu.graph.device_export import DeviceResidentState
    from ksched_tpu.obs import DeviceProfiler, set_profiler
    from ksched_tpu.obs.devprof import problem_nbytes
    from ksched_tpu.obs.metrics import Registry
    from ksched_tpu.obs.soltel import SolverStallError
    from ksched_tpu.solver.jax_solver import JaxSolver
    from ksched_tpu.utils import seed_rng

    k = max(1, int(tasks * churn))
    # the arms sharing the new default policy — placements must match
    # bit-for-bit across these, every round
    _PARITY_ARMS = ("full_rebuild", "delta_scatter", "device_resident")
    # (label, export, restart_budget, r11-policy?) — r11 policy =
    # legacy argsort plan + warm prices OFF (the defaults before the
    # slot-stable plan and the dirty-frontier refit landed)
    arm_specs = (
        ("reference", "full", None, True),
        ("r11_policy", "resident", restart_budget, True),
        ("full_rebuild", "full", restart_budget, False),
        ("delta_scatter", "cache", restart_budget, False),
        ("device_resident", "resident", restart_budget, False),
    )
    out_arms = {}
    placements_by_round = {}

    class _Arm:
        def __init__(self, label, export, budget, r11_policy):
            self.label = label
            self.export = export
            # the reference (status-quo) arm's warm attempts degenerate
            # cumulatively on this workload — by ~round 27 even the
            # 50k-superstep cost-scaling fallback stalls (the failure
            # mode the budgeted restart escape removes). Cap its rounds
            # and record a stall as DATA, not a crash.
            self.arm_rounds = min(rounds, 12) if label == "reference" else rounds
            self.reg = Registry()
            self.prof = DeviceProfiler(registry=self.reg)
            set_profiler(self.prof)
            seed_rng(7)
            self.solver = JaxSolver(
                restart_budget=budget,
                slot_stable=not r11_policy,
                warm_potentials=not r11_policy,
                journal_scoped_warm=not r11_policy,
            )
            (
                self.sched, self.rmap, self.jmap, self.tmap, self.root,
            ) = build_cluster(
                num_machines=machines, num_cores=1, pus_per_core=4,
                max_tasks_per_pu=4, backend=self.solver,
            )
            if export == "resident":
                self.sched.solver.device_resident = True
                self.sched.solver.resident = DeviceResidentState(
                    self.sched.solver.state
                )
            self.job_id = add_job(self.sched, self.jmap, self.tmap, num_tasks=tasks)
            t0 = time.perf_counter()
            self.sched.schedule_all_jobs()
            self.fill_s = time.perf_counter() - t0
            self.fill_ss = self.solver.last_supersteps
            self.rng = np.random.default_rng(123)
            self.lat_ms = []
            self.ss_hist = []
            self.h2d_mark = (0.0, 0.0)
            self.plan_kinds = {}  # resident plan sync kinds, post-warmup
            self.plan_bytes = 0
            self.scope_counts = {}  # journal-scoped warm decisions
            self.stalled_at = None
            # task/job ids come from the process-global seeded RNG
            # (utils.seed_rng); interleaved arms must each see their
            # OWN continuation of the seed-7 stream or ids (and thus
            # placements) diverge across arms — snapshot the stream
            # here and swap it in around every round
            from ksched_tpu.utils.ids import rng as global_rng

            self._global_rng = global_rng
            self._rng_state = global_rng().getstate()

        def h2d(self, kind):
            return self.reg.value("ksched_h2d_bytes_total", kind=kind)

        def drive_round(self, r):
            set_profiler(self.prof)
            self._global_rng().setstate(self._rng_state)
            if r == warmup:
                # steady state reached: pow2 record buckets and the
                # budgeted-attempt executables are compiled; start the
                # clock and the byte accounting
                self.h2d_mark = (self.h2d("full_build"), self.h2d("delta"))
            sched, tmap = self.sched, self.tmap
            bound = sorted(sched.task_bindings.items())
            idx = sorted(
                int(x) for x in self.rng.choice(len(bound), k, replace=False)
            )
            for i in reversed(idx):
                sched.handle_task_completion(tmap.find(bound[i][0]))
            for _ in range(k):
                add_task_to_job(self.job_id, self.jmap, tmap)
            sched.add_job(self.jmap.find(self.job_id))
            # the adds were this round's only global-RNG consumers:
            # park the arm's stream for its next round
            self._rng_state = self._global_rng().getstate()
            if self.export == "full":
                # status-quo export: bypass the problem() cache so
                # every round re-copies and refolds all arrays
                st = sched.solver.state
                st._cache_nodes_ok = st._cache_arcs_ok = False
            t0 = time.perf_counter()
            try:
                sched.schedule_all_jobs()
            except SolverStallError as e:
                self.stalled_at = r
                print(
                    f"# churn[{self.label}] STALLED at round {r}: {e}",
                    file=sys.stderr,
                )
                return
            wall_ms = (time.perf_counter() - t0) * 1e3
            if self.label in _PARITY_ARMS:
                snap = {
                    tmap.find(t).name: rid
                    for t, rid in sched.task_bindings.items()
                }
                placements_by_round.setdefault(r, {})[self.label] = snap
            if r < warmup:
                return
            self.lat_ms.append(wall_ms)
            self.ss_hist.append(self.solver.last_supersteps)
            scope = self.solver.last_warm_scope
            self.scope_counts[scope] = self.scope_counts.get(scope, 0) + 1
            if self.export == "resident":
                res = sched.solver.resident
                kind = res.last_plan_kind
                self.plan_kinds[kind] = self.plan_kinds.get(kind, 0) + 1
                self.plan_bytes += res.last_plan_bytes
            if verbose:
                print(
                    f"# churn[{self.label}] round {r}: {wall_ms:.1f}ms "
                    f"ss={self.ss_hist[-1]}",
                    file=sys.stderr,
                )

    try:
        arm_objs = [_Arm(*spec) for spec in arm_specs]
        for r in range(warmup + rounds):
            for a in arm_objs:
                if a.stalled_at is not None or r >= warmup + a.arm_rounds:
                    continue
                a.drive_round(r)
    finally:
        set_profiler(None)

    for a in arm_objs:
        label, export = a.label, a.export
        sched, solver = a.sched, a.solver
        lat_ms, ss_hist, stalled_at = a.lat_ms, a.ss_hist, a.stalled_at
        full_b, delta_b = a.h2d("full_build"), a.h2d("delta")
        h2d_mark = a.h2d_mark
        prob = sched.solver.state.problem()
        measured = max(len(lat_ms), 1)
        arm = {
            "p50_ms": round(float(np.percentile(lat_ms, 50)), 3) if lat_ms else None,
            "p99_ms": round(float(np.percentile(lat_ms, 99)), 3) if lat_ms else None,
            "mean_ms": round(float(np.mean(lat_ms)), 3) if lat_ms else None,
            "fill_s": round(a.fill_s, 2),
            "fill_supersteps": int(a.fill_ss),
            "supersteps_p50": int(np.percentile(ss_hist, 50)) if ss_hist else None,
            "supersteps_p99": int(np.percentile(ss_hist, 99)) if ss_hist else None,
            "supersteps_max": int(max(ss_hist)) if ss_hist else None,
            "measured_rounds": len(lat_ms),
            "warm_scope_rounds": dict(a.scope_counts),
            "h2d_full_bytes": int(full_b - h2d_mark[0]),
            "h2d_delta_bytes": int(delta_b - h2d_mark[1]),
            "h2d_delta_bytes_per_round": int((delta_b - h2d_mark[1]) / measured),
            "problem_nbytes": int(problem_nbytes(prob)),
        }
        if stalled_at is not None:
            arm["stalled_at_round"] = stalled_at
            arm["stall"] = (
                "cost-scaling fallback exceeded max_supersteps — the "
                "unbudgeted warm path degenerates cumulatively; the "
                "restart_budget arms do not exhibit this"
            )
        if export == "resident":
            sched.solver.resident.parity_check()
            sched.solver.resident.plan_parity_check()
            arm["h2d_accounting"] = "exact (packed-record nbytes)"
            # for the resident arm the counted delta bytes ARE
            # the real per-round upload
            arm["h2d_real_upload_per_round"] = arm["h2d_delta_bytes_per_round"]
            arm["delta_records_last"] = int(
                sched.solver.resident.last_arc_records
                + sched.solver.resident.last_node_records
            )
            # slot-stable plan maintenance: sync kinds per measured
            # round (clean = no endpoint churn, delta = packed plan
            # records through the scatter, rebuild = layout rebuilt —
            # full_build / bucket growth / region overflow only) and
            # the plan bytes that rode the boundary post-warmup
            arm["plan_sync_kinds"] = dict(a.plan_kinds)
            arm["plan_bytes_total"] = int(a.plan_bytes)
            arm["plan_bytes_per_round"] = int(a.plan_bytes / measured)
            arm["plan_layout_rebuilds"] = int(
                sched.solver.state.plan.layout_rebuilds
            )
            arm["plan_region_overflows"] = int(
                sched.solver.state.plan.region_overflows
            )
            arm["plan_region_relocations"] = int(
                sched.solver.state.plan.region_relocations
            )
        else:
            arm["h2d_accounting"] = (
                "journal estimate; device uploads remain full arrays"
            )
            # non-resident arms re-device_put the five solver
            # arrays (cap/cost/excess/flow0 + the int32 casts)
            # every round: the real upload is graph-sized
            arm["h2d_real_upload_per_round"] = int(
                prob.cap.nbytes + prob.cost.nbytes
                + prob.excess.astype(np.int32).nbytes
                + prob.cap.nbytes  # flow0
            )
        if cold_control and label == "device_resident":
            # canonical cold solve on the final problem: zero
            # flow, full cost-scaling (the complete() fallback)
            from ksched_tpu.solver.jax_solver import _solve_mcmf

            n = prob.num_nodes
            m = len(prob.src)
            max_cost = int(np.abs(prob.cost).max())
            plan_dev = solver._plan_for(
                prob.src.astype(np.int32), prob.dst.astype(np.int32), n
            )
            import jax.numpy as jnp

            t0 = time.perf_counter()
            cold = _solve_mcmf(
                jnp.asarray(prob.cap.astype(np.int32)),
                jnp.asarray(prob.cost.astype(np.int32) * np.int32(n)),
                jnp.asarray(prob.excess.astype(np.int32)),
                jnp.asarray(np.zeros(m, np.int32)),
                jnp.asarray(np.int32(max(1, max_cost * n))),
                *plan_dev,
                alpha=solver.alpha,
                max_supersteps=200_000,
            )
            jax.block_until_ready(cold[0])
            arm["cold_costscaling_supersteps"] = int(cold[2])
            arm["cold_costscaling_wall_s"] = round(time.perf_counter() - t0, 2)
            # fresh-restart control: zero flow + tightened
            # prices at eps=1 (attempt-1 cold)
            t0 = time.perf_counter()
            fresh = _solve_mcmf(
                jnp.asarray(prob.cap.astype(np.int32)),
                jnp.asarray(prob.cost.astype(np.int32) * np.int32(n)),
                jnp.asarray(prob.excess.astype(np.int32)),
                jnp.asarray(np.zeros(m, np.int32)),
                jnp.asarray(np.int32(1)),
                *plan_dev,
                alpha=solver.alpha,
                max_supersteps=4096,
            )
            jax.block_until_ready(fresh[0])
            arm["cold_fresh_restart_supersteps"] = int(fresh[2])
            arm["cold_fresh_restart_wall_s"] = round(time.perf_counter() - t0, 2)
        out_arms[label] = arm

    # bit-parity across the three same-policy arms, every round. An
    # arm that stalled mid-run (recorded above as data) simply stops
    # contributing rounds; parity is asserted over whatever overlap
    # exists — at least two arms per compared round.
    compared = 0
    for r, per_arm in sorted(placements_by_round.items()):
        present = [a for a in _PARITY_ARMS if a in per_arm]
        if len(present) < 2:
            continue
        base = per_arm[present[0]]
        for a in present[1:]:
            assert per_arm[a] == base, (
                f"round {r}: arm {a!r} placements diverged from "
                f"{present[0]!r} ({len(per_arm[a])} vs {len(base)} bindings)"
            )
        compared += 1

    def _improvement(a, b):
        if a.get("p50_ms") and b.get("p50_ms"):
            return round(1.0 - a["p50_ms"] / b["p50_ms"], 3)
        return "arm stalled before measuring"

    dr = out_arms["device_resident"]
    fr = out_arms["full_rebuild"]
    ref = out_arms["reference"]
    r11 = out_arms["r11_policy"]
    target_ms = 10.0
    dr_p50 = dr.get("p50_ms")
    return {
        "metric": (
            f"p50 scheduling-round latency, {tasks} tasks x {machines} "
            f"machines, {churn:.0%} churn, device-resident incremental "
            f"rounds (event path), backend=jax/"
            f"{jax.devices()[0].platform}"
        ),
        "value": dr_p50,
        "unit": "ms",
        "vs_baseline": (
            round(target_ms / max(dr_p50, 1e-9), 3) if dr_p50 else 0.0
        ),
        "detail": {
            "arms": out_arms,
            "placements_bit_identical_across_arms": True,
            "parity_rounds_compared": compared,
            "p50_improvement_vs_full_rebuild": _improvement(dr, fr),
            "p50_improvement_vs_reference_path": _improvement(dr, ref),
            "p50_improvement_vs_r11_policy": _improvement(dr, r11),
            "restart_budget": restart_budget,
            "rounds": rounds,
            "warmup_rounds": warmup,
            "churn_tasks_per_round": k,
        },
    }


def _multitenant_bench(
    cells: int = 16,
    rounds: int = 24,
    warmup: int = 4,
    restart_budget: int = 64,
    verbose: bool = False,
) -> dict:
    """The multi-tenant scheduler-as-a-service benchmark (tenancy/):
    N mixed-size cells served by ONE warm process, comparing

    - ``batched``: every cell dispatches its round, then same-bucket
      lanes solve through one stacked program per (bucket, policy)
      group (solver/jax_solver.stacked_solve_fn) — the multi-tenant
      service's hot path;
    - ``sequential``: the same N cells solved one at a time, each by
      its own plain JaxSolver — the one-process-per-tenant status quo
      folded into a single loop (per-tenant warm state kept, so this
      is the strongest sequential baseline, not a strawman).

    The arms run the IDENTICAL seeded scenario (same per-cell id
    streams, same churn draws) and are interleaved round-robin so
    ambient drift hits both equally (paired, like the churn bench);
    per-cell placements are asserted bit-identical across arms every
    round — the batched stack must change WHERE lanes solve, never
    what they compute. Cell sizes cycle 3 classes so the fleet spans
    3 pow2 shape buckets; with per-lane warm scopes agreeing in
    steady state the fleet solves in ~3 stacked programs per round
    instead of N solver calls. On CPU the win is dispatch/compile-
    cache amortization; the lane-axis vectorization gain is a device
    property (UNMEASURED until a TPU ambient appears — same posture
    as the mega/device claims)."""
    import jax

    from ksched_tpu.drivers import add_job, build_cluster
    from ksched_tpu.drivers.synthetic import add_task_to_job
    from ksched_tpu.solver.jax_solver import JaxSolver
    from ksched_tpu.tenancy import LaneSolver, StackedBatcher
    from ksched_tpu.utils import seed_rng
    from ksched_tpu.utils.ids import rng as global_rng

    #: (machines, tasks) per cell class — 3 classes -> 3 pow2 buckets
    SIZES = ((12, 96), (24, 192), (48, 384))

    class _Cell:
        def __init__(self, idx: int, backend):
            machines, tasks = SIZES[idx % len(SIZES)]
            self.idx = idx
            self.tasks = tasks
            # per-cell id stream, IDENTICAL across arms: both arms'
            # cell idx consumes the same seed's continuation
            seed_rng(10_000 + idx)
            self.backend = backend
            (
                self.sched, self.rmap, self.jmap, self.tmap, self.root,
            ) = build_cluster(
                num_machines=machines, num_cores=1, pus_per_core=4,
                max_tasks_per_pu=4, backend=backend,
            )
            self.job_id = add_job(
                self.sched, self.jmap, self.tmap, num_tasks=tasks
            )
            self.sched.schedule_all_jobs()  # fill solve (not measured)
            self.rng = np.random.default_rng(500 + idx)
            self.k = max(1, tasks // 50)
            self._rng_state = global_rng().getstate()

        def swap_in(self):
            self._outer = global_rng().getstate()
            global_rng().setstate(self._rng_state)

        def park(self):
            self._rng_state = global_rng().getstate()
            global_rng().setstate(self._outer)

        def churn(self):
            bound = sorted(self.sched.task_bindings.items())
            idx = sorted(
                int(x) for x in self.rng.choice(len(bound), self.k, replace=False)
            )
            for i in reversed(idx):
                self.sched.handle_task_completion(self.tmap.find(bound[i][0]))
            for _ in range(self.k):
                add_task_to_job(self.job_id, self.jmap, self.tmap)
            self.sched.add_job(self.jmap.find(self.job_id))

        def placements(self):
            return {
                self.tmap.find(t).name: rid
                for t, rid in self.sched.task_bindings.items()
            }

    batcher = StackedBatcher()
    arms = {}
    arms["batched"] = [
        _Cell(i, LaneSolver(batcher, tenant=f"c{i}", restart_budget=restart_budget))
        for i in range(cells)
    ]
    arms["sequential"] = [
        _Cell(i, JaxSolver(slot_stable=False, restart_budget=restart_budget))
        for i in range(cells)
    ]
    fleet_ms = {"batched": [], "sequential": []}
    cell_ms = {
        "batched": [[] for _ in range(cells)],
        "sequential": [[] for _ in range(cells)],
    }
    ss_hist = {"batched": [], "sequential": []}
    programs_per_round = []
    for r in range(warmup + rounds):
        snaps = {}
        for label in ("batched", "sequential"):
            fleet = arms[label]
            t0 = time.perf_counter()
            if label == "batched":
                tokens = []
                for cell in fleet:
                    tc = time.perf_counter()
                    cell.swap_in()
                    cell.churn()
                    tokens.append(cell.sched.schedule_all_jobs_async())
                    cell.park()
                    cell_ms[label][cell.idx].append(
                        (time.perf_counter() - tc) * 1e3
                    )
                groups = batcher.flush()
                for cell, token in zip(fleet, tokens):
                    tc = time.perf_counter()
                    if token is not None:
                        cell.sched.finish_scheduling()
                    cell_ms[label][cell.idx][-1] += (
                        time.perf_counter() - tc
                    ) * 1e3
                if r >= warmup:
                    programs_per_round.append(groups)
            else:
                for cell in fleet:
                    tc = time.perf_counter()
                    cell.swap_in()
                    cell.churn()
                    cell.sched.schedule_all_jobs()
                    cell.park()
                    cell_ms[label][cell.idx].append(
                        (time.perf_counter() - tc) * 1e3
                    )
            wall_ms = (time.perf_counter() - t0) * 1e3
            snaps[label] = [c.placements() for c in fleet]
            if r >= warmup:
                fleet_ms[label].append(wall_ms)
                ss_hist[label].append(
                    sum(c.backend.last_supersteps for c in fleet)
                )
            else:
                # warm-up rounds carry the compiles; drop their
                # per-cell samples too so both stats cover the same
                # measured window
                for cell in fleet:
                    cell_ms[label][cell.idx].pop()
            if verbose:
                print(
                    f"# multitenant[{label}] round {r}: {wall_ms:.1f}ms",
                    file=sys.stderr,
                )
        # bit-parity per cell per round: batching must never change a
        # lane's answer
        for i in range(cells):
            assert snaps["batched"][i] == snaps["sequential"][i], (
                f"round {r}: cell {i} placements diverged between the "
                "batched and sequential arms"
            )

    def _arm_stats(label):
        lat = fleet_ms[label]
        per_cell = {
            f"cell_{i}": {
                "p50_ms": round(float(np.percentile(v, 50)), 3),
                "p99_ms": round(float(np.percentile(v, 99)), 3),
            }
            for i, v in enumerate(cell_ms[label])
            if v
        }
        return {
            "fleet_p50_ms": round(float(np.percentile(lat, 50)), 3),
            "fleet_p99_ms": round(float(np.percentile(lat, 99)), 3),
            "fleet_mean_ms": round(float(np.mean(lat)), 3),
            "supersteps_per_round_p50": int(np.percentile(ss_hist[label], 50)),
            "per_tenant": per_cell,
        }

    out_arms = {label: _arm_stats(label) for label in fleet_ms}
    b, s = out_arms["batched"], out_arms["sequential"]
    return {
        "metric": (
            f"p50 fleet-round latency, {cells} cells (mixed sizes, 3 pow2 "
            "buckets), batched stacked-CSR vs sequential-per-tenant, "
            f"backend=lane/{jax.devices()[0].platform}"
        ),
        "value": b["fleet_p50_ms"],
        "unit": "ms",
        "vs_baseline": (
            round(s["fleet_p50_ms"] / max(b["fleet_p50_ms"], 1e-9), 3)
        ),
        "detail": {
            "arms": out_arms,
            "placements_bit_identical_across_arms": True,
            "p50_improvement_vs_sequential": round(
                1.0 - b["fleet_p50_ms"] / s["fleet_p50_ms"], 3
            ),
            "stacked_programs_per_round_p50": int(
                np.percentile(programs_per_round, 50)
            ),
            "lanes": cells,
            "rounds": rounds,
            "warmup_rounds": warmup,
            "supersteps_p50": b["supersteps_per_round_p50"],
            "note": (
                "paired arms, same seeded scenario; CPU measures "
                "dispatch/compile amortization only — lane-axis device "
                "vectorization UNMEASURED (no TPU reachable)"
            ),
        },
    }


def _sharded_scale_bench(
    tasks: int = 100_000,
    machines: int = 10_000,
    rounds: int = 30,
    warmup: int = 4,
    churn: float = 0.01,
    burst_every: int = 8,
    burst_factor: int = 10,
    devices: int = 8,
    restart_budget: int = 64,
    verbose: bool = False,
) -> dict:
    """gtrace100k: the sharded rung's scale proof — 100k tasks × 10k
    machines on the event path, KEEP-MODE (preemption on, so post-fill
    graphs carry per-task leaf arcs and are genuinely non-collapsible:
    the general-graph path the fitting gate governs).

    Two PAIRED arms drive the identical seeded scenario through
    AutoSolver — dispatch included, so the escalation is measured, not
    simulated:

    - ``scan_csr``: AutoSolver with no sharded rung — every
      non-collapsible round solves on the single-chip slot-stable
      scan-CSR rung (the reference arm, run "where it fits": on the
      CPU host it always fits RAM);
    - ``sharded``: AutoSolver with the sharded rung attached and the
      HBM working-set budget set BETWEEN the per-shard and single-chip
      live sets at this bucket, so the gate escalates every
      non-collapsible round to the mesh; the device-resident mirror
      runs in sharded plan mode (per-shard routed record scatters).

    Both arms share the sharded-block plan layout (one entry order,
    one rebuild schedule), so placements are bit-identical BY
    CONSTRUCTION and asserted every round. The round timeline mixes
    steady churn rounds with BURST rounds (every `burst_every`-th
    round churns `burst_factor`× the base rate — the arrival-storm
    arm); percentiles are reported per kind.

    Measured and asserted: per-round supersteps, exact h2d bytes/round
    (packed records), plan sync kinds (delta-sized after warm-up), the
    per-superstep ICI reduction budget (3 psums — counted from the
    traced program, analysis/jaxpr_contracts), and a fitted
    latency = t_fixed + kappa·supersteps model over the measured
    rounds (tools/model_check.py's comparison target). The CROSS-CHIP
    latency win is UNMEASURED on the virtual CPU mesh (8 "devices" on
    one socket share memory bandwidth — same honest posture as the
    mega/device-resident claims); parity, delta-sized h2d, and the
    superstep/ICI counts are what a real mesh would pay.
    """
    import jax
    from jax.sharding import Mesh

    from ksched_tpu.analysis import jaxpr_contracts as jc
    from ksched_tpu.drivers import add_job, build_cluster
    from ksched_tpu.drivers.synthetic import add_task_to_job
    from ksched_tpu.graph.device_export import DeviceResidentState
    from ksched_tpu.obs import DeviceProfiler, set_profiler
    from ksched_tpu.obs.metrics import Registry
    from ksched_tpu.parallel.sharded_solver import (
        ShardedJaxSolver,
        csr_working_set_bytes,
        sharded_shard_bytes,
    )
    from ksched_tpu.solver.graph_collapse import AutoSolver
    from ksched_tpu.solver.jax_solver import JaxSolver
    from ksched_tpu.utils import seed_rng
    from ksched_tpu.utils.ids import rng as global_rng

    devs = jax.devices()
    if len(devs) < devices:
        raise SystemExit(
            f"gtrace100k needs {devices} devices (virtual CPU mesh: set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8); "
            f"got {len(devs)}"
        )
    mesh = Mesh(np.array(devs[:devices]), ("x",))
    k_base = max(1, int(tasks * churn))

    class _Arm:
        def __init__(self, label, sharded):
            self.label = label
            self.reg = Registry()
            self.prof = DeviceProfiler(registry=self.reg)
            set_profiler(self.prof)
            seed_rng(7)
            csr = JaxSolver(slot_stable=True, restart_budget=restart_budget)
            auto_kw = {}
            if sharded:
                self.sharded_backend = ShardedJaxSolver(
                    mesh, restart_budget=restart_budget
                )
                auto_kw = dict(
                    sharded=self.sharded_backend,
                    # the forcing budget is computed AFTER the fill (we
                    # need the padded bucket); start with 0 = never
                    hbm_budget_bytes=0,
                )
            self.auto = AutoSolver(csr, **auto_kw)
            (
                self.sched, self.rmap, self.jmap, self.tmap, self.root,
            ) = build_cluster(
                num_machines=machines, num_cores=1, pus_per_core=4,
                max_tasks_per_pu=4, backend=self.auto, preemption=True,
            )
            self.res = DeviceResidentState(self.sched.solver.state)
            if sharded:
                self.res.enable_sharded_plan(mesh, "x")
            else:
                # the reference arm consumes the SAME sharded-block
                # layout: one entry order + one rebuild schedule across
                # arms, so layout-rebuild timing (which legally
                # re-sorts cost-tied optima) can't confound the parity
                self.sched.solver.state.plan.enable_sharding(devices)
            self.sched.solver.device_resident = True
            self.sched.solver.resident = self.res
            self.job_id = add_job(
                self.sched, self.jmap, self.tmap, num_tasks=tasks
            )
            t0 = time.perf_counter()
            self.sched.schedule_all_jobs()
            self.fill_s = time.perf_counter() - t0
            if sharded:
                # the forcing budget, recorded in the artifact: halfway
                # between the per-shard and single-chip working sets of
                # the FILLED bucket — csr no longer "fits", the shard
                # slice does, so the gate escalates every general-graph
                # round (docs/sharding.md derives the default budget
                # this overrides and the scale where it trips unforced)
                st = self.sched.solver.state
                self.budget = (
                    sharded_shard_bytes(st.n_cap, st.m_cap, devices)
                    + csr_working_set_bytes(st.n_cap, st.m_cap)
                ) // 2
                self.auto.hbm_budget_bytes = self.budget
            self.rng = np.random.default_rng(123)
            self.lat = {"churn": [], "burst": []}
            self.ss = {"churn": [], "burst": []}
            self.lat_all = []
            self.ss_all = []
            self.paths = {}
            self.plan_kinds = {}
            self.h2d_mark = (0.0, 0.0)
            self.waived_rebuilds = 0
            self._global_rng = global_rng
            self._rng_state = global_rng().getstate()

        def h2d(self, kind):
            return self.reg.value("ksched_h2d_bytes_total", kind=kind)

        def drive_round(self, r):
            set_profiler(self.prof)
            self._global_rng().setstate(self._rng_state)
            if r == warmup:
                self.h2d_mark = (self.h2d("full_build"), self.h2d("delta"))
            kind = (
                "burst" if burst_every and r % burst_every == burst_every - 1
                else "churn"
            )
            k = k_base * (burst_factor if kind == "burst" else 1)
            sched, tmap = self.sched, self.tmap
            bound = sorted(sched.task_bindings.items())
            k = min(k, len(bound))
            idx = sorted(
                int(x) for x in self.rng.choice(len(bound), k, replace=False)
            )
            for i in reversed(idx):
                sched.handle_task_completion(tmap.find(bound[i][0]))
            for _ in range(k):
                add_task_to_job(self.job_id, self.jmap, tmap)
            sched.add_job(self.jmap.find(self.job_id))
            self._rng_state = self._global_rng().getstate()
            gen0 = sched.solver.state.generation
            t0 = time.perf_counter()
            sched.schedule_all_jobs()
            wall_ms = (time.perf_counter() - t0) * 1e3
            self.paths[self.auto.last_path] = (
                self.paths.get(self.auto.last_path, 0) + 1
            )
            snap = {
                tmap.find(t).name: rid
                for t, rid in sched.task_bindings.items()
            }
            if r < warmup:
                return snap
            pk = self.res.last_plan_kind
            if pk == "rebuild" and sched.solver.state.generation != gen0:
                self.waived_rebuilds += 1  # pow2 growth: rebuilds by design
                pk = "rebuild_pow2_growth"
            self.plan_kinds[pk] = self.plan_kinds.get(pk, 0) + 1
            self.lat[kind].append(wall_ms)
            self.ss[kind].append(self.auto.last_supersteps)
            self.lat_all.append(wall_ms)
            self.ss_all.append(self.auto.last_supersteps)
            if verbose:
                print(
                    f"# gtrace100k[{self.label}] round {r} ({kind}): "
                    f"{wall_ms:.0f}ms ss={self.auto.last_supersteps} "
                    f"path={self.auto.last_path} plan={pk}",
                    file=sys.stderr,
                )
            return snap

    try:
        arms = [_Arm("scan_csr", False), _Arm("sharded", True)]
        for r in range(warmup + rounds):
            snaps = [a.drive_round(r) for a in arms]
            assert snaps[0] == snaps[1], (
                f"round {r}: sharded placements diverged from the "
                f"scan-CSR reference arm "
                f"({len(snaps[1])} vs {len(snaps[0])} bindings)"
            )
    finally:
        set_profiler(None)

    sh = arms[1]
    ref = arms[0]
    # dispatch really escalated: every measured general-graph round of
    # the sharded arm took the sharded rung (fill/collapsible rounds
    # take dense); the reference arm never did
    assert sh.paths.get("sharded", 0) >= rounds, sh.paths
    assert "sharded" not in ref.paths, ref.paths
    assert sh.sharded_backend._plan is None, (
        "legacy build_sharded_plan ran on the slot-stable path"
    )
    # delta-sized rounds: zero plan layout rebuilds outside pow2 growth
    bad_rebuilds = sh.plan_kinds.get("rebuild", 0)
    assert bad_rebuilds == 0, (
        f"{bad_rebuilds} sharded plan rebuild(s) outside full_build/"
        f"pow2 growth (kinds: {sh.plan_kinds})"
    )
    sh.res.parity_check()
    sh.res.plan_parity_check()
    # ICI budget, counted from the traced program (loop-body psums)
    ici = jc.count_superstep_collectives(
        jc.trace_sharded_slot(64, 256, num_devices=devices)
    )
    assert ici.get("psum", 0) == 3, ici

    def _arm_stats(a):
        measured = max(len(a.lat_all), 1)
        full_b, delta_b = a.h2d("full_build"), a.h2d("delta")
        out = {
            "fill_s": round(a.fill_s, 1),
            "p50_ms": round(float(np.percentile(a.lat_all, 50)), 1),
            "p99_ms": round(float(np.percentile(a.lat_all, 99)), 1),
            "supersteps_p50": int(np.percentile(a.ss_all, 50)),
            "supersteps_max": int(max(a.ss_all)),
            "measured_rounds": len(a.lat_all),
            "autosolver_paths": dict(a.paths),
            "plan_sync_kinds": dict(a.plan_kinds),
            "waived_pow2_growth_rebuilds": a.waived_rebuilds,
            "h2d_delta_bytes_per_round": int(
                (delta_b - a.h2d_mark[1]) / measured
            ),
            "h2d_full_bytes_post_warmup": int(full_b - a.h2d_mark[0]),
        }
        for kind in ("churn", "burst"):
            if a.lat[kind]:
                out[f"{kind}_p50_ms"] = round(
                    float(np.percentile(a.lat[kind], 50)), 1
                )
                out[f"{kind}_supersteps_p50"] = int(
                    np.percentile(a.ss[kind], 50)
                )
        return out

    out_arms = {"scan_csr": _arm_stats(ref), "sharded": _arm_stats(sh)}
    # latency model over the sharded arm's measured rounds (each round
    # its own R=1 "chunk"): wall = t_fixed + kappa * supersteps
    model = _round_latency_model(
        sh.lat_all, 1, [[s] for s in sh.ss_all]
    )
    st = sh.sched.solver.state
    sh_p50 = out_arms["sharded"]["p50_ms"]
    target_ms = 10.0
    return {
        "metric": (
            f"p50 scheduling-round latency, {tasks} tasks x {machines} "
            f"machines, keep-mode churn+burst, sharded AutoSolver rung "
            f"({devices}-device mesh), backend=sharded/"
            f"{jax.devices()[0].platform}"
        ),
        "value": sh_p50,
        "unit": "ms",
        "vs_baseline": round(target_ms / max(sh_p50, 1e-9), 3),
        "detail": {
            "arms": out_arms,
            "placements_bit_identical_across_arms": True,
            "mesh_devices": devices,
            "graph_bucket": {"n_cap": st.n_cap, "m_cap": st.m_cap,
                             "entry_cap": st.plan.entry_cap,
                             "block_extent": st.plan.block_extent},
            "fitting_gate": {
                "budget_bytes": sh.budget,
                "csr_working_set_bytes": csr_working_set_bytes(
                    st.n_cap, st.m_cap
                ),
                "sharded_shard_bytes": sharded_shard_bytes(
                    st.n_cap, st.m_cap, devices
                ),
                "note": (
                    "budget forced between the two working sets so the "
                    "gate escalates at this bucket; at the 1 GiB "
                    "default the crossover sits near ~1M tasks "
                    "(docs/sharding.md)"
                ),
            },
            "ici_reductions_per_superstep": ici,
            "ici_vector_psums_per_round_p50": 3 * out_arms["sharded"][
                "supersteps_p50"
            ],
            "latency_model": model,
            "supersteps_p50": out_arms["sharded"]["supersteps_p50"],
            "rounds": rounds,
            "warmup_rounds": warmup,
            "churn_tasks_per_round": k_base,
            "burst_every": burst_every,
            "burst_factor": burst_factor,
            "restart_budget": restart_budget,
            "cross_chip_win": (
                "UNMEASURED: virtual 8-device CPU mesh shares one "
                "socket's memory bandwidth, so per-chip speedup is not "
                "observable here (same posture as the mega/device-"
                "resident claims); parity, delta-sized h2d, and the "
                "superstep/ICI budgets above are the measured facts"
            ),
        },
    }


#: the five BASELINE.json benchmark configs plus the Quincy
#: data-locality config (see run_config for each)
SUITE_CONFIGS = (
    "ref100", "10kx1k", "quincy10k", "quincy10k-multiblock", "coco50k",
    "coco50k-preempt", "whare-hetero", "gtrace12k", "gtrace12k-burst",
    "gtrace12k-coco",
)
#: configs runnable via --config but not part of the default suite
EXTRA_CONFIGS = (
    "gtrace12k-host", "mcmf-mega", "churn", "multitenant", "gtrace100k",
)


def run_config(args) -> None:
    """One BASELINE.json config, one JSON line.

    ref100       100 tasks x 10 machines, trivial (the reference's
                 fakeMachines smoke — cmd/k8sscheduler/scheduler.go:191-202).
    10kx1k       the headline north-star config.
    quincy10k    Quincy data-locality model at the north-star scale:
                 480 blocks x 3 replicas over 1k machines, one block
                 per task; per-task preference arcs ride the device
                 fast path as preference GROUPS (device_bulk group
                 mode + costmodels/quincy_device.py).
    coco50k      CoCo interference model, 50k tasks
                 (coco_interference_scores.proto): 4 task classes,
                 per-machine penalties, fused-Pallas multi-class solve.
    whare-hetero Whare-Map (whare_map_stats.proto): per-machine platform
                 factors modelling a heterogeneous fleet.
    gtrace12k    Google 2011 cluster-trace replay at 12.5k machines
                 (task_desc.proto:76-78 trace ids): synthesized trace
                 streams, elastic membership, incremental re-solves via
                 the host bulk path.
    """
    from ksched_tpu.costmodels.device_costs import (
        coco_device_cost_fn,
        whare_device_cost_fn,
    )

    rng = np.random.default_rng(7)
    name = args.config
    if name == "ref100":
        out = _device_bench(
            tasks=100, machines=10, pus=1, slots=16, jobs=3,
            churn=0.05, rounds=128, chunk=64, verbose=args.verbose,
        )
    elif name == "10kx1k":
        out = _device_bench(
            tasks=10_000, machines=1_000, pus=4, slots=4, jobs=10,
            churn=0.01, rounds=args.rounds, chunk=args.chunk,
            verbose=args.verbose,
        )
    elif name == "quincy10k":
        from ksched_tpu.costmodels.quincy_device import QuincyGroupTable

        MBv = 1 << 20
        n_blocks, G, machines = 480, 512, 1_000

        def group_setup(dev, setup_rng):
            # 64 MB cost units: block-transfer cost GAPS bound the
            # price-war depth of blocked-contention rounds — measured
            # 40x on captured tail instances (1795 -> 44 mean
            # supersteps, 3319 -> 68 max; docs/NOTES.md)
            table = QuincyGroupTable(
                num_groups=G, num_machines=machines, cost_unit_mb=64
            )
            for b in range(1, n_blocks + 1):
                table.blocks.register(
                    b, 512 * MBv,
                    setup_rng.choice(machines, size=3, replace=False).tolist(),
                )
            blocks = setup_rng.integers(1, n_blocks + 1, 10_000)
            groups = table.groups_for(
                np.zeros(10_000, np.int32), [[int(b)] for b in blocks]
            )
            table.sync(dev)
            return groups

        out = _device_bench(
            tasks=10_000, machines=machines, pus=4, slots=4, jobs=10,
            churn=0.01, rounds=args.rounds, chunk=args.chunk,
            num_groups=G,
            group_setup=group_setup,
            supersteps=1 << 17,
            decode_width=2048,
            label=(
                f"Quincy data-locality model ({n_blocks} blocks x 3 "
                f"replicas, {G} preference groups)"
            ),
            verbose=args.verbose,
        )
    elif name == "quincy10k-multiblock":
        out = _quincy_multiblock_bench(
            rounds=args.rounds, chunk=args.chunk, verbose=args.verbose
        )
    elif name == "coco50k":
        from ksched_tpu.costmodels import coco

        penalties = rng.integers(0, 40, (1_000, 4)).astype(np.int64)
        out = _device_bench(
            tasks=50_000, machines=1_000, pus=4, slots=16, jobs=20,
            churn=0.01, rounds=128, chunk=32,
            num_task_classes=4,
            class_cost_fn=coco_device_cost_fn(penalties),
            unsched_cost=coco.UNSCHEDULED_COST,
            ec_cost=0,
            supersteps=1 << 17,
            # 1024, was 4096: the r5 anatomy probe (tools/coco_anatomy)
            # measured the decode at 0.166 ms per 1024 width; churn is
            # 500/round and steady backlog ~0 at 78% occupancy, so
            # 1024 keeps 2x headroom and banks ~0.5 ms of the 2.2 ms
            # round
            decode_width=1024,
            label="CoCo interference cost model (4 classes)",
            verbose=args.verbose,
        )
    elif name == "coco50k-preempt":
        from ksched_tpu.costmodels import coco

        pov = parse_overrides(args.override, (
            "preempt_drift", "preempt_every", "preempt_global_every",
            "preempt_scope_tau", "preempt_incr_budget",
        ))
        penalties = rng.integers(0, 40, (1_000, 4)).astype(np.int64)
        out = _device_bench(
            tasks=50_000, machines=1_000, pus=4, slots=16, jobs=20,
            churn=0.01, rounds=128, chunk=32,
            num_task_classes=4,
            class_cost_fn=coco_device_cost_fn(penalties),
            unsched_cost=coco.UNSCHEDULED_COST,
            ec_cost=0,
            supersteps=1 << 17,
            preemption=True,
            continuation_discount=8,
            # Stability-aware preemption (VERDICT r3 #1): incremental
            # rounds pin residents and place the backlog through the
            # bounded 4096-row decode window; the FULL tiered re-solve
            # (Tcap-wide mover decode — a bounded window spirals on
            # this workload's thousands-of-migrations rounds) fires
            # every 16 rounds or on >10k census drift. Round cost now
            # tracks the delta, as the reference's incremental solver
            # does (placement/solver.go:60-90); quality drift vs
            # full-every-round is bounded by test and measured in
            # realized_cost.
            preempt_every=pov.get("preempt_every", 16),
            preempt_drift=pov.get("preempt_drift", 10_000),
            # Three-tier stability (VERDICT r4 #2): cadence/drift
            # rounds re-price only residents of machines whose census
            # drifted >= tau (plus the backlog); a truly GLOBAL
            # re-solve fires 1-in-128 rounds — outside p99 by
            # construction, and the documented bound on how long
            # scoping can defer multi-hop migration chains. tau=16
            # (CPU-swept: tau=12 -> scoped ss max 3641, tau=16 -> 1477
            # with the same fire rate) keeps the scope on the ~10% of
            # machines holding the concentrated drift; the 16384 mover
            # window is ~1.5x the measured scoped mover count so
            # nothing parks (docs/NOTES.md round-5: scope-on-any-
            # change + a binding window was a measured catastrophe).
            preempt_global_every=pov.get("preempt_global_every", 128),
            preempt_scope_tau=pov.get("preempt_scope_tau", 16),
            # bound the incremental-round solve; a non-converged
            # attempt escalates to the scoped tier (the measured incr
            # monsters — 42.7k and 62.3k supersteps — become
            # budget + scoped-cost rounds by construction)
            # 0 = off; the default follows the global tier — a two-tier
            # ablation (--override preempt_global_every=0) has no scoped
            # tier to escalate to
            preempt_incr_budget=(
                pov.get(
                    "preempt_incr_budget",
                    8192 if pov.get("preempt_global_every", 128) > 0 else 0,
                ) or None
            ),
            preempt_scoped_width=16_384,
            decode_width=4096,
            label=(
                "CoCo interference cost model (4 classes), preemption ON "
                "(three-tier: budgeted incremental rounds escalating to "
                "scoped re-solves over drifted columns every 16 or on "
                "census drift + global re-solve every 128)"
            ),
            verbose=args.verbose,
        )
        if pov:
            out["detail"]["overrides"] = dict(sorted(pov.items()))
    elif name == "whare-hetero":
        from ksched_tpu.costmodels import whare

        platform_factor = rng.integers(80, 140, 1_000).astype(np.int64)
        out = _device_bench(
            tasks=20_000, machines=1_000, pus=4, slots=8, jobs=20,
            churn=0.01, rounds=128, chunk=32,
            num_task_classes=4,
            class_cost_fn=whare_device_cost_fn(
                slots_per_machine=32, platform_factor=platform_factor
            ),
            unsched_cost=whare.UNSCHEDULED_COST,
            ec_cost=0,
            supersteps=1 << 17,
            decode_width=2048,
            label="Whare-Map cost model, heterogeneous platforms",
            verbose=args.verbose,
        )
    elif name == "gtrace12k":
        out = _gtrace_device_bench(verbose=args.verbose, overrides=args.override)
    elif name == "gtrace12k-burst":
        out = _gtrace_device_bench(
            verbose=args.verbose, burst=True, overrides=args.override
        )
    elif name == "gtrace12k-coco":
        out = _gtrace_device_bench(
            verbose=args.verbose, cost_model="coco", overrides=args.override
        )
    elif name == "gtrace12k-host":
        from ksched_tpu.drivers.trace_replay import TraceReplayDriver, synthesize_trace
        from ksched_tpu.solver.layered import LayeredTransportSolver

        machines, events = synthesize_trace(
            num_machines=12_500, num_tasks=60_000, duration_s=600.0, seed=11,
            machine_churn=0.02,
        )
        driver = TraceReplayDriver(
            machines, backend=LayeredTransportSolver(), slots_per_machine=8
        )
        stats = driver.replay(events, window_s=5.0, max_rounds=60)
        target_ms = 10.0
        out = {
            "metric": (
                f"p50 scheduling-round latency, Google-trace replay, "
                f"{driver.num_machines} machines, {stats.rounds} rounds "
                f"({stats.submitted} submits, {stats.finished} finishes, "
                f"{stats.evicted} evictions), 4 classes, host bulk path"
            ),
            "value": round(stats.p50_ms, 3),
            "unit": "ms",
            "vs_baseline": round(target_ms / max(stats.p50_ms, 1e-9), 3),
        }
    elif name == "churn":
        # the device-resident round-pipeline benchmark: full-rebuild vs
        # delta-scatter vs device-resident export arms at 1% churn on
        # the event path, bit-identical placements asserted per round
        # (docs/round_pipeline.md; BENCH_PIPELINE artifacts)
        pov = parse_overrides(
            args.override,
            ("tasks", "machines", "rounds", "churn", "restart_budget",
             "cold_control"),
        )
        out = _churn_pipeline_bench(
            tasks=int(pov.get("tasks", 10_000)),
            machines=int(pov.get("machines", 1_000)),
            rounds=int(pov.get("rounds", 24)),
            churn=float(pov.get("churn", 0.01)),
            restart_budget=int(pov.get("restart_budget", 64)),
            cold_control=bool(int(pov.get("cold_control", 1))),
            verbose=args.verbose,
        )
        if pov:
            out["detail"]["overrides"] = dict(sorted(pov.items()))
    elif name == "gtrace100k":
        # the sharded rung's scale proof: 100k x 10k keep-mode churn +
        # burst through AutoSolver's HBM fitting gate on the virtual
        # 8-device mesh, paired vs the single-chip scan-CSR arm with
        # bit-identical placements asserted per round
        # (docs/sharding.md; BENCH_GTRACE100K artifacts)
        pov = parse_overrides(
            args.override,
            ("tasks", "machines", "rounds", "warmup", "churn",
             "burst_every", "burst_factor", "devices", "restart_budget"),
        )
        out = _sharded_scale_bench(
            tasks=int(pov.get("tasks", 100_000)),
            machines=int(pov.get("machines", 10_000)),
            rounds=int(pov.get("rounds", 30)),
            warmup=int(pov.get("warmup", 4)),
            churn=float(pov.get("churn", 0.01)),
            burst_every=int(pov.get("burst_every", 8)),
            burst_factor=int(pov.get("burst_factor", 10)),
            devices=int(pov.get("devices", 8)),
            restart_budget=int(pov.get("restart_budget", 64)),
            verbose=args.verbose,
        )
        if pov:
            out["detail"]["overrides"] = dict(sorted(pov.items()))
    elif name == "multitenant":
        # scheduler-as-a-service: N mixed-size cells through one warm
        # batched solver vs sequential-per-tenant, paired arms with
        # bit-identical placements asserted per cell per round
        # (ksched_tpu/tenancy; docs/multitenancy.md)
        pov = parse_overrides(
            args.override, ("cells", "rounds", "warmup", "restart_budget")
        )
        out = _multitenant_bench(
            cells=int(pov.get("cells", 16)),
            rounds=int(pov.get("rounds", 24)),
            warmup=int(pov.get("warmup", 4)),
            restart_budget=int(pov.get("restart_budget", 64)),
            verbose=args.verbose,
        )
        if pov:
            out["detail"]["overrides"] = dict(sorted(pov.items()))
    elif name == "mcmf-mega":
        # the general-graph megakernel microbench (ops/mcmf_pallas.py):
        # mega vs the scan-based CSR/ELL backends on the 10k x 1k
        # graph-path instance. On TPU the kernel runs compiled and the
        # record carries the measured mega-vs-csr ratio; on CPU the
        # kernel runs under the Pallas interpreter and the record marks
        # the device claim unmeasured (tools/mcmf_mega_bench.py).
        from tools.mcmf_mega_bench import run_bench as _mega_bench

        pov = parse_overrides(args.override, ("tasks", "machines", "solves"))
        out = _mega_bench(
            tasks=int(pov.get("tasks", 10_000)),
            machines=int(pov.get("machines", 1_000)),
            solves=int(pov.get("solves", 8)),
        )
        if pov:
            out["detail"]["overrides"] = dict(sorted(pov.items()))
    else:
        raise SystemExit(f"unknown config {name!r}; choose from {SUITE_CONFIGS}")
    out["config"] = name
    _emit_record(out, args)


def _quincy_multiblock_bench(
    rounds: int, chunk: int, verbose: bool = False
) -> dict:
    """Quincy BEYOND the maximally-compressive case: tasks read 2-3
    blocks each (signature = the SET of blocks), drawn from a skewed
    template pool larger than the group table, with fresh templates
    arriving between chunks — so the bench exercises signature
    diversity, overflow, and LRU eviction (QuincyGroupTable.evict_idle)
    rather than the one-block-per-task regime where 480 signatures fit
    G=512 trivially.

    Two phases: (1) TIMED device chunks (the standard floor-barred
    protocol) with on-device churn over the registered groups; between
    chunks the host registers new templates + evicts idle signatures
    and re-uploads the table (host->device only). (2) An UNTIMED
    host-driven quality segment where every task's true signature is
    known: each round's capped-table objective is compared against the
    EXACT full-diversity solve (every distinct signature its own row —
    the compression-loss oracle)."""
    import time

    import jax

    from ksched_tpu.costmodels.quincy_device import QuincyGroupTable
    from ksched_tpu.scheduler.device_bulk import DeviceBulkCluster
    from ksched_tpu.solver.layered import (
        LayeredProblem,
        LayeredTransportSolver,
    )
    from ksched_tpu.utils import next_pow2

    MBv = 1 << 20
    tasks, machines = 10_000, 1_000
    # G=1024 absorbs the whole ~500-signature working set (r3 measured
    # the G=512 cap costing 17.8%/26.6% realized-cost gap via ~86
    # overflowed signatures at sig_unit=cost_unit, 27 at sig 128 —
    # docs/NOTES.md); the compaction LADDER (256, 512) keeps typical
    # rounds on the 256-wide fused-kernel solve and routes the
    # ~500-active tail to a 512-wide solve instead of full-G width
    # (VERDICT r3 #2: both knobs measured, now turned).
    n_blocks, G = 480, 1024
    n_templates = 640
    rng = np.random.default_rng(7)

    # Split quanta: MB-granularity costs on multi-GB reads span ~12k
    # values and price-war depth scales with cost gaps in units
    # (unsolvable-in-budget at unit=1 on JAX-CPU); but cost and
    # signature quantization pull OPPOSITE ways — coarse costs create
    # exact cross-group ties that herd the synchronous solve (measured
    # p99 supersteps 3253 at 64 MB vs 6989 at uniform 128 MB), while a
    # coarse SIGNATURE key merges near-identical templates (overflow
    # 86 -> 27, realized gap 17.8% -> 3.6% at 128). cost 64 / sig 128
    # takes both.
    table = QuincyGroupTable(
        num_groups=G, num_machines=machines,
        cost_unit_mb=64, sig_unit_mb=128,
    )
    # Heavy-tailed block sizes (128 MB .. 4 GB): with uniform sizes a
    # multi-block read has NO preferred machine (no single holder
    # clears Quincy's >50% locality threshold, PREFERENCE_FRACTION),
    # and every template collapses to one no-preference signature. A
    # dominant block per read is what makes multi-block signatures
    # both diverse AND preference-carrying — the regime this config
    # exists to measure.
    sizes = (128 * MBv * np.exp(rng.exponential(1.2, n_blocks))).astype(
        np.int64
    )
    sizes = np.minimum(sizes, 4096 * MBv)
    for b in range(1, n_blocks + 1):
        table.blocks.register(
            b, int(sizes[b - 1]),
            rng.choice(machines, size=3, replace=False).tolist(),
        )

    def new_template():
        k = int(rng.integers(2, 4))  # 2-3 blocks
        return sorted(rng.choice(n_blocks, size=k, replace=False) + 1)

    templates = [new_template() for _ in range(n_templates)]
    # skewed popularity (the map-task pattern: few hot inputs)
    popularity = 1.0 / np.arange(1, n_templates + 1) ** 0.8
    popularity /= popularity.sum()

    def draw_groups(n):
        t_idx = rng.choice(n_templates, size=n, p=popularity)
        return (
            table.groups_for(
                np.zeros(n, np.int32), [templates[t] for t in t_idx]
            ),
            t_idx,
        )

    dev = DeviceBulkCluster(
        num_machines=machines, pus_per_machine=4, slots_per_pu=4,
        num_jobs=10, task_capacity=next_pow2(tasks + 4096),
        num_groups=G, supersteps=1 << 17, decode_width=2048,
        # measured active rows p50/p99/max = 91/96/99 (BENCH_SUITE r4):
        # the 128-wide rung carries virtually every round at about half
        # the 256-wide per-superstep cost; 256/512 catch diversity
        # spikes, full 1024 the pathological rest
        active_groups_cap=(128, 256, 512),
        # heavy-tailed discounts want the n/4 stage-1 schedule:
        # captured tail rounds 3580/3500 -> 51/261 supersteps (r4
        # sweep; the eps=1 schedule pays ~190-unit descents in unit
        # bounces)
        two_stage_eps0="quarter",
    )
    init_groups, _ = draw_groups(tasks)
    table.sync(dev)
    sigs_initial = len(table._sig2gid)
    dev.add_tasks(
        tasks, rng.integers(0, 10, tasks).astype(np.int32),
        groups=init_groups,
    )
    fill = dev.round()
    jax.block_until_ready(fill)

    platform = jax.devices()[0].platform
    min_wall_ms = MIN_CHUNK_WALL_MS if platform != "cpu" else 0.0
    churn_n = 100

    def maintain_table():
        """Between chunks: fresh templates arrive, idle signatures age
        out. Live counts come from the fetched state (outside any
        timed region); the refreshed table re-uploads host->device."""
        st = dev.fetch_state()
        live = np.asarray(st["live"])
        grp = np.asarray(st["grp"])
        live_per_group = np.bincount(grp[live], minlength=G)
        table.evict_idle(live_per_group, keep_fraction=0.75)
        for _ in range(32):
            templates[int(rng.integers(0, n_templates))] = new_template()
        # touch a sample so new templates register (and count overflow)
        _ = draw_groups(256)
        table.sync(dev)
        # on-device arrivals draw only REGISTERED signatures (freed
        # rows are not valid commodities until reused)
        occupied = sorted(table._sig2gid.values())
        dev.set_arrival_groups(np.unique(occupied))

    def timed_chunk(R, seed):
        t0 = time.perf_counter()
        stats = dev.run_steady_rounds(R, 0.01, churn_n, seed=seed)
        jax.block_until_ready(stats)
        np.asarray(jax.device_get(stats["live"][-1]))
        return (time.perf_counter() - t0) * 1e3, stats

    R = min(chunk, rounds)
    while True:
        jax.block_until_ready(dev.run_steady_rounds(R, 0.01, churn_n, seed=1))
        probe_ms, _ = timed_chunk(R, seed=1)
        if probe_ms >= 4 * min_wall_ms or R >= (1 << 20):
            break
        R *= 8
    if probe_ms < min_wall_ms:
        raise RuntimeError(f"chunk wall {probe_ms:.2f} ms unmeasurable")

    # round cost varies WIDELY across table-maintenance epochs (an
    # eviction sweep can leave a chunk 10x cheaper than the probe's),
    # so undercut chunks grow R and restart, as _device_bench does
    while True:
        chunks = max(3, -(-rounds // R))
        per_round_ms, chunk_walls, chunk_stats = [], [], []
        grown = False
        for rep in range(chunks):
            maintain_table()
            wall, stats = timed_chunk(R, seed=2 + rep)
            if wall < min_wall_ms:
                wall, stats = timed_chunk(R, seed=100 + rep)
            if wall < min_wall_ms:
                if R >= (1 << 20):
                    raise RuntimeError(
                        f"chunk {rep} wall {wall:.1f} ms below the bar "
                        f"at R={R} - rejecting the measurement"
                    )
                R *= 4
                warm = dev.run_steady_rounds(R, 0.01, churn_n, seed=1)
                jax.block_until_ready(warm)
                np.asarray(jax.device_get(warm["live"][-1]))
                grown = True
                break
            per_round_ms.append(wall / R)
            chunk_walls.append(round(wall, 1))
            chunk_stats.append(stats)
        if not grown:
            break

    ss_all, act_all = [], []
    for stats in chunk_stats:
        got = dev.fetch_stats(stats)
        assert got["converged"].all(), "a steady round did not converge"
        ss_all.append(np.asarray(got["supersteps"]))
        if "active_groups" in got:
            act_all.append(np.asarray(got["active_groups"]))
    from ksched_tpu.obs import soltel

    soltel.publish_round_supersteps(
        np.concatenate(ss_all), backend=f"device/{platform}"
    )

    # ---- untimed quality segment: capped table vs exact diversity ----
    solver = LayeredTransportSolver(max_supersteps=1 << 17)
    quality = _multiblock_quality_probe(
        table, templates, popularity, rng, solver, machines
    )

    ss_cat = np.concatenate(ss_all)
    p50 = float(np.percentile(per_round_ms, 50))
    target_ms = 10.0
    detail = {
        "rounds_per_chunk": R,
        "chunks_wall_ms": chunk_walls,
        "floor_bar_ms": round(min_wall_ms, 1),
        "signatures_initial": sigs_initial,
        "signatures_final": len(table._sig2gid),
        "overflow_distinct": table.overflowed,
        "evicted": table.evicted,
        "supersteps_p50": int(np.percentile(ss_cat, 50)),
        "supersteps_p99": int(np.percentile(ss_cat, 99)),
        "supersteps_max": int(ss_cat.max()),
        "latency_model": _round_latency_model(
            np.array(chunk_walls), R, ss_all
        ),
        **quality,
    }
    if act_all:
        act_cat = np.concatenate(act_all)
        detail["active_groups_p50"] = int(np.percentile(act_cat, 50))
        detail["active_groups_p99"] = int(np.percentile(act_cat, 99))
        detail["active_groups_max"] = int(act_cat.max())
    return {
        "metric": (
            f"p50 scheduling-round latency, {tasks} tasks x {machines} "
            f"machines, Quincy multi-block (2-3 blocks/task, "
            f"{n_templates} templates, G={G} + LRU eviction), 1% churn, "
            f"device-resident rounds ({R}-round chains), "
            f"backend=device/{platform}"
        ),
        "value": round(p50, 4),
        "unit": "ms",
        "vs_baseline": round(target_ms / p50, 3),
        "detail": detail,
    }


def _multiblock_quality_probe(
    table, templates, popularity, rng, solver, machines, n_rounds=8
):
    """Compression-loss oracle: for synthetic backlogs drawn from the
    template pool, solve (a) the CAPPED-table grouping (tasks of
    overflowed signatures pooled in the conservative overflow row,
    preferences lost) vs (b) the EXACT full-diversity grouping (every
    distinct signature its own row, all preferences kept) on identical
    machine capacity — then price BOTH placements at the TRUE per-task
    costs (each task's real template row). The realized-cost gap is the
    honest price of the static G cap: the capped solve's REPORTED
    objective also carries the overflow row's deliberate overcharge,
    which is accounting conservatism, not placement loss."""
    from ksched_tpu.costmodels.quincy import PREFERENCE_FRACTION
    from ksched_tpu.costmodels.quincy_device import _transfer_cost
    from ksched_tpu.solver.layered import LayeredProblem

    def true_row(t):
        total = 0
        local = {}
        for b in templates[t]:
            size = table.blocks.size(b)
            total += size
            for m in table.blocks.holders(b):
                local[m] = local.get(m, 0) + size
        unit = table.cost_unit_mb
        worst = _transfer_cost(total, 0, unit)
        row = np.full(machines, worst, np.int64)
        # same preference rule AND cost quantum as group_for, so the
        # gap measures the G cap, not a policy difference
        threshold = PREFERENCE_FRACTION * total
        for m, loc in local.items():
            if loc > threshold and 0 <= m < machines:
                row[m] = min(row[m], _transfer_cost(total, loc, unit))
        return row, worst

    def realized_cost(y, row_tasks):
        """Price a solve's placement at true per-task costs: tasks of
        each solved row take that row's machine grants in order (tasks
        within a row are interchangeable TO THE SOLVER; their true
        costs differ only in pooled overflow rows, where the in-order
        assignment is as arbitrary as the decode's)."""
        total = 0
        for r, tasks_r in enumerate(row_tasks):
            grants = y[r]
            ti = 0
            for m in np.nonzero(grants)[0]:
                for _ in range(int(grants[m])):
                    t = tasks_r[ti]
                    total += int(true_rows[t][0][m])
                    ti += 1
            for t in tasks_r[ti:]:  # unplaced: true escape cost
                total += int(true_rows[t][1] + 1)
        return total

    gaps = []
    n_templates = len(templates)
    true_rows = {t: true_row(t) for t in range(n_templates)}
    for _ in range(n_rounds):
        n = 200
        t_idx = rng.choice(n_templates, size=n, p=popularity)
        cap = rng.integers(0, 3, machines).astype(np.int32)

        # (a) capped table rows
        groups = table.groups_for(
            np.zeros(n, np.int32), [templates[t] for t in t_idx]
        )
        sup_a = np.bincount(groups, minlength=table.G).astype(np.int32)
        route_a = np.minimum(
            np.broadcast_to(table.e[:, None], (table.G, machines)),
            table.pref_w,
        ).astype(np.int64)
        act = np.nonzero(sup_a > 0)[0]
        res_a = solver.solve_layered(
            LayeredProblem(
                supply=sup_a[act],
                col_cap=cap,
                cost_cm=route_a[act].astype(np.int32),
                unsched_cost=0, ec_cost=0,
                row_unsched_cost=table.effective_u()[act],
            )
        )
        row_tasks_a = [
            [int(t) for t, g in zip(t_idx, groups) if g == gid]
            for gid in act
        ]
        realized_a = realized_cost(res_a.y, row_tasks_a)

        # (b) exact full-diversity rows (one per distinct template)
        uniq, inv = np.unique(t_idx, return_inverse=True)
        sup_b = np.bincount(inv, minlength=len(uniq)).astype(np.int32)
        route_b = np.stack([true_rows[t][0] for t in uniq])
        u_b = np.array([true_rows[t][1] + 1 for t in uniq], np.int64)
        res_b = solver.solve_layered(
            LayeredProblem(
                supply=sup_b, col_cap=cap,
                cost_cm=route_b.astype(np.int32),
                unsched_cost=0, ec_cost=0,
                row_unsched_cost=u_b,
            )
        )
        row_tasks_b = [
            [int(t) for t in t_idx[inv == r]] for r in range(len(uniq))
        ]
        realized_b = realized_cost(res_b.y, row_tasks_b)
        gaps.append((realized_a - realized_b) / max(1, realized_b))
    return {
        "realized_cost_gap_mean": round(float(np.mean(gaps)), 5),
        "realized_cost_gap_max": round(float(np.max(gaps)), 5),
    }


def _gtrace_device_bench(
    verbose: bool = False, burst: bool = False,
    cost_model: Optional[str] = None,
    overrides: Optional[list] = None,
) -> dict:
    """BASELINE config 5 on the PRODUCTION path: Google-trace replay at
    12.5k machines through DeviceBulkCluster's scanned replay program
    (per-job unsched costs, 4 classes, elastic membership — machine
    outages mid-trace). The host stages the whole windowed event stream
    up front; each timed chunk is ONE device dispatch covering K
    consecutive trace windows, closed by the scalar-fetch barrier and
    held to the same 2 s floor bar as the steady-state configs.

    burst=True (gtrace12k-burst, VERDICT r3 #5): the same scale under
    real-trace burst statistics — arrival spikes at 6x the mean rate
    (24 bursts x 30 s) and 4 CORRELATED outages of 256 machines each
    (rack failures), on top of the independent churn. Windows during a
    spike admit ~6x the steady batch and outage windows evict
    thousands at once; the steady number's headroom either survives
    this or the exception gets measured.

    cost_model="coco" (gtrace12k-coco, VERDICT r4 #1): the same trace
    scale with the CoCo interference model pricing the 4 scheduling
    classes against the running-class census — rows are census-
    dependent, so EVERY window runs the real iterative transport at
    the full [4, 12.5k] machine width instead of the per-job closed
    form. This is the machine axis of the iterative solver at the
    reference's flagship scale (Flowlessly solves whatever graph it
    is handed, scheduling/flow/placement/solver.go:60-90); the
    supersteps_max detail proves the solves are not degenerate."""
    import time

    import jax

    from ksched_tpu.drivers.trace_replay import (
        DeviceTraceReplayDriver,
        synthesize_trace,
    )

    platform = jax.devices()[0].platform
    # CPU runs (suite --cpu / CI) scale the trace down: the full 12.5k
    # machine x 8k window scan takes hours on a host backend, and the
    # CPU clock is honest at any chunk size (min_wall_ms = 0).
    if platform == "cpu":
        n_machines, window_s, n_windows, rate = 12_500, 1.0, 96, 60.0
        K0, chunks_wanted = 24, 3
        min_wall_ms = 0.0
        if cost_model:
            # iterative [4, 12.5k] solves are ~ms on TPU but the CPU
            # backend pays them serially; fewer windows keep CI honest
            n_windows, K0 = 32, 8
    else:
        n_machines, window_s, n_windows, rate = 12_500, 1.0, 12_288, 100.0
        K0, chunks_wanted = 512, 3
        min_wall_ms = MIN_CHUNK_WALL_MS
    # the census-priced variant must be CONTENDED to be meaningful: at
    # the default 8 slots/machine the trace occupies ~12% of 100k
    # slots and any solver converges in a handful of supersteps. Two
    # slots/machine + a hotter arrival rate put steady residency near
    # ~75% of 25k slots — the regime where interference pricing does
    # real work (comparable to coco50k's ~78% occupancy).
    slots_per_machine = 8
    decode_width = 4096
    task_capacity = 1 << 16 if burst else 1 << 15
    if burst:
        # r5 paired A/B/A (same-hour, identical workload totals):
        # decode 4096 -> 2048 measures 9.61/6.78/7.36 ms — the burst
        # spikes admit at most 527/window, so 2048 keeps 4x headroom
        # and halves the [width, M] mover-ranking passes
        decode_width = 2048
    else:
        # steady trace admissions peak at 129/window (8x headroom at
        # 1024); the decode-width term measured 4.1 ms/round on the
        # coco variant's same-hour ablation. The plain config's own
        # paired A/B/A (10.61 / 7.45 / 7.69) was ambient-dominated —
        # the adoption rests on the headroom argument plus the
        # coco-variant measurement, and on identical workload totals
        # in the B run
        decode_width = 1024
    if cost_model:
        slots_per_machine = 2
        rate = 160.0 if platform != "cpu" else 60.0
        # r5 ablation (BENCH_GTRACE_ABLATION_r05): at M=12.5k the
        # iterative config's cost was machinery, not supersteps —
        # decode 4096 -> 1024 saves 4.1 ms/round (admissions p50 160 /
        # max 199 per window; 1024 is 5x headroom) and Tcap 65536 ->
        # 32768 saves ~2.1 ms of Tcap-wide scans (steady live ~19.2k
        # at 160/s x 120 s runtimes). 12.48 -> 4.63 ms p50 measured,
        # identical placed/finished totals.
        decode_width = 1024
        task_capacity = 1 << 15
    # --override k=v ablation knobs (round-anatomy forensics — a
    # deviation from the named config is recorded in the metric line)
    ov = parse_overrides(overrides, (
        "n_machines", "rate", "slots_per_machine", "decode_width",
        "task_capacity", "n_windows",
    ))
    n_machines = int(ov.get("n_machines", n_machines))
    rate = float(ov.get("rate", rate))
    slots_per_machine = int(ov.get("slots_per_machine", slots_per_machine))
    decode_width = int(ov.get("decode_width", decode_width))
    task_capacity = int(ov.get("task_capacity", task_capacity))
    if "n_windows" in ov:
        n_windows = int(ov["n_windows"])
    duration_s = n_windows * window_s
    num_tasks = int(duration_s * rate)
    burst_kw = {}
    if burst:
        burst_kw = dict(
            burst_spike=6.0,
            burst_count=max(2, n_windows // 340),  # ~24 at 8192 windows
            burst_s=30.0 if n_windows > 512 else 4.0,
            correlated_outages=4,
            outage_block=max(8, n_machines // 50),  # 2% of the fleet
        )
    machines, events = synthesize_trace(
        num_machines=n_machines, num_tasks=num_tasks,
        duration_s=duration_s, mean_runtime_s=120.0, seed=11,
        machine_churn=0.02,
        **burst_kw,
    )
    policy_kw = {}
    if cost_model == "coco":
        from ksched_tpu.costmodels import coco
        from ksched_tpu.costmodels.device_costs import coco_device_cost_fn

        pen_rng = np.random.default_rng(7)
        penalties = pen_rng.integers(0, 40, (n_machines, 4)).astype(
            np.int64
        )
        policy_kw = dict(
            class_cost_fn=coco_device_cost_fn(penalties),
            unsched_cost=coco.UNSCHEDULED_COST,
            supersteps=1 << 17,
        )
    elif cost_model is not None:
        raise SystemExit(f"unknown gtrace cost_model {cost_model!r}")
    driver = DeviceTraceReplayDriver(
        machines, slots_per_machine=slots_per_machine, num_jobs_hint=64,
        task_capacity=task_capacity,
        decode_width=decode_width,
        **policy_kw,
    )
    t0 = time.perf_counter()
    sch = driver.stage(events, window_s=window_s)
    if verbose:
        print(
            f"# staged {sch['rounds']} windows ({sch['submitted']} submits, "
            f"{sch['finished']} finishes, {sch['dropped']} dropped) in "
            f"{time.perf_counter() - t0:.1f}s",
            file=sys.stderr,
        )

    def slice_schedule(i0, k):
        return {
            key: (v[i0 : i0 + k] if isinstance(v, np.ndarray) else v)
            for key, v in sch.items()
        }

    def timed_chunk(i0, k, seed):
        t0 = time.perf_counter()
        stats = driver.replay(slice_schedule(i0, k), seed=seed)
        jax.block_until_ready(stats)
        np.asarray(jax.device_get(stats["live"][-1]))
        return (time.perf_counter() - t0) * 1e3, stats

    total = sch["rounds"]
    K = min(K0, total // (chunks_wanted + 1))
    i0 = 0
    # warm chunk: compile + advance into the steady regime
    wall, _ = timed_chunk(i0, K, seed=1)
    i0 += K
    # 3x margin, not 2x: the replay configs carry ~2x ambient variance
    # on the shared host (docs/NOTES.md) — a warm chunk at 2.1x the bar
    # can be followed by timed chunks UNDER it when the ambient load
    # lifts mid-run (measured: 4.1 s warm, 1.97 s chunk 3)
    while min_wall_ms and wall < 3 * min_wall_ms and i0 + (chunks_wanted + 1) * 2 * K <= total:
        K *= 2
        wall, _ = timed_chunk(i0, K, seed=1)  # recompile at the new K
        i0 += K
    chunk_walls, chunk_stats = [], []
    timed_lo = i0
    while len(chunk_walls) < chunks_wanted and i0 + K <= total:
        wall, stats = timed_chunk(i0, K, seed=2 + len(chunk_walls))
        i0 += K
        if wall < min_wall_ms:
            # a chunk dipped under the bar mid-measurement (ambient
            # lift): grow K and restart the measured set if the staged
            # stream has room, else fail honestly
            if i0 + (chunks_wanted + 1) * 2 * K <= total:
                K *= 2
                wall, _ = timed_chunk(i0, K, seed=1)  # recompile+warm
                i0 += K
                chunk_walls, chunk_stats = [], []
                timed_lo = i0
                continue
            raise RuntimeError(
                f"gtrace chunk wall {wall:.1f} ms under the "
                f"{min_wall_ms:.0f} ms bar at K={K} with no windows left "
                "to grow into"
            )
        chunk_walls.append(round(wall, 1))
        chunk_stats.append(stats)
    # burst-coverage evidence: admission-batch stats of the TIMED
    # window range (a burst claim is only as good as the spikes the
    # clock actually saw)
    adm_timed = sch["adm_n"][timed_lo:i0]
    if len(chunk_walls) < 2:
        raise RuntimeError("not enough staged windows for 2 measured chunks")

    per_round_ms = [w / K for w in chunk_walls]
    ss_all, evicted, placed = [], 0, 0
    for stats in chunk_stats:
        got = driver.cluster.fetch_stats(stats)
        assert got["converged"].all(), "a replay round did not converge"
        ss_all.append(np.asarray(got["supersteps"]))
        evicted += int(got["evicted"].sum())
        placed += int(got["placed"].sum())
    from ksched_tpu.obs import soltel

    soltel.publish_round_supersteps(
        np.concatenate(ss_all), backend=f"device/{platform}"
    )
    p50 = float(np.percentile(per_round_ms, 50))
    target_ms = 10.0
    detail = {
        "rounds_per_chunk": K,
        "chunks_wall_ms": chunk_walls,
        "floor_bar_ms": round(min_wall_ms, 1),
        "windows_total": total,
        "submitted": sch["submitted"],
        "finished": sch["finished"],
        "evicted_measured": evicted,
        "placed_measured": placed,
        "adm_per_window_timed_p50": int(np.percentile(adm_timed, 50)),
        "adm_per_window_timed_max": int(adm_timed.max()),
        "supersteps_max": int(np.concatenate(ss_all).max()),
        "latency_model": _round_latency_model(
            np.array(chunk_walls), K, ss_all
        ),
    }
    burst_tag = (
        "BURST arrivals (6x spikes) + correlated rack outages, "
        if burst else ""
    )
    ss_cat = np.concatenate(ss_all)
    detail["supersteps_p50"] = int(np.percentile(ss_cat, 50))
    if ov:
        detail["overrides"] = {k: ov[k] for k in sorted(ov)}
    policy_tag = (
        "CoCo census-priced classes (iterative transport every window)"
        if cost_model == "coco" else "per-job unsched"
    )
    return {
        "metric": (
            f"p50 scheduling-round latency, Google-trace replay, "
            f"{n_machines} machines, {total} windows staged, 4 classes, "
            f"{policy_tag}, elastic membership, {burst_tag}"
            f"device replay scan ({K}-round chunks), backend=device/{platform}"
        ),
        "value": round(p50, 4),
        "unit": "ms",
        "vs_baseline": round(target_ms / p50, 3),
        "detail": detail,
    }


def _suite_stamp() -> dict:
    """Provenance header for the suite artifact: commit, platform, env.
    The reference's measurement point is a RECORDED per-round print
    (cmd/k8sscheduler/scheduler.go:146-150); the rebuild's equivalent
    must be a committed file, not prose (VERDICT r3 missing #1)."""
    import subprocess

    try:
        commit = subprocess.run(
            ["git", "rev-parse", "HEAD"], capture_output=True, text=True,
            cwd=os.path.dirname(os.path.abspath(__file__)), timeout=10,
        ).stdout.strip()
    except Exception:
        commit = "unknown"
    try:
        import jax

        platform = jax.devices()[0].platform
        jax_ver = jax.__version__
    except Exception:
        platform, jax_ver = "unknown", "unknown"
    return {
        "suite_stamp": True,
        "commit": commit,
        "platform": platform,
        "jax": jax_ver,
        "utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "configs": list(SUITE_CONFIGS),
    }


def run_suite(args) -> None:
    """All suite configs, each in its OWN subprocess: a device-to-host
    stats fetch permanently degrades later dispatches in the process on
    the tunneled-TPU transport (see _device_bench), so configs must not
    share a process or config N's fetches would poison config N+1's
    measurement.

    Every run writes its own machine-readable artifact (--suite-out,
    default BENCH_SUITE.jsonl next to this file): a provenance stamp
    line, then one JSON line per config — the committed equivalent of
    the reference's recorded round timer. Persistence no longer
    depends on a human redirecting stdout."""
    import subprocess

    out_path = args.suite_out
    if out_path is None:
        out_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_SUITE.jsonl"
        )
    lines = [json.dumps(_suite_stamp())]

    def emit(line: str) -> None:
        print(line)
        lines.append(line)
        # rewrite on every config so a crashed/interrupted suite still
        # leaves a valid partial artifact
        with open(out_path, "w") as f:
            f.write("\n".join(lines) + "\n")

    for name in SUITE_CONFIGS:
        cmd = [sys.executable, __file__, "--config", name,
               "--rounds", str(args.rounds), "--chunk", str(args.chunk)]
        if args.cpu:
            cmd.append("--cpu")
        if getattr(args, "fell_back", False):
            cmd.append("--fell-back")
        if args.verbose:
            cmd.append("--verbose")
        r = subprocess.run(cmd, capture_output=True, text=True)
        if args.verbose and r.stderr:
            sys.stderr.write(r.stderr)
        line = (r.stdout.strip().splitlines() or ["<no output>"])[-1]
        if r.returncode != 0:
            emit(json.dumps({"metric": f"config {name} FAILED", "value": None,
                             "unit": "ms", "vs_baseline": 0.0,
                             "config": name,
                             "error": (r.stderr or line)[-400:]}))
        else:
            emit(line)
    print(f"# suite artifact: {out_path}", file=sys.stderr)


def build(args):
    from ksched_tpu.scheduler.bulk import BulkCluster

    from ksched_tpu.solver.select import make_backend

    name = "auto" if args.backend == "autograph" else args.backend
    backend = make_backend(name, warm_start=not args.cold, fallback=False)
    cluster = BulkCluster(
        num_machines=args.machines,
        pus_per_machine=args.pus,
        slots_per_pu=args.slots,
        num_jobs=args.jobs,
        backend=backend,
        task_capacity=args.tasks + 4096,
    )
    return cluster, backend


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--tasks", type=int, default=10_000)
    ap.add_argument("--machines", type=int, default=1_000)
    ap.add_argument("--pus", type=int, default=4, help="PUs per machine")
    ap.add_argument("--slots", type=int, default=4, help="slots per PU")
    ap.add_argument("--jobs", type=int, default=10)
    ap.add_argument("--rounds", type=int, default=512, help="total measured rounds")
    ap.add_argument("--churn", type=float, default=0.01)
    ap.add_argument("--cold", action="store_true", help="no warm start between rounds")
    ap.add_argument("--small", action="store_true", help="quick smoke (100 tasks x 10 machines)")
    ap.add_argument("--cpu", action="store_true", help="run host-only on JAX-CPU (skip the accelerator); combine with --backend native/ref for the host solver paths")
    ap.add_argument(
        "--backend",
        choices=["auto", "device", "layered", "jax", "ell", "mega",
                 "native", "ref", "autograph"],
        default="auto",
        help=(
            "scheduling path: device = device-resident cluster (the TPU "
            "production path), layered/jax/ell/mega/native/ref = host "
            "cluster with that MCMF backend (mega = the VMEM-resident "
            "Pallas megakernel, interpreter-backed off-TPU), autograph "
            "= host cluster with the per-solve dense -> mega -> CSR "
            "dispatch (make_backend('auto')); auto = device"
        ),
    )
    ap.add_argument(
        "--chunk", type=int, default=64,
        help="device path: rounds per on-device scan chunk",
    )
    ap.add_argument(
        "--suite", action="store_true",
        help="run all five BASELINE.json configs (prints one JSON line "
        "per config instead of the single headline line); --rounds/"
        "--chunk apply only to the 10kx1k config — the others use "
        "fixed per-config budgets",
    )
    ap.add_argument(
        "--config", choices=SUITE_CONFIGS + EXTRA_CONFIGS, default=None,
        help="run a single named BASELINE.json config",
    )
    ap.add_argument(
        "--suite-out", default=None, metavar="PATH",
        help="suite artifact path (default: BENCH_SUITE.jsonl next to "
        "bench.py); written incrementally, one JSON line per config "
        "after a provenance stamp line",
    )
    ap.add_argument(
        "--override", action="append", default=[], metavar="K=V",
        help="config-knob override for round-anatomy ablations "
        "(gtrace configs: n_machines, rate, slots_per_machine, "
        "decode_width, task_capacity, n_windows); recorded in the "
        "output record",
    )
    ap.add_argument(
        "--obs-out", default=None, metavar="PATH",
        help="write the obs metrics-registry snapshot JSON at exit "
        "(ksched_tpu/obs; docs/observability.md)",
    )
    ap.add_argument(
        "--trace-out", default=None, metavar="PATH",
        help="record obs spans during the measured rounds and write a "
        "Chrome/Perfetto trace-event JSON at exit",
    )
    ap.add_argument("--verbose", action="store_true")
    ap.add_argument("--fell-back", dest="fell_back_flag",
                    action="store_true", help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.small:
        args.tasks, args.machines, args.rounds = 100, 10, 128
    args.fell_back = getattr(args, "fell_back_flag", False)
    if not args.cpu and not _accelerator_alive():
        print("# accelerator unreachable; falling back to cpu", file=sys.stderr)
        args.cpu = True
        args.fell_back = True
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"
        from ksched_tpu.utils import force_cpu_platform

        force_cpu_platform()

    import jax

    if args.suite:
        if args.trace_out or args.obs_out:
            # each suite config runs in its own subprocess; a tracer or
            # registry in this parent would capture nothing
            ap.error(
                "--trace-out/--obs-out apply to a single run, not "
                "--suite (pass them to one config instead)"
            )
        return run_suite(args)

    span_tracer = None
    if args.trace_out:
        from ksched_tpu.obs import SpanTracer

        span_tracer = SpanTracer().install()
    try:
        if args.config:
            return run_config(args)
        if args.backend in ("auto", "device"):
            args.backend = "device"
            return run_device_bench(args)
        return _run_bulk_bench(args)
    finally:
        if span_tracer is not None:
            span_tracer.uninstall()
            span_tracer.dump(args.trace_out)
            print(f"# obs: span trace -> {args.trace_out}", file=sys.stderr)
            if span_tracer.total == 0:
                print(
                    "# obs: WARNING: no spans were recorded — spans cover "
                    "the host bulk/layered round paths; the device-resident "
                    "path runs fused inside jit and records none",
                    file=sys.stderr,
                )
        if args.obs_out:
            from ksched_tpu.obs import dump_registry, get_registry

            reg = get_registry()
            dump_registry(reg, args.obs_out)
            print(f"# obs: registry snapshot -> {args.obs_out}", file=sys.stderr)
            fams = {f.name for f in reg.collect()}
            if not fams:
                print(
                    "# obs: WARNING: the registry snapshot is empty — "
                    "enable obs (drop KSCHED_OBS=0/--no-obs) to record",
                    file=sys.stderr,
                )
            elif "ksched_solve_supersteps" not in fams:
                # device-fused paths and the compiled host backends all
                # publish solver-interior telemetry now; only backends
                # that genuinely expose none land here
                print(
                    "# obs: WARNING: no solver-interior telemetry was "
                    "recorded — the native/cpu_ref backends expose no "
                    "superstep counters (docs/observability.md, Solver "
                    "interior)",
                    file=sys.stderr,
                )


def _publish_bench_obs(lat_ms, rounds_meta) -> None:
    """Mirror the measured rounds onto the obs metrics registry AFTER
    the clock stops, so --obs-out snapshots carry the same round/phase
    series the service publishes live while the measured loop itself
    performs zero registry operations — the overhead protocol in
    BENCH_OBS_OVERHEAD_r09.json depends on that. Publication goes
    through RoundTracer so the metric names, label sets, and the
    timing-key → phase mapping stay single-sourced in runtime/trace.py."""
    from ksched_tpu.runtime.trace import RoundTracer

    tracer = RoundTracer(capacity=1)  # publication only; records unused
    for total_ms, (timing, placed, work) in zip(lat_ms, rounds_meta):
        tracer.record_timed_round(
            timing, total_ms=total_ms, num_scheduled=placed, solver_work=work
        )


def _run_bulk_bench(args):
    import jax

    rng = np.random.default_rng(0)
    cluster, backend = build(args)
    devices = jax.devices()

    # Fill: admit all tasks, run rounds until placements settle.
    job_ids = rng.integers(0, args.jobs, args.tasks).astype(np.int32)
    cluster.add_tasks(args.tasks, job_ids)
    t0 = time.perf_counter()
    r = cluster.round()
    fill_s = time.perf_counter() - t0
    if args.verbose:
        print(
            f"# fill: placed {len(r.placed_tasks)}/{args.tasks} in {fill_s:.2f}s "
            f"(cold solve, incl. compile), unsched={r.num_unscheduled}, "
            f"work={_solver_work(backend)}",
            file=sys.stderr,
        )

    # Steady state: churn + measure.
    churn_n = max(1, int(args.tasks * args.churn))
    lat_ms = []
    rounds_meta = []
    for i in range(args.rounds):
        placed_rows = np.nonzero(cluster.task_pu >= 0)[0]
        done = rng.choice(placed_rows, size=min(churn_n, len(placed_rows)), replace=False)
        t0 = time.perf_counter()
        cluster.complete_tasks(cluster.task0 + done.astype(np.int32))
        cluster.add_tasks(churn_n, rng.integers(0, args.jobs, churn_n).astype(np.int32))
        r = cluster.round()
        lat_ms.append((time.perf_counter() - t0) * 1e3)
        rounds_meta.append((r.timing, len(r.placed_tasks), _solver_work(backend)))
        if args.verbose:
            t = r.timing
            print(
                f"# round {i}: {lat_ms[-1]:.2f}ms placed={len(r.placed_tasks)} "
                f"(solve={t['solve_s']*1e3:.2f} decode={t['decode_s']*1e3:.2f} "
                f"stats={t['stats_s']*1e3:.2f} apply={t['apply_s']*1e3:.2f}) "
                f"work={_solver_work(backend)}",
                file=sys.stderr,
            )

    if args.obs_out:
        _publish_bench_obs(lat_ms, rounds_meta)
    p50 = float(np.percentile(lat_ms, 50))
    target_ms = 10.0
    _emit_record(
        {
            "metric": (
                f"p50 scheduling-round latency, {args.tasks} tasks x "
                f"{args.machines} machines, trivial cost model, "
                f"{args.churn:.0%} churn, backend={args.backend}/{devices[0].platform}"
            ),
            "value": round(p50, 3),
            "unit": "ms",
            "vs_baseline": round(target_ms / p50, 3),
        },
        args,
    )


if __name__ == "__main__":
    main()
