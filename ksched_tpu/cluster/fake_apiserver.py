"""A loopback fake API server speaking the slice of the k8s API the
scheduler uses: pending-pod listing (field-selector semantics), node
listing, and the Binding subresource POST. Lets the HTTP adapter
(cluster/http_api.py) and the scheduler service run end-to-end over
real sockets with no cluster — the hermetic analogue of running the
reference against a bare kube-apiserver with no kubelets
(reference README.md:55-70).

Side-door endpoints (prefixed /_test) play podgen and the node
lifecycle: POST /_test/pods {"count": N}, POST /_test/nodes {...},
GET /_test/bindings.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional


class _State:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.pods: Dict[str, dict] = {}  # name -> spec
        self.nodes: List[dict] = []
        self.bindings: Dict[str, str] = {}  # pod -> node

    # shared by the HTTP handlers and the Python side-door so the two
    # entry points cannot drift on object schema
    def add_node(self, name: str, capacity: dict, unschedulable: bool) -> None:
        with self.lock:
            self.nodes.append(
                {
                    "metadata": {"name": name},
                    "spec": {"unschedulable": bool(unschedulable)},
                    "status": {"capacity": dict(capacity)},
                }
            )

    def add_pods(self, count: int, prefix: str, spec: dict) -> None:
        with self.lock:
            start = len(self.pods)
            for i in range(count):
                self.pods[f"{prefix}_{start + i}"] = dict(spec)


class _Handler(BaseHTTPRequestHandler):
    state: _State  # set by FakeAPIServer

    def log_message(self, *args) -> None:  # silence request logging
        pass

    def _json(self, code: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> dict:
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n).decode()) if n else {}

    def do_GET(self) -> None:
        st = self.state
        if self.path.startswith("/api/v1/pods"):
            with st.lock:
                # field-selector semantics: only pods not yet bound
                items = [
                    {"metadata": {"name": name}, "spec": spec}
                    for name, spec in st.pods.items()
                    if name not in st.bindings
                ]
            self._json(200, {"kind": "PodList", "items": items})
        elif self.path.startswith("/api/v1/nodes"):
            with st.lock:
                items = list(st.nodes)
            self._json(200, {"kind": "NodeList", "items": items})
        elif self.path == "/_test/bindings":
            with st.lock:
                self._json(200, dict(st.bindings))
        else:
            self._json(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:
        st = self.state
        parts = self.path.strip("/").split("/")
        # /api/v1/namespaces/{ns}/pods/{name}/binding
        if (
            len(parts) == 7
            and parts[:3] == ["api", "v1", "namespaces"]
            and parts[4] == "pods"
            and parts[6] == "binding"
        ):
            body = self._read_body()
            pod = parts[5]
            node = body.get("target", {}).get("name", "")
            with st.lock:
                if pod not in st.pods:
                    return self._json(404, {"error": f"pod {pod} not found"})
                st.bindings[pod] = node
            return self._json(201, {"kind": "Status", "status": "Success"})
        # /api/v1/namespaces/{ns}/pods — pod creation (the podgen path,
        # cmd/podgen/podgen.go:34-74 creates pods via the API server)
        if (
            len(parts) == 5
            and parts[:3] == ["api", "v1", "namespaces"]
            and parts[4] == "pods"
        ):
            body = self._read_body()
            name = body.get("metadata", {}).get("name")
            if not name:
                return self._json(400, {"error": "metadata.name required"})
            with st.lock:
                st.pods[name] = dict(body.get("spec", {}))
            return self._json(201, {"kind": "Pod", "metadata": {"name": name}})
        if self.path == "/_test/pods":
            body = self._read_body()
            count = int(body.get("count", 1))
            st.add_pods(count, body.get("prefix", "pod"), body.get("spec", {}))
            return self._json(201, {"created": count})
        if self.path == "/_test/nodes":
            body = self._read_body()
            st.add_node(
                body["name"], body.get("capacity", {}),
                bool(body.get("unschedulable")),
            )
            return self._json(201, {"ok": True})
        self._json(404, {"error": f"no route {self.path}"})


class FakeAPIServer:
    """Threaded loopback server; `base_url` after start()."""

    def __init__(self) -> None:
        self._state = _State()
        handler = type("Handler", (_Handler,), {"state": self._state})
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def base_url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "FakeAPIServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=2)

    # -- convenience for tests/demos (the podgen/node side-door) -----------

    def add_node(self, name: str, cores: int = 1, pus_per_core: int = 1,
                 unschedulable: bool = False) -> None:
        self._state.add_node(
            name, {"cores": cores, "pus_per_core": pus_per_core}, unschedulable
        )

    def create_pods(self, count: int, prefix: str = "pod", **spec) -> None:
        self._state.add_pods(count, prefix, spec)

    def bindings(self) -> Dict[str, str]:
        with self._state.lock:
            return dict(self._state.bindings)

    def pending_pods(self) -> int:
        with self._state.lock:
            return sum(
                1 for p in self._state.pods if p not in self._state.bindings
            )
