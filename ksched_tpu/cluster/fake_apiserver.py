"""A loopback fake API server speaking the slice of the k8s API the
scheduler uses: pending-pod listing (field-selector semantics), node
listing, and the Binding subresource POST. Lets the HTTP adapter
(cluster/http_api.py) and the scheduler service run end-to-end over
real sockets with no cluster — the hermetic analogue of running the
reference against a bare kube-apiserver with no kubelets
(reference README.md:55-70).

Side-door endpoints (prefixed /_test) play podgen and the node
lifecycle: POST /_test/pods {"count": N}, POST /_test/nodes {...},
GET /_test/bindings.

Hermetic fault hooks: `fault_hook` (constructor arg or
`set_fault_hook`) is consulted once per API request with a route kind
("list_pods" | "list_nodes" | "bind" | "create_pod"; /_test side-door
routes are never faulted) and may return
``{"kind": "error", "code": 503}`` (respond with that status),
``{"kind": "latency", "seconds": s}`` (sleep, then serve normally), or
``{"kind": "hang", "seconds": s}`` (sleep, then drop the connection
with no response — the client sees a timeout/connection error). A
`runtime.chaos.FaultInjector.http_fault` plugs in directly, giving
seeded 5xx/hang/latency schedules over real sockets.
"""

from __future__ import annotations

import json
import ssl
import subprocess
import tempfile
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Callable, Dict, List, Optional


#: process-wide cert cache: one keygen (+ one auto-cleaned temp dir)
#: shared by every TLS-mode server in the process
_CERT_DIR: Optional[tempfile.TemporaryDirectory] = None
_CERT_PATHS: Optional[tuple] = None


def make_self_signed_cert(directory: Optional[str] = None):
    """(cert_path, key_path) for a 127.0.0.1 self-signed cert, via the
    system openssl CLI (hermetic TLS tests; no cryptography dep).
    Without `directory`, the pair is generated once per process into a
    TemporaryDirectory cleaned up at interpreter exit — RSA keygen
    costs ~100 ms and every FakeAPIServer(tls=True) would otherwise
    leak a fresh /tmp dir."""
    global _CERT_DIR, _CERT_PATHS
    if directory is None and _CERT_PATHS is not None:
        return _CERT_PATHS
    if directory is None:
        _CERT_DIR = tempfile.TemporaryDirectory(prefix="ksched_tls_")
        d = Path(_CERT_DIR.name)
    else:
        d = Path(directory)
    cert, key = d / "cert.pem", d / "key.pem"
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048",
            "-keyout", str(key), "-out", str(cert),
            "-days", "1", "-nodes", "-subj", "/CN=127.0.0.1",
            "-addext", "subjectAltName=IP:127.0.0.1",
        ],
        check=True, capture_output=True,
    )
    if directory is None:
        _CERT_PATHS = (str(cert), str(key))
        return _CERT_PATHS
    return str(cert), str(key)


class _State:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.pods: Dict[str, dict] = {}  # name -> spec
        self.nodes: List[dict] = []
        self.bindings: Dict[str, str] = {}  # pod -> node
        #: (route_kind) -> None | {"kind": "error"|"hang"|"latency", ...};
        #: mutable at runtime so tests flip faults on and off mid-flight
        self.fault_hook: Optional[Callable[[str], Optional[dict]]] = None

    # shared by the HTTP handlers and the Python side-door so the two
    # entry points cannot drift on object schema
    def add_node(self, name: str, capacity: dict, unschedulable: bool) -> None:
        with self.lock:
            self.nodes.append(
                {
                    "metadata": {"name": name},
                    "spec": {"unschedulable": bool(unschedulable)},
                    "status": {"capacity": dict(capacity)},
                }
            )

    def add_pods(self, count: int, prefix: str, spec: dict) -> None:
        with self.lock:
            start = len(self.pods)
            for i in range(count):
                self.pods[f"{prefix}_{start + i}"] = dict(spec)


class _Handler(BaseHTTPRequestHandler):
    state: _State  # set by FakeAPIServer
    bearer: Optional[str] = None  # require this token when set

    def log_message(self, *args) -> None:  # silence request logging
        pass

    def _json(self, code: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> dict:
        n = int(self.headers.get("Content-Length", 0))
        return json.loads(self.rfile.read(n).decode()) if n else {}

    def _authorized(self) -> bool:
        if self.bearer is None:
            return True
        if self.headers.get("Authorization") == f"Bearer {self.bearer}":
            return True
        self._json(401, {"error": "unauthorized"})
        return False

    def _faulted(self, route: str) -> bool:
        """Consult the fault hook; True = the request was consumed by an
        injected fault and no normal handling should run."""
        hook = self.state.fault_hook
        if hook is None:
            return False
        action = hook(route)
        if action is None:
            return False
        kind = action.get("kind")
        if kind == "error":
            self._json(int(action.get("code", 503)), {"error": "chaos: injected fault"})
            return True
        if kind == "hang":
            # stall, then drop the connection without a response: the
            # client experiences a hung request ending in a transport
            # error (its timeout must be the bound, not our sleep)
            time.sleep(float(action.get("seconds", 1.0)))
            self.close_connection = True
            return True
        if kind == "latency":
            time.sleep(float(action.get("seconds", 0.05)))
            return False  # spike absorbed; serve normally
        raise ValueError(f"unknown fault action {action!r}")

    def do_GET(self) -> None:
        if not self._authorized():
            return
        st = self.state
        if self.path.startswith("/api/v1/pods"):
            if self._faulted("list_pods"):
                return
            with st.lock:
                # field-selector semantics: only pods not yet bound
                items = [
                    {"metadata": {"name": name}, "spec": spec}
                    for name, spec in st.pods.items()
                    if name not in st.bindings
                ]
            self._json(200, {"kind": "PodList", "items": items})
        elif self.path.startswith("/api/v1/nodes"):
            if self._faulted("list_nodes"):
                return
            with st.lock:
                items = list(st.nodes)
            self._json(200, {"kind": "NodeList", "items": items})
        elif self.path == "/_test/bindings":
            with st.lock:
                self._json(200, dict(st.bindings))
        else:
            self._json(404, {"error": f"no route {self.path}"})

    def do_POST(self) -> None:
        if not self._authorized():
            return
        st = self.state
        parts = self.path.strip("/").split("/")
        # /api/v1/namespaces/{ns}/pods/{name}/binding
        if (
            len(parts) == 7
            and parts[:3] == ["api", "v1", "namespaces"]
            and parts[4] == "pods"
            and parts[6] == "binding"
        ):
            if self._faulted("bind"):
                return
            body = self._read_body()
            pod = parts[5]
            node = body.get("target", {}).get("name", "")
            with st.lock:
                if pod not in st.pods:
                    return self._json(404, {"error": f"pod {pod} not found"})
                st.bindings[pod] = node
            return self._json(201, {"kind": "Status", "status": "Success"})
        # /api/v1/namespaces/{ns}/pods — pod creation (the podgen path,
        # cmd/podgen/podgen.go:34-74 creates pods via the API server)
        if (
            len(parts) == 5
            and parts[:3] == ["api", "v1", "namespaces"]
            and parts[4] == "pods"
        ):
            if self._faulted("create_pod"):
                return
            body = self._read_body()
            name = body.get("metadata", {}).get("name")
            if not name:
                return self._json(400, {"error": "metadata.name required"})
            with st.lock:
                st.pods[name] = dict(body.get("spec", {}))
            return self._json(201, {"kind": "Pod", "metadata": {"name": name}})
        if self.path == "/_test/pods":
            body = self._read_body()
            count = int(body.get("count", 1))
            st.add_pods(count, body.get("prefix", "pod"), body.get("spec", {}))
            return self._json(201, {"created": count})
        if self.path == "/_test/nodes":
            body = self._read_body()
            st.add_node(
                body["name"], body.get("capacity", {}),
                bool(body.get("unschedulable")),
            )
            return self._json(201, {"ok": True})
        self._json(404, {"error": f"no route {self.path}"})


class FakeAPIServer:
    """Threaded loopback server; `base_url` after start().

    `tls=True` serves https with a freshly generated self-signed
    127.0.0.1 cert (`ca_cert_path` is what clients should pin);
    `bearer` requires `Authorization: Bearer <token>` on every route
    (401 otherwise) — the hermetic stand-in for a kube-apiserver with
    token auth (the reference's client is built with credentials,
    k8s/k8sclient/client.go:34-42)."""

    def __init__(
        self,
        tls: bool = False,
        bearer: Optional[str] = None,
        fault_hook: Optional[Callable[[str], Optional[dict]]] = None,
    ) -> None:
        self._state = _State()
        self._state.fault_hook = fault_hook
        handler = type(
            "Handler", (_Handler,), {"state": self._state, "bearer": bearer}
        )
        self._tls = bool(tls)
        self.ca_cert_path: Optional[str] = None
        if tls:
            cert, key = make_self_signed_cert()
            self.ca_cert_path = cert
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(cert, key)

            class _TLSServer(ThreadingHTTPServer):
                # Per-CONNECTION wrap with a handshake timeout, run on
                # the per-connection handler thread (finish_request),
                # NOT the accept thread: a client that stalls its
                # handshake must cost only its own connection, not
                # serialize every other accept behind its 5 s timeout.
                # (Wrapping the listening socket would be worse still:
                # handshakes with no timeout inside serve_forever, and
                # a failed handshake raising out of the serve loop.)
                # wrap_socket detaches the raw socket's fd into the SSL
                # socket, so the caller's shutdown_request on the raw
                # socket is a no-op; the wrapper is closed here.
                def finish_request(self_inner, request, client_address):
                    request.settimeout(5)
                    try:
                        tls_sock = ctx.wrap_socket(request, server_side=True)
                    except (ssl.SSLError, OSError):
                        request.close()
                        return
                    try:
                        self_inner.RequestHandlerClass(
                            tls_sock, client_address, self_inner
                        )
                    finally:
                        tls_sock.close()

            self._httpd = _TLSServer(("127.0.0.1", 0), handler)
        else:
            self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
        self._thread: Optional[threading.Thread] = None

    @property
    def base_url(self) -> str:
        host, port = self._httpd.server_address[:2]
        scheme = "https" if self._tls else "http"
        return f"{scheme}://{host}:{port}"

    def start(self) -> "FakeAPIServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=2)

    def set_fault_hook(
        self, hook: Optional[Callable[[str], Optional[dict]]]
    ) -> None:
        """Install (or clear, with None) the per-request fault hook —
        e.g. a FaultInjector's ``http_fault`` — at runtime."""
        self._state.fault_hook = hook

    # -- convenience for tests/demos (the podgen/node side-door) -----------

    def add_node(self, name: str, cores: int = 1, pus_per_core: int = 1,
                 unschedulable: bool = False) -> None:
        self._state.add_node(
            name, {"cores": cores, "pus_per_core": pus_per_core}, unschedulable
        )

    def create_pods(self, count: int, prefix: str = "pod", **spec) -> None:
        self._state.add_pods(count, prefix, spec)

    def bindings(self) -> Dict[str, str]:
        with self._state.lock:
            return dict(self._state.bindings)

    def pending_pods(self) -> int:
        with self._state.lock:
            return sum(
                1 for p in self._state.pods if p not in self._state.bindings
            )
