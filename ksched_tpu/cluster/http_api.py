"""HTTP transport for the cluster API: the real-control-plane adapter.

Reference shape: the k8s client (k8s/k8sclient/client.go) runs informers
against the API server (HTTP watches feeding channels, :49-105) and
POSTs Binding subresources back (:128-147). This adapter is that
pattern over the rebuild's ClusterAPI protocol:

- two watch threads poll the pending-pods and nodes listings (the
  informer analogue; field-selector semantics — only pods with no node
  assignment — live server-side, exactly as the reference's selector
  `spec.nodeName==""` does, client.go:53-60) and feed the same buffered
  channels + debounce machinery the synthetic control plane uses;
- `assign_bindings` POSTs one k8s-shaped Binding subresource per
  placement: POST /api/v1/namespaces/{ns}/pods/{pod}/binding with a
  {"target": {"kind": "Node", "name": node}} body (client.go:128-147).

stdlib urllib only — no client dependencies. Pairs with
cluster/fake_apiserver.py for hermetic tests and demos. Auth plumbing
for a real kube-apiserver (the reference builds an authenticated
client, k8s/k8sclient/client.go:34-42): `bearer_token` rides every
request as an Authorization header, `ca_cert` pins the server cert for
https URLs, and `client_cert`/`client_key` enable mTLS — exercised
hermetically against the fake server's TLS mode.
"""

from __future__ import annotations

import json
import random
import ssl
import threading
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Set

from ..obs.metrics import Registry
from ..utils.backoff import ExpBackoff
from .api import Binding, ClusterAPI, NodeEvent, PodEvent
from .synthetic_api import SyntheticClusterAPI


class HTTPClusterAPI(ClusterAPI):
    def __init__(
        self,
        base_url: str,
        namespace: str = "default",
        poll_interval_s: float = 0.2,
        pod_chan_size: int = 5000,
        bearer_token: Optional[str] = None,
        ca_cert: Optional[str] = None,
        client_cert: Optional[str] = None,
        client_key: Optional[str] = None,
        request_timeout_s: float = 5.0,
        retry_budget: int = 4,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        backoff_rng: Optional[random.Random] = None,
        registry: Optional[Registry] = None,
    ) -> None:
        self.base_url = base_url.rstrip("/")
        self.namespace = namespace
        self.poll_interval_s = poll_interval_s
        self._auth_headers = (
            {"Authorization": f"Bearer {bearer_token}"} if bearer_token else {}
        )
        self._ssl_ctx: Optional[ssl.SSLContext] = None
        if self.base_url.startswith("https"):
            self._ssl_ctx = ssl.create_default_context(cafile=ca_cert)
            if client_cert:
                self._ssl_ctx.load_cert_chain(client_cert, client_key)
        elif ca_cert or client_cert or client_key:
            # cert material with a plain-http URL is always a config
            # mistake (a forgotten scheme would silently drop the mTLS
            # identity and send the bearer token in cleartext)
            raise ValueError(
                "ca_cert/client_cert/client_key require an https base_url"
            )
        self.request_timeout_s = request_timeout_s
        self.retry_budget = retry_budget
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self._backoff_rng = backoff_rng if backoff_rng is not None else random.Random()
        # The watch loops' failure-streak backoff shares the ExpBackoff
        # growth/jitter policy with the budgeted POST retries; base is
        # the healthy cadence, and the cap never drops below it (a down
        # control plane must not be probed faster than a healthy one).
        self._watch_backoff = ExpBackoff(
            base_s=max(poll_interval_s, 1e-6),
            max_s=max(backoff_max_s, poll_interval_s),
            rng=self._backoff_rng,
        )
        # Retry/drop observability (binding_retries / binding_drops /
        # watch_retries): counters live on an obs metrics registry —
        # every labeled child carries its own lock, so the two watch
        # threads and the scheduler thread publish without a shared
        # read-modify-write (tests/test_obs.py hammers this). The
        # default is a PRIVATE registry: stats() must be per-adapter
        # exact, and two adapters on a shared registry would alias the
        # same counter family. The service passes the process registry
        # explicitly (one adapter per process) so the counters also
        # serve on /metricsz; with obs disabled that falls back to a
        # private real Registry so stats() stays correct.
        reg = registry if registry is not None else Registry()
        if not isinstance(reg, Registry):  # e.g. handed the NullRegistry
            reg = Registry()
        self._events = reg.counter(
            "ksched_http_api_events_total",
            "control-plane adapter events (binding_retries, binding_drops, "
            "watch_retries)",
            labelnames=("event",),
        )
        # The channel+debounce layer is shared with the synthetic
        # control plane; this adapter only adds the HTTP watch/post.
        self._chan = SyntheticClusterAPI(pod_chan_size=pod_chan_size)
        self._seen_pods: Set[str] = set()
        self._seen_nodes: Set[str] = set()
        self._posted_bindings: dict = {}
        self._bindings_lock = threading.Lock()
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self._watch_pods, daemon=True),
            threading.Thread(target=self._watch_nodes, daemon=True),
        ]
        for t in self._threads:
            t.start()

    # -- HTTP plumbing -----------------------------------------------------

    def _open(self, req_or_url, timeout: Optional[float] = None):
        return urllib.request.urlopen(
            req_or_url,
            timeout=self.request_timeout_s if timeout is None else timeout,
            context=self._ssl_ctx,
        )

    def _count(self, key: str, n: int = 1) -> None:
        self._events.labels(event=key).inc(n)

    def stats(self) -> Dict[str, int]:
        """Retry/drop counters (binding_retries, binding_drops,
        watch_retries) — the observability surface the round trace
        folds into RoundRecord.retries."""
        return {
            labels["event"]: int(child.value)
            for labels, child in self._events.samples()
        }

    def _backoff(self) -> ExpBackoff:
        return ExpBackoff(
            base_s=self.backoff_base_s,
            max_s=self.backoff_max_s,
            max_retries=self.retry_budget,
            rng=self._backoff_rng,
        )

    def _post_with_retry(self, req, retry_counter: str) -> None:
        """POST with exponential backoff + jitter under a retry budget.
        5xx and transport errors are transient (retried); 4xx are
        config/state errors and re-raise immediately. Raises the last
        error once the budget is spent."""
        backoff = self._backoff()
        while True:
            try:
                with self._open(req) as r:
                    r.read()
                return
            except urllib.error.HTTPError as e:
                if e.code < 500:
                    raise
                err: Exception = e
            except (urllib.error.URLError, OSError) as e:
                err = e
            delay = backoff.next_delay()
            if delay is None:
                raise err
            self._count(retry_counter)
            if self._stop.wait(delay):
                raise err  # shutting down: stop retrying

    def _get_json(self, path: str) -> Optional[dict]:
        try:
            req = urllib.request.Request(
                self.base_url + path, headers=dict(self._auth_headers)
            )
            with self._open(req) as r:
                return json.loads(r.read().decode())
        except (urllib.error.URLError, OSError, json.JSONDecodeError):
            return None  # transient outage: informers keep retrying

    def _watch_wait(self, failure_streak: int) -> float:
        """Poll cadence with failure backoff: the normal interval while
        the server answers; exponentially longer (capped, jittered)
        across consecutive failures so a down control plane is probed,
        not hammered."""
        if failure_streak <= 0:
            return self.poll_interval_s
        # floor AFTER jitter: a downward draw must not probe a down
        # control plane faster than the healthy cadence
        return max(
            self.poll_interval_s,
            self._watch_backoff.delay_for(min(failure_streak, 8)),
        )

    # -- watch loops (informer analogue) -----------------------------------

    def _watch_pods(self) -> None:
        failure_streak = 0
        while not self._stop.wait(self._watch_wait(failure_streak)):
            got = self._get_json("/api/v1/pods?fieldSelector=spec.nodeName%3D%3D")
            if got is None:
                failure_streak += 1
                self._count("watch_retries")
                continue
            failure_streak = 0
            items = got.get("items", [])
            listed = {item["metadata"]["name"] for item in items}
            with self._bindings_lock:
                # Reconcile against the listing: a name that left the
                # pending set (bound, or deleted server-side) is
                # forgotten, so a pod re-created with the same name is
                # re-surfaced — and _seen_pods stays bounded by the
                # listing size instead of growing forever.
                self._seen_pods &= listed
                fresh = [
                    item for item in items
                    if item["metadata"]["name"] not in self._seen_pods
                ]
            for item in fresh:
                name = item["metadata"]["name"]
                spec = item.get("spec", {})
                event = PodEvent(
                    pod_id=name,
                    cpu_request=float(spec.get("cpu_request", 0.0)),
                    net_bw_request=int(spec.get("net_bw_request", 0)),
                    task_class=int(spec.get("task_class", 0)),
                )
                # bounded-wait offer so a full channel cannot wedge this
                # thread past close(); an unoffered pod is re-listed
                while not self._stop.is_set():
                    if self._chan.offer_pod(event, timeout_s=0.2):
                        with self._bindings_lock:
                            self._seen_pods.add(name)
                        break

    def _watch_nodes(self) -> None:
        failure_streak = 0
        while not self._stop.wait(self._watch_wait(failure_streak)):
            got = self._get_json("/api/v1/nodes")
            if got is None:  # transport failure — an empty listing is a healthy answer
                failure_streak += 1
                self._count("watch_retries")
                continue
            failure_streak = 0
            for item in got.get("items", []):
                if item.get("spec", {}).get("unschedulable"):
                    continue  # reference skips unschedulable nodes (:91-95)
                name = item["metadata"]["name"]
                if name in self._seen_nodes:
                    continue
                self._seen_nodes.add(name)
                cap = item.get("status", {}).get("capacity", {})
                self._chan.submit_node(
                    NodeEvent(
                        node_id=name,
                        num_cores=int(cap.get("cores", 1)),
                        pus_per_core=int(cap.get("pus_per_core", 1)),
                        net_bw_capacity=int(cap.get("net_bw", 0)),
                    )
                )

    # -- ClusterAPI --------------------------------------------------------

    def get_pod_batch(self, timeout_s: float) -> List[PodEvent]:
        return self._chan.get_pod_batch(timeout_s)

    def poll_pod_batch(self, timeout_s: float) -> List[PodEvent]:
        return self._chan.poll_pod_batch(timeout_s)

    def is_closed(self) -> bool:
        return self._stop.is_set()

    def get_node_batch(self, timeout_s: float) -> List[NodeEvent]:
        return self._chan.get_node_batch(timeout_s)

    def create_pod(self, pod_id: str, **spec) -> None:
        """Create a pod via the control plane (the podgen path: the
        reference's load generator POSTs pods to the API server,
        cmd/podgen/podgen.go:34-74). Posts exactly once; retry policy
        belongs to the caller — podgen already retries transient
        failures with backoff under its own budget, and an adapter-level
        retry layer underneath it would multiply worst-case attempts
        (budget × budget) and stack two backoff schedules."""
        body = json.dumps(
            {"apiVersion": "v1", "kind": "Pod",
             "metadata": {"name": pod_id}, "spec": spec}
        ).encode()
        req = urllib.request.Request(
            f"{self.base_url}/api/v1/namespaces/{self.namespace}/pods",
            data=body,
            headers={"Content-Type": "application/json", **self._auth_headers},
            method="POST",
        )
        with self._open(req) as r:
            r.read()

    def bindings(self) -> dict:
        """Pod→node placements this adapter successfully posted."""
        with self._bindings_lock:
            return dict(self._posted_bindings)

    def assign_bindings(self, bindings: List[Binding]) -> None:
        for b in bindings:
            body = json.dumps(
                {
                    "apiVersion": "v1",
                    "kind": "Binding",
                    "metadata": {"name": b.pod_id},
                    "target": {"apiVersion": "v1", "kind": "Node", "name": b.node_id},
                }
            ).encode()
            req = urllib.request.Request(
                f"{self.base_url}/api/v1/namespaces/{self.namespace}"
                f"/pods/{b.pod_id}/binding",
                data=body,
                headers={"Content-Type": "application/json", **self._auth_headers},
                method="POST",
            )
            try:
                self._post_with_retry(req, "binding_retries")
            except (urllib.error.URLError, OSError):
                # Retry budget spent (or a 4xx): the reference logs and
                # moves on (client.go:141-146); the pod stays pending
                # and re-enters a later batch, where the service's
                # re-deliver machinery re-emits the binding.
                self._count("binding_drops")
                with self._bindings_lock:
                    self._seen_pods.discard(b.pod_id)
            else:
                with self._bindings_lock:
                    self._posted_bindings[b.pod_id] = b.node_id

    def close(self) -> None:
        self._stop.set()
        self._chan.close()
        for t in self._threads:
            t.join(timeout=2)
