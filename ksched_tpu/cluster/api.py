"""The cluster API protocol: pods/nodes in, bindings out.

Reference shape: k8s/k8sclient/client.go —
- two informers feed buffered channels (pods :49-78, nodes :82-105);
- `GetPodBatch` debounce-batches pod arrivals (:153-193);
- `AssignBinding` posts pod→node bindings back (:128-147);
- internal types Pod{ID}, Node{ID}, Binding{PodID, NodeID}
  (k8s/k8stype/types.go:3-13).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import List, Optional


@dataclass(frozen=True)
class PodEvent:
    """An unscheduled pod surfaced by the control plane."""

    pod_id: str
    # Optional scheduling inputs (the reference's Pod carries only the
    # id; the rebuild forwards resource requests when the source has them)
    cpu_request: float = 0.0
    net_bw_request: int = 0
    task_class: int = 0


@dataclass(frozen=True)
class NodeEvent:
    """A schedulable node surfaced by the control plane."""

    node_id: str
    num_cores: int = 1
    pus_per_core: int = 1
    net_bw_capacity: int = 0


@dataclass(frozen=True)
class Binding:
    pod_id: str
    node_id: str


class ClusterAPI(abc.ABC):
    """What the scheduler main loop needs from a control plane."""

    @abc.abstractmethod
    def get_pod_batch(self, timeout_s: float) -> List[PodEvent]:
        """Debounced batch: block until the first pod arrives, then keep
        draining, restarting the quiet-period timer on every arrival,
        until ``timeout_s`` elapses with no new pod (reference:
        client.go:153-193). Returns [] only on close/shutdown."""

    @abc.abstractmethod
    def get_node_batch(self, timeout_s: float) -> List[NodeEvent]:
        """Same debounce contract for node arrivals (the reference polls
        its node channel for a fixed window at startup,
        cmd/k8sscheduler/scheduler.go:206-238)."""

    @abc.abstractmethod
    def assign_bindings(self, bindings: List[Binding]) -> None:
        """Push pod→node placements to the control plane."""

    def close(self) -> None:
        """Stop delivering events; get_*_batch return [] afterwards."""
