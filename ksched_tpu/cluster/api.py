"""The cluster API protocol: pods/nodes in, bindings out.

Reference shape: k8s/k8sclient/client.go —
- two informers feed buffered channels (pods :49-78, nodes :82-105);
- `GetPodBatch` debounce-batches pod arrivals (:153-193);
- `AssignBinding` posts pod→node bindings back (:128-147);
- internal types Pod{ID}, Node{ID}, Binding{PodID, NodeID}
  (k8s/k8stype/types.go:3-13).
"""

from __future__ import annotations

import abc
from dataclasses import dataclass
from typing import List, Optional


@dataclass(frozen=True)
class PodEvent:
    """An unscheduled pod surfaced by the control plane."""

    pod_id: str
    # Optional scheduling inputs (the reference's Pod carries only the
    # id; the rebuild forwards resource requests when the source has them)
    cpu_request: float = 0.0
    net_bw_request: int = 0
    task_class: int = 0


@dataclass(frozen=True)
class NodeEvent:
    """A schedulable node surfaced by the control plane."""

    node_id: str
    num_cores: int = 1
    pus_per_core: int = 1
    net_bw_capacity: int = 0


@dataclass(frozen=True)
class Binding:
    pod_id: str
    node_id: str


class ClusterAPI(abc.ABC):
    """What the scheduler main loop needs from a control plane."""

    @abc.abstractmethod
    def get_pod_batch(self, timeout_s: float) -> List[PodEvent]:
        """Debounced batch: block until the first pod arrives, then keep
        draining, restarting the quiet-period timer on every arrival,
        until ``timeout_s`` elapses with no new pod (reference:
        client.go:153-193). Returns [] only on close/shutdown."""

    def poll_pod_batch(self, timeout_s: float) -> List[PodEvent]:
        """Bounded variant of get_pod_batch: the *first* wait is capped
        at ``timeout_s`` too, so an empty return can mean "no pods right
        now" — not only "closed". The hardened service loop uses this
        plus ``is_closed()`` to tell a transient API-server outage from
        shutdown (an outage must idle the scheduler, never exit it) and
        to keep heartbeat sweeps running while the queue is quiet.

        Default: delegate to the blocking contract, under which an
        empty batch *does* mean closed — recorded so the default
        ``is_closed()`` agrees and the service loop still exits cleanly
        for adapters that override neither method (overriding only one
        of the pair would otherwise leave the loop spinning on instant
        empty batches forever after close)."""
        batch = self.get_pod_batch(timeout_s)
        if not batch:
            self._default_poll_closed = True
        return batch

    def is_closed(self) -> bool:
        """True once close() has been called (or the transport knows the
        control plane is gone for good). The loop-exit signal: an empty
        batch alone is NOT one. Adapters with a real channel override
        this; the default pairs with the default poll_pod_batch above."""
        return getattr(self, "_default_poll_closed", False)

    @abc.abstractmethod
    def get_node_batch(self, timeout_s: float) -> List[NodeEvent]:
        """Same debounce contract for node arrivals (the reference polls
        its node channel for a fixed window at startup,
        cmd/k8sscheduler/scheduler.go:206-238)."""

    @abc.abstractmethod
    def assign_bindings(self, bindings: List[Binding]) -> None:
        """Push pod→node placements to the control plane."""

    def close(self) -> None:
        """Stop delivering events; get_*_batch return [] afterwards."""


#: The ``stats()`` keys that count retry/re-post attempts — the only
#: keys the round trace folds into ``RoundRecord.retries``. Drop
#: counters (binding_drops) are a separate signal and must stay out.
#: An adapter defining a new retry counter must list it here to be
#: attributed; an explicit list fails visibly where a substring match
#: would drift silently.
RETRY_STAT_KEYS = ("binding_retries", "watch_retries", "binding_reposts_pending")
