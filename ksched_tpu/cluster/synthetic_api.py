"""In-process synthetic control plane.

Plays the role the real API server plays for the reference's informers
(k8s/k8sclient/client.go:49-105) and the role `podgen` plays for load
(cmd/podgen/podgen.go): producers submit pods/nodes from any thread;
the scheduler loop drains them with the same debounced-batch semantics
as GetPodBatch (client.go:153-193); bindings are recorded and can be
asserted on by tests or scraped by drivers.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List

from .api import Binding, ClusterAPI, NodeEvent, PodEvent


class SyntheticClusterAPI(ClusterAPI):
    def __init__(self, pod_chan_size: int = 5000) -> None:
        # Buffered like the reference's pod channel (-pcs flag,
        # cmd/k8sscheduler/scheduler.go:36).
        self._pods: "queue.Queue[PodEvent]" = queue.Queue(maxsize=pod_chan_size)
        self._nodes: "queue.Queue[NodeEvent]" = queue.Queue()
        self._bindings: Dict[str, str] = {}
        self._lock = threading.Lock()
        self._closed = threading.Event()

    # -- producer side (what podgen / node lifecycle drives) --------------

    def submit_pod(self, pod: PodEvent) -> None:
        self._pods.put(pod)

    def offer_pod(self, pod: PodEvent, timeout_s: float) -> bool:
        """Bounded-wait submit for producers that must stay responsive
        to shutdown (the HTTP watch threads): returns False instead of
        blocking past timeout_s when the channel is full."""
        try:
            self._pods.put(pod, timeout=timeout_s)
            return True
        except queue.Full:
            return False

    def submit_node(self, node: NodeEvent) -> None:
        self._nodes.put(node)

    def close(self) -> None:
        self._closed.set()

    def is_closed(self) -> bool:
        return self._closed.is_set()

    # -- consumer side (the scheduler main loop) --------------------------

    def _batch(self, q: "queue.Queue", timeout_s: float, wait_first: bool) -> list:
        """Debounce: wait for the first event, then drain with the quiet
        timer reset per arrival (reference: client.go:153-193).
        wait_first=True blocks indefinitely for the first event (the pod
        contract); False bounds the initial wait by timeout_s (the node
        startup-window contract, cmd/k8sscheduler/scheduler.go:206-238)."""
        batch = []
        first_deadline = None if wait_first else time.monotonic() + timeout_s
        # Phase 1 (poll so close() can land).
        while not self._closed.is_set():
            wait = 0.05
            if first_deadline is not None:
                remaining = first_deadline - time.monotonic()
                if remaining <= 0:
                    return batch
                wait = min(wait, remaining)
            try:
                batch.append(q.get(timeout=wait))
                break
            except queue.Empty:
                continue
        if not batch:
            return batch
        # Phase 2: keep draining until quiet for timeout_s.
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                batch.append(q.get(timeout=remaining))
                deadline = time.monotonic() + timeout_s  # timer reset
            except queue.Empty:
                break
        return batch

    def get_pod_batch(self, timeout_s: float) -> List[PodEvent]:
        return self._batch(self._pods, timeout_s, wait_first=True)

    def poll_pod_batch(self, timeout_s: float) -> List[PodEvent]:
        """Bounded-first-wait batch (see ClusterAPI.poll_pod_batch):
        empty means "quiet", not "closed" — check is_closed()."""
        return self._batch(self._pods, timeout_s, wait_first=False)

    def get_node_batch(self, timeout_s: float) -> List[NodeEvent]:
        return self._batch(self._nodes, timeout_s, wait_first=False)

    def assign_bindings(self, bindings: List[Binding]) -> None:
        with self._lock:
            for b in bindings:
                self._bindings[b.pod_id] = b.node_id

    # -- inspection -------------------------------------------------------

    def bindings(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._bindings)
