"""L7 cluster integration: the API-server adapter layer.

Reference: k8s/ — a thin anti-corruption layer between the scheduler
core and the cluster control plane (k8s/k8sclient/client.go:32-147,
k8s/k8stype/types.go). The rebuild keeps the same boundary: the
scheduler consumes pod/node events and emits bindings through the
ClusterAPI protocol. Backends:

- SyntheticClusterAPI — in-process channels (the fakeMachines role);
- HTTPClusterAPI — the real-control-plane shape: HTTP watch loops
  feeding the same channels, k8s Binding-subresource POSTs out;
- FakeAPIServer — a loopback server speaking the API slice the
  scheduler uses, for hermetic end-to-end runs over real sockets.
"""

from .api import Binding, ClusterAPI, NodeEvent, PodEvent
from .fake_apiserver import FakeAPIServer
from .http_api import HTTPClusterAPI
from .synthetic_api import SyntheticClusterAPI

__all__ = [
    "Binding",
    "ClusterAPI",
    "FakeAPIServer",
    "HTTPClusterAPI",
    "NodeEvent",
    "PodEvent",
    "SyntheticClusterAPI",
]
