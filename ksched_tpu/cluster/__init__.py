"""L7 cluster integration: the API-server adapter layer.

Reference: k8s/ — a thin anti-corruption layer between the scheduler
core and the cluster control plane (k8s/k8sclient/client.go:32-147,
k8s/k8stype/types.go). The rebuild keeps the same boundary: the
scheduler consumes pod/node events and emits bindings through the
ClusterAPI protocol; backends are the in-process SyntheticClusterAPI
(for benchmarks/tests — the role fakeMachines plays in the reference)
and, where a kubernetes client is installed, a real adapter following
the same informer → channel → debounced-batch shape.
"""

from .api import Binding, ClusterAPI, NodeEvent, PodEvent
from .synthetic_api import SyntheticClusterAPI

__all__ = [
    "Binding",
    "ClusterAPI",
    "NodeEvent",
    "PodEvent",
    "SyntheticClusterAPI",
]
