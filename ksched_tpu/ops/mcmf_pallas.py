"""Pallas TPU megakernel: the general-graph CSR MCMF solve, fused.

The scan-based CSR/ELL backends (solver/jax_solver.py, ell_solver.py)
pay ~6 full-entry HBM gathers plus 3 global scans per push-relabel
superstep — measured gather-bound at ~60 ms/solve for the 10k x 1k
general graph on TPU v5e and CPU alike, with CSR and ELL tying because
the layouts change nothing about the HBM round-trips (docs/ROUND5.md
section 5 closed the arithmetic: ~7.6 ns/element per gather pass, 6-10
ms per superstep). The identified lever, built here, is a megakernel:
the ENTIRE superstep loop — Bellman-Ford price tightening, the
cost-scaling phase schedule, every push/relabel superstep — runs inside
one `pl.pallas_call` with the sorted-entry tables pinned in VMEM for the
whole solve, following the pattern proven by ops/transport_pallas.py
for the dense layered transport.

Two representation changes make the CSR algorithm VMEM-shaped:

- PER-ENTRY state instead of per-node/per-arc state. Each of the 2M
  doubled residual entries carries its arc's flow and its SOURCE node's
  potential. The one cross-segment access the algorithm needs — the
  destination node's potential / tightening distance — is the PARTNER
  entry's source value, because arc (u, v)'s backward entry is exactly
  (v, u): a single fixed permutation (prow/pcol index pair, VMEM-
  resident, built once per graph structure) replaces every p[s_dst],
  excess[s_src] and delta[inv_order] gather of the HBM formulation.
- Per-node segment reductions (excess, maximal-push prefix, relabel
  bound) become SEGMENTED Hillis-Steele scans with head flags —
  log-step `pltpu.roll` + iota-masked combines, the construction the
  transport kernel already uses for plain cumsum (jnp.cumsum and
  lax.associative_scan do not lower on Pallas TPU). The entry tables
  are tiled into VMEM-friendly [R, L] blocks (row-major flattening of
  the sorted order); an intra-block scan plus a cross-block carry
  propagation over the R block rows yields the global segmented scan.

Semantics are the same synchronous Goldberg-Tarjan cost-scaling
push-relabel as solver/jax_solver.py `_solve_mcmf` — identical entry
order, identical maximal-push prefix allocation, identical jump
relabels and tightening sweeps — so the kernel's flows are
BIT-IDENTICAL to the CSR solver's, superstep for superstep (tests
assert exact flow equality, not just objective parity). Integer
arithmetic only.

Capacity: everything must fit VMEM (~16 MB/core). The live set is
~_MEGA_LIVE_TILES int32 entry tables, so graphs beyond
`mega_fits_vmem` route to the scan-based CSR fallback via the
dispatch seams (solver/select.py --backend mega, AutoSolver
escalation). The 10k x 1k headline graph is 131072 entries — ~9 MB
of live tables — comfortably resident.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Python ints (not jnp scalars): jnp constants captured by the kernel
# closure trip pallas_call's "captures constants" check.
_BIG = 1 << 30
_BIG_D = 1 << 28
_P_GUARD = 1 << 30

#: live int32 [R, L] tiles across a superstep (9 input tables + flow/
#: potential state + scan temporaries), used by the VMEM dispatch gate
_MEGA_LIVE_TILES = 18
_MEGA_VMEM_BUDGET_BYTES = 15 << 20

#: lane width of the entry tiling ([R, L] row-major); 512 keeps the
#: intra-row scan at 9 roll steps and the row counts small
MEGA_LANES = 512


def mega_entry_rows(num_entries: int, lanes: int = MEGA_LANES) -> int:
    """Block rows R for a 2M-entry table tiled [R, lanes]."""
    return max(1, -(-num_entries // lanes))


def mega_fits_vmem(
    num_entries: int,
    lanes: int = MEGA_LANES,
    budget_bytes: int = _MEGA_VMEM_BUDGET_BYTES,
    telemetry: bool = False,
) -> bool:
    """Whether the whole-solve live set stays VMEM-resident. With
    solver telemetry on, the budget charges one extra tile: the
    telemetry ring is clamped to at most one [R, L] tile of int32
    (`mega_telemetry_cap`), so +1 tile is exact, not an estimate."""
    padded = mega_entry_rows(num_entries, lanes) * lanes
    tiles = _MEGA_LIVE_TILES + (1 if telemetry else 0)
    return tiles * padded * 4 <= budget_bytes


def mega_telemetry_cap(R: int, L: int, cap: int) -> int:
    """Clamp a telemetry ring capacity so the [cap, SOLTEL_WIDTH]
    buffer never exceeds one [R, L] entry tile of VMEM — the +1-tile
    budget `mega_fits_vmem(telemetry=True)` charges. Small graphs get
    a shorter ring (their solves are short too); the ring keeps the
    FINAL supersteps either way."""
    from ..obs.soltel import SOLTEL_WIDTH

    return max(1, min(int(cap), (R * L) // SOLTEL_WIDTH))


def _mcmf_kernel(
    sign_ref, cap_ref, sc_ref, sup_ref, hs_ref, he_ref,
    prow_ref, pcol_ref, f0_ref, eps_ref,
    fout_ref, steps_ref, conv_ref, povf_ref,
    *tel_refs,
    R: int, L: int, alpha: int, max_supersteps: int,
    tighten_sweeps: int, telemetry_cap: int = 0,
):
    i32 = jnp.int32
    sign = sign_ref[:]       # [R, L] +1 fwd / -1 bwd / 0 pad
    cap = cap_ref[:]         # [R, L] arc capacity per entry
    sc = sc_ref[:]           # [R, L] signed scaled cost per entry
    sup = sup_ref[:]         # [R, L] source-node supply per entry
    hs = hs_ref[:]           # [R, L] segment-start flags (0/1 int32)
    he = he_ref[:]           # [R, L] segment-end flags (0/1 int32)
    prow = prow_ref[:]       # [R, L] partner block row
    pcol = pcol_ref[:]       # [R, L] partner lane
    eps0 = eps_ref[0]

    col = lax.broadcasted_iota(i32, (R, L), 1)
    row = lax.broadcasted_iota(i32, (R, 1), 0)

    def perm(x):
        """The partner permutation: entry (u, v) <-> entry (v, u) of
        the same arc. The ONLY non-elementwise data movement in the
        solve, and it reads VMEM."""
        return x[prow, pcol]

    def seg_scan(v, combine, rev: bool = False):
        """Inclusive segmented scan of v over the row-major [R, L]
        flattening (forward from segment starts, or reverse from
        segment ends): flag-carrying Hillis-Steele — at each log step
        an element absorbs its 2^t-neighbor unless its covered
        interval already reaches its segment head. Flags ride as 0/1
        int32 vectors (only int32 goes through pltpu.roll, matching
        the transport kernel's proven lowerings)."""
        f = he if rev else hs
        k = 1
        while k < L:
            if rev:
                pv = pltpu.roll(v, shift=L - k, axis=1)
                pf = pltpu.roll(f, shift=L - k, axis=1)
                ok = col < (L - k)
            else:
                pv = pltpu.roll(v, shift=k, axis=1)
                pf = pltpu.roll(f, shift=k, axis=1)
                ok = col >= k
            v = jnp.where(ok & (f == 0), combine(pv, v), v)
            f = jnp.maximum(f, jnp.where(ok, pf, i32(0)))
            k <<= 1
        if R > 1:
            # cross-block carry: pair-scan the per-row summaries, then
            # fold the exclusive carry into rows whose prefix never hit
            # a segment head — the "fori over blocks" of the global scan
            if rev:
                sv, sf = v[:, 0:1], f[:, 0:1]
            else:
                sv, sf = v[:, L - 1:L], f[:, L - 1:L]
            k = 1
            while k < R:
                if rev:
                    pv = pltpu.roll(sv, shift=R - k, axis=0)
                    pf = pltpu.roll(sf, shift=R - k, axis=0)
                    ok = row < (R - k)
                else:
                    pv = pltpu.roll(sv, shift=k, axis=0)
                    pf = pltpu.roll(sf, shift=k, axis=0)
                    ok = row >= k
                sv = jnp.where(ok & (sf == 0), combine(pv, sv), sv)
                sf = jnp.maximum(sf, jnp.where(ok, pf, i32(0)))
                k <<= 1
            if rev:
                cv = pltpu.roll(sv, shift=R - 1, axis=0)
                has = row < (R - 1)
            else:
                cv = pltpu.roll(sv, shift=1, axis=0)
                has = row >= 1
            v = jnp.where((f == 0) & has, combine(cv, v), v)
        return v

    def add(a, b):
        return a + b

    def seg_sum(v):
        """Segment total, broadcast to every entry of the segment."""
        return seg_scan(v, add) + seg_scan(v, add, rev=True) - v

    def seg_max(v):
        return jnp.maximum(
            seg_scan(v, jnp.maximum), seg_scan(v, jnp.maximum, rev=True)
        )

    def seg_min(v):
        return jnp.minimum(
            seg_scan(v, jnp.minimum), seg_scan(v, jnp.minimum, rev=True)
        )

    def seg_excl(v):
        """In-segment exclusive prefix sum (the maximal-push order)."""
        return seg_scan(v, add) - v

    def resid(f):
        return jnp.where(sign > 0, cap - f, jnp.where(sign < 0, f, i32(0)))

    def excess_of(f):
        return sup - seg_sum(sign * f)

    def saturate(f, p):
        # per-arc refine expressed per entry: rc_fwd(arc) = sign * rc
        rcf = sign * (sc + p - perm(p))
        return jnp.where(rcf < 0, cap, jnp.where(rcf > 0, i32(0), f))

    def tighten(f):
        """Price tightening: synchronous Bellman-Ford over residual
        reduced costs, exactly solver/jax_solver.py tighten — d lives
        broadcast per segment; d[s_dst] is the partner's value."""
        exc0 = excess_of(f)
        r = resid(f)
        d0 = jnp.where(exc0 < 0, i32(0), i32(_BIG_D))

        def t_cond(state):
            _d, changed, it = state
            return changed & (it < tighten_sweeps)

        def t_body(state):
            d, _, it = state
            cand = jnp.where(r > 0, sc + perm(d), i32(_BIG_D))
            best = seg_min(cand)
            d2 = jnp.maximum(jnp.minimum(d, best), -i32(_BIG_D))
            return d2, jnp.any(d2 != d), it + 1

        d, _, _ = lax.while_loop(
            t_cond, t_body, (d0, jnp.bool_(True), i32(0))
        )
        return -jnp.minimum(d, i32(_BIG_D))

    def superstep(f, p, eps, exc):
        r = resid(f)
        rc = sc + p - perm(p)
        adm = (r > 0) & (rc < 0) & (exc > 0)
        r_adm = jnp.where(adm, r, i32(0))
        # maximal push: allocate each node's excess across admissible
        # entries front-to-back (same sorted order as the CSR solver)
        delta = jnp.clip(exc - seg_excl(r_adm), 0, r_adm)
        new_f = f + sign * (delta - perm(delta))

        pushed = seg_sum(delta)
        sum_r = seg_sum(r)
        cand = jnp.where(r > 0, perm(p) - sc, -i32(_BIG))
        best = seg_max(cand)
        relabel = (exc > 0) & (pushed == 0) & (sum_r > 0)
        new_p = jnp.where(relabel, best - eps, p)
        if not telemetry_cap:
            return new_f, new_p, ()
        # soltel counters (cols 3..6) from state this superstep already
        # holds in VMEM — pure reductions and masks, no new gathers, so
        # the kernel's MEGA_KERNEL_PERM_GATHERS budget is unchanged.
        # Per-node quantities (relabels) are counted at segment heads;
        # delta counts each pushed unit once (per-entry amounts).
        aux = (
            jnp.sum(delta),
            jnp.sum(jnp.where((hs == 1) & relabel, i32(1), i32(0))),
            jnp.sum(jnp.where((sign > 0) & (r == 0), i32(1), i32(0))),
            jnp.sum(adm.astype(i32)),
        )
        return new_f, new_p, aux

    if telemetry_cap:
        tel_rows_iota = lax.broadcasted_iota(i32, (telemetry_cap, 1), 0)
        tel_cols_iota = lax.broadcasted_iota(i32, (1, 8), 1)

    def tel_update(tel, steps, eps, exc, aux):
        """Write one soltel row at steps % cap — a masked elementwise
        select over the [cap, 8] ring (dynamic-index stores don't
        lower on Pallas TPU; this does, and the ring is small)."""
        pushed_n, relabels, saturated, work = aux
        active = jnp.sum(jnp.where((hs == 1) & (exc > 0), i32(1), i32(0)))
        exc_pos = jnp.sum(jnp.where(hs == 1, jnp.maximum(exc, 0), i32(0)))
        vals = (eps, active, exc_pos, pushed_n, relabels, saturated, work)
        row = i32(0)
        for j, v in enumerate(vals):
            row = jnp.where(tel_cols_iota == j, v, row)
        idx = jnp.remainder(steps, i32(telemetry_cap))
        return jnp.where(tel_rows_iota == idx, row, tel)

    def phase_cond(state):
        steps, done = state[3], state[4]
        return ~done & (steps < max_supersteps)

    def phase_body(state):
        if telemetry_cap:
            f, p, eps, steps, done, tel = state
        else:
            f, p, eps, steps, done = state
        exc = excess_of(f)
        any_active = jnp.any(exc > 0)

        def do_step(_):
            f2, p2, aux = superstep(f, p, eps, exc)
            if not telemetry_cap:
                return f2, p2, eps, steps + 1, jnp.bool_(False)
            tel2 = tel_update(tel, steps, eps, exc, aux)
            return f2, p2, eps, steps + 1, jnp.bool_(False), tel2

        def next_phase(_):
            finished = eps <= 1
            new_eps = jnp.maximum(i32(1), eps // alpha)
            f2 = jnp.where(finished, f, saturate(f, p))
            out = (f2, p, jnp.where(finished, eps, new_eps), steps, finished)
            return out + ((tel,) if telemetry_cap else ())

        return lax.cond(any_active, do_step, next_phase, operand=None)

    f0 = f0_ref[:]
    p0 = tighten(f0)
    f1 = saturate(f0, p0)  # mop up any residual violations
    state = (f1, p0, eps0, i32(0), jnp.bool_(False))
    if telemetry_cap:
        state = state + (jnp.zeros((telemetry_cap, 8), i32),)
        f, p, eps, steps, done, tel = lax.while_loop(
            phase_cond, phase_body, state
        )
        tel_refs[0][:] = tel
    else:
        f, p, eps, steps, done = lax.while_loop(phase_cond, phase_body, state)
    exc = excess_of(f)
    fout_ref[:] = f
    steps_ref[0] = steps
    conv_ref[0] = (done & (jnp.max(jnp.abs(exc)) == 0)).astype(i32)
    povf_ref[0] = (jnp.max(jnp.abs(p)) >= i32(_P_GUARD)).astype(i32)


@functools.partial(
    jax.jit,  # kschedlint: program=mega_solve
    static_argnames=(
        "R", "L", "alpha", "max_supersteps", "tighten_sweeps", "interpret",
        "telemetry_cap",
    ),
)
def mcmf_loop_pallas(
    cap, cost, supply, flow0, eps_init,
    e_arc, e_sign, e_src, e_hs, e_he, e_prow, e_pcol, fwd_pos,
    R: int, L: int,
    alpha: int = 8,
    max_supersteps: int = 50_000,
    tighten_sweeps: int = 32,
    interpret: bool = False,
    telemetry_cap: int = 0,
):
    """One fused kernel per general-graph MCMF solve.

    cap/cost/flow0: int32[M] per arc (cost pre-scaled by the node
    count); supply: int32[N]; eps_init: int32 scalar. e_*: the padded
    [R*L] entry tables of a MegaPlan (solver/mega_solver.py), built
    from the cached `build_csr_plan` ordering; fwd_pos: int32[M] flat
    position of each arc's forward entry. Returns
    (flow[M], steps, converged, p_overflow) matching `_solve_mcmf`'s
    public result bit-for-bit (+ the [telemetry_cap, 8] soltel ring
    when telemetry_cap > 0 — written from inside the pallas_call to a
    dedicated VMEM output, clamped by `mega_telemetry_cap` to one
    entry tile so the VMEM budget grows by exactly +1 tile). The
    per-solve entry materialization (cap/cost/supply/flow gathered to
    entry order) runs as plain XLA ONCE per solve — the kernel itself
    never touches HBM between supersteps."""
    i32 = jnp.int32
    if telemetry_cap:
        telemetry_cap = mega_telemetry_cap(R, L, telemetry_cap)
    live = e_sign != 0
    arc = jnp.clip(e_arc, 0, cap.shape[0] - 1)
    src = jnp.clip(e_src, 0, supply.shape[0] - 1)
    sign2 = e_sign.astype(i32).reshape(R, L)
    cap2 = jnp.where(live, cap[arc], 0).astype(i32).reshape(R, L)
    sc2 = jnp.where(live, e_sign * cost[arc], 0).astype(i32).reshape(R, L)
    sup2 = jnp.where(live, supply[src], 0).astype(i32).reshape(R, L)
    f02 = jnp.where(live, flow0[arc], 0).astype(i32).reshape(R, L)

    out_shape = [
        jax.ShapeDtypeStruct((R, L), jnp.int32),
        jax.ShapeDtypeStruct((1,), jnp.int32),
        jax.ShapeDtypeStruct((1,), jnp.int32),
        jax.ShapeDtypeStruct((1,), jnp.int32),
    ]
    out_specs = [
        pl.BlockSpec(memory_space=pltpu.VMEM),
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec(memory_space=pltpu.SMEM),
    ]
    if telemetry_cap:
        out_shape.append(jax.ShapeDtypeStruct((telemetry_cap, 8), jnp.int32))
        out_specs.append(pl.BlockSpec(memory_space=pltpu.VMEM))
    outs = pl.pallas_call(  # kschedlint: program=mega_solve
        functools.partial(
            _mcmf_kernel,
            R=R, L=L, alpha=alpha, max_supersteps=max_supersteps,
            tighten_sweeps=tighten_sweeps, telemetry_cap=telemetry_cap,
        ),
        out_shape=out_shape,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=out_specs,
        interpret=interpret,
    )(
        sign2,
        cap2,
        sc2,
        sup2,
        e_hs.astype(i32).reshape(R, L),
        e_he.astype(i32).reshape(R, L),
        e_prow.astype(i32).reshape(R, L),
        e_pcol.astype(i32).reshape(R, L),
        f02,
        eps_init.astype(i32).reshape(1),
    )
    f_out, steps, conv, povf = outs[:4]
    flow = f_out.reshape(-1)[fwd_pos]
    base = (flow, steps[0], conv[0] != 0, povf[0] != 0)
    if telemetry_cap:
        return base + (outs[4],)
    return base


# Level-3 registry ownership (ksched_tpu/analysis/program_registry.py)
from ..analysis.program_registry import declare_programs as _declare_programs

_declare_programs(__name__, "mega_solve")
