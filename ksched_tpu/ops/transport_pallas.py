"""Pallas TPU kernel: the dense layered-transport MCMF solve, fused.

The XLA formulation (solver/layered.py `_transport_loop`) dispatches ~20
fused-but-separate ops per push/relabel superstep, with `lax.while_loop`
round-tripping the [C, Mp] state through HBM between supersteps. This
kernel runs the ENTIRE solve — Bellman–Ford price tightening, the
cost-scaling phase schedule, and every push/relabel superstep — inside a
single `pl.pallas_call`: the flow matrix, potentials, and residuals stay
resident in VMEM for the whole solve, and the host dispatches exactly one
kernel per scheduling round.

Semantics are the same synchronous Goldberg–Tarjan cost-scaling
push-relabel as the XLA path (costs pre-scaled so eps=1 is exact; maximal
pushes via exclusive prefix sums; jump relabels; the reference solver this
replaces is Flowlessly, invoked over DIMACS pipes at
scheduling/flow/placement/solver.go:92-123). Integer arithmetic only, so
the kernel and the XLA path produce bit-identical flows — tests assert
exact equality superstep-for-superstep.

Pallas TPU constraints shape the port (probed on TPU v5e):

- `jnp.cumsum` / `jnp.sort` do NOT lower; prefix sums are hand-rolled
  Hillis–Steele scans (log2 steps of `pltpu.roll` + iota-masked adds).
- `lax.while_loop` / `lax.cond` DO lower, so the convergence-bounded
  phase loop runs in-kernel (no fixed trip count, early exit preserved).
- Scalars (step count, convergence flag) exit through SMEM outputs.
- All state is >=2D: supplies are [C,1] columns, machine vectors [1,Mp]
  rows, the sink potential a [1,1] cell.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Python ints (not jnp scalars): jnp constants captured by the kernel
# closure trip pallas_call's "captures constants" check.
_BIG = 1 << 30
_BIG_D = 1 << 28


def _cumsum(x, axis: int, n: int):
    """Inclusive prefix sum along `axis` (length n): Hillis–Steele —
    log-step rolls with iota masks, since cumsum doesn't lower."""
    idx = lax.broadcasted_iota(jnp.int32, x.shape, axis)
    k = 1
    while k < n:
        shifted = pltpu.roll(x, shift=k, axis=axis)
        x = x + jnp.where(idx >= k, shifted, 0)
        k <<= 1
    return x


def _transport_kernel(
    wS_ref, supply_ref, colcap_ref, eps_ref, pminit_ref,
    y_ref, pm_ref, steps_ref, conv_ref,
    *, C: int, Mp: int, alpha: int, max_supersteps: int,
    refine_waves: int = 0,
):
    i32 = jnp.int32
    wS = wS_ref[:]                       # [C, Mp]
    supply = supply_ref[:]               # [C, 1]
    col_cap = colcap_ref[:]              # [1, Mp]
    eps0 = eps_ref[0]
    pm_init = pminit_ref[:]              # [1, Mp] carried machine prices
    U = jnp.minimum(supply, col_cap)     # [C, Mp] fwd arc capacity

    def excesses(y, z):
        e_row = supply - jnp.sum(y, axis=1, keepdims=True)        # [C, 1]
        e_col = jnp.sum(y, axis=0, keepdims=True) - z             # [1, Mp]
        e_sink = jnp.sum(z) - jnp.sum(supply)                     # scalar
        return e_row, e_col, e_sink

    # --- price tightening from the carried machine prices: re-derive
    # row/sink potentials so the zero flow is 0-optimal for ANY pm_init
    # (zeros reduce exactly to cold shortest-distance tightening; see
    # solver/layered.py transport_tighten) ---
    live = col_cap > 0
    # clamp carried prices so pm0 - wS cannot wrap int32 (see
    # solver/layered.py transport_tighten)
    pm0 = jnp.where(live, jnp.clip(pm_init, -_BIG_D, _BIG_D), -_BIG_D)
    has_arc = U > 0
    pr0 = jnp.max(jnp.where(has_arc, pm0 - wS, -_BIG_D), axis=1, keepdims=True)
    pr0 = jnp.where(jnp.any(has_arc, axis=1, keepdims=True), pr0, i32(0))
    psink0 = jnp.min(jnp.where(live, pm0, _BIG_D)).reshape(1, 1)
    psink0 = jnp.where(jnp.any(live), psink0, i32(0))

    def saturate(y, z, pr, pm, psink):
        rcf = wS + pr - pm
        y2 = jnp.where(rcf < 0, U, jnp.where(rcf > 0, i32(0), y))
        rcs = pm - psink
        z2 = jnp.where(rcs < 0, col_cap, jnp.where(rcs > 0, i32(0), z))
        return y2, z2

    def price_refine(y, z, pr, pm, psink, eps):
        """Price refinement between eps phases (solver/layered.py
        _price_refine): lower potentials toward eps-optimality of the
        CURRENT flow so the following partial saturate floods only the
        few still-violating arcs. min-reductions and selects only — no
        cumsum/sort, so it lowers cleanly in Pallas TPU."""
        def body(_, state):
            pr, pm, psink = state
            bound_m = jnp.min(
                jnp.where(U - y > 0, wS + pr + eps, _BIG), axis=0,
                keepdims=True,
            )
            pm2 = jnp.maximum(jnp.minimum(pm, bound_m), -_BIG_D)
            pm2 = jnp.minimum(pm2, jnp.where(z > 0, psink + eps, _BIG))
            bound_r = jnp.min(
                jnp.where(y > 0, pm2 - wS + eps, _BIG), axis=1,
                keepdims=True,
            )
            pr2 = jnp.maximum(jnp.minimum(pr, bound_r), -_BIG_D)
            bound_s = jnp.min(
                jnp.where(col_cap - z > 0, pm2 + eps, _BIG)
            ).reshape(1, 1)
            psink2 = jnp.maximum(jnp.minimum(psink, bound_s), -_BIG_D)
            return pr2, pm2, psink2

        return lax.fori_loop(0, refine_waves, body, (pr, pm, psink))

    def saturate_eps(y, z, pr, pm, psink, eps):
        rcf = wS + pr - pm
        y2 = jnp.where(rcf < -eps, U, jnp.where(rcf > eps, i32(0), y))
        rcs = pm - psink
        z2 = jnp.where(rcs < -eps, col_cap, jnp.where(rcs > eps, i32(0), z))
        return y2, z2

    def superstep(y, z, pr, pm, psink, eps):
        e_row, e_col, e_sink = excesses(y, z)
        rcf = wS + pr - pm

        # rows push forward along admissible arcs (maximal push via
        # in-row exclusive prefix sums)
        r_fwd = U - y
        r_adm = jnp.where((r_fwd > 0) & (rcf < 0), r_fwd, i32(0))
        excl = _cumsum(r_adm, 1, Mp) - r_adm
        delta_f = jnp.clip(e_row - excl, 0, r_adm)

        # columns push: sink entry first, then backward col->row entries
        r_s = col_cap - z
        adm_s = jnp.where((r_s > 0) & (pm - psink < 0), r_s, i32(0))   # [1, Mp]
        rc_b = pm - pr - wS
        adm_b = jnp.where((y > 0) & (rc_b < 0), y, i32(0))             # [C, Mp]
        excl_b = adm_s + (_cumsum(adm_b, 0, C) - adm_b)
        delta_s = jnp.clip(e_col, 0, adm_s)
        delta_b = jnp.clip(e_col - excl_b, 0, adm_b)

        # sink pushes back along backward sink->col arcs
        zb_adm = jnp.where((z > 0) & (psink - pm < 0), z, i32(0))      # [1, Mp]
        excl_zb = _cumsum(zb_adm, 1, Mp) - zb_adm
        delta_zb = jnp.clip(e_sink - excl_zb, 0, zb_adm)

        y2 = y + delta_f - delta_b
        z2 = z + delta_s - delta_zb

        # jump relabels for active nodes that pushed nothing
        pushed_row = jnp.sum(delta_f, axis=1, keepdims=True)
        best_row = jnp.max(jnp.where(r_fwd > 0, pm - wS, -_BIG), axis=1, keepdims=True)
        pr2 = jnp.where((e_row > 0) & (pushed_row == 0), best_row - eps, pr)

        pushed_col = delta_s + jnp.sum(delta_b, axis=0, keepdims=True)
        cand_col = jnp.maximum(
            jnp.max(jnp.where(y > 0, pr + wS, -_BIG), axis=0, keepdims=True),
            jnp.where(r_s > 0, psink, -_BIG),
        )
        pm2 = jnp.where((e_col > 0) & (pushed_col == 0), cand_col - eps, pm)

        pushed_sink = jnp.sum(delta_zb)
        cand_sink = jnp.max(jnp.where(z > 0, pm, -_BIG))
        psink2 = jnp.where(
            (e_sink > 0) & (pushed_sink == 0), cand_sink - eps, psink
        )
        return y2, z2, pr2, pm2, psink2

    def phase_cond(state):
        *_rest, steps, done = state
        return ~done & (steps < max_supersteps)

    def phase_body(state):
        y, z, pr, pm, psink, eps, steps, done = state
        e_row, e_col, e_sink = excesses(y, z)
        any_active = jnp.any(e_row > 0) | jnp.any(e_col > 0) | (e_sink > 0)

        def do_step(_):
            y2, z2, pr2, pm2, psink2 = superstep(y, z, pr, pm, psink, eps)
            return y2, z2, pr2, pm2, psink2, eps, steps + 1, jnp.bool_(False)

        def next_phase(_):
            finished = eps <= 1
            new_eps = jnp.maximum(i32(1), eps // alpha)
            if refine_waves:
                pr2, pm2, psink2 = price_refine(y, z, pr, pm, psink, new_eps)
                y2, z2 = saturate_eps(y, z, pr2, pm2, psink2, new_eps)
            else:
                pr2, pm2, psink2 = pr, pm, psink
                y2, z2 = saturate(y, z, pr, pm, psink)
            return (
                jnp.where(finished, y, y2),
                jnp.where(finished, z, z2),
                jnp.where(finished, pr, pr2),
                jnp.where(finished, pm, pm2),
                jnp.where(finished, psink, psink2),
                jnp.where(finished, eps, new_eps),
                steps,
                finished,
            )

        return lax.cond(any_active, do_step, next_phase, operand=None)

    y0 = jnp.zeros((C, Mp), i32)
    z0 = jnp.zeros((1, Mp), i32)
    state = (y0, z0, pr0, pm0, psink0, eps0, i32(0), jnp.bool_(False))
    y, z, pr, pm, psink, eps, steps, done = lax.while_loop(
        phase_cond, phase_body, state
    )
    e_row, e_col, e_sink = excesses(y, z)
    max_abs = jnp.maximum(
        jnp.max(jnp.abs(e_row)),
        jnp.maximum(jnp.max(jnp.abs(e_col)), jnp.abs(e_sink)),
    )
    y_ref[:] = y
    pm_ref[:] = pm
    steps_ref[0] = steps
    conv_ref[0] = (done & (max_abs == 0)).astype(i32)


def _transport_kernel_tiered(
    wLo_ref, wHi_ref, R_ref, supply_ref, colcap_ref, eps_ref,
    y_ref, pm_ref, steps_ref, conv_ref,
    *, C: int, Mp: int, alpha: int, max_supersteps: int,
    refine_waves: int = 0,
):
    """Tiered (continuation-priced) twin of _transport_kernel: per cell
    the first R units are the residents at wLo = w - discount, the rest
    pay wHi — a pair of parallel arcs, so cost-scaling push-relabel
    stays exact with residuals split by tier (the canonical convex-arc
    split yA = min(y, R), yB = y - yA; see solver/layered.py
    _transport_loop_tiered, which this kernel matches BIT-FOR-BIT
    superstep-for-superstep). The preemption-on round was the one
    iterative solve left on the ~20 us/superstep XLA phase-loop path;
    fusing it brings the full tiered re-solve onto the same
    VMEM-resident footing as the backlog solve."""
    i32 = jnp.int32
    wLo = wLo_ref[:]                     # [C, Mp]
    wHi = wHi_ref[:]                     # [C, Mp]
    supply = supply_ref[:]               # [C, 1]
    col_cap = colcap_ref[:]              # [1, Mp]
    eps0 = eps_ref[0]
    U = jnp.minimum(supply, col_cap)     # [C, Mp] fwd arc capacity
    R = jnp.minimum(R_ref[:], U)         # resident (cheap-tier) capacity

    def excesses(y, z):
        e_row = supply - jnp.sum(y, axis=1, keepdims=True)        # [C, 1]
        e_col = jnp.sum(y, axis=0, keepdims=True) - z             # [1, Mp]
        e_sink = jnp.sum(z) - jnp.sum(supply)                     # scalar
        return e_row, e_col, e_sink

    # cold tightening against the CHEAP tier (wLo <= wHi cellwise, so
    # the zero flow is 0-optimal) — transport_tighten(wLo, U, ...) with
    # pm0 = zeros
    live = col_cap > 0
    pm0 = jnp.where(live, i32(0), -_BIG_D)
    has_arc = U > 0
    pr0 = jnp.max(jnp.where(has_arc, pm0 - wLo, -_BIG_D), axis=1,
                  keepdims=True)
    pr0 = jnp.where(jnp.any(has_arc, axis=1, keepdims=True), pr0, i32(0))
    psink0 = jnp.min(jnp.where(live, pm0, _BIG_D)).reshape(1, 1)
    psink0 = jnp.where(jnp.any(live), psink0, i32(0))

    def saturate(y, z, pr, pm, psink):
        rcl = wLo + pr - pm
        rch = wHi + pr - pm
        yA = jnp.minimum(y, R)
        yB = y - yA
        yA2 = jnp.where(rcl < 0, R, jnp.where(rcl > 0, i32(0), yA))
        yB2 = jnp.where(rch < 0, U - R, jnp.where(rch > 0, i32(0), yB))
        rcs = pm - psink
        z2 = jnp.where(rcs < 0, col_cap, jnp.where(rcs > 0, i32(0), z))
        return yA2 + yB2, z2

    def saturate_eps(y, z, pr, pm, psink, eps):
        rcl = wLo + pr - pm
        rch = wHi + pr - pm
        yA = jnp.minimum(y, R)
        yB = y - yA
        yA2 = jnp.where(rcl < -eps, R, jnp.where(rcl > eps, i32(0), yA))
        yB2 = jnp.where(rch < -eps, U - R, jnp.where(rch > eps, i32(0), yB))
        rcs = pm - psink
        z2 = jnp.where(rcs < -eps, col_cap, jnp.where(rcs > eps, i32(0), z))
        return yA2 + yB2, z2

    def price_refine(y, z, pr, pm, psink, eps):
        """_price_refine_tiered: each tier's residuals contribute their
        own Bellman-Ford constraints. min-reductions and selects only."""
        def body(_, state):
            pr, pm, psink = state
            yA = jnp.minimum(y, R)
            yB = y - yA
            bound_m = jnp.minimum(
                jnp.min(jnp.where(R - yA > 0, wLo + pr + eps, _BIG),
                        axis=0, keepdims=True),
                jnp.min(jnp.where((U - R) - yB > 0, wHi + pr + eps, _BIG),
                        axis=0, keepdims=True),
            )
            pm2 = jnp.maximum(jnp.minimum(pm, bound_m), -_BIG_D)
            pm2 = jnp.minimum(pm2, jnp.where(z > 0, psink + eps, _BIG))
            bound_r = jnp.minimum(
                jnp.min(jnp.where(yA > 0, pm2 - wLo + eps, _BIG), axis=1,
                        keepdims=True),
                jnp.min(jnp.where(yB > 0, pm2 - wHi + eps, _BIG), axis=1,
                        keepdims=True),
            )
            pr2 = jnp.maximum(jnp.minimum(pr, bound_r), -_BIG_D)
            bound_s = jnp.min(
                jnp.where(col_cap - z > 0, pm2 + eps, _BIG)
            ).reshape(1, 1)
            psink2 = jnp.maximum(jnp.minimum(psink, bound_s), -_BIG_D)
            return pr2, pm2, psink2

        return lax.fori_loop(0, refine_waves, body, (pr, pm, psink))

    def superstep(y, z, pr, pm, psink, eps):
        e_row, e_col, e_sink = excesses(y, z)
        yA = jnp.minimum(y, R)
        yB = y - yA
        rcl = wLo + pr - pm
        rch = wHi + pr - pm

        # rows push forward: tier-A residual at wLo, tier-B at wHi
        rA = R - yA
        rB = (U - R) - yB
        r_adm = jnp.where((rA > 0) & (rcl < 0), rA, i32(0)) + jnp.where(
            (rB > 0) & (rch < 0), rB, i32(0)
        )
        excl = _cumsum(r_adm, 1, Mp) - r_adm
        delta_f = jnp.clip(e_row - excl, 0, r_adm)

        # columns push: sink entry first, then dear-tier returns, then
        # cheap — the same exclusive-prefix order as the XLA loop's
        # [sink; yB rows; yA rows] concatenation
        r_s = col_cap - z
        adm_s = jnp.where((r_s > 0) & (pm - psink < 0), r_s, i32(0))
        rcb_hi = pm - pr - wHi
        rcb_lo = pm - pr - wLo
        adm_bh = jnp.where((yB > 0) & (rcb_hi < 0), yB, i32(0))
        adm_bl = jnp.where((yA > 0) & (rcb_lo < 0), yA, i32(0))
        excl_bh = adm_s + (_cumsum(adm_bh, 0, C) - adm_bh)
        excl_bl = (
            adm_s
            + jnp.sum(adm_bh, axis=0, keepdims=True)
            + (_cumsum(adm_bl, 0, C) - adm_bl)
        )
        delta_s = jnp.clip(e_col, 0, adm_s)
        delta_bh = jnp.clip(e_col - excl_bh, 0, adm_bh)
        delta_bl = jnp.clip(e_col - excl_bl, 0, adm_bl)
        delta_b = delta_bh + delta_bl

        # sink pushes back (tier-less)
        zb_adm = jnp.where((z > 0) & (psink - pm < 0), z, i32(0))
        excl_zb = _cumsum(zb_adm, 1, Mp) - zb_adm
        delta_zb = jnp.clip(e_sink - excl_zb, 0, zb_adm)

        y2 = y + delta_f - delta_b
        z2 = z + delta_s - delta_zb

        # jump relabels (candidates consider both tiers' residuals)
        pushed_row = jnp.sum(delta_f, axis=1, keepdims=True)
        cand_row = jnp.maximum(
            jnp.max(jnp.where(rA > 0, pm - wLo, -_BIG), axis=1,
                    keepdims=True),
            jnp.max(jnp.where(rB > 0, pm - wHi, -_BIG), axis=1,
                    keepdims=True),
        )
        pr2 = jnp.where((e_row > 0) & (pushed_row == 0), cand_row - eps, pr)

        pushed_col = delta_s + jnp.sum(delta_b, axis=0, keepdims=True)
        cand_col = jnp.maximum(
            jnp.maximum(
                jnp.max(jnp.where(yA > 0, pr + wLo, -_BIG), axis=0,
                        keepdims=True),
                jnp.max(jnp.where(yB > 0, pr + wHi, -_BIG), axis=0,
                        keepdims=True),
            ),
            jnp.where(r_s > 0, psink, -_BIG),
        )
        pm2 = jnp.where((e_col > 0) & (pushed_col == 0), cand_col - eps, pm)

        pushed_sink = jnp.sum(delta_zb)
        cand_sink = jnp.max(jnp.where(z > 0, pm, -_BIG))
        psink2 = jnp.where(
            (e_sink > 0) & (pushed_sink == 0), cand_sink - eps, psink
        )
        return y2, z2, pr2, pm2, psink2

    def phase_cond(state):
        *_rest, steps, done = state
        return ~done & (steps < max_supersteps)

    def phase_body(state):
        y, z, pr, pm, psink, eps, steps, done = state
        e_row, e_col, e_sink = excesses(y, z)
        any_active = jnp.any(e_row > 0) | jnp.any(e_col > 0) | (e_sink > 0)

        def do_step(_):
            y2, z2, pr2, pm2, psink2 = superstep(y, z, pr, pm, psink, eps)
            return y2, z2, pr2, pm2, psink2, eps, steps + 1, jnp.bool_(False)

        def next_phase(_):
            finished = eps <= 1
            new_eps = jnp.maximum(i32(1), eps // alpha)
            if refine_waves:
                pr2, pm2, psink2 = price_refine(y, z, pr, pm, psink, new_eps)
                y2, z2 = saturate_eps(y, z, pr2, pm2, psink2, new_eps)
            else:
                pr2, pm2, psink2 = pr, pm, psink
                y2, z2 = saturate(y, z, pr, pm, psink)
            return (
                jnp.where(finished, y, y2),
                jnp.where(finished, z, z2),
                jnp.where(finished, pr, pr2),
                jnp.where(finished, pm, pm2),
                jnp.where(finished, psink, psink2),
                jnp.where(finished, eps, new_eps),
                steps,
                finished,
            )

        return lax.cond(any_active, do_step, next_phase, operand=None)

    y0 = jnp.zeros((C, Mp), i32)
    z0 = jnp.zeros((1, Mp), i32)
    state = (y0, z0, pr0, pm0, psink0, eps0, i32(0), jnp.bool_(False))
    y, z, pr, pm, psink, eps, steps, done = lax.while_loop(
        phase_cond, phase_body, state
    )
    e_row, e_col, e_sink = excesses(y, z)
    max_abs = jnp.maximum(
        jnp.max(jnp.abs(e_row)),
        jnp.maximum(jnp.max(jnp.abs(e_col)), jnp.abs(e_sink)),
    )
    y_ref[:] = y
    pm_ref[:] = pm
    steps_ref[0] = steps
    conv_ref[0] = (done & (max_abs == 0)).astype(i32)


@functools.partial(
    jax.jit,  # kschedlint: disable=unregistered-program -- transport research kernel, bit-parity gated by tests/test_pallas_transport.py, not a dispatch rung
    static_argnames=("alpha", "max_supersteps", "interpret", "refine_waves"),
)
def transport_loop_pallas_tiered(
    wLo, wHi, R, supply, col_cap, eps_init,
    alpha: int = 8,
    max_supersteps: int = 20_000,
    interpret: bool = False,
    refine_waves: int = 0,
):
    """Drop-in twin of solver/layered.py `_transport_loop_tiered`'s
    public result (y, pm, steps, converged), one fused kernel per
    solve. wLo/wHi: int32[C, Mp] scaled tier costs; R: int32[C, Mp]
    resident capacities; supply: int32[C]; col_cap: int32[Mp]."""
    C, Mp = wLo.shape
    y, pm, steps, conv = pl.pallas_call(  # kschedlint: disable=unregistered-program -- transport research kernel, bit-parity gated by tests/test_pallas_transport.py
        functools.partial(
            _transport_kernel_tiered,
            C=C, Mp=Mp, alpha=alpha, max_supersteps=max_supersteps,
            refine_waves=refine_waves,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((C, Mp), jnp.int32),
            jax.ShapeDtypeStruct((1, Mp), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        interpret=interpret,
    )(
        wLo.astype(jnp.int32),
        wHi.astype(jnp.int32),
        R.astype(jnp.int32),
        supply.astype(jnp.int32).reshape(C, 1),
        col_cap.astype(jnp.int32).reshape(1, Mp),
        eps_init.astype(jnp.int32).reshape(1),
    )
    return y, pm.reshape(Mp), steps[0], conv[0] != 0


@functools.partial(
    jax.jit,  # kschedlint: disable=unregistered-program -- transport research kernel, bit-parity gated by tests/test_pallas_transport.py, not a dispatch rung
    static_argnames=("alpha", "max_supersteps", "interpret", "refine_waves"),
)
def transport_loop_pallas(
    wS, supply, col_cap, eps_init, pm0=None,
    alpha: int = 8,
    max_supersteps: int = 20_000,
    interpret: bool = False,
    refine_waves: int = 0,
):
    """Drop-in twin of solver/layered.py `_transport_loop`'s public
    result (y, pm, steps, converged), one fused kernel per solve.

    wS: int32[C, Mp] scaled costs; supply: int32[C]; col_cap: int32[Mp];
    eps_init: int32 scalar; pm0: optional int32[Mp] carried machine
    prices (warm start — any value valid, zeros = cold). `interpret=True`
    runs the kernel under the Pallas interpreter (for CPU-only test
    environments)."""
    C, Mp = wS.shape
    if pm0 is None:
        pm0 = jnp.zeros((Mp,), jnp.int32)
    y, pm, steps, conv = pl.pallas_call(  # kschedlint: disable=unregistered-program -- transport research kernel, bit-parity gated by tests/test_pallas_transport.py
        functools.partial(
            _transport_kernel,
            C=C, Mp=Mp, alpha=alpha, max_supersteps=max_supersteps,
            refine_waves=refine_waves,
        ),
        out_shape=[
            jax.ShapeDtypeStruct((C, Mp), jnp.int32),
            jax.ShapeDtypeStruct((1, Mp), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        interpret=interpret,
    )(
        wS.astype(jnp.int32),
        supply.astype(jnp.int32).reshape(C, 1),
        col_cap.astype(jnp.int32).reshape(1, Mp),
        eps_init.astype(jnp.int32).reshape(1),
        pm0.astype(jnp.int32).reshape(1, Mp),
    )
    return y, pm.reshape(Mp), steps[0], conv[0] != 0
