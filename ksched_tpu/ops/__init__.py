"""Pallas TPU kernels for the solver hot ops, plus the dispatch switch.

`set_pallas_mode` controls whether the layered-transport solve runs as
the fused Pallas kernel (ops/transport_pallas.py) or the multi-op XLA
path (solver/layered.py):

- "auto" (default): Pallas on TPU backends, XLA elsewhere;
- "on": always Pallas (compiled);
- "interpret": always Pallas under the interpreter (CPU test envs);
- "off": always the XLA path.

The mode is read at TRACE time: it must be set before the consuming
program is built (before constructing a DeviceBulkCluster, and before a
solver's first solve). Already-compiled programs keep the dispatch they
were traced with — rebuild the cluster/solver after switching modes.

`jax.experimental.pallas.tpu` is imported lazily, only when a Pallas
branch is actually taken, so XLA-only deployments never depend on the
Pallas TPU lowerings being importable.
"""

from __future__ import annotations

from typing import Tuple

_PALLAS_MODE = "auto"
_VALID_MODES = ("auto", "on", "interpret", "off")


def set_pallas_mode(mode: str) -> None:
    if mode not in _VALID_MODES:
        raise ValueError(f"pallas mode must be one of {_VALID_MODES}, got {mode!r}")
    global _PALLAS_MODE
    _PALLAS_MODE = mode


def get_pallas_mode() -> str:
    return _PALLAS_MODE


def resolve_pallas() -> Tuple[bool, bool]:
    """(use_pallas, interpret) for the ambient backend, at trace time."""
    mode = _PALLAS_MODE
    if mode == "on":
        return True, False
    if mode == "interpret":
        return True, True
    if mode == "off":
        return False, False
    import jax

    return jax.default_backend() == "tpu", False


#: the fused kernel holds the whole solve state in VMEM; XLA's scoped
#: vmem limit for custom calls is 16 MiB (measured: a [512, 1024] i32
#: instance wants 21.33M against a 16.00M limit), and the kernel's live
#: set is ~10 [C, Mp] i32 tiles across a superstep (wS/U/y +
#: push/relabel temps). Beyond the budget the XLA phase loop
#: (HBM-resident state, fused per superstep) is the correct dispatch —
#: for many-row instances (hundreds of groups) its per-superstep HBM
#: traffic amortizes fine, and the kernel's VMEM-residency win matters
#: most exactly where instances are small.
_PALLAS_VMEM_BUDGET_BYTES = 15 << 20
_PALLAS_LIVE_TILES = 10


def transport_solve(
    wS, supply, col_cap, eps_init, pm0=None, *,
    alpha: int = 8, max_supersteps: int = 20_000, refine_waves: int = 0,
):
    """The layered-transport solve behind the mode switch: the fused
    Pallas kernel or the XLA phase loop, one call site for both.
    pm0 optionally warm-starts machine prices (carried across rounds).
    refine_waves > 0 enables price refinement between eps phases (see
    solver/layered.py _price_refine) in both implementations.
    Returns (y, pm, steps, converged); traceable inside jit/scan."""
    use_pallas, interpret = resolve_pallas()
    if use_pallas and not interpret:
        C, Mp = wS.shape
        if _PALLAS_LIVE_TILES * C * Mp * 4 > _PALLAS_VMEM_BUDGET_BYTES:
            use_pallas = False  # state would not fit VMEM-resident
    if use_pallas:
        from .transport_pallas import transport_loop_pallas

        return transport_loop_pallas(
            wS, supply, col_cap, eps_init, pm0,
            alpha=alpha, max_supersteps=max_supersteps, interpret=interpret,
            refine_waves=refine_waves,
        )
    from ..solver.layered import _solve_transport

    return _solve_transport(
        wS, supply, col_cap, eps_init, pm0,
        alpha=alpha, max_supersteps=max_supersteps,
        refine_waves=refine_waves,
    )


#: the tiered kernel's live set is larger (two cost tiers + resident
#: caps + per-tier splits) — budget conservatively
_PALLAS_TIERED_LIVE_TILES = 16


def transport_solve_tiered(
    wLo, wHi, R, supply, col_cap, eps_init, *,
    alpha: int = 8, max_supersteps: int = 20_000, refine_waves: int = 0,
):
    """The tiered (continuation-priced) solve behind the mode switch:
    the fused tiered Pallas kernel or the XLA phase loop — the
    preemption-on twin of transport_solve. Bit-identical results both
    ways. Returns (y, pm, steps, converged); traceable inside
    jit/scan."""
    use_pallas, interpret = resolve_pallas()
    if use_pallas and not interpret:
        C, Mp = wLo.shape
        if _PALLAS_TIERED_LIVE_TILES * C * Mp * 4 > _PALLAS_VMEM_BUDGET_BYTES:
            use_pallas = False
    if use_pallas:
        from .transport_pallas import transport_loop_pallas_tiered

        return transport_loop_pallas_tiered(
            wLo, wHi, R, supply, col_cap, eps_init,
            alpha=alpha, max_supersteps=max_supersteps, interpret=interpret,
            refine_waves=refine_waves,
        )
    from ..solver.layered import _solve_transport_tiered

    return _solve_transport_tiered(
        wLo, wHi, R, supply, col_cap, eps_init,
        alpha=alpha, max_supersteps=max_supersteps,
        refine_waves=refine_waves,
    )


def __getattr__(name):
    if name == "transport_loop_pallas":
        from .transport_pallas import transport_loop_pallas

        return transport_loop_pallas
    if name == "transport_loop_pallas_tiered":
        from .transport_pallas import transport_loop_pallas_tiered

        return transport_loop_pallas_tiered
    if name == "mcmf_loop_pallas":
        # the general-graph MCMF megakernel (mcmf_pallas.py): the whole
        # CSR push-relabel loop in one kernel, tables VMEM-resident
        from .mcmf_pallas import mcmf_loop_pallas

        return mcmf_loop_pallas
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


# transport_loop_pallas is intentionally NOT in __all__: a star import
# would trigger the lazy Pallas TPU import that XLA-only deployments
# must never take. Access it explicitly (module __getattr__).
__all__ = [
    "transport_solve",
    "transport_solve_tiered",
    "set_pallas_mode",
    "get_pallas_mode",
    "resolve_pallas",
]
