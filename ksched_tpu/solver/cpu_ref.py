"""Exact CPU reference MCMF solver (the parity oracle).

Successive shortest paths with Johnson potentials and Dijkstra over the
residual graph. This fills the gap the reference left open — it has no
in-process mock solver, its integration test needs the real Flowlessly
binary on disk (SURVEY §4). Pure Python; intended for tests and small
graphs, not the hot path.

Algorithm: standard SSP. All supplies route to demands; optimality by
nonnegative reduced costs maintained via potentials. Negative arc costs
are handled by a Bellman-Ford potential bootstrap.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Tuple

import numpy as np

from ..graph.device_export import FlowProblem
from .base import FlowResult, FlowSolver, check_finite_costs, lower_bound_cost

_INF = float("inf")


class ReferenceSolver(FlowSolver):
    def solve(self, problem: FlowProblem) -> FlowResult:
        n = problem.num_nodes
        m = len(problem.src)
        src = problem.src
        dst = problem.dst
        check_finite_costs(problem)
        cap = problem.cap.astype(np.int64)
        cost = problem.cost.astype(np.int64)
        excess = problem.excess.astype(np.int64).copy()

        # Residual adjacency: per node, list of (arc_index, direction).
        # direction +1 = forward residual (cap - flow), -1 = backward (flow).
        adj: List[List[Tuple[int, int]]] = [[] for _ in range(n)]
        live = np.nonzero(cap > 0)[0]
        for i in live:
            adj[src[i]].append((int(i), +1))
            adj[dst[i]].append((int(i), -1))

        flow = np.zeros(m, dtype=np.int64)
        potential = [0] * n

        if (cost[live] < 0).any() if len(live) else False:
            self._bellman_ford_potentials(n, live, src, dst, cost, potential)

        supplies = [v for v in range(n) if excess[v] > 0]
        total_pushed = 0
        iterations = 0
        while supplies:
            s_set = [v for v in supplies if excess[v] > 0]
            if not s_set:
                break
            dist, parent_arc, parent_dir, reached_demand = self._dijkstra(
                n, adj, src, dst, cap, cost, flow, potential, s_set, excess
            )
            if reached_demand is None:
                raise RuntimeError(
                    "infeasible flow problem: supply cannot reach any demand "
                    "(the unscheduled-aggregator escape arcs should prevent this)"
                )
            # Update potentials for all reached nodes.
            d_t = dist[reached_demand]
            for v in range(n):
                if dist[v] < _INF:
                    potential[v] += min(dist[v], d_t)
                else:
                    potential[v] += d_t
            # Trace path back, find bottleneck.
            path: List[Tuple[int, int]] = []
            v = reached_demand
            while parent_arc[v] != -1:
                i, d = parent_arc[v], parent_dir[v]
                path.append((i, d))
                v = src[i] if d == +1 else dst[i]
            source = v
            bottleneck = min(excess[source], -excess[reached_demand])
            for i, d in path:
                residual = cap[i] - flow[i] if d == +1 else flow[i]
                bottleneck = min(bottleneck, residual)
            assert bottleneck > 0
            for i, d in path:
                flow[i] += bottleneck * d
            excess[source] -= bottleneck
            excess[reached_demand] += bottleneck
            total_pushed += bottleneck
            iterations += 1
            supplies = [v for v in supplies if excess[v] > 0]

        objective = int((flow * cost).sum()) + lower_bound_cost(problem)
        return FlowResult(flow=flow, objective=objective, iterations=iterations)

    @staticmethod
    def _dijkstra(n, adj, src, dst, cap, cost, flow, potential, sources, excess):
        dist = [_INF] * n
        parent_arc = [-1] * n
        parent_dir = [0] * n
        pq: List[Tuple[float, int]] = []
        for s in sources:
            dist[s] = 0.0
            heapq.heappush(pq, (0.0, s))
        best_demand = None
        while pq:
            d, v = heapq.heappop(pq)
            if d > dist[v]:
                continue
            if excess[v] < 0:
                best_demand = v
                break
            for i, direction in adj[v]:
                if direction == +1:
                    residual = cap[i] - flow[i]
                    w = dst[i]
                    rc = cost[i] + potential[v] - potential[w]
                else:
                    residual = flow[i]
                    w = src[i]
                    rc = -cost[i] + potential[v] - potential[w]
                if residual <= 0:
                    continue
                nd = d + rc
                if nd < dist[w] - 1e-9:
                    dist[w] = nd
                    parent_arc[w] = i
                    parent_dir[w] = direction
                    heapq.heappush(pq, (nd, w))
        return dist, parent_arc, parent_dir, best_demand

    @staticmethod
    def _bellman_ford_potentials(n, live, src, dst, cost, potential):
        for _ in range(n):
            changed = False
            for i in live:
                u, v = src[i], dst[i]
                if potential[u] + cost[i] < potential[v]:
                    potential[v] = potential[u] + cost[i]
                    changed = True
            if not changed:
                return
        raise RuntimeError("negative cost cycle in flow network")
