"""Bucketed-ELL push-relabel: the CSR fallback without the global scans.

Same algorithm as solver/jax_solver.py (synchronous Goldberg–Tarjan
cost-scaling push-relabel with maximal pushes, price tightening and an
eps=1 warm attempt) — different data layout. The CSR formulation pays
for generality with GLOBAL segmented reductions: every superstep runs
~4 full-length cumsums plus a `lax.associative_scan` segmented max,
each O(log n) passes over the 2M sorted residual entries — measured
gather/scan-bound at ~60 ms/solve for the 10k x 1k graph on TPU v5e
and JAX-CPU alike (docs/NOTES.md, tools/csr_tpu_bench.py). VERDICT r4
weak #6 asked for one real lever on that number.

The lever is the degree distribution: scheduling flow graphs are
near-bipartite with a handful of aggregator hubs. The 10k x 1k graph
measures deg p99.9 = 5 with exactly 13 nodes over degree 8 (job
aggregators and the sink, up to deg 28755). So bucket:

- SMALL nodes (deg <= w_small, 99.96% of nodes) pack into one dense
  [Ns, w_small] entry block — per-node reductions are per-ROW
  reductions (one pass, no scan), the maximal-push prefix is a
  w_small-wide row cumsum;
- HUB nodes row-split into a [Rh, w_hub] block (standard CSR row
  splitting); per-hub combines run over a tiny [Hn, Kmax] row-index
  matrix (13 x ~57 here) — noise;
- per-node values assemble by GATHER from the block partials
  (node_kind/node_slot), never by scatter (TPU serializes scatters).

Everything the superstep touches is a dense elementwise op, a short
row reduction, or a flat gather; the log-pass global scans are gone.
The entry blocks are ~2.4x the CSR entry count (padding), but every
op over them is single-pass.

Semantics match the CSR solver: any maximal-push allocation is a valid
discharge, so flows/objectives agree with the oracle exactly even
though per-node allocation ORDER (hence superstep counts) may differ.

Reference parity note: this is still the Flowlessly replacement seam
(scheduling/flow/placement/solver.go:60-123) — same FlowProblem in,
same FlowResult out, warm-started across rounds.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..graph.device_export import FlowProblem
from .base import FlowResult, FlowSolver, check_finite_costs, lower_bound_cost

_BIG = jnp.int32(1 << 30)
_BIG_D = 1 << 28
_P_GUARD = 1 << 30


@dataclass
class EllPlan:
    """Host-prebuilt bucketed-ELL layout of the doubled residual entries."""

    # small block [Ns, Ws]: one row per small node
    s_node: np.ndarray  # int32[Ns]
    s_arc: np.ndarray  # int32[Ns, Ws] (0 on pad)
    s_sign: np.ndarray  # int32[Ns, Ws] +1/-1, 0 on pad
    s_peer: np.ndarray  # int32[Ns, Ws] (self on pad)
    # hub block [Rh, Wh]: hub nodes row-split in entry order
    h_node: np.ndarray  # int32[Rh]
    h_arc: np.ndarray  # int32[Rh, Wh]
    h_sign: np.ndarray  # int32[Rh, Wh]
    h_peer: np.ndarray  # int32[Rh, Wh]
    h_rowhub: np.ndarray  # int32[Rh] hub slot of each row
    h_rowk: np.ndarray  # int32[Rh] row's position within its hub
    # per-hub combine [Hn, K]
    hub_rows: np.ndarray  # int32[Hn, K] row indices (clamped on pad)
    hub_rows_valid: np.ndarray  # bool[Hn, K]
    hub_node: np.ndarray  # int32[Hn]
    # per-node assembly
    node_kind: np.ndarray  # int32[N] 0=empty 1=small 2=hub
    node_slot: np.ndarray  # int32[N] small-row index or hub slot
    # flow update: entry position of each arc's fwd/bwd entry in the
    # CONCATENATED flat delta array [Ns*Ws + Rh*Wh]
    fwd_flat: np.ndarray  # int32[M]
    bwd_flat: np.ndarray  # int32[M]
    src: np.ndarray  # int32[M] endpoints the plan was built for
    dst: np.ndarray  # int32[M]


def build_ell_plan(
    src: np.ndarray, dst: np.ndarray, num_nodes: int,
    w_small: int = 8, w_hub: int = 512,
) -> EllPlan:
    n = num_nodes
    m = len(src)
    node = np.concatenate([src, dst]).astype(np.int64)  # kschedlint: host-only (numpy plan build)
    peer = np.concatenate([dst, src]).astype(np.int32)
    arc = np.concatenate([np.arange(m), np.arange(m)]).astype(np.int32)
    sign = np.concatenate(
        [np.ones(m, np.int32), -np.ones(m, np.int32)]
    )
    deg = np.bincount(node, minlength=n)
    # in-node rank of every doubled entry, via stable node sort
    order = np.argsort(node, kind="stable")
    row_ptr = np.zeros(n + 1, np.int64)  # kschedlint: host-only (numpy plan build)
    row_ptr[1:] = np.cumsum(deg)
    rank = np.empty(2 * m, np.int64)  # kschedlint: host-only (numpy plan build)
    rank[order] = np.arange(2 * m) - row_ptr[node[order]]

    is_hub_node = deg > w_small
    small_ids = np.nonzero((deg > 0) & ~is_hub_node)[0]
    hub_ids = np.nonzero(is_hub_node)[0]
    ns = max(len(small_ids), 1)
    hn = max(len(hub_ids), 1)
    small_slot = np.full(n, 0, np.int64)  # kschedlint: host-only (numpy plan build)
    small_slot[small_ids] = np.arange(len(small_ids))
    hub_slot = np.full(n, 0, np.int64)  # kschedlint: host-only (numpy plan build)
    hub_slot[hub_ids] = np.arange(len(hub_ids))

    # hub row allocation: ceil(deg/w_hub) consecutive rows per hub
    hub_deg = deg[hub_ids] if len(hub_ids) else np.zeros(0, np.int64)  # kschedlint: host-only (numpy plan build)
    rows_per_hub = (hub_deg + w_hub - 1) // w_hub
    hub_row_start = np.zeros(len(hub_ids) + 1, np.int64)  # kschedlint: host-only (numpy plan build)
    hub_row_start[1:] = np.cumsum(rows_per_hub)
    rh = max(int(hub_row_start[-1]), 1)
    kmax = max(int(rows_per_hub.max()) if len(rows_per_hub) else 0, 1)

    s_node = np.zeros(ns, np.int32)
    s_node[: len(small_ids)] = small_ids
    s_arc = np.zeros((ns, w_small), np.int32)
    s_sign = np.zeros((ns, w_small), np.int32)
    s_peer = np.tile(s_node[:, None], (1, w_small)).astype(np.int32)
    h_node = np.zeros(rh, np.int32)
    h_rowhub = np.zeros(rh, np.int32)
    h_rowk = np.zeros(rh, np.int32)
    for i, hub in enumerate(hub_ids):
        r0, r1 = hub_row_start[i], hub_row_start[i + 1]
        h_node[r0:r1] = hub
        h_rowhub[r0:r1] = i
        h_rowk[r0:r1] = np.arange(r1 - r0)
    h_arc = np.zeros((rh, w_hub), np.int32)
    h_sign = np.zeros((rh, w_hub), np.int32)
    h_peer = np.tile(h_node[:, None], (1, w_hub)).astype(np.int32)

    # scatter entries into their block cells (host numpy, build-time only)
    e_small = ~is_hub_node[node]
    srow = small_slot[node[e_small]]
    scol = rank[e_small]
    s_arc[srow, scol] = arc[e_small]
    s_sign[srow, scol] = sign[e_small]
    s_peer[srow, scol] = peer[e_small]
    e_hub = ~e_small
    hrow = hub_row_start[hub_slot[node[e_hub]]] + rank[e_hub] // w_hub
    hcol = rank[e_hub] % w_hub
    h_arc[hrow, hcol] = arc[e_hub]
    h_sign[hrow, hcol] = sign[e_hub]
    h_peer[hrow, hcol] = peer[e_hub]

    # flat position of every doubled entry in concat([small, hub]) order
    flat = np.empty(2 * m, np.int64)  # kschedlint: host-only (numpy plan build)
    flat[e_small] = srow * w_small + scol
    flat[e_hub] = ns * w_small + hrow * w_hub + hcol

    hub_rows = np.zeros((hn, kmax), np.int32)
    hub_rows_valid = np.zeros((hn, kmax), bool)
    for i in range(len(hub_ids)):
        k = int(rows_per_hub[i])
        hub_rows[i, :k] = np.arange(hub_row_start[i], hub_row_start[i + 1])
        hub_rows_valid[i, :k] = True
    hub_node = np.zeros(hn, np.int32)
    hub_node[: len(hub_ids)] = hub_ids

    node_kind = np.where(
        deg == 0, 0, np.where(is_hub_node, 2, 1)
    ).astype(np.int32)
    node_slot = np.where(is_hub_node, hub_slot, small_slot).astype(np.int32)

    return EllPlan(
        s_node=s_node, s_arc=s_arc, s_sign=s_sign, s_peer=s_peer,
        h_node=h_node, h_arc=h_arc, h_sign=h_sign, h_peer=h_peer,
        h_rowhub=h_rowhub, h_rowk=h_rowk,
        hub_rows=hub_rows, hub_rows_valid=hub_rows_valid,
        hub_node=hub_node,
        node_kind=node_kind, node_slot=node_slot,
        fwd_flat=flat[:m].astype(np.int32),
        bwd_flat=flat[m:].astype(np.int32),
        src=src.copy(), dst=dst.copy(),
    )



def _g2(table, idx2):
    """2D-indexed gather. Measured equivalent to a flat gather of the
    same element count on TPU (~2.0 ms per 262k int32 elements, i.e.
    ~7.6 ns/element — tools/tpu_primitives_bench.py with REAL carried
    dependencies; an earlier flat+optimization_barrier+reshape variant
    that appeared 13x faster was a dead-code artifact). Kept as a
    helper so the gather cost model has one grep-able seam."""
    return table[idx2]

@functools.partial(
    jax.jit, static_argnames=("alpha", "max_supersteps", "tighten_sweeps", "telemetry_cap")  # kschedlint: program=ell_solve
)
def _solve_mcmf_ell(
    cap, cost, supply, flow0, eps_init,
    s_node, s_arc, s_sign, s_peer,
    h_node, h_arc, h_sign, h_peer, h_rowhub, h_rowk,
    hub_rows, hub_rows_valid, hub_node, node_kind, node_slot,
    fwd_flat, bwd_flat, a_src, a_dst,
    alpha: int = 8,
    max_supersteps: int = 50_000,
    tighten_sweeps: int = 32,
    telemetry_cap: int = 0,
):
    """telemetry_cap > 0 appends the superstep-indexed telemetry ring
    (obs/soltel.py layout) to the returned tuple — same contract as
    solver/jax_solver.py `_solve_mcmf`; cap=0 traces the exact
    pre-telemetry jaxpr."""
    from ..obs.soltel import SOLTEL_WIDTH

    i32 = jnp.int32
    kmax = hub_rows.shape[1]

    # entry-block constants (costs/caps don't change during a solve)
    sc_s = s_sign * _g2(cost, s_arc)  # signed cost per small entry
    sc_h = h_sign * _g2(cost, h_arc)
    cap_s = _g2(cap, s_arc)
    cap_h = _g2(cap, h_arc)

    def per_node(part_s, part_h_row, combine, identity):
        """Assemble a per-node [N] value from block partials by gather.
        `combine` reduces a hub's row partials (axis=1)."""
        hub_part = combine(
            jnp.where(
                hub_rows_valid, part_h_row[hub_rows], identity
            ),
            axis=1,
        )
        v = jnp.where(
            node_kind == 2, hub_part[node_slot], part_s[node_slot]
        )
        return jnp.where(node_kind == 0, identity, v)

    def residuals(flow):
        f_s = _g2(flow, s_arc)
        f_h = _g2(flow, h_arc)
        r_s = jnp.where(
            s_sign > 0, cap_s - f_s,
            jnp.where(s_sign < 0, f_s, i32(0)),
        )
        r_h = jnp.where(
            h_sign > 0, cap_h - f_h,
            jnp.where(h_sign < 0, f_h, i32(0)),
        )
        return r_s, r_h

    def excess_of(flow):
        out_s = jnp.sum(s_sign * _g2(flow, s_arc), axis=1)
        out_h = jnp.sum(h_sign * _g2(flow, h_arc), axis=1)
        return supply - per_node(out_s, out_h, jnp.sum, i32(0))

    def saturate(flow, p):
        rc_fwd = cost + p[a_src] - p[a_dst]
        return jnp.where(rc_fwd < 0, cap, jnp.where(rc_fwd > 0, i32(0), flow))

    def tighten(flow):
        excess0 = excess_of(flow)
        r_s, r_h = residuals(flow)
        d0 = jnp.where(excess0 < 0, i32(0), i32(_BIG_D))

        def t_cond(state):
            _d, changed, it = state
            return changed & (it < tighten_sweeps)

        def t_body(state):
            d, _, it = state
            cand_s = jnp.where(r_s > 0, sc_s + _g2(d, s_peer), i32(_BIG_D))
            cand_h = jnp.where(r_h > 0, sc_h + _g2(d, h_peer), i32(_BIG_D))
            best = per_node(
                jnp.min(cand_s, axis=1), jnp.min(cand_h, axis=1),
                jnp.min, i32(_BIG_D),
            )
            d2 = jnp.maximum(jnp.minimum(d, best), -i32(_BIG_D))
            return d2, jnp.any(d2 != d), it + 1

        d, _, _ = lax.while_loop(t_cond, t_body, (d0, jnp.bool_(True), i32(0)))
        return -jnp.minimum(d, i32(_BIG_D))

    def superstep(flow, p, eps, excess):
        r_s, r_h = residuals(flow)
        pp_s = _g2(p, s_peer)
        pp_h = _g2(p, h_peer)
        rc_s = sc_s + p[s_node][:, None] - pp_s
        rc_h = sc_h + p[h_node][:, None] - pp_h
        e_s = excess[s_node]
        e_h = excess[h_node]
        adm_s = (r_s > 0) & (rc_s < 0) & (e_s[:, None] > 0)
        adm_h = (r_h > 0) & (rc_h < 0) & (e_h[:, None] > 0)
        ra_s = jnp.where(adm_s, r_s, i32(0))
        ra_h = jnp.where(adm_h, r_h, i32(0))

        # maximal push: allocate each node's excess across admissible
        # entries in block order via exclusive prefix sums — per-row
        # cumsum for smalls; hubs add a cross-row offset (per-hub
        # exclusive cumsum of row totals over the tiny [Hn, K] matrix)
        pre_s = jnp.cumsum(ra_s, axis=1) - ra_s
        row_tot = jnp.sum(ra_h, axis=1)
        hub_row_tot = jnp.where(hub_rows_valid, row_tot[hub_rows], i32(0))
        hub_excl = jnp.cumsum(hub_row_tot, axis=1) - hub_row_tot
        row_off = hub_excl.reshape(-1)[h_rowhub * kmax + h_rowk]
        pre_h = (jnp.cumsum(ra_h, axis=1) - ra_h) + row_off[:, None]

        d_s = jnp.clip(e_s[:, None] - pre_s, 0, ra_s)
        d_h = jnp.clip(e_h[:, None] - pre_h, 0, ra_h)

        delta_flat = jnp.concatenate([d_s.reshape(-1), d_h.reshape(-1)])
        new_flow = flow + delta_flat[fwd_flat] - delta_flat[bwd_flat]

        pushed = per_node(
            jnp.sum(d_s, axis=1), jnp.sum(d_h, axis=1), jnp.sum, i32(0)
        )
        sum_r = per_node(
            jnp.sum(r_s, axis=1), jnp.sum(r_h, axis=1), jnp.sum, i32(0)
        )
        cand_s = jnp.where(r_s > 0, pp_s - sc_s, -_BIG)
        cand_h = jnp.where(r_h > 0, pp_h - sc_h, -_BIG)
        best = per_node(
            jnp.max(cand_s, axis=1), jnp.max(cand_h, axis=1),
            jnp.max, -_BIG,
        )
        relabel = (excess > 0) & (pushed == 0) & (sum_r > 0)
        new_p = jnp.where(relabel, best - eps, p)
        if not telemetry_cap:
            return new_flow, new_p, ()
        aux = (
            jnp.sum(pushed),
            jnp.sum(relabel.astype(i32)),
            # flow == cap <=> forward residual 0 (zero-cap arcs count:
            # their residual is zero) — matches the CSR/mega counters
            jnp.sum((flow >= cap).astype(i32)),
            jnp.sum(adm_s.astype(i32)) + jnp.sum(adm_h.astype(i32)),
        )
        return new_flow, new_p, aux

    if telemetry_cap:
        from ..obs import soltel as _soltel

        _tel_rows_iota = _soltel.device_rows_iota(telemetry_cap)

    def tel_row(eps, excess, aux):
        return _soltel.device_row(
            eps,
            jnp.sum((excess > 0).astype(i32)),
            jnp.sum(jnp.maximum(excess, 0)),
            *aux,
        )

    def tel_write(tel, steps, row):
        return _soltel.device_ring_write(
            tel, steps, row, telemetry_cap, _tel_rows_iota
        )

    def phase_cond(state):
        steps, done = state[3], state[4]
        return ~done & (steps < max_supersteps)

    def phase_body(state):
        if telemetry_cap:
            flow, p, eps, steps, done, tel = state
        else:
            flow, p, eps, steps, done = state
        excess = excess_of(flow)
        any_active = jnp.any(excess > 0)

        def do_superstep(_):
            f2, p2, aux = superstep(flow, p, eps, excess)
            if not telemetry_cap:
                return f2, p2, eps, steps + 1, jnp.bool_(False)
            tel2 = tel_write(tel, steps, tel_row(eps, excess, aux))
            return f2, p2, eps, steps + 1, jnp.bool_(False), tel2

        def next_phase(_):
            finished = eps <= 1
            new_eps = jnp.maximum(i32(1), eps // alpha)
            f2 = jnp.where(finished, flow, saturate(flow, p))
            out = (f2, p, jnp.where(finished, eps, new_eps), steps, finished)
            return out + ((tel,) if telemetry_cap else ())

        return lax.cond(any_active, do_superstep, next_phase, operand=None)

    p0 = tighten(flow0)
    flow1 = saturate(flow0, p0)
    state = (flow1, p0, eps_init, i32(0), jnp.bool_(False))
    if telemetry_cap:
        state = state + (jnp.zeros((telemetry_cap, SOLTEL_WIDTH), i32),)
        flow, p, eps, steps, done, tel = lax.while_loop(
            phase_cond, phase_body, state
        )
    else:
        flow, p, eps, steps, done = lax.while_loop(phase_cond, phase_body, state)
    converged = done & (jnp.max(jnp.abs(excess_of(flow))) == 0)
    p_overflow = jnp.max(jnp.abs(p)) >= _P_GUARD
    if telemetry_cap:
        return flow, p, steps, converged, p_overflow, tel
    return flow, p, steps, converged, p_overflow


def _plan_args(plan: EllPlan) -> tuple:
    return tuple(
        jnp.asarray(x)
        for x in (
            plan.s_node, plan.s_arc, plan.s_sign, plan.s_peer,
            plan.h_node, plan.h_arc, plan.h_sign, plan.h_peer,
            plan.h_rowhub, plan.h_rowk,
            plan.hub_rows, plan.hub_rows_valid, plan.hub_node,
            plan.node_kind, plan.node_slot,
            plan.fwd_flat, plan.bwd_flat,
            plan.src.astype(np.int32), plan.dst.astype(np.int32),
        )
    )


class EllSolver(FlowSolver):
    """Bucketed-ELL cost-scaling push-relabel, warm-started across
    rounds — drop-in for JaxSolver with the scan-free layout."""

    def __init__(
        self, alpha: int = 8, max_supersteps: int = 50_000,
        warm_start: bool = True, w_small: int = 8, w_hub: int = 512,
        telemetry: Optional[int] = None,
    ):
        from .layered import validate_alpha

        self.alpha = validate_alpha(alpha)
        self.max_supersteps = max_supersteps
        self.warm_start = warm_start
        self.w_small = w_small
        self.w_hub = w_hub
        self.telemetry = telemetry
        self._prev: Optional[np.ndarray] = None
        self._prev_dev = None  # warm flow as a device array (no re-upload)
        # endpoints at the LAST SUCCESSFUL SOLVE (see jax_solver: the
        # warm mask must not use a failed round's refresh endpoints)
        self._prev_src_dev = None
        self._prev_dst_dev = None
        self._plan: Optional[EllPlan] = None
        self._plan_dev: Optional[tuple] = None
        #: endpoint-generation key of the cached plan (FlowProblem.
        #: plan_key): equal keys skip the O(M) endpoint scans entirely
        self._plan_key = None
        self.last_supersteps = 0
        self.last_telemetry = None

    def reset(self) -> None:
        self._prev = None
        self._prev_dev = None
        self._prev_src_dev = None
        self._prev_dst_dev = None

    def _plan_for(self, src, dst, n, plan_key=None) -> tuple:
        plan = self._plan
        if plan_key is not None and self._plan_key == plan_key and plan is not None:
            return self._plan_dev  # generation key match: no scans at all
        if plan is None or len(plan.src) != len(src) or len(
            plan.node_kind
        ) != n or plan_key is not None or not (
            np.array_equal(plan.src, src) and np.array_equal(plan.dst, dst)
        ):
            plan = build_ell_plan(
                src, dst, n, w_small=self.w_small, w_hub=self.w_hub
            )
            self._plan = plan
            self._plan_dev = _plan_args(plan)
        self._plan_key = plan_key
        return self._plan_dev

    def solve_async(self, problem: FlowProblem):
        n = problem.num_nodes
        m = len(problem.src)
        if m == 0 or problem.num_arcs == 0:
            if (problem.excess > 0).any():
                raise RuntimeError("infeasible flow problem: supply but no arcs")
            return (problem, None, None, None)
        check_finite_costs(problem)
        src = problem.src.astype(np.int32)
        dst = problem.dst.astype(np.int32)
        max_cost = int(np.abs(problem.cost).max()) if m else 0
        if max_cost * n >= (1 << 30):
            raise OverflowError(
                f"scaled costs overflow int32: max|cost|={max_cost} at {n} nodes"
            )

        prev_plan = self._plan
        plan_dev = self._plan_for(
            src, dst, n, plan_key=getattr(problem, "plan_key", None)
        )

        from ..obs import soltel

        tel_cap = soltel.resolve_cap(self.telemetry)
        resident = getattr(problem, "d_cap", None) is not None
        if resident:
            # device-resident problem handle: persistent buffers in,
            # device-carried warm flow — no per-round array re-uploads
            # (see solver/jax_solver.py; same contract)
            from ..graph.device_export import resident_solver_inputs

            dev_args, flow0_dev, _warm = resident_solver_inputs(
                problem, self._prev_dev, self._prev_src_dev,
                self._prev_dst_dev, self.warm_start,
            )
        else:
            cap = problem.cap.astype(np.int32)
            supply = problem.excess.astype(np.int32)
            cost = problem.cost.astype(np.int32) * np.int32(n)
            dev_args = (jnp.asarray(cap), jnp.asarray(cost), jnp.asarray(supply))
            flow0 = np.zeros(m, dtype=np.int32)
            if self.warm_start and self._prev is not None:
                f_prev = self._prev
                if len(f_prev) == m and prev_plan is not None and len(prev_plan.src) == m:
                    same = (prev_plan.src == src) & (prev_plan.dst == dst)
                    flow0 = np.where(same, np.minimum(f_prev, cap), 0).astype(np.int32)
            flow0_dev = jnp.asarray(flow0)
        fut = _solve_mcmf_ell(
            *dev_args,
            flow0_dev,
            jnp.asarray(np.int32(1)),
            *plan_dev,
            alpha=self.alpha,
            max_supersteps=min(4096, self.max_supersteps),
            telemetry_cap=tel_cap,
        )
        cold = (np.zeros(m, dtype=np.int32), max(1, max_cost * n))
        return (problem, fut, (dev_args, plan_dev, cold, tel_cap), resident)

    def complete(self, pending) -> FlowResult:
        from ..obs import soltel

        problem, fut, rest, resident = pending
        if fut is None:
            self.last_telemetry = None
            return FlowResult(
                flow=np.zeros(len(problem.src), dtype=np.int64),  # kschedlint: host-only (FlowResult contract is int64)
                objective=0, iterations=0,
            )
        dev_args, plan_dev, (f0_cold, eps_cold), tel_cap = rest
        tel_buf = None
        if tel_cap:
            flow, p, steps, converged, p_overflow, tel_buf = fut
        else:
            flow, p, steps, converged, p_overflow = fut
        if not (bool(converged) and not bool(p_overflow)):
            out = _solve_mcmf_ell(
                *dev_args,
                jnp.asarray(f0_cold),
                jnp.asarray(np.int32(eps_cold)),
                *plan_dev,
                alpha=self.alpha,
                max_supersteps=self.max_supersteps,
                telemetry_cap=tel_cap,
            )
            if tel_cap:
                flow, p, steps, converged, p_overflow, tel_buf = out
            else:
                flow, p, steps, converged, p_overflow = out
        self.last_supersteps = int(steps)
        # budget = the SOLVER's budget, not the warm attempt's 4096 cap
        # (see jax_solver.complete)
        self.last_telemetry = (
            soltel.decode(
                tel_buf, int(steps), tel_cap, "ell", self.max_supersteps,
                converged=bool(converged) and not bool(p_overflow),
                nodes=problem.num_nodes, arcs=len(problem.src),
            )
            if tel_buf is not None
            else None
        )
        if bool(p_overflow) or not bool(converged):
            self.reset()
        if bool(p_overflow):
            raise OverflowError("push-relabel potentials approached int32 range")
        if not bool(converged):
            tel = self.last_telemetry
            raise soltel.SolverStallError(
                f"push-relabel did not converge within {self.max_supersteps} "
                "supersteps; the flow problem may be infeasible",
                reason=soltel.detect_stall(tel) if tel is not None else None,
                telemetry=tel,
            )
        flow_np = np.asarray(flow)
        if self.warm_start:
            self._prev = flow_np.astype(np.int32)
            self._prev_dev = flow if resident else None
            self._prev_src_dev = problem.d_src if resident else None
            self._prev_dst_dev = problem.d_dst if resident else None
        objective = int(
            (flow_np.astype(np.int64) * problem.cost.astype(np.int64)).sum()  # kschedlint: host-only (int64 objective math on host)
        ) + lower_bound_cost(problem)
        return FlowResult(
            flow=flow_np.astype(np.int64), objective=objective,  # kschedlint: host-only (FlowResult contract is int64)
            iterations=int(steps),
        )

    def solve(self, problem: FlowProblem) -> FlowResult:
        return self.complete(self.solve_async(problem))


# Level-3 registry ownership (ksched_tpu/analysis/program_registry.py)
from ..analysis.program_registry import declare_programs as _declare_programs

_declare_programs(__name__, "ell_solve")
