"""MegaSolver: the general-graph MCMF backend on the Pallas megakernel.

Same FlowSolver seam, same algorithm, same host-cached `build_csr_plan`
ordering as solver/jax_solver.py — but the whole superstep loop runs
inside one `pl.pallas_call` with every table VMEM-resident
(ops/mcmf_pallas.py), instead of ~6 HBM gather passes + 3 global scans
per superstep. Flows are bit-identical to the CSR solver's.

The megakernel's reach is bounded by VMEM (~16 MB/core): graphs whose
padded entry tables exceed `mega_fits_vmem` are refused by `fits()`.
A standalone MegaSolver (--backend mega) delegates refused solves to
its `fallback` CSR solver; under AutoSolver (solver/graph_collapse.py)
the refusal routes the solve to the scan-based CSR backend instead —
the dense -> mega -> scan-CSR escalation ladder.

The plan adds three derived structures to the CSR ordering, all
structure-only (cached and rebuilt with the same key as CsrPlan):

- the partner permutation (each entry's reverse twin), which replaces
  every cross-node gather inside the kernel;
- segment START and END flags for the flag-carrying segmented scans;
- padding to the [R, MEGA_LANES] tile grid, with pad entries forming
  one inert trailing segment (sign 0, supply 0, partner self).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np
import jax.numpy as jnp

from ..graph.device_export import FlowProblem
from .base import FlowResult, FlowSolver, check_finite_costs, lower_bound_cost
from .jax_solver import CsrPlan, build_csr_plan


def _pad_pow2(x: np.ndarray, floor: int = 256) -> np.ndarray:
    """Zero-pad a 1D array to a power-of-two length (>= floor), so the
    kernel wrapper's traced shapes bucket instead of recompiling for
    every arc/node count (DeviceGraphState already grows its padded
    generations the same way). Padded arc slots are never referenced
    by a live entry; padded node slots carry zero supply."""
    from ..utils import next_pow2

    p = max(floor, next_pow2(len(x)))
    if p == len(x):
        return x
    return np.concatenate([x, np.zeros(p - len(x), x.dtype)])


@dataclass
class MegaPlan:
    """Padded, partner-linked entry tables for the megakernel."""

    R: int  # block rows of the [R, L] entry tiling
    L: int  # lanes
    e_arc: np.ndarray  # int32[R*L] arc slot (0 on pad)
    e_sign: np.ndarray  # int32[R*L] +1/-1, 0 on pad
    e_src: np.ndarray  # int32[R*L] source node (0 on pad)
    e_hs: np.ndarray  # int32[R*L] segment-start flags
    e_he: np.ndarray  # int32[R*L] segment-end flags
    e_prow: np.ndarray  # int32[R*L] partner block row
    e_pcol: np.ndarray  # int32[R*L] partner lane
    fwd_pos: np.ndarray  # int32[M] flat entry position of each arc's fwd entry
    src: np.ndarray  # int32[M] endpoints the plan was built for
    dst: np.ndarray  # int32[M]


def build_mega_plan(plan: CsrPlan, lanes: Optional[int] = None) -> MegaPlan:
    """Derive the megakernel tables from a (cached) CsrPlan."""
    from ..ops.mcmf_pallas import MEGA_LANES, mega_entry_rows

    L = MEGA_LANES if lanes is None else lanes
    m2 = len(plan.s_arc)
    m = m2 // 2
    R = mega_entry_rows(m2, L)
    E = R * L
    pad = E - m2

    e_arc = np.zeros(E, np.int32)
    e_arc[:m2] = plan.s_arc
    e_sign = np.zeros(E, np.int32)
    e_sign[:m2] = plan.s_sign
    e_src = np.zeros(E, np.int32)
    e_src[:m2] = plan.s_src
    e_hs = np.zeros(E, np.int32)
    e_hs[:m2] = plan.s_isstart
    e_he = np.zeros(E, np.int32)
    if m2:
        e_he[: m2 - 1] = plan.s_isstart[1:]
        e_he[m2 - 1] = 1
    if pad:
        e_hs[m2] = 1  # the pad region is one inert segment
        e_he[E - 1] = 1

    # partner permutation: entry (u, v) of arc a pairs with (v, u) —
    # the fwd entry's twin is original entry a + m, and vice versa
    ppos = np.arange(E, dtype=np.int64)  # kschedlint: host-only (numpy plan build)
    ppos[:m2] = plan.inv_order[
        np.where(plan.s_sign > 0, plan.s_arc + m, plan.s_arc)
    ]
    e_prow = (ppos // L).astype(np.int32)
    e_pcol = (ppos % L).astype(np.int32)

    return MegaPlan(
        R=R, L=L,
        e_arc=e_arc, e_sign=e_sign, e_src=e_src,
        e_hs=e_hs, e_he=e_he, e_prow=e_prow, e_pcol=e_pcol,
        fwd_pos=plan.inv_order[:m].astype(np.int32),
        src=plan.src.copy(), dst=plan.dst.copy(),
    )


class MegaSolver(FlowSolver):
    """VMEM-resident megakernel push-relabel, warm-started across
    rounds — drop-in for JaxSolver on graphs that fit VMEM.

    interpret: None = auto (compiled on TPU, Pallas interpreter
    elsewhere, honoring set_pallas_mode("interpret")); True/False
    force. fallback: optional CSR FlowSolver for graphs `fits()`
    refuses (oversized / degenerate); without one, refused solves
    raise."""

    def __init__(
        self,
        alpha: int = 8,
        max_supersteps: int = 50_000,
        warm_start: bool = True,
        lanes: Optional[int] = None,
        vmem_budget_bytes: Optional[int] = None,
        interpret: Optional[bool] = None,
        fallback: Optional[FlowSolver] = None,
        telemetry: Optional[int] = None,
    ):
        from .layered import validate_alpha
        from ..ops.mcmf_pallas import MEGA_LANES, _MEGA_VMEM_BUDGET_BYTES

        self.alpha = validate_alpha(alpha)
        self.max_supersteps = max_supersteps
        self.warm_start = warm_start
        self.lanes = MEGA_LANES if lanes is None else int(lanes)
        self.vmem_budget_bytes = (
            _MEGA_VMEM_BUDGET_BYTES
            if vmem_budget_bytes is None
            else int(vmem_budget_bytes)
        )
        self.interpret = interpret
        self.fallback = fallback
        self.telemetry = telemetry
        self._prev: Optional[np.ndarray] = None
        self._plan: Optional[MegaPlan] = None
        self._plan_dev: Optional[tuple] = None
        #: endpoint-generation key of the cached plan (FlowProblem.
        #: plan_key): equal keys skip the O(M) endpoint scans entirely
        self._plan_key = None
        self._fits_ok_for: Optional[FlowProblem] = None
        self._prev_dev = None  # warm flow as a device array (no re-upload)
        # endpoints at the LAST SUCCESSFUL SOLVE (see jax_solver)
        self._prev_src_dev = None
        self._prev_dst_dev = None
        self.last_supersteps = 0
        self.last_telemetry = None
        self.last_refusal = ""

    def reset(self) -> None:
        self._prev = None
        self._prev_dev = None
        self._prev_src_dev = None
        self._prev_dst_dev = None
        if self.fallback is not None:
            self.fallback.reset()

    def _resolve_interpret(self) -> bool:
        if self.interpret is not None:
            return bool(self.interpret)
        from ..ops import get_pallas_mode

        mode = get_pallas_mode()
        if mode == "interpret":
            return True
        if mode == "on":
            return False
        import jax

        return jax.default_backend() != "tpu"

    def fits(self, problem: FlowProblem) -> bool:
        """Whether the megakernel can take this solve; on refusal
        `last_refusal` names why (the AutoSolver escalation reads it)."""
        from ..obs import soltel
        from ..ops.mcmf_pallas import mega_fits_vmem

        m = len(problem.src)
        if m == 0 or problem.num_arcs == 0:
            self.last_refusal = "empty graph"
            return False
        if not mega_fits_vmem(
            2 * m, self.lanes, self.vmem_budget_bytes,
            telemetry=soltel.resolve_cap(self.telemetry) > 0,
        ):
            self.last_refusal = (
                f"{2 * m} entries exceed the VMEM tiling budget "
                f"({self.vmem_budget_bytes} bytes)"
            )
            return False
        # the kernel shares the CSR solver's exactness contract (costs
        # pre-scaled by the node count must fit int32); refusing here
        # keeps the dispatch ladder total — the fallback rung (native
        # CSR under AutoSolver) solves such graphs on raw costs
        max_cost = int(np.abs(problem.cost).max()) if m else 0
        if max_cost * problem.num_nodes >= (1 << 30):
            self.last_refusal = (
                f"scaled costs overflow int32 (max|cost|={max_cost} at "
                f"{problem.num_nodes} nodes)"
            )
            return False
        # nodes with excess but no entries never appear in the kernel's
        # segment space: their (infeasible) excess would go unnoticed,
        # so route such graphs to the CSR solver's canonical handling
        deg = np.bincount(
            np.concatenate([problem.src, problem.dst]),
            minlength=problem.num_nodes,
        )
        if (np.asarray(problem.excess)[deg == 0] != 0).any():
            self.last_refusal = "isolated node with nonzero excess"
            return False
        self.last_refusal = ""
        # remember the vetted problem (by identity) so the dispatch
        # seam's fits() + solve() sequence audits the arrays once
        self._fits_ok_for = problem
        return True

    def _plan_for(self, src: np.ndarray, dst: np.ndarray, n: int, plan_key=None) -> tuple:
        plan = self._plan
        if plan_key is not None and self._plan_key == plan_key and plan is not None:
            return self._plan_dev  # generation key match: no scans at all
        if plan is None or len(plan.src) != len(src) or plan_key is not None or not (
            np.array_equal(plan.src, src) and np.array_equal(plan.dst, dst)
        ):
            plan = build_mega_plan(build_csr_plan(src, dst, n), self.lanes)
            self._plan = plan
            # fwd_pos rides the cache PADDED (zero fill: the garbage
            # tail rows of the gathered flow are sliced off in
            # complete()) so its traced shape buckets with cap/cost
            self._plan_dev = tuple(
                jnp.asarray(x)
                for x in (
                    plan.e_arc, plan.e_sign, plan.e_src,
                    plan.e_hs, plan.e_he, plan.e_prow, plan.e_pcol,
                    _pad_pow2(plan.fwd_pos),
                )
            )
        self._plan_key = plan_key
        return self._plan_dev

    def solve_async(self, problem: FlowProblem):
        from ..ops.mcmf_pallas import mcmf_loop_pallas

        n = problem.num_nodes
        m = len(problem.src)
        if m == 0 or problem.num_arcs == 0:
            if (problem.excess > 0).any():
                raise RuntimeError("infeasible flow problem: supply but no arcs")
            return (problem, None, None, None)
        check_finite_costs(problem)
        vetted = self._fits_ok_for is problem
        self._fits_ok_for = None
        if not vetted and not self.fits(problem):
            if self.fallback is None:
                raise RuntimeError(
                    f"megakernel refused the graph ({self.last_refusal}) "
                    "and no fallback solver is attached"
                )
            return (problem, None, None, self.fallback.solve_async(problem))
        # the internal fits() call above re-primed the cache; vetting
        # is single-use — a re-solve of a MUTATED problem object must
        # re-audit (costs may have drifted past the overflow bound)
        self._fits_ok_for = None
        src = problem.src.astype(np.int32)
        dst = problem.dst.astype(np.int32)
        max_cost = int(np.abs(problem.cost).max()) if m else 0

        prev_plan = self._plan
        plan_dev = self._plan_for(
            src, dst, n, plan_key=getattr(problem, "plan_key", None)
        )

        from ..obs import soltel
        from ..ops.mcmf_pallas import mega_telemetry_cap

        interpret = self._resolve_interpret()
        # A device-resident handle is consumable directly only when the
        # resident pow2 extents already satisfy the kernel's _pad_pow2
        # floor (256) — then the padded shapes ARE the resident shapes
        # and no per-round re-upload (or device-side re-pad) is needed.
        resident = (
            getattr(problem, "d_cap", None) is not None
            and m >= 256
            and n >= 256
        )
        if resident:
            from ..graph.device_export import resident_solver_inputs

            dev_args, flow0_dev, _warm = resident_solver_inputs(
                problem, self._prev_dev, self._prev_src_dev,
                self._prev_dst_dev, self.warm_start,
            )
        else:
            cap = problem.cap.astype(np.int32)
            supply = problem.excess.astype(np.int32)
            cost = problem.cost.astype(np.int32) * np.int32(n)
            dev_args = (
                jnp.asarray(_pad_pow2(cap)),
                jnp.asarray(_pad_pow2(cost)),
                jnp.asarray(_pad_pow2(supply)),
            )
            flow0 = np.zeros(m, dtype=np.int32)
            if self.warm_start and self._prev is not None:
                f_prev = self._prev
                if len(f_prev) == m and prev_plan is not None and len(prev_plan.src) == m:
                    same = (prev_plan.src == src) & (prev_plan.dst == dst)
                    flow0 = np.where(same, np.minimum(f_prev, cap), 0).astype(np.int32)
            flow0_dev = jnp.asarray(_pad_pow2(flow0))
        # geometry rides the pending token: a later solve_async for a
        # different graph may rebuild self._plan before this dispatch
        # is complete()d (the async-pipelining seam)
        RL = (self._plan.R, self._plan.L)
        tel_cap = soltel.resolve_cap(self.telemetry)
        if tel_cap:
            # ring clamped to one [R, L] entry tile (the +1-tile VMEM
            # budget fits() charged); decode needs the effective cap
            tel_cap = mega_telemetry_cap(RL[0], RL[1], tel_cap)
        fut = mcmf_loop_pallas(
            *dev_args,
            flow0_dev,
            jnp.asarray(np.int32(1)),
            *plan_dev,
            R=RL[0], L=RL[1],
            alpha=self.alpha,
            max_supersteps=min(4096, self.max_supersteps),
            interpret=interpret,
            telemetry_cap=tel_cap,
        )
        cold = (
            _pad_pow2(np.zeros(m, dtype=np.int32)),
            max(1, max_cost * n),
            interpret,
            resident,
        )
        return (problem, fut, (dev_args, plan_dev, RL, cold, tel_cap), None)

    def complete(self, pending) -> FlowResult:
        from ..obs import soltel
        from ..ops.mcmf_pallas import mcmf_loop_pallas

        problem, fut, rest, delegated = pending
        if delegated is not None:
            res = self.fallback.complete(delegated)
            self.last_supersteps = getattr(
                self.fallback, "last_supersteps", res.iterations
            )
            self.last_telemetry = getattr(self.fallback, "last_telemetry", None)
            return res
        if fut is None:
            self.last_telemetry = None
            return FlowResult(
                flow=np.zeros(len(problem.src), dtype=np.int64),  # kschedlint: host-only (FlowResult contract is int64)
                objective=0, iterations=0,
            )
        dev_args, plan_args, (R, L), (f0_cold, eps_cold, interpret, resident), tel_cap = rest
        tel_buf = None
        if tel_cap:
            flow, steps, converged, p_overflow, tel_buf = fut
        else:
            flow, steps, converged, p_overflow = fut
        if not (bool(converged) and not bool(p_overflow)):
            out = mcmf_loop_pallas(
                *dev_args,
                jnp.asarray(f0_cold),
                jnp.asarray(np.int32(eps_cold)),
                *plan_args,
                R=R, L=L,
                alpha=self.alpha,
                max_supersteps=self.max_supersteps,
                interpret=interpret,
                telemetry_cap=tel_cap,
            )
            if tel_cap:
                flow, steps, converged, p_overflow, tel_buf = out
            else:
                flow, steps, converged, p_overflow = out
        self.last_supersteps = int(steps)
        # budget = the SOLVER's budget, not the warm attempt's 4096 cap
        # (see jax_solver.complete)
        self.last_telemetry = (
            soltel.decode(
                tel_buf, int(steps), tel_cap, "mega", self.max_supersteps,
                converged=bool(converged) and not bool(p_overflow),
                nodes=problem.num_nodes, arcs=len(problem.src),
            )
            if tel_buf is not None
            else None
        )
        if bool(p_overflow) or not bool(converged):
            self._prev = None
            self._prev_dev = None
        if bool(p_overflow):
            raise OverflowError("push-relabel potentials approached int32 range")
        if not bool(converged):
            tel = self.last_telemetry
            raise soltel.SolverStallError(
                f"push-relabel did not converge within {self.max_supersteps} "
                "supersteps; the flow problem may be infeasible",
                reason=soltel.detect_stall(tel) if tel is not None else None,
                telemetry=tel,
            )
        flow_np = np.asarray(flow)[: len(problem.src)]
        if self.warm_start:
            self._prev = flow_np.astype(np.int32)
            # the padded kernel flow aligns with the resident extent
            # only when no extra _pad_pow2 padding was applied
            keep = resident and flow.shape[0] == len(problem.src)
            self._prev_dev = flow if keep else None
            self._prev_src_dev = problem.d_src if keep else None
            self._prev_dst_dev = problem.d_dst if keep else None
        objective = int(
            (flow_np.astype(np.int64) * problem.cost.astype(np.int64)).sum()  # kschedlint: host-only (int64 objective math on host)
        ) + lower_bound_cost(problem)
        return FlowResult(
            flow=flow_np.astype(np.int64), objective=objective,  # kschedlint: host-only (FlowResult contract is int64)
            iterations=int(steps),
        )

    def solve(self, problem: FlowProblem) -> FlowResult:
        return self.complete(self.solve_async(problem))
