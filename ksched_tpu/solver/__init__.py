from .base import FlowResult, FlowSolver
from .cpu_ref import ReferenceSolver
from .decode import flow_to_mapping
from .native import NativeSolver
from .placement import PlacementSolver

__all__ = [
    "FlowResult",
    "FlowSolver",
    "NativeSolver",
    "ReferenceSolver",
    "flow_to_mapping",
    "PlacementSolver",
]
