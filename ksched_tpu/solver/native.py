"""NativeSolver: the in-process C++ MCMF backend.

Role-equivalent to the reference's Flowlessly subprocess
(scheduling/flow/placement/solver.go:31-34,92-123): the production CPU
solver. Differences by design: in-process shared library instead of a
daemon + DIMACS pipes; warm start carried by an opaque price context
instead of daemon process state; solver failure raises instead of
panicking the scheduler (solver.go:98-108).

Algorithms (mirroring Flowlessly's --algorithm flag, solver.go:32):
  "ssp"          exact successive shortest paths — oracle-grade
  "cost_scaling" Goldberg-Tarjan push-relabel, warm-started across rounds
"""

from __future__ import annotations

import ctypes

import numpy as np

from ..graph.device_export import FlowProblem
from .base import FlowResult, FlowSolver, check_finite_costs, lower_bound_cost

_ALGORITHMS = {"ssp": 0, "cost_scaling": 1}

_ERRORS = {
    1: "infeasible flow problem: supply cannot reach any demand "
    "(the unscheduled-aggregator escape arcs should prevent this)",
    2: "unbalanced excess: total supply != total demand",
    3: "malformed problem arrays",
    4: "negative cost cycle in flow network",
}


class NativeSolver(FlowSolver):
    def __init__(self, algorithm: str = "cost_scaling", warm_start: bool = True):
        if algorithm not in _ALGORITHMS:
            raise ValueError(f"unknown algorithm {algorithm!r}; want one of {sorted(_ALGORITHMS)}")
        from ..native import load_library

        self._lib = load_library()
        self._algorithm = _ALGORITHMS[algorithm]
        self._ctx = self._lib.ksched_mcmf_ctx_new() if warm_start else None
        self.last_iterations = 0

    def __del__(self):  # pragma: no cover - interpreter-shutdown dependent
        ctx = getattr(self, "_ctx", None)
        if ctx is not None:
            try:
                self._lib.ksched_mcmf_ctx_free(ctx)
            except Exception:
                pass
            self._ctx = None

    def reset(self) -> None:
        if self._ctx is not None:
            self._lib.ksched_mcmf_ctx_free(self._ctx)
            self._ctx = self._lib.ksched_mcmf_ctx_new()

    def solve(self, problem: FlowProblem) -> FlowResult:
        n = int(problem.num_nodes)
        m = len(problem.src)
        check_finite_costs(problem)
        src = np.ascontiguousarray(problem.src, dtype=np.int32)
        dst = np.ascontiguousarray(problem.dst, dtype=np.int32)
        cap = np.ascontiguousarray(problem.cap, dtype=np.int32)
        cost = np.ascontiguousarray(problem.cost, dtype=np.int32)
        excess = np.ascontiguousarray(problem.excess[:n], dtype=np.int64)
        flow = np.zeros(m, dtype=np.int64)
        objective = ctypes.c_int64(0)
        iters = ctypes.c_int64(0)

        def p32(a):
            return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))

        def p64(a):
            return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))

        rc = self._lib.ksched_mcmf_solve(
            self._ctx,
            self._algorithm,
            n,
            m,
            p32(src),
            p32(dst),
            p32(cap),
            p32(cost),
            p64(excess),
            p64(flow),
            ctypes.byref(objective),
            ctypes.byref(iters),
        )
        if rc != 0:
            raise RuntimeError(_ERRORS.get(rc, f"native solver error {rc}"))
        self.last_iterations = int(iters.value)
        return FlowResult(
            flow=flow,
            objective=int(objective.value) + lower_bound_cost(problem),
            iterations=self.last_iterations,
        )
