"""One place to pick an MCMF backend by name.

Mirrors the reference's solver selection flags (placement/solver.go:
30-34) with graceful degradation: "native" needs a C++ toolchain at
first use (compile-on-demand), so callers that cannot guarantee one get
the JAX backend instead of a traceback.
"""

from __future__ import annotations

import warnings

from .base import FlowSolver


def make_backend(name: str, warm_start: bool = True, fallback: bool = True) -> FlowSolver:
    """name: "native" | "jax" | "ell" | "mega" | "sharded" | "ref" |
    "layered" | "auto". With fallback=True a failed native build degrades to the
    JAX solver with a RuntimeWarning (capturable by callers/tests via
    warnings.catch_warnings, unlike the stderr print it replaced)."""
    if name == "native":
        try:
            from .native import NativeSolver

            return NativeSolver(algorithm="cost_scaling", warm_start=warm_start)
        except (RuntimeError, OSError, FileNotFoundError) as e:
            if not fallback:
                raise
            warnings.warn(
                f"native backend unavailable ({e}); using jax",
                RuntimeWarning,
                stacklevel=2,
            )
            name = "jax"
    if name == "jax":
        from .jax_solver import JaxSolver

        return JaxSolver(warm_start=warm_start)
    if name == "ell":
        # bucketed-ELL layout of the same push-relabel (ell_solver.py):
        # measured within ~2% of the CSR layout on TPU at 10k x 1k —
        # both are bound by gather/iteration costs, not the scans —
        # kept selectable for degree-skewed graphs where the dense
        # row ops pay off
        from .ell_solver import EllSolver

        return EllSolver(warm_start=warm_start)
    if name == "mega":
        # the Pallas megakernel (ops/mcmf_pallas.py): the whole
        # push-relabel loop in one kernel launch, tables VMEM-resident
        # for the solve — compiled on TPU, interpreter elsewhere.
        # Graphs beyond the VMEM tiling budget delegate to the
        # scan-based CSR solver so the backend stays total.
        from .jax_solver import JaxSolver
        from .mega_solver import MegaSolver

        return MegaSolver(
            warm_start=warm_start,
            fallback=JaxSolver(warm_start=warm_start),
        )
    if name == "sharded":
        # the multi-chip slot-stable backend over the full device mesh
        # (parallel/sharded_solver.py); under AutoSolver ("auto") it is
        # the fourth rung behind the HBM fitting gate — selecting it
        # directly forces every general-graph solve onto the mesh
        import numpy as _np
        import jax
        from jax.sharding import Mesh

        from ..parallel.sharded_solver import ShardedJaxSolver

        return ShardedJaxSolver(
            Mesh(_np.array(jax.devices()), ("x",)), warm_start=warm_start
        )
    if name == "ref":
        from .cpu_ref import ReferenceSolver

        return ReferenceSolver()
    if name == "layered":
        from .layered import LayeredTransportSolver

        return LayeredTransportSolver()
    if name == "auto":
        # the policy-dispatch seam (docs/solver_coverage.md): dense
        # transport whenever the graph audits as collapsible, then the
        # megakernel for general graphs inside its VMEM budget, the
        # scan-based CSR backend while its HBM working set fits one
        # chip, the sharded multi-chip backend beyond that — per
        # solve, automatically. The mega rung is attached only when
        # Pallas dispatch is live (TPU backend, or a forced
        # "on"/"interpret" mode): interpreting the kernel on CPU would
        # be strictly slower than the XLA scan path it replaces. The
        # sharded rung is attached (lazily — no mesh or shard_map
        # compile until the fitting gate escalates) whenever the
        # process sees more than one device.
        from ..ops import resolve_pallas
        from .graph_collapse import AutoSolver

        mega = None
        if resolve_pallas()[0]:
            from .mega_solver import MegaSolver

            mega = MegaSolver(warm_start=warm_start)
        sharded = None
        import jax

        if len(jax.devices()) > 1:
            def _make_sharded():
                import numpy as _np
                from jax.sharding import Mesh

                from ..parallel.sharded_solver import ShardedJaxSolver

                devs = _np.array(jax.devices())
                return ShardedJaxSolver(
                    Mesh(devs, ("x",)), warm_start=warm_start
                )

            sharded = _make_sharded
        return AutoSolver(
            make_backend("native", warm_start=warm_start, fallback=fallback),
            mega=mega,
            sharded=sharded,
        )
    raise ValueError(
        f"unknown backend {name!r}; want native | jax | ell | mega | "
        "sharded | ref | layered | auto"
    )
