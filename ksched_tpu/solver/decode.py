"""Flow → task-placement decoding.

Reference: scheduling/flow/placement/solver.go:183-269 — start from leaf
(PU) nodes that send flow to the sink and push PU ids backwards up each
flow-carrying arc until task nodes are reached; asserts a 1:1 task→PU
mapping. Tasks whose unit drained through their job's unscheduled
aggregator never receive a PU and stay unplaced.

Divergence from the reference: its reverse *BFS* can pop a node before
all of that node's unit contributors have been processed when flow paths
skip levels, silently dropping units. We instead process nodes in strict
topological order of the positive-flow DAG (longest-distance-from-sink
strata), which is correct for any acyclic flow. Positive-flow cycles
cannot appear in a minimal-cost flow from our backends (SSP never creates
them; the push-relabel backend cancels zero-cost cycles before decode).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Set

import numpy as np

from ..graph.device_export import FlowProblem

TaskMapping = Dict[int, int]


def flow_to_mapping(
    problem: FlowProblem,
    total_flow: np.ndarray,
    leaf_node_ids: Iterable[int],
    sink_node_id: int,
    task_node_ids: Iterable[int],
) -> TaskMapping:
    """Decode a solved flow into {task node id -> PU node id}.

    total_flow must include lower-bound offsets (FlowResult.total_flow).
    Any consistent decomposition of the flow is a valid assignment (flow
    conservation guarantees it); per-node units are matched to incoming
    arcs in arc order.
    """
    src = problem.src
    dst = problem.dst
    live = np.nonzero(total_flow > 0)[0]
    task_nodes: Set[int] = set(int(t) for t in task_node_ids)
    leaf_set: Set[int] = set(int(x) for x in leaf_node_ids)

    # Per-node incoming positive-flow arcs: dst -> [(src, flow), ...].
    incoming: Dict[int, List[tuple]] = {}
    for i in live:
        incoming.setdefault(int(dst[i]), []).append((int(src[i]), int(total_flow[i])))

    # Stratify the positive-flow DAG by longest distance from the sink,
    # walking backwards. level[v] = 1 + max(level[w] for flow arcs v->w).
    level: Dict[int, int] = {sink_node_id: 0}
    frontier = {sink_node_id}
    n_nodes = problem.num_nodes
    rounds = 0
    while frontier:
        rounds += 1
        if rounds > n_nodes:
            raise RuntimeError("positive-flow cycle detected during decode")
        nxt: Set[int] = set()
        for w in frontier:
            lw = level[w]
            for s, _f in incoming.get(w, []):
                if level.get(s, -1) < lw + 1:
                    level[s] = lw + 1
                    nxt.add(s)
        frontier = nxt

    # pu_units[v] = PU ids of the flow units passing through v.
    pu_units: Dict[int, List[int]] = {}
    for s, f in incoming.get(sink_node_id, []):
        if s in leaf_set and f > 0:
            pu_units[s] = [s] * f

    mapping: TaskMapping = {}
    order = sorted((v for v in level if v != sink_node_id), key=lambda v: level[v])
    for v in order:
        units = pu_units.get(v)
        if units is None:
            continue  # e.g. unscheduled aggregators: no PU units flow through
        if v in task_nodes:
            if len(units) != 1:
                raise AssertionError(
                    f"task node {v} decoded {len(units)} units; task->PU must be 1:1"
                )
            mapping[v] = units[0]
            continue
        it = 0
        for s, f in incoming.get(v, []):
            take = min(f, len(units) - it)
            if take > 0:
                pu_units.setdefault(s, []).extend(units[it : it + take])
                it += take
            if it >= len(units):
                break
    return mapping
