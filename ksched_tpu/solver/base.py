"""L5': the solver-dispatch boundary.

Reference: scheduling/flow/placement/solver.go:36-38 — a single-method
``Solve() -> TaskMapping`` seam behind which the MCMF backend lives. The
TPU build keeps the seam but the wire format is flat arrays
(graph/device_export.FlowProblem) instead of DIMACS text, and three
backends plug in:

- ReferenceSolver (solver/cpu_ref.py): exact successive-shortest-path
  oracle, pure Python — the mock-solver/test oracle the reference lacks;
- NativeSolver (solver/native.py): in-process C++ library, the
  Flowlessly-equivalent CPU production backend;
- JaxSolver (solver/jax_solver.py): jit cost-scaling push-relabel on TPU,
  warm-started across rounds — the centerpiece of the rebuild.
"""

from __future__ import annotations

import abc
from dataclasses import dataclass

import numpy as np

from ..graph.device_export import FlowProblem


@dataclass
class FlowResult:
    """A feasible min-cost flow over a FlowProblem's arc slots.

    ``flow`` excludes lower-bound offsets; add ``problem.flow_offset`` to
    recover total arc flow. ``objective`` is the total cost including the
    lower-bound flow's cost.
    """

    flow: np.ndarray  # int64[M]
    objective: int
    iterations: int = 0

    def total_flow(self, problem: FlowProblem) -> np.ndarray:
        return self.flow + problem.flow_offset


def check_finite_costs(problem: FlowProblem) -> None:
    """Reject a poisoned cost model (NaN/inf costs) up front. Every
    backend calls this before its int cast — a non-finite float would
    otherwise wrap into garbage potentials and be "solved" silently
    (the chaos harness's nan_cost fault exists to catch exactly that;
    see runtime/chaos.poison_costs)."""
    if problem.cost.dtype.kind == "f" and not np.isfinite(problem.cost).all():
        raise ValueError(
            "non-finite arc costs in flow problem (NaN/inf from the "
            "cost model); refusing to solve"
        )


def lower_bound_cost(problem: FlowProblem) -> int:
    """Cost carried by the folded lower-bound flow; every backend adds
    this to its solved objective so objectives are comparable."""
    return int(
        (problem.flow_offset.astype(np.int64) * problem.cost.astype(np.int64)).sum()
    )


class FlowSolver(abc.ABC):
    """A min-cost max-flow backend over flat arrays."""

    @abc.abstractmethod
    def solve(self, problem: FlowProblem) -> FlowResult: ...

    def solve_traced(self, problem: FlowProblem) -> FlowResult:
        """``solve()`` inside a ``backend_solve`` obs span carrying the
        backend name, problem shape, and solver effort. This is the one
        instrumentation seam shared by every backend — the placement
        driver and the degradation ladder call it, so each rung attempt
        (including a failing one, whose span records the error) is a
        nested span in a captured trace. Costs two ``perf_counter``
        reads when no tracer is installed.

        Backends that emit solver-interior telemetry (``last_telemetry``
        after a solve — the compiled jax/ell/mega/layered/sharded
        loops) additionally get their buffer decoded here: superstep
        histograms onto the registry, per-superstep child spans under
        this span (Perfetto shows the convergence shape), and the
        stall detector (obs/soltel.py). ``native``/``cpu_ref`` expose
        no interior telemetry and skip all of it."""
        from ..obs import soltel
        from ..obs.spans import span

        with span(
            "backend_solve",
            backend=type(self).__name__,
            nodes=int(problem.num_nodes),
            arcs=int(problem.num_arcs),
        ) as sp:
            result = self.solve(problem)
            work = int(result.iterations or 0) or int(
                getattr(self, "last_iterations", 0)
                or getattr(self, "last_supersteps", 0)
                or 0
            )
            if work:
                sp.set("supersteps", work)
            tel = getattr(self, "last_telemetry", None)
            if tel is not None:
                soltel.publish(tel, sp)
        return result

    def reset(self) -> None:
        """Drop warm-start state (e.g. after a full graph rebuild)."""
