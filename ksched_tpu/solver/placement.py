"""The placement solver driver: graph manager → backend → task mapping.

Reference: scheduling/flow/placement/solver.go:60-123. Round 1 exports
the full graph; round N first refreshes task→unsched costs
(UpdateAllCostsToUnscheduledAggs) and then ships only the journaled
changes. In the reference the export is DIMACS text to a daemon
subprocess; here it is a scatter into the flat device arrays
(DeviceGraphState), and the backend is called in-process.
"""

from __future__ import annotations

from typing import Dict

from ..graph.device_export import DeviceGraphState
from ..graph.graph_manager import GraphManager, TaskMapping
from .base import FlowSolver
from .decode import flow_to_mapping


class PlacementSolver:
    def __init__(self, gm: GraphManager, backend: FlowSolver, incremental: bool = True) -> None:
        self.gm = gm
        self.backend = backend
        self.incremental = incremental
        self.state = DeviceGraphState()
        self._started = False
        self.last_result = None

    def solve(self) -> TaskMapping:
        gm = self.gm
        if not self._started or not self.incremental:
            self._started = True
            self.state.full_build(gm.cm.graph)
            gm.cm.reset_changes()
            self.backend.reset()
        else:
            gm.update_all_costs_to_unscheduled_aggs()
            self.state.apply_changes(gm.cm.get_optimized_graph_changes())
            gm.cm.reset_changes()
        # Sink excess is maintained outside the journal (reference:
        # graph_manager.go:636-640); sync it before each solve.
        self.state.set_excess(gm.sink_node.id, gm.sink_node.excess)

        problem = self.state.problem()
        result = self.backend.solve(problem)
        self.last_result = result
        task_node_ids = [node.id for node in gm.task_to_node.values()]
        return flow_to_mapping(
            problem,
            result.total_flow(problem),
            gm.leaf_node_ids,
            gm.sink_node.id,
            task_node_ids,
        )
