"""The placement solver driver: graph manager → backend → task mapping.

Reference: scheduling/flow/placement/solver.go:60-123. Round 1 exports
the full graph; round N first refreshes task→unsched costs
(UpdateAllCostsToUnscheduledAggs) and then ships only the journaled
changes. In the reference the export is DIMACS text to a daemon
subprocess; here it is a scatter into the flat device arrays
(DeviceGraphState), and the backend is called in-process.
"""

from __future__ import annotations

from typing import Dict

from ..graph.device_export import DeviceGraphState, DeviceResidentState
from ..graph.graph_manager import GraphManager, TaskMapping
from ..obs.devprof import get_profiler
from ..obs.spans import span
from .base import FlowSolver
from .decode import flow_to_mapping


class PlacementSolver:
    """``device_resident=True`` keeps the folded problem arrays live on
    device between rounds (graph/device_export.DeviceResidentState):
    after the first full upload, each round ships only the packed delta
    records — one jit'd scatter applies them — and device-aware
    backends consume the handle without re-uploading anything. Host
    consumers (decode, cpu_ref/native ladder rungs) are unaffected: the
    handle still carries the host arrays."""

    def __init__(
        self,
        gm: GraphManager,
        backend: FlowSolver,
        incremental: bool = True,
        device_resident: bool = False,
    ) -> None:
        self.gm = gm
        self.backend = backend
        self.incremental = incremental
        self.device_resident = device_resident
        self.state = DeviceGraphState()
        self.resident = DeviceResidentState(self.state) if device_resident else None
        self._started = False
        self.last_result = None
        # ---- device-state integrity (runtime/integrity.py) -----------
        #: audit cadence in exports (0 = off); the service sets it from
        #: --audit-every. On due rounds the post-refresh mirror is
        #: fingerprinted against the host journal truth and divergence
        #: is repaired through the escalating ladder before the solve.
        self.audit_every = 0
        self.auditor = None
        self._export_count = 0
        #: array names that diverged on the LAST audited export (the
        #: service's flight-dump trigger), None when clean
        self.last_divergence = None
        #: cumulative integrity accounting for this solver's lifetime
        #: (divergences, repair_<rung>) — soaks sum it across restores
        from collections import Counter as _Counter

        self.integrity_counts = _Counter()

    def solve_async(self):
        """Phase 1 of a pipelined round: export the journal, snapshot
        the problem, and DISPATCH the backend solve, returning before
        it completes. The problem arrays are a snapshot, so the caller
        may keep journaling next-round graph mutations while the solve
        is in flight — the overlap the reference's daemon-mode
        subprocess provides across its pipe boundary
        (placement/solver.go:60-90). Backends without solve_async run
        synchronously here (the token then carries the result)."""
        gm = self.gm
        full = not self._started or not self.incremental
        changes = None
        with span("graph_export", kind="full_build" if full else "delta"):
            if full:
                self._started = True
                self.state.full_build(gm.cm.graph)
                gm.cm.reset_changes()
                self.backend.reset()
            else:
                gm.update_all_costs_to_unscheduled_aggs()
                changes = gm.cm.get_optimized_graph_changes()
                self.state.apply_changes(changes)
                gm.cm.reset_changes()
            # Sink excess is maintained outside the journal (reference:
            # graph_manager.go:636-640); sync it before each solve.
            self.state.set_excess(gm.sink_node.id, gm.sink_node.excess)
            if self.resident is not None:
                # pack + scatter this round's delta into the persistent
                # device buffers (delta_pack / delta_upload child spans)
                problem = self.resident.refresh()
                problem = self._integrity_gate(problem)
            else:
                problem = self.state.problem()
        # Byte accounting: in device-resident mode the EXACT nbytes
        # that crossed the boundary (packed records, or the rebuild
        # upload); otherwise from the journal just applied — NOT from
        # the per-round ChangeStats, which miss the previous round's
        # post-solve mutations (journaled after the round-start stats
        # reset but shipped in this scatter).
        if self.resident is not None:
            get_profiler().note_export(
                problem,
                full=self.resident.last_upload_kind == "full_build",
                exact_bytes=self.resident.last_upload_bytes,
            )
        else:
            get_profiler().note_export(problem, full=full, changes=changes)
        # Task nodes captured NOW: the decode must map the snapshot's
        # tasks, not tasks added while the solve is in flight.
        task_node_ids = [node.id for node in gm.task_to_node.values()]
        get_profiler().solve_starting()
        try:
            if hasattr(self.backend, "solve_async"):
                pending = self.backend.solve_async(problem)
                return (problem, task_node_ids, pending, True)
            return (problem, task_node_ids, self.backend.solve_traced(problem), False)
        except BaseException:
            get_profiler().solve_failed()  # stop an Nth-solve capture
            raise

    def _integrity_gate(self, problem):
        """The post-refresh integrity seam: apply any injected device
        corruption (the chaos seam — the injector rides the ladder
        backend, so corruption is drawn per cell and contained exactly
        like solver faults), then on audit-due rounds fingerprint the
        mirror against the host truth and run the divergence response
        ladder: re-scatter dirty span -> full re-upload -> plan
        _rebuild -> full_build (here) -> the degradation ladder's NOOP
        backstop. Repairs restore the exact host values, so a repaired
        round's placements are bit-identical to a clean-state solve."""
        inj = getattr(self.backend, "injector", None)
        if inj is not None and hasattr(inj, "device_corruption"):
            available = set(("excess", "src", "dst", "cap", "cost"))
            if self.resident.d_p_sign is not None:
                available |= {"p_arc", "p_sign", "p_src", "p_dst"}
            spec = inj.device_corruption(
                self.state.n_cap, self.state.m_cap, available=available
            )
            if spec is not None:
                from ..runtime.integrity import apply_device_corruption

                apply_device_corruption(self.resident, spec)
                self.resident.rebind(problem)
        self.last_divergence = None
        self._export_count += 1
        if not self.audit_every or (self._export_count - 1) % self.audit_every:
            return problem
        from ..runtime.integrity import IntegrityError, StateAuditor

        if self.auditor is None or self.auditor.resident is not self.resident:
            self.auditor = StateAuditor(self.resident)
        # the solver's carried warm flow is solver-owned device state:
        # fingerprint it against the solver's host copy alongside the
        # mirror (a diverged warm carry escalates straight to
        # full_build below, whose backend.reset() drops it)
        from ..runtime.checkpoint import find_jax_solver

        jaxs = find_jax_solver(self.backend)
        warm_flow = warm_expected = None
        if jaxs is not None and jaxs._prev_dev is not None and jaxs._prev is not None:
            warm_flow, warm_expected = jaxs._prev_dev, jaxs._prev
        with span("state_audit"):
            diverged = self.auditor.audit(warm_flow, warm_expected)
        if not diverged:
            return problem
        self.last_divergence = list(diverged)
        self.integrity_counts["divergences"] += 1
        try:
            with span("state_repair", arrays=len(diverged)):
                rung = self.auditor.repair(diverged)
            self.integrity_counts[f"repair_{rung}"] += 1
            self.resident.rebind(problem)
            return problem
        except IntegrityError:
            pass
        # ladder exhausted on the mirror: rebuild the device state from
        # the host graph wholesale — the last repair rung before the
        # degradation ladder's NOOP round. full_build reassigns the
        # slot table, so warm solver state is dropped with it.
        self.integrity_counts["repair_full_build"] += 1
        with span("state_repair", kind="full_build"):
            gm = self.gm
            self.state.full_build(gm.cm.graph)
            gm.cm.reset_changes()
            self.backend.reset()
            self.state.set_excess(gm.sink_node.id, gm.sink_node.excess)
            problem = self.resident.refresh()
        if self.auditor is not None:
            self.auditor._m_repairs.labels(rung="full_build").inc()
        return problem

    def complete(self, token) -> TaskMapping:
        """Phase 2: synchronize the solve and decode the task mapping."""
        problem, task_node_ids, pending, is_async = token
        if is_async:
            try:
                with span("backend_solve", backend=type(self.backend).__name__) as sp:
                    result = self.backend.complete(pending)
                    # async dispatches bypass solve_traced; publish the
                    # solver-interior telemetry here instead (registry
                    # histograms + per-superstep child spans + stall
                    # detection — obs/soltel.py)
                    tel = getattr(self.backend, "last_telemetry", None)
                    if tel is not None:
                        from ..obs import soltel

                        soltel.publish(tel, sp)
            except BaseException:
                get_profiler().solve_failed()  # stop an Nth-solve capture
                raise
        else:
            result = pending
        self.last_result = result
        get_profiler().note_solve(self.backend, problem, result)
        gm = self.gm
        with span("decode", tasks=len(task_node_ids)):
            return flow_to_mapping(
                problem,
                result.total_flow(problem),
                gm.leaf_node_ids,
                gm.sink_node.id,
                task_node_ids,
            )

    def solve(self) -> TaskMapping:
        return self.complete(self.solve_async())
