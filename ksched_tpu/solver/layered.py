"""Dense layered MCMF: the TPU fast path for the aggregate topology.

The quincy-style scheduling graph the bulk scheduler builds
(scheduler/bulk.py; reference: trivial_cost_modeler.go:101-110 +
graph_manager.go:931-1010) is layered and aggregate:

    task --(u)--> unsched[job] --> sink
    task --(e)--> EC[class]
    EC[c] --(cost[c,m], cap free_m)--> machine_m --> PU --> sink

Tasks of one class are interchangeable (identical arc costs u and e for
every job — trivial_cost_modeler.go:41-43,69-74), the PU layer never
binds tighter than its machine (machine free capacity IS the sum of its
PU free capacities), and the per-job unscheduled aggregators always have
enough escape capacity. So the min-cost flow collapses EXACTLY to a
transportation problem over a dense [C, M+1] matrix:

    minimize    sum_{c,m} y[c,m] * w[c,m]
    subject to  sum_m y[c,m] == supply[c]          (every task routed)
                sum_c y[c,m] <= col_cap[m]         (machine free slots)

with w[c,m] = cost[c,m] + e - u for real machines and w[c,M] = 0 for the
"unscheduled" column (cap = total supply, so the problem is always
feasible — the unscheduled-aggregator invariant, graph_manager.go:
1270-1305). The full 10k-task solve becomes a ~[4, 1024] dense problem.

Why this is the TPU formulation: the general CSR push-relabel
(solver/jax_solver.py) is correct for arbitrary graphs but spends
milliseconds per superstep in random gathers — TPU serializes them.
Here every push/relabel superstep is ~20 fused dense ops on one
[C, M+1] tile (row/col reductions, axis cumsums, elementwise masks):
microseconds on the VPU, no gathers, no scatters, one compiled
executable reused across rounds.

The kernel is the same synchronous Goldberg-Tarjan cost-scaling
push-relabel as the general solver (costs pre-scaled so eps=1 is exact;
maximal pushes via in-row exclusive prefix sums; jump relabels), plus a
Bellman-Ford price-tightening prelude which is EXACT here (the residual
graph of the zero flow has diameter 2), so the eps=1 discharge follows
shortest paths from the start.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

#: sentinel magnitudes shared with parallel/sharded_transport.py — the
#: sharded solve's bit-identity contract depends on matching fills
BIG = 1 << 30
BIG_D = 1 << 28
_BIG = np.int32(BIG)
_BIG_D = np.int32(BIG_D)


def validate_job_unsched_cost(job_unsched_cost, num_jobs: int):
    """Normalize/validate the per-job unsched-cost knob (None passes
    through). One definition shared by BulkCluster, DeviceBulkCluster,
    and tests so the three call sites cannot drift."""
    if job_unsched_cost is None:
        return None
    out = np.asarray(job_unsched_cost, np.int64)  # kschedlint: host-only (host cost prep; overflow-guarded before the i32 cast)
    if out.shape != (num_jobs,):
        raise ValueError(
            f"job_unsched_cost must have shape ({num_jobs},), got {out.shape}"
        )
    # Values at or beyond COST_SCALE_LIMIT are guaranteed to overflow
    # once scaled — and the device path casts to int32 BEFORE its
    # in-graph guard, so an unchecked huge cost would silently wrap to
    # a strongly-negative escape instead of raising like the host path.
    if out.size and int(np.abs(out).max()) >= COST_SCALE_LIMIT:
        raise OverflowError(
            f"job_unsched_cost magnitude {int(np.abs(out).max())} exceeds "
            f"the scaled-cost limit {COST_SCALE_LIMIT}"
        )
    return out


def validate_alpha(alpha: int) -> int:
    """alpha < 2 would make the eps phase schedule a fixed point and
    hang the solve loop; one guard shared by every constructor that
    accepts the knob."""
    if alpha < 2:
        raise ValueError(f"alpha must be >= 2 (got {alpha}): the eps "
                         "phase schedule would never shrink")
    return int(alpha)


@dataclass
class LayeredProblem:
    """The aggregate scheduling round, in row-by-machine form. A row is
    a commodity of interchangeable tasks: a task class in the basic
    shape, or a (job, class) group when per-job unscheduled costs
    differentiate jobs (the reference's per-job unsched aggregators,
    graph_manager.go:1291-1305 — each job's escape arc has its own
    cost, so tasks of one class but different jobs are distinct
    commodities)."""

    supply: np.ndarray  # int32[C] unplaced live tasks per row
    col_cap: np.ndarray  # int32[M] free slots per machine
    cost_cm: np.ndarray  # int32[C, M] EC->machine arc cost per row
    unsched_cost: int  # u: task->unsched arc cost
    ec_cost: int  # e: task->EC arc cost
    #: optional per-row unsched costs overriding the scalar (int[C]);
    #: row r's escape then costs row_unsched_cost[r]
    row_unsched_cost: Optional[np.ndarray] = None


@dataclass
class LayeredResult:
    y: np.ndarray  # int64[C, M] tasks of class c placed on machine m
    num_unsched: int
    objective: int  # in full-graph units: u*unplaced + sum((e+cost)*y)
    supersteps: int


def _pad_to(x: np.ndarray, size: int, fill) -> np.ndarray:
    out = np.full(size, fill, dtype=x.dtype)
    out[: len(x)] = x
    return out


def pad_geometry(num_machines: int, num_classes: int) -> Tuple[int, int]:
    """(Mp, n_scale) for the padded transport problem — shared by the
    host path (solve_layered) and the device-resident path
    (scheduler/device_bulk.py) so the two cannot drift.

    Mp pads the machine axis to a lane-friendly multiple of 128 with
    room for the unsched column; n_scale is the cost multiplier that
    makes eps=1 termination exact: smallest pow2 > the REAL node count
    C + (M+1) + 1 (rows + live columns + sink). Padded columns have no
    arcs (cap 0), so residual cycles traverse only live nodes and the
    exactness bound is independent of Mp — deriving n_scale from Mp
    would inflate the scaled-cost range (and with it the price ground
    the eps=1 phase must cover, i.e. supersteps) by the pad factor; the
    mesh-sharded solver pads Mp to a multiple of 128*devices, where
    that inflation was measured at ~50x supersteps on small instances."""
    Mp = ((num_machines + 1 + 127) // 128) * 128
    n_scale = 1
    while n_scale < num_classes + num_machines + 3:
        n_scale <<= 1
    return Mp, n_scale


#: scaled costs must stay below 2^29 for int32 arithmetic headroom:
#: with |wS| < 2^29 and pm clamped to ±2^28 (transport_tighten), the
#: derived row prices satisfy |pr| <= 2^28 + 2^29, so any reduced cost
#: rcf = wS + pr - pm is bounded by 2^29 + (2^28 + 2^29) + 2^28 =
#: 1.5 * 2^30 < 2^31 - 1, wrap-free. (At 2^30 a worst-case pair of
#: near-limit arcs of opposite sign could overflow the guard.)
COST_SCALE_LIMIT = 1 << 29


def default_eps0(n_scale: int) -> int:
    """The tuned eps-schedule start for iterative transport solves:
    n_scale/4 — a quarter of one original cost unit. Valid for any
    value — tightened potentials make the zero flow 0-optimal
    regardless; callers keep a full-range fallback. One definition so
    the three solve sites cannot drift.

    Measured (round-3 tail study, tools/tail_repro.py on captured
    steady-state whare + coco tail rounds): deeply sub-quantum starts
    are the tail's CAUSE — at eps << one cost unit the synchronous
    maximal pushes circulate flow around admissible cycles whose total
    reduced cost sits between -len*eps and 0, with prices inching down
    one eps per failed push (traced: 7k steps with excess sloshing
    rows<->cols through 1-3 active columns and near-zero relabels).
    The old n_scale/16 start burned 2.5-7k supersteps per contended
    round; the superstep count is invariant to n_scale at a FIXED
    eps0/n_scale ratio (measured: 64x n_scale change, identical
    counts), so the ratio is the knob. The landscape is jagged and
    regime-dependent (whare tails prefer 1.0: mean 934; coco tails
    prefer 1/4: mean 419), but 1/4 has the best combined worst case —
    max 1756 supersteps over every captured tail instance vs 3270 for
    1.0 and 7136 for 1/16 — and is alpha-insensitive (a4 == a8 within
    noise). Objectives identical across all starts, as theory demands.

    Only correct for instances that are NOT oversubscribed: when total
    supply exceeds real machine capacity, prices must descend deep on
    the unsched column and the short start pays for the descent in
    eps-sized relabels (measured 1387 vs 284 supersteps on a 3x16 toy
    at 1.25x oversubscription). Use choose_eps0 where supply/capacity
    are at hand."""
    return max(1, n_scale // 4)


def choose_eps0(n_scale: int, eps_full, supply_total, real_cap_total,
                short=None):
    """Adaptive eps-schedule start: the tuned short start for the
    common regime (supply fits real machine capacity — steady-state
    backlogs vs free slots), the classic full-range start when the
    instance is oversubscribed. Works on Python ints or traced scalars
    (returns a traced scalar if any input is traced). `short` overrides
    the default_eps0 start for regimes with their own tuning (the
    grouped locality solve uses n_scale — see device_bulk)."""
    if short is None:
        short = default_eps0(n_scale)
    if isinstance(supply_total, (int, np.integer)) and isinstance(
        real_cap_total, (int, np.integer)
    ):
        return eps_full if supply_total > real_cap_total else short
    return jnp.where(
        supply_total > real_cap_total,
        jnp.int32(eps_full),
        jnp.int32(short),
    )


def _excesses(supply, y, z):
    e_row = supply - jnp.sum(y, axis=1)
    e_col = jnp.sum(y, axis=0) - z
    e_sink = jnp.sum(z) - jnp.sum(supply)
    return e_row, e_col, e_sink


def transport_tighten(wS, U, col_cap, pm0=None):
    """Potentials making the ZERO flow 0-optimal, from optional carried
    machine prices pm0 (warm start across rounds).

    pm = pm0 on live columns (cap>0), sunk for dead ones; row prices are
    re-derived as pr[c] = max_{U>0}(pm - wS) so every forward residual
    arc has reduced cost >= 0, and psink = min_{cap>0} pm likewise. Any
    pm0 is VALID (it is clamped to ±_BIG_D, then optimality of the
    start point is re-established by construction — without the clamp a
    price vector carried over many rounds drifts monotonically negative
    until pm0 - wS wraps int32) — a good pm0 just makes the discharge
    shorter. With pm0 = None/zeros this reduces exactly to shortest
    residual-cost distances for the zero flow (the all-forward residual
    graph has diameter 2), i.e. the cold start."""
    i32 = jnp.int32
    big_d = jnp.int32(_BIG_D)
    if pm0 is None:
        pm0 = jnp.zeros_like(col_cap)
    live = col_cap > 0
    pm = jnp.where(live, jnp.clip(pm0, -big_d, big_d), -big_d)
    has_arc = U > 0
    pr = jnp.max(jnp.where(has_arc, pm[None, :] - wS, -big_d), axis=1)
    pr = jnp.where(jnp.any(has_arc, axis=1), pr, i32(0))
    psink = jnp.min(jnp.where(live, pm, big_d))
    psink = jnp.where(jnp.any(live), psink, i32(0))
    return pr, pm, psink


def transport_saturate(wS, U, col_cap, y, z, pr, pm, psink):
    i32 = jnp.int32
    rcf = wS + pr[:, None] - pm[None, :]
    y2 = jnp.where(rcf < 0, U, jnp.where(rcf > 0, i32(0), y))
    rcs = pm - psink
    z2 = jnp.where(rcs < 0, col_cap, jnp.where(rcs > 0, i32(0), z))
    return y2, z2


def transport_saturate_eps(wS, U, col_cap, y, z, pr, pm, psink, eps):
    """Partial saturate: reset ONLY the arcs violating eps-optimality
    (|reduced cost| beyond eps on a residual direction), keeping the
    rest of the flow. With eps=0 this is transport_saturate. Used with
    price refinement, where most of the converged flow already
    satisfies the next phase's eps and re-flooding it would re-fight
    every contended column from scratch."""
    i32 = jnp.int32
    rcf = wS + pr[:, None] - pm[None, :]
    y2 = jnp.where(rcf < -eps, U, jnp.where(rcf > eps, i32(0), y))
    rcs = pm - psink
    z2 = jnp.where(rcs < -eps, col_cap, jnp.where(rcs > eps, i32(0), z))
    return y2, z2


def _price_refine(wS, U, col_cap, y, z, pr, pm, psink, eps, waves: int):
    """Price refinement (the classic cost-scaling speedup, cf. CS2's
    price updates): `waves` synchronous Bellman-Ford relaxations that
    LOWER potentials toward eps-optimality of the CURRENT flow before
    the next phase. Each eps-optimality constraint has the form
    potential <= other + slack over a residual arc; relaxing monotonely
    downward converges in graph-diameter waves on this shallow layered
    structure. The wave count is bounded (a residual cycle more
    negative than the slack would otherwise descend forever — possible
    while eps shrinks); whatever violations remain are cleaned by
    transport_saturate_eps, so optimality never depends on the refit
    finishing."""
    big = jnp.int32(_BIG)
    big_d = jnp.int32(_BIG_D)

    def body(_, state):
        pr, pm, psink = state
        # fwd residual row->col (U-y>0): pm <= wS + pr + eps
        bound_m = jnp.min(
            jnp.where(U - y > 0, wS + pr[:, None] + eps, big), axis=0
        )
        pm2 = jnp.maximum(jnp.minimum(pm, bound_m), -big_d)
        # sink->col residual (z>0): pm <= psink + eps
        pm2 = jnp.minimum(pm2, jnp.where(z > 0, psink + eps, big))
        # bwd residual col->row (y>0): pr <= pm - wS + eps
        bound_r = jnp.min(
            jnp.where(y > 0, pm2[None, :] - wS + eps, big), axis=1
        )
        pr2 = jnp.maximum(jnp.minimum(pr, bound_r), -big_d)
        # col->sink residual (cap-z>0): psink <= pm + eps
        bound_s = jnp.min(jnp.where(col_cap - z > 0, pm2 + eps, big))
        psink2 = jnp.maximum(jnp.minimum(psink, bound_s), -big_d)
        return pr2, pm2, psink2

    return lax.fori_loop(0, waves, body, (pr, pm, psink))


def transport_superstep(wS, U, supply, col_cap, y, z, pr, pm, psink, eps,
                        with_stats: bool = False):
    """One synchronous push/relabel wave over the dense bipartite
    residual graph. A fixed point once no node has positive excess, so
    it is safe to run under a fixed trip count (lax.fori_loop).

    with_stats=True additionally returns the soltel counter tuple
    (pushed, relabels, saturated, work) computed from this wave's own
    intermediates (obs/soltel.py cols 3..6) — observational only,
    never fed back, so flows are bit-identical either way."""
    i32 = jnp.int32
    big = jnp.int32(_BIG)
    e_row, e_col, e_sink = _excesses(supply, y, z)
    rcf = wS + pr[:, None] - pm[None, :]

    # --- rows push forward along admissible arcs (maximal push via
    # in-row exclusive prefix sums) ---
    r_fwd = U - y
    adm_f = (r_fwd > 0) & (rcf < 0)
    r_adm = jnp.where(adm_f, r_fwd, i32(0))
    excl = jnp.cumsum(r_adm, axis=1) - r_adm
    delta_f = jnp.clip(e_row[:, None] - excl, 0, r_adm)

    # --- columns push: entry 0 = col->sink, entries 1..C = backward
    # col->row (returning flow) ---
    r_s = col_cap - z
    rc_s = pm - psink
    r_b = y  # backward residual col->row
    rc_b = pm[None, :] - pr[:, None] - wS  # cost of bwd arc is -wS
    colA = jnp.concatenate(
        [
            jnp.where((r_s > 0) & (rc_s < 0), r_s, i32(0))[None, :],
            jnp.where((r_b > 0) & (rc_b < 0), r_b, i32(0)),
        ],
        axis=0,
    )  # [1+C, Mp1], allocation order: sink first, then rows
    exclA = jnp.cumsum(colA, axis=0) - colA
    deltaA = jnp.clip(e_col[None, :] - exclA, 0, colA)
    delta_s = deltaA[0]
    delta_b = deltaA[1:]

    # --- sink pushes back (transient positive excess after a
    # saturate): backward sink->col arcs, residual z, cost 0 ---
    r_zb = z
    rc_zb = psink - pm
    zb_adm = jnp.where((r_zb > 0) & (rc_zb < 0), r_zb, i32(0))
    excl_zb = jnp.cumsum(zb_adm) - zb_adm
    delta_zb = jnp.clip(e_sink - excl_zb, 0, zb_adm)

    y2 = y + delta_f - delta_b
    z2 = z + delta_s - delta_zb

    # --- jump relabels for active nodes that pushed nothing ---
    pushed_row = jnp.sum(delta_f, axis=1)
    cand_row = jnp.where(r_fwd > 0, pm[None, :] - wS, -big)
    best_row = jnp.max(cand_row, axis=1)
    relabel_row = (e_row > 0) & (pushed_row == 0)
    pr2 = jnp.where(relabel_row, best_row - eps, pr)

    pushed_col = delta_s + jnp.sum(delta_b, axis=0)
    cand_col = jnp.maximum(
        jnp.max(jnp.where(y > 0, pr[:, None] + wS, -big), axis=0),
        jnp.where(r_s > 0, psink, -big),
    )
    relabel_col = (e_col > 0) & (pushed_col == 0)
    pm2 = jnp.where(relabel_col, cand_col - eps, pm)

    pushed_sink = jnp.sum(delta_zb)
    cand_sink = jnp.max(jnp.where(z > 0, pm, -big))
    relabel_sink = (e_sink > 0) & (pushed_sink == 0)
    psink2 = jnp.where(relabel_sink, cand_sink - eps, psink)
    if not with_stats:
        return y2, z2, pr2, pm2, psink2
    stats = (
        jnp.sum(delta_f) + jnp.sum(deltaA) + jnp.sum(delta_zb),
        jnp.sum(relabel_row.astype(i32))
        + jnp.sum(relabel_col.astype(i32))
        + relabel_sink.astype(i32),
        jnp.sum(((U > 0) & (y >= U)).astype(i32))
        + jnp.sum(((col_cap > 0) & (z >= col_cap)).astype(i32)),
        jnp.sum((r_adm > 0).astype(i32))
        + jnp.sum((colA > 0).astype(i32))
        + jnp.sum((zb_adm > 0).astype(i32)),
    )
    return y2, z2, pr2, pm2, psink2, stats


# ---------------------------------------------------------------------------
# Tiered (continuation-priced) transport: the preemption-on formulation
# ---------------------------------------------------------------------------
#
# With preemption on (graph_manager.go:855-888), placed tasks re-enter
# every round's solve: machine capacity is total slots (the capacity
# rule flips, :662-667) and a task's CURRENT machine offers a cheaper
# "continuation" price than a fresh placement (TaskContinuationCost vs
# TaskToResourceNodeCost, costmodel/interface.go:75-79). In aggregate
# form each cell (row g, machine m) prices its first R[g,m] units (the
# residents) at wLo = w - discount and the rest at w. A per-cell convex
# two-tier cost is exactly a pair of parallel arcs, so cost-scaling
# push-relabel remains exact: every residual/relabel rule below is the
# parallel-arc rule with the canonical cheapest-first split
# yA = min(y, R), yB = y - yA.


def transport_saturate_tiered(wLo, wHi, R, U, col_cap, y, z, pr, pm, psink):
    """Phase-start saturation, per tier (wLo <= wHi cellwise, so a
    saturated cheap tier is implied by a saturated dear one)."""
    i32 = jnp.int32
    rcl = wLo + pr[:, None] - pm[None, :]
    rch = wHi + pr[:, None] - pm[None, :]
    yA = jnp.minimum(y, R)
    yB = y - yA
    yA2 = jnp.where(rcl < 0, R, jnp.where(rcl > 0, i32(0), yA))
    yB2 = jnp.where(rch < 0, U - R, jnp.where(rch > 0, i32(0), yB))
    rcs = pm - psink
    z2 = jnp.where(rcs < 0, col_cap, jnp.where(rcs > 0, i32(0), z))
    return yA2 + yB2, z2


def transport_saturate_eps_tiered(
    wLo, wHi, R, U, col_cap, y, z, pr, pm, psink, eps
):
    """Tiered twin of transport_saturate_eps: reset ONLY tiers whose
    reduced cost violates eps-optimality, keeping the rest of the
    carried flow (price-refinement phase starts)."""
    i32 = jnp.int32
    rcl = wLo + pr[:, None] - pm[None, :]
    rch = wHi + pr[:, None] - pm[None, :]
    yA = jnp.minimum(y, R)
    yB = y - yA
    yA2 = jnp.where(rcl < -eps, R, jnp.where(rcl > eps, i32(0), yA))
    yB2 = jnp.where(rch < -eps, U - R, jnp.where(rch > eps, i32(0), yB))
    rcs = pm - psink
    z2 = jnp.where(rcs < -eps, col_cap, jnp.where(rcs > eps, i32(0), z))
    return yA2 + yB2, z2


def _price_refine_tiered(
    wLo, wHi, R, U, col_cap, y, z, pr, pm, psink, eps, waves: int
):
    """Tiered twin of _price_refine: synchronous Bellman-Ford
    relaxations lowering potentials toward eps-optimality of the
    CURRENT flow, with each tier's residuals contributing its own
    constraints (fwd tier A at wLo while R-yA>0, fwd tier B at wHi
    while (U-R)-yB>0; bwd with the signs flipped)."""
    big = jnp.int32(_BIG)
    big_d = jnp.int32(_BIG_D)

    def body(_, state):
        pr, pm, psink = state
        yA = jnp.minimum(y, R)
        yB = y - yA
        bound_m = jnp.minimum(
            jnp.min(jnp.where(R - yA > 0, wLo + pr[:, None] + eps, big),
                    axis=0),
            jnp.min(jnp.where((U - R) - yB > 0, wHi + pr[:, None] + eps, big),
                    axis=0),
        )
        pm2 = jnp.maximum(jnp.minimum(pm, bound_m), -big_d)
        pm2 = jnp.minimum(pm2, jnp.where(z > 0, psink + eps, big))
        bound_r = jnp.minimum(
            jnp.min(jnp.where(yA > 0, pm2[None, :] - wLo + eps, big), axis=1),
            jnp.min(jnp.where(yB > 0, pm2[None, :] - wHi + eps, big), axis=1),
        )
        pr2 = jnp.maximum(jnp.minimum(pr, bound_r), -big_d)
        bound_s = jnp.min(jnp.where(col_cap - z > 0, pm2 + eps, big))
        psink2 = jnp.maximum(jnp.minimum(psink, bound_s), -big_d)
        return pr2, pm2, psink2

    return lax.fori_loop(0, waves, body, (pr, pm, psink))


def transport_superstep_tiered(
    wLo, wHi, R, U, supply, col_cap, y, z, pr, pm, psink, eps
):
    """One synchronous push/relabel wave over the two-tier residual
    graph. Identical structure to transport_superstep, with forward and
    backward residuals split by tier (cheap tier fills first, dear tier
    empties first — the canonical split of a convex arc cost)."""
    i32 = jnp.int32
    big = jnp.int32(_BIG)
    e_row, e_col, e_sink = _excesses(supply, y, z)
    yA = jnp.minimum(y, R)
    yB = y - yA
    rcl = wLo + pr[:, None] - pm[None, :]
    rch = wHi + pr[:, None] - pm[None, :]

    # --- rows push forward: tier-A residual at wLo, tier-B at wHi ---
    rA = R - yA
    rB = (U - R) - yB
    r_adm = jnp.where((rA > 0) & (rcl < 0), rA, i32(0)) + jnp.where(
        (rB > 0) & (rch < 0), rB, i32(0)
    )
    excl = jnp.cumsum(r_adm, axis=1) - r_adm
    delta_f = jnp.clip(e_row[:, None] - excl, 0, r_adm)

    # --- columns push: sink first, then dear-tier returns, then cheap ---
    r_s = col_cap - z
    rc_s = pm - psink
    rcb_hi = pm[None, :] - pr[:, None] - wHi  # backward tier B (cost -wHi)
    rcb_lo = pm[None, :] - pr[:, None] - wLo  # backward tier A
    colA = jnp.concatenate(
        [
            jnp.where((r_s > 0) & (rc_s < 0), r_s, i32(0))[None, :],
            jnp.where((yB > 0) & (rcb_hi < 0), yB, i32(0)),
            jnp.where((yA > 0) & (rcb_lo < 0), yA, i32(0)),
        ],
        axis=0,
    )  # [1 + 2C, Mp1]
    C = y.shape[0]
    exclA = jnp.cumsum(colA, axis=0) - colA
    deltaA = jnp.clip(e_col[None, :] - exclA, 0, colA)
    delta_s = deltaA[0]
    delta_b = deltaA[1 : 1 + C] + deltaA[1 + C :]

    # --- sink pushes back (tier-less, as before) ---
    r_zb = z
    rc_zb = psink - pm
    zb_adm = jnp.where((r_zb > 0) & (rc_zb < 0), r_zb, i32(0))
    excl_zb = jnp.cumsum(zb_adm) - zb_adm
    delta_zb = jnp.clip(e_sink - excl_zb, 0, zb_adm)

    y2 = y + delta_f - delta_b
    z2 = z + delta_s - delta_zb

    # --- jump relabels (candidates consider both tiers' residuals) ---
    pushed_row = jnp.sum(delta_f, axis=1)
    cand_row = jnp.maximum(
        jnp.max(jnp.where(rA > 0, pm[None, :] - wLo, -big), axis=1),
        jnp.max(jnp.where(rB > 0, pm[None, :] - wHi, -big), axis=1),
    )
    relabel_row = (e_row > 0) & (pushed_row == 0)
    pr2 = jnp.where(relabel_row, cand_row - eps, pr)

    pushed_col = delta_s + jnp.sum(delta_b, axis=0)
    cand_col = jnp.maximum(
        jnp.maximum(
            jnp.max(jnp.where(yA > 0, pr[:, None] + wLo, -big), axis=0),
            jnp.max(jnp.where(yB > 0, pr[:, None] + wHi, -big), axis=0),
        ),
        jnp.where(r_s > 0, psink, -big),
    )
    relabel_col = (e_col > 0) & (pushed_col == 0)
    pm2 = jnp.where(relabel_col, cand_col - eps, pm)

    pushed_sink = jnp.sum(delta_zb)
    cand_sink = jnp.max(jnp.where(z > 0, pm, -big))
    relabel_sink = (e_sink > 0) & (pushed_sink == 0)
    psink2 = jnp.where(relabel_sink, cand_sink - eps, psink)
    return y2, z2, pr2, pm2, psink2


def _transport_loop_tiered(wLo, wHi, R, U, supply, col_cap, eps_init, alpha,
                           max_supersteps, refine_waves: int = 0):
    """Tiered twin of _transport_loop (cold start: tightening against
    the cheap tier makes the zero flow 0-optimal, since wLo <= wHi).
    refine_waves enables the tiered price refinement between phases —
    measured essential at scale (the preemption-on 50k round burned
    31-58k supersteps/round without it)."""
    i32 = jnp.int32

    def phase_cond(state):
        *_rest, steps, done = state
        return ~done & (steps < max_supersteps)

    def phase_body(state):
        y, z, pr, pm, psink, eps, steps, done = state
        e_row, e_col, e_sink = _excesses(supply, y, z)
        any_active = jnp.any(e_row > 0) | jnp.any(e_col > 0) | (e_sink > 0)

        def do_step(_):
            y2, z2, pr2, pm2, psink2 = transport_superstep_tiered(
                wLo, wHi, R, U, supply, col_cap, y, z, pr, pm, psink, eps
            )
            return y2, z2, pr2, pm2, psink2, eps, steps + 1, jnp.bool_(False)

        def next_phase(_):
            finished = eps <= 1
            new_eps = jnp.maximum(i32(1), eps // alpha)
            if refine_waves:
                pr2, pm2, psink2 = _price_refine_tiered(
                    wLo, wHi, R, U, col_cap, y, z, pr, pm, psink, new_eps,
                    refine_waves,
                )
                y2, z2 = transport_saturate_eps_tiered(
                    wLo, wHi, R, U, col_cap, y, z, pr2, pm2, psink2, new_eps
                )
            else:
                pr2, pm2, psink2 = pr, pm, psink
                y2, z2 = transport_saturate_tiered(
                    wLo, wHi, R, U, col_cap, y, z, pr, pm, psink
                )
            return (
                jnp.where(finished, y, y2),
                jnp.where(finished, z, z2),
                jnp.where(finished, pr, pr2),
                jnp.where(finished, pm, pm2),
                jnp.where(finished, psink, psink2),
                jnp.where(finished, eps, new_eps),
                steps,
                finished,
            )

        return lax.cond(any_active, do_step, next_phase, operand=None)

    C, Mp1 = wLo.shape
    pr0, pm0, psink0 = transport_tighten(wLo, U, col_cap, None)
    y0 = jnp.zeros((C, Mp1), jnp.int32)
    z0 = jnp.zeros((Mp1,), jnp.int32)
    state = (y0, z0, pr0, pm0, psink0, eps_init, jnp.int32(0), jnp.bool_(False))
    y, z, pr, pm, psink, eps, steps, done = lax.while_loop(
        phase_cond, phase_body, state
    )
    e_row, e_col, e_sink = _excesses(supply, y, z)
    max_abs = jnp.maximum(
        jnp.max(jnp.abs(e_row)), jnp.maximum(jnp.max(jnp.abs(e_col)), jnp.abs(e_sink))
    )
    return y, z, pm, steps, done & (max_abs == 0)


def solve_single_class_tiered(wLo, wHi, R, supply, col_cap):
    """EXACT closed form for one tiered row: expand each column into a
    cheap tier (cap min(R, col_cap), cost wLo) and a base tier (the
    rest at wHi), then greedy-fill strictly-profitable capacity by
    sorted marginal cost — valid because the per-cell cost is convex
    (the cheap tier always fills first). Returns y int32[Mp1] (tier
    totals per column)."""
    i32 = jnp.int32
    Mp1 = wLo.shape[0]
    Reff = jnp.minimum(R, col_cap)
    w2 = jnp.concatenate([wLo, wHi])
    cap2 = jnp.concatenate([Reff, col_cap - Reff])
    take = jnp.where(w2 < 0, cap2, i32(0))
    order = jnp.argsort(w2)
    take_s = take[order]
    excl = jnp.cumsum(take_s) - take_s
    y_s = jnp.clip(supply - excl, 0, take_s)
    inv = jnp.argsort(order)
    y2 = y_s[inv]
    return y2[:Mp1] + y2[Mp1:]


def _solve_transport_tiered(wLo, wHi, R, supply, col_cap, eps_init,
                            alpha: int = 8, max_supersteps: int = 20_000,
                            refine_waves: int = 0):
    """XLA form of the tiered solve behind ops.transport_solve_tiered
    (the fused kernel is ops/transport_pallas.py
    transport_loop_pallas_tiered — bit-identical)."""
    R = jnp.minimum(R, jnp.minimum(supply[:, None], col_cap[None, :]))
    U = jnp.minimum(supply[:, None], col_cap[None, :])
    y, _z, pm, steps, conv = _transport_loop_tiered(
        wLo, wHi, R, U, supply, col_cap, eps_init, alpha, max_supersteps,
        refine_waves=refine_waves,
    )
    return y, pm, steps, conv


def transport_fori_tiered(wLo, wHi, R, supply, col_cap, num_supersteps: int,
                          alpha: int = 8, eps0: Optional[int] = None,
                          refine_waves: int = 0):
    """Bounded tiered transport solve, embeddable in jitted programs —
    the preemption-on twin of transport_fori. Dispatches through
    ops.transport_solve_tiered: the fused tiered Pallas kernel on TPU
    (~a handful of us/superstep, VMEM-resident), the XLA phase loop
    elsewhere — bit-identical either way. Single-row instances take
    the exact closed form. Returns (y, pm, steps, converged)."""
    C, Mp1 = wLo.shape
    i32 = jnp.int32
    if C == 1:
        R1 = jnp.minimum(
            R, jnp.minimum(supply[:, None], col_cap[None, :])
        )
        y = solve_single_class_tiered(
            wLo[0], wHi[0], R1[0], supply[0], col_cap
        )
        return y[None, :], jnp.zeros_like(col_cap), i32(0), jnp.bool_(True)

    from ..ops import transport_solve_tiered

    eps_full = jnp.maximum(jnp.max(jnp.abs(wHi)), i32(1))

    def run(eps_init):
        return transport_solve_tiered(
            wLo, wHi, R, supply, col_cap, eps_init,
            alpha=alpha, max_supersteps=num_supersteps,
            refine_waves=refine_waves,
        )

    if eps0 is None:
        return run(eps_full)
    y1, pm1, s1, conv1 = run(i32(eps0))

    def keep(_):
        return y1, pm1, s1, conv1

    def retry(_):
        y2, pm2, s2, conv2 = run(eps_full)
        return y2, pm2, s1 + s2, conv2

    # plain `conv1` on purpose — see the note in transport_fori: the
    # skip-identical-retry gate form crashes the tunneled TPU runtime
    return lax.cond(conv1, keep, retry, operand=None)


def solve_row_constant(v, supply, col_cap):
    """EXACT closed form when every row's cost is machine-uniform:
    w[g, m] = v[g] for all real columns m (the per-job-unsched shape
    with no class cost model — each (job, class) row's shifted cost is
    e - u_job everywhere). The objective sum_g v_g * placed_g is linear
    in per-row placement totals, so the optimum is the fractional-
    knapsack greedy: rows in ascending v (most profitable first), rows
    with v >= 0 place nothing (ties at 0 left unscheduled, matching
    solve_single_class), machine split arbitrary — assigned in
    (row-order, machine-order) interval overlaps, mirroring
    split_grants_by_class. Generalizes the class-degenerate collapse
    (all rows equal) to rows equal only WITHIN themselves; the
    iterative solve herds pathologically on such instances (a trivially
    easy 12.5k-machine per-job instance blew a 20k-superstep budget —
    docs/NOTES.md).

    v int32[G]; supply int32[G]; col_cap int32[Mp1] (last = escape).
    Returns y int32[G, Mp1] with the escape column filled.
    """
    i32 = jnp.int32
    cap_real = col_cap[:-1]
    cap_total = jnp.sum(cap_real)
    order = jnp.argsort(v)
    v_s = v[order]
    sup_s = supply[order]
    take_s = jnp.where(v_s < 0, sup_s, i32(0))
    excl = jnp.cumsum(take_s) - take_s
    q_s = jnp.clip(cap_total - excl, 0, take_s)  # placed per sorted row
    Q = jnp.cumsum(q_s)
    starts = Q - q_s
    cum_m = jnp.cumsum(cap_real)
    lo = jnp.maximum((cum_m - cap_real)[None, :], starts[:, None])
    hi = jnp.minimum(cum_m[None, :], Q[:, None])
    y_s = jnp.maximum(hi - lo, 0).astype(i32)  # [G, M] sorted rows
    inv = jnp.argsort(order)
    y_real = y_s[inv]
    esc = (supply - jnp.sum(y_real, axis=1)).astype(i32)
    return jnp.concatenate([y_real, esc[:, None]], axis=1)


def solve_row_constant_np(v, supply, col_cap):
    """Host (numpy) twin of solve_row_constant."""
    cap_real = col_cap[:-1].astype(np.int64)  # kschedlint: host-only (host greedy decode)
    cap_total = int(cap_real.sum())
    order = np.argsort(v, kind="stable")
    sup_s = supply[order].astype(np.int64)  # kschedlint: host-only (host greedy decode)
    take_s = np.where(v[order] < 0, sup_s, 0)
    excl = np.cumsum(take_s) - take_s
    q_s = np.clip(cap_total - excl, 0, take_s)
    Q = np.cumsum(q_s)
    starts = Q - q_s
    cum_m = np.cumsum(cap_real)
    lo = np.maximum((cum_m - cap_real)[None, :], starts[:, None])
    hi = np.minimum(cum_m[None, :], Q[:, None])
    y_s = np.maximum(hi - lo, 0)
    y_real = np.empty_like(y_s)
    y_real[order] = y_s
    esc = supply.astype(np.int64) - y_real.sum(axis=1)  # kschedlint: host-only (host greedy decode)
    return np.concatenate([y_real, esc[:, None]], axis=1)


def solve_single_class(w, supply, col_cap):
    """EXACT closed form for the C=1 transportation row (the trivial
    cost model's shape, and the Google-trace / quincy-base shape): sort
    columns by cost and greedily fill strictly-profitable capacity.

    Exchange argument: any optimal solution places exactly
    min(supply, sum of capacity at w<0) units, on the cheapest such
    capacity; ties at w==0 are objective-neutral (left unscheduled).
    One sort + one cumsum — no iterations, no convergence concerns.

    w, col_cap: int32[Mp1]; returns y int32[Mp1].
    """
    i32 = jnp.int32
    take = jnp.where(w < 0, col_cap, i32(0))
    order = jnp.argsort(w)
    take_s = take[order]
    excl = jnp.cumsum(take_s) - take_s
    y_s = jnp.clip(supply - excl, 0, take_s)
    inv = jnp.argsort(order)
    return y_s[inv]


def solve_single_class_np(w: np.ndarray, supply: int, col_cap: np.ndarray) -> np.ndarray:
    """Host (numpy) twin of solve_single_class."""
    take = np.where(w < 0, col_cap, 0).astype(np.int64)  # kschedlint: host-only (host closed-form decode)
    order = np.argsort(w, kind="stable")
    take_s = take[order]
    excl = np.cumsum(take_s) - take_s
    y_s = np.clip(supply - excl, 0, take_s)
    y = np.empty_like(y_s)
    y[order] = y_s
    return y


def split_grants_by_class(y_tot, supply):
    """Split single-class machine grants y_tot[Mp] among C classes with
    per-class supplies [C] — any split is cost-equal when every class
    has the same cost row (the class-degenerate case), so grant units
    are handed out in (machine-order, class-order): y[c,m] = overlap of
    the class's supply interval with the machine's grant interval.
    Works on numpy or jnp arrays (pure elementwise/broadcast math)."""
    xp = np if isinstance(y_tot, np.ndarray) else jnp
    cum_s = xp.cumsum(supply)
    excl_s = (cum_s - supply)[:, None]  # [C, 1] class interval starts
    cum_m = xp.cumsum(y_tot)[None, :]  # [1, Mp] machine interval ends
    lo = xp.maximum(cum_m - y_tot[None, :], excl_s)
    hi = xp.minimum(cum_m, excl_s + supply[:, None])
    return xp.maximum(hi - lo, 0).astype(y_tot.dtype)


def _transport_loop(wS, U, supply, col_cap, eps_init, alpha, max_supersteps,
                    pm_init=None, refine_waves: int = 0,
                    telemetry_cap: int = 0):
    """The cost-scaling phase schedule as a bounded lax.while_loop:
    each iteration either runs a superstep (while active nodes exist)
    or advances the eps phase; exits as soon as the eps=1 phase drains
    (early exit matters — a converged multi-class solve typically takes
    tens of supersteps against a bound of thousands). Legal inside jit
    and inside lax.scan bodies. pm_init optionally warm-starts the
    machine prices (see transport_tighten). Returns
    (y, z, pm, steps, converged) — pm is the final machine-price vector,
    for carrying into the next round. telemetry_cap > 0 appends the
    superstep-indexed soltel ring (obs/soltel.py) to the returned
    tuple; cap=0 traces the exact pre-telemetry jaxpr."""
    from ..obs.soltel import SOLTEL_WIDTH

    i32 = jnp.int32

    def phase_cond(state):
        steps, done = state[6], state[7]
        return ~done & (steps < max_supersteps)

    if telemetry_cap:
        from ..obs import soltel as _soltel

        _tel_rows_iota = _soltel.device_rows_iota(telemetry_cap)

    def tel_row(eps, e_row, e_col, e_sink, stats):
        active = (
            jnp.sum((e_row > 0).astype(i32))
            + jnp.sum((e_col > 0).astype(i32))
            + (e_sink > 0).astype(i32)
        )
        exc_pos = (
            jnp.sum(jnp.maximum(e_row, 0))
            + jnp.sum(jnp.maximum(e_col, 0))
            + jnp.maximum(e_sink, 0)
        )
        return _soltel.device_row(eps, active, exc_pos, *stats)

    def tel_write(tel, steps, row):
        return _soltel.device_ring_write(
            tel, steps, row, telemetry_cap, _tel_rows_iota
        )

    def phase_body(state):
        if telemetry_cap:
            y, z, pr, pm, psink, eps, steps, done, tel = state
        else:
            y, z, pr, pm, psink, eps, steps, done = state
        e_row, e_col, e_sink = _excesses(supply, y, z)
        any_active = jnp.any(e_row > 0) | jnp.any(e_col > 0) | (e_sink > 0)

        def do_step(_):
            out = transport_superstep(
                wS, U, supply, col_cap, y, z, pr, pm, psink, eps,
                with_stats=bool(telemetry_cap),
            )
            if not telemetry_cap:
                y2, z2, pr2, pm2, psink2 = out
                return y2, z2, pr2, pm2, psink2, eps, steps + 1, jnp.bool_(False)
            y2, z2, pr2, pm2, psink2, stats = out
            tel2 = tel_write(
                tel, steps, tel_row(eps, e_row, e_col, e_sink, stats)
            )
            return (
                y2, z2, pr2, pm2, psink2, eps, steps + 1, jnp.bool_(False),
                tel2,
            )

        def next_phase(_):
            finished = eps <= 1
            new_eps = jnp.maximum(i32(1), eps // alpha)
            if refine_waves:
                # price refinement: tighten potentials for the CURRENT
                # converged flow at the next eps, then reset only the
                # arcs still violating it — instead of re-flooding
                # every negative arc and re-fighting each contended
                # column from scratch every phase.
                pr2, pm2, psink2 = _price_refine(
                    wS, U, col_cap, y, z, pr, pm, psink, new_eps,
                    refine_waves,
                )
                y2, z2 = transport_saturate_eps(
                    wS, U, col_cap, y, z, pr2, pm2, psink2, new_eps
                )
            else:
                pr2, pm2, psink2 = pr, pm, psink
                y2, z2 = transport_saturate(
                    wS, U, col_cap, y, z, pr, pm, psink
                )
            out = (
                jnp.where(finished, y, y2),
                jnp.where(finished, z, z2),
                jnp.where(finished, pr, pr2),
                jnp.where(finished, pm, pm2),
                jnp.where(finished, psink, psink2),
                jnp.where(finished, eps, new_eps),
                steps,
                finished,
            )
            return out + ((tel,) if telemetry_cap else ())

        return lax.cond(any_active, do_step, next_phase, operand=None)

    C, Mp1 = wS.shape
    pr0, pm0, psink0 = transport_tighten(wS, U, col_cap, pm_init)
    y0 = jnp.zeros((C, Mp1), i32)
    z0 = jnp.zeros((Mp1,), i32)
    state = (y0, z0, pr0, pm0, psink0, eps_init, i32(0), jnp.bool_(False))
    if telemetry_cap:
        state = state + (jnp.zeros((telemetry_cap, SOLTEL_WIDTH), i32),)
        y, z, pr, pm, psink, eps, steps, done, tel = lax.while_loop(
            phase_cond, phase_body, state
        )
    else:
        y, z, pr, pm, psink, eps, steps, done = lax.while_loop(
            phase_cond, phase_body, state
        )
    e_row, e_col, e_sink = _excesses(supply, y, z)
    max_abs = jnp.maximum(
        jnp.max(jnp.abs(e_row)), jnp.maximum(jnp.max(jnp.abs(e_col)), jnp.abs(e_sink))
    )
    base = (y, z, pm, steps, done & (max_abs == 0))
    if telemetry_cap:
        return base + (tel,)
    return base


def transport_fori(wS, supply, col_cap, num_supersteps: int, alpha: int = 8,
                   eps0: Optional[int] = None, class_degenerate: bool = False,
                   pm0=None, eps0_budget: Optional[int] = None,
                   refine_waves: int = 0, eps0_retry: bool = True):
    """Bounded transport solve, embeddable in larger jitted programs.

    C == 1: the exact closed form (solve_single_class) — O(sort(M)).
    C >= 2: the cost-scaling phase schedule, exiting as soon as it
    converges, bounded by num_supersteps — as the fused Pallas kernel
    (ops/transport_pallas.py, one kernel launch with all state in VMEM)
    when the ambient backend is TPU, else the XLA `_transport_loop`.

    eps0: optional static eps-schedule start. Passing the problem's
    n_scale (one original cost unit) cuts supersteps ~20x on contended
    instances — valid for any start since tightened potentials make the
    zero flow 0-optimal; if the short schedule stalls within the budget,
    an in-graph lax.cond falls back to the full range, so convergence
    never regresses.

    class_degenerate: static flag asserting every class has the SAME
    cost row (e.g. no class cost model wired in). Classes are then
    interchangeable and the iterative multi-class solve — which herds
    badly on identical costs (all classes chase the same columns in
    lockstep) — collapses to the exact C=1 closed form plus an
    arbitrary-but-feasible split of grants among classes.

    pm0: optional carried machine prices [Mp1] (previous round's pm)
    warm-starting the solve; any value is valid, a near-optimal one
    makes the discharge a handful of supersteps.

    Returns (y, pm, steps, converged) — pm is the final machine-price
    vector and steps the executed superstep count (both zero on the
    closed-form paths, where no iterations run).
    """
    C, Mp1 = wS.shape
    i32 = jnp.int32
    if C == 1:
        y = solve_single_class(wS[0], supply[0], col_cap)[None, :]
        return y, jnp.zeros_like(col_cap), i32(0), jnp.bool_(True)
    if class_degenerate:
        y_tot = solve_single_class(wS[0], jnp.sum(supply), col_cap)
        return (
            split_grants_by_class(y_tot, supply),
            jnp.zeros_like(col_cap),
            i32(0),
            jnp.bool_(True),
        )

    eps_full = jnp.maximum(jnp.max(jnp.abs(wS)), i32(1))
    from ..ops import transport_solve

    if eps0 is None:
        return transport_solve(
            wS, supply, col_cap, eps_full, pm0,
            alpha=alpha, max_supersteps=num_supersteps,
            refine_waves=refine_waves,
        )

    # eps0_budget bounds ONLY the short first attempt: when the short
    # schedule is instance-dependent (great on some shapes, a stall on
    # others), a small budget caps the damage before the full-range
    # retry — instead of burning the whole num_supersteps first.
    y1, pm1, s1, conv1 = transport_solve(
        wS, supply, col_cap, i32(eps0), pm0,
        alpha=alpha,
        max_supersteps=min(eps0_budget or num_supersteps, num_supersteps),
        refine_waves=refine_waves,
    )
    if not eps0_retry:
        # caller owns the fallback: return the bounded attempt as-is
        # (conv flag honest) — used by the grouped two-stage solve,
        # whose stall recovery is a DIFFERENT instance (the original
        # cost matrix), not a full-range retry on this one
        return y1, pm1, s1, conv1

    def keep(_):
        return y1, pm1, s1, conv1

    def retry(_):
        # Cold restart: full eps range, no carried prices.
        y2, pm2, s2, conv2 = transport_solve(
            wS, supply, col_cap, eps_full, None,
            alpha=alpha, max_supersteps=num_supersteps,
            refine_waves=refine_waves,
        )
        return y2, pm2, s1 + s2, conv2

    # NOTE: the retry predicate must stay plain `conv1`. Gating it with
    # `conv1 | (i32(eps0) >= eps_full)` (to skip an identical retry
    # when choose_eps0 already picked the full range) deterministically
    # crashed the TPU worker on the tunneled runtime whenever this ran
    # inside a scanned round — a runtime miscompile we can only avoid.
    # The duplicated full-range retry only fires on a non-converged
    # oversubscribed solve, a rare path worth the waste.
    return lax.cond(conv1, keep, retry, operand=None)


@functools.partial(
    jax.jit, static_argnames=("alpha", "max_supersteps", "refine_waves", "telemetry_cap")  # kschedlint: program=layered_solve
)
def _solve_transport(
    wS,  # int32[C, Mp1] scaled costs (column Mp1-1 = unsched, 0)
    supply,  # int32[C]
    col_cap,  # int32[Mp1]
    eps_init,  # int32 scalar
    pm0=None,  # optional int32[Mp1] carried machine prices
    alpha: int = 8,
    max_supersteps: int = 20_000,
    refine_waves: int = 0,
    telemetry_cap: int = 0,
):
    U = jnp.minimum(supply[:, None], col_cap[None, :])  # fwd arc capacity
    out = _transport_loop(
        wS, U, supply, col_cap, eps_init, alpha, max_supersteps, pm_init=pm0,
        refine_waves=refine_waves, telemetry_cap=telemetry_cap,
    )
    y, z, pm, steps, converged = out[:5]
    if telemetry_cap:
        return y, pm, steps, converged, out[5]
    return y, pm, steps, converged


def solve_layered_host(lp: LayeredProblem, *, pad, solve,
                       max_supersteps: int) -> LayeredResult:
    """The shared host harness around a device transport solve: cost
    shift (subtract the unsched cost so the escape column is 0), padded
    geometry, int32 overflow guard, closed-form dispatch for C==1 and
    class-degenerate instances, the short-then-full eps attempts loop,
    and objective reconstruction. One definition so the single-device
    and mesh-sharded solvers cannot drift.

    pad(M, C) -> (Mp, n_scale); solve(wS, supply, col_cap, eps_init)
    -> (y, steps, converged) on device arrays."""
    C, M = lp.cost_cm.shape
    supply = lp.supply.astype(np.int64)  # kschedlint: host-only (host cost prep; overflow-guarded before the i32 cast)
    total = int(supply.sum())
    if total == 0:
        return LayeredResult(
            y=np.zeros((C, M), np.int64), num_unsched=0, objective=0, supersteps=0  # kschedlint: host-only (LayeredResult contract is int64)
        )
    # Shifted per-unit cost: placing costs (e + cost[c,m]), leaving
    # unscheduled costs u (per row when row_unsched_cost is set);
    # subtract u so the unsched column is 0 for every row.
    if lp.row_unsched_cost is not None:
        u_row = np.asarray(lp.row_unsched_cost, np.int64)  # kschedlint: host-only (host cost prep; overflow-guarded before the i32 cast)
        assert u_row.shape == (C,), f"row_unsched_cost must be [{C}]"
    else:
        u_row = np.full(C, int(lp.unsched_cost), np.int64)  # kschedlint: host-only (host cost prep; overflow-guarded before the i32 cast)
    w = lp.cost_cm.astype(np.int64) + int(lp.ec_cost) - u_row[:, None]  # kschedlint: host-only (host cost prep; overflow-guarded before the i32 cast)
    Mp, n_scale = pad(M, C)
    wP = np.zeros((C, Mp), np.int64)  # kschedlint: host-only (host cost prep; overflow-guarded before the i32 cast)
    wP[:, :M] = w
    col_cap = np.zeros(Mp, np.int64)  # kschedlint: host-only (host cost prep; overflow-guarded before the i32 cast)
    col_cap[:M] = lp.col_cap
    col_cap[-1] = total

    max_w = int(np.abs(wP).max())
    if max_w * n_scale >= COST_SCALE_LIMIT:
        raise OverflowError(
            f"scaled layered costs overflow int32: max|w|={max_w} * {n_scale}"
        )

    if C == 1:
        y_np = solve_single_class_np(wP[0], total, col_cap)[None, :]
        steps_taken = 0
    elif (wP == wP[0]).all():
        # Class-degenerate (all cost rows equal): exact closed form on
        # the total supply, grants split arbitrarily by class — the
        # iterative solve herds pathologically on identical costs.
        y_tot = solve_single_class_np(wP[0], total, col_cap)
        y_np = split_grants_by_class(y_tot, supply)
        steps_taken = 0
    elif (w == w[:, :1]).all():
        # Row-constant (each row machine-uniform, rows differ — the
        # per-job-unsched shape with no class cost model): the
        # fractional-knapsack closed form.
        y_np = solve_row_constant_np(
            w[:, 0].astype(np.int32), supply.astype(np.int32),
            col_cap.astype(np.int32),
        )
        steps_taken = 0
    else:
        wS = jnp.asarray((wP * n_scale).astype(np.int32))
        sup = jnp.asarray(supply.astype(np.int32))
        cap = jnp.asarray(col_cap.astype(np.int32))
        eps_full = int(max(1, max_w * n_scale))
        eps0 = int(
            choose_eps0(n_scale, eps_full, total, int(lp.col_cap.sum()))
        )
        attempts = [np.int32(eps0)]
        if eps0 != eps_full:
            attempts.append(np.int32(eps_full))
        y = None
        converged = False
        # supersteps accumulate ACROSS attempts (matching the in-graph
        # retry in transport_fori, which reports s1 + s2)
        steps_taken = 0
        for eps_init in attempts:
            y, steps, converged = solve(wS, sup, cap, jnp.asarray(eps_init))
            steps_taken += int(steps)
            if bool(converged):
                break
        if not bool(converged):
            raise RuntimeError(
                f"layered transport solve did not converge in "
                f"{max_supersteps} supersteps"
            )
        y_np = np.asarray(y).astype(np.int64)  # kschedlint: host-only (host decode of device results)
    y_real = y_np[:, :M]
    placed = int(y_real.sum())
    unplaced_row = supply - y_real.sum(axis=1)
    objective = int((u_row * unplaced_row).sum()) + int(
        ((lp.cost_cm.astype(np.int64) + int(lp.ec_cost)) * y_real).sum()  # kschedlint: host-only (int64 objective math on host)
    )
    return LayeredResult(
        y=y_real,
        num_unsched=total - placed,
        objective=objective,
        supersteps=steps_taken,
    )


class LayeredTransportSolver:
    """The bulk scheduler's production TPU backend.

    Not a generic FlowSolver: it understands only the aggregate layered
    topology (which is the one BulkCluster builds) and is dispatched via
    ``solve_layered`` — BulkCluster picks this fast path whenever its
    backend provides the method, and otherwise falls back to the generic
    FlowProblem seam (the same graceful dispatch the reference has
    between full and incremental solver modes, placement/solver.go:60-90).
    """

    def __init__(self, alpha: int = 8, max_supersteps: int = 20_000,
                 telemetry: Optional[int] = None):
        self.alpha = validate_alpha(alpha)
        self.max_supersteps = max_supersteps
        #: soltel ring capacity override; None = module default (see
        #: obs/soltel.resolve_cap). The fused Pallas transport kernel
        #: carries no telemetry ring, so telemetry is only collected
        #: where the XLA `_solve_transport` loop runs ANYWAY (CPU, or
        #: a forced non-Pallas mode) — it must never silently swap the
        #: TPU hot path off the fused kernel. Flows are bit-identical
        #: either way by the kernel's parity contract.
        self.telemetry = telemetry
        self.last_supersteps = 0
        self.last_telemetry = None

    def reset(self) -> None:
        pass

    def solve_layered(self, lp: LayeredProblem) -> LayeredResult:
        from ..obs import soltel
        from ..ops import resolve_pallas, transport_solve

        tel_cap = soltel.resolve_cap(self.telemetry)
        if tel_cap and resolve_pallas()[0]:
            # Pallas dispatch is live (TPU or forced on): keep the
            # fused kernel and skip interior telemetry rather than
            # silently demoting the hot path to the XLA loop. The
            # superstep COUNT still reaches the registry via
            # solve_traced/solve_layered consumers.
            tel_cap = 0
        captured = []  # (tel_buf, steps, converged) of the last attempt

        def solve(wS, sup, cap, eps_init):
            if tel_cap:
                y, _pm, steps, converged, tel = _solve_transport(
                    wS, sup, cap, eps_init,
                    alpha=self.alpha, max_supersteps=self.max_supersteps,
                    telemetry_cap=tel_cap,
                )
                captured.append((tel, steps, converged))
            else:
                y, _pm, steps, converged = transport_solve(
                    wS, sup, cap, eps_init,
                    alpha=self.alpha, max_supersteps=self.max_supersteps,
                )
            return y, steps, converged

        def decode_last(converged_override=None):
            if not captured:
                return None
            tel, steps, converged = captured[-1]
            return soltel.decode(
                tel, int(steps), tel_cap, "layered", self.max_supersteps,
                converged=(
                    bool(converged)
                    if converged_override is None
                    else converged_override
                ),
                nodes=int(lp.supply.shape[0]),
                arcs=int(lp.cost_cm.size),
            )

        self.last_telemetry = None
        try:
            res = solve_layered_host(
                lp, pad=pad_geometry, solve=solve,
                max_supersteps=self.max_supersteps,
            )
        except RuntimeError as e:
            self.last_supersteps = self.max_supersteps  # budget exhausted
            tel = decode_last(converged_override=False)
            self.last_telemetry = tel
            if tel is not None and not isinstance(e, soltel.SolverStallError):
                raise soltel.SolverStallError(
                    str(e),
                    reason=soltel.detect_stall(tel),
                    telemetry=tel,
                ) from e
            raise
        self.last_supersteps = res.supersteps
        self.last_telemetry = decode_last()
        return res


# Level-3 registry ownership (ksched_tpu/analysis/program_registry.py)
from ..analysis.program_registry import declare_programs as _declare_programs

_declare_programs(__name__, "layered_solve")
