"""The TPU MCMF backend: cost-scaling push-relabel in JAX.

This is the centerpiece of the rebuild — the replacement for the
reference's external Flowlessly C++ solver (invoked over DIMACS pipes at
scheduling/flow/placement/solver.go:92-123). The flow network arrives as
flat arrays (graph/device_export.py), lives in device memory, and is
solved by a synchronous Goldberg–Tarjan cost-scaling push-relabel:

- arcs are doubled into residual entries (forward + backward);
- each superstep, every active node (excess > 0) pushes along ALL its
  admissible arcs at once via an in-segment prefix-sum allocation
  (maximal push), and active nodes with no admissible arc relabel;
- simultaneous pushes/relabels preserve eps-optimality: a relabel only
  lowers its own potential (reduced costs of in-arcs rise, and out-arc
  bounds were computed against neighbor potentials that only decrease),
  and opposite-direction pushes on one arc are mutually exclusive;
- phases shrink eps by alpha until eps = 1 on costs pre-scaled by the
  node count, at which point the flow is exactly optimal.

TPU-shaped implementation notes:

- NO scatters. TPU serializes scatter-adds (a 64k segment_sum measured
  ~68 ms), so all segment reductions are expressed over a host-prebuilt
  CSR ordering of the residual entries as cumsum + gather
  (diff-at-row-boundaries) and a segmented max via
  lax.associative_scan — each tens of microseconds at 64k entries.
- The CSR ordering depends only on arc endpoints. For plain array
  problems it is cached and rebuilt on the host (numpy argsort) when
  the structure changes; problems that carry a slot-stable plan
  (graph/slot_plan.py — every DeviceGraphState problem) skip the host
  rebuild entirely: endpoint churn mutates O(1) maintained plan rows,
  shipped as packed records through one jit'd scatter (a node that
  out-churns its region relocates to a tail-pool span the same way),
  and the argsort survives only on full_build / pow2 growth /
  tail-pool exhaustion.
- Everything is int32: TPU v5e has no native int64 (emulation trips XLA
  scoped-vmem issues and is slow). Scaled costs |c|*N must fit int32
  (checked on entry); potentials are guarded against overflow.
- Shapes are static per padded generation (power-of-two growth in
  DeviceGraphState), so repeated rounds reuse one compiled executable.

Incremental warm start (the property Flowlessly's daemon mode
provides), JOURNAL-SCOPED since r12: the change journal decides which
warm state each round may carry. Node potentials always carry — on
rounds whose journal holds only cap/cost/excess changes, the warm
prologue REFITS them (the tightening Bellman sweep seeded with the
carried prices moves only the journal-dirty frontier) and the carried
flow discharges at eps=1 in a handful of supersteps. Carried FLOW,
however, is kept only when the journal re-wired NO arc endpoints:
an endpoint-churn round's optimum displaces carried flow, and
discharging displaced excess is the measured unit-relabel price war
(600-4,000 supersteps at 1% churn — and measured NOT fixable by
price quality: exact entry prices, deeper Bellman budgets, warm eps
ladders, and periodic global relabels all leave or worsen it, see
_solve_mcmf). Those rounds dispatch the fresh-restart program up
front — zero flow, tightened prices, eps=1, ~10 supersteps on these
graphs — the same program the old `restart_budget` escape reached
only after burning a doomed warm attempt. Cost-scaling from max-cost
remains the final fallback, and `restart_budget` still backstops the
kept-flow warm attempts (a budget blow is reported as a structured
`warm_price_war` soltel event).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..graph.device_export import FlowProblem
from .base import FlowResult, FlowSolver, check_finite_costs, lower_bound_cost

_BIG = jnp.int32(1 << 30)
_P_GUARD = 1 << 30  # potential magnitude beyond this risks int32 overflow


@dataclass
class CsrPlan:
    """Host-prebuilt ordering of the doubled residual entries by source
    node, with everything the device needs for segment reductions."""

    s_arc: np.ndarray  # int32[2M] arc slot per sorted entry
    s_sign: np.ndarray  # int32[2M] +1 forward, -1 backward
    s_src: np.ndarray  # int32[2M]
    s_dst: np.ndarray  # int32[2M]
    s_segstart: np.ndarray  # int32[2M] sorted index of the entry's segment start
    s_isstart: np.ndarray  # bool[2M] segment-start flags
    inv_order: np.ndarray  # int32[2M] sorted position of original entry j
    node_first: np.ndarray  # int32[N] row_ptr[:-1] clamped
    node_last: np.ndarray  # int32[N] row_ptr[1:]-1 clamped
    node_nonempty: np.ndarray  # bool[N]
    src: np.ndarray  # int32[M] the endpoints this plan was built for
    dst: np.ndarray  # int32[M]


def build_csr_plan(src: np.ndarray, dst: np.ndarray, num_nodes: int) -> CsrPlan:
    m = len(src)
    esrc = np.concatenate([src, dst])
    order = np.argsort(esrc, kind="stable").astype(np.int32)
    s_src = esrc[order]
    s_dst = np.concatenate([dst, src])[order]
    s_arc = np.where(order < m, order, order - m).astype(np.int32)
    s_sign = np.where(order < m, 1, -1).astype(np.int32)
    inv_order = np.empty(2 * m, dtype=np.int32)
    inv_order[order] = np.arange(2 * m, dtype=np.int32)
    row_ptr = np.zeros(num_nodes + 1, dtype=np.int64)  # kschedlint: host-only (numpy plan build (row_ptr of 2M entries can exceed int32 in principle))
    counts = np.bincount(s_src, minlength=num_nodes)
    row_ptr[1:] = np.cumsum(counts)
    s_segstart = row_ptr[s_src].astype(np.int32)
    s_isstart = np.zeros(2 * m, dtype=bool)
    s_isstart[np.unique(s_segstart)] = True
    node_first = np.minimum(row_ptr[:-1], 2 * m - 1).astype(np.int32)
    node_last = np.maximum(row_ptr[1:] - 1, 0).astype(np.int32)
    node_nonempty = (row_ptr[1:] > row_ptr[:-1])
    return CsrPlan(
        s_arc=s_arc,
        s_sign=s_sign,
        s_src=s_src.astype(np.int32),
        s_dst=s_dst.astype(np.int32),
        s_segstart=s_segstart,
        s_isstart=s_isstart,
        inv_order=inv_order,
        node_first=node_first,
        node_last=node_last,
        node_nonempty=node_nonempty,
        src=src.copy(),
        dst=dst.copy(),
    )


def _seg_sum(vals, node_first, node_last, node_nonempty):
    """Per-node sum over a sorted-entry array: cumsum + boundary gathers."""
    c = jnp.cumsum(vals)
    excl_first = c[node_first] - vals[node_first]
    seg = c[node_last] - excl_first
    return jnp.where(node_nonempty, seg, 0)


def _seg_max(vals, isstart, node_last, node_nonempty, identity):
    """Per-node max via a segmented-max associative scan."""

    def combine(a, b):
        f1, v1 = a
        f2, v2 = b
        return f1 | f2, jnp.where(f2, v2, jnp.maximum(v1, v2))

    _, scanned = lax.associative_scan(combine, (isstart, vals))
    return jnp.where(node_nonempty, scanned[node_last], identity)


def _seg_min(vals, isstart, node_last, node_nonempty, identity):
    """Per-node min via a segmented-min associative scan."""

    def combine(a, b):
        f1, v1 = a
        f2, v2 = b
        return f1 | f2, jnp.where(f2, v2, jnp.minimum(v1, v2))

    _, scanned = lax.associative_scan(combine, (isstart, vals))
    return jnp.where(node_nonempty, scanned[node_last], identity)


_BIG_D = 1 << 28  # "unreachable" distance sentinel for price tightening


@functools.partial(jax.jit, static_argnames=("alpha", "max_supersteps", "tighten_sweeps", "telemetry_cap", "use_warm_p", "slot_stable"))  # kschedlint: program=csr_solve
def _solve_mcmf(
    cap, cost, supply, flow0, eps_init,
    s_arc, s_sign, s_src, s_dst, s_segstart, s_isstart, inv_order,
    node_first, node_last, node_nonempty,
    warm_p=None,
    alpha: int = 8,
    max_supersteps: int = 50_000,
    tighten_sweeps: int = 32,
    telemetry_cap: int = 0,
    use_warm_p: bool = False,
    slot_stable: bool = False,
):
    """telemetry_cap > 0 appends a superstep-indexed int32 telemetry
    ring [telemetry_cap, SOLTEL_WIDTH] to the returned tuple (row
    layout: obs/soltel.py), written at `step % cap` so the final
    supersteps always survive. The counters read state each superstep
    already computes — flows are bit-identical on/off, and with cap=0
    this traces the exact pre-telemetry jaxpr (no cost when off;
    pinned by the jaxpr contracts).

    use_warm_p=True REFITS the caller-supplied ``warm_p`` potentials
    (the previous round's device-resident prices) instead of running
    the from-scratch tightening pass: the same Bellman sweep loop is
    seeded with d0 = -warm_p, so the first sweep only moves nodes with
    a violated residual out-arc — exactly the journal-touched dirty
    frontier — and later sweeps expand that frontier until the prices
    are consistent again (or the sweep budget runs out; the saturate
    step then restores 0-optimality regardless, so the result is an
    exact optimum either way). Because last round's converged prices
    certify last round's flow, violations exist only around the churn,
    which is what kills the warm-start price war: the discharge starts
    eps-optimal-ish and drains in fresh-restart-like superstep counts
    instead of unit-relabel wars. With the defaults (None, False) the
    traced program is byte-identical to the pre-warm_p jaxpr: warm_p=
    None contributes no invars and the tighten branch traces exactly
    as before (the pinned off-hash contracts depend on that).

    slot_stable=True consumes a scatter-maintained slot-stable plan
    (graph/slot_plan.py): entry rows live in fixed per-node regions
    with slack, and liveness is encoded in the sign column (s_sign in
    {+1, -1, 0}) — the residual of a dead row is forced to 0, which
    makes it inert in every reduction (no separate mask tensor). The
    default (False) keeps the tightly-packed build_csr_plan layout and
    traces the exact pre-slot-stable program.

    Discharging DISPLACED excess through carried flow is structurally
    slow here, and no price seeding fixes it (measured, r12): with the
    prologue tighten CONVERGED (exact prices — raising its sweep cap
    changes nothing), a churn round's warm attempt still drains its
    bulk excess in ~20 supersteps and then strands the last displaced
    units in a unit-relabel crawl for hundreds-to-thousands of steps —
    the displacement chains are discovered one eps-relabel at a time,
    and a periodic mid-discharge global relabel makes it WORSE (10x,
    measured: re-tightening un-does the relabel progress that IS the
    chain discovery). That is why JaxSolver keeps carried flow only on
    journal-rounds with no endpoint churn (see its docstring)."""
    from ..obs.soltel import SOLTEL_WIDTH

    m = cap.shape[0]
    i32 = jnp.int32

    def residual(a_flow):
        """Residual per sorted entry; in slot-stable mode a dead row
        (sign 0) gets residual 0 and thus cannot push, relabel, carry
        excess, or consume prefix allocation."""
        if slot_stable:
            return jnp.where(
                s_sign > 0, cap[s_arc] - a_flow,
                jnp.where(s_sign < 0, a_flow, i32(0)),
            )
        return jnp.where(s_sign > 0, cap[s_arc] - a_flow, a_flow)

    def excess_of(flow):
        flow_signed = s_sign * flow[s_arc]
        return supply - _seg_sum(flow_signed, node_first, node_last, node_nonempty)

    def saturate(flow, p):
        """Refine step: saturate every residual entry with negative
        reduced cost, making the pseudoflow 0-optimal for the phase."""
        rc_fwd = cost + p[cap_src] - p[cap_dst]
        return jnp.where(rc_fwd < 0, cap, jnp.where(rc_fwd > 0, i32(0), flow))

    # Per-arc endpoints for the saturate step, recovered from the sorted
    # entries to avoid shipping src/dst twice: arc j's forward entry sits
    # at inv_order[j].
    fwd_pos = inv_order[:m]
    cap_src = s_src[fwd_pos]
    cap_dst = s_dst[fwd_pos]

    def tighten(flow, d0=None):
        """Price tightening: p = -(shortest residual-cost distance to a
        demand node), via synchronous Bellman-Ford sweeps over the sorted
        entries. Afterwards every residual arc between reachable nodes
        has nonnegative reduced cost, so the discharge can run at eps=1
        regardless of how flows/capacities changed since the last round —
        this is what makes warm restarts cheap and drift-free.

        With an explicit ``d0`` this is the warm-prologue REFIT instead:
        seeded from the carried prices, the relaxation only moves nodes
        whose residual out-arcs are violated (the dirty frontier), and
        the `changed` early-exit stops as soon as the frontier drains —
        a bounded Bellman sweep over the journal-touched subgraph,
        expressed data-parallel."""
        excess0 = excess_of(flow) if d0 is None else None
        a_flow = flow[s_arc]
        r = residual(a_flow)
        s_cost = s_sign * cost[s_arc]
        if d0 is None:
            d0 = jnp.where(excess0 < 0, i32(0), i32(_BIG_D))

        def t_cond(state):
            _d, changed, it = state
            return changed & (it < tighten_sweeps)

        def t_body(state):
            d, _, it = state
            cand = jnp.where(r > 0, s_cost + d[s_dst], i32(_BIG_D))
            best = _seg_min(cand, s_isstart, node_last, node_nonempty, i32(_BIG_D))
            # Clamp from below: a negative-cost residual cycle (possible
            # transiently with warm flows + changed costs) must not run d
            # toward int32 wraparound; the discharge handles the rest.
            d2 = jnp.maximum(jnp.minimum(d, best), -i32(_BIG_D))
            return d2, jnp.any(d2 != d), it + 1

        d, _, _ = lax.while_loop(t_cond, t_body, (d0, jnp.bool_(True), i32(0)))
        return -jnp.minimum(d, i32(_BIG_D))

    def superstep(flow, p, eps, excess):
        a_flow = flow[s_arc]
        r = residual(a_flow)
        s_cost = s_sign * cost[s_arc]
        rc = s_cost + p[s_src] - p[s_dst]
        e_at = excess[s_src]
        admissible = (r > 0) & (rc < 0) & (e_at > 0)

        # Maximal push: allocate each node's excess across its admissible
        # entries front-to-back via an in-segment exclusive prefix sum.
        r_adm = jnp.where(admissible, r, i32(0))
        cum = jnp.cumsum(r_adm)
        excl = cum - r_adm
        prefix_before = excl - excl[s_segstart]
        delta = jnp.clip(e_at - prefix_before, 0, r_adm)

        delta_orig = delta[inv_order]
        new_flow = flow + delta_orig[:m] - delta_orig[m:]

        # Relabel nodes that were active but pushed nothing (maximal push
        # guarantees active nodes with an admissible entry push >= 1).
        pushed = _seg_sum(delta, node_first, node_last, node_nonempty)
        sum_r = _seg_sum(r, node_first, node_last, node_nonempty)
        cand = jnp.where(r > 0, p[s_dst] - s_cost, -_BIG)
        best = _seg_max(cand, s_isstart, node_last, node_nonempty, -_BIG)
        relabel = (excess > 0) & (pushed == 0) & (sum_r > 0)
        new_p = jnp.where(relabel, best - eps, p)
        if not telemetry_cap:
            return new_flow, new_p, ()
        # counters over state this superstep already computed (soltel
        # row cols 3..6); purely observational, never fed back — and
        # appended AFTER the original dataflow so the telemetry-off
        # trace keeps the exact pre-telemetry op order (pinned hash).
        # Cost discipline: `pushed` is the already-reduced [N] per-node
        # push total (sum == sum(delta) since segments partition the
        # entries), and the saturated mask reuses r/s_sign — the only
        # NEW entry-space passes are two compare+sum sweeps, no
        # gathers (a zero-capacity arc counts as saturated: its
        # residual is zero, which is what the counter means).
        aux = (
            jnp.sum(pushed),
            jnp.sum(relabel.astype(i32)),
            jnp.sum(((s_sign > 0) & (r == 0)).astype(i32)),
            # r_adm > 0 <=> admissible (admissibility requires r > 0),
            # and r_adm is already materialized for the prefix cumsum
            jnp.sum((r_adm > 0).astype(i32)),
        )
        return new_flow, new_p, aux

    if telemetry_cap:
        from ..obs import soltel as _soltel

        _tel_rows_iota = _soltel.device_rows_iota(telemetry_cap)

    def tel_row(eps, excess, aux):
        active = jnp.sum((excess > 0).astype(i32))
        exc_pos = jnp.sum(jnp.maximum(excess, 0))
        return _soltel.device_row(eps, active, exc_pos, *aux)

    def tel_write(tel, steps, row):
        return _soltel.device_ring_write(
            tel, steps, row, telemetry_cap, _tel_rows_iota
        )

    def phase_cond(state):
        done = state[4]
        steps = state[3]
        return ~done & (steps < max_supersteps)

    def phase_body(state):
        if telemetry_cap:
            flow, p, eps, steps, done, tel = state
        else:
            flow, p, eps, steps, done = state
        excess = excess_of(flow)
        any_active = jnp.any(excess > 0)

        def do_superstep(_):
            f2, p2, aux = superstep(flow, p, eps, excess)
            if not telemetry_cap:
                return f2, p2, eps, steps + 1, jnp.bool_(False)
            tel2 = tel_write(tel, steps, tel_row(eps, excess, aux))
            return f2, p2, eps, steps + 1, jnp.bool_(False), tel2

        def next_phase(_):
            finished = eps <= 1
            new_eps = jnp.maximum(i32(1), eps // alpha)
            f2 = jnp.where(finished, flow, saturate(flow, p))
            out = (f2, p, jnp.where(finished, eps, new_eps), steps, finished)
            return out + ((tel,) if telemetry_cap else ())

        return lax.cond(any_active, do_superstep, next_phase, operand=None)

    if use_warm_p:
        # dirty-frontier refit: Bellman sweeps seeded from the carried
        # prices (clipped into tighten's distance range so the relax
        # arithmetic cannot overflow int32)
        p0 = tighten(
            flow0, d0=jnp.clip(-warm_p, -i32(_BIG_D), i32(_BIG_D))
        )
    else:
        p0 = tighten(flow0)
    flow1 = saturate(flow0, p0)  # mop up any residual violations
    state = (flow1, p0, eps_init, i32(0), jnp.bool_(False))
    if telemetry_cap:
        state = state + (jnp.zeros((telemetry_cap, SOLTEL_WIDTH), i32),)
        flow, p, eps, steps, done, tel = lax.while_loop(
            phase_cond, phase_body, state
        )
    else:
        flow, p, eps, steps, done = lax.while_loop(phase_cond, phase_body, state)
    converged = done & (jnp.max(jnp.abs(excess_of(flow))) == 0)
    p_overflow = jnp.max(jnp.abs(p)) >= _P_GUARD
    if telemetry_cap:
        return flow, p, steps, converged, p_overflow, tel
    return flow, p, steps, converged, p_overflow


# ---------------------------------------------------------------------------
# Stacked-CSR batched entry: one compiled program for a whole shape
# bucket of tenant lanes (ksched_tpu/tenancy — multi-tenant service)
# ---------------------------------------------------------------------------

#: lane counts pad to pow2 buckets (repeating a real lane, which is
#: idempotent: a duplicate lane computes the same solve and its outputs
#: are ignored), so tenants joining/leaving re-use executables instead
#: of recompiling per lane-count — same policy as the record buckets in
#: graph/device_export.pad_record_count
MIN_LANE_BUCKET = 1


def pad_lane_count(k: int) -> int:
    from ..utils import next_pow2

    return max(next_pow2(max(k, 1)), MIN_LANE_BUCKET)


_STACKED_SOLVES: dict = {}


def stacked_solve_fn(
    *,
    alpha: int = 8,
    max_supersteps: int = 4096,
    tighten_sweeps: int = 32,
    telemetry_cap: int = 0,
    use_warm_p: bool = False,
):
    """The batched (block-diagonal stacked-CSR) solve program: same-
    bucket tenant lanes solved through ONE compiled executable.

    Independent flow components in a block-diagonal stack never
    interact, so batching them is semantically free; the lane axis is
    the leading dimension of every argument (the flat offset-id stack
    reshaped [L, ...] — lane i's node ids are its local ids plus
    i*n_cap in the flat view, see tenancy/batch.py). The program is
    ``jit(vmap(_solve_mcmf))`` with the statics bound, which gives the
    two properties the multi-tenant acceptance demands by
    construction:

    - **per-lane convergence masks**: jax's while-loop batching runs
      the loop until every lane's own condition is false and freezes
      finished lanes via select — a slow tenant cannot change another
      lane's state, and each lane's superstep count (carried in its
      own lane of the loop state) stops the moment IT converges;
    - **bit-identical per-lane solves**: each lane's carry evolves
      through exactly the ops the single-lane `_solve_mcmf` applies to
      the same int32 data, so flows, potentials, superstep counts, and
      telemetry rows equal the lane solved alone (asserted exhaustively
      by tests/test_tenancy.py).

    A lane that exhausts ``max_supersteps`` freezes unconverged
    (its ``converged`` output stays False) without extending the other
    lanes' superstep counts; wall-clock for the whole program is
    bounded by the slowest lane's budget, which is why the tenancy
    layer batches only budget-capped attempts and escalates per lane
    (tenancy/batch.py). Returns per-lane tuples shaped
    ``(flow [L, m], p [L, n], steps [L], converged [L],
    p_overflow [L][, telemetry [L, cap, W]])``.

    Cached per statics tuple: with pow2 lane-count and shape buckets
    the warm service re-uses one executable per (bucket, policy), the
    compile-cache amortization the ROADMAP's multi-tenant story names.
    The jaxpr contracts pin this program scatter-free, 32-bit, and
    hash-stable across raw sizes in a bucket and lane counts in a lane
    bucket (tests/test_static_analysis.py)."""
    key = (alpha, max_supersteps, tighten_sweeps, telemetry_cap, use_warm_p)
    fn = _STACKED_SOLVES.get(key)
    if fn is None:
        statics = dict(
            alpha=alpha,
            max_supersteps=max_supersteps,
            tighten_sweeps=tighten_sweeps,
            telemetry_cap=telemetry_cap,
            slot_stable=False,
        )
        if use_warm_p:

            def lane(cap, cost, supply, flow0, eps, warm_p, *plan):
                return _solve_mcmf(
                    cap, cost, supply, flow0, eps, *plan,
                    warm_p=warm_p, use_warm_p=True, **statics,
                )

        else:

            def lane(cap, cost, supply, flow0, eps, *plan):
                return _solve_mcmf(cap, cost, supply, flow0, eps, *plan, **statics)

        fn = jax.jit(jax.vmap(lane))  # kschedlint: program=stacked_solve
        _STACKED_SOLVES[key] = fn
    return fn


class JaxSolver(FlowSolver):
    """Cost-scaling push-relabel on device, warm-started across rounds.

    Handed a DeviceResidentProblem (graph/device_export.py), the solve
    reads the persistent device buffers directly — no device_put of
    unchanged arrays — and the warm flow is carried BETWEEN rounds as a
    device array (masked against the pre-delta endpoints by the
    scatter-free ``device_warm_flow_fn`` program), bit-identical to the
    host warm path. Node potentials are likewise kept device-resident;
    with ``warm_potentials=True`` (default) a kept-flow warm attempt
    REFITS the carried prices around the journal-touched subgraph
    instead of re-deriving them from scratch — an exact solve either
    way. ``journal_scoped_warm=True`` (default) decides PER ROUND
    whether the carried flow itself is reusable: only when the round's
    journal re-wired no endpoints (see the module docstring for the
    measured price-war evidence behind that rule). Every loop mode /
    export arm shares the same policy, so the bit-for-bit
    placement-parity suites still hold.

    ``slot_stable=True`` (default) consumes the scatter-maintained
    slot-stable plan when the problem carries one
    (graph/slot_plan.py): endpoint churn then never costs a host
    argsort or a full plan re-upload — the plan deltas ride the same
    dirty-slot journal as the problem deltas. Plain array problems
    (no plan handle) keep the legacy host-built CsrPlan."""

    def __init__(self, alpha: int = 8, max_supersteps: int = 50_000, warm_start: bool = True, telemetry: Optional[int] = None, warm_potentials: bool = True, restart_budget: Optional[int] = None, slot_stable: bool = True, journal_scoped_warm: bool = True):
        from .layered import validate_alpha

        self.alpha = validate_alpha(alpha)
        self.max_supersteps = max_supersteps
        self.warm_start = warm_start
        self.warm_potentials = warm_potentials
        self.slot_stable = slot_stable
        #: journal-scoped warm restart (default): the change journal
        #: decides WHICH warm state each round may carry. Prices are
        #: always reusable — the refit repairs them around whatever
        #: the journal touched — but carried FLOW is kept only when
        #: the journal holds no endpoint changes (plan_key match).
        #: An endpoint-churn round deletes/rewires arcs, so its
        #: optimum displaces carried flow, and discharging displaced
        #: excess is the measured unit-relabel price war (600-4,000
        #: supersteps at 1% churn; exact entry prices, deeper Bellman
        #: budgets, eps ladders, and periodic global relabels all
        #: measured NOT to fix it — see _solve_mcmf's docstring).
        #: Those rounds dispatch the fresh-restart program up front
        #: (zero flow, tightened prices, eps=1: ~10 supersteps on
        #: these graphs) instead of burning a doomed warm attempt.
        #: False restores the r11 policy (always attempt the carried
        #: flow; rely on restart_budget to escape).
        self.journal_scoped_warm = journal_scoped_warm
        #: superstep budget for the WARM attempt before escaping to a
        #: fresh-restart solve (flow0=0, tightened prices, eps=1 — the
        #: ~10-superstep machine on these graphs) instead of burning
        #: the full 4096-step attempt-1 budget. None keeps the original
        #: two-attempt ladder. Since the dirty-frontier refit landed
        #: this is a BACKSTOP, not the fix: refitted warm attempts
        #: converge in fresh-restart-like superstep counts, and a
        #: budget blow is reported as a structured `warm_price_war`
        #: soltel event before escaping.
        self.restart_budget = restart_budget
        #: telemetry ring capacity override; None = the soltel module
        #: default (0 when KSCHED_SOLTEL=0 — telemetry off, identical
        #: traced program), resolved per solve
        self.telemetry = telemetry
        self._prev: Optional[np.ndarray] = None  # previous round's flow
        self._prev_dev = None  # same flow as a device array (no re-upload)
        self._prev_p = None  # previous round's potentials, device-resident
        #: endpoint buffers AT THE LAST SUCCESSFUL SOLVE — the warm
        #: mask must compare against these, not the pre-delta buffers
        #: of the latest refresh: a failed/degraded round still
        #: refreshes the mirror, and masking against its endpoints
        #: would miss changes from the round the solver never saw
        self._prev_src_dev = None
        self._prev_dst_dev = None
        #: same endpoints as host arrays (the non-resident warm mask)
        self._prev_src_host = None
        self._prev_dst_host = None
        self._plan: Optional[CsrPlan] = None
        self._plan_dev: Optional[tuple] = None
        #: endpoint-generation key of the cached plan
        #: (FlowProblem.plan_key) — equal keys skip the O(M) endpoint
        #: scans entirely on clean rounds
        self._plan_key = None
        #: endpoint key AT THE LAST SUCCESSFUL SOLVE — the journal-
        #: scoped warm policy keeps carried flow only when the current
        #: problem's key matches (no endpoint churn since that solve)
        self._key_solved = None
        self.last_supersteps = 0
        self.last_telemetry = None  # SolveTelemetry of the last solve
        self.last_warm_scope = "cold"  # warm | fresh | cold (see solve_async)

    def reset(self) -> None:
        self._prev = None
        self._prev_dev = None
        self._prev_p = None
        self._prev_src_dev = None
        self._prev_dst_dev = None
        self._prev_src_host = None
        self._prev_dst_host = None
        self._key_solved = None

    # -- warm-state checkpointing (runtime/checkpoint.save_warm_manifest) --

    def export_warm_state(self) -> Optional[dict]:
        """The carried warm state as host arrays, or None when cold —
        what a warm crash restore needs to make its first solve
        bit-identical to the never-killed process's. One D2H fetch of
        the potentials (the flow already has a host copy)."""
        if self._prev is None:
            return None
        return {
            "prev": np.asarray(self._prev, np.int32),
            "prev_p": (
                np.asarray(self._prev_p, np.int32)
                if self._prev_p is not None else None
            ),
            "prev_src": (
                np.asarray(self._prev_src_host, np.int32)
                if self._prev_src_host is not None else None
            ),
            "prev_dst": (
                np.asarray(self._prev_dst_host, np.int32)
                if self._prev_dst_host is not None else None
            ),
            "key_solved": self._key_solved,
        }

    def import_warm_state(
        self, state: dict, key_solved=None, resident: bool = False
    ) -> None:
        """Adopt an export_warm_state payload. `key_solved` is the
        endpoint key REMAPPED onto the restored DeviceGraphState (its
        uid changes across processes; the checkpoint loader owns the
        remap). With `resident`, the warm flow and the last-solve
        endpoint masks are re-uploaded so a device-resident loop's
        first post-restore warm attempt consumes the exact buffers the
        killed process carried."""
        self._prev = np.asarray(state["prev"], np.int32)
        self._prev_p = (
            jnp.asarray(state["prev_p"]) if state.get("prev_p") is not None else None
        )
        self._prev_src_host = (
            np.asarray(state["prev_src"], np.int32)
            if state.get("prev_src") is not None else None
        )
        self._prev_dst_host = (
            np.asarray(state["prev_dst"], np.int32)
            if state.get("prev_dst") is not None else None
        )
        self._key_solved = key_solved if key_solved is not None else state.get("key_solved")
        if resident and self._prev_src_host is not None:
            self._prev_dev = jnp.asarray(self._prev)
            self._prev_src_dev = jnp.asarray(self._prev_src_host)
            self._prev_dst_dev = jnp.asarray(self._prev_dst_host)
        else:
            self._prev_dev = None
            self._prev_src_dev = None
            self._prev_dst_dev = None

    def _plan_for(self, src: np.ndarray, dst: np.ndarray, n: int, plan_key=None) -> tuple:
        plan = self._plan
        if plan_key is not None and self._plan_key == plan_key and plan is not None:
            return self._plan_dev  # generation key match: no scans at all
        if plan is None or len(plan.src) != len(src) or len(plan.node_first) != n or plan_key is not None or not (
            np.array_equal(plan.src, src) and np.array_equal(plan.dst, dst)
        ):
            plan = build_csr_plan(src, dst, n)
            self._plan = plan
            self._plan_dev = tuple(
                jnp.asarray(x)
                for x in (
                    plan.s_arc, plan.s_sign, plan.s_src, plan.s_dst,
                    plan.s_segstart, plan.s_isstart, plan.inv_order,
                    plan.node_first, plan.node_last, plan.node_nonempty,
                )
            )
            # Structure changed: stale flows are only reusable per-slot if
            # endpoints match, checked in solve().
        self._plan_key = plan_key
        return self._plan_dev

    def solve_async(self, problem: FlowProblem):
        """Dispatch the warm attempt WITHOUT synchronizing and return an
        opaque pending token for complete(). The device works while the
        host is free to build the next round's graph — the pipelining
        seam the reference's daemon-mode solver implies
        (placement/solver.go:60-90): its subprocess crunches DIMACS
        concurrently with the Go process, and here the asynchronous
        dispatch gives the same overlap in-process."""
        n = problem.num_nodes
        m = len(problem.src)
        if m == 0 or problem.num_arcs == 0:
            if (problem.excess > 0).any():
                raise RuntimeError("infeasible flow problem: supply but no arcs")
            return (problem, None, None, None)
        check_finite_costs(problem)
        src = np.asarray(problem.src, np.int32)
        dst = np.asarray(problem.dst, np.int32)

        # Pre-scale costs by the node count so eps = 1 implies exactness;
        # the scaled range must fit int32 comfortably.
        max_cost = int(np.abs(problem.cost).max()) if m else 0
        if max_cost * n >= (1 << 30):
            raise OverflowError(
                f"scaled costs overflow int32: max|cost|={max_cost} at {n} nodes; "
                "rescale cost-model outputs or shrink the graph padding"
            )

        plan_state = getattr(problem, "plan", None) if self.slot_stable else None
        slot_stable = plan_state is not None
        if slot_stable:
            # slot-stable plan: endpoint churn was already folded into
            # the maintained layout — no argsort, no endpoint scans.
            # Prefer the device-resident scatter-maintained mirror;
            # otherwise the plan's own cached full upload (re-shipped
            # only when its value_version moved).
            d_plan = getattr(problem, "d_plan", None)
            if d_plan is not None and getattr(d_plan[0], "ndim", 1) == 2:
                # sharded-mode mirror: the entry tensors are [D, Es]
                # stacked per-shard tables. The stacking is a lossless
                # reshape of the global layout (graph/slot_plan.py
                # sharded block form), so flattening them recovers the
                # exact single-chip tensors — this is the degradation
                # ladder's jax rung (and AutoSolver's too-big-even-
                # per-shard CSR fallback) consuming a sharded mirror.
                # On a real mesh the reshape gathers the shards; a
                # degraded round may pay that once.
                d_plan = tuple(
                    x.reshape(-1) if getattr(x, "ndim", 1) == 2 else x
                    for x in d_plan
                )
            plan_dev = d_plan if d_plan is not None else plan_state.device_args()
        else:
            plan_dev = self._plan_for(
                src, dst, n, plan_key=getattr(problem, "plan_key", None)
            )

        from ..obs import soltel

        tel_cap = soltel.resolve_cap(self.telemetry)
        resident = getattr(problem, "d_cap", None) is not None
        # Journal-scoped warm restart: the endpoint generation key says
        # whether this round's journal re-wired any arc. If it did, the
        # optimum displaces carried flow and the warm discharge is the
        # measured unit-relabel price war — dispatch the fresh-restart
        # program (~10 supersteps) up front instead. Carried PRICES
        # survive either way (the refit repairs them on clean rounds).
        plan_key = getattr(problem, "plan_key", None)
        keep_flow = True
        if self.journal_scoped_warm and plan_key is not None:
            keep_flow = (
                self._key_solved is not None and plan_key == self._key_solved
            )
        if resident:
            # Device-resident problem: the folded arrays are already on
            # device (only this round's delta records crossed the
            # boundary); the warm flow is last round's device output,
            # masked against the last successful solve's endpoints —
            # the same values the host mask below computes, without the
            # flow round-trip.
            from ..graph.device_export import resident_solver_inputs

            dev_args, flow0_dev, warm = resident_solver_inputs(
                problem, self._prev_dev, self._prev_src_dev,
                self._prev_dst_dev, self.warm_start and keep_flow,
            )
        else:
            cap = problem.cap.astype(np.int32)
            supply = problem.excess.astype(np.int32)
            cost = problem.cost.astype(np.int32) * np.int32(n)
            dev_args = (
                jnp.asarray(cap), jnp.asarray(cost), jnp.asarray(supply),
            )
            warm = (
                self.warm_start
                and keep_flow
                and self._prev is not None
                and len(self._prev) == m
                and self._prev_src_host is not None
                and len(self._prev_src_host) == m
            )
            flow0 = np.zeros(m, dtype=np.int32)
            if warm:
                # Reuse prior flow where the arc endpoints are unchanged
                # since the last SUCCESSFUL solve; the refit/tighten
                # prologue inside the solve restores consistent prices.
                # (With a matched plan_key the mask is all-ones by
                # construction; plain-array problems carry no key, so
                # the journal-scoped policy falls back to this compare.)
                same = (self._prev_src_host == src) & (self._prev_dst_host == dst)
                if self.journal_scoped_warm and plan_key is None and not same.all():
                    warm = False
                    flow0 = np.zeros(m, dtype=np.int32)
                else:
                    flow0 = np.where(same, np.minimum(self._prev, cap), 0).astype(np.int32)
            flow0_dev = jnp.asarray(flow0)
        had_state = self._prev is not None or self._prev_dev is not None
        #: per-solve warm scope, for bench/obs accounting: "warm" =
        #: carried flow + refit prices, "fresh" = journal-scoped
        #: restart (endpoint churn; zero flow, tightened prices),
        #: "cold" = no carried state at all (first round / post-reset)
        self.last_warm_scope = (
            "warm" if warm else ("fresh" if had_state else "cold")
        )

        # Attempt 1: warm flow, tightened prices (or, with
        # warm_potentials, the previous round's device-resident prices)
        # + eps=1 discharge. Attempt 2: genuinely cold — zero flow and
        # full cost-scaling — so a poisoned warm state can always
        # recover. Only attempt 1 is dispatched here; the cold fallback
        # runs synchronously in complete() if needed (rare).
        warm_p_ok = (
            self.warm_potentials
            and warm
            and self._prev_p is not None
            and self._prev_p.shape[0] == n
        )
        attempt1_budget = min(4096, self.max_supersteps)
        if warm and self.restart_budget is not None:
            # budgeted warm attempt: a price-war round escapes to the
            # fresh-restart attempt in complete() instead of burning
            # the full attempt-1 budget first
            attempt1_budget = min(attempt1_budget, self.restart_budget)
        fut = _solve_mcmf(
            *dev_args,
            flow0_dev,
            jnp.asarray(np.int32(1)),
            *plan_dev,
            warm_p=self._prev_p if warm_p_ok else None,
            alpha=self.alpha,
            max_supersteps=attempt1_budget,
            telemetry_cap=tel_cap,
            use_warm_p=warm_p_ok,
            slot_stable=slot_stable,
        )
        cold = (np.zeros(m, dtype=np.int32), max(1, max_cost * n))
        rest = (dev_args, plan_dev, cold, tel_cap, warm, slot_stable, attempt1_budget)
        return (problem, fut, rest, resident)

    def complete(self, pending) -> FlowResult:
        """Synchronize a solve_async dispatch into a FlowResult."""
        from ..obs import soltel

        problem, fut, rest, resident = pending
        if fut is None:
            self.last_telemetry = None
            return FlowResult(
                flow=np.zeros(len(problem.src), dtype=np.int64),  # kschedlint: host-only (FlowResult contract is int64)
                objective=0, iterations=0,
            )
        dev_args, plan_dev, (f0_cold, eps_cold), tel_cap, warm, slot_stable, attempt1_budget = rest
        tel_buf = None
        if tel_cap:
            flow, p, steps, converged, p_overflow, tel_buf = fut
        else:
            flow, p, steps, converged, p_overflow = fut
        spent = int(steps)  # device work across ALL attempts this solve
        warm_failed = warm and not (bool(converged) and not bool(p_overflow))
        if warm_failed and not bool(converged):
            # A warm attempt that exhausted its budget is a price war,
            # not a hard instance (the fresh restart below converges in
            # ~10 supersteps): report it as a structured soltel event so
            # flight dumps distinguish it from genuine non-convergence.
            # A CONVERGED attempt that tripped the potential-overflow
            # guard still escapes below, but is NOT a price war — and
            # must not masquerade as one on the stall ring.
            soltel.warm_price_war(
                "jax",
                supersteps=int(steps),
                budget=attempt1_budget,
                escaped_to=(
                    "fresh_restart" if self.restart_budget is not None
                    else "cost_scaling"
                ),
                tel=(
                    soltel.decode(
                        tel_buf, int(steps), tel_cap, "jax", attempt1_budget,
                        converged=False,
                        nodes=problem.num_nodes, arcs=len(problem.src),
                    )
                    if tel_buf is not None
                    else None
                ),
            )
        if warm_failed and self.restart_budget is not None:
            # Attempt 1b (restart escape): a warm attempt that blew its
            # budget re-solves FRESH — zero flow, tightened prices,
            # eps=1 — the ~10-superstep path on these graphs, instead
            # of the ~20k-superstep full cost-scaling below. Exact
            # either way; the cost-scaling attempt remains the backstop
            # for genuinely hard instances.
            out = _solve_mcmf(
                *dev_args,
                jnp.asarray(f0_cold),
                jnp.asarray(np.int32(1)),
                *plan_dev,
                alpha=self.alpha,
                max_supersteps=min(4096, self.max_supersteps),
                telemetry_cap=tel_cap,
                slot_stable=slot_stable,
            )
            if tel_cap:
                flow, p, steps, converged, p_overflow, tel_buf = out
            else:
                flow, p, steps, converged, p_overflow = out
            spent += int(steps)
        if not (bool(converged) and not bool(p_overflow)):
            out = _solve_mcmf(
                *dev_args,
                jnp.asarray(f0_cold),
                jnp.asarray(np.int32(eps_cold)),
                *plan_dev,
                alpha=self.alpha,
                max_supersteps=self.max_supersteps,
                telemetry_cap=tel_cap,
                slot_stable=slot_stable,
            )
            if tel_cap:
                flow, p, steps, converged, p_overflow, tel_buf = out
            else:
                flow, p, steps, converged, p_overflow = out
            spent += int(steps)
        # work accounting covers every attempt (a budget-blown warm
        # attempt's burn included) — the supersteps the DEVICE ran this
        # round, not just the attempt that won; telemetry decode below
        # stays attempt-local (the ring indexes the final attempt)
        self.last_supersteps = spent
        # the telemetry budget is the SOLVER's budget (max_supersteps),
        # not the warm attempt's internal 4096 cap: a warm solve that
        # converges near 4096 steps is escalated to the cold fallback,
        # not failed, so cap-proximity against the warm cap would be a
        # spurious stall event (and would spam the flight ring)
        self.last_telemetry = (
            soltel.decode(
                tel_buf, int(steps), tel_cap, "jax", self.max_supersteps,
                converged=bool(converged) and not bool(p_overflow),
                nodes=problem.num_nodes, arcs=len(problem.src),
            )
            if tel_buf is not None
            else None
        )
        if bool(p_overflow) or not bool(converged):
            self.reset()  # never reuse the state that failed
        if bool(p_overflow):
            raise OverflowError("push-relabel potentials approached int32 range")
        if not bool(converged):
            # non-convergence now carries its interior evidence: the
            # stall detector's structured reason + the decoded ring
            # (the degradation ladder forwards both to flight dumps)
            tel = self.last_telemetry
            raise soltel.SolverStallError(
                f"push-relabel did not converge within {self.max_supersteps} supersteps; "
                "the flow problem may be infeasible (missing unscheduled-aggregator arcs?)",
                reason=soltel.detect_stall(tel) if tel is not None else None,
                telemetry=tel,
            )
        flow_np = np.asarray(flow)  # fetched ONCE, for the decode
        if self.warm_start:
            self._prev = flow_np.astype(np.int32)
            # flow and potentials stay device-resident between rounds:
            # the next warm attempt consumes the handles directly
            # instead of re-uploading what the device just produced,
            # masked against THIS solve's endpoint buffers
            self._prev_dev = flow if resident else None
            self._prev_src_dev = problem.d_src if resident else None
            self._prev_dst_dev = problem.d_dst if resident else None
            # host-side endpoints at this (successful) solve, for the
            # non-resident warm mask; problem arrays are snapshots
            self._prev_src_host = np.asarray(problem.src, np.int32)
            self._prev_dst_host = np.asarray(problem.dst, np.int32)
            # endpoint key at this solve: the journal-scoped warm
            # policy compares the next round's key against it
            self._key_solved = getattr(problem, "plan_key", None)
            self._prev_p = p
        objective = int(
            (flow_np.astype(np.int64) * problem.cost.astype(np.int64)).sum()  # kschedlint: host-only (int64 objective math on host)
        ) + lower_bound_cost(problem)
        return FlowResult(flow=flow_np.astype(np.int64), objective=objective, iterations=spent)  # kschedlint: host-only (FlowResult contract is int64)

    def solve(self, problem: FlowProblem) -> FlowResult:
        return self.complete(self.solve_async(problem))


# Level-3 registry ownership: the programs this module compiles
# (ksched_tpu/analysis/program_registry.py; audited by analysis/engine.py)
from ..analysis.program_registry import declare_programs as _declare_programs

_declare_programs(
    __name__,
    "csr_solve", "csr_solve_warmp", "csr_solve_slot", "csr_refit_slot",
    "stacked_solve", "stacked_solve_warmp",
)
