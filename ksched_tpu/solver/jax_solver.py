"""The TPU MCMF backend: cost-scaling push-relabel in JAX.

This is the centerpiece of the rebuild — the replacement for the
reference's external Flowlessly C++ solver (invoked over DIMACS pipes at
scheduling/flow/placement/solver.go:92-123). The flow network arrives as
flat arrays (graph/device_export.py), lives in device memory, and is
solved by a synchronous Goldberg–Tarjan cost-scaling push-relabel:

- arcs are doubled into residual entries (forward + backward);
- each superstep, every active node (excess > 0) pushes along ALL its
  admissible arcs at once via an in-segment prefix-sum allocation
  (maximal push), and active nodes with no admissible arc relabel;
- simultaneous pushes/relabels preserve eps-optimality: a relabel only
  lowers its own potential (reduced costs of in-arcs rise, and out-arc
  bounds were computed against neighbor potentials that only decrease),
  and opposite-direction pushes on one arc are mutually exclusive;
- phases shrink eps by alpha until eps = 1 on costs pre-scaled by the
  node count, at which point the flow is exactly optimal.

TPU-shaped implementation notes:

- NO scatters. TPU serializes scatter-adds (a 64k segment_sum measured
  ~68 ms), so all segment reductions are expressed over a host-prebuilt
  CSR ordering of the residual entries as cumsum + gather
  (diff-at-row-boundaries) and a segmented max via
  lax.associative_scan — each tens of microseconds at 64k entries.
- The CSR ordering depends only on arc endpoints, which change far less
  often than costs/capacities; it is cached and rebuilt on the host
  (cheap numpy argsort) only when the arc structure changes.
- Everything is int32: TPU v5e has no native int64 (emulation trips XLA
  scoped-vmem issues and is slow). Scaled costs |c|*N must fit int32
  (checked on entry); potentials are guarded against overflow.
- Shapes are static per padded generation (power-of-two growth in
  DeviceGraphState), so repeated rounds reuse one compiled executable.

Incremental warm start (the property Flowlessly's daemon mode provides):
potentials and flows from the previous round are reused; flows on arc
slots whose endpoints changed are dropped, and remaining eps-optimality
violations define the starting eps — so re-solve cost tracks the delta.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..graph.device_export import FlowProblem
from .base import FlowResult, FlowSolver

_BIG = jnp.int32(1 << 30)
_P_GUARD = 1 << 30  # potential magnitude beyond this risks int32 overflow


@dataclass
class CsrPlan:
    """Host-prebuilt ordering of the doubled residual entries by source
    node, with everything the device needs for segment reductions."""

    s_arc: np.ndarray  # int32[2M] arc slot per sorted entry
    s_sign: np.ndarray  # int32[2M] +1 forward, -1 backward
    s_src: np.ndarray  # int32[2M]
    s_dst: np.ndarray  # int32[2M]
    s_segstart: np.ndarray  # int32[2M] sorted index of the entry's segment start
    s_isstart: np.ndarray  # bool[2M] segment-start flags
    inv_order: np.ndarray  # int32[2M] sorted position of original entry j
    node_first: np.ndarray  # int32[N] row_ptr[:-1] clamped
    node_last: np.ndarray  # int32[N] row_ptr[1:]-1 clamped
    node_nonempty: np.ndarray  # bool[N]
    src: np.ndarray  # int32[M] the endpoints this plan was built for
    dst: np.ndarray  # int32[M]


def build_csr_plan(src: np.ndarray, dst: np.ndarray, num_nodes: int) -> CsrPlan:
    m = len(src)
    esrc = np.concatenate([src, dst])
    order = np.argsort(esrc, kind="stable").astype(np.int32)
    s_src = esrc[order]
    s_dst = np.concatenate([dst, src])[order]
    s_arc = np.where(order < m, order, order - m).astype(np.int32)
    s_sign = np.where(order < m, 1, -1).astype(np.int32)
    inv_order = np.empty(2 * m, dtype=np.int32)
    inv_order[order] = np.arange(2 * m, dtype=np.int32)
    row_ptr = np.zeros(num_nodes + 1, dtype=np.int64)
    counts = np.bincount(s_src, minlength=num_nodes)
    row_ptr[1:] = np.cumsum(counts)
    s_segstart = row_ptr[s_src].astype(np.int32)
    s_isstart = np.zeros(2 * m, dtype=bool)
    s_isstart[np.unique(s_segstart)] = True
    node_first = np.minimum(row_ptr[:-1], 2 * m - 1).astype(np.int32)
    node_last = np.maximum(row_ptr[1:] - 1, 0).astype(np.int32)
    node_nonempty = (row_ptr[1:] > row_ptr[:-1])
    return CsrPlan(
        s_arc=s_arc,
        s_sign=s_sign,
        s_src=s_src.astype(np.int32),
        s_dst=s_dst.astype(np.int32),
        s_segstart=s_segstart,
        s_isstart=s_isstart,
        inv_order=inv_order,
        node_first=node_first,
        node_last=node_last,
        node_nonempty=node_nonempty,
        src=src.copy(),
        dst=dst.copy(),
    )


def _seg_sum(vals, node_first, node_last, node_nonempty):
    """Per-node sum over a sorted-entry array: cumsum + boundary gathers."""
    c = jnp.cumsum(vals)
    excl_first = c[node_first] - vals[node_first]
    seg = c[node_last] - excl_first
    return jnp.where(node_nonempty, seg, 0)


def _seg_max(vals, isstart, node_last, node_nonempty, identity):
    """Per-node max via a segmented-max associative scan."""

    def combine(a, b):
        f1, v1 = a
        f2, v2 = b
        return f1 | f2, jnp.where(f2, v2, jnp.maximum(v1, v2))

    _, scanned = lax.associative_scan(combine, (isstart, vals))
    return jnp.where(node_nonempty, scanned[node_last], identity)


@functools.partial(jax.jit, static_argnames=("alpha", "max_supersteps"))
def _solve_mcmf(
    cap, cost, supply, p0, flow0, eps_init,
    s_arc, s_sign, s_src, s_dst, s_segstart, s_isstart, inv_order,
    node_first, node_last, node_nonempty,
    alpha: int = 8,
    max_supersteps: int = 50_000,
):
    m = cap.shape[0]
    i32 = jnp.int32

    def excess_of(flow):
        flow_signed = s_sign * flow[s_arc]
        return supply - _seg_sum(flow_signed, node_first, node_last, node_nonempty)

    def saturate(flow, p):
        """Refine step: saturate every residual entry with negative
        reduced cost, making the pseudoflow 0-optimal for the phase."""
        rc_fwd = cost + p[cap_src] - p[cap_dst]
        return jnp.where(rc_fwd < 0, cap, jnp.where(rc_fwd > 0, i32(0), flow))

    # Per-arc endpoints for the saturate step, recovered from the sorted
    # entries to avoid shipping src/dst twice: arc j's forward entry sits
    # at inv_order[j].
    fwd_pos = inv_order[:m]
    cap_src = s_src[fwd_pos]
    cap_dst = s_dst[fwd_pos]

    def superstep(flow, p, eps, excess):
        a_flow = flow[s_arc]
        r = jnp.where(s_sign > 0, cap[s_arc] - a_flow, a_flow)
        s_cost = s_sign * cost[s_arc]
        rc = s_cost + p[s_src] - p[s_dst]
        e_at = excess[s_src]
        admissible = (r > 0) & (rc < 0) & (e_at > 0)

        # Maximal push: allocate each node's excess across its admissible
        # entries front-to-back via an in-segment exclusive prefix sum.
        r_adm = jnp.where(admissible, r, i32(0))
        cum = jnp.cumsum(r_adm)
        excl = cum - r_adm
        prefix_before = excl - excl[s_segstart]
        delta = jnp.clip(e_at - prefix_before, 0, r_adm)

        delta_orig = delta[inv_order]
        new_flow = flow + delta_orig[:m] - delta_orig[m:]

        # Relabel nodes that were active but pushed nothing (maximal push
        # guarantees active nodes with an admissible entry push >= 1).
        pushed = _seg_sum(delta, node_first, node_last, node_nonempty)
        sum_r = _seg_sum(r, node_first, node_last, node_nonempty)
        cand = jnp.where(r > 0, p[s_dst] - s_cost, -_BIG)
        best = _seg_max(cand, s_isstart, node_last, node_nonempty, -_BIG)
        relabel = (excess > 0) & (pushed == 0) & (sum_r > 0)
        new_p = jnp.where(relabel, best - eps, p)
        return new_flow, new_p

    def phase_cond(state):
        _flow, _p, _eps, steps, done = state
        return ~done & (steps < max_supersteps)

    def phase_body(state):
        flow, p, eps, steps, done = state
        excess = excess_of(flow)
        any_active = jnp.any(excess > 0)

        def do_superstep(_):
            f2, p2 = superstep(flow, p, eps, excess)
            return f2, p2, eps, steps + 1, jnp.bool_(False)

        def next_phase(_):
            finished = eps <= 1
            new_eps = jnp.maximum(i32(1), eps // alpha)
            f2 = jnp.where(finished, flow, saturate(flow, p))
            return f2, p, jnp.where(finished, eps, new_eps), steps, finished

        return lax.cond(any_active, do_superstep, next_phase, operand=None)

    flow1 = saturate(flow0, p0)  # establish eps_init-optimality
    state = (flow1, p0, eps_init, i32(0), jnp.bool_(False))
    flow, p, eps, steps, done = lax.while_loop(phase_cond, phase_body, state)
    converged = done & (jnp.max(jnp.abs(excess_of(flow))) == 0)
    p_overflow = jnp.max(jnp.abs(p)) >= _P_GUARD
    return flow, p, steps, converged, p_overflow


class JaxSolver(FlowSolver):
    """Cost-scaling push-relabel on device, warm-started across rounds."""

    def __init__(self, alpha: int = 8, max_supersteps: int = 50_000, warm_start: bool = True):
        self.alpha = alpha
        self.max_supersteps = max_supersteps
        self.warm_start = warm_start
        self._prev: Optional[Tuple[np.ndarray, np.ndarray]] = None  # (p, flow)
        self._plan: Optional[CsrPlan] = None
        self._plan_dev: Optional[tuple] = None
        self.last_supersteps = 0

    def reset(self) -> None:
        self._prev = None

    def _plan_for(self, src: np.ndarray, dst: np.ndarray, n: int) -> tuple:
        plan = self._plan
        if plan is None or len(plan.src) != len(src) or len(plan.node_first) != n or not (
            np.array_equal(plan.src, src) and np.array_equal(plan.dst, dst)
        ):
            plan = build_csr_plan(src, dst, n)
            self._plan = plan
            self._plan_dev = tuple(
                jnp.asarray(x)
                for x in (
                    plan.s_arc, plan.s_sign, plan.s_src, plan.s_dst,
                    plan.s_segstart, plan.s_isstart, plan.inv_order,
                    plan.node_first, plan.node_last, plan.node_nonempty,
                )
            )
            # Structure changed: stale flows are only reusable per-slot if
            # endpoints match, checked in solve().
        return self._plan_dev

    def solve(self, problem: FlowProblem) -> FlowResult:
        n = problem.num_nodes
        m = len(problem.src)
        if m == 0 or problem.num_arcs == 0:
            if (problem.excess > 0).any():
                raise RuntimeError("infeasible flow problem: supply but no arcs")
            return FlowResult(flow=np.zeros(m, dtype=np.int64), objective=0, iterations=0)
        src = problem.src.astype(np.int32)
        dst = problem.dst.astype(np.int32)
        cap = problem.cap.astype(np.int32)
        supply = problem.excess.astype(np.int32)

        # Pre-scale costs by the node count so eps = 1 implies exactness;
        # the scaled range must fit int32 comfortably.
        max_cost = int(np.abs(problem.cost).max()) if m else 0
        if max_cost * n >= (1 << 30):
            raise OverflowError(
                f"scaled costs overflow int32: max|cost|={max_cost} at {n} nodes; "
                "rescale cost-model outputs or shrink the graph padding"
            )
        cost = problem.cost.astype(np.int32) * np.int32(n)

        prev_plan = self._plan
        plan_dev = self._plan_for(src, dst, n)

        p0 = np.zeros(n, dtype=np.int32)
        flow0 = np.zeros(m, dtype=np.int32)
        warm = False
        if self.warm_start and self._prev is not None:
            p_prev, f_prev = self._prev
            if len(p_prev) == n and len(f_prev) == m and prev_plan is not None:
                warm = True
                p0 = p_prev
                same = (prev_plan.src == src) & (prev_plan.dst == dst)
                flow0 = np.where(same, np.minimum(f_prev, cap), 0).astype(np.int32)

        if warm:
            # Start eps at the largest eps-optimality violation of the
            # carried-over state: re-solve cost tracks the delta size.
            rc = cost.astype(np.int64) + p0[src].astype(np.int64) - p0[dst].astype(np.int64)
            viol = 0
            fwd_live = cap > flow0
            if fwd_live.any():
                viol = max(viol, int(np.max(-rc[fwd_live])))
            bwd_live = flow0 > 0
            if bwd_live.any():
                viol = max(viol, int(np.max(rc[bwd_live])))
            eps_init = max(1, viol)
        else:
            eps_init = max(1, max_cost * n)

        flow, p, steps, converged, p_overflow = _solve_mcmf(
            jnp.asarray(cap),
            jnp.asarray(cost),
            jnp.asarray(supply),
            jnp.asarray(p0),
            jnp.asarray(flow0),
            jnp.asarray(np.int32(eps_init)),
            *plan_dev,
            alpha=self.alpha,
            max_supersteps=self.max_supersteps,
        )
        if warm and (not bool(converged) or bool(p_overflow)):
            # Warm start led the search astray (e.g. a large structural
            # delta): retry cold rather than failing the round.
            self._prev = None
            return self.solve(problem)
        self.last_supersteps = int(steps)
        if bool(p_overflow):
            raise OverflowError("push-relabel potentials approached int32 range")
        if not bool(converged):
            raise RuntimeError(
                f"push-relabel did not converge within {self.max_supersteps} supersteps; "
                "the flow problem may be infeasible (missing unscheduled-aggregator arcs?)"
            )
        flow_np = np.asarray(flow)
        if self.warm_start:
            self._prev = (np.asarray(p), flow_np)
        objective = int(
            (flow_np.astype(np.int64) * problem.cost.astype(np.int64)).sum()
            + (problem.flow_offset.astype(np.int64) * problem.cost.astype(np.int64)).sum()
        )
        return FlowResult(flow=flow_np.astype(np.int64), objective=objective, iterations=int(steps))
