"""Generic FlowProblem -> dense-transport collapse with automatic CSR
fallback: the policy-dispatch seam of docs/solver_coverage.md, encoded.

The reference serves every policy through one solver seam
(scheduling/flow/placement/solver.go:36-38). The rebuild's production
path is the dense layered transport — exact whenever the graph is
"dense-collapsible" (no binding interior-EC capacities, cost-uniform
resource interiors, no per-task leaf arcs; docs/solver_coverage.md) —
with the CSR backends as the total-generality fallback. Until round 4
the CALLER chose the path; this module encodes the losslessness
predicate so the choice is automatic per solve:

    AutoSolver(csr_backend).solve(problem)
      -> try_collapse(problem): a full structural audit of the flat
         arc arrays. Collapsible -> group tasks into signature rows,
         solve ONE dense transport, reconstruct exact per-arc flows.
         Any refusal (with a reason, kept for observability) -> the
         CSR backend, unchanged semantics.

Soundness: every refusal is conservative (routing to CSR can only cost
time, never correctness), and the collapse itself is exact by the
signature argument of docs/solver_coverage.md — tasks with identical
(escape cost, effective machine-cost row) are interchangeable
commodities, and interior resource trees with a unique path cost fold
into per-column constants + tree capacities (computed as the exact
tree max-flow). Reconstructed flows satisfy conservation and caps by
construction; tests assert objective equality against the CSR oracle.

Collapsible today (the entire non-preempt planned-policy surface):
tasks -> {job unsched aggregator | equivalence classes | machines},
EC -> EC chains that cannot bind, EC -> machine routes, machine
subtrees with a unique per-machine path cost to the sink. Pinned
running tasks (preemption-off) arrive lower-bound-folded and cost
nothing. Keep-mode (preemption-on) graphs carry per-task running arcs
to leaves -> refused -> CSR, as are binding interior capacities and
any structure outside the audited shape.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..graph.flowgraph import NodeType
from .base import FlowResult, FlowSolver, lower_bound_cost

_TASK_TYPES = (
    int(NodeType.ROOT_TASK),
    int(NodeType.SCHEDULED_TASK),
    int(NodeType.UNSCHEDULED_TASK),
)
_BELOW_MACHINE = (
    int(NodeType.NUMA),
    int(NodeType.SOCKET),
    int(NodeType.CACHE),
    int(NodeType.CORE),
    int(NodeType.PU),
)


@dataclass
class _MachineTree:
    """One machine column: exact tree capacity, the unique path cost
    machine->sink, and the arc lists needed to push decoded units."""

    node: int
    capacity: int
    path_cost: int
    # (arc_idx, child_node) per node, in arc order; child == -1 -> sink
    children: Dict[int, List[Tuple[int, int]]]


@dataclass
class GraphCollapse:
    """Everything needed to solve the dense form and reconstruct."""

    supply: np.ndarray  # int32[G]
    col_cap: np.ndarray  # int32[M]
    cost_cm: np.ndarray  # int32[G, M] full placement cost per unit
    row_unsched: np.ndarray  # int64[G] full escape cost per unit
    machines: List[_MachineTree]
    pre_flows: List[Tuple[int, int]]  # folded pinned units (arc, units)
    rows_tasks: List[List[int]]  # task node ids per row
    # per task: route realization per machine column:
    #   ("d", arc) direct | ("e", t_ec_arc, (chain arcs...), ec_m_arc)
    task_routes: List[Dict[int, tuple]]
    task_escape: List[Tuple[int, int]]  # (task->agg arc, agg->sink arc)


def _refuse(reason: str):
    return None, reason


def try_collapse(problem) -> Tuple[Optional[GraphCollapse], str]:
    """Audit a FlowProblem against the dense-collapsibility predicate.

    Returns (collapse, "") when lossless, (None, reason) otherwise.
    Pure host-side numpy over the flat arrays; O(nodes + arcs + G*M).
    """
    nt = np.asarray(problem.node_type)
    excess = np.asarray(problem.excess)
    src = np.asarray(problem.src)
    dst = np.asarray(problem.dst)
    cap = np.asarray(problem.cap)
    cost = np.asarray(problem.cost)

    live = np.nonzero((src > 0) & (cap > 0))[0]
    sinks = np.nonzero(nt == int(NodeType.SINK))[0]
    if len(sinks) != 1:
        return _refuse(f"{len(sinks)} sink nodes")
    sink = int(sinks[0])

    out: Dict[int, List[int]] = {}
    for a in live:
        out.setdefault(int(src[a]), []).append(int(a))

    # Positive excess: task nodes (one row unit each) or resource
    # nodes — the latter are lower-bound-FOLDED pinned running tasks
    # (preemption-off pins with cap_lower=1, graph_manager.go:675-720).
    # Folded units stay stranded at their resource (the CSR backends
    # leave them exactly so: the occupied slot's residual sink cap is
    # already 0, and the decode reads the pin from the arc's
    # flow_offset); the collapse ignores them the same way. Any other
    # excess pattern is outside the audited shape.
    _RESOURCE_TYPES = (int(NodeType.MACHINE),) + _BELOW_MACHINE
    pos = np.nonzero(excess > 0)[0]
    if not np.isin(nt[pos], _TASK_TYPES + _RESOURCE_TYPES).all():
        return _refuse("positive excess off tasks/resources")
    neg = np.nonzero(excess < 0)[0]
    if len(neg) > 1 or (len(neg) == 1 and int(neg[0]) != sink):
        return _refuse("negative excess off the sink")
    task_mask = np.isin(nt, _TASK_TYPES)
    total_supply = int(excess[(excess > 0) & task_mask].sum())

    # ---- machine subtrees: unique path cost + exact tree capacity ----
    machine_nodes = np.nonzero(nt == int(NodeType.MACHINE))[0]
    col_of: Dict[int, int] = {}
    machines: List[_MachineTree] = []
    claimed: Dict[int, int] = {}  # below-machine node -> owning machine

    # ---- folded pinned units: route each resource node's positive
    # excess to the sink FIRST (the pinned task occupies its slot; the
    # occupancy-reduced interior caps — graph_manager.go:662-667 — mean
    # the unit typically has exactly its own leaf->sink hop left).
    # Machine capacities below are computed on the remaining caps. ----
    pre_flows: List[Tuple[int, int]] = []
    cap_res = cap.astype(np.int64).copy()
    _ROUTABLE = _BELOW_MACHINE + (int(NodeType.MACHINE),)

    def _route(v: int, units: int) -> int:
        routed = 0
        for a in out.get(v, []):
            if units == 0:
                break
            d = int(dst[a])
            if d == sink:
                take = min(units, int(cap_res[a]))
            elif int(nt[d]) in _ROUTABLE:
                take = _route(d, min(units, int(cap_res[a])))
            else:
                continue
            if take:
                cap_res[a] -= take
                pre_flows.append((int(a), take))
                units -= take
                routed += take
        return routed

    for v in pos:
        v = int(v)
        if int(nt[v]) in _ROUTABLE:
            e = int(excess[v])
            if _route(v, e) != e:
                return _refuse(
                    f"resource {v}: folded pinned units exceed capacity"
                )

    for m in machine_nodes:
        m = int(m)
        children: Dict[int, List[Tuple[int, int]]] = {}
        path_cost: Optional[int] = None
        defect: Optional[str] = None

        def walk(v: int, acc: int) -> int:
            """Returns remaining capacity-to-sink of v; records the
            children arcs; checks the unique-path-cost condition."""
            nonlocal path_cost, defect
            total_cap = 0
            kids: List[Tuple[int, int]] = []
            for a in out.get(v, []):
                d = int(dst[a])
                if d == sink:
                    c = acc + int(cost[a])
                    if path_cost is None:
                        path_cost = c
                    elif path_cost != c:
                        defect = "non-uniform interior path costs"
                    kids.append((a, -1))
                    total_cap += int(cap_res[a])
                elif int(nt[d]) in _BELOW_MACHINE:
                    if d in claimed:
                        # reached twice — from another machine OR from
                        # this one (diamond/cycle): either way not a
                        # tree; refuse rather than double-count
                        defect = "non-tree interior (shared/diamond node)"
                        continue
                    claimed[d] = m
                    sub = walk(d, acc + int(cost[a]))
                    kids.append((a, d))
                    total_cap += min(int(cap_res[a]), sub)
                else:
                    defect = "interior arc to a non-resource node"
            children[v] = kids
            return total_cap

        capacity = walk(m, 0)
        if defect is not None:
            return _refuse(f"machine {m}: {defect}")
        if path_cost is None:
            capacity, path_cost = 0, 0  # no route to sink: dead column
        col_of[m] = len(machines)
        machines.append(_MachineTree(
            node=m, capacity=capacity, path_cost=path_cost,
            children=children,
        ))
    if not machines:
        return _refuse("no machine nodes")
    M = len(machines)

    # ---- EC routing (chains folded; caps must never bind) ----
    ec_nodes = [int(e) for e in np.nonzero(nt == int(NodeType.EQUIV_CLASS))[0]]
    # upper bound on flow through an EC: tasks with an arc into it,
    # PLUS everything its upstream ECs could forward (a chain-fed EC
    # sees the whole upstream inflow — counting only direct task arcs
    # would understate the bound to 0 and wave binding caps through)
    ec_direct: Dict[int, int] = {e: 0 for e in ec_nodes}
    ec_parents: Dict[int, List[int]] = {e: [] for e in ec_nodes}
    task_ids = [
        int(t) for t in np.nonzero(
            np.isin(nt, _TASK_TYPES) & (excess > 0)
        )[0]
    ]
    for t in task_ids:
        for a in out.get(t, []):
            d = int(dst[a])
            if int(nt[d]) == int(NodeType.EQUIV_CLASS):
                ec_direct[d] = ec_direct.get(d, 0) + 1
    for e in ec_nodes:
        for a in out.get(e, []):
            d = int(dst[a])
            if int(nt[d]) == int(NodeType.EQUIV_CLASS) and d in ec_parents:
                ec_parents[d].append(e)

    ec_inflow: Dict[int, object] = {}
    _PENDING = object()

    def inflow_of(e: int) -> int:
        got = ec_inflow.get(e)
        if got is _PENDING:
            raise ValueError("EC cycle")
        if got is not None:
            return got
        ec_inflow[e] = _PENDING
        total = ec_direct.get(e, 0) + sum(
            inflow_of(p) for p in ec_parents.get(e, [])
        )
        ec_inflow[e] = total
        return total

    try:
        for e in ec_nodes:
            inflow_of(e)
    except ValueError as err:
        return _refuse(str(err))

    # ec_route[e] = {col: (cost, path arcs...)} cheapest route to each
    # machine column through EC->EC chains (memoized DFS, cycle check)
    _IN_PROGRESS = object()
    ec_route: Dict[int, object] = {}

    def route_of(e: int):
        got = ec_route.get(e)
        if got is _IN_PROGRESS:
            raise ValueError("EC cycle")
        if got is not None:
            return got
        ec_route[e] = _IN_PROGRESS
        routes: Dict[int, Tuple[int, tuple]] = {}
        for a in out.get(e, []):
            d = int(dst[a])
            td = int(nt[d])
            if td == int(NodeType.MACHINE):
                # the arc can only bind if it could carry less than
                # both the feeding tasks AND the machine's own column
                # capacity (which already limits total inflow)
                bound = min(
                    int(ec_inflow.get(e, 0)), total_supply,
                    machines[col_of[d]].capacity,
                )
                if int(cap[a]) < bound:
                    raise ValueError(
                        f"EC {e}: machine arc cap {int(cap[a])} can bind"
                    )
                c = int(cost[a])
                col = col_of[d]
                if col not in routes or c < routes[col][0]:
                    routes[col] = (c, (a,))
            elif td == int(NodeType.EQUIV_CLASS):
                if int(cap[a]) < min(int(ec_inflow.get(e, 0)), total_supply):
                    raise ValueError(
                        f"EC {e}: interior EC arc cap {int(cap[a])} can bind"
                    )
                for col, (c2, arcs2) in route_of(d).items():
                    c = int(cost[a]) + c2
                    if col not in routes or c < routes[col][0]:
                        routes[col] = (c, (a,) + arcs2)
            else:
                raise ValueError(f"EC {e} arcs to node type {td}")
        ec_route[e] = routes
        return routes

    try:
        for e in ec_nodes:
            route_of(e)
    except ValueError as err:
        return _refuse(str(err))

    # ---- unsched aggregators (lookup over RAW arcs: a fully-drained
    # agg's sink arc has cap 0 and is absent from the live set; it only
    # matters if some task still routes to it — the escape-capacity
    # check below catches that) ----
    agg_sink_arc: Dict[int, int] = {}
    agg_load: Dict[int, int] = {}
    agg_mask = nt[src] == int(NodeType.JOB_AGGREGATOR)
    for a in np.nonzero((src > 0) & agg_mask)[0]:
        g = int(src[a])
        if int(dst[a]) != sink:
            return _refuse(f"unsched agg {g}: non-sink arc")
        if g in agg_sink_arc:
            return _refuse(f"unsched agg {g}: multiple sink arcs")
        agg_sink_arc[g] = int(a)

    # ---- tasks -> signature rows ----
    BIG = 1 << 26  # disallowed-cell cost; escape is always cheaper
    sig_to_row: Dict[bytes, int] = {}
    rows_tasks: List[List[int]] = []
    row_cost: List[np.ndarray] = []
    row_u: List[int] = []
    task_routes: List[Dict[int, tuple]] = []
    task_escape: List[Tuple[int, int]] = []
    col_base = np.array([mt.path_cost for mt in machines], np.int64)

    for t in task_ids:
        if int(excess[t]) != 1:
            return _refuse(f"task {t}: excess {int(excess[t])} != 1")
        crow = np.full(M, BIG, np.int64)
        routes: Dict[int, tuple] = {}
        esc: Optional[Tuple[int, int]] = None
        for a in out.get(t, []):
            d = int(dst[a])
            td = int(nt[d])
            if td == int(NodeType.JOB_AGGREGATOR):
                if esc is not None:
                    return _refuse(f"task {t}: two escape arcs")
                if d not in agg_sink_arc:
                    return _refuse(f"task {t}: escape agg {d} has no sink arc")
                esc = (int(a), agg_sink_arc[d])
            elif td == int(NodeType.MACHINE):
                col = col_of[d]
                c = int(cost[a])
                if c < crow[col]:
                    crow[col] = c
                    routes[col] = ("d", int(a))
            elif td == int(NodeType.EQUIV_CLASS):
                for col, (c2, arcs2) in ec_route[d].items():
                    c = int(cost[a]) + c2
                    if c < crow[col]:
                        crow[col] = c
                        routes[col] = ("e", int(a)) + tuple(arcs2)
            else:
                return _refuse(
                    f"task {t}: arc to node type {td} (leaf/keep-mode?)"
                )
        if esc is None:
            return _refuse(f"task {t}: no unsched-aggregator arc")
        u_eff = int(cost[esc[0]]) + int(cost[esc[1]])
        agg_load[int(dst[esc[0]])] = agg_load.get(int(dst[esc[0]]), 0) + 1
        crow = crow + col_base
        key = crow.tobytes() + u_eff.to_bytes(8, "little", signed=True)
        r = sig_to_row.get(key)
        if r is None:
            r = len(rows_tasks)
            sig_to_row[key] = r
            rows_tasks.append([])
            row_cost.append(crow)
            row_u.append(u_eff)
        rows_tasks[r].append(t)
        task_routes.append(routes)
        task_escape.append(esc)

    # escape capacity must not bind (cap >= tasks that may take it)
    for g, load in agg_load.items():
        if int(cap[agg_sink_arc[g]]) < load:
            return _refuse(
                f"unsched agg {g}: sink cap {int(cap[agg_sink_arc[g]])} "
                f"< {load} tasks (binding escape)"
            )

    # disallowed cells: any finite value strictly above every escape
    # cost (escape capacity is unbounded, so such a cell is never
    # taken); keeping it small avoids int32 overflow under the
    # solver's internal n_scale cost scaling
    if rows_tasks:
        cost_mat = np.stack(row_cost)
        finite = cost_mat[cost_mat < BIG]
        hi = int(finite.max()) if finite.size else 0
        disallowed = max(hi, int(max(row_u))) + 1
        cost_mat = np.where(cost_mat >= BIG, disallowed, cost_mat)
        row_cost = list(cost_mat)

    # task_routes/task_escape are parallel to task_ids order; the
    # reconstructor re-keys them per task node id via the escape arc
    return GraphCollapse(
        supply=np.array([len(r) for r in rows_tasks], np.int32),
        col_cap=np.array([mt.capacity for mt in machines], np.int32),
        cost_cm=(
            np.stack(row_cost).astype(np.int64)
            if rows_tasks else np.zeros((0, M), np.int64)
        ),
        row_unsched=np.array(row_u, np.int64),
        machines=machines,
        pre_flows=pre_flows,
        rows_tasks=rows_tasks,
        task_routes=task_routes,
        task_escape=task_escape,
    ), ""


class AutoSolver(FlowSolver):
    """The automatic policy-dispatch seam: dense transport when the
    graph is collapsible, the CSR backend otherwise. Drop-in FlowSolver
    (PlacementSolver/FlowScheduler-compatible); `last_path` /
    `last_refusal` expose which way each solve went."""

    def __init__(self, csr_backend: FlowSolver,
                 alpha: int = 8, max_supersteps: int = 1 << 17):
        self.csr = csr_backend
        self.alpha = alpha
        self.max_supersteps = max_supersteps
        self.last_path = ""
        self.last_refusal = ""
        self.last_supersteps = 0

    def reset(self) -> None:
        self.csr.reset()

    def solve(self, problem) -> FlowResult:
        collapse, reason = try_collapse(problem)
        if collapse is None:
            self.last_path, self.last_refusal = "csr", reason
            res = self.csr.solve(problem)
            self.last_supersteps = getattr(
                self.csr, "last_supersteps", None
            ) or getattr(self.csr, "last_iterations", 0)
            return res
        self.last_path, self.last_refusal = "dense", ""
        return self._solve_dense(problem, collapse)

    def _solve_dense(self, problem, gc: GraphCollapse) -> FlowResult:
        from .layered import LayeredProblem, LayeredTransportSolver

        if not gc.rows_tasks:
            # nothing unplaced: only the folded pins' continuation flow
            flow = np.zeros(len(problem.src), np.int64)
            for a, units in gc.pre_flows:
                flow[a] += units
            self.last_supersteps = 0
            return FlowResult(
                flow=flow,
                objective=int(
                    (flow * np.asarray(problem.cost, np.int64)).sum()
                ) + lower_bound_cost(problem),
                iterations=0,
            )
        solver = LayeredTransportSolver(
            alpha=self.alpha, max_supersteps=self.max_supersteps
        )
        res = solver.solve_layered(LayeredProblem(
            supply=gc.supply,
            col_cap=gc.col_cap,
            cost_cm=gc.cost_cm.astype(np.int32),
            unsched_cost=0,
            ec_cost=0,
            row_unsched_cost=gc.row_unsched,
        ))
        self.last_supersteps = res.supersteps
        y = np.asarray(res.y, np.int64)

        # ---- exact per-arc flow reconstruction ----
        flow = np.zeros(len(problem.src), np.int64)
        # folded pinned units first: they consumed tree capacity at
        # audit time, so the greedy pushes below see the same residuals
        for a, units in gc.pre_flows:
            flow[a] += units
        # per-task lookups, keyed by node id via each escape arc's src
        esc_by_task: Dict[int, Tuple[int, int]] = {}
        routes_by_task: Dict[int, Dict[int, tuple]] = {}
        src = np.asarray(problem.src)
        for routes, esc in zip(gc.task_routes, gc.task_escape):
            t = int(src[esc[0]])
            esc_by_task[t] = esc
            routes_by_task[t] = routes

        def tree_cap(mt: _MachineTree, v: int) -> int:
            total = 0
            for a, child in mt.children.get(v, []):
                if child == -1:
                    total += int(problem.cap[a]) - int(flow[a])
                else:
                    total += min(
                        int(problem.cap[a]) - int(flow[a]),
                        tree_cap(mt, child),
                    )
            return total

        def push_down(mt: _MachineTree, v: int, units: int) -> None:
            """Distribute `units` down the machine tree (greedy against
            residual throughput; any split is optimal — path costs are
            uniform by audit)."""
            for a, child in mt.children.get(v, []):
                if units == 0:
                    return
                if child == -1:
                    room = int(problem.cap[a]) - int(flow[a])
                    take = min(units, room)
                    flow[a] += take
                    units -= take
                else:
                    room = min(
                        int(problem.cap[a]) - int(flow[a]),
                        tree_cap(mt, child),
                    )
                    take = min(units, room)
                    if take > 0:
                        push_down(mt, child, take)
                        flow[a] += take
                        units -= take
            assert units == 0, "tree capacity audit violated"

        for g, tasks in enumerate(gc.rows_tasks):
            grants = y[g]
            ti = 0
            for col in np.nonzero(grants > 0)[0]:
                n = int(grants[col])
                mt = gc.machines[col]
                for _ in range(n):
                    t = tasks[ti]
                    ti += 1
                    route = routes_by_task[t].get(int(col))
                    assert route is not None, (
                        "solver granted a disallowed cell — cost "
                        "dominance audit violated"
                    )
                    if route[0] == "d":
                        flow[route[1]] += 1
                    else:
                        for a in route[1:]:
                            flow[a] += 1
                push_down(mt, mt.node, n)
            for t in tasks[ti:]:  # escapes
                a1, a2 = esc_by_task[t]
                flow[a1] += 1
                flow[a2] += 1

        objective = int(
            (flow * np.asarray(problem.cost, np.int64)).sum()
        ) + lower_bound_cost(problem)
        return FlowResult(
            flow=flow, objective=objective, iterations=int(res.supersteps)
        )
