"""Generic FlowProblem -> dense-transport collapse with automatic CSR
fallback: the policy-dispatch seam of docs/solver_coverage.md, encoded.

The reference serves every policy through one solver seam
(scheduling/flow/placement/solver.go:36-38). The rebuild's production
path is the dense layered transport — exact whenever the graph is
"dense-collapsible" (no binding interior-EC capacities, cost-uniform
resource interiors, no per-task leaf arcs; docs/solver_coverage.md) —
with the CSR backends as the total-generality fallback. Until round 4
the CALLER chose the path; this module encodes the losslessness
predicate so the choice is automatic per solve:

    AutoSolver(csr_backend).solve(problem)
      -> try_collapse(problem): a full structural audit of the flat
         arc arrays. Collapsible -> group tasks into signature rows,
         solve ONE dense transport, reconstruct exact per-arc flows.
         Any refusal (with a reason, kept for observability) -> the
         general-graph backends, unchanged semantics: the VMEM-resident
         Pallas megakernel (solver/mega_solver.py) when the graph fits
         its tiling budget, else the scan-based CSR backend.

Soundness: every refusal is conservative (routing to CSR can only cost
time, never correctness), and the collapse itself is exact by the
signature argument of docs/solver_coverage.md — tasks with identical
(escape cost, effective machine-cost row) are interchangeable
commodities, and interior resource trees with a unique path cost fold
into per-column constants + tree capacities (computed as the exact
tree max-flow). Reconstructed flows satisfy conservation and caps by
construction; tests assert objective equality against the CSR oracle.

Collapsible today (the entire non-preempt planned-policy surface):
tasks -> {job unsched aggregator | equivalence classes | machines},
EC -> EC chains that cannot bind, EC -> machine routes, machine
subtrees with a unique per-machine path cost to the sink. Pinned
running tasks (preemption-off) arrive lower-bound-folded and cost
nothing. Keep-mode (preemption-on) graphs carry per-task running arcs
to leaves -> refused -> CSR, as are binding interior capacities and
any structure outside the audited shape.

Performance (round 5): the audit is vectorized end to end —
 * machine subtrees: a level-synchronized BFS over the interior arc
   arrays (owner / depth / path-cost accumulators per node, capacity
   by per-level segment sums) replaces the per-machine Python DFS;
 * EC routes: dense [nE, M] cost tables with (first-arc, next-EC)
   realization pointers replace per-EC column dicts;
 * task rows: one [T, M] numpy min-reduction + byte-view signature
   grouping replaces the per-task loop that iterated every EC route
   dict (measured 46 ms/round of the 57 ms audit at 10k x 1k).
Routes are realized lazily at decode, only for granted cells. The
remaining scalar loops (pin routing, EC chain build) run over plain
Python lists, not numpy scalars. See docs/NOTES.md round-5 section
for the before/after anatomy.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..graph.flowgraph import NodeType
from .base import FlowResult, FlowSolver, lower_bound_cost

_TASK_TYPES = (
    int(NodeType.ROOT_TASK),
    int(NodeType.SCHEDULED_TASK),
    int(NodeType.UNSCHEDULED_TASK),
)
_BELOW_MACHINE = (
    int(NodeType.NUMA),
    int(NodeType.SOCKET),
    int(NodeType.CACHE),
    int(NodeType.CORE),
    int(NodeType.PU),
)
_BM_SET = frozenset(_BELOW_MACHINE)
_MACH_T = int(NodeType.MACHINE)
_EC_T = int(NodeType.EQUIV_CLASS)
_AGG_T = int(NodeType.JOB_AGGREGATOR)

#: disallowed-cell cost; escape is always cheaper (remapped to a tight
#: bound before the solve to stay inside int32 cost scaling)
_BIG = 1 << 26


@dataclass
class GraphCollapse:
    """Everything needed to solve the dense form and reconstruct.

    Task-side structures are flat arrays parallel to `task_ids` (the
    audited tasks in node-id order); EC routes are dense [nE, M]
    tables realized lazily at decode via (ec_arc, ec_via) pointer
    chains; machine interiors are a (src-sorted arc, child) CSR the
    decode walks only for machines that actually receive grants."""

    supply: np.ndarray  # int32[G]
    col_cap: np.ndarray  # int32[M]
    cost_cm: np.ndarray  # int64[G, M] full placement cost per unit
    row_unsched: np.ndarray  # int64[G] full escape cost per unit
    machine_node: np.ndarray  # int64[M] machine node id per column
    pre_flows: List[Tuple[int, int]]  # folded pinned units (arc, units)
    # interior arcs sorted by (src, arc id): child == -1 -> sink
    dec_src: np.ndarray  # int64[A]
    dec_arc: np.ndarray  # int64[A]
    dec_child: np.ndarray  # int64[A]
    task_ids: np.ndarray  # int64[T] audited task node ids
    rows_tasks: List[np.ndarray]  # per row: indices into task_ids
    esc1: np.ndarray  # int64[T] task->agg arc
    esc2: np.ndarray  # int64[T] agg->sink arc
    # candidate placement arcs, grouped by kind (indices into task_ids)
    mac_t: np.ndarray  # int64[Dm] owning task index
    mac_col: np.ndarray  # int64[Dm] machine column
    mac_arc: np.ndarray  # int64[Dm] arc id
    mac_cost: np.ndarray  # int64[Dm]
    ect_t: np.ndarray  # int64[De] owning task index
    ect_ec: np.ndarray  # int64[De] EC row index
    ect_arc: np.ndarray  # int64[De] task->EC arc id
    ect_cost: np.ndarray  # int64[De]
    # dense EC route tables
    ec_cost_row: np.ndarray  # int64[nE, M] (_BIG = unreachable)
    ec_arc: np.ndarray  # int32[nE, M] first arc on the route
    ec_via: np.ndarray  # int32[nE, M] next EC row, -1 = direct machine


def _refuse(reason: str):
    return None, reason


def _csr_arcs(dec_src, dec_arc, dec_child, v: int):
    """(arc, child) pairs leaving node v in ascending-arc order, from
    the (src, arc)-sorted interior CSR; child == -1 means the sink.
    Shared by the audit's pin router and the decode's tree pushes so
    the two walkers cannot drift."""
    lo = np.searchsorted(dec_src, v)
    hi = np.searchsorted(dec_src, v, side="right")
    return zip(dec_arc[lo:hi].tolist(), dec_child[lo:hi].tolist())


def try_collapse(problem) -> Tuple[Optional[GraphCollapse], str]:
    """Audit a FlowProblem against the dense-collapsibility predicate.

    Returns (collapse, "") when lossless, (None, reason) otherwise.
    Pure host-side numpy over the flat arrays; O(nodes + arcs + G*M).
    """
    nt = np.asarray(problem.node_type)
    excess = np.asarray(problem.excess)
    src = np.asarray(problem.src)
    dst = np.asarray(problem.dst)
    cap = np.asarray(problem.cap)
    cost = np.asarray(problem.cost)
    N = len(nt)

    live = np.nonzero((src > 0) & (cap > 0))[0]
    sinks = np.nonzero(nt == int(NodeType.SINK))[0]
    if len(sinks) != 1:
        return _refuse(f"{len(sinks)} sink nodes")
    sink = int(sinks[0])

    # type-membership lookup tables (nt is small ints >= -1): one
    # fancy-index gather replaces a sort-based np.isin per category
    ntp = (nt + 1).astype(np.int64)
    _n_types = int(ntp.max()) + 2 if len(ntp) else 2
    bm_lut = np.zeros(_n_types, bool)
    bm_lut[[t + 1 for t in _BELOW_MACHINE if t + 1 < _n_types]] = True
    task_lut = np.zeros(_n_types, bool)
    task_lut[[t + 1 for t in _TASK_TYPES if t + 1 < _n_types]] = True

    # arc-wise scalar access below is confined to SMALL loops (pin
    # routing, EC chain build, agg arcs) — numpy scalar extraction is
    # fine there; the big sections are whole-array ops

    # no dict adjacency anywhere: EC arcs are classified with whole-
    # array ops, interior nodes get a sorted-CSR view below
    _ROUTABLE = _BM_SET | {_MACH_T}
    nt_src_live = nt[src[live]]
    out_arcs = live[nt_src_live == _EC_T]

    # interior arcs (live arcs leaving a machine or below-machine
    # node), as a (src, arc-id)-sorted CSR: the pin router and the
    # decode's greedy pushes walk it per node via binary search, in
    # the same ascending-arc order the old adjacency dict preserved
    is_int_src = (nt_src_live == _MACH_T) | bm_lut[ntp[src[live]]]
    int_arcs = live[is_int_src]
    ia_src = src[int_arcs]
    ia_dst = dst[int_arcs]
    _o = np.lexsort((int_arcs, ia_src))
    dec_arc = int_arcs[_o]
    dec_src = ia_src[_o].astype(np.int64)
    dec_child = np.where(dst[dec_arc] == sink, -1, dst[dec_arc]).astype(
        np.int64
    )


    # Positive excess: task nodes (one row unit each) or resource
    # nodes — the latter are lower-bound-FOLDED pinned running tasks
    # (preemption-off pins with cap_lower=1, graph_manager.go:675-720).
    # Folded units are greedily routed to the sink against residual
    # caps before the transport (see _route / pre_flows below); that
    # routing is cost-exact because the audit below proves every
    # leaf->sink path under a machine has one uniform cost, so the
    # greedy path's cost equals any other's. Their cost and flow are
    # charged into the reconstructed solution. Any other excess
    # pattern is outside the audited shape.
    _RESOURCE_TYPES = (_MACH_T,) + _BELOW_MACHINE
    pos = np.nonzero(excess > 0)[0]
    ok_lut = task_lut.copy()
    ok_lut[[t + 1 for t in _RESOURCE_TYPES if t + 1 < _n_types]] = True
    if not ok_lut[ntp[pos]].all():
        return _refuse("positive excess off tasks/resources")
    neg = np.nonzero(excess < 0)[0]
    if len(neg) > 1 or (len(neg) == 1 and int(neg[0]) != sink):
        return _refuse("negative excess off the sink")
    task_mask = task_lut[ntp]
    total_supply = int(excess[(excess > 0) & task_mask].sum())

    # ---- folded pinned units: route each resource node's positive
    # excess to the sink FIRST (the pinned task occupies its slot; the
    # occupancy-reduced interior caps — graph_manager.go:662-667 — mean
    # the unit typically has exactly its own leaf->sink hop left).
    # Machine capacities below are computed on the remaining caps. ----
    pre_flows: List[Tuple[int, int]] = []
    cap_res = cap.astype(np.int64)  # owned copy; pin routing mutates

    def _route(v: int, units: int) -> int:
        routed = 0
        for a, d in _csr_arcs(dec_src, dec_arc, dec_child, v):
            if units == 0:
                break
            if d == -1:  # sink
                take = min(units, int(cap_res[a]))
            elif int(nt[d]) in _ROUTABLE:
                take = _route(d, min(units, int(cap_res[a])))
            else:
                continue
            if take:
                cap_res[a] -= take
                pre_flows.append((a, take))
                units -= take
                routed += take
        return routed

    for v in pos.tolist():
        if int(nt[v]) in _ROUTABLE:
            e = int(excess[v])
            try:
                ok = _route(v, e) == e
            except RecursionError:
                return _refuse("graph too deep for collapse audit")
            if not ok:
                return _refuse(
                    f"resource {v}: folded pinned units exceed capacity"
                )

    # ---- machine subtrees: vectorized level-BFS over interior arcs.
    # Assign every reachable below-machine node an owning column, a
    # depth, and an accumulated path cost; refuse on re-reached nodes
    # (non-tree), non-resource interiors, and non-uniform sink path
    # costs. Capacity is the exact tree max-flow, computed by per-level
    # segment sums from the leaves up. Orphan below-machine nodes (not
    # reachable from any machine) are ignored, exactly as the old DFS
    # never visited them. ----
    machine_nodes = np.nonzero(nt == _MACH_T)[0]
    M = len(machine_nodes)
    if M == 0:
        return _refuse("no machine nodes")

    dst_is_sink = ia_dst == sink
    dst_is_bm = bm_lut[ntp[ia_dst]]
    dst_bad = ~(dst_is_sink | dst_is_bm)

    owner = np.full(N, -1, np.int64)  # owning column per node
    owner[machine_nodes] = np.arange(M)
    depth = np.full(N, -1, np.int64)
    depth[machine_nodes] = 0
    acc = np.zeros(N, np.int64)  # path cost from the machine root

    tree_sel = np.nonzero(dst_is_bm)[0]
    t_src = ia_src[tree_sel]
    t_dst = ia_dst[tree_sel]
    t_cost = cost[int_arcs[tree_sel]].astype(np.int64)
    active = np.ones(len(tree_sel), bool)
    for _ in range(N + 1):
        sel = np.nonzero(active & (depth[t_src] >= 0))[0]
        if not len(sel):
            break
        csrc, cdst = t_src[sel], t_dst[sel]
        already = depth[cdst] >= 0
        if already.any():
            m = int(machine_nodes[owner[csrc[already][0]]])
            return _refuse(
                f"machine {m}: non-tree interior (shared/diamond node)"
            )
        uq, cnt = np.unique(cdst, return_counts=True)
        if (cnt > 1).any():
            dup = uq[cnt > 1][0]
            m = int(machine_nodes[owner[csrc[cdst == dup][0]]])
            return _refuse(
                f"machine {m}: non-tree interior (shared/diamond node)"
            )
        owner[cdst] = owner[csrc]
        depth[cdst] = depth[csrc] + 1
        acc[cdst] = acc[csrc] + t_cost[sel]
        active[sel] = False

    # the audit itself is iterative, but the decode greedily pushes
    # units down the tree with recursive walks (push_down nests
    # tree_cap, so the stack can reach ~2x the tree depth plus the
    # caller's frames) — bound the depth against the REMAINING
    # recursion headroom so a pathological chain refuses here instead
    # of blowing the stack mid-decode (the refusal contract:
    # unauditable -> CSR)
    if len(tree_sel):
        import sys

        frame, live_frames = sys._getframe(), 0
        while frame is not None:
            live_frames += 1
            frame = frame.f_back
        headroom = sys.getrecursionlimit() - live_frames - 100
        if 4 * int(depth.max()) > headroom:
            return _refuse("graph too deep for collapse audit")

    assigned_src = depth[ia_src] >= 0
    bad = np.nonzero(dst_bad & assigned_src)[0]
    if len(bad):
        m = int(machine_nodes[owner[ia_src[bad]].min()])
        return _refuse(f"machine {m}: interior arc to a non-resource node")

    # sink-path uniformity + per-column path cost
    s_sel = np.nonzero(dst_is_sink & assigned_src)[0]
    s_cols = owner[ia_src[s_sel]]
    s_tot = acc[ia_src[s_sel]] + cost[int_arcs[s_sel]]
    col_path = np.zeros(M, np.int64)
    if len(s_sel):
        o = np.argsort(s_cols, kind="stable")
        cs, ts = s_cols[o], s_tot[o]
        starts = np.nonzero(np.r_[True, np.diff(cs) > 0])[0]
        mins = np.minimum.reduceat(ts, starts)
        maxs = np.maximum.reduceat(ts, starts)
        ne = np.nonzero(mins != maxs)[0]
        if len(ne):
            m = int(machine_nodes[cs[starts[ne[0]]]])
            return _refuse(f"machine {m}: non-uniform interior path costs")
        col_path[cs[starts]] = mins

    # exact tree max-flow, leaves up (per-level segment sums)
    aud = int_arcs[assigned_src]
    node_cap = np.zeros(N, np.int64)
    if len(aud):
        a_depth = depth[src[aud]]
        for d in range(int(a_depth.max()), -1, -1):
            s = aud[a_depth == d]
            sd = dst[s]
            contrib = np.where(
                sd == sink, cap_res[s],
                np.minimum(cap_res[s], node_cap[sd]),
            )
            node_cap += np.bincount(
                src[s], weights=contrib, minlength=N
            ).astype(np.int64)
    col_cap = node_cap[machine_nodes]

    # ---- task arcs, classified in one pass ----
    task_ids = np.nonzero(task_mask & (excess > 0))[0]
    T = len(task_ids)
    bad_excess = np.nonzero(excess[task_ids] != 1)[0]
    if len(bad_excess):
        t = int(task_ids[bad_excess[0]])
        return _refuse(f"task {t}: excess {int(excess[t])} != 1")
    tpos = np.full(N, -1, np.int64)
    tpos[task_ids] = np.arange(T)

    ta = live[tpos[src[live]] >= 0]  # all live arcs leaving a task
    ta_dst_t = nt[dst[ta]]
    is_agg = ta_dst_t == _AGG_T
    is_mac = ta_dst_t == _MACH_T
    is_ec = ta_dst_t == _EC_T
    other = ~(is_agg | is_mac | is_ec)
    if other.any():
        a = int(ta[other][0])
        return _refuse(
            f"task {int(src[a])}: arc to node type {int(nt[dst[a]])} "
            "(leaf/keep-mode?)"
        )
    ect_arcs = ta[is_ec]

    # ---- EC routing (chains folded; caps must never bind) ----
    ec_nodes = np.nonzero(nt == _EC_T)[0]
    nE = len(ec_nodes)
    ec_pos = np.full(N, -1, np.int64)
    ec_pos[ec_nodes] = np.arange(nE)
    ec_node_list = ec_nodes.tolist()
    # upper bound on flow through an EC: tasks with an arc into it,
    # PLUS everything its upstream ECs could forward (a chain-fed EC
    # sees the whole upstream inflow — counting only direct task arcs
    # would understate the bound to 0 and wave binding caps through)
    ec_direct_arr = (
        np.bincount(ec_pos[dst[ect_arcs]], minlength=nE)
        if len(ect_arcs) else np.zeros(nE, np.int64)
    )
    # classify every EC-source live arc in one pass
    el_dt = nt[dst[out_arcs]]
    e_isM = el_dt == _MACH_T
    e_isE = el_dt == _EC_T
    e_bad = ~(e_isM | e_isE)
    if e_bad.any():
        a = int(out_arcs[e_bad][0])
        return _refuse(
            f"EC {int(src[a])} arcs to node type {int(nt[dst[a]])}"
        )
    ee = out_arcs[e_isE]  # EC -> EC chain arcs (rare; scalar is fine)
    ec_parents: Dict[int, List[int]] = {e: [] for e in ec_node_list}
    for e_, d_ in zip(src[ee].tolist(), dst[ee].tolist()):
        if d_ in ec_parents:
            ec_parents[d_].append(e_)
    ec_direct = {
        e: int(c) for e, c in zip(ec_node_list, ec_direct_arr.tolist())
    }

    ec_inflow: Dict[int, object] = {}
    _PENDING = object()

    def inflow_of(e: int) -> int:
        got = ec_inflow.get(e)
        if got is _PENDING:
            raise ValueError("EC cycle")
        if got is not None:
            return got
        ec_inflow[e] = _PENDING
        total = ec_direct.get(e, 0) + sum(
            inflow_of(p) for p in ec_parents.get(e, [])
        )
        ec_inflow[e] = total
        return total

    try:
        for e in ec_node_list:
            inflow_of(e)
    except ValueError as err:
        return _refuse(str(err))
    except RecursionError:
        return _refuse("graph too deep for collapse audit")
    inflow_arr = (
        np.array([ec_inflow[e] for e in ec_node_list], np.int64)
        if nE else np.zeros(0, np.int64)
    )

    # dense route tables: per EC row, cheapest cost to every machine
    # column through EC->EC chains, with realization pointers (the
    # first arc + the next EC row, -1 = the arc lands on the machine).
    ec_cost_row = np.full((nE, M), _BIG, np.int64)
    ec_arc = np.full((nE, M), -1, np.int32)
    ec_via = np.full((nE, M), -1, np.int32)

    # EC -> machine arcs: binding checks + scatter, fully vectorized.
    # The arc can only bind if it could carry less than both the
    # feeding tasks AND the machine's own column capacity (which
    # already limits total inflow). The scatter writes costs in
    # DESCENDING order so the last (cheapest) write per cell wins.
    ma = out_arcs[e_isM]
    if len(ma):
        m_e = ec_pos[src[ma]]
        m_col = owner[dst[ma]]
        m_cap = cap[ma].astype(np.int64)
        bound = np.minimum(
            np.minimum(inflow_arr[m_e], total_supply), col_cap[m_col]
        )
        viol = np.nonzero(m_cap < bound)[0]
        if len(viol):
            a = int(ma[viol[0]])
            return _refuse(
                f"EC {int(src[a])}: machine arc cap {int(cap[a])} "
                "can bind"
            )
        m_cost = cost[ma].astype(np.int64)
        o = np.argsort(-m_cost, kind="stable")
        ec_cost_row[m_e[o], m_col[o]] = m_cost[o]
        ec_arc[m_e[o], m_col[o]] = ma[o]

    # EC -> EC chain arcs: binding checks vectorized; the chain fold
    # itself is a memoized DFS with M-vector min-merges per arc (the
    # inflow pass above already proved the chain graph acyclic)
    if len(ee):
        ee_cap = cap[ee].astype(np.int64)
        ee_bound = np.minimum(inflow_arr[ec_pos[src[ee]]], total_supply)
        viol = np.nonzero(ee_cap < ee_bound)[0]
        if len(viol):
            a = int(ee[viol[0]])
            return _refuse(
                f"EC {int(src[a])}: interior EC arc cap {int(cap[a])} "
                "can bind"
            )
        ee_by_row: Dict[int, list] = {}
        for a_, e_, d_ in zip(
            ee.tolist(), ec_pos[src[ee]].tolist(), ec_pos[dst[ee]].tolist()
        ):
            ee_by_row.setdefault(e_, []).append((a_, d_))
        ec_done: Dict[int, bool] = {}

        def build_ec(i: int) -> None:
            if ec_done.get(i):
                return
            ec_done[i] = True
            row, arow, vrow = ec_cost_row[i], ec_arc[i], ec_via[i]
            for a, j in ee_by_row.get(i, []):
                build_ec(j)
                child = ec_cost_row[j]
                cand = int(cost[a]) + child
                better = (child < _BIG) & (cand < row)
                row[better] = cand[better]
                arow[better] = a
                vrow[better] = j

        try:
            for i in range(nE):
                build_ec(i)
        except RecursionError:
            return _refuse("graph too deep for collapse audit")

    # ---- unsched aggregators (lookup over RAW arcs: a fully-drained
    # agg's sink arc has cap 0 and is absent from the live set; it only
    # matters if some task still routes to it — the escape-capacity
    # check below catches that) ----
    agg_sink_of = np.full(N, -1, np.int64)
    agg_mask = nt[src] == _AGG_T
    for a in np.nonzero((src > 0) & agg_mask)[0].tolist():
        g = src[a]
        if int(dst[a]) != sink:
            return _refuse(f"unsched agg {g}: non-sink arc")
        if agg_sink_of[g] >= 0:
            return _refuse(f"unsched agg {g}: multiple sink arcs")
        agg_sink_of[g] = a

    # ---- escapes: exactly one agg arc per task, agg must reach sink ----
    esc_arcs = ta[is_agg]
    esc_t = tpos[src[esc_arcs]]
    if T:
        esc_count = np.bincount(esc_t, minlength=T)
        multi = np.nonzero(esc_count > 1)[0]
        if len(multi):
            return _refuse(
                f"task {int(task_ids[multi[0]])}: two escape arcs"
            )
        none = np.nonzero(esc_count == 0)[0]
        if len(none):
            return _refuse(
                f"task {int(task_ids[none[0]])}: no unsched-aggregator arc"
            )
    esc1 = np.zeros(T, np.int64)
    esc1[esc_t] = esc_arcs
    esc_aggs = dst[esc1] if T else np.zeros(0, np.int64)
    esc2 = agg_sink_of[esc_aggs] if T else np.zeros(0, np.int64)
    no_sink = np.nonzero(esc2 < 0)[0]
    if len(no_sink):
        i = int(no_sink[0])
        return _refuse(
            f"task {int(task_ids[i])}: escape agg {int(esc_aggs[i])} "
            "has no sink arc"
        )
    u_eff = (
        cost[esc1].astype(np.int64) + cost[esc2]
        if T else np.zeros(0, np.int64)
    )

    # escape capacity must not bind (cap >= tasks that may take it)
    if T:
        aggs_u, agg_loads = np.unique(esc_aggs, return_counts=True)
        agg_caps = cap[agg_sink_of[aggs_u]]
        binding = np.nonzero(agg_caps < agg_loads)[0]
        if len(binding):
            i = int(binding[0])
            return _refuse(
                f"unsched agg {int(aggs_u[i])}: sink cap "
                f"{int(agg_caps[i])} < {int(agg_loads[i])} tasks "
                "(binding escape)"
            )

    # ---- effective cost rows: min over direct arcs and EC routes ----
    crow = np.full((T, M), _BIG, np.int64)

    mac_arcs = ta[is_mac]
    mac_t = tpos[src[mac_arcs]]
    mac_col = owner[dst[mac_arcs]]
    mac_cost = cost[mac_arcs].astype(np.int64)
    if len(mac_arcs):
        np.minimum.at(crow, (mac_t, mac_col), mac_cost)

    ect_t = tpos[src[ect_arcs]]
    ect_ec = ec_pos[dst[ect_arcs]]
    ect_cost = cost[ect_arcs].astype(np.int64)
    if len(ect_arcs):
        o = np.argsort(ect_t, kind="stable")
        owner_t = ect_t[o]
        child = ec_cost_row[ect_ec[o]]  # [De, M]
        cand = np.where(child >= _BIG, _BIG, ect_cost[o, None] + child)
        starts = np.nonzero(np.r_[True, np.diff(owner_t) > 0])[0]
        red = np.minimum.reduceat(cand, starts, axis=0)
        rows = owner_t[starts]
        crow[rows] = np.minimum(crow[rows], red)

    crow = np.where(crow >= _BIG, _BIG, crow + col_path[None, :])

    # ---- signature grouping: byte-view unique over (row, escape) ----
    if T:
        key = np.ascontiguousarray(
            np.concatenate([crow, u_eff[:, None]], axis=1)
        )
        kv = key.view(
            np.dtype((np.void, key.shape[1] * key.itemsize))
        ).reshape(T)
        _, first_idx, inv = np.unique(
            kv, return_index=True, return_inverse=True
        )
        supply = np.bincount(inv).astype(np.int32)
        order = np.argsort(inv, kind="stable")
        starts = np.nonzero(np.r_[True, np.diff(inv[order]) > 0])[0]
        rows_tasks = np.split(order, starts[1:])
        row_cost = crow[first_idx]
        row_u = u_eff[first_idx]
    else:
        supply = np.zeros(0, np.int32)
        rows_tasks = []
        row_cost = np.zeros((0, M), np.int64)
        row_u = np.zeros(0, np.int64)

    # disallowed cells: any finite value strictly above every escape
    # cost (escape capacity is unbounded, so such a cell is never
    # taken); keeping it small avoids int32 overflow under the
    # solver's internal n_scale cost scaling
    if T:
        finite = row_cost[row_cost < _BIG]
        hi = int(finite.max()) if finite.size else 0
        disallowed = max(hi, int(row_u.max())) + 1
        row_cost = np.where(row_cost >= _BIG, disallowed, row_cost)

    return GraphCollapse(
        supply=supply,
        col_cap=col_cap.astype(np.int32),
        cost_cm=row_cost,
        row_unsched=row_u,
        machine_node=machine_nodes.astype(np.int64),
        pre_flows=pre_flows,
        dec_src=dec_src, dec_arc=dec_arc.astype(np.int64),
        dec_child=dec_child,
        task_ids=task_ids.astype(np.int64),
        rows_tasks=rows_tasks,
        esc1=esc1,
        esc2=esc2,
        mac_t=mac_t, mac_col=mac_col,
        mac_arc=mac_arcs.astype(np.int64), mac_cost=mac_cost,
        ect_t=ect_t, ect_ec=ect_ec,
        ect_arc=ect_arcs.astype(np.int64), ect_cost=ect_cost,
        ec_cost_row=ec_cost_row, ec_arc=ec_arc, ec_via=ec_via,
    ), ""


class AutoSolver(FlowSolver):
    """The automatic policy-dispatch seam, now a FOUR-rung ladder by
    graph size: dense transport when the graph is collapsible, the
    VMEM-resident Pallas megakernel (solver/mega_solver.py) when a
    general graph fits the kernel's VMEM tiling budget, the scan-based
    CSR backend while its HBM working set fits one chip, and the
    SHARDED multi-chip backend (parallel/sharded_solver.py) beyond
    that. Drop-in FlowSolver (PlacementSolver/FlowScheduler-
    compatible); `last_path` ("dense" | "mega" | "csr" | "sharded") /
    `last_refusal` / `last_mega_refusal` expose which way each solve
    went and why.

    `mega` and `sharded` are optional: without them the ladder is the
    historical dense -> CSR dispatch. The cost model behind the mega
    rung is the kernel's live-set arithmetic (ops/mcmf_pallas.py
    mega_fits_vmem); the sharded rung mirrors it one level up the
    memory hierarchy (`scan_csr_fits_hbm` / `sharded_fits_hbm`,
    parallel/sharded_solver.py): escalation to the sharded rung
    happens exactly when the scan-CSR live set outgrows the per-chip
    HBM working-set budget AND the per-shard slice fits it — a graph
    too big even per-shard falls back to scan-CSR, the guaranteed-
    correct (if memory-risky) total rung. The budget resolves from
    `hbm_budget_bytes`, else the KSCHED_HBM_BUDGET env var, else
    DEFAULT_HBM_BUDGET_BYTES (docs/sharding.md derives it)."""

    def __init__(self, csr_backend: FlowSolver,
                 alpha: int = 8, max_supersteps: int = 1 << 17,
                 mega: Optional[FlowSolver] = None,
                 sharded=None,
                 hbm_budget_bytes: Optional[int] = None):
        self.csr = csr_backend
        self.mega = mega
        #: sharded rung: a FlowSolver, or a zero-arg factory resolved
        #: lazily on the first escalation (mesh construction and
        #: shard_map compiles cost nothing until a graph needs them)
        self._sharded = sharded
        if hbm_budget_bytes is None:
            import os

            env = os.environ.get("KSCHED_HBM_BUDGET")
            hbm_budget_bytes = int(env) if env else None
        self.hbm_budget_bytes = hbm_budget_bytes
        self.alpha = alpha
        self.max_supersteps = max_supersteps
        self.last_path = ""
        self.last_refusal = ""
        self.last_mega_refusal = ""
        self.last_supersteps = 0
        #: solver-interior telemetry of the rung that produced the last
        #: solve (obs/soltel.py); solve_traced publishes it
        self.last_telemetry = None

    @property
    def sharded(self):
        """The sharded rung, resolving a lazy factory on first use."""
        s = self._sharded
        if s is not None and not isinstance(s, FlowSolver) and callable(s):
            s = s()
            if not isinstance(s, FlowSolver):
                raise TypeError(
                    f"sharded factory returned {type(s).__name__}"
                )
            self._sharded = s
        return s

    def reset(self) -> None:
        self.csr.reset()
        if self.mega is not None:
            self.mega.reset()
        if isinstance(self._sharded, FlowSolver):
            self._sharded.reset()

    def _escalates_to_sharded(self, problem) -> bool:
        """The HBM fitting gate: True when the single-chip scan-CSR
        working set exceeds the per-chip budget AND the per-shard
        slice fits it (parallel/sharded_solver.py live-set
        arithmetic, mirroring mega_fits_vmem one memory level up)."""
        if self._sharded is None:
            return False
        from ..parallel.sharded_solver import (
            DEFAULT_HBM_BUDGET_BYTES,
            scan_csr_fits_hbm,
            sharded_fits_hbm,
        )

        budget = self.hbm_budget_bytes
        if budget is None:
            budget = DEFAULT_HBM_BUDGET_BYTES
        n_cap = problem.num_nodes
        m_cap = len(problem.src)
        if scan_csr_fits_hbm(n_cap, m_cap, budget):
            return False
        sharded = self.sharded  # resolve the factory: we need its mesh
        num_shards = getattr(sharded, "num_shards", 1)
        return sharded_fits_hbm(n_cap, m_cap, num_shards, budget)

    def solve(self, problem) -> FlowResult:
        collapse, reason = try_collapse(problem)
        if collapse is None:
            mega = self.mega
            if mega is not None and mega.fits(problem):
                self.last_path, self.last_refusal = "mega", reason
                self.last_mega_refusal = ""
                res = mega.solve(problem)
                self.last_supersteps = getattr(
                    mega, "last_supersteps", res.iterations
                )
                self.last_telemetry = getattr(mega, "last_telemetry", None)
                return res
            self.last_mega_refusal = (
                getattr(mega, "last_refusal", "") if mega is not None
                else "no megakernel attached"
            )
            if self._escalates_to_sharded(problem):
                sharded = self.sharded
                self.last_path, self.last_refusal = "sharded", reason
                res = sharded.solve(problem)
                self.last_supersteps = getattr(
                    sharded, "last_supersteps", res.iterations
                )
                self.last_telemetry = getattr(sharded, "last_telemetry", None)
                return res
            self.last_path, self.last_refusal = "csr", reason
            res = self.csr.solve(problem)
            ss = getattr(self.csr, "last_supersteps", None)
            self.last_supersteps = (
                ss if ss is not None
                else getattr(self.csr, "last_iterations", 0)
            )
            self.last_telemetry = getattr(self.csr, "last_telemetry", None)
            return res
        self.last_path, self.last_refusal = "dense", ""
        self.last_mega_refusal = ""
        return self._solve_dense(problem, collapse)

    def _solve_dense(self, problem, gc: GraphCollapse) -> FlowResult:
        from .layered import LayeredProblem, LayeredTransportSolver

        if not len(gc.supply):
            # nothing unplaced: only the folded pins' continuation flow
            flow = np.zeros(len(problem.src), np.int64)
            for a, units in gc.pre_flows:
                flow[a] += units
            self.last_supersteps = 0
            self.last_telemetry = None
            return FlowResult(
                flow=flow,
                objective=int(
                    (flow * np.asarray(problem.cost, np.int64)).sum()
                ) + lower_bound_cost(problem),
                iterations=0,
            )
        solver = LayeredTransportSolver(
            alpha=self.alpha, max_supersteps=self.max_supersteps
        )
        res = solver.solve_layered(LayeredProblem(
            supply=gc.supply,
            col_cap=gc.col_cap,
            cost_cm=gc.cost_cm.astype(np.int32),
            unsched_cost=0,
            ec_cost=0,
            row_unsched_cost=gc.row_unsched,
        ))
        self.last_supersteps = res.supersteps
        self.last_telemetry = solver.last_telemetry
        y = np.asarray(res.y, np.int64)

        # ---- exact per-arc flow reconstruction ----
        flow = np.zeros(len(problem.src), np.int64)
        # folded pinned units first: they consumed tree capacity at
        # audit time, so the greedy pushes below see the same residuals
        for a, units in gc.pre_flows:
            flow[a] += units

        # per-task candidate arcs (only granted cells realize a route)
        cands: Dict[int, list] = {}
        for tp, col, a, c in zip(
            gc.mac_t.tolist(), gc.mac_col.tolist(),
            gc.mac_arc.tolist(), gc.mac_cost.tolist(),
        ):
            cands.setdefault(tp, []).append(("d", a, col, c))
        for tp, ei, a, c in zip(
            gc.ect_t.tolist(), gc.ect_ec.tolist(),
            gc.ect_arc.tolist(), gc.ect_cost.tolist(),
        ):
            cands.setdefault(tp, []).append(("e", a, ei, c))
        esc1 = gc.esc1.tolist()
        esc2 = gc.esc2.tolist()
        ec_cost_row, ec_arc, ec_via = gc.ec_cost_row, gc.ec_arc, gc.ec_via
        cap_arr = np.asarray(problem.cap)
        dec_src, dec_arc, dec_child = gc.dec_src, gc.dec_arc, gc.dec_child

        def children_of(v: int):
            return _csr_arcs(dec_src, dec_arc, dec_child, v)

        def tree_cap(v: int) -> int:
            total = 0
            for a, child in children_of(v):
                if child == -1:
                    total += int(cap_arr[a]) - int(flow[a])
                else:
                    total += min(
                        int(cap_arr[a]) - int(flow[a]), tree_cap(child)
                    )
            return total

        def push_down(v: int, units: int) -> None:
            """Distribute `units` down the machine tree (greedy against
            residual throughput; any split is optimal — path costs are
            uniform by audit)."""
            for a, child in children_of(v):
                if units == 0:
                    return
                if child == -1:
                    room = int(cap_arr[a]) - int(flow[a])
                    take = min(units, room)
                    flow[a] += take
                    units -= take
                else:
                    room = min(
                        int(cap_arr[a]) - int(flow[a]), tree_cap(child)
                    )
                    take = min(units, room)
                    if take > 0:
                        push_down(child, take)
                        flow[a] += take
                        units -= take
            assert units == 0, "tree capacity audit violated"

        def realize(tp: int, col: int) -> None:
            """Push task tp's unit along its cheapest route to col."""
            best = None
            for kind, a, x, c in cands.get(tp, []):
                if kind == "d":
                    if x != col:
                        continue
                    cc = c
                else:
                    r = int(ec_cost_row[x, col])
                    if r >= _BIG:
                        continue
                    cc = c + r
                if best is None or cc < best[0]:
                    best = (cc, kind, a, x)
            assert best is not None, (
                "solver granted a disallowed cell — cost "
                "dominance audit violated"
            )
            _, kind, a, x = best
            flow[a] += 1
            if kind == "e":
                e = x
                while True:
                    flow[int(ec_arc[e, col])] += 1
                    nxt = int(ec_via[e, col])
                    if nxt < 0:
                        break
                    e = nxt

        machine_node = gc.machine_node.tolist()
        for g, tasks in enumerate(gc.rows_tasks):
            grants = y[g]
            ti = 0
            task_list = tasks.tolist()
            for col in np.nonzero(grants > 0)[0].tolist():
                n = int(grants[col])
                for _ in range(n):
                    realize(task_list[ti], col)
                    ti += 1
                push_down(machine_node[col], n)
            for tp in task_list[ti:]:  # escapes
                flow[esc1[tp]] += 1
                flow[esc2[tp]] += 1

        objective = int(
            (flow * np.asarray(problem.cost, np.int64)).sum()
        ) + lower_bound_cost(problem)
        return FlowResult(
            flow=flow, objective=objective, iterations=int(res.supersteps)
        )
