"""L3: the graph-change journal.

Reference: scheduling/flow/dimacs/{change.go,change_stats.go,*_change.go}
and scheduling/flow/flowmanager/graph_change_manager.go. Every graph
mutation flows through the ChangeManager, which journals it as a typed
change record. In the reference the journal is serialized to DIMACS text
for the solver subprocess; here the journal is scattered into flat device
arrays by the exporter (graph/device_export.py) — the wire format became
array indices. A DIMACS text codec is kept in graph/dimacs.py for
debugging and golden-file parity.

The four structural change kinds mirror the reference's incremental
DIMACS lines (add node / remove node / new arc / change arc), and the
36-bucket ChangeType taxonomy mirrors dimacs/change_stats.go:19-58 —
including per-type accumulation, which the reference left as a TODO stub
(change_stats.go:96-98).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Union

from .flowgraph import Arc, ArcType, FlowGraph, Node, NodeType


class ChangeType(enum.IntEnum):
    """Reference: dimacs/change_stats.go:19-58."""

    ADD_TASK_NODE = 0
    ADD_RESOURCE_NODE = 1
    ADD_EQUIV_CLASS_NODE = 2
    ADD_UNSCHED_JOB_NODE = 3
    ADD_SINK_NODE = 4
    ADD_ARC_TASK_TO_EQUIV_CLASS = 5
    ADD_ARC_TASK_TO_RES = 6
    ADD_ARC_EQUIV_CLASS_TO_RES = 7
    ADD_ARC_BETWEEN_EQUIV_CLASS = 8
    ADD_ARC_BETWEEN_RES = 9
    ADD_ARC_TO_UNSCHED = 10
    ADD_ARC_FROM_UNSCHED = 11
    ADD_ARC_RUNNING_TASK = 12
    ADD_ARC_RES_TO_SINK = 13
    DEL_UNSCHED_JOB_NODE = 14
    DEL_TASK_NODE = 15
    DEL_RESOURCE_NODE = 16
    DEL_EQUIV_CLASS_NODE = 17
    DEL_ARC_EQUIV_CLASS_TO_RES = 18
    DEL_ARC_RUNNING_TASK = 19
    DEL_ARC_EVICTED_TASK = 20
    DEL_ARC_BETWEEN_EQUIV_CLASS = 21
    DEL_ARC_BETWEEN_RES = 22
    DEL_ARC_TASK_TO_EQUIV_CLASS = 23
    DEL_ARC_TASK_TO_RES = 24
    CHG_ARC_EVICTED_TASK = 25
    CHG_ARC_TO_UNSCHED = 26
    CHG_ARC_FROM_UNSCHED = 27
    CHG_ARC_TASK_TO_EQUIV_CLASS = 28
    CHG_ARC_TASK_TO_RES = 29
    CHG_ARC_EQUIV_CLASS_TO_RES = 30
    CHG_ARC_BETWEEN_EQUIV_CLASS = 31
    CHG_ARC_BETWEEN_RES = 32
    CHG_ARC_RES_TO_SINK = 33
    CHG_ARC_RUNNING_TASK = 34
    CHG_ARC_TASK_TO_UNSCHED = 35


@dataclass(frozen=True)
class AddNodeChange:
    """Incremental 'add node' record (reference: dimacs/add_node_change.go)."""

    node_id: int
    excess: int
    node_type: NodeType
    comment: str = ""


@dataclass(frozen=True)
class RemoveNodeChange:
    """Reference: dimacs/remove_node_change.go."""

    node_id: int
    comment: str = ""


@dataclass(frozen=True)
class NewArcChange:
    """Reference: dimacs/create_arc_change.go."""

    src: int
    dst: int
    cap_lower: int
    cap_upper: int
    cost: int
    arc_type: ArcType
    comment: str = ""


@dataclass(frozen=True)
class ChangeArcChange:
    """Reference: dimacs/update_arc_change.go (carries old_cost so a
    solver can cheaply detect pure capacity changes)."""

    src: int
    dst: int
    cap_lower: int
    cap_upper: int
    cost: int
    arc_type: ArcType
    old_cost: int
    comment: str = ""


Change = Union[AddNodeChange, RemoveNodeChange, NewArcChange, ChangeArcChange]


class ChangeStats:
    """Per-round mutation counters (reference: dimacs/change_stats.go:62-98;
    per-type accumulation implemented here rather than stubbed)."""

    def __init__(self) -> None:
        self.nodes_added = 0
        self.nodes_removed = 0
        self.arcs_added = 0
        self.arcs_changed = 0
        self.arcs_removed = 0
        self.by_type: Dict[ChangeType, int] = {t: 0 for t in ChangeType}

    def update(self, change_type: ChangeType, change: Change) -> None:
        self.by_type[change_type] += 1
        if isinstance(change, AddNodeChange):
            self.nodes_added += 1
        elif isinstance(change, RemoveNodeChange):
            self.nodes_removed += 1
        elif isinstance(change, NewArcChange):
            self.arcs_added += 1
        elif isinstance(change, ChangeArcChange):
            if change.cap_lower == 0 and change.cap_upper == 0:
                self.arcs_removed += 1
            else:
                self.arcs_changed += 1

    def reset(self) -> None:
        self.__init__()

    def to_csv(self) -> str:
        """Reference: dimacs/change_stats.go:70-82."""
        totals = [
            self.nodes_added,
            self.nodes_removed,
            self.arcs_added,
            self.arcs_changed,
            self.arcs_removed,
        ]
        per_type = [self.by_type[t] for t in ChangeType]
        return ",".join(str(v) for v in totals + per_type)


class ChangeManager:
    """The sole mutation path for the flow graph; journals every change
    for the next incremental solve (reference:
    flowmanager/graph_change_manager.go:71-218).

    Keeps the reference's no-op short-circuits (idempotent ChangeArc calls
    journal nothing) and its delete-is-capacity-zero convention, which is
    what makes warm-started incremental re-solves sound.
    """

    def __init__(self, stats: Optional[ChangeStats] = None) -> None:
        self.graph = FlowGraph()
        self.stats = stats if stats is not None else ChangeStats()
        self._journal: List[Change] = []
        # (src, dst) -> index in _journal of the latest arc record, for O(1)
        # merge-to-same-arc. Safe because an arc record for (src, dst) always
        # postdates any structural change to its endpoints (arcs are detached
        # before node removal and re-journaled on re-add).
        self._arc_index: Dict[tuple, int] = {}
        # Optimization passes over the journal (reference declares these
        # flags at graph_change_manager.go:72-76 but panics in the passes;
        # we implement merge-to-same-arc for real).
        self.remove_duplicate = True

    # -- journal ----------------------------------------------------------

    def _record(self, change_type: ChangeType, change: Change) -> None:
        self.stats.update(change_type, change)
        if self.remove_duplicate and self._merge(change):
            return
        if isinstance(change, (NewArcChange, ChangeArcChange)):
            self._arc_index[(change.src, change.dst)] = len(self._journal)
        self._journal.append(change)

    def _merge(self, change: Change) -> bool:
        """Collapse repeated updates to the same arc into one journal entry
        (the reference's unimplemented MergeChangesToSameArc,
        graph_change_manager.go:243-261)."""
        if not isinstance(change, ChangeArcChange):
            return False
        idx = self._arc_index.get((change.src, change.dst))
        if idx is None:
            return False
        prev = self._journal[idx]
        if isinstance(prev, NewArcChange):
            self._journal[idx] = NewArcChange(
                src=prev.src,
                dst=prev.dst,
                cap_lower=change.cap_lower,
                cap_upper=change.cap_upper,
                cost=change.cost,
                arc_type=prev.arc_type,
                comment=prev.comment,
            )
        else:
            self._journal[idx] = ChangeArcChange(
                src=prev.src,
                dst=prev.dst,
                cap_lower=change.cap_lower,
                cap_upper=change.cap_upper,
                cost=change.cost,
                arc_type=change.arc_type,
                old_cost=prev.old_cost,
                comment=prev.comment,
            )
        return True

    def get_graph_changes(self) -> List[Change]:
        return list(self._journal)

    def get_optimized_graph_changes(self) -> List[Change]:
        return list(self._journal)

    def reset_changes(self) -> None:
        self._journal.clear()
        self._arc_index.clear()

    @property
    def has_changes(self) -> bool:
        return bool(self._journal)

    # -- mutations (reference: graph_change_manager.go:93-193) ------------

    def add_node(
        self,
        node_type: NodeType,
        excess: int,
        change_type: ChangeType,
        comment: str = "",
    ) -> Node:
        node = self.graph.add_node()
        node.type = node_type
        node.excess = excess
        node.comment = comment
        self._record(change_type, AddNodeChange(node.id, excess, node_type, comment))
        return node

    def delete_node(self, node: Node, change_type: ChangeType, comment: str = "") -> None:
        # Journal arc removals implied by the node removal so the device
        # exporter can invalidate their slots.
        for arc in list(node.outgoing.values()):
            self._record(
                change_type,
                ChangeArcChange(arc.src, arc.dst, 0, 0, arc.cost, arc.type, arc.cost, "DeleteNode: implied arc removal"),
            )
        for arc in list(node.incoming.values()):
            self._record(
                change_type,
                ChangeArcChange(arc.src, arc.dst, 0, 0, arc.cost, arc.type, arc.cost, "DeleteNode: implied arc removal"),
            )
        self.graph.delete_node(node)
        self._record(change_type, RemoveNodeChange(node.id, comment))

    def add_arc(
        self,
        src: Node,
        dst: Node,
        cap_lower: int,
        cap_upper: int,
        cost: int,
        arc_type: ArcType,
        change_type: ChangeType,
        comment: str = "",
    ) -> Arc:
        arc = self.graph.add_arc(src, dst)
        arc.cap_lower = cap_lower
        arc.cap_upper = cap_upper
        arc.cost = cost
        arc.type = arc_type
        self._record(
            change_type,
            NewArcChange(src.id, dst.id, cap_lower, cap_upper, cost, arc_type, comment),
        )
        return arc

    def change_arc(
        self,
        arc: Arc,
        cap_lower: int,
        cap_upper: int,
        cost: int,
        change_type: ChangeType,
        comment: str = "",
    ) -> None:
        """No-op short-circuit when nothing changes (reference:
        graph_change_manager.go:142-156)."""
        if arc.cap_lower == cap_lower and arc.cap_upper == cap_upper and arc.cost == cost:
            return
        old_cost = arc.cost
        self.graph.change_arc(arc, cap_lower, cap_upper, cost)
        self._record(
            change_type,
            ChangeArcChange(arc.src, arc.dst, cap_lower, cap_upper, cost, arc.type, old_cost, comment),
        )

    def change_arc_capacity(self, arc: Arc, cap_upper: int, change_type: ChangeType, comment: str = "") -> None:
        self.change_arc(arc, arc.cap_lower, cap_upper, arc.cost, change_type, comment)

    def change_arc_cost(self, arc: Arc, cost: int, change_type: ChangeType, comment: str = "") -> None:
        self.change_arc(arc, arc.cap_lower, arc.cap_upper, cost, change_type, comment)

    def delete_arc(self, arc: Arc, change_type: ChangeType, comment: str = "") -> None:
        """Delete = capacity→0 journal entry, then detach (reference:
        graph_change_manager.go:184-193)."""
        old_cost = arc.cost
        self.graph.change_arc(arc, 0, 0, arc.cost)
        self._record(
            change_type,
            ChangeArcChange(arc.src, arc.dst, 0, 0, arc.cost, arc.type, old_cost, comment),
        )
        self.graph.delete_arc(arc)
