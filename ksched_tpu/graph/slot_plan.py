"""Slot-stable CSR plan: scatter-maintained entry layout for scan-CSR.

The scan-CSR solver (solver/jax_solver.py) needs its doubled residual
entries grouped per source node so segment reductions stay in
cumsum/gather/associative-scan form (no scatters on the solve path).
The original `build_csr_plan` derives that grouping by argsorting the
2M entries by endpoint every time any arc ENDPOINT changes — an
O(M log M) host pass plus a full plan re-upload per endpoint-churn
round, the last O(graph) cost on the event path after r11 made the
problem arrays delta-sized.

This module replaces the per-round rebuild with a MAINTAINED layout,
the same move `scheduler/bulk.py` makes by pre-wiring arc endpoints:

- every node owns a contiguous REGION of the entry table, sized to
  its degree high-water mark plus slack; segment-boundary tensors
  (`seg_start`/`is_start`/`node_first`/`node_last`/`node_nonempty`)
  therefore change only when a region MOVES (relocation, below) —
  ordinary endpoint churn never touches them;
- each live arc slot owns two plan rows (forward entry in its src's
  region, backward in its dst's region), assigned when the slot's
  endpoints are set and freed when the arc is removed. Within a
  region, forward rows fill from the FRONT and backward rows from
  the BACK — a load-bearing invariant, not bookkeeping taste: the
  discharge allocates each node's excess over its admissible entries
  front-to-back, and backward rows ahead of forward ones soak pushes
  into bounce-back moves (measured: interleaved wiring order drove
  fresh-restart supersteps 10 → 17-23 within six churn rounds;
  restoring the split restores ~10). Liveness is encoded in the sign
  column (`p_sign` in {+1, -1, 0}): a dead row has sign 0 and the
  solver's slot-stable residual formula makes it contribute nothing
  to any reduction — no separate mask tensor, no extra gathers;
- an endpoint change within existing slots (slot recycle — the churn
  workload's task-completion/arrival dance) mutates O(1) plan rows,
  journaled as dirty positions and shipped as pow2-padded int32
  records applied by ONE jit'd scatter (`plan_apply_fn`, the second
  and last scoped scatter exemption after the problem-delta apply);
- the host mirror of the plan tensors is maintained in place, so the
  "full-rebuild" path is a straight re-upload of the same values the
  scatter path maintains incrementally — which is what makes
  scatter-vs-rebuild parity assertable bit-for-bit (flows,
  supersteps, telemetry rows), and what keeps the sync / pipelined /
  device-resident service loops placement-identical;
- host argsort + full plan re-upload survive ONLY on `full_build`
  (slot table reassigned), pow2 bucket growth (n_cap/m_cap), and
  tail-pool exhaustion — all counted on `layout_rebuilds`;
- regions are sized by a per-node-id degree HIGH-WATER MARK that
  persists across layouts, not by the instantaneous degree. Node ids
  are recycled (flowgraph.py free-list), and the recycled id's new
  tenant routinely needs more rows than the old one held at layout
  time — a completed (bound) task carries ~2 arcs while the arriving
  task that inherits its id wires a full preference set. Sizing by
  current degree alone makes that mismatch overflow a region EVERY
  churn round (measured: 24/24 bench rounds degenerated to layout
  rebuilds); with the high-water mark each id overflows at most when
  it sets a new degree record;
- on top of the high-water mark, active nodes get slack headroom
  (+2 rows plus 25% of the mark, granted whole in descending
  churn × region-size order — the weight is the expected relocation
  cost saved), funded strictly from the pow2 surplus the entry
  table already carries — `entry_cap` never grows past the bare-hwm
  sizing, so solver cost is untouched. This matters because
  aggregator occupancy (EQUIV_CLASS / PU / machine nodes)
  random-walks under churn: somewhere in the fleet a node beats its
  record by +1 nearly every round (measured: bare-hwm sizing still
  rebuilt every other round, one fresh record-setter per rebuild),
  and exact-mark regions turn every record into a rebuild. The mark
  DECAYS toward the instantaneous degree at each rebuild (halving
  the excess), so one fill-time spike cannot inflate the entry
  budget forever;
- a node that out-churns its region anyway is RELOCATED, not
  rebuilt around: the surplus left after slack grants stays past
  the packed spans as a shared TAIL POOL, and `_relocate` moves the
  node's live rows into a grown (1.25x) region — best-fit from the
  dead-span list (returned spans coalesce with neighbours and the
  tail frontier, and loose fits split, so churn cannot shred the
  arena), else fresh tail — in O(degree) host writes, journaled
  through the same per-round scatter as ordinary endpoint churn
  (the segment-boundary tensors gain their own record stream:
  relocation rewires `seg_start`/`is_start` rows for the new span
  and the node's `node_first`/`node_last`/`node_nonempty` entries;
  the abandoned span keeps its — now all-dead — segment structure,
  which no reduction ever samples);
- fresh regions (an id with no history: node ids are recycled, so
  the per-round EPHEMERAL aggregators — born, grown to full size,
  drained, freed — reappear under a different id every round) are
  sized by the node TYPE's degree record (reset per rebuild — the
  fill-time giants must not ghost-poison it), capped by pool health,
  so they claim one right-sized span instead of laddering 4→8→…→64
  through the pool; a node that empties returns a BIG span to the
  pool (the dying aggregator funds its successor) while small spans
  stay attached to the id as recycle insurance — the next tenant of
  a completed task's id refills in place, zero relocations, zero
  journal bytes. A full layout rebuild therefore survives ONLY
  full_build, pow2 bucket growth, and tail-pool exhaustion
  (`region_overflows`, the rare compaction case).

Entry position 0 is permanently reserved and dead: freed slots'
`inv_order` rows are parked there, so a stale slot can never alias a
live row's push allocation.

SHARDED layout mode (``enable_sharding(D)``, the multi-chip rung —
parallel/sharded_solver.py): the table is laid out as D equal-extent
per-shard BLOCKS, block d holding exactly the regions of the nodes
shard d owns (``shard_owner``'s contiguous id ranges — and regions
were ALWAYS allocated in node-id order, so this is the same layout
with per-block bases). Each block reserves its local position 0 as a
per-shard dead slot and keeps its own tail arena + dead-span list, so
relocation traffic stays owner-local and the maintained entry tensors
reshape losslessly to ``[D, E/D]`` stacked per-shard tables — the
sharded solver's plan IS the reshaped global plan, no second
allocator, no drift. Entry order within every node's region is
unchanged, so a single-chip consumer of the same plan (the jax
ladder rung below the sharded one) solves bit-identically to the
unsharded layout.
"""

from __future__ import annotations

import functools
import heapq
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils import next_pow2

#: int32 columns of one packed plan-row record:
#: (position, arc slot, sign, src, dst)
PLAN_RECORD_COLS = 5
#: int32 columns of one packed inv-order record: (entry index, position)
INV_RECORD_COLS = 2
#: int32 columns of one packed segment-static record (relocations):
#: (position, seg_start value, is_start flag)
SEG_RECORD_COLS = 3
#: int32 columns of one packed node-static record (relocations):
#: (node, node_first, node_last, node_nonempty flag)
NODE_RECORD_COLS = 4


def _pad_records(k: int) -> int:
    from .device_export import pad_record_count

    return pad_record_count(k)


def shard_owner(node_ids, num_nodes: int, num_shards: int) -> np.ndarray:
    """Owner shard per node id: contiguous range partition, so resource
    subtrees laid out contiguously stay on one shard. The SAME
    arithmetic the sharded solve kernel re-derives from iota on device
    (parallel/sharded_solver.py re-exports this as ``node_owner``) —
    one source of truth for who owns what."""
    per = -(-num_nodes // max(num_shards, 1))
    return np.minimum(np.asarray(node_ids) // per, num_shards - 1)


_PLAN_APPLY = None


def plan_apply_fn():
    """The SECOND (and last) scoped scatter exemption of the solver
    stack: applies a round's packed plan-row + inv-order + segment-
    static + node-static records to the persistent device plan
    tensors. Like the problem-delta apply
    (graph/device_export.delta_apply_fn) it is O(records), runs once
    per round, and is pinned by the jaxpr contracts: the exemption is
    non-vacuous (it really scatters), 32-bit, and hash-stable within a
    pow2 record bucket. Records are padded by repeating a real row
    (idempotent duplicates), and the host coalesces multiple writes to
    one position before packing, so scatter ordering can never matter.

    The segment/node statics ride the same program (not a third
    exemption): on ordinary endpoint-churn rounds their record
    streams are empty pads (an idempotent rewrite of the permanently
    dead position 0 / node 0's current meta); they carry real dirt
    only when a region RELOCATION moved a node's rows into the tail
    pool (module docstring).
    """
    global _PLAN_APPLY
    if _PLAN_APPLY is None:
        import jax

        # All ten plan tensors are DONATED: the scatter updates the
        # persistent buffers in place.
        @functools.partial(
            jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5, 6, 7, 8, 9)  # kschedlint: program=plan_apply
        )
        def _apply_plan(
            p_arc, p_sign, p_src, p_dst, inv_order,
            seg_start, is_start, node_first, node_last, node_nonempty,
            row_rec, inv_rec, seg_rec, node_rec,
        ):
            pos = row_rec[:, 0]
            p_arc = p_arc.at[pos].set(row_rec[:, 1])
            p_sign = p_sign.at[pos].set(row_rec[:, 2])
            p_src = p_src.at[pos].set(row_rec[:, 3])
            p_dst = p_dst.at[pos].set(row_rec[:, 4])
            inv_order = inv_order.at[inv_rec[:, 0]].set(inv_rec[:, 1])
            spos = seg_rec[:, 0]
            seg_start = seg_start.at[spos].set(seg_rec[:, 1])
            is_start = is_start.at[spos].set(seg_rec[:, 2] != 0)
            nid = node_rec[:, 0]
            node_first = node_first.at[nid].set(node_rec[:, 1])
            node_last = node_last.at[nid].set(node_rec[:, 2])
            node_nonempty = node_nonempty.at[nid].set(node_rec[:, 3] != 0)
            return (
                p_arc, p_sign, p_src, p_dst, inv_order,
                seg_start, is_start, node_first, node_last, node_nonempty,
            )

        _PLAN_APPLY = _apply_plan
    return _PLAN_APPLY


class SlotPlanState:
    """Maintained slot-stable plan over a DeviceGraphState's arc slots.

    Created as an inert shell on every DeviceGraphState; it costs
    nothing until a slot-stable consumer (JaxSolver) calls
    ``ensure_built()``, which flips ``enabled`` and builds the first
    layout. From then on the DeviceGraphState's ``_set_arc`` hooks
    keep it in sync per mutation (O(1) each), and the device-resident
    mirror drains ``drain_records()`` once per round.
    """

    def __init__(self, state) -> None:
        self.state = state  # owning DeviceGraphState
        self.enabled = False
        self.needs_rebuild = True
        self.layout_gen = 0  # bumped per layout (re)build
        self.value_version = 0  # bumped per mutation batch and rebuild
        self.static_version = 0  # bumped per relocation and rebuild
        self.layout_rebuilds = 0  # full rebuilds (telemetry)
        self.region_overflows = 0  # rebuilds forced by tail-pool exhaustion
        self.region_relocations = 0  # regions moved to the tail pool
        # ---- layout (static per layout_gen) --------------------------
        self.entry_cap = 0  # E: padded entry-table extent
        self.region_start: Optional[np.ndarray] = None  # int32[n_cap]
        self.region_cap: Optional[np.ndarray] = None  # int32[n_cap]
        self.seg_start: Optional[np.ndarray] = None  # int32[E]
        self.is_start: Optional[np.ndarray] = None  # bool[E]
        self.node_first: Optional[np.ndarray] = None  # int32[n_cap]
        self.node_last: Optional[np.ndarray] = None  # int32[n_cap]
        self.node_nonempty: Optional[np.ndarray] = None  # bool[n_cap]
        # ---- values (scatter-maintained) -----------------------------
        self.p_arc: Optional[np.ndarray] = None  # int32[E]
        self.p_sign: Optional[np.ndarray] = None  # int32[E] {+1,-1,0}
        self.p_src: Optional[np.ndarray] = None  # int32[E]
        self.p_dst: Optional[np.ndarray] = None  # int32[E]
        self.inv_order: Optional[np.ndarray] = None  # int32[2*m_cap]
        self.pos_fwd: Optional[np.ndarray] = None  # int32[m_cap], -1 unassigned
        self.pos_bwd: Optional[np.ndarray] = None  # int32[m_cap]
        # ---- allocation state ----------------------------------------
        #: forward-row frontier (ascends from region start) and
        #: backward-row frontier (descends from region end) — forward
        #: rows fill the front, backward rows the back (load-bearing;
        #: see _rebuild)
        self._next_seq: Optional[np.ndarray] = None  # int64[n_cap]
        self._next_back: Optional[np.ndarray] = None  # int64[n_cap]
        self._freed_f: Dict[int, List[int]] = {}  # node -> min-heap (fwd side)
        self._freed_b: Dict[int, List[int]] = {}  # node -> max-heap, negated (bwd side)
        #: live rows currently in each node's region, and the max ever
        #: seen per node id (region sizing input — survives rebuilds;
        #: see the module docstring's recycled-id rationale)
        self._occ: Optional[np.ndarray] = None  # int64[n_cap]
        self._deg_hwm = np.zeros(0, np.int64)  # kschedlint: host-only (host allocation bookkeeping)
        #: max degree ever seen per node TYPE — sizes the first span of
        #: a fresh region, where the id has no history (see _rebuild)
        self._type_hwm: Dict[int, int] = {}
        #: cumulative alloc/release events per node id — the slack
        #: rationing weight (churn-hot nodes get headroom first);
        #: persists across rebuilds like the high-water mark
        self._churn_ct = np.zeros(0, np.int64)  # kschedlint: host-only (host allocation bookkeeping)
        #: first unassigned tail-pool position PER SHARD BLOCK
        #: (relocation arena; one block covering the whole table in
        #: the default single-shard layout)
        self._tail_next = np.zeros(1, np.int64)  # kschedlint: host-only (host allocation bookkeeping)
        #: abandoned (start, cap) spans per shard block — relocation
        #: reuses them best-fit before carving fresh tail, so moves
        #: don't leak
        self._dead_spans: List[List[Tuple[int, int]]] = [[]]
        #: sharded layout mode (enable_sharding): block count, equal
        #: per-block extent (== entry_cap when unsharded), and the
        #: node -> owner-shard map of the current layout
        self._num_shards = 1
        self.block_extent = 0
        self._owner = np.zeros(0, np.int64)  # kschedlint: host-only (host allocation bookkeeping)
        # ---- dirty journal (for the device scatter) ------------------
        self._dirty_pos: set = set()
        self._dirty_inv: set = set()
        self._dirty_seg: set = set()  # relocated segment statics
        self._dirty_node: set = set()  # relocated node statics
        # ---- device caches (non-resident full-upload path) -----------
        self._static_dev: Optional[Tuple] = None  # (layout_gen, tensors)
        self._values_dev: Optional[Tuple] = None  # (layout_gen, version, tensors)

    # -- pickling (the warm-restore manifest, runtime/checkpoint.py) -------

    def __getstate__(self):
        # the device caches hold live jax buffers; they are rebuilt on
        # first use in the restored process
        state = dict(self.__dict__)
        state["_static_dev"] = None
        state["_values_dev"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)

    # -- lifecycle ---------------------------------------------------------

    def invalidate(self) -> None:
        """Layout is stale (full_build / pow2 growth / region
        overflow): the next consumer rebuilds from the arrays.
        Mutation hooks no-op until then — the rebuild reads final
        state, so per-entry dirt in between is noise."""
        self.needs_rebuild = True
        self._dirty_pos.clear()
        self._dirty_inv.clear()
        self._dirty_seg.clear()
        self._dirty_node.clear()

    def ensure_built(self) -> None:
        self.enabled = True
        if self.needs_rebuild:
            self._rebuild()

    def enable_sharding(self, num_shards: int) -> None:
        """Switch every FUTURE layout to the per-shard block form (see
        the module docstring): block d holds the regions of exactly
        the nodes shard d owns, with a per-block reserved dead slot
        and a shard-local tail arena. Idempotent; a shard-count change
        invalidates the layout (the sharded solver owns exactly one
        mesh, so this fires once per process in practice)."""
        if num_shards < 1:
            raise ValueError(f"num_shards must be >= 1, got {num_shards}")
        if num_shards != self._num_shards:
            self._num_shards = num_shards
            self.invalidate()

    # -- layout build ------------------------------------------------------

    def _rebuild(self) -> None:
        """Re-derive regions and entry placement from the current
        arrays (vectorized; the moral equivalent of build_csr_plan's
        argsort, run only on full_build / growth / overflow)."""
        st = self.state
        n_cap, m_cap = st.n_cap, st.m_cap
        slots = np.fromiter(st._arc_slot.values(), np.int64, len(st._arc_slot))  # kschedlint: host-only (host layout build)
        slots.sort()
        src_l = st.src[slots].astype(np.int64)  # kschedlint: host-only (host layout build)
        dst_l = st.dst[slots].astype(np.int64)  # kschedlint: host-only (host layout build)
        deg = np.bincount(src_l, minlength=n_cap) + np.bincount(dst_l, minlength=n_cap)
        # region sizing: the per-id degree high-water mark (so a
        # recycled id can re-house its historical max — see the module
        # docstring), + 1 slack row for every node that ever held rows,
        # then the surplus up to the pow2 entry budget distributed
        # proportionally to degree (hubs absorb churn; pos 0 reserved)
        if len(self._deg_hwm) < n_cap:
            self._deg_hwm = np.concatenate([
                self._deg_hwm,
                np.zeros(n_cap - len(self._deg_hwm), np.int64),  # kschedlint: host-only (host allocation bookkeeping)
            ])
        if len(self._churn_ct) < n_cap:
            self._churn_ct = np.concatenate([
                self._churn_ct,
                np.zeros(n_cap - len(self._churn_ct), np.int64),  # kschedlint: host-only (host allocation bookkeeping)
            ])
        # decay the mark halfway toward the instantaneous degree (the
        # fill-time spike of a since-bound task, or a recycled id's
        # past big tenant, must not inflate the entry budget forever);
        # the type-hinted relocation path catches whoever decays too
        # far
        hwm = np.maximum(deg, (self._deg_hwm[:n_cap] + deg + 1) // 2)
        self._deg_hwm = hwm
        # RESET the per-TYPE degree records to the live peak (fresh-
        # region sizing hints: an id never predicts its next tenant —
        # ephemeral aggregators are reborn each round under a recycled
        # id — but the TYPE's record does). Reset, not accumulate: the
        # fill-time cluster aggregator leaves a ~N-degree ghost record
        # on its type that would poison every later fresh claim
        nt = self.state.node_type[:n_cap].astype(np.int64)  # kschedlint: host-only (host layout build)
        self._type_hwm = {
            int(t): int(deg[nt == t].max()) for t in np.unique(nt[deg > 0])
        }
        self._occ = deg.astype(np.int64)  # kschedlint: host-only (host allocation bookkeeping)
        # regions are sized to the mark EXACTLY: a node allocating past
        # its historical max is the record-setter case, and relocation
        # (not a pre-paid spare row for every node in the cluster — a
        # ~25%-of-table tax at production fill) is the designed path
        base = hwm.copy()
        churn = self._churn_ct[:n_cap]
        active = hwm > 0
        # slack headroom (module docstring): an active node wants a
        # flat +2 (the ±2 occupancy jump a task binding makes in one
        # round) plus 25% of its mark (drift room for the big
        # aggregators), granted whole from the pow2 surplus in
        # descending churn × region-size order — the weight is the
        # expected relocation COST saved, so a slowly-growing hub
        # outranks a small id that recycles often. A tail-pool FLOOR
        # is reserved before any grant: whatever the grants leave (and
        # at least the floor) stays contiguous past the packed spans
        # as the relocation arena (per shard block in sharded mode).
        want = np.where(active, 2 + (hwm >> 2), 0)
        D = self._num_shards
        if D == 1:
            owner = np.zeros(n_cap, np.int64)  # kschedlint: host-only (host layout build)
            need = 1 + int(base.sum())
            self.entry_cap = max(2 * m_cap, next_pow2(need))
            # guarantee the relocation arena: when the pow2 lands so
            # close to `need` that no real tail pool would remain, take
            # the next bucket — at production fill the 2*m_cap term
            # plus the dropped per-node spare row carry the floor
            # comfortably
            if self.entry_cap - need < max(64, self.entry_cap >> 4):
                self.entry_cap = max(
                    2 * m_cap,
                    next_pow2(need + max(64, self.entry_cap >> 4)),
                )
            surplus = self.entry_cap - need
            grantable = max(surplus - max(64, self.entry_cap >> 4), 0)
            slack = want
            if int(want.sum()) > grantable:
                order = np.argsort(-(churn * (hwm + 1)), kind="stable")
                fits = np.cumsum(want[order]) <= grantable
                slack = np.zeros_like(want)
                slack[order[fits]] = want[order[fits]]
            self.block_extent = self.entry_cap
        else:
            # sharded layout: equal-extent per-shard blocks, each with
            # its own reserved dead slot (local 0), packed regions, and
            # tail arena. The block extent is sized for the DENSEST
            # shard with full slack wants, floored at (2*m_cap)/D —
            # the pow2-bucket common case the jaxpr contracts pin
            # (sharded_entry_extent in parallel/sharded_solver.py)
            owner = shard_owner(np.arange(n_cap), n_cap, D)
            full = (base + want).astype(np.int64)  # kschedlint: host-only (host layout build)
            shard_need = np.bincount(owner, weights=full, minlength=D).astype(np.int64) + 1  # kschedlint: host-only (host layout build)
            max_need = int(shard_need.max())
            Es = next_pow2(max_need)
            if Es - max_need < max(64, Es >> 4):
                Es = next_pow2(max_need + max(64, Es >> 4))
            if (2 * m_cap) % D == 0:
                Es = max(Es, (2 * m_cap) // D)
            self.block_extent = Es
            self.entry_cap = D * Es
            base_sum = np.bincount(owner, weights=base.astype(np.float64), minlength=D).astype(np.int64)  # kschedlint: host-only (host layout build)
            slack = np.zeros_like(want)
            for d in range(D):
                sel = np.flatnonzero(owner == d)
                grantable = max(
                    int(Es - 1 - base_sum[d] - max(64, Es >> 4)), 0
                )
                wd = want[sel]
                if int(wd.sum()) <= grantable:
                    slack[sel] = wd
                else:
                    order = np.argsort(
                        -(churn[sel] * (hwm[sel] + 1)), kind="stable"
                    )
                    fits = np.cumsum(wd[order]) <= grantable
                    slack[sel[order[fits]]] = wd[order[fits]]
        caps = base + slack
        E = self.entry_cap
        Es = self.block_extent
        self._owner = owner
        start = np.empty(n_cap, np.int64)  # kschedlint: host-only (host layout build)
        seg = np.zeros(E, np.int32)
        isstart = np.zeros(E, bool)
        tail0 = np.zeros(D, np.int64)  # kschedlint: host-only (host allocation bookkeeping)
        for d in range(D):
            sel = np.flatnonzero(owner == d) if D > 1 else np.arange(n_cap)
            # each block's local position 0 is its reserved dead slot:
            # its own one-row segment, never allocated (global position
            # 0 keeps the historical reserved role on shard 0)
            seg[d * Es] = d * Es
            isstart[d * Es] = True
            if len(sel) == 0:
                # a shard can legitimately own zero nodes (D close to
                # or above n_cap: ceil-division ranges leave trailing
                # shards empty); its block is one dead slot + tail
                tail0[d] = d * Es + 1
                continue
            cd = caps[sel]
            sd = d * Es + 1 + np.concatenate(([0], np.cumsum(cd[:-1])))
            start[sel] = sd
            used_d = int(cd.sum())
            seg[d * Es + 1 : d * Es + 1 + used_d] = np.repeat(sd, cd).astype(np.int32)
            isstart[sd[cd > 0]] = True
            tail0[d] = d * Es + 1 + used_d
        self.region_start = start.astype(np.int32)
        self.region_cap = caps.astype(np.int32)
        self.node_first = np.minimum(start, E - 1).astype(np.int32)
        self.node_last = np.minimum(start + caps - 1, E - 1).astype(np.int32)
        self.node_nonempty = caps > 0
        self.seg_start = seg
        self.is_start = isstart
        # entry placement: within a region, forward entries (slot
        # ascending) at the FRONT and backward entries (slot
        # ascending) at the BACK, slack between. Live-row order
        # matches the stable argsort's fwd-then-bwd order (dead slack
        # rows between are inert), so the first layout after a build
        # is allocation-order identical to the legacy plan. The
        # fwd-front/bwd-back split is LOAD-BEARING for solve speed,
        # not cosmetics: the discharge allocates a node's excess over
        # its admissible entries front-to-back, and backward rows
        # sitting in front of forward ones soak pushes into
        # bounce-back moves (measured: interleaved wiring order drove
        # fresh-restart supersteps 10 -> 17-23 within six churn
        # rounds; separating the sides restores ~10, so the incre-
        # mentally maintained layout must preserve the split)
        counts_f = np.bincount(src_l, minlength=n_cap)
        cum_f = np.concatenate(([0], np.cumsum(counts_f)[:-1]))
        order_f = np.argsort(src_l, kind="stable")
        gsrc = src_l[order_f]
        rank_f = np.arange(len(slots), dtype=np.int64) - cum_f[gsrc]  # kschedlint: host-only (host layout build)
        pos_f = start[gsrc] + rank_f
        counts_b = np.bincount(dst_l, minlength=n_cap)
        cum_b = np.concatenate(([0], np.cumsum(counts_b)[:-1]))
        order_b = np.argsort(dst_l, kind="stable")
        gdst = dst_l[order_b]
        rank_b = np.arange(len(slots), dtype=np.int64) - cum_b[gdst]  # kschedlint: host-only (host layout build)
        pos_b = start[gdst] + caps[gdst] - counts_b[gdst] + rank_b
        self.p_arc = np.zeros(E, np.int32)
        self.p_sign = np.zeros(E, np.int32)
        self.p_src = np.zeros(E, np.int32)
        self.p_dst = np.zeros(E, np.int32)
        pf = np.full(m_cap, -1, np.int32)
        pb = np.full(m_cap, -1, np.int32)
        sf = slots[order_f]
        sb = slots[order_b]
        pf[sf] = pos_f
        pb[sb] = pos_b
        self.pos_fwd = pf
        self.pos_bwd = pb
        self.p_arc[pos_f] = sf
        self.p_sign[pos_f] = 1
        self.p_src[pos_f] = gsrc
        self.p_dst[pos_f] = st.dst[sf]
        self.p_arc[pos_b] = sb
        self.p_sign[pos_b] = -1
        self.p_src[pos_b] = gdst
        self.p_dst[pos_b] = st.src[sb]
        inv = np.zeros(2 * m_cap, np.int32)
        inv[sf] = pos_f
        inv[m_cap + sb] = pos_b
        self.inv_order = inv
        self._next_seq = start + counts_f
        self._next_back = start + caps - counts_b - 1
        self._freed_f = {}
        self._freed_b = {}
        self._tail_next = tail0
        self._dead_spans = [[] for _ in range(D)]
        self._dirty_pos.clear()
        self._dirty_inv.clear()
        self._dirty_seg.clear()
        self._dirty_node.clear()
        self.layout_gen += 1
        self.value_version += 1
        self.static_version += 1
        self.layout_rebuilds += 1
        self.needs_rebuild = False

    # -- per-mutation hooks (called by DeviceGraphState._set_arc) ----------

    def _alloc(self, node: int, sign: int) -> int:
        """A free position in `node`'s region for a row of `sign` —
        forward rows fill from the region FRONT, backward rows from
        the BACK (the load-bearing split; see _rebuild). -1 when the
        region is full and the tail pool can't house a relocated
        one."""
        self._churn_ct[node] += 1  # failed attempts weigh in too
        nf = int(self._next_seq[node])
        nb = int(self._next_back[node])
        if sign > 0:
            h = self._freed_f.get(node)
            if h and (nf > nb or h[0] < nf):
                pos = heapq.heappop(h)
            elif nf <= nb:
                self._next_seq[node] = nf + 1
                pos = nf
            else:
                if not self._relocate(node):
                    return -1
                return self._alloc(node, sign)
        else:
            h = self._freed_b.get(node)
            if h and (nb < nf or -h[0] > nb):
                pos = -heapq.heappop(h)
            elif nb >= nf:
                self._next_back[node] = nb - 1
                pos = nb
            else:
                if not self._relocate(node):
                    return -1
                return self._alloc(node, sign)
        occ = int(self._occ[node]) + 1
        self._occ[node] = occ
        if occ > self._deg_hwm[node]:
            self._deg_hwm[node] = occ
        t = int(self.state.node_type[node])
        if occ > self._type_hwm.get(t, 0):
            self._type_hwm[t] = occ
        return pos

    def _release(self, node: int, pos: int, sign: int) -> None:
        occ = int(self._occ[node]) - 1
        self._occ[node] = occ
        self._churn_ct[node] += 1
        if occ == 0:
            # an emptied node returns a BIG span to the pool: the
            # per-round ephemeral aggregators (born, grown to full
            # size, and drained under a different recycled id every
            # round) would otherwise strand a full-size region per
            # round and bleed the pool dry. SMALL spans stay attached
            # to the id as recycle insurance — the next tenant of a
            # completed task's id refills a task-shaped arc set in
            # place, costing zero relocations and zero journal bytes
            start = int(self.region_start[node])
            cap = int(self.region_cap[node])
            if cap > 16:
                self._return_span(start, cap)
                self.region_cap[node] = 0
                self._next_seq[node] = start
                self._next_back[node] = start - 1
                self._freed_f.pop(node, None)
                self._freed_b.pop(node, None)
                if self.node_nonempty[node]:
                    self.node_nonempty[node] = False
                    self._dirty_node.add(node)
                self.value_version += 1
                self.static_version += 1
            else:
                # keep the span; reset the frontiers once empty so the
                # next tenant fills it front/back from scratch
                self._next_seq[node] = start
                self._next_back[node] = start + cap - 1
                self._freed_f.pop(node, None)
                self._freed_b.pop(node, None)
        elif sign > 0:
            heapq.heappush(self._freed_f.setdefault(node, []), pos)
        else:
            heapq.heappush(self._freed_b.setdefault(node, []), -pos)

    def _return_span(self, start: int, cap: int) -> None:
        """Give a span back to its owner block's arena, coalescing with
        adjacent dead spans and with the tail frontier — relocation
        churn must not shred the pool into unusable slivers (measured:
        ~90 abandoned 2-4 row fragments starving 6-row claims). A span
        never straddles a block boundary by construction."""
        d = start // self.block_extent if self.block_extent else 0
        spans = self._dead_spans[d]
        merged = True
        while merged:
            merged = False
            for i, (s0, c0) in enumerate(spans):
                if s0 + c0 == start:
                    start, cap = s0, c0 + cap
                    spans.pop(i)
                    merged = True
                    break
                if start + cap == s0:
                    cap += c0
                    spans.pop(i)
                    merged = True
                    break
        if start + cap == self._tail_next[d]:
            self._tail_next[d] = start
        else:
            spans.append((start, cap))

    def _claim_span(self, k: int, shard: int = 0) -> Optional[Tuple[int, int]]:
        """A (start, cap) span of >= k rows in `shard`'s block for a
        relocated region: best-fit from the block's dead-span list
        (split when the fit is loose — the remainder stays claimable),
        else fresh tail. None when neither fits."""
        spans = self._dead_spans[shard]
        best = -1
        for i, (_s0, c0) in enumerate(spans):
            if c0 >= k and (best < 0 or c0 < spans[best][1]):
                best = i
        if best >= 0:
            s0, c0 = spans.pop(best)
            if c0 - k >= 8:
                spans.append((s0 + k, c0 - k))
                return (s0, k)
            return (s0, c0)
        limit = (shard + 1) * self.block_extent
        if self._tail_next[shard] + k <= limit:
            s0 = int(self._tail_next[shard])
            self._tail_next[shard] += k
            return (s0, k)
        return None

    def _relocate(self, node: int) -> bool:
        """Move `node`'s live rows into a doubled region carved from
        the tail pool, preserving their relative order. O(degree) host
        writes, all journaled: the moved value rows and freshly dead
        old rows ride the ordinary row records, the new span's segment
        statics and the node's boundary statics ride the seg/node
        record streams. The abandoned span keeps its (all-dead)
        segment structure — no reduction samples a span outside every
        node's `node_first..node_last`. False iff the pool is spent."""
        old_start = int(self.region_start[node])
        old_cap = int(self.region_cap[node])
        occ = int(self._occ[node])
        shard = int(self._owner[node]) if len(self._owner) > node else 0
        # 1.25x growth: big aggregator regions dominate pool traffic,
        # and doubling a 70-row region for a +1 record wastes half the
        # arena; a quarter-step still amortizes the move count
        want = max(old_cap + max(old_cap >> 2, 2), occ + 2, 4)
        if old_cap == 0:
            # fresh region: the id's TYPE already names the NEW tenant
            # (nodes are typed before arcs wire), so its degree record
            # sizes the span — an ephemeral aggregator reborn on a
            # recycled task id claims its full span at once instead of
            # laddering 4→8→…→64 through the pool. The id's own mark
            # folds in as a floor, and the whole hint is capped by
            # pool health so a poisoned type record (types can mix
            # giants with minnows) can't let a few fresh claims drain
            # the arena.
            pool_left = int(
                (shard + 1) * self.block_extent - self._tail_next[shard]
            ) + sum(c for _, c in self._dead_spans[shard])
            rec = max(
                self._type_hwm.get(int(self.state.node_type[node]), 0),
                int(self._deg_hwm[node]),
            )
            hint = rec + max(2, rec >> 3)  # drift margin atop the record
            want = max(want, min(hint, max(pool_left >> 1, 8)))
        placed = self._claim_span(want, shard)
        if placed is None:
            # doubling doesn't fit — a minimal region still beats a
            # full layout rebuild
            placed = self._claim_span(max(occ + 2, 4), shard)
        if placed is None:
            return False
        new_start, new_cap = placed
        if old_cap > 0:
            self._return_span(old_start, old_cap)
        m_cap = self.state.m_cap
        # forward rows (relative order kept) to the FRONT of the new
        # span, backward rows to the BACK — the load-bearing split
        # (see _rebuild) survives every move
        rows = [
            (pos, int(self.p_sign[pos]))
            for pos in range(old_start, old_start + old_cap)
            if self.p_sign[pos] != 0
        ]
        n_bwd = sum(1 for _, sign in rows if sign < 0)
        wf = new_start
        wb = new_start + new_cap - n_bwd
        for pos, sign in rows:
            slot = int(self.p_arc[pos])
            if sign > 0:
                w = wf
                wf += 1
            else:
                w = wb
                wb += 1
            self._write_row(
                w, slot, sign, int(self.p_src[pos]), int(self.p_dst[pos])
            )
            if sign > 0:
                self.pos_fwd[slot] = w
                self.inv_order[slot] = w
                self._dirty_inv.add(slot)
            else:
                self.pos_bwd[slot] = w
                self.inv_order[m_cap + slot] = w
                self._dirty_inv.add(m_cap + slot)
            self._write_row(pos, 0, 0, 0, 0)
        self.region_start[node] = new_start
        self.region_cap[node] = new_cap
        self.node_first[node] = new_start
        self.node_last[node] = new_start + new_cap - 1
        self.node_nonempty[node] = True
        self._dirty_node.add(node)
        for pos in range(new_start, new_start + new_cap):
            self.seg_start[pos] = new_start
            self.is_start[pos] = pos == new_start
            self._dirty_seg.add(pos)
        self._next_seq[node] = wf
        self._next_back[node] = new_start + new_cap - n_bwd - 1
        self._freed_f[node] = []
        self._freed_b[node] = []
        self.value_version += 1
        self.static_version += 1
        self.region_relocations += 1
        return True

    def _overflow(self) -> None:
        self.region_overflows += 1
        self.invalidate()

    def _write_row(self, pos: int, arc: int, sign: int, src: int, dst: int) -> None:
        self.p_arc[pos] = arc
        self.p_sign[pos] = sign
        self.p_src[pos] = src
        self.p_dst[pos] = dst
        self._dirty_pos.add(pos)

    def slot_assigned(self, slot: int, src: int, dst: int) -> None:
        """A slot gained endpoints (new arc, or a recycled slot re-wired
        to a different (src, dst)): wire its two plan rows."""
        if not self.enabled or self.needs_rebuild:
            return
        pf = self._alloc(src, 1)
        if pf < 0:
            self._overflow()
            return
        pb = self._alloc(dst, -1)
        if pb < 0:
            self._release(src, pf, 1)
            self._overflow()
            return
        m_cap = self.state.m_cap
        self._write_row(pf, slot, 1, src, dst)
        self._write_row(pb, slot, -1, dst, src)
        self.pos_fwd[slot] = pf
        self.pos_bwd[slot] = pb
        self.inv_order[slot] = pf
        self.inv_order[m_cap + slot] = pb
        self._dirty_inv.add(slot)
        self._dirty_inv.add(m_cap + slot)
        self.value_version += 1

    def slot_freed(self, slot: int, src: int, dst: int) -> None:
        """The arc in `slot` was removed: kill its plan rows (sign 0 ⇒
        inert in every reduction) and park its inv entries on the
        reserved dead position 0 so a later recycling of the row can
        never alias this slot's flow update."""
        if not self.enabled or self.needs_rebuild:
            return
        pf = int(self.pos_fwd[slot])
        pb = int(self.pos_bwd[slot])
        if pf < 0:  # pragma: no cover - defensive (never assigned)
            return
        m_cap = self.state.m_cap
        self._write_row(pf, 0, 0, 0, 0)
        self._write_row(pb, 0, 0, 0, 0)
        self._release(src, pf, 1)
        self._release(dst, pb, -1)
        self.pos_fwd[slot] = -1
        self.pos_bwd[slot] = -1
        self.inv_order[slot] = 0
        self.inv_order[m_cap + slot] = 0
        self._dirty_inv.add(slot)
        self._dirty_inv.add(m_cap + slot)
        self.value_version += 1

    # -- record packing (device-resident scatter path) ---------------------

    @property
    def has_pending(self) -> bool:
        return bool(
            self._dirty_pos or self._dirty_inv
            or self._dirty_seg or self._dirty_node
        )

    def drain_records(
        self,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Pack the dirty plan rows / inv entries / relocated segment
        and node statics into pow2-padded int32 records and clear the
        journal. Positions are coalesced (a position written twice
        this round ships once, final value) and sorted, so the packed
        records are deterministic and duplicate-free — scatter
        ordering can never matter. Empty streams pad with an
        idempotent rewrite of the permanently dead position 0 (rows /
        segment statics) or node 0's current boundary meta."""
        pos = np.sort(np.fromiter(self._dirty_pos, np.int32, len(self._dirty_pos)))
        ents = np.sort(np.fromiter(self._dirty_inv, np.int32, len(self._dirty_inv)))
        segs = np.sort(np.fromiter(self._dirty_seg, np.int32, len(self._dirty_seg)))
        nids = np.sort(np.fromiter(self._dirty_node, np.int32, len(self._dirty_node)))
        kp, ki, ks, kn = len(pos), len(ents), len(segs), len(nids)
        row_rec = np.zeros((_pad_records(kp), PLAN_RECORD_COLS), np.int32)
        if kp:
            row_rec[:kp, 0] = pos
            row_rec[:kp, 1] = self.p_arc[pos]
            row_rec[:kp, 2] = self.p_sign[pos]
            row_rec[:kp, 3] = self.p_src[pos]
            row_rec[:kp, 4] = self.p_dst[pos]
            row_rec[kp:] = row_rec[0]
        # else: all-zero rows rewrite the reserved dead position 0 with
        # its permanent (0, 0, 0, 0) values — idempotent by invariant
        inv_rec = np.zeros((_pad_records(ki), INV_RECORD_COLS), np.int32)
        if ki:
            inv_rec[:ki, 0] = ents
            inv_rec[:ki, 1] = self.inv_order[ents]
            inv_rec[ki:] = inv_rec[0]
        else:
            inv_rec[:, 1] = self.inv_order[0]  # rewrite entry 0 as-is
        seg_rec = np.zeros((_pad_records(ks), SEG_RECORD_COLS), np.int32)
        if ks:
            seg_rec[:ks, 0] = segs
            seg_rec[:ks, 1] = self.seg_start[segs]
            seg_rec[:ks, 2] = self.is_start[segs]
            seg_rec[ks:] = seg_rec[0]
        else:
            seg_rec[:, 1] = self.seg_start[0]
            seg_rec[:, 2] = self.is_start[0]
        node_rec = np.zeros((_pad_records(kn), NODE_RECORD_COLS), np.int32)
        if kn:
            node_rec[:kn, 0] = nids
            node_rec[:kn, 1] = self.node_first[nids]
            node_rec[:kn, 2] = self.node_last[nids]
            node_rec[:kn, 3] = self.node_nonempty[nids]
            node_rec[kn:] = node_rec[0]
        else:
            node_rec[:, 1] = self.node_first[0]
            node_rec[:, 2] = self.node_last[0]
            node_rec[:, 3] = self.node_nonempty[0]
        self.clear_pending()
        return row_rec, inv_rec, seg_rec, node_rec

    def drain_records_sharded(
        self,
    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Per-shard routed form of ``drain_records`` (requires sharded
        layout mode): dirty plan rows and relocated segment statics are
        grouped by OWNER SHARD (position // block_extent) with
        block-local positions, stacked ``[D, K, cols]`` and padded to
        one shared pow2 record bucket per stream — a shard with fewer
        (or zero) records pads idempotently by rewriting its own
        reserved dead local slot 0 (rows: zeros; segment statics: the
        dead slot's permanent meta). The inv-order and node-boundary
        records stay in the global replicated form (those tensors are
        replicated on device by the partition rules). Returns
        ``(row [D, Kp, 5], seg [D, Ks, 3], inv [Ki, 2], node [Kn, 4])``
        and clears the journal."""
        D = self._num_shards
        Es = self.block_extent
        pos = np.sort(np.fromiter(self._dirty_pos, np.int64, len(self._dirty_pos)))  # kschedlint: host-only (host record packing)
        segs = np.sort(np.fromiter(self._dirty_seg, np.int64, len(self._dirty_seg)))  # kschedlint: host-only (host record packing)
        ents = np.sort(np.fromiter(self._dirty_inv, np.int32, len(self._dirty_inv)))
        nids = np.sort(np.fromiter(self._dirty_node, np.int32, len(self._dirty_node)))

        def route(idx, cols, fill):
            """[D, K, cols] per-shard records from global positions."""
            owner = idx // Es
            counts = np.bincount(owner, minlength=D)
            k = _pad_records(int(counts.max()) if len(idx) else 0)
            rec = np.zeros((D, k, cols), np.int32)
            for d in range(D):
                rec[d] = fill(d)  # idempotent dead-slot pad, whole block
                mine = idx[owner == d]
                kd = len(mine)
                if kd:
                    rec[d, :kd, 0] = (mine - d * Es).astype(np.int32)
                    rec[d, :kd, 1:] = self._row_values(mine, cols)
                    rec[d, kd:] = rec[d, 0]
            return rec

        row_rec = route(
            pos, PLAN_RECORD_COLS,
            lambda d: np.zeros(PLAN_RECORD_COLS, np.int32),
        )
        seg_rec = route(
            segs, SEG_RECORD_COLS,
            lambda d: np.array([0, d * Es, 1], np.int32),
        )
        ki, kn = len(ents), len(nids)
        inv_rec = np.zeros((_pad_records(ki), INV_RECORD_COLS), np.int32)
        if ki:
            inv_rec[:ki, 0] = ents
            inv_rec[:ki, 1] = self.inv_order[ents]
            inv_rec[ki:] = inv_rec[0]
        else:
            inv_rec[:, 1] = self.inv_order[0]
        node_rec = np.zeros((_pad_records(kn), NODE_RECORD_COLS), np.int32)
        if kn:
            node_rec[:kn, 0] = nids
            node_rec[:kn, 1] = self.node_first[nids]
            node_rec[:kn, 2] = self.node_last[nids]
            node_rec[:kn, 3] = self.node_nonempty[nids]
            node_rec[kn:] = node_rec[0]
        else:
            node_rec[:, 1] = self.node_first[0]
            node_rec[:, 2] = self.node_last[0]
            node_rec[:, 3] = self.node_nonempty[0]
        self.clear_pending()
        return row_rec, seg_rec, inv_rec, node_rec

    def _row_values(self, idx: np.ndarray, cols: int) -> np.ndarray:
        """Value columns for routed records at global positions `idx`
        (row records carry the four plan-row values, segment records
        the (seg_start, is_start) pair)."""
        if cols == PLAN_RECORD_COLS:
            return np.stack(
                [self.p_arc[idx], self.p_sign[idx], self.p_src[idx], self.p_dst[idx]],
                axis=1,
            )
        return np.stack(
            [self.seg_start[idx], self.is_start[idx].astype(np.int32)], axis=1
        )

    def clear_pending(self) -> None:
        self._dirty_pos.clear()
        self._dirty_inv.clear()
        self._dirty_seg.clear()
        self._dirty_node.clear()

    # -- materialization ---------------------------------------------------

    def host_args(self) -> Tuple:
        """The plan tensors as host arrays, in `_solve_mcmf` positional
        order — the full-rebuild/full-ship materialization the scatter
        path must match bit-for-bit."""
        self.ensure_built()
        return (
            self.p_arc, self.p_sign, self.p_src, self.p_dst,
            self.seg_start, self.is_start, self.inv_order,
            self.node_first, self.node_last, self.node_nonempty,
        )

    def device_static(self) -> Tuple:
        """The segment/node boundary tensors on device, cached per
        (layout_gen, static_version) — uploaded once per layout and
        re-shipped only when a relocation moved a region (ordinary
        endpoint churn never touches them)."""
        self.ensure_built()
        key = (self.layout_gen, self.static_version)
        if self._static_dev is None or self._static_dev[0] != key:
            import jax.numpy as jnp

            self._static_dev = (
                key,
                tuple(
                    jnp.asarray(x)
                    for x in (
                        self.seg_start, self.is_start,
                        self.node_first, self.node_last, self.node_nonempty,
                    )
                ),
            )
        return self._static_dev[1]

    def static_nbytes(self) -> int:
        return int(
            self.seg_start.nbytes + self.is_start.nbytes
            + self.node_first.nbytes + self.node_last.nbytes
            + self.node_nonempty.nbytes
        )

    def values_nbytes(self) -> int:
        return int(
            self.p_arc.nbytes + self.p_sign.nbytes
            + self.p_src.nbytes + self.p_dst.nbytes + self.inv_order.nbytes
        )

    def device_args(self) -> Tuple:
        """The full plan as device tensors in `_solve_mcmf` order,
        cached by (layout_gen, value_version): a clean round re-uses
        the previous upload outright; a dirty round re-ships the
        maintained host arrays wholesale (the non-resident path — the
        device-resident mirror scatters records instead)."""
        self.ensure_built()
        key = (self.layout_gen, self.value_version)
        if self._values_dev is None or self._values_dev[:2] != key:
            import jax.numpy as jnp

            self._values_dev = key + (
                tuple(
                    jnp.asarray(x)
                    for x in (self.p_arc, self.p_sign, self.p_src, self.p_dst)
                ),
                jnp.asarray(self.inv_order),
            )
        values, inv = self._values_dev[2], self._values_dev[3]
        seg, isstart, first, last, nonempty = self.device_static()
        return values + (seg, isstart, inv, first, last, nonempty)

    # -- invariants (tests / debug) ----------------------------------------

    def check_invariants(self) -> None:
        """Verify the maintained layout is internally consistent with
        the owning DeviceGraphState (O(E)). Raises a structured
        `runtime.integrity.IntegrityError` (an AssertionError subclass,
        so bare-assert-era consumers keep working) — promoted from a
        test helper to the `--audit-every` service audit surface."""
        try:
            self._check_invariants_impl()
        except AssertionError as e:
            from ..runtime.integrity import IntegrityError

            if isinstance(e, IntegrityError):
                raise
            raise IntegrityError(f"slot-plan invariant violated: {e}", array="slot_plan") from e

    def _check_invariants_impl(self) -> None:
        st = self.state
        assert not self.needs_rebuild, "plan not built"
        live = sorted(st._arc_slot.values())
        seen = set()
        for slot in live:
            pf, pb = int(self.pos_fwd[slot]), int(self.pos_bwd[slot])
            s, d = int(st.src[slot]), int(st.dst[slot])
            assert pf > 0 and pb > 0, f"slot {slot} unassigned"
            assert pf not in seen and pb not in seen, f"slot {slot} aliases a row"
            seen.update((pf, pb))
            rs, rc = int(self.region_start[s]), int(self.region_cap[s])
            assert rs <= pf < rs + rc, f"fwd row of slot {slot} outside src region"
            assert int(self.seg_start[pf]) == rs, (
                f"fwd row of slot {slot} carries a stale segment start"
            )
            rs, rc = int(self.region_start[d]), int(self.region_cap[d])
            assert rs <= pb < rs + rc, f"bwd row of slot {slot} outside dst region"
            assert int(self.seg_start[pb]) == rs, (
                f"bwd row of slot {slot} carries a stale segment start"
            )
            assert (
                self.p_arc[pf] == slot and self.p_sign[pf] == 1
                and self.p_src[pf] == s and self.p_dst[pf] == d
            ), f"fwd row of slot {slot} stale"
            assert (
                self.p_arc[pb] == slot and self.p_sign[pb] == -1
                and self.p_src[pb] == d and self.p_dst[pb] == s
            ), f"bwd row of slot {slot} stale"
            assert int(self.inv_order[slot]) == pf
            assert int(self.inv_order[st.m_cap + slot]) == pb
        n_live_rows = int((self.p_sign != 0).sum())
        assert n_live_rows == 2 * len(live), (
            f"{n_live_rows} live plan rows for {len(live)} live slots"
        )
        assert self.p_sign[0] == 0, "reserved position 0 must stay dead"
        occ = np.bincount(
            self.p_src[self.p_sign != 0], minlength=st.n_cap
        )
        if not np.array_equal(occ, self._occ[: st.n_cap]):
            from ..runtime.integrity import bounded_diff

            # raised AS the structured error: check_invariants passes
            # IntegrityError through unwrapped, keeping the
            # machine-readable indices/expected/found fields
            raise bounded_diff("plan_occupancy", self._occ[: st.n_cap], occ)
        assert (self._deg_hwm[: st.n_cap] >= occ).all(), (
            "degree high-water mark fell below live occupancy"
        )
        block_limits = (np.arange(self._num_shards, dtype=np.int64) + 1) * self.block_extent  # kschedlint: host-only (test-only invariant check)
        assert (self._tail_next <= block_limits).all(), (
            "a tail pool overran its shard block"
        )
        # the load-bearing fwd-front/bwd-back split within every region
        fpos = np.flatnonzero(self.p_sign == 1).astype(np.int64)  # kschedlint: host-only (test-only invariant check)
        bpos = np.flatnonzero(self.p_sign == -1).astype(np.int64)  # kschedlint: host-only (test-only invariant check)
        maxf = np.full(st.n_cap, -1, np.int64)  # kschedlint: host-only (test-only invariant check)
        np.maximum.at(maxf, self.p_src[fpos], fpos)
        minb = np.full(st.n_cap, self.entry_cap, np.int64)  # kschedlint: host-only (test-only invariant check)
        np.minimum.at(minb, self.p_src[bpos], bpos)
        assert (maxf < minb).all(), (
            "a backward row precedes a forward row in its region"
        )
        # current regions (original spans and relocated tail spans
        # alike) must be pairwise disjoint and inside [1, tail)
        starts = self.region_start.astype(np.int64)  # kschedlint: host-only (test-only invariant check)
        caps64 = self.region_cap.astype(np.int64)  # kschedlint: host-only (test-only invariant check)
        held = caps64 > 0
        order = np.argsort(starts[held], kind="stable")
        lo = starts[held][order]
        hi = lo + caps64[held][order]
        if lo.size:
            assert (hi[:-1] <= lo[1:]).all(), "regions overlap"
            # every held region lives inside its OWNER's block, past
            # the block's reserved dead slot and under its tail
            # frontier (one block == the whole table when unsharded)
            own = self._owner[np.flatnonzero(held)]
            s_h = starts[held]
            e_h = s_h + caps64[held]
            assert (s_h >= own * self.block_extent + 1).all(), (
                "a region precedes its block's reserved dead slot"
            )
            assert (e_h <= self._tail_next[own]).all(), (
                "a region lies outside the packed/tail extent"
            )
        for node in np.flatnonzero(held):
            assert int(self.node_first[node]) == int(starts[node])
            assert int(self.node_last[node]) == int(starts[node] + caps64[node] - 1)


# Level-3 registry ownership (ksched_tpu/analysis/program_registry.py)
from ..analysis.program_registry import declare_programs as _declare_programs

_declare_programs(__name__, "plan_apply")
