"""DIMACS text codec.

Reference: scheduling/flow/dimacs/{doc.go,export.go,add_node_change.go,
create_arc_change.go,update_arc_change.go,remove_node_change.go}. In the
reference this text stream over pipes IS the solver wire protocol; in the
TPU build the solver consumes flat arrays (graph/device_export.py), so
this codec exists for debugging, golden-file tests, and interop with
external DIMACS tooling.

Format (reference: dimacs/doc.go:3-22):
    c <comment>
    p min <num nodes> <num arcs>
    n <id> <excess> [<solver node type>]
    a <src> <dst> <cap lower> <cap upper> <cost> [<arc type>]
Incremental lines additionally use
    r <id>                                      (remove node)
    x <src> <dst> <low> <cap> <cost> <type> <old cost>   (update arc)
and each batch ends with "c EOI" (end of iteration).

The solver's RESPONSE direction (flow assignments back to the
scheduler) is
    f <src> <dst> <flow>
lines terminated by "c EOI" (reference: placement/solver.go:134-179
readFlowGraph). export_flow/parse_flow below close that loop so an
external DIMACS solver can serve as a parity oracle against the
in-process backends.
"""

from __future__ import annotations

from typing import IO, Dict, Iterable, List, Tuple

from .changes import AddNodeChange, Change, ChangeArcChange, NewArcChange, RemoveNodeChange
from .flowgraph import FlowGraph, NodeType

# Solver-side node taxonomy (reference: dimacs/export.go:53-70 and
# add_node_change.go:27-36; the ordering is ABI with the solver there).
SOLVER_NODE_OTHER = 0
SOLVER_NODE_TASK = 1
SOLVER_NODE_PU = 2
SOLVER_NODE_SINK = 3
SOLVER_NODE_MACHINE = 4
SOLVER_NODE_INTERMEDIATE_RES = 5

_SOLVER_TYPE = {
    NodeType.UNSCHEDULED_TASK: SOLVER_NODE_TASK,
    NodeType.SCHEDULED_TASK: SOLVER_NODE_TASK,
    NodeType.ROOT_TASK: SOLVER_NODE_TASK,
    NodeType.PU: SOLVER_NODE_PU,
    NodeType.SINK: SOLVER_NODE_SINK,
    NodeType.MACHINE: SOLVER_NODE_MACHINE,
    NodeType.NUMA: SOLVER_NODE_INTERMEDIATE_RES,
    NodeType.SOCKET: SOLVER_NODE_INTERMEDIATE_RES,
    NodeType.CACHE: SOLVER_NODE_INTERMEDIATE_RES,
    NodeType.CORE: SOLVER_NODE_INTERMEDIATE_RES,
}


def solver_node_type(node_type: NodeType) -> int:
    return _SOLVER_TYPE.get(node_type, SOLVER_NODE_OTHER)


def export(graph: FlowGraph, out: IO[str], with_node_types: bool = True) -> None:
    """Full-graph export (reference: dimacs/export.go:11-29)."""
    out.write(f"p min {graph.num_nodes} {graph.num_arcs}\n")
    for node in graph.nodes():
        if with_node_types:
            out.write(f"n {node.id} {node.excess} {solver_node_type(node.type)}\n")
        else:
            out.write(f"n {node.id} {node.excess}\n")
    for arc in graph.arcs():
        out.write(f"a {arc.src} {arc.dst} {arc.cap_lower} {arc.cap_upper} {arc.cost}\n")
    out.write("c EOI\n")
    out.flush()


def export_incremental(changes: Iterable[Change], out: IO[str]) -> None:
    """Incremental delta export (reference: dimacs/export.go:31-49)."""
    for ch in changes:
        if isinstance(ch, AddNodeChange):
            out.write(f"n {ch.node_id} {ch.excess} {solver_node_type(ch.node_type)}\n")
        elif isinstance(ch, RemoveNodeChange):
            out.write(f"r {ch.node_id}\n")
        elif isinstance(ch, NewArcChange):
            out.write(
                f"a {ch.src} {ch.dst} {ch.cap_lower} {ch.cap_upper} {ch.cost} {int(ch.arc_type)}\n"
            )
        elif isinstance(ch, ChangeArcChange):
            out.write(
                f"x {ch.src} {ch.dst} {ch.cap_lower} {ch.cap_upper} {ch.cost} "
                f"{int(ch.arc_type)} {ch.old_cost}\n"
            )
        else:  # pragma: no cover - exhaustive over Change union
            raise TypeError(f"unknown change record: {ch!r}")
    out.write("c EOI\n")
    out.flush()


def export_flow(src, dst, flow, out: IO[str]) -> None:
    """Write a solver flow response: one `f src dst flow` line per
    positive-flow arc, then the `c EOI` terminator — the stdout side of
    the reference solver protocol (placement/solver.go:134-179 parses
    exactly this). src/dst/flow are parallel arrays/sequences."""
    for s, d, f in zip(src, dst, flow):
        if f > 0:
            out.write(f"f {int(s)} {int(d)} {int(f)}\n")
    out.write("c EOI\n")
    out.flush()


def parse_flow(lines: Iterable[str]) -> Dict[Tuple[int, int], int]:
    """Parse `f src dst flow` response lines until `c EOI` into
    {(src, dst): flow} (reference: readFlowGraph's dstToSrcAndFlow,
    placement/solver.go:134-179 — keyed there as map[dst]map[src]; the
    flat pair key is equivalent since DIMACS cannot express parallel
    arcs). Comment lines other than the terminator are skipped, as the
    reference skips the solver's `c ALGORITHM TIME` chatter
    (solver.go:169-170). A repeated pair overwrites (last wins)."""
    flows: Dict[Tuple[int, int], int] = {}
    terminated = False
    for line in lines:
        line = line.strip()
        if not line:
            continue
        if line.startswith("c"):
            if line == "c EOI":
                terminated = True
                break
            continue  # solver timing/debug chatter
        parts = line.split()
        if parts[0] != "f":
            raise ValueError(f"unexpected line in flow response: {line!r}")
        if len(parts) < 4:
            raise ValueError(f"truncated flow line (want `f src dst flow`): {line!r}")
        if len(parts) > 4:
            # a flow value split by pipe corruption must not silently
            # decode as its first fragment
            raise ValueError(f"trailing fields on flow line: {line!r}")
        try:
            s, d, f = (int(x) for x in parts[1:4])
        except ValueError:
            raise ValueError(f"non-integer field in flow line: {line!r}") from None
        if f < 0:
            raise ValueError(f"negative flow in response line: {line!r}")
        flows[(s, d)] = f
    if not terminated:
        # A dead solver / cut pipe must fail loudly, not decode as a
        # partial assignment (the reference panics there, solver.go:178).
        raise ValueError("flow response truncated: no 'c EOI' terminator")
    return flows


def flow_on_arcs(flows: Dict[Tuple[int, int], int], src, dst):
    """Align a parsed {(src, dst): flow} response with a problem's arc
    order: returns int64[num_arcs] with each arc's flow (0 when the
    response omitted the arc). Feed the result to
    solver.decode.flow_to_mapping for the task→PU assignment — the same
    decode the in-process backends use, so an external solver's answer
    is directly comparable."""
    import numpy as np

    out = np.zeros(len(src), np.int64)
    for i, (s, d) in enumerate(zip(src, dst)):
        out[i] = flows.get((int(s), int(d)), 0)
    return out


def _ints(parts: List[str], line: str, lineno: int) -> Tuple[int, ...]:
    try:
        return tuple(int(x) for x in parts)
    except ValueError:
        raise ValueError(
            f"DIMACS line {lineno}: non-integer field in {line!r}"
        ) from None


def parse_graph(lines: Iterable[str]):
    """Parse a full-graph DIMACS export into (num_nodes, node_lines, arc_lines)
    tuples of ints, for golden-file tests and external-solver interop.

    Malformed input fails loudly with the offending line — a truncated
    arc line, a negative capacity, or a node id outside the header's
    range must never decode into a flow problem that silently
    mis-places flow (downstream indexes device arrays by these ids)."""
    nodes: List[tuple] = []
    arcs: List[tuple] = []
    header = None
    terminated = False
    for lineno, line in enumerate(lines, 1):
        line = line.strip()
        if not line or line.startswith("c"):
            if line == "c EOI":
                terminated = True
            continue
        parts = line.split()
        if parts[0] == "p":
            if len(parts) < 4 or parts[1] != "min":
                raise ValueError(
                    f"DIMACS line {lineno}: malformed header (want `p min N M`): {line!r}"
                )
            header = _ints(parts[2:4], line, lineno)
            if header[0] < 0 or header[1] < 0:
                raise ValueError(
                    f"DIMACS line {lineno}: negative extent in header: {line!r}"
                )
        elif parts[0] == "n":
            if header is None:
                raise ValueError(
                    f"DIMACS line {lineno}: node line before `p min` header"
                )
            if len(parts) < 3:
                raise ValueError(
                    f"DIMACS line {lineno}: truncated node line "
                    f"(want `n id excess [type]`): {line!r}"
                )
            fields = _ints(parts[1:], line, lineno)
            # ids are 1-based (graph/flowgraph.py IDGenerator(start=1));
            # 0 is tolerated as the device-array padding row
            if not 0 <= fields[0] <= header[0]:
                raise ValueError(
                    f"DIMACS line {lineno}: node id {fields[0]} out of range "
                    f"[0, {header[0]}]: {line!r}"
                )
            nodes.append(fields)
        elif parts[0] == "a":
            if header is None:
                raise ValueError(
                    f"DIMACS line {lineno}: arc line before `p min` header"
                )
            if len(parts) < 6:
                raise ValueError(
                    f"DIMACS line {lineno}: truncated arc line "
                    f"(want `a src dst low cap cost [type]`): {line!r}"
                )
            fields = _ints(parts[1:], line, lineno)
            src, dst, low, cap = fields[0], fields[1], fields[2], fields[3]
            for nid in (src, dst):
                if not 0 <= nid <= header[0]:
                    raise ValueError(
                        f"DIMACS line {lineno}: arc endpoint {nid} out of range "
                        f"[0, {header[0]}]: {line!r}"
                    )
            if low < 0 or cap < 0:
                raise ValueError(
                    f"DIMACS line {lineno}: negative capacity: {line!r}"
                )
            if cap < low:
                raise ValueError(
                    f"DIMACS line {lineno}: upper capacity {cap} below lower "
                    f"bound {low}: {line!r}"
                )
            arcs.append(fields)
        else:
            raise ValueError(
                f"DIMACS line {lineno}: unknown record type {parts[0]!r}: {line!r}"
            )
    if header is not None:
        # a dead writer / cut pipe must fail loudly, not decode as a
        # partial graph (mirrors parse_flow's terminator contract);
        # node lines are not counted — standard DIMACS lists only
        # nonzero-excess nodes
        if not terminated:
            raise ValueError("DIMACS stream truncated: no 'c EOI' terminator")
        if len(arcs) != header[1]:
            raise ValueError(
                f"DIMACS stream truncated: header declares {header[1]} arcs, "
                f"got {len(arcs)}"
            )
    return header, nodes, arcs
