from .changes import ChangeManager, ChangeStats, ChangeType
from .device_export import DeviceGraphState, FlowProblem
from .flowgraph import Arc, ArcType, FlowGraph, Node, NodeType, resource_node_type
from .graph_manager import GraphManager, TaskMapping, task_needs_node

__all__ = [
    "ChangeManager",
    "ChangeStats",
    "ChangeType",
    "DeviceGraphState",
    "FlowProblem",
    "Arc",
    "ArcType",
    "FlowGraph",
    "Node",
    "NodeType",
    "resource_node_type",
    "GraphManager",
    "TaskMapping",
    "task_needs_node",
]
