"""Device-array graph state: the TPU-native replacement for the DIMACS wire.

Where the reference streams DIMACS text to a solver subprocess
(scheduling/flow/placement/solver.go:92-123), the TPU build keeps the
flow network as flat structure-of-arrays buffers whose row indices ARE
the flow-graph node ids (dense + recycled, see graph/flowgraph.py). A
full build converts the host graph once; afterwards the per-round change
journal (graph/changes.py) is scattered into the arrays in place, so the
cost of preparing a round's solve tracks the delta, not the graph — the
same property the reference gets from Flowlessly's incremental daemon
mode.

Arrays are padded to power-of-two extents so repeated jit solves reuse
the same compiled executable as the cluster grows (XLA static shapes).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from .changes import AddNodeChange, Change, ChangeArcChange, NewArcChange, RemoveNodeChange
from .flowgraph import FlowGraph, NodeType
from ..utils import next_pow2


@dataclass
class FlowProblem:
    """A min-cost max-flow instance in flat arrays.

    Row 0 of the node arrays is a padding row (graph node ids start at 1).
    Arc lower bounds are already folded into ``excess`` via the standard
    transformation; ``flow_offset`` holds the folded lower bound per arc so
    decoded flows can be restored (decoded_flow = solver_flow + flow_offset).
    """

    num_nodes: int  # dense extent including padding row
    excess: np.ndarray  # int64[N] supply(+)/demand(-) after lower-bound fold
    node_type: np.ndarray  # int8[N] NodeType, -1 for invalid rows
    src: np.ndarray  # int32[M]
    dst: np.ndarray  # int32[M]
    cap: np.ndarray  # int32[M] residual upper bound after lower-bound fold
    cost: np.ndarray  # int32[M]
    flow_offset: np.ndarray  # int32[M] folded lower bounds
    num_arcs: int  # live arc slots (<= len(src))

    @property
    def total_supply(self) -> int:
        return int(self.excess[self.excess > 0].sum())


class DeviceGraphState:
    """Maintains the padded flat arrays + the (src, dst) → arc-slot map.

    ``full_build`` constructs arrays from a host FlowGraph; ``apply_changes``
    scatters a change journal into them. Freed arc slots are recycled.
    """

    def __init__(self) -> None:
        self.n_cap = 0  # padded node extent
        self.m_cap = 0  # padded arc extent
        self.excess: Optional[np.ndarray] = None
        self.node_type: Optional[np.ndarray] = None
        self.src: Optional[np.ndarray] = None
        self.dst: Optional[np.ndarray] = None
        self.cap: Optional[np.ndarray] = None
        self.low: Optional[np.ndarray] = None
        self.cost: Optional[np.ndarray] = None
        self._arc_slot: Dict[Tuple[int, int], int] = {}
        self._free_slots: List[int] = []
        self._num_slots = 0
        self.num_nodes = 0
        self.generation = 0  # bumped when padded extents change (recompile signal)

    # -- construction -----------------------------------------------------

    def _alloc(self, n: int, m: int) -> None:
        self.n_cap = max(next_pow2(n), 16)
        self.m_cap = max(next_pow2(m), 16)
        self.excess = np.zeros(self.n_cap, dtype=np.int64)
        self.node_type = np.full(self.n_cap, -1, dtype=np.int8)
        self.src = np.zeros(self.m_cap, dtype=np.int32)
        self.dst = np.zeros(self.m_cap, dtype=np.int32)
        self.cap = np.zeros(self.m_cap, dtype=np.int32)
        self.low = np.zeros(self.m_cap, dtype=np.int32)
        self.cost = np.zeros(self.m_cap, dtype=np.int32)
        self.generation += 1

    def full_build(self, graph: FlowGraph) -> None:
        n = graph.max_node_id
        m = graph.num_arcs
        self._alloc(n, m)
        self._arc_slot.clear()
        self._free_slots.clear()
        self._num_slots = 0
        self.num_nodes = n
        for node in graph.nodes():
            self.excess[node.id] = node.excess
            self.node_type[node.id] = int(node.type)
        for arc in graph.arcs():
            self._set_arc(arc.src, arc.dst, arc.cap_lower, arc.cap_upper, arc.cost)

    # -- incremental updates ----------------------------------------------

    def _grow_nodes(self, need: int) -> None:
        new_cap = next_pow2(need)
        if new_cap <= self.n_cap:
            return
        self.excess = np.concatenate([self.excess, np.zeros(new_cap - self.n_cap, np.int64)])
        self.node_type = np.concatenate(
            [self.node_type, np.full(new_cap - self.n_cap, -1, np.int8)]
        )
        self.n_cap = new_cap
        self.generation += 1

    def _grow_arcs(self, need: int) -> None:
        new_cap = next_pow2(need)
        if new_cap <= self.m_cap:
            return
        pad = new_cap - self.m_cap
        for name in ("src", "dst", "cap", "low", "cost"):
            arr = getattr(self, name)
            setattr(self, name, np.concatenate([arr, np.zeros(pad, arr.dtype)]))
        self.m_cap = new_cap
        self.generation += 1

    def _take_slot(self) -> int:
        if self._free_slots:
            return self._free_slots.pop()
        slot = self._num_slots
        self._grow_arcs(slot + 1)
        self._num_slots += 1
        return slot

    def _set_arc(self, src: int, dst: int, low: int, cap: int, cost: int) -> None:
        key = (src, dst)
        slot = self._arc_slot.get(key)
        if cap == 0 and low == 0:
            if slot is not None:
                self.cap[slot] = 0
                self.low[slot] = 0
                self.cost[slot] = 0
                self.src[slot] = 0
                self.dst[slot] = 0
                del self._arc_slot[key]
                self._free_slots.append(slot)
            return
        if slot is None:
            slot = self._take_slot()
            self._arc_slot[key] = slot
        self.src[slot] = src
        self.dst[slot] = dst
        self.cap[slot] = cap
        self.low[slot] = low
        self.cost[slot] = cost

    def apply_changes(self, changes: List[Change]) -> None:
        for ch in changes:
            if isinstance(ch, AddNodeChange):
                self._grow_nodes(ch.node_id + 1)
                self.excess[ch.node_id] = ch.excess
                self.node_type[ch.node_id] = int(ch.node_type)
                self.num_nodes = max(self.num_nodes, ch.node_id + 1)
            elif isinstance(ch, RemoveNodeChange):
                self.excess[ch.node_id] = 0
                self.node_type[ch.node_id] = -1
            elif isinstance(ch, (NewArcChange, ChangeArcChange)):
                self._set_arc(ch.src, ch.dst, ch.cap_lower, ch.cap_upper, ch.cost)
            else:  # pragma: no cover
                raise TypeError(f"unknown change record: {ch!r}")

    def set_excess(self, node_id: int, excess: int) -> None:
        """Sink-excess bookkeeping happens outside the journal in the
        reference (graph_manager.go:636-640); mirror of that path."""
        self.excess[node_id] = excess

    # -- solver view ------------------------------------------------------

    def problem(self) -> FlowProblem:
        """Materialize the lower-bound-folded FlowProblem view.

        Copies the arrays (cheap at these sizes) so a solver can run while
        further host mutations accumulate.
        """
        m = self.m_cap
        excess = self.excess.copy()
        cap = self.cap[:m].astype(np.int32).copy()
        low = self.low[:m]
        cost = self.cost[:m].copy()
        src = self.src[:m].copy()
        dst = self.dst[:m].copy()
        flow_offset = low.astype(np.int32).copy()
        has_low = low > 0
        if has_low.any():
            idx = np.nonzero(has_low)[0]
            np.subtract.at(excess, src[idx], low[idx].astype(np.int64))
            np.add.at(excess, dst[idx], low[idx].astype(np.int64))
            cap[idx] -= low[idx]
        return FlowProblem(
            num_nodes=self.n_cap,
            excess=excess,
            node_type=self.node_type.copy(),
            src=src,
            dst=dst,
            cap=cap,
            cost=cost,
            flow_offset=flow_offset,
            num_arcs=self._num_slots,
        )
