"""Device-array graph state: the TPU-native replacement for the DIMACS wire.

Where the reference streams DIMACS text to a solver subprocess
(scheduling/flow/placement/solver.go:92-123), the TPU build keeps the
flow network as flat structure-of-arrays buffers whose row indices ARE
the flow-graph node ids (dense + recycled, see graph/flowgraph.py). A
full build converts the host graph once; afterwards the per-round change
journal (graph/changes.py) is scattered into the arrays in place, so the
cost of preparing a round's solve tracks the delta, not the graph — the
same property the reference gets from Flowlessly's incremental daemon
mode.

Arrays are padded to power-of-two extents so repeated jit solves reuse
the same compiled executable as the cluster grows (XLA static shapes).

Two consumers read the per-round mutations:

- ``problem()`` materializes the lower-bound-folded host FlowProblem,
  rebuilding only the array groups a journal entry actually touched
  since the last materialize (clean rounds return the cached object);
- ``DeviceResidentState`` mirrors the folded arrays as PERSISTENT
  device buffers: the round's dirty slots/nodes are packed on host
  into flat int32 delta records and applied by ONE jit'd scatter
  (`delta_apply_fn`), so after the initial full upload only
  delta-sized records cross the host/device boundary. The mirror is
  rebuilt only when a pow2 bucket grows or `full_build` reassigns the
  slot table — the recompile/reupload boundary the reference pays as
  a full DIMACS re-export.
"""

from __future__ import annotations

import functools
import itertools
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from .changes import AddNodeChange, Change, ChangeArcChange, NewArcChange, RemoveNodeChange
from .flowgraph import FlowGraph, NodeType
from ..utils import next_pow2


@dataclass
class FlowProblem:
    """A min-cost max-flow instance in flat arrays.

    Row 0 of the node arrays is a padding row (graph node ids start at 1).
    Arc lower bounds are already folded into ``excess`` via the standard
    transformation; ``flow_offset`` holds the folded lower bound per arc so
    decoded flows can be restored (decoded_flow = solver_flow + flow_offset).
    """

    num_nodes: int  # dense extent including padding row
    excess: np.ndarray  # int64[N] supply(+)/demand(-) after lower-bound fold
    node_type: np.ndarray  # int8[N] NodeType, -1 for invalid rows
    src: np.ndarray  # int32[M]
    dst: np.ndarray  # int32[M]
    cap: np.ndarray  # int32[M] residual upper bound after lower-bound fold
    cost: np.ndarray  # int32[M]
    flow_offset: np.ndarray  # int32[M] folded lower bounds
    num_arcs: int  # live arc slots (<= len(src))
    #: slot-stable CSR plan handle (graph/slot_plan.SlotPlanState) when
    #: the problem came from a DeviceGraphState; None for plain
    #: array-built problems (bulk, tests) — consumers that don't know
    #: about it (cpu_ref, native, ell, mega, sharded) just ignore it
    plan: object = None
    #: cheap endpoint-structure generation key
    #: (state uid, rebuild_count, n_cap, m_cap, endpoint_gen): two
    #: problems with equal keys have identical arc endpoints, so
    #: solver plan caches can skip their O(M) endpoint scans entirely
    #: on clean rounds (None = unknown, fall back to comparing arrays)
    plan_key: object = None

    @property
    def total_supply(self) -> int:
        return int(self.excess[self.excess > 0].sum())


def pad_problem(problem: FlowProblem, n_cap: int, m_cap: int) -> FlowProblem:
    """Zero-pad a FlowProblem into a LARGER pow2 shape bucket (the
    multi-tenant lane-alignment helper, tenancy/batch.py).

    Padding rows are inert by construction: pad nodes carry zero excess
    and node_type -1, pad arc slots are (0, 0) self-loops at node 0
    with zero cap/cost, whose forward AND backward residuals are zero —
    they can never push, relabel, or absorb prefix allocation, so the
    real prefix of the solved flow is unchanged by the padding.

    One caveat the tenancy layer documents and tests: the general-graph
    solvers pre-scale costs by ``num_nodes`` for eps=1 exactness, so a
    padded problem is a DIFFERENT (equally exact) solve than the
    unpadded one — bit-parity holds between runs that pad identically
    (a lane vs the same lane solved alone at the same bucket), not
    between a padded and an unpadded solve. Bucket assignment is
    therefore a per-tenant property (its own caps + a static floor),
    never a function of which co-tenants happen to share the process.
    """
    n0, m0 = problem.num_nodes, len(problem.src)
    if n_cap < n0 or m_cap < m0:
        raise ValueError(
            f"pad_problem cannot shrink: ({n0}, {m0}) -> ({n_cap}, {m_cap})"
        )
    if n_cap == n0 and m_cap == m0:
        return problem

    def pad_to(arr, size, fill=0):
        out = np.full(size, fill, dtype=arr.dtype)
        out[: len(arr)] = arr
        return out

    return FlowProblem(
        num_nodes=n_cap,
        excess=pad_to(problem.excess, n_cap),
        node_type=pad_to(problem.node_type, n_cap, fill=-1),
        src=pad_to(problem.src, m_cap),
        dst=pad_to(problem.dst, m_cap),
        cap=pad_to(problem.cap, m_cap),
        cost=pad_to(problem.cost, m_cap),
        flow_offset=pad_to(problem.flow_offset, m_cap),
        num_arcs=problem.num_arcs,
        plan=None,  # slot-stable plans do not survive re-padding
        plan_key=(
            ("padded", problem.plan_key, n_cap, m_cap)
            if problem.plan_key is not None
            else None
        ),
    )


_STATE_UIDS = itertools.count()


class DeviceGraphState:
    """Maintains the padded flat arrays + the (src, dst) → arc-slot map.

    ``full_build`` constructs arrays from a host FlowGraph; ``apply_changes``
    scatters a change journal into them. Freed arc slots are recycled.
    """

    def __init__(self) -> None:
        self.n_cap = 0  # padded node extent
        self.m_cap = 0  # padded arc extent
        self.excess: Optional[np.ndarray] = None
        self.node_type: Optional[np.ndarray] = None
        self.src: Optional[np.ndarray] = None
        self.dst: Optional[np.ndarray] = None
        self.cap: Optional[np.ndarray] = None
        self.low: Optional[np.ndarray] = None
        self.cost: Optional[np.ndarray] = None
        #: per-node lower-bound fold contribution, maintained
        #: incrementally as arc lows change: folded excess ==
        #: ``excess + fold`` (replaces the O(M) scatter fold the old
        #: problem() ran every round)
        self.fold: Optional[np.ndarray] = None
        self._arc_slot: Dict[Tuple[int, int], int] = {}
        self._free_slots: List[int] = []
        self._num_slots = 0
        self.num_nodes = 0
        self.generation = 0  # bumped when padded extents change (recompile signal)
        #: bumped by full_build only: the slot table was reassigned, so
        #: any device mirror of the arc arrays is wholesale invalid
        #: (growth keeps slots stable and is signaled by n_cap/m_cap)
        self.rebuild_count = 0
        #: bumped whenever some slot's (src, dst) actually changes —
        #: cap/cost-only journals leave it alone, so solver plan caches
        #: keyed on plan_key() skip their endpoint scans on clean rounds
        self.endpoint_gen = 0
        self._uid = next(_STATE_UIDS)
        #: slot-stable CSR plan (graph/slot_plan.py): an inert shell
        #: until a slot-stable consumer calls plan.ensure_built();
        #: after that the _set_arc hooks below keep it in sync per
        #: endpoint change, O(1) each
        from .slot_plan import SlotPlanState

        self.plan = SlotPlanState(self)
        # -- mutation tracking ------------------------------------------
        # Two consumers, two mechanisms: the problem() cache needs only
        # "did anything in this group change" booleans; the device-
        # resident mirror needs the exact touched slots/nodes to pack
        # delta records from. drain_dirty() empties the sets without
        # touching the cache flags, and vice versa.
        self._dirty_slots: Set[int] = set()
        self._dirty_nodes: Set[int] = set()
        self._cache: Optional[FlowProblem] = None
        self._cache_nodes_ok = False
        self._cache_arcs_ok = False

    # -- mutation bookkeeping ---------------------------------------------

    def _touch_slot(self, slot: int) -> None:
        self._dirty_slots.add(slot)
        self._cache_arcs_ok = False

    def _touch_node(self, node: int) -> None:
        self._dirty_nodes.add(node)
        self._cache_nodes_ok = False

    def _reset_tracking(self) -> None:
        """After a full (re)build every consumer must resync from the
        arrays wholesale; per-entry dirt from the build is noise."""
        self._dirty_slots.clear()
        self._dirty_nodes.clear()
        self._cache = None
        self._cache_nodes_ok = False
        self._cache_arcs_ok = False

    def drain_dirty(self) -> Tuple[np.ndarray, np.ndarray]:
        """The slots/nodes touched since the last drain, sorted (set
        order is not deterministic; packed records must be), and clear
        them. Consumed by DeviceResidentState.refresh()."""
        slots = np.sort(np.fromiter(self._dirty_slots, np.int32, len(self._dirty_slots)))
        nodes = np.sort(np.fromiter(self._dirty_nodes, np.int32, len(self._dirty_nodes)))
        self._dirty_slots.clear()
        self._dirty_nodes.clear()
        return slots, nodes

    # -- construction -----------------------------------------------------

    def _alloc(self, n: int, m: int) -> None:
        self.n_cap = max(next_pow2(n), 16)
        self.m_cap = max(next_pow2(m), 16)
        self.excess = np.zeros(self.n_cap, dtype=np.int64)  # kschedlint: host-only (host graph arrays; the device mirror is int32)
        self.node_type = np.full(self.n_cap, -1, dtype=np.int8)
        self.src = np.zeros(self.m_cap, dtype=np.int32)
        self.dst = np.zeros(self.m_cap, dtype=np.int32)
        self.cap = np.zeros(self.m_cap, dtype=np.int32)
        self.low = np.zeros(self.m_cap, dtype=np.int32)
        self.cost = np.zeros(self.m_cap, dtype=np.int32)
        self.fold = np.zeros(self.n_cap, dtype=np.int64)  # kschedlint: host-only (host graph arrays; the device mirror is int32)
        self.generation += 1
        self.plan.invalidate()

    def plan_key(self) -> Tuple:
        """Endpoint-structure generation key for this state's current
        arrays (see FlowProblem.plan_key)."""
        return (self._uid, self.rebuild_count, self.n_cap, self.m_cap, self.endpoint_gen)

    def full_build(self, graph: FlowGraph) -> None:
        n = graph.max_node_id
        m = graph.num_arcs
        self._alloc(n, m)
        self._arc_slot.clear()
        self._free_slots.clear()
        self._num_slots = 0
        self.num_nodes = n
        for node in graph.nodes():
            self.excess[node.id] = node.excess
            self.node_type[node.id] = int(node.type)
        for arc in graph.arcs():
            self._set_arc(arc.src, arc.dst, arc.cap_lower, arc.cap_upper, arc.cost)
        self.rebuild_count += 1  # slot table reassigned: device mirrors resync
        self._reset_tracking()

    # -- incremental updates ----------------------------------------------

    def _grow_nodes(self, need: int) -> None:
        new_cap = next_pow2(need)
        if new_cap <= self.n_cap:
            return
        self.excess = np.concatenate([self.excess, np.zeros(new_cap - self.n_cap, np.int64)])  # kschedlint: host-only (host graph arrays; the device mirror is int32)
        self.node_type = np.concatenate(
            [self.node_type, np.full(new_cap - self.n_cap, -1, np.int8)]
        )
        self.fold = np.concatenate([self.fold, np.zeros(new_cap - self.n_cap, np.int64)])  # kschedlint: host-only (host graph arrays; the device mirror is int32)
        self.n_cap = new_cap
        self.generation += 1
        self.plan.invalidate()  # regions must cover the new rows
        # shapes changed: every cached materialization is stale
        self._cache = None
        self._cache_nodes_ok = False
        self._cache_arcs_ok = False

    def _grow_arcs(self, need: int) -> None:
        new_cap = next_pow2(need)
        if new_cap <= self.m_cap:
            return
        pad = new_cap - self.m_cap
        for name in ("src", "dst", "cap", "low", "cost"):
            arr = getattr(self, name)
            setattr(self, name, np.concatenate([arr, np.zeros(pad, arr.dtype)]))
        self.m_cap = new_cap
        self.generation += 1
        self.plan.invalidate()  # entry budget + inv_order extent stale
        self._cache = None
        self._cache_nodes_ok = False
        self._cache_arcs_ok = False

    def _take_slot(self) -> int:
        if self._free_slots:
            return self._free_slots.pop()
        slot = self._num_slots
        self._grow_arcs(slot + 1)
        self._num_slots += 1
        return slot

    def _set_arc(self, src: int, dst: int, low: int, cap: int, cost: int) -> None:
        key = (src, dst)
        slot = self._arc_slot.get(key)
        low0 = int(self.low[slot]) if slot is not None else 0
        if cap == 0 and low == 0:
            if slot is not None:
                self.plan.slot_freed(slot, src, dst)
                self.endpoint_gen += 1
                self.cap[slot] = 0
                self.low[slot] = 0
                self.cost[slot] = 0
                self.src[slot] = 0
                self.dst[slot] = 0
                del self._arc_slot[key]
                self._free_slots.append(slot)
                self._touch_slot(slot)
                if low0:
                    self.fold[src] += low0
                    self.fold[dst] -= low0
                    self._touch_node(src)
                    self._touch_node(dst)
            return
        if slot is None:
            slot = self._take_slot()
            self._arc_slot[key] = slot
            self.plan.slot_assigned(slot, src, dst)
            self.endpoint_gen += 1
        if low != low0:
            # fold delta: an arc (src, dst) with lower bound L
            # contributes -L to src's folded excess and +L to dst's
            self.fold[src] += low0 - low
            self.fold[dst] += low - low0
            self._touch_node(src)
            self._touch_node(dst)
        self.src[slot] = src
        self.dst[slot] = dst
        self.cap[slot] = cap
        self.low[slot] = low
        self.cost[slot] = cost
        self._touch_slot(slot)

    def apply_changes(self, changes: List[Change]) -> None:
        for ch in changes:
            if isinstance(ch, AddNodeChange):
                self._grow_nodes(ch.node_id + 1)
                self.excess[ch.node_id] = ch.excess
                self.node_type[ch.node_id] = int(ch.node_type)
                self.num_nodes = max(self.num_nodes, ch.node_id + 1)
                self._touch_node(ch.node_id)
            elif isinstance(ch, RemoveNodeChange):
                self.excess[ch.node_id] = 0
                self.node_type[ch.node_id] = -1
                self._touch_node(ch.node_id)
            elif isinstance(ch, (NewArcChange, ChangeArcChange)):
                self._set_arc(ch.src, ch.dst, ch.cap_lower, ch.cap_upper, ch.cost)
            else:  # pragma: no cover
                raise TypeError(f"unknown change record: {ch!r}")

    def set_excess(self, node_id: int, excess: int) -> None:
        """Sink-excess bookkeeping happens outside the journal in the
        reference (graph_manager.go:636-640); mirror of that path. A
        no-op write stays invisible to the dirty tracking, so the
        every-round sink sync does not invalidate a clean cache."""
        if int(self.excess[node_id]) != excess:
            self.excess[node_id] = excess
            self._touch_node(node_id)

    # -- solver view ------------------------------------------------------

    def problem(self) -> FlowProblem:
        """Materialize the lower-bound-folded FlowProblem view.

        Copies the arrays (never aliases them) so a solver can keep its
        snapshot while further host mutations accumulate — but only the
        array GROUPS a journal entry touched since the last materialize
        are re-copied/refolded: the node side (excess, node_type) and
        the arc side (src/dst/cap/cost/flow_offset) invalidate
        independently, and a mutation-free round returns the cached
        FlowProblem outright. The lower-bound fold is the incrementally
        maintained ``fold`` array (one vector add), not a scatter pass.
        """
        cache = self._cache
        if cache is not None and self._cache_nodes_ok and self._cache_arcs_ok:
            return cache
        m = self.m_cap
        if cache is not None and self._cache_arcs_ok:
            src, dst, cap = cache.src, cache.dst, cache.cap
            cost, flow_offset = cache.cost, cache.flow_offset
        else:
            low = self.low[:m]
            src = self.src[:m].copy()
            dst = self.dst[:m].copy()
            cap = self.cap[:m] - low  # folded residual bound (new array)
            cost = self.cost[:m].copy()
            flow_offset = low.astype(np.int32)
        if cache is not None and self._cache_nodes_ok:
            excess, node_type = cache.excess, cache.node_type
        else:
            excess = self.excess + self.fold  # folded supply (new array)
            node_type = self.node_type.copy()
        self._cache = FlowProblem(
            num_nodes=self.n_cap,
            excess=excess,
            node_type=node_type,
            src=src,
            dst=dst,
            cap=cap,
            cost=cost,
            flow_offset=flow_offset,
            num_arcs=self._num_slots,
            plan=self.plan,
            plan_key=self.plan_key(),
        )
        self._cache_nodes_ok = True
        self._cache_arcs_ok = True
        return self._cache


# ---------------------------------------------------------------------------
# Device-resident mirror: persistent buffers + packed-record delta scatter
# ---------------------------------------------------------------------------

#: int32 columns of one packed arc delta record:
#: (slot, src, dst, folded cap, cost). flow_offset stays host-only —
#: no solver reads it on device (decode adds it back on host), so
#: shipping it would pad every record by a sixth for nothing.
ARC_RECORD_COLS = 5
#: int32 columns of one packed node delta record: (node, folded excess)
NODE_RECORD_COLS = 2
#: smallest padded record count — one compiled scatter program per pow2
#: record bucket, so tiny deltas share one executable
MIN_RECORD_BUCKET = 8


def pad_record_count(k: int) -> int:
    """Pow2 bucket for a delta-record count (>= 1 so an empty delta
    still has a well-formed — idempotent — record to ship)."""
    return max(next_pow2(max(k, 1)), MIN_RECORD_BUCKET)


_DELTA_APPLY = None


def delta_apply_fn():
    """The ONE jit'd scatter program of the solver stack: applies a
    round's packed delta records to the persistent device buffers.

    TPU serializes scatters, which is why every solver program is
    scatter-free (the zero-scatter jaxpr contract) — but the delta
    apply is O(records), not O(graph), and runs once per round, so a
    serialized scatter of ~churn-sized records is exactly the right
    tool. The jaxpr contracts grant this program a SCOPED exemption
    from the zero-scatter rule and pin its pow2-bucket hash stability
    (analysis/jaxpr_contracts.py).

    Records are pow2-padded by REPEATING a real record (or, for an
    empty delta, re-writing slot/node 0 with its current values):
    duplicate scatter updates carry identical values, so the result is
    deterministic regardless of XLA's scatter ordering.
    """
    global _DELTA_APPLY
    if _DELTA_APPLY is None:
        import jax

        # excess/cap/cost are DONATED: XLA scatters into the existing
        # buffers instead of copying the whole mirror first (measured
        # 498 -> 8.7 us/apply at 256k rows on CPU XLA; donation is
        # honored on CPU and TPU alike). src/dst are NOT donated — the
        # pre-delta endpoint buffers stay alive as the warm-flow masks
        # (device_warm_flow_fn) and the solvers' last-solve endpoint
        # handles; donating them would tear the buffers out from under
        # those references.
        @functools.partial(jax.jit, donate_argnums=(0, 3, 4))  # kschedlint: program=delta_apply
        def _apply_delta(excess, src, dst, cap, cost, arc_rec, node_rec):
            nid = node_rec[:, 0]
            excess = excess.at[nid].set(node_rec[:, 1])
            slot = arc_rec[:, 0]
            src = src.at[slot].set(arc_rec[:, 1])
            dst = dst.at[slot].set(arc_rec[:, 2])
            cap = cap.at[slot].set(arc_rec[:, 3])
            cost = cost.at[slot].set(arc_rec[:, 4])
            return excess, src, dst, cap, cost

        _DELTA_APPLY = _apply_delta
    return _DELTA_APPLY


_WARM_FLOW = None


def device_warm_flow_fn():
    """Scatter-free warm-flow carry: the previous round's device flow,
    kept where the arc endpoints are unchanged (compared against the
    PRE-delta endpoint buffers, which jax's immutability keeps alive
    for free) and clipped to the new capacities. Bit-identical to the
    host path's ``np.where(same, minimum(prev, cap), 0)``, so a
    device-resident loop decodes the same placements as a host loop.
    """
    global _WARM_FLOW
    if _WARM_FLOW is None:
        import jax
        import jax.numpy as jnp

        @jax.jit  # kschedlint: program=warm_flow
        def _warm_flow(prev_flow, src_prev, dst_prev, src, dst, cap):
            same = (src_prev == src) & (dst_prev == dst)
            return jnp.where(same, jnp.minimum(prev_flow, cap), jnp.int32(0))

        _WARM_FLOW = _warm_flow
    return _WARM_FLOW


_SCALE_COST = None


def _scale_cost_fn():
    global _SCALE_COST
    if _SCALE_COST is None:
        import jax

        @jax.jit  # kschedlint: program=scale_cost
        def _scale(cost, n):
            return cost * n

        _SCALE_COST = _scale
    return _SCALE_COST


@dataclass
class DeviceResidentProblem(FlowProblem):
    """A FlowProblem whose folded arrays ALSO live as persistent device
    buffers. The host arrays stay populated (decode, the cpu_ref/native
    ladder rungs, and the objective math read them), so every existing
    consumer keeps working; device-aware solvers read the ``d_*``
    handles instead of re-uploading.

    The warm-flow masks deliberately compare against endpoint buffers
    each solver captured at its own last SUCCESSFUL solve (not this
    refresh's pre-delta buffers): a failed/degraded round still
    refreshes the mirror, and masking against its endpoints would miss
    changes from the round the solver never saw — see
    ``resident_solver_inputs``.
    """

    d_excess: object = None  # jax int32[n_cap] folded supply
    d_src: object = None  # jax int32[m_cap]
    d_dst: object = None  # jax int32[m_cap]
    d_cap: object = None  # jax int32[m_cap] folded residual bound
    d_cost: object = None  # jax int32[m_cap] UNSCALED costs
    #: scatter-maintained slot-stable plan tensors in _solve_mcmf
    #: order (graph/slot_plan.py), or None until the mirror's first
    #: plan sync (the solver then full-uploads via the plan handle)
    d_plan: object = None
    resident: object = None  # owning DeviceResidentState
    version: int = 0

    def device_scaled_cost(self):
        """Costs pre-scaled by the node count (the general-graph
        solvers' exactness convention), computed on device once per
        refresh and cached on the owning resident state."""
        return self.resident.scaled_cost(self)


def resident_solver_inputs(problem, prev_flow, prev_src, prev_dst, warm_start):
    """The shared device-resident solve prologue for the general-graph
    backends (jax/ell/mega): the dispatch args read straight from the
    persistent buffers, and the warm flow is derived ON DEVICE from the
    solver's previous flow, masked against the endpoint buffers the
    solver captured at its last successful solve. Returns
    ``(dev_args, flow0, warm)`` where dev_args is
    (cap, scaled cost, supply). One implementation so the warm-gate
    rule can never silently diverge between backends."""
    import jax.numpy as jnp

    m = problem.d_cap.shape[0]
    dev_args = (
        problem.d_cap,
        problem.device_scaled_cost(),
        problem.d_excess,
    )
    warm = (
        warm_start
        and prev_flow is not None
        and prev_flow.shape[0] == m
        and prev_src is not None
        and prev_src.shape[0] == m
    )
    if warm:
        flow0 = device_warm_flow_fn()(
            prev_flow, prev_src, prev_dst,
            problem.d_src, problem.d_dst, problem.d_cap,
        )
    else:
        flow0 = jnp.zeros(m, jnp.int32)
    return dev_args, flow0, warm


class DeviceResidentState:
    """Persistent device mirror of a DeviceGraphState's folded problem
    arrays.

    ``refresh()`` (once per round, after the journal is applied on
    host) packs the touched slots/nodes into flat int32 records, ships
    ONLY those bytes, and applies them with the one jit'd scatter. The
    mirror is rebuilt wholesale only when:

    - ``full_build`` reassigned the slot table (rebuild_count moved),
    - the arc pow2 bucket grew (m_cap changed — slot values survive but
      the buffer shape is stale), or
    - the node pow2 bucket grew (n_cap; node side only — the arc
      buffers and the warm-flow geometry survive, as they do on host).

    ``last_upload_bytes``/``last_upload_kind`` expose the EXACT nbytes
    of what crossed the host→device boundary this refresh — the
    devprof h2d accounting reads them instead of estimating from
    ChangeStats.
    """

    def __init__(self, state: DeviceGraphState) -> None:
        self.state = state
        self.d_excess = None
        self.d_src = None
        self.d_dst = None
        self.d_cap = None
        self.d_cost = None
        self._rebuild_count = -1
        self._n_cap = -1
        self._m_cap = -1
        self.version = 0
        self.last_upload_bytes = 0
        self.last_upload_kind = "full_build"
        self.last_arc_records = 0
        self.last_node_records = 0
        self._scaled = None  # (version, jax scaled-cost buffer)
        # ---- slot-stable plan mirror (graph/slot_plan.py) ------------
        self.d_p_arc = None
        self.d_p_sign = None
        self.d_p_src = None
        self.d_p_dst = None
        self.d_inv = None
        #: boundary statics — mirror-OWNED copies (they are donated to
        #: the plan scatter when a relocation rewires them, so they
        #: must never alias the plan's own full-upload cache)
        self.d_seg = None
        self.d_isstart = None
        self.d_first = None
        self.d_last = None
        self.d_nonempty = None
        self._plan_gen = -1  # layout_gen mirrored
        self._plan_ver = -1  # value_version mirrored
        self.last_plan_kind = "none"  # none | rebuild | delta | clean
        self.last_plan_bytes = 0
        self.last_plan_records = 0
        #: sharded plan mirror mode (enable_sharded_plan): the entry-
        #: shaped plan tensors are maintained as [D, Es] stacked
        #: per-shard tables and the round's records route to their
        #: owner shards — None = single-chip mirror (the default)
        self._shard = None  # (mesh, axis, num_shards)

    # -- packing -----------------------------------------------------------

    def _pack_arcs(self, slots: np.ndarray) -> np.ndarray:
        st = self.state
        ka = len(slots)
        rec = np.zeros((pad_record_count(ka), ARC_RECORD_COLS), np.int32)
        if ka:
            low = st.low[slots]
            rec[:ka, 0] = slots
            rec[:ka, 1] = st.src[slots]
            rec[:ka, 2] = st.dst[slots]
            rec[:ka, 3] = st.cap[slots] - low
            rec[:ka, 4] = st.cost[slots]
            rec[ka:] = rec[0]  # idempotent pad: repeat a real record
        else:
            rec[:, 1] = st.src[0]
            rec[:, 2] = st.dst[0]
            rec[:, 3] = st.cap[0] - st.low[0]
            rec[:, 4] = st.cost[0]
        return rec

    def _pack_nodes(self, nodes: np.ndarray) -> np.ndarray:
        st = self.state
        kn = len(nodes)
        rec = np.zeros((pad_record_count(kn), NODE_RECORD_COLS), np.int32)
        folded0 = st.excess[nodes] + st.fold[nodes] if kn else None
        if kn:
            rec[:kn, 0] = nodes
            rec[:kn, 1] = folded0.astype(np.int32)
            rec[kn:] = rec[0]
        else:
            rec[:, 1] = np.int32(int(st.excess[0]) + int(st.fold[0]))
        return rec

    # -- refresh -----------------------------------------------------------

    def _full_upload(self, problem: FlowProblem, arcs_too: bool) -> int:
        import jax.numpy as jnp

        nbytes = 0
        self.d_excess = jnp.asarray(problem.excess.astype(np.int32))
        nbytes += self.d_excess.nbytes
        if arcs_too:
            self.d_src = jnp.asarray(problem.src)
            self.d_dst = jnp.asarray(problem.dst)
            self.d_cap = jnp.asarray(problem.cap)
            self.d_cost = jnp.asarray(problem.cost.astype(np.int32))
            nbytes += (
                self.d_src.nbytes + self.d_dst.nbytes
                + self.d_cap.nbytes + self.d_cost.nbytes
            )
        return nbytes

    def refresh(self) -> DeviceResidentProblem:
        """Sync the mirror with the host state and return the
        device-resident problem handle for this round's solve."""
        from ..obs.spans import span

        st = self.state
        problem = st.problem()
        slots, nodes = st.drain_dirty()
        rebuilt = self._rebuild_count != st.rebuild_count
        arcs_stale = rebuilt or self._m_cap != st.m_cap or self.d_src is None
        nodes_stale = rebuilt or self._n_cap != st.n_cap or self.d_excess is None
        if arcs_stale or nodes_stale:
            with span(
                "delta_upload",
                kind="full_build" if arcs_stale else "node_rebuild",
            ):
                nbytes = self._full_upload(problem, arcs_too=arcs_stale)
                if not arcs_stale:
                    # node bucket grew, arc side still delta-sized: the
                    # endpoint geometry survives, so warm flow does too
                    arc_rec = self._pack_arcs(slots)
                    self._scatter_arcs(arc_rec)
                    nbytes += arc_rec.nbytes
            self.last_upload_kind = "full_build"
            self.last_upload_bytes = nbytes
            self.last_arc_records = len(slots)
            self.last_node_records = len(nodes)
        else:
            with span("delta_pack", arcs=len(slots), nodes=len(nodes)):
                arc_rec = self._pack_arcs(slots)
                node_rec = self._pack_nodes(nodes)
            with span(
                "delta_upload", bytes=arc_rec.nbytes + node_rec.nbytes
            ):
                import jax.numpy as jnp

                apply_delta = delta_apply_fn()
                (
                    self.d_excess, self.d_src, self.d_dst,
                    self.d_cap, self.d_cost,
                ) = apply_delta(
                    self.d_excess, self.d_src, self.d_dst,
                    self.d_cap, self.d_cost,
                    jnp.asarray(arc_rec), jnp.asarray(node_rec),
                )
            self.last_upload_kind = "delta"
            self.last_upload_bytes = arc_rec.nbytes + node_rec.nbytes
            self.last_arc_records = len(slots)
            self.last_node_records = len(nodes)
        self._rebuild_count = st.rebuild_count
        self._n_cap = st.n_cap
        self._m_cap = st.m_cap
        self.version += 1
        d_plan = self._sync_plan()
        return DeviceResidentProblem(
            num_nodes=problem.num_nodes,
            excess=problem.excess,
            node_type=problem.node_type,
            src=problem.src,
            dst=problem.dst,
            cap=problem.cap,
            cost=problem.cost,
            flow_offset=problem.flow_offset,
            num_arcs=problem.num_arcs,
            d_excess=self.d_excess,
            d_src=self.d_src,
            d_dst=self.d_dst,
            d_cap=self.d_cap,
            d_cost=self.d_cost,
            d_plan=d_plan,
            resident=self,
            version=self.version,
            plan=st.plan,
            plan_key=st.plan_key(),
        )

    def enable_sharded_plan(self, mesh, axis: str = "x") -> None:
        """Maintain the slot-plan mirror in SHARDED form for the
        multi-chip rung (parallel/sharded_solver.py): the owning
        SlotPlanState switches to per-shard block layout, the
        entry-shaped device tensors become [D, Es] stacked tables
        placed by the partition rules (entry tables partitioned on the
        mesh axis, everything else replicated), and each round's dirty
        rows/segment statics ship as per-shard routed records through
        the donated shard_map scatter. Idempotent per (mesh, axis)."""
        D = int(mesh.shape[axis])
        if self._shard is not None and self._shard[0] is mesh and self._shard[1] == axis:
            return
        self._shard = (mesh, axis, D)
        self.state.plan.enable_sharding(D)
        self._plan_gen = -1  # mode flip: next sync re-uploads wholesale

    def _upload_plan_full(self, plan) -> None:
        """Fresh plan buffers from the host truth — the rebuild path
        AND the integrity ladder's reupload rung. In sharded mode the
        entry-shaped tensors are placed as [D, Es] stacked tables on
        the mesh; the rest replicate."""
        import jax.numpy as jnp

        if self._shard is None:
            self.d_p_arc = jnp.asarray(plan.p_arc)
            self.d_p_sign = jnp.asarray(plan.p_sign)
            self.d_p_src = jnp.asarray(plan.p_src)
            self.d_p_dst = jnp.asarray(plan.p_dst)
            self.d_inv = jnp.asarray(plan.inv_order)
            self.d_seg = jnp.asarray(plan.seg_start)
            self.d_isstart = jnp.asarray(plan.is_start)
            self.d_first = jnp.asarray(plan.node_first)
            self.d_last = jnp.asarray(plan.node_last)
            self.d_nonempty = jnp.asarray(plan.node_nonempty)
            return
        from ..parallel.sharded_solver import place_sharded_plan

        mesh, axis, D = self._shard
        (
            self.d_p_arc, self.d_p_sign, self.d_p_src, self.d_p_dst,
            self.d_seg, self.d_isstart, self.d_inv,
            self.d_first, self.d_last, self.d_nonempty,
        ) = place_sharded_plan(
            mesh, axis, plan.host_args(), D, plan.block_extent
        )

    def _scatter_plan_delta(self, plan) -> Tuple[int, int]:
        """Apply a round's dirty plan records; (bytes, records)."""
        import jax.numpy as jnp

        from ..obs.spans import span

        if self._shard is None:
            from .slot_plan import plan_apply_fn

            row_rec, inv_rec, seg_rec, node_rec = plan.drain_records()
            rec_bytes = (
                row_rec.nbytes + inv_rec.nbytes
                + seg_rec.nbytes + node_rec.nbytes
            )
            with span("plan_upload", kind="delta", bytes=rec_bytes):
                apply_plan = plan_apply_fn()
                (
                    self.d_p_arc, self.d_p_sign, self.d_p_src,
                    self.d_p_dst, self.d_inv,
                    self.d_seg, self.d_isstart,
                    self.d_first, self.d_last, self.d_nonempty,
                ) = apply_plan(
                    self.d_p_arc, self.d_p_sign, self.d_p_src,
                    self.d_p_dst, self.d_inv,
                    self.d_seg, self.d_isstart,
                    self.d_first, self.d_last, self.d_nonempty,
                    jnp.asarray(row_rec), jnp.asarray(inv_rec),
                    jnp.asarray(seg_rec), jnp.asarray(node_rec),
                )
            records = (
                len(row_rec) + len(inv_rec) + len(seg_rec) + len(node_rec)
            )
            return rec_bytes, records
        from ..parallel.sharded_solver import (
            replicated_plan_apply_fn,
            sharded_plan_apply_fn,
        )

        mesh, axis, D = self._shard
        row_rec, seg_rec, inv_rec, node_rec = plan.drain_records_sharded()
        rec_bytes = (
            row_rec.nbytes + seg_rec.nbytes
            + inv_rec.nbytes + node_rec.nbytes
        )
        with span(
            "plan_upload", kind="sharded_delta", bytes=rec_bytes, shards=D
        ):
            (
                self.d_p_arc, self.d_p_sign, self.d_p_src, self.d_p_dst,
                self.d_seg, self.d_isstart,
            ) = sharded_plan_apply_fn(mesh, axis)(
                self.d_p_arc, self.d_p_sign, self.d_p_src, self.d_p_dst,
                self.d_seg, self.d_isstart,
                jnp.asarray(row_rec), jnp.asarray(seg_rec),
            )
            (
                self.d_inv, self.d_first, self.d_last, self.d_nonempty,
            ) = replicated_plan_apply_fn()(
                self.d_inv, self.d_first, self.d_last, self.d_nonempty,
                jnp.asarray(inv_rec), jnp.asarray(node_rec),
            )
        records = (
            int(np.prod(row_rec.shape[:2])) + int(np.prod(seg_rec.shape[:2]))
            + len(inv_rec) + len(node_rec)
        )
        return rec_bytes, records

    def plan_fingerprints(self) -> np.ndarray:
        """uint32 checksum per mirrored plan tensor, FP_PLAN_ARRAYS
        order — the sharded mirror psums per-shard partials with
        global-index weights, so both modes compare against the SAME
        host twins (runtime/integrity.StateAuditor)."""
        bufs = (
            self.d_p_arc, self.d_p_sign, self.d_p_src, self.d_p_dst,
            self.d_inv, self.d_seg, self.d_isstart,
            self.d_first, self.d_last, self.d_nonempty,
        )
        if self._shard is None:
            from ..runtime.integrity import device_fingerprints

            return device_fingerprints(bufs)
        from ..parallel.sharded_solver import sharded_plan_fingerprint_fn

        mesh, axis, _D = self._shard
        fps = sharded_plan_fingerprint_fn(mesh, axis)(*bufs)
        return np.asarray(fps).astype(np.int32).view(np.uint32)

    def _sync_plan(self):
        """Mirror the slot-stable plan (graph/slot_plan.py) as
        persistent device tensors. Inactive until a slot-stable
        consumer enables the plan (so non-jax backends pay nothing);
        afterwards each round ships only the dirty plan rows / inv
        entries through the ONE jit'd plan scatter (per-shard routed
        in sharded mode), and the full re-upload survives only on
        layout rebuilds (full_build, pow2 bucket growth, region
        overflow). Returns the plan tensors in `_solve_mcmf` order
        (entry-shaped ones stacked [D, Es] in sharded mode), or None
        while inactive."""
        from ..obs.spans import span

        plan = self.state.plan
        self.last_plan_kind = "none"
        self.last_plan_bytes = 0
        self.last_plan_records = 0
        if plan is None or not plan.enabled:
            return None
        plan.ensure_built()
        if self._plan_gen != plan.layout_gen:
            # layout rebuilt: fresh buffers all around (they will be
            # donated by later scatters, so never share the plan's own
            # full-upload cache)
            with span("plan_upload", kind="rebuild"):
                self._upload_plan_full(plan)
            plan.clear_pending()
            self._plan_gen = plan.layout_gen
            self._plan_ver = plan.value_version
            self.last_plan_kind = "rebuild"
            self.last_plan_bytes = plan.values_nbytes() + plan.static_nbytes()
            self.last_upload_bytes += self.last_plan_bytes
        elif plan.value_version != self._plan_ver or plan.has_pending:
            rec_bytes, records = self._scatter_plan_delta(plan)
            self._plan_ver = plan.value_version
            self.last_plan_kind = "delta"
            self.last_plan_bytes = rec_bytes
            self.last_plan_records = records
            self.last_upload_bytes += self.last_plan_bytes
        else:
            self.last_plan_kind = "clean"
        return (
            self.d_p_arc, self.d_p_sign, self.d_p_src, self.d_p_dst,
            self.d_seg, self.d_isstart, self.d_inv,
            self.d_first, self.d_last, self.d_nonempty,
        )

    def _scatter_arcs(self, arc_rec: np.ndarray) -> None:
        """Arc-side-only scatter (node-rebuild refreshes): reuses the
        one delta program with an empty — idempotent — node record."""
        import jax.numpy as jnp

        node_rec = self._pack_nodes(np.zeros(0, np.int32))
        apply_delta = delta_apply_fn()
        (
            self.d_excess, self.d_src, self.d_dst, self.d_cap, self.d_cost,
        ) = apply_delta(
            self.d_excess, self.d_src, self.d_dst, self.d_cap, self.d_cost,
            jnp.asarray(arc_rec), jnp.asarray(node_rec),
        )

    def scaled_cost(self, problem: DeviceResidentProblem):
        """d_cost * num_nodes, computed on device, cached per refresh."""
        if self._scaled is None or self._scaled[0] != problem.version:
            import jax.numpy as jnp

            scaled = _scale_cost_fn()(
                problem.d_cost, jnp.int32(problem.num_nodes)
            )
            self._scaled = (problem.version, scaled)
        return self._scaled[1]

    def rebind(self, problem: "DeviceResidentProblem") -> None:
        """Re-point a problem handle at the mirror's CURRENT buffers.
        Required after any out-of-band buffer replacement (divergence
        repair, injected corruption): the repair/poison scatters
        produce new buffers (and repairs may donate the old ones), so
        a handle built at refresh time would read dead or stale
        arrays."""
        problem.d_excess = self.d_excess
        problem.d_src = self.d_src
        problem.d_dst = self.d_dst
        problem.d_cap = self.d_cap
        problem.d_cost = self.d_cost
        if problem.d_plan is not None and self._plan_gen >= 0:
            problem.d_plan = (
                self.d_p_arc, self.d_p_sign, self.d_p_src, self.d_p_dst,
                self.d_seg, self.d_isstart, self.d_inv,
                self.d_first, self.d_last, self.d_nonempty,
            )
        self._scaled = None

    def parity_check(self) -> None:
        """Verify the device mirror equals the host folded view
        bit-for-bit (fetches the buffers; audit/debug — the cheap
        per-round path is the fingerprint audit in
        runtime/integrity.py). Raises a structured IntegrityError
        carrying a bounded diff (first-k mismatching indices,
        expected vs found)."""
        from ..runtime.integrity import bounded_diff

        problem = self.state.problem()
        pairs = (
            (self.d_excess, problem.excess.astype(np.int32)),
            (self.d_src, problem.src),
            (self.d_dst, problem.dst),
            (self.d_cap, problem.cap),
            (self.d_cost, problem.cost.astype(np.int32)),
        )
        names = ("excess", "src", "dst", "cap", "cost")
        for name, (dev, host) in zip(names, pairs):
            got = np.asarray(dev)
            if not np.array_equal(got, host):
                raise bounded_diff(f"device mirror {name}", got, host)

    def plan_parity_check(self) -> None:
        """Assert the scatter-maintained device plan tensors equal the
        host-maintained plan arrays bit-for-bit (the full-rebuild
        materialization; test/debug only)."""
        plan = self.state.plan
        if plan is None or not plan.enabled or self._plan_gen < 0:
            return
        if plan.needs_rebuild or self._plan_gen != plan.layout_gen or (
            self._plan_ver != plan.value_version
        ):
            return  # mirror legitimately behind (mutations since refresh)
        pairs = (
            ("p_arc", self.d_p_arc, plan.p_arc),
            ("p_sign", self.d_p_sign, plan.p_sign),
            ("p_src", self.d_p_src, plan.p_src),
            ("p_dst", self.d_p_dst, plan.p_dst),
            ("inv_order", self.d_inv, plan.inv_order),
            ("seg_start", self.d_seg, plan.seg_start),
            ("is_start", self.d_isstart, plan.is_start),
            ("node_first", self.d_first, plan.node_first),
            ("node_last", self.d_last, plan.node_last),
            ("node_nonempty", self.d_nonempty, plan.node_nonempty),
        )
        from ..runtime.integrity import bounded_diff

        for name, dev, host in pairs:
            got = np.asarray(dev)
            if got.ndim > 1:  # sharded [D, Es] stacking of the [E] host tensor
                got = got.reshape(-1)
            if not np.array_equal(got, host):
                raise bounded_diff(f"device plan mirror {name}", got, host)


# Level-3 registry ownership (ksched_tpu/analysis/program_registry.py)
from ..analysis.program_registry import declare_programs as _declare_programs

_declare_programs(__name__, "delta_apply", "warm_flow", "scale_cost")
