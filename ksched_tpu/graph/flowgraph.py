"""L2: the mutable flow network.

Reference: scheduling/flow/flowgraph/{graph.go,node.go,arc.go}. Same
capability surface — add/change/delete nodes and arcs, id recycling,
13 node kinds, running-vs-other arc types — with one structural change
for the TPU build: node ids are dense, recycled ints handed out by an
IDGenerator so they double as row indices into the flat device arrays
that the solver consumes (no DIMACS text in between).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, Iterator, Optional

from ..data import ResourceDescriptor, ResourceType, TaskDescriptor, TaskState
from ..utils import IDGenerator


class NodeType(enum.IntEnum):
    """Flow node kinds (reference: flowgraph/node.go:27-41)."""

    ROOT_TASK = 0
    SCHEDULED_TASK = 1
    UNSCHEDULED_TASK = 2
    JOB_AGGREGATOR = 3
    SINK = 4
    EQUIV_CLASS = 5
    COORDINATOR = 6
    MACHINE = 7
    NUMA = 8
    SOCKET = 9
    CACHE = 10
    CORE = 11
    PU = 12


_TASK_NODE_TYPES = frozenset(
    {NodeType.ROOT_TASK, NodeType.SCHEDULED_TASK, NodeType.UNSCHEDULED_TASK}
)
_RESOURCE_NODE_TYPES = frozenset(
    {
        NodeType.COORDINATOR,
        NodeType.MACHINE,
        NodeType.NUMA,
        NodeType.SOCKET,
        NodeType.CACHE,
        NodeType.CORE,
        NodeType.PU,
    }
)

_RESOURCE_TO_NODE_TYPE = {
    ResourceType.PU: NodeType.PU,
    ResourceType.CORE: NodeType.CORE,
    ResourceType.CACHE: NodeType.CACHE,
    ResourceType.MACHINE: NodeType.MACHINE,
    ResourceType.NUMA_NODE: NodeType.NUMA,
    ResourceType.SOCKET: NodeType.SOCKET,
    ResourceType.COORDINATOR: NodeType.COORDINATOR,
}


def resource_node_type(rd: ResourceDescriptor) -> NodeType:
    """Map a resource descriptor's type to a flow node type (reference:
    flowgraph/node.go:161-191; NIC/DISK/SSD/LOGICAL unsupported there too)."""
    try:
        return _RESOURCE_TO_NODE_TYPE[rd.type]
    except KeyError:
        raise ValueError(f"resource type not supported as a flow node: {rd.type!r}")


class ArcType(enum.IntEnum):
    """Reference: flowgraph/arc.go:20-23."""

    OTHER = 0
    RUNNING = 1


@dataclass
class Arc:
    """A directed arc with capacity bounds and cost (reference:
    flowgraph/arc.go:26-47)."""

    src: int
    dst: int
    src_node: "Node"
    dst_node: "Node"
    cap_lower: int = 0
    cap_upper: int = 0
    cost: int = 0
    type: ArcType = ArcType.OTHER


@dataclass
class Node:
    """A flow-graph node (reference: flowgraph/node.go:76-106)."""

    id: int
    excess: int = 0
    type: NodeType = NodeType.ROOT_TASK
    comment: str = ""
    task: Optional[TaskDescriptor] = None
    job_id: int = 0
    resource_id: int = 0
    resource_descriptor: Optional[ResourceDescriptor] = None
    equiv_class: Optional[int] = None
    outgoing: Dict[int, Arc] = field(default_factory=dict)
    incoming: Dict[int, Arc] = field(default_factory=dict)
    visited: int = 0

    @property
    def is_task_node(self) -> bool:
        return self.type in _TASK_NODE_TYPES

    @property
    def is_resource_node(self) -> bool:
        return self.type in _RESOURCE_NODE_TYPES

    @property
    def is_equiv_class_node(self) -> bool:
        return self.type == NodeType.EQUIV_CLASS

    @property
    def is_task_assigned_or_running(self) -> bool:
        assert self.task is not None, f"node {self.id} has no task descriptor"
        return self.task.state in (TaskState.ASSIGNED, TaskState.RUNNING)


class FlowGraph:
    """Mutable directed flow network with recycled dense integer node ids
    (reference: flowgraph/graph.go:27-201). The id free-list keeps the id
    space compact so ids can serve as device-array row indices."""

    def __init__(self) -> None:
        self._nodes: Dict[int, Node] = {}
        self._arcs: Dict[tuple, Arc] = {}  # (src, dst) -> Arc; capacity>0 arcs
        self._ids = IDGenerator(start=1)

    # -- nodes ------------------------------------------------------------

    def add_node(self) -> Node:
        nid = self._ids.take()
        if nid in self._nodes:
            raise RuntimeError(f"node id {nid} already present")
        node = Node(id=nid)
        self._nodes[nid] = node
        return node

    def delete_node(self, node: Node) -> None:
        """Remove a node and all its arcs; recycle the id (reference:
        flowgraph/graph.go:131-161)."""
        for arc in list(node.outgoing.values()):
            self.delete_arc(arc)
        for arc in list(node.incoming.values()):
            self.delete_arc(arc)
        del self._nodes[node.id]
        self._ids.give_back(node.id)

    def node(self, nid: int) -> Optional[Node]:
        return self._nodes.get(nid)

    def nodes(self) -> Iterator[Node]:
        return iter(self._nodes.values())

    @property
    def num_nodes(self) -> int:
        return len(self._nodes)

    @property
    def max_node_id(self) -> int:
        """One past the largest id ever allocated — the dense array extent."""
        return self._ids.high_water_mark

    # -- arcs -------------------------------------------------------------

    def add_arc(self, src: Node, dst: Node) -> Arc:
        if src.id not in self._nodes or dst.id not in self._nodes:
            raise RuntimeError(f"add_arc: unknown endpoint {src.id}->{dst.id}")
        arc = Arc(src=src.id, dst=dst.id, src_node=src, dst_node=dst)
        if dst.id in src.outgoing:
            raise RuntimeError(f"arc {src.id}->{dst.id} already present")
        src.outgoing[dst.id] = arc
        dst.incoming[src.id] = arc
        self._arcs[(src.id, dst.id)] = arc
        return arc

    def change_arc(self, arc: Arc, cap_lower: int, cap_upper: int, cost: int) -> None:
        """Update an arc in place; zero capacity removes it from the live
        arc set but keeps it attached to its endpoints (reference:
        flowgraph/graph.go:77-84 — delete = capacity→0 is the trick that
        keeps incremental re-solves sound)."""
        if cap_lower == 0 and cap_upper == 0:
            self._arcs.pop((arc.src, arc.dst), None)
        elif (arc.src, arc.dst) not in self._arcs and arc.dst in arc.src_node.outgoing:
            # Re-register an arc that was previously zeroed out (the
            # reference never re-adds these to its arc set — graph.go:77-84 —
            # which silently drops them from full re-exports; we fix that).
            self._arcs[(arc.src, arc.dst)] = arc
        arc.cap_lower = cap_lower
        arc.cap_upper = cap_upper
        arc.cost = cost

    def delete_arc(self, arc: Arc) -> None:
        arc.src_node.outgoing.pop(arc.dst, None)
        arc.dst_node.incoming.pop(arc.src, None)
        self._arcs.pop((arc.src, arc.dst), None)

    def get_arc(self, src: Node, dst: Node) -> Optional[Arc]:
        return src.outgoing.get(dst.id)

    def arcs(self) -> Iterator[Arc]:
        return iter(self._arcs.values())

    @property
    def num_arcs(self) -> int:
        return len(self._arcs)
