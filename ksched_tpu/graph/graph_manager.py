"""L5: the graph manager — job/task/resource lifecycle → graph mutations.

Reference: scheduling/flow/flowmanager/graph_manager.go (the heart of the
system, 1338 lines). Behavior parity notes:

- every mutation goes through the journaled ChangeManager (the invariant
  that makes incremental solving possible, SURVEY §3.5);
- task nodes carry supply 1 and the sink absorbs it (addTaskNode
  graph_manager.go:632-648, removeTaskNode :803-813);
- each job gets an unscheduled-aggregator escape node so infeasibility is
  impossible (updateUnscheduledAggNode :1287-1305);
- the preemption flag flips both the capacity rule on resource arcs
  (:662-667) and scheduled-task arc handling (pin vs keep, :675-720,
  :855-888);
- AddOrUpdateJobNodes drives a worklist BFS (updateFlowGraph :1012-1033)
  that touches task, EC, and resource nodes exactly once per round.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, Dict, List, Optional, Set, Tuple

from ..costmodels.base import CostModeler
from ..data import (
    DeltaType,
    JobDescriptor,
    ResourceDescriptor,
    ResourceTopologyNodeDescriptor,
    ResourceType,
    SchedulingDelta,
    TaskDescriptor,
    TaskState,
)
from ..utils import ResourceMap, job_id_from_string, resource_id_from_string
from .changes import ChangeManager, ChangeStats, ChangeType
from .flowgraph import Arc, ArcType, Node, NodeType, resource_node_type

TaskMapping = Dict[int, int]  # task node id -> PU node id (flowmanager/types.go:6)


def task_needs_node(td: TaskDescriptor) -> bool:
    """Reference: graph_manager.go:1333-1338."""
    return td.state in (TaskState.RUNNABLE, TaskState.RUNNING, TaskState.ASSIGNED)


class GraphManager:
    def __init__(
        self,
        cost_model: CostModeler,
        leaf_resource_ids: Set[int],
        stats: Optional[ChangeStats] = None,
        max_tasks_per_pu: int = 1,
        preemption: bool = False,
        update_preferences_running_task: bool = False,
    ) -> None:
        self.preemption = preemption
        self.update_preferences_running_task = update_preferences_running_task
        self.max_tasks_per_pu = max_tasks_per_pu
        self.cm = ChangeManager(stats)
        self.cost_model = cost_model
        self.sink_node = self.cm.add_node(NodeType.SINK, 0, ChangeType.ADD_SINK_NODE, "SINK")

        self.resource_to_node: Dict[int, Node] = {}
        self.task_to_node: Dict[int, Node] = {}
        self.task_ec_to_node: Dict[int, Node] = {}
        self.job_unsched_to_node: Dict[int, Node] = {}
        self.task_to_running_arc: Dict[int, Arc] = {}
        self.node_to_parent_node: Dict[int, Node] = {}  # keyed by node id
        self.leaf_resource_ids = leaf_resource_ids  # shared with the cost model
        self.leaf_node_ids: Set[int] = set()
        self._cur_traversal_counter = 0
        self._ec_purge_candidates: Set[int] = set()  # unconnected last purge

    # ------------------------------------------------------------------
    # Public lifecycle API (reference interface graph_manager.go:32-86)
    # ------------------------------------------------------------------

    def add_or_update_job_nodes(self, jobs: List[JobDescriptor]) -> None:
        """Reference: graph_manager.go:166-208."""
        node_queue: Deque[Tuple[Optional[Node], TaskDescriptor]] = deque()
        marked: Set[int] = set()
        for job in jobs:
            jid = job_id_from_string(job.uuid)
            if jid not in self.job_unsched_to_node:
                self._add_unscheduled_agg_node(jid)
            root_td = job.root_task
            assert root_td is not None, f"job {job.uuid} has no root task"
            root_node = self.task_to_node.get(root_td.uid)
            if root_node is not None:
                node_queue.append((root_node, root_td))
                marked.add(root_node.id)
                continue
            if task_needs_node(root_td):
                root_node = self._add_task_node(jid, root_td)
                self._update_unscheduled_agg_node(self.job_unsched_to_node[jid], 1)
                node_queue.append((root_node, root_td))
                marked.add(root_node.id)
            else:
                # No node yet; still traverse for schedulable children.
                node_queue.append((None, root_td))
        self._update_flow_graph(node_queue, marked)

    def update_time_dependent_costs(self, jobs: List[JobDescriptor]) -> None:
        self.add_or_update_job_nodes(jobs)

    def add_resource_topology(self, rtnd: ResourceTopologyNodeDescriptor) -> None:
        """Reference: graph_manager.go:238-251."""
        rd = rtnd.resource_desc
        self._add_resource_topology_dfs(rtnd)
        if rtnd.parent_id:
            curr = self.resource_to_node[resource_id_from_string(rtnd.parent_id)]
            self._update_resource_stats_up_to_root(
                curr,
                self._capacity_to_parent(rd),
                rd.num_slots_below,
                rd.num_running_tasks_below,
            )

    def update_resource_topology(self, rtnd: ResourceTopologyNodeDescriptor) -> None:
        """Reference: graph_manager.go:217-236."""
        rd = rtnd.resource_desc
        old_capacity = self._capacity_to_parent(rd)
        old_slots = rd.num_slots_below
        old_running = rd.num_running_tasks_below
        self._update_resource_topology_dfs(rtnd)
        if rtnd.parent_id:
            curr = self.resource_to_node[resource_id_from_string(rtnd.parent_id)]
            self._update_resource_stats_up_to_root(
                curr,
                self._capacity_to_parent(rd) - old_capacity,
                rd.num_slots_below - old_slots,
                rd.num_running_tasks_below - old_running,
            )

    def remove_resource_topology(self, rd: ResourceDescriptor) -> List[int]:
        """Reference: graph_manager.go:362-387. Returns removed PU node ids."""
        r_node = self.resource_to_node.get(resource_id_from_string(rd.uuid))
        if r_node is None:
            raise KeyError(f"no node for resource {rd.uuid}")
        removed_pus: List[int] = []
        cap_delta = 0
        for arc in list(r_node.outgoing.values()):
            cap_delta -= arc.cap_upper
            if arc.dst_node.resource_id != 0:
                removed_pus.extend(self._traverse_and_remove_topology(arc.dst_node))
        self._update_resource_stats_up_to_root(
            r_node,
            cap_delta,
            -r_node.resource_descriptor.num_slots_below,
            -r_node.resource_descriptor.num_running_tasks_below,
        )
        if r_node.type == NodeType.PU:
            removed_pus.append(r_node.id)
        elif r_node.type == NodeType.MACHINE:
            self.cost_model.remove_machine(r_node.resource_id)
        self._remove_resource_node(r_node)
        return removed_pus

    def job_completed(self, job_id: int) -> None:
        """Reference: graph_manager.go:341-345."""
        node = self.job_unsched_to_node.pop(job_id)
        self.cm.delete_node(node, ChangeType.DEL_UNSCHED_JOB_NODE, "JobCompleted")

    def purge_unconnected_equiv_class_nodes(self) -> None:
        """Remove equivalence-class nodes nothing points at (reference
        declares this, graph_manager.go:347-357, but never calls it;
        the scheduler here runs it per round).

        Debounced: an EC must be unconnected on two consecutive calls
        before removal, so ECs that are merely transiently unconnected
        (e.g. every task pinned this round, new arrivals next round)
        don't churn their wide EC->machine fan-outs through the change
        journal each cycle. ECs orphaned by a removal within this call
        (their only in-arcs came from a purged EC) are dead for certain
        and cascade immediately — the reference's note about multi-call
        subgraph cleanup (graph_manager.go:348-351) without leaving
        chains behind if the cluster quiesces."""

        def unconnected() -> set:
            return {
                ec for ec, node in self.task_ec_to_node.items() if not node.incoming
            }

        seen = unconnected()
        doomed = seen & self._ec_purge_candidates
        while doomed:
            for ec in doomed:
                self._remove_equiv_class_node(self.task_ec_to_node[ec])
            now = unconnected()
            doomed = now - seen  # newly orphaned by this wave: cascade
            seen |= now
        self._ec_purge_candidates = unconnected()

    def task_completed(self, task_id: int) -> int:
        """Reference: graph_manager.go:389-405."""
        task_node = self.task_to_node[task_id]
        if self.preemption:
            self._update_unscheduled_agg_node(self.job_unsched_to_node[task_node.job_id], -1)
        self.task_to_running_arc.pop(task_id, None)
        return self._remove_task_node(task_node)
        # The task stays in the cost model: final-report handling still
        # needs its equivalence classes (reference note at :402-404).

    def task_evicted(self, task_id: int, resource_id: int) -> None:
        """Reference: graph_manager.go:412-433."""
        task_node = self.task_to_node[task_id]
        task_node.type = NodeType.UNSCHEDULED_TASK
        arc = self.task_to_running_arc.pop(task_id)
        self.cm.delete_arc(arc, ChangeType.DEL_ARC_EVICTED_TASK, "TaskEvicted: delete running arc")
        if not self.preemption:
            jid = job_id_from_string(task_node.task.job_id)
            self._update_unscheduled_agg_node(self.job_unsched_to_node[jid], 1)

    def task_failed(self, task_id: int) -> None:
        """Reference: graph_manager.go:435-448."""
        task_node = self.task_to_node[task_id]
        if self.preemption:
            self._update_unscheduled_agg_node(self.job_unsched_to_node[task_node.job_id], -1)
        self.task_to_running_arc.pop(task_id, None)
        self._remove_task_node(task_node)
        self.cost_model.remove_task(task_id)

    def task_killed(self, task_id: int) -> None:
        self.task_failed(task_id)

    def task_migrated(self, task_id: int, from_rid: int, to_rid: int) -> None:
        self.task_evicted(task_id, from_rid)
        self.task_scheduled(task_id, to_rid)

    def task_scheduled(self, task_id: int, resource_id: int) -> None:
        """Reference: graph_manager.go:454-460."""
        task_node = self.task_to_node[task_id]
        task_node.type = NodeType.SCHEDULED_TASK
        res_node = self.resource_to_node[resource_id]
        self._update_arcs_for_scheduled_task(task_node, res_node)

    def update_all_costs_to_unscheduled_aggs(self) -> None:
        """Reference: graph_manager.go:462-475."""
        for job_node in self.job_unsched_to_node.values():
            for arc in list(job_node.incoming.values()):
                if arc.src_node.is_task_assigned_or_running:
                    self._update_running_task_node(arc.src_node, False, None, None)
                else:
                    self._update_task_to_unscheduled_agg_arc(arc.src_node)

    def compute_topology_statistics(self, start: Node) -> None:
        """Reverse BFS from the sink, gathering usage statistics; correct
        only for tree topologies (reference: graph_manager.go:478-511)."""
        self._cur_traversal_counter += 1
        counter = self._cur_traversal_counter
        to_visit: Deque[Node] = deque([start])
        start.visited = counter
        while to_visit:
            cur = to_visit.popleft()
            for arc in cur.incoming.values():
                src = arc.src_node
                if src.visited != counter:
                    self.cost_model.prepare_stats(src)
                    to_visit.append(src)
                    src.visited = counter
                self.cost_model.gather_stats(src, cur)
                self.cost_model.update_stats(src, cur)

    # ------------------------------------------------------------------
    # Delta generation (reference: graph_manager.go:253-339)
    # ------------------------------------------------------------------

    def node_binding_to_scheduling_delta(
        self, task_node_id: int, res_node_id: int, task_bindings: Dict[int, int]
    ) -> Optional[SchedulingDelta]:
        task_node = self.cm.graph.node(task_node_id)
        assert task_node is not None and task_node.is_task_node, f"non-task node {task_node_id}"
        res_node = self.cm.graph.node(res_node_id)
        assert res_node is not None and res_node.type == NodeType.PU, f"non-PU node {res_node_id}"
        task = task_node.task
        rd = res_node.resource_descriptor
        bound = task_bindings.get(task.uid)
        if bound is None:
            return SchedulingDelta(DeltaType.PLACE, task.uid, rd.uuid)
        if bound != resource_id_from_string(rd.uuid):
            return SchedulingDelta(DeltaType.MIGRATE, task.uid, rd.uuid)
        # Already scheduled here; repopulate the running-task list that
        # SchedulingDeltasForPreemptedTasks cleared.
        rd.current_running_tasks.append(task.uid)
        return None

    def scheduling_deltas_for_preempted_tasks(
        self, task_mapping: TaskMapping, resource_map: ResourceMap
    ) -> List[SchedulingDelta]:
        deltas: List[SchedulingDelta] = []
        for rs in resource_map.unsafe_get().values():
            rd = rs.descriptor
            for task_id in rd.current_running_tasks:
                task_node = self.task_to_node.get(task_id)
                if task_node is None:
                    continue  # task finished; no PREEMPT needed
                if task_node.id not in task_mapping:
                    deltas.append(SchedulingDelta(DeltaType.PREEMPT, task_id, rd.uuid))
            # Cleared wholesale; NodeBindingToSchedulingDelta repopulates
            # (reference: graph_manager.go:327-337).
            rd.current_running_tasks = []
        return deltas

    # ------------------------------------------------------------------
    # Private: node add/remove helpers
    # ------------------------------------------------------------------

    def _add_equiv_class_node(self, ec: int) -> Node:
        node = self.cm.add_node(NodeType.EQUIV_CLASS, 0, ChangeType.ADD_EQUIV_CLASS_NODE, f"EC_{ec}")
        node.equiv_class = ec
        assert ec not in self.task_ec_to_node
        self.task_ec_to_node[ec] = node
        return node

    def _add_resource_node(self, rd: ResourceDescriptor) -> Node:
        comment = rd.friendly_name or "AddResourceNode"
        node = self.cm.add_node(resource_node_type(rd), 0, ChangeType.ADD_RESOURCE_NODE, comment)
        rid = resource_id_from_string(rd.uuid)
        node.resource_id = rid
        node.resource_descriptor = rd
        assert rid not in self.resource_to_node
        self.resource_to_node[rid] = node
        if node.type == NodeType.PU:
            self.leaf_node_ids.add(node.id)
            self.leaf_resource_ids.add(rid)
        return node

    def _add_task_node(self, job_id: int, td: TaskDescriptor) -> Node:
        self.cost_model.add_task(td.uid)
        node = self.cm.add_node(NodeType.UNSCHEDULED_TASK, 1, ChangeType.ADD_TASK_NODE, td.name or "AddTaskNode")
        node.task = td
        node.job_id = job_id
        self.sink_node.excess -= 1
        assert td.uid not in self.task_to_node
        self.task_to_node[td.uid] = node
        return node

    def _add_unscheduled_agg_node(self, job_id: int) -> Node:
        node = self.cm.add_node(
            NodeType.JOB_AGGREGATOR, 0, ChangeType.ADD_UNSCHED_JOB_NODE, f"UNSCHED_AGG_for_{job_id}"
        )
        node.job_id = job_id
        assert job_id not in self.job_unsched_to_node
        self.job_unsched_to_node[job_id] = node
        return node

    def _remove_equiv_class_node(self, node: Node) -> None:
        del self.task_ec_to_node[node.equiv_class]
        self.cm.delete_node(node, ChangeType.DEL_EQUIV_CLASS_NODE, "RemoveEquivClassNode")

    def _remove_resource_node(self, node: Node) -> None:
        self.node_to_parent_node.pop(node.id, None)
        self.leaf_node_ids.discard(node.id)
        self.leaf_resource_ids.discard(node.resource_id)
        self.resource_to_node.pop(node.resource_id, None)
        self.cm.delete_node(node, ChangeType.DEL_RESOURCE_NODE, "RemoveResourceNode")

    def _remove_task_node(self, node: Node) -> int:
        node_id = node.id
        node.excess = 0
        self.sink_node.excess += 1
        del self.task_to_node[node.task.uid]
        self.cm.delete_node(node, ChangeType.DEL_TASK_NODE, "RemoveTaskNode")
        return node_id

    # ------------------------------------------------------------------
    # Private: resource topology
    # ------------------------------------------------------------------

    def _capacity_to_parent(self, rd: ResourceDescriptor) -> int:
        """Reference: graph_manager.go:662-667 — slots below, minus running
        tasks below when preemption is off (a running task's slot must not
        be handed out again if it cannot be preempted)."""
        if self.preemption:
            return rd.num_slots_below
        return rd.num_slots_below - rd.num_running_tasks_below

    def _add_resource_topology_dfs(self, rtnd: ResourceTopologyNodeDescriptor) -> None:
        """Reference: graph_manager.go:557-630."""
        rd = rtnd.resource_desc
        rid = resource_id_from_string(rd.uuid)
        node = self.resource_to_node.get(rid)
        added_new = False
        if node is None:
            added_new = True
            node = self._add_resource_node(rd)
            if node.type == NodeType.PU:
                self._update_res_to_sink_arc(node)
                if rd.num_slots_below == 0:
                    rd.num_slots_below = self.max_tasks_per_pu
                    if rd.num_running_tasks_below == 0:
                        rd.num_running_tasks_below = len(rd.current_running_tasks)
            else:
                if node.type == NodeType.MACHINE:
                    self.cost_model.add_machine(rtnd)
                rd.num_slots_below = 0
                rd.num_running_tasks_below = 0
        else:
            rd.num_slots_below = 0
            rd.num_running_tasks_below = 0

        for child in rtnd.children:
            self._add_resource_topology_dfs(child)
            rd.num_slots_below += child.resource_desc.num_slots_below
            rd.num_running_tasks_below += child.resource_desc.num_running_tasks_below

        if not rtnd.parent_id:
            if rd.type != ResourceType.COORDINATOR:
                raise ValueError("a non-coordinator resource must have a parent")
            return
        if added_new:
            parent = self.resource_to_node[resource_id_from_string(rtnd.parent_id)]
            assert node.id not in self.node_to_parent_node
            self.node_to_parent_node[node.id] = parent
            self.cm.add_arc(
                parent,
                node,
                0,
                self._capacity_to_parent(rd),
                self.cost_model.resource_node_to_resource_node_cost(parent.resource_descriptor, rd),
                ArcType.OTHER,
                ChangeType.ADD_ARC_BETWEEN_RES,
                "AddResourceTopologyDFS",
            )

    def _update_resource_topology_dfs(self, rtnd: ResourceTopologyNodeDescriptor) -> None:
        """Reference: graph_manager.go:1063-1092."""
        rd = rtnd.resource_desc
        rd.num_slots_below = 0
        rd.num_running_tasks_below = 0
        if rd.type == ResourceType.PU:
            rd.num_slots_below = self.max_tasks_per_pu
            rd.num_running_tasks_below = len(rd.current_running_tasks)
        for child in rtnd.children:
            self._update_resource_topology_dfs(child)
            rd.num_slots_below += child.resource_desc.num_slots_below
            rd.num_running_tasks_below += child.resource_desc.num_running_tasks_below
        if rtnd.parent_id:
            curr = self.resource_to_node[resource_id_from_string(rd.uuid)]
            parent = self.node_to_parent_node[curr.id]
            parent_arc = self.cm.graph.get_arc(parent, curr)
            self.cm.change_arc_capacity(
                parent_arc, self._capacity_to_parent(rd), ChangeType.CHG_ARC_BETWEEN_RES, "UpdateResourceTopologyDFS"
            )

    def _update_resource_stats_up_to_root(
        self, curr: Node, cap_delta: int, slots_delta: int, running_delta: int
    ) -> None:
        """Reference: graph_manager.go:1041-1061."""
        while True:
            parent = self.node_to_parent_node.get(curr.id)
            if parent is None:
                return
            parent_arc = self.cm.graph.get_arc(parent, curr)
            assert parent_arc is not None, f"missing arc {parent.id}->{curr.id}"
            self.cm.change_arc_capacity(
                parent_arc, parent_arc.cap_upper + cap_delta, ChangeType.CHG_ARC_BETWEEN_RES, "UpdateCapacityUpToRoot"
            )
            prd = parent.resource_descriptor
            prd.num_slots_below += slots_delta
            prd.num_running_tasks_below += running_delta
            curr = parent

    def _traverse_and_remove_topology(self, node: Node) -> List[int]:
        """Reference: graph_manager.go:829-844."""
        removed: List[int] = []
        for arc in list(node.outgoing.values()):
            if arc.dst_node.resource_id != 0:
                removed.extend(self._traverse_and_remove_topology(arc.dst_node))
        if node.type == NodeType.PU:
            removed.append(node.id)
        elif node.type == NodeType.MACHINE:
            self.cost_model.remove_machine(node.resource_id)
        self._remove_resource_node(node)
        return removed

    # ------------------------------------------------------------------
    # Private: worklist update (the per-round hot path)
    # ------------------------------------------------------------------

    def _update_flow_graph(
        self, node_queue: Deque[Tuple[Optional[Node], TaskDescriptor]], marked: Set[int]
    ) -> None:
        """Reference: graph_manager.go:1012-1033."""
        while node_queue:
            node, task = node_queue.popleft()
            if node is None:
                self._update_children_tasks(task, node_queue, marked)
            elif node.is_task_node:
                self._update_task_node(node, node_queue, marked)
                self._update_children_tasks(task, node_queue, marked)
            elif node.is_equiv_class_node:
                self._update_equiv_class_node(node, node_queue, marked)
            elif node.is_resource_node:
                self._update_res_outgoing_arcs(node, node_queue, marked)
            else:
                raise ValueError(f"unexpected node type in worklist: {node.type}")

    def _update_children_tasks(
        self, td: TaskDescriptor, node_queue: Deque, marked: Set[int]
    ) -> None:
        """Reference: graph_manager.go:895-929."""
        for child in td.spawned:
            child_node = self.task_to_node.get(child.uid)
            if child_node is not None:
                if child_node.id not in marked:
                    node_queue.append((child_node, child))
                    marked.add(child_node.id)
                continue
            if not task_needs_node(child):
                node_queue.append((None, child))
                continue
            jid = job_id_from_string(child.job_id)
            child_node = self._add_task_node(jid, child)
            self._update_unscheduled_agg_node(self.job_unsched_to_node[jid], 1)
            node_queue.append((child_node, child))
            marked.add(child_node.id)

    def _update_task_node(self, task_node: Node, node_queue: Deque, marked: Set[int]) -> None:
        """Reference: graph_manager.go:1183-1192."""
        if task_node.is_task_assigned_or_running:
            self._update_running_task_node(
                task_node, self.update_preferences_running_task, node_queue, marked
            )
            return
        self._update_task_to_unscheduled_agg_arc(task_node)
        self._update_task_to_equiv_arcs(task_node, node_queue, marked)
        self._update_task_to_res_arcs(task_node, node_queue, marked)

    def _update_equiv_class_node(self, ec_node: Node, node_queue: Deque, marked: Set[int]) -> None:
        self._update_equiv_to_equiv_arcs(ec_node, node_queue, marked)
        self._update_equiv_to_res_arcs(ec_node, node_queue, marked)

    def _update_equiv_to_equiv_arcs(self, ec_node: Node, node_queue: Deque, marked: Set[int]) -> None:
        """Reference: graph_manager.go:939-970."""
        pref_ecs = self.cost_model.get_equiv_class_to_equiv_classes_arcs(ec_node.equiv_class)
        if not pref_ecs:
            self._remove_invalid_ec_pref_arcs(ec_node, pref_ecs, ChangeType.DEL_ARC_BETWEEN_EQUIV_CLASS)
            return
        for pref_ec in pref_ecs:
            pref_node = self.task_ec_to_node.get(pref_ec)
            if pref_node is None:
                pref_node = self._add_equiv_class_node(pref_ec)
            cost, cap_upper = self.cost_model.equiv_class_to_equiv_class(ec_node.equiv_class, pref_ec)
            arc = self.cm.graph.get_arc(ec_node, pref_node)
            if arc is None:
                self.cm.add_arc(
                    ec_node, pref_node, 0, cap_upper, cost, ArcType.OTHER,
                    ChangeType.ADD_ARC_BETWEEN_EQUIV_CLASS, "UpdateEquivClassNode",
                )
            else:
                self.cm.change_arc(
                    arc, arc.cap_lower, cap_upper, cost,
                    ChangeType.CHG_ARC_BETWEEN_EQUIV_CLASS, "UpdateEquivClassNode",
                )
            if pref_node.id not in marked:
                marked.add(pref_node.id)
                node_queue.append((pref_node, pref_node.task))
        self._remove_invalid_ec_pref_arcs(ec_node, pref_ecs, ChangeType.DEL_ARC_BETWEEN_EQUIV_CLASS)

    def _update_equiv_to_res_arcs(self, ec_node: Node, node_queue: Deque, marked: Set[int]) -> None:
        """Reference: graph_manager.go:974-1010, vectorized through the
        batch cost-model hook so wide fan-outs (EC → every machine) cost
        one call."""
        pref_rids = self.cost_model.get_outgoing_equiv_class_pref_arcs(ec_node.equiv_class)
        if not pref_rids:
            self._remove_invalid_pref_res_arcs(ec_node, pref_rids, ChangeType.DEL_ARC_EQUIV_CLASS_TO_RES)
            return
        costs, caps = self.cost_model.ec_to_resource_batch(ec_node.equiv_class, pref_rids)
        for pref_rid, cost, cap_upper in zip(pref_rids, costs, caps):
            pref_node = self.resource_to_node.get(pref_rid)
            assert pref_node is not None, "cost model preferred an unknown resource"
            arc = self.cm.graph.get_arc(ec_node, pref_node)
            if arc is None:
                self.cm.add_arc(
                    ec_node, pref_node, 0, cap_upper, cost, ArcType.OTHER,
                    ChangeType.ADD_ARC_EQUIV_CLASS_TO_RES, "UpdateEquivToResArcs",
                )
            else:
                self.cm.change_arc(
                    arc, arc.cap_lower, cap_upper, cost,
                    ChangeType.CHG_ARC_EQUIV_CLASS_TO_RES, "UpdateEquivToResArcs",
                )
            if pref_node.id not in marked:
                marked.add(pref_node.id)
                node_queue.append((pref_node, pref_node.task))
        self._remove_invalid_pref_res_arcs(ec_node, pref_rids, ChangeType.DEL_ARC_EQUIV_CLASS_TO_RES)

    def _update_res_outgoing_arcs(self, res_node: Node, node_queue: Deque, marked: Set[int]) -> None:
        """Reference: graph_manager.go:1094-1111."""
        for arc in list(res_node.outgoing.values()):
            if arc.dst_node.resource_id == 0:
                self._update_res_to_sink_arc(res_node)
                continue
            cost = self.cost_model.resource_node_to_resource_node_cost(
                res_node.resource_descriptor, arc.dst_node.resource_descriptor
            )
            self.cm.change_arc_cost(arc, cost, ChangeType.CHG_ARC_BETWEEN_RES, "UpdateResOutgoingArcs")
            if arc.dst_node.id not in marked:
                marked.add(arc.dst_node.id)
                node_queue.append((arc.dst_node, arc.dst_node.task))

    def _update_res_to_sink_arc(self, res_node: Node) -> None:
        """Reference: graph_manager.go:1116-1129."""
        if res_node.type != NodeType.PU:
            raise ValueError("only PU nodes connect to the sink")
        arc = self.cm.graph.get_arc(res_node, self.sink_node)
        cost = self.cost_model.leaf_resource_node_to_sink_cost(res_node.resource_id)
        if arc is None:
            self.cm.add_arc(
                res_node, self.sink_node, 0, self.max_tasks_per_pu, cost, ArcType.OTHER,
                ChangeType.ADD_ARC_RES_TO_SINK, "UpdateResToSinkArc",
            )
        else:
            self.cm.change_arc_cost(arc, cost, ChangeType.CHG_ARC_RES_TO_SINK, "UpdateResToSinkArc")

    # -- task arcs ---------------------------------------------------------

    def _update_running_task_node(
        self,
        task_node: Node,
        update_preferences: bool,
        node_queue: Optional[Deque],
        marked: Optional[Set[int]],
    ) -> None:
        """Reference: graph_manager.go:1140-1158."""
        task_id = task_node.task.uid
        running_arc = self.task_to_running_arc.get(task_id)
        assert running_arc is not None, f"no running arc for task {task_id}"
        new_cost = self.cost_model.task_continuation_cost(task_id)
        self.cm.change_arc_cost(
            running_arc, new_cost, ChangeType.CHG_ARC_RUNNING_TASK, "UpdateRunningTaskNode: continuation cost"
        )
        if not self.preemption:
            return
        self._update_running_task_to_unscheduled_agg_arc(task_node)
        if update_preferences:
            self._update_task_to_res_arcs(task_node, node_queue, marked)
            self._update_task_to_equiv_arcs(task_node, node_queue, marked)

    def _update_running_task_to_unscheduled_agg_arc(self, task_node: Node) -> None:
        """Reference: graph_manager.go:1164-1181 (preemption-only)."""
        assert self.preemption, "running task has no unsched arc without preemption"
        unsched = self.job_unsched_to_node[task_node.job_id]
        arc = self.cm.graph.get_arc(task_node, unsched)
        assert arc is not None, "running task must keep its unsched arc under preemption"
        cost = self.cost_model.task_preemption_cost(task_node.task.uid)
        self.cm.change_arc_cost(arc, cost, ChangeType.CHG_ARC_TO_UNSCHED, "UpdateRunningTaskToUnscheduledAggArc")

    def _update_task_to_equiv_arcs(self, task_node: Node, node_queue: Deque, marked: Set[int]) -> None:
        """Reference: graph_manager.go:1197-1226."""
        pref_ecs = self.cost_model.get_task_equiv_classes(task_node.task.uid)
        if not pref_ecs:
            self._remove_invalid_ec_pref_arcs(task_node, pref_ecs, ChangeType.DEL_ARC_TASK_TO_EQUIV_CLASS)
            return
        for pref_ec in pref_ecs:
            pref_node = self.task_ec_to_node.get(pref_ec)
            if pref_node is None:
                pref_node = self._add_equiv_class_node(pref_ec)
            cost = self.cost_model.task_to_equiv_class_aggregator(task_node.task.uid, pref_ec)
            arc = self.cm.graph.get_arc(task_node, pref_node)
            if arc is None:
                self.cm.add_arc(
                    task_node, pref_node, 0, 1, cost, ArcType.OTHER,
                    ChangeType.ADD_ARC_TASK_TO_EQUIV_CLASS, "UpdateTaskToEquivArcs",
                )
            else:
                self.cm.change_arc(
                    arc, arc.cap_lower, arc.cap_upper, cost,
                    ChangeType.CHG_ARC_TASK_TO_EQUIV_CLASS, "UpdateTaskToEquivArcs",
                )
            if pref_node.id not in marked:
                marked.add(pref_node.id)
                node_queue.append((pref_node, pref_node.task))
        self._remove_invalid_ec_pref_arcs(task_node, pref_ecs, ChangeType.DEL_ARC_TASK_TO_EQUIV_CLASS)

    def _update_task_to_res_arcs(self, task_node: Node, node_queue: Deque, marked: Set[int]) -> None:
        """Reference: graph_manager.go:1229-1264."""
        pref_rids = self.cost_model.get_task_preference_arcs(task_node.task.uid)
        if not pref_rids:
            self._remove_invalid_pref_res_arcs(task_node, pref_rids, ChangeType.DEL_ARC_TASK_TO_RES)
            return
        for pref_rid in pref_rids:
            pref_node = self.resource_to_node.get(pref_rid)
            assert pref_node is not None, "cost model preferred an unknown resource"
            cost = self.cost_model.task_to_resource_node_cost(task_node.task.uid, pref_rid)
            arc = self.cm.graph.get_arc(task_node, pref_node)
            if arc is None:
                self.cm.add_arc(
                    task_node, pref_node, 0, 1, cost, ArcType.OTHER,
                    ChangeType.ADD_ARC_TASK_TO_RES, "UpdateTaskToResArcs",
                )
            elif arc.type != ArcType.RUNNING:
                # Running arcs are priced by TaskContinuationCost elsewhere.
                self.cm.change_arc_cost(arc, cost, ChangeType.CHG_ARC_TASK_TO_RES, "UpdateTaskToResArcs")
            if pref_node.id not in marked:
                marked.add(pref_node.id)
                node_queue.append((pref_node, pref_node.task))
        self._remove_invalid_pref_res_arcs(task_node, pref_rids, ChangeType.DEL_ARC_TASK_TO_RES)

    def _update_task_to_unscheduled_agg_arc(self, task_node: Node) -> Node:
        """Reference: graph_manager.go:1270-1285."""
        unsched = self.job_unsched_to_node.get(task_node.job_id)
        if unsched is None:
            unsched = self._add_unscheduled_agg_node(task_node.job_id)
        cost = self.cost_model.task_to_unscheduled_agg_cost(task_node.task.uid)
        arc = self.cm.graph.get_arc(task_node, unsched)
        if arc is None:
            self.cm.add_arc(
                task_node, unsched, 0, 1, cost, ArcType.OTHER,
                ChangeType.ADD_ARC_TO_UNSCHED, "UpdateTaskToUnscheduledAggArc",
            )
        else:
            self.cm.change_arc_cost(arc, cost, ChangeType.CHG_ARC_TO_UNSCHED, "UpdateTaskToUnscheduledAggArc")
        return unsched

    def _update_unscheduled_agg_node(self, unsched: Node, cap_delta: int) -> None:
        """Reference: graph_manager.go:1291-1305."""
        arc = self.cm.graph.get_arc(unsched, self.sink_node)
        cost = self.cost_model.unscheduled_agg_to_sink_cost(unsched.job_id)
        if arc is not None:
            self.cm.change_arc(
                arc, arc.cap_lower, arc.cap_upper + cap_delta, cost,
                ChangeType.CHG_ARC_FROM_UNSCHED, "UpdateUnscheduledAggNode",
            )
            return
        assert cap_delta >= 1, f"first capacity delta must be >=1, got {cap_delta}"
        self.cm.add_arc(
            unsched, self.sink_node, 0, cap_delta, cost, ArcType.OTHER,
            ChangeType.ADD_ARC_FROM_UNSCHED, "UpdateUnscheduledAggNode",
        )

    # -- preference pruning ------------------------------------------------

    def _remove_invalid_ec_pref_arcs(self, node: Node, pref_ecs: List[int], change_type: ChangeType) -> None:
        """Reference: graph_manager.go:732-760."""
        pref = set(pref_ecs)
        to_delete = [
            arc
            for arc in node.outgoing.values()
            if arc.dst_node.equiv_class is not None and arc.dst_node.equiv_class not in pref
        ]
        for arc in to_delete:
            self.cm.delete_arc(arc, change_type, "RemoveInvalidECPrefArcs")

    def _remove_invalid_pref_res_arcs(self, node: Node, pref_rids: List[int], change_type: ChangeType) -> None:
        """Reference: graph_manager.go:766-790 — prunes arcs to resources
        no longer preferred, skipping running arcs is NOT done there; the
        running arc always points at the bound resource which the cost
        model keeps in its preference lists when relevant."""
        pref = set(pref_rids)
        to_delete = [
            arc
            for arc in node.outgoing.values()
            if arc.dst_node.resource_id != 0 and arc.dst_node.resource_id not in pref
        ]
        for arc in to_delete:
            self.cm.delete_arc(arc, change_type, "RemoveInvalidPrefResArcs")

    # -- scheduled-task arc handling ---------------------------------------

    def _update_arcs_for_scheduled_task(self, task_node: Node, res_node: Node) -> None:
        """Reference: graph_manager.go:855-888."""
        if not self.preemption:
            self._pin_task_to_node(task_node, res_node)
            return
        task_id = task_node.task.uid
        new_cost = self.cost_model.task_continuation_cost(task_id)
        running_arc = self.task_to_running_arc.get(task_id)
        if running_arc is None:
            # A preference arc to the chosen resource doubles as the
            # running arc (the graph doesn't support multi-arcs; reference
            # note at graph_manager.go:869-872).
            running_arc = self.cm.graph.get_arc(task_node, res_node)
        if running_arc is not None:
            running_arc.type = ArcType.RUNNING
            self.cm.change_arc(running_arc, 0, 1, new_cost, ChangeType.CHG_ARC_RUNNING_TASK,
                               "UpdateArcsForScheduledTask: transform to running arc")
            self.task_to_running_arc[task_id] = running_arc
            self._update_running_task_to_unscheduled_agg_arc(task_node)
            return
        running_arc = self.cm.add_arc(
            task_node, res_node, 0, 1, new_cost, ArcType.RUNNING,
            ChangeType.ADD_ARC_RUNNING_TASK, "UpdateArcsForScheduledTask: add running arc",
        )
        assert task_id not in self.task_to_running_arc
        self.task_to_running_arc[task_id] = running_arc
        self._update_running_task_to_unscheduled_agg_arc(task_node)

    def _pin_task_to_node(self, task_node: Node, res_node: Node) -> None:
        """Preemption-off path: delete all non-chosen arcs, keep/create one
        running arc with lower bound 1 (reference: graph_manager.go:675-720)."""
        added_running_arc = False
        task_id = task_node.task.uid
        for arc in list(task_node.outgoing.values()):
            if arc.dst != res_node.id:
                self.cm.delete_arc(arc, ChangeType.DEL_ARC_TASK_TO_EQUIV_CLASS, "PinTaskToNode")
                continue
            added_running_arc = True
            new_cost = self.cost_model.task_continuation_cost(task_id)
            arc.type = ArcType.RUNNING
            self.cm.change_arc(arc, 1, 1, new_cost, ChangeType.CHG_ARC_RUNNING_TASK,
                               "PinTaskToNode: transform to running arc")
            assert task_id not in self.task_to_running_arc
            self.task_to_running_arc[task_id] = arc
        self._update_unscheduled_agg_node(self.job_unsched_to_node[task_node.job_id], -1)
        if not added_running_arc:
            new_cost = self.cost_model.task_continuation_cost(task_id)
            arc = self.cm.add_arc(
                task_node, res_node, 1, 1, new_cost, ArcType.RUNNING,
                ChangeType.ADD_ARC_RUNNING_TASK, "PinTaskToNode: add running arc",
            )
            assert task_id not in self.task_to_running_arc
            self.task_to_running_arc[task_id] = arc
