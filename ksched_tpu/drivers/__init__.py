from .synthetic import (
    add_job,
    add_machine,
    add_task_to_job,
    build_cluster,
    build_machine_topology,
    make_coordinator_root,
    make_resource_desc,
)

__all__ = [
    "add_job",
    "add_machine",
    "add_task_to_job",
    "build_cluster",
    "build_machine_topology",
    "make_coordinator_root",
    "make_resource_desc",
]
