"""Google 2011 cluster-trace replay driver.

The reference carries trace-replay identity fields precisely so the
Google trace can be replayed through the scheduler
(TaskDescriptor.trace_job_id/trace_task_id, proto/task_desc.proto:76-78;
ResourceDescriptor.trace_machine_id, resource_desc.proto:62-63) but
ships no replay driver. This is that driver, built over the bulk array
path so the 12.5k-machine trace scale (BASELINE config 5) solves in
device arrays with incremental warm-started re-solves.

Input format: the public clusterdata-2011 schema —
  machine_events: timestamp_us, machine_id, event_type(0 ADD/1 REMOVE/
                  2 UPDATE), platform_id, cpus, memory
  task_events:    timestamp_us, missing_info, job_id, task_index,
                  machine_id, event_type(0 SUBMIT/1 SCHEDULE/2 EVICT/
                  3 FAIL/4 FINISH/5 KILL/6 LOST/7-8 UPDATE), user,
                  scheduling_class, priority, cpu_req, ram_req,
                  disk_req, different_machine_constraint
CSV (optionally .gz), as published. Because the image has no network
access, `synthesize_trace` fabricates streams with the same schema and
realistic arrival/finish dynamics for benchmarks and tests.

Replay protocol: events are consumed in timestamp order and batched
into fixed simulated-time windows (the trace analogue of the
reference's 2s pod-batch debounce, k8sclient/client.go:153-193); each
window ends with one scheduling round; FINISH/KILL/EVICT free slots.
"""

from __future__ import annotations

import csv
import gzip
import io
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

import numpy as np

# task_events event_type values (clusterdata-2011 schema)
SUBMIT, SCHEDULE, EVICT, FAIL, FINISH, KILL, LOST = 0, 1, 2, 3, 4, 5, 6
MACHINE_ADD, MACHINE_REMOVE, MACHINE_UPDATE = 0, 1, 2


@dataclass(frozen=True)
class TraceTaskEvent:
    time_us: int
    job_id: int
    task_index: int
    event_type: int
    scheduling_class: int = 0
    priority: int = 0
    cpu_req: float = 0.0


@dataclass(frozen=True)
class TraceMachineEvent:
    time_us: int
    machine_id: int
    event_type: int
    cpus: float = 1.0


def _open_maybe_gz(path: str):
    if path.endswith(".gz"):
        return io.TextIOWrapper(gzip.open(path, "rb"))
    return open(path, "r")


def parse_task_events(path: str) -> Iterator[TraceTaskEvent]:
    """Stream task events from a clusterdata-2011 task_events CSV."""
    with _open_maybe_gz(path) as f:
        for row in csv.reader(f):
            if not row:
                continue
            yield TraceTaskEvent(
                time_us=int(row[0]),
                job_id=int(row[2]),
                task_index=int(row[3]),
                event_type=int(row[5]),
                scheduling_class=int(row[7]) if len(row) > 7 and row[7] else 0,
                priority=int(row[8]) if len(row) > 8 and row[8] else 0,
                cpu_req=float(row[9]) if len(row) > 9 and row[9] else 0.0,
            )


def parse_machine_events(path: str) -> Iterator[TraceMachineEvent]:
    """Stream machine events from a clusterdata-2011 machine_events CSV."""
    with _open_maybe_gz(path) as f:
        for row in csv.reader(f):
            if not row:
                continue
            yield TraceMachineEvent(
                time_us=int(row[0]),
                machine_id=int(row[1]),
                event_type=int(row[2]),
                cpus=float(row[4]) if len(row) > 4 and row[4] else 1.0,
            )


def synthesize_trace(
    num_machines: int,
    num_tasks: int,
    duration_s: float = 600.0,
    mean_runtime_s: float = 120.0,
    seed: int = 0,
    machine_churn: float = 0.0,
    outage_s: float = 60.0,
    burst_spike: float = 0.0,
    burst_count: int = 0,
    burst_s: float = 30.0,
    correlated_outages: int = 0,
    outage_block: int = 0,
) -> Tuple[List[TraceMachineEvent], List[TraceTaskEvent]]:
    """Fabricate machine/task event streams in the clusterdata-2011
    schema: machines ADD at t=0, Poisson task arrivals, exponential
    runtimes emitting SUBMIT then FINISH. A `machine_churn` fraction of
    machines additionally suffers a mid-trace outage (REMOVE, then ADD
    ~outage_s later — the real trace's dominant machine-event pattern),
    so replay exercises eviction + rescheduling, not just placement.
    Defaults to 0 so seeded streams stay reproducible for existing
    callers; opt in explicitly (the churn draws precede the arrival
    draws, so enabling it changes the whole stream for a seed).

    BURST statistics (VERDICT r3 #5 — the real trace's arrival spikes,
    which steady Poisson streams never produce): `burst_count` windows
    of `burst_s` seconds carry arrival intensity `burst_spike`x the
    base rate (spikes >= 5x mean are the regime of interest); the total
    task count stays `num_tasks`, redistributed between burst and base
    time. `correlated_outages` additionally drops `outage_block`
    machines SIMULTANEOUSLY (a rack/power-domain failure, vs
    machine_churn's independent outages), each block restored after
    ~outage_s."""
    rng = np.random.default_rng(seed)
    machines = [
        TraceMachineEvent(time_us=0, machine_id=m + 1, event_type=MACHINE_ADD)
        for m in range(num_machines)
    ]
    n_churn = int(num_machines * machine_churn)
    if n_churn:
        down = rng.choice(num_machines, n_churn, replace=False)
        downtimes = rng.uniform(0.1 * duration_s, 0.8 * duration_s, n_churn)
        for m, t_down in zip(down, downtimes):
            t0 = int(t_down * 1e6)
            machines.append(
                TraceMachineEvent(time_us=t0, machine_id=int(m) + 1,
                                  event_type=MACHINE_REMOVE)
            )
            back = t0 + int(rng.exponential(outage_s) * 1e6)
            if back < duration_s * 1e6:
                machines.append(
                    TraceMachineEvent(time_us=back, machine_id=int(m) + 1,
                                      event_type=MACHINE_ADD)
                )
        machines.sort(key=lambda e: e.time_us)
    if correlated_outages and outage_block:
        for _ in range(correlated_outages):
            t0 = int(rng.uniform(0.15 * duration_s, 0.85 * duration_s) * 1e6)
            block = rng.choice(num_machines, outage_block, replace=False)
            back = t0 + int(rng.exponential(outage_s) * 1e6)
            for m in block:
                machines.append(
                    TraceMachineEvent(time_us=t0, machine_id=int(m) + 1,
                                      event_type=MACHINE_REMOVE)
                )
                if back < duration_s * 1e6:
                    machines.append(
                        TraceMachineEvent(time_us=back, machine_id=int(m) + 1,
                                          event_type=MACHINE_ADD)
                    )
        machines.sort(key=lambda e: e.time_us)
    if burst_spike > 0 and burst_count > 0:
        # piecewise-constant intensity: burst windows at spike x base
        starts = np.sort(
            rng.uniform(0, duration_s - burst_s, burst_count)
        )
        f = burst_count * burst_s / duration_s
        share = burst_spike * f / (burst_spike * f + max(1e-9, 1.0 - f))
        n_burst = int(num_tasks * share)
        base = rng.uniform(0, duration_s * 1e6, num_tasks - n_burst)
        which = rng.integers(0, burst_count, n_burst)
        inside = rng.uniform(0, burst_s * 1e6, n_burst)
        burst_t = starts[which] * 1e6 + inside
        arrivals = np.sort(
            np.concatenate([base, burst_t])
        ).astype(np.int64)  # kschedlint: host-only (synthetic trace gen, host-side)
    else:
        arrivals = np.sort(
            rng.uniform(0, duration_s * 1e6, num_tasks)
        ).astype(np.int64)  # kschedlint: host-only (synthetic trace gen, host-side)
    runtimes = (rng.exponential(mean_runtime_s, num_tasks) * 1e6).astype(np.int64)  # kschedlint: host-only (synthetic trace gen, host-side)
    jobs = rng.integers(1, max(2, num_tasks // 50), num_tasks)
    events: List[TraceTaskEvent] = []
    for i in range(num_tasks):
        events.append(
            TraceTaskEvent(
                time_us=int(arrivals[i]),
                job_id=int(jobs[i]),
                task_index=i,
                event_type=SUBMIT,
                scheduling_class=int(rng.integers(0, 4)),
                cpu_req=float(rng.uniform(0.01, 0.5)),
            )
        )
        events.append(
            TraceTaskEvent(
                time_us=int(arrivals[i] + runtimes[i]),
                job_id=int(jobs[i]),
                task_index=i,
                event_type=FINISH,
                scheduling_class=0,
            )
        )
    events.sort(key=lambda e: e.time_us)
    return machines, events


def iter_windows(
    task_events: Iterable[TraceTaskEvent],
    window_s: float,
    machine_events_until=None,
    max_rounds: Optional[int] = None,
) -> Iterator[List[TraceTaskEvent]]:
    """Batch a timestamp-ordered task-event stream into scheduling
    windows (the trace analogue of the reference's 2s pod-batch
    debounce, k8sclient/client.go:153-193). Yields one STREAM-ORDERED
    event list per non-empty window — submits and finish-kind events
    interleaved as the trace carries them, so window_net_ops can
    replay each task's intra-window lifecycle exactly; calls
    `machine_events_until(t_us)` before each yield so the caller can
    drain machine events up to the window boundary. ONE definition
    shared by the host and device replay drivers so their windowing
    protocols cannot drift."""
    window_us = int(window_s * 1e6)
    pending: List[TraceTaskEvent] = []
    window_end = None
    rounds = 0
    for ev in task_events:
        if window_end is None:
            window_end = ev.time_us + window_us
            if machine_events_until is not None:
                machine_events_until(ev.time_us)
        while ev.time_us >= window_end:
            if pending:
                if machine_events_until is not None:
                    machine_events_until(window_end)
                yield pending
                pending = []
                rounds += 1
                if max_rounds is not None and rounds >= max_rounds:
                    return
            window_end += window_us
        if ev.event_type == SUBMIT or ev.event_type in (
            FINISH, KILL, FAIL, LOST, EVICT
        ):
            pending.append(ev)
    if pending:
        yield pending


def window_net_ops(events: List[TraceTaskEvent], is_live):
    """Collapse one window's events into their NET per-task effect by
    replaying each task's events in stream order against its
    window-start liveness (`is_live(key) -> bool`). Batching a window
    into one scheduling round loses intra-window interleaving; this
    automaton is the single place that semantics lives, shared by the
    host and device drivers so they cannot disagree (the round-4
    review found them diverging on duplicate-SUBMIT/FINISH
    interleavings).

    Per key, in order: a SUBMIT while live is the reference's
    duplicate-pod skip (cmd/k8sscheduler/scheduler.go:133-136); a
    finish-kind event while dead targets an unknown task and is
    dropped; otherwise submits open a row and finishes close one —
    the pre-existing row first, then in-window rows.

    Returns (retires, admits, pairs):
      retires: keys whose PRE-EXISTING row completes this window
      admits:  SUBMIT events whose new row survives the window
      pairs:   SUBMIT events admitted AND finished inside the window
               (a full lifecycle per entry — possibly several per key)
    """
    seq: Dict[Tuple[int, int], List[TraceTaskEvent]] = {}
    order: List[Tuple[int, int]] = []
    for ev in events:
        key = (ev.job_id, ev.task_index)
        if key not in seq:
            seq[key] = []
            order.append(key)
        seq[key].append(ev)
    retires: List[Tuple[int, int]] = []
    admits: List[TraceTaskEvent] = []
    pairs: List[TraceTaskEvent] = []
    for key in order:
        pre_live = bool(is_live(key))
        cur_live = pre_live
        pre_row_live = pre_live
        open_submit: Optional[TraceTaskEvent] = None
        for ev in seq[key]:
            if ev.event_type == SUBMIT:
                if cur_live:
                    continue  # duplicate-pod skip
                cur_live = True
                open_submit = ev
            else:
                if not cur_live:
                    continue  # finish for an unknown/dead task
                cur_live = False
                if pre_row_live:
                    retires.append(key)
                    pre_row_live = False
                else:
                    pairs.append(open_submit)
                open_submit = None
        if cur_live and open_submit is not None:
            admits.append(open_submit)
        # cur_live with no open submit: the pre-existing row survives
    return retires, admits, pairs


@dataclass
class ReplayStats:
    rounds: int = 0
    submitted: int = 0
    finished: int = 0
    placed: int = 0
    evicted: int = 0  # tasks displaced by machine REMOVE events
    round_latencies_s: List[float] = field(default_factory=list)

    @property
    def p50_ms(self) -> float:
        if not self.round_latencies_s:
            return 0.0
        return float(np.percentile(self.round_latencies_s, 50) * 1e3)


class TraceReplayDriver:
    """Replays a trace through the bulk array scheduler.

    The cluster's machine-index space covers every machine_id that ever
    appears; machines toggle in/out of service at their trace timestamps
    (ADD/REMOVE → BulkCluster.set_machine_enabled — the elastic
    membership path; a mid-trace REMOVE evicts its running tasks for
    rescheduling). Tasks flow SUBMIT → (round places) → FINISH/KILL.
    Window size is simulated time per scheduling round.
    """

    def __init__(
        self,
        machine_events: Iterable[TraceMachineEvent],
        backend=None,
        slots_per_machine: int = 8,
        num_jobs_hint: int = 64,
        task_capacity: int = 1 << 17,
    ) -> None:
        from ..scheduler.bulk import BulkCluster
        from ..solver.native import NativeSolver

        self._machine_events = sorted(machine_events, key=lambda e: e.time_us)
        self._machine_index: Dict[int, int] = {}
        for ev in self._machine_events:
            if ev.machine_id not in self._machine_index:
                self._machine_index[ev.machine_id] = len(self._machine_index)
        self.num_machines = len(self._machine_index)
        self.cluster = BulkCluster(
            num_machines=self.num_machines,
            pus_per_machine=1,
            slots_per_pu=slots_per_machine,
            num_jobs=num_jobs_hint,
            backend=backend or NativeSolver(),
            num_task_classes=4,  # the trace's scheduling_class domain
            task_capacity=task_capacity,
        )
        # Everything starts out of service; time-0 ADDs enable in replay.
        self.cluster.machine_enabled[:] = False
        self._machine_cursor = 0
        self.num_jobs = num_jobs_hint
        # (trace job_id, task_index) -> bulk task row id
        self._live_tasks: Dict[Tuple[int, int], int] = {}

    def _apply_machine_events_until(self, time_us: int, stats: "ReplayStats") -> None:
        while (
            self._machine_cursor < len(self._machine_events)
            and self._machine_events[self._machine_cursor].time_us <= time_us
        ):
            ev = self._machine_events[self._machine_cursor]
            self._machine_cursor += 1
            idx = self._machine_index[ev.machine_id]
            if ev.event_type == MACHINE_ADD:
                self.cluster.set_machine_enabled(idx, True)
            elif ev.event_type == MACHINE_REMOVE:
                evicted = self.cluster.set_machine_enabled(idx, False)
                stats.evicted += len(evicted)

    def replay(
        self,
        task_events: Iterable[TraceTaskEvent],
        window_s: float = 5.0,
        max_rounds: Optional[int] = None,
    ) -> ReplayStats:
        import time as _time

        stats = ReplayStats()

        def flush_window(events):
            t0 = _time.perf_counter()
            # Net per-task window effect from the shared automaton
            # (window_net_ops): pre-existing rows that complete, new
            # rows that survive, and full in-window lifecycles (pairs)
            # — the host path expresses a pair exactly: admit, then
            # complete before the round runs.
            retires, admits, pairs = window_net_ops(
                events, lambda k: k in self._live_tasks
            )
            done_rows = [self._live_tasks.pop(k) for k in retires]
            if done_rows:
                self.cluster.complete_tasks(np.asarray(done_rows, np.int32))
                stats.finished += len(done_rows)
            fresh = admits + pairs
            if fresh:
                jobs = np.asarray(
                    [ev.job_id % self.num_jobs for ev in fresh], np.int32
                )
                classes = np.asarray(
                    [ev.scheduling_class % 4 for ev in fresh], np.int32
                )
                abs_rows = self.cluster.add_tasks(len(fresh), jobs, classes)
                for ev, row in zip(admits, abs_rows[: len(admits)]):
                    self._live_tasks[(ev.job_id, ev.task_index)] = int(row)
                stats.submitted += len(fresh)
                pair_rows = np.asarray(abs_rows[len(admits):], np.int32)
                if len(pair_rows):
                    self.cluster.complete_tasks(pair_rows)
                    stats.finished += len(pair_rows)
            result = self.cluster.round()
            stats.round_latencies_s.append(_time.perf_counter() - t0)
            stats.placed += len(result.placed_tasks)
            stats.rounds += 1

        for events in iter_windows(
            task_events, window_s,
            machine_events_until=lambda t: self._apply_machine_events_until(
                t, stats
            ),
            max_rounds=max_rounds,
        ):
            flush_window(events)
        return stats


class DeviceTraceReplayDriver:
    """Trace replay on the DEVICE-resident path at full trace scale.

    The host TraceReplayDriver above round-trips device<->host every
    window (admit, solve, fetch, complete) — honest on JAX-CPU,
    unmeasurable over a tunneled TPU (docs/NOTES.md). This driver is
    the TPU-idiomatic form: `stage()` batches the whole event stream
    into fixed-width per-window arrays (admissions, completions,
    machine toggles) and `replay()` hands them to
    DeviceBulkCluster.run_replay_rounds, which scans all K rounds as
    ONE device program — the reference's event loop
    (cmd/k8sscheduler/scheduler.go:120-188) with the host round-trips
    compiled away.

    Row assignment is predicted by a HOST MIRROR of the live bitmap:
    the device admit fills the first `count` free rows in ascending
    row order (a deterministic rule), so the host can track
    (job_id, task_index) -> row without ever fetching device state.

    Policy (default): 4 task classes (the trace's scheduling_class
    domain) and per-job unscheduled costs (graph_manager.go:1291-1305)
    — the per-job row-constant shape, solved by the exact closed form.

    Policy (class_cost_fn given): the same 4-class admission stream
    priced by a census-dependent interference model (CoCo/Whare device
    twins, costmodels/device_costs.py) — rows are NOT machine-uniform,
    so every window runs the real iterative transport at full trace
    width [C, M]. This is the machine axis of the iterative solver at
    the reference's flagship 12.5k-machine scale (VERDICT r4 #1): the
    reference hands whatever graph the policy builds to Flowlessly
    (scheduling/flow/placement/solver.go:60-90); the closed-form
    default above never exercises that path."""

    def __init__(
        self,
        machine_events: Iterable[TraceMachineEvent],
        slots_per_machine: int = 8,
        num_jobs_hint: int = 64,
        task_capacity: int = 1 << 15,
        decode_width: int = 4096,
        class_cost_fn=None,
        unsched_cost: int = 5,
        supersteps: Optional[int] = None,
    ) -> None:
        import jax.numpy as jnp

        from ..scheduler.device_bulk import DeviceBulkCluster

        self._machine_events = sorted(machine_events, key=lambda e: e.time_us)
        self._machine_index: Dict[int, int] = {}
        for ev in self._machine_events:
            if ev.machine_id not in self._machine_index:
                self._machine_index[ev.machine_id] = len(self._machine_index)
        self.num_machines = len(self._machine_index)
        self.num_jobs = num_jobs_hint
        self.Tcap = int(task_capacity)
        if class_cost_fn is None:
            # distinct per-job escape costs (u_j > e = 0 so placement
            # always profits): the row-constant per-job shape
            job_u = 1 + (np.arange(num_jobs_hint, dtype=np.int64) % 8)  # kschedlint: host-only (synthetic trace gen, host-side)
            self.cluster = DeviceBulkCluster(
                num_machines=self.num_machines,
                pus_per_machine=1,
                slots_per_pu=slots_per_machine,
                num_jobs=num_jobs_hint,
                num_task_classes=4,
                task_capacity=self.Tcap,
                ec_cost=0,
                job_unsched_cost=job_u,
                decode_width=decode_width,
            )
            assert self.cluster.row_constant, (
                "trace policy must take the closed form"
            )
        else:
            # census-priced classes: G = C = 4 transport rows over the
            # full machine axis, solved iteratively every window
            self.cluster = DeviceBulkCluster(
                num_machines=self.num_machines,
                pus_per_machine=1,
                slots_per_pu=slots_per_machine,
                num_jobs=num_jobs_hint,
                num_task_classes=4,
                task_capacity=self.Tcap,
                ec_cost=0,
                unsched_cost=unsched_cost,
                class_cost_fn=class_cost_fn,
                supersteps=supersteps,
                decode_width=decode_width,
            )
            assert not self.cluster.row_constant and (
                not self.cluster.class_degenerate
            ), "class_cost_fn must force the iterative transport"
        # everything starts out of service; time-0 ADDs enable in stage()
        self.cluster.state = self.cluster.state._replace(
            machine_enabled=jnp.zeros(self.num_machines, jnp.bool_)
        )

    def stage(
        self,
        task_events: Iterable[TraceTaskEvent],
        window_s: float = 5.0,
        max_rounds: Optional[int] = None,
    ) -> dict:
        """Batch events into per-window arrays via the shared
        iter_windows protocol; returns the schedule dict
        run_replay_rounds takes, with staging metadata (rounds,
        submits, finishes, toggles).

        Window semantics come from the shared window_net_ops automaton
        (exact intra-window lifecycle replay, agreeing with the host
        driver by construction). The device round applies toggles ->
        completions -> admissions, so a PAIR (a task admitted AND
        finished inside one window) cannot complete in its own round
        — its row is admitted this round and carried to complete in
        the NEXT round, preserving the submit/finish counts the host
        driver reports."""
        live = np.zeros(self.Tcap, bool)  # host mirror of the live bitmap
        row_of: Dict[Tuple[int, int], int] = {}
        machine_cursor = 0

        windows: List[dict] = []
        pending_toggles: Dict[int, bool] = {}  # dedup keep-last per window
        carry_rows: List[int] = []  # pair rows retiring next window
        submitted = finished = dropped = 0

        def machine_events_until(t_us):
            nonlocal machine_cursor
            while (
                machine_cursor < len(self._machine_events)
                and self._machine_events[machine_cursor].time_us <= t_us
            ):
                ev = self._machine_events[machine_cursor]
                machine_cursor += 1
                idx = self._machine_index[ev.machine_id]
                if ev.event_type == MACHINE_ADD:
                    pending_toggles[idx] = True
                elif ev.event_type == MACHINE_REMOVE:
                    pending_toggles[idx] = False

        def flush_window(events):
            nonlocal carry_rows, pending_toggles
            nonlocal submitted, finished, dropped
            # Net per-task window effect (shared window_net_ops
            # automaton — identical semantics to the host driver).
            # Completions first in the mirror (matching the device
            # round's order): pre-existing retires + pair rows carried
            # from the previous window.
            retires, admits, pairs = window_net_ops(
                events, lambda k: k in row_of
            )
            done_rows = list(carry_rows)
            for key in retires:
                row = row_of.pop(key)
                done_rows.append(row)
            for row in done_rows:
                live[row] = False
            carry_rows = []
            finished += len(done_rows)
            # admissions: first n free rows, ascending — the admit
            # rule. Surviving admits first, then pair rows (admitted
            # now, completed next round via the carry), so capacity
            # pressure drops pairs before durable tasks.
            fresh = admits + pairs
            free = np.nonzero(~live)[0]
            n_adm = min(len(fresh), len(free))
            dropped += len(fresh) - n_adm
            rows = free[:n_adm]
            adm = []
            for i, (ev, row) in enumerate(zip(fresh[:n_adm], rows)):
                live[row] = True
                if i < len(admits):
                    row_of[(ev.job_id, ev.task_index)] = int(row)
                else:
                    # completes NEXT round via the carry (counted in
                    # `finished` when its done_rows entry lands)
                    carry_rows.append(int(row))
                adm.append(
                    (ev.job_id % self.num_jobs, ev.scheduling_class % 4)
                )
            submitted += n_adm
            windows.append(
                dict(
                    adm=adm,
                    done=done_rows,
                    toggles=sorted(pending_toggles.items()),
                )
            )
            pending_toggles = {}

        for events in iter_windows(
            task_events, window_s,
            machine_events_until=machine_events_until,
            max_rounds=max_rounds,
        ):
            flush_window(events)
        if carry_rows and (max_rounds is None or len(windows) < max_rounds):
            # trace ended with carried pair rows: one extra
            # completion-only window retires them
            flush_window([])
        if not windows:
            raise ValueError(
                "trace yielded no schedulable windows (no task events, "
                "or only finishes for unknown tasks)"
            )

        K = len(windows)
        Amax = max(1, max(len(w["adm"]) for w in windows))
        Dmax = max(1, max(len(w["done"]) for w in windows))
        Emax = max(1, max(len(w["toggles"]) for w in windows))
        sch = {
            "adm_job": np.zeros((K, Amax), np.int32),
            "adm_cls": np.zeros((K, Amax), np.int32),
            "adm_grp": np.zeros((K, Amax), np.int32),
            "adm_n": np.zeros(K, np.int32),
            "done_rows": np.full((K, Dmax), self.Tcap, np.int32),
            "done_n": np.zeros(K, np.int32),
            "tog_idx": np.zeros((K, Emax), np.int32),
            "tog_on": np.zeros((K, Emax), bool),
            "tog_n": np.zeros(K, np.int32),
            "rounds": K,
            "submitted": submitted,
            "finished": finished,
            "dropped": dropped,
        }
        for i, w in enumerate(windows):
            sch["adm_n"][i] = len(w["adm"])
            for j, (job, cls) in enumerate(w["adm"]):
                sch["adm_job"][i, j] = job
                sch["adm_cls"][i, j] = cls
            sch["done_n"][i] = len(w["done"])
            sch["done_rows"][i, : len(w["done"])] = w["done"]
            sch["tog_n"][i] = len(w["toggles"])
            for j, (idx, on) in enumerate(w["toggles"]):
                sch["tog_idx"][i, j] = idx
                sch["tog_on"][i, j] = on
        return sch

    def replay(self, schedule: dict, seed: int = 0):
        """Run a staged schedule; returns un-fetched stacked stats."""
        return self.cluster.run_replay_rounds(schedule, seed=seed)
