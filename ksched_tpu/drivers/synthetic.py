"""Synthetic cluster driver: fabricate machines, jobs, and tasks.

The fakeMachines analogue (reference: cmd/k8sscheduler/scheduler.go:
37-39,191-202,297-350) plus the in-memory fixture builders the
integration test uses (reference: flowscheduler/schedule_iteration_test.go:
152-331). Machines are built as machine → core* → PU* topologies,
registered into the resource map, and handed to the scheduler; jobs are a
root task plus spawned children under one JobDescriptor.
"""

from __future__ import annotations

from typing import List, Optional

from ..data import (
    JobDescriptor,
    JobState,
    ResourceDescriptor,
    ResourceState,
    ResourceTopologyNodeDescriptor,
    ResourceType,
    TaskDescriptor,
    TaskState,
)
from ..scheduler import FlowScheduler
from ..utils import (
    JobMap,
    ResourceMap,
    ResourceStatus,
    TaskMap,
    rand_uint64,
    resource_id_from_string,
)


def make_resource_desc(
    rtype: ResourceType, friendly_name: str = "", uuid: Optional[int] = None
) -> ResourceDescriptor:
    if uuid is None:
        uuid = rand_uint64()
    return ResourceDescriptor(
        uuid=str(uuid),
        friendly_name=friendly_name or f"{rtype.name.lower()}_{uuid % 10_000}",
        type=rtype,
        state=ResourceState.UNKNOWN,
        schedulable=rtype == ResourceType.PU,
    )


def make_coordinator_root() -> ResourceTopologyNodeDescriptor:
    return ResourceTopologyNodeDescriptor(
        resource_desc=make_resource_desc(ResourceType.COORDINATOR, "coordinator")
    )


def _register_subtree(rtnd: ResourceTopologyNodeDescriptor, resource_map: ResourceMap) -> None:
    rid = resource_id_from_string(rtnd.resource_desc.uuid)
    resource_map.insert(rid, ResourceStatus(descriptor=rtnd.resource_desc, topology_node=rtnd))
    for child in rtnd.children:
        _register_subtree(child, resource_map)


def build_machine_topology(
    num_cores: int,
    pus_per_core: int,
    task_capacity_per_pu: int,
    parent: ResourceTopologyNodeDescriptor,
    machine_index: int = 0,
) -> ResourceTopologyNodeDescriptor:
    """machine → core* → PU* subtree attached under parent (reference:
    schedule_iteration_test.go:257-331 createMachineNode)."""
    machine_rd = make_resource_desc(ResourceType.MACHINE, f"machine_{machine_index}")
    machine = ResourceTopologyNodeDescriptor(
        resource_desc=machine_rd, parent_id=parent.resource_desc.uuid
    )
    parent.children.append(machine)
    for c in range(num_cores):
        core_rd = make_resource_desc(ResourceType.CORE, f"machine_{machine_index}_core_{c}")
        core = ResourceTopologyNodeDescriptor(
            resource_desc=core_rd, parent_id=machine_rd.uuid
        )
        machine.children.append(core)
        for p in range(pus_per_core):
            pu_rd = make_resource_desc(
                ResourceType.PU, f"machine_{machine_index}_core_{c}_pu_{p}"
            )
            pu_rd.task_capacity = task_capacity_per_pu
            pu = ResourceTopologyNodeDescriptor(resource_desc=pu_rd, parent_id=core_rd.uuid)
            core.children.append(pu)
    return machine


def add_machine(
    scheduler: FlowScheduler,
    resource_map: ResourceMap,
    root: ResourceTopologyNodeDescriptor,
    num_cores: int = 1,
    pus_per_core: int = 1,
    task_capacity_per_pu: int = 1,
    machine_index: int = 0,
) -> ResourceTopologyNodeDescriptor:
    machine = build_machine_topology(
        num_cores, pus_per_core, task_capacity_per_pu, root, machine_index
    )
    _register_subtree(machine, resource_map)
    scheduler.register_resource(machine)
    return machine


def add_task_to_job(
    job_id: int, job_map: JobMap, task_map: TaskMap, name: str = ""
) -> TaskDescriptor:
    """Create a task under the job's root task (first task becomes the
    root; reference: schedule_iteration_test.go:212-253)."""
    jd = job_map.find(job_id)
    task_id = rand_uint64()
    td = TaskDescriptor(
        uid=task_id,
        name=name or f"task_{task_id % 100_000}",
        state=TaskState.CREATED,
        job_id=str(job_id),
    )
    if jd is None:
        jd = JobDescriptor(
            uuid=str(job_id),
            name=f"job_{job_id % 100_000}",
            state=JobState.CREATED,
            root_task=td,
        )
        job_map.insert(job_id, jd)
    else:
        jd.root_task.spawned.append(td)
    task_map.insert(task_id, td)
    return td


def add_job(
    scheduler: FlowScheduler,
    job_map: JobMap,
    task_map: TaskMap,
    num_tasks: int,
) -> int:
    """Create a job with num_tasks tasks and register it (reference:
    schedule_iteration_test.go:152-162)."""
    job_id = rand_uint64()
    for _ in range(num_tasks):
        add_task_to_job(job_id, job_map, task_map)
    jd = job_map.find(job_id)
    if jd is not None:
        scheduler.add_job(jd)
    return job_id


def build_cluster(
    num_machines: int,
    num_cores: int = 1,
    pus_per_core: int = 1,
    max_tasks_per_pu: int = 1,
    backend=None,
    cost_model_factory=None,
    preemption: bool = False,
):
    """Assemble maps + root + scheduler + machines in one call. Returns
    (scheduler, resource_map, job_map, task_map, root)."""
    resource_map = ResourceMap()
    job_map = JobMap()
    task_map = TaskMap()
    root = make_coordinator_root()
    resource_map.insert(
        resource_id_from_string(root.resource_desc.uuid),
        ResourceStatus(descriptor=root.resource_desc, topology_node=root),
    )
    scheduler = FlowScheduler(
        resource_map,
        job_map,
        task_map,
        root,
        max_tasks_per_pu=max_tasks_per_pu,
        cost_model_factory=cost_model_factory,
        backend=backend,
        preemption=preemption,
    )
    for i in range(num_machines):
        add_machine(
            scheduler, resource_map, root, num_cores, pus_per_core, max_tasks_per_pu, machine_index=i
        )
    return scheduler, resource_map, job_map, task_map, root
