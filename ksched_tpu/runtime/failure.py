"""Heartbeat-driven failure detection.

The reference stores heartbeats (ResourceStatus.LastHeartbeat,
resourcestatus.go:26; TaskDescriptor.last_heartbeat_*, task_desc.proto:
46-47) and defines ResourceState LOST (resource_desc.proto:22) but
ships no checker — machine loss must be driven externally through
DeregisterResource (flowscheduler/scheduler.go:162-210). This monitor
closes the loop: heartbeats in, expiry sweep, and the reference's own
reaction machinery out (deregister for lost machines, HandleTaskFailure
for silent tasks).
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional, Tuple

from ..data import ResourceState, TaskState
from ..scheduler import FlowScheduler
from ..utils import resource_id_from_string


class HeartbeatMonitor:
    def __init__(
        self,
        scheduler: FlowScheduler,
        machine_timeout_s: float = 30.0,
        task_timeout_s: float = 60.0,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self.scheduler = scheduler
        self.machine_timeout_s = machine_timeout_s
        self.task_timeout_s = task_timeout_s
        self.clock = clock or time.monotonic

    # -- heartbeat ingestion ----------------------------------------------

    def record_machine_heartbeat(self, resource_id: int, now: Optional[float] = None) -> None:
        rs = self.scheduler.resource_map.find(resource_id)
        if rs is None:
            raise KeyError(f"heartbeat for unknown resource {resource_id}")
        rs.last_heartbeat = now if now is not None else self.clock()

    def record_task_heartbeat(self, task_id: int, now: Optional[float] = None) -> None:
        td = self.scheduler.task_map.find(task_id)
        if td is None:
            raise KeyError(f"heartbeat for unknown task {task_id}")
        td.last_heartbeat_time = int((now if now is not None else self.clock()) * 1e9)

    # -- expiry sweep ------------------------------------------------------

    def check(self, now: Optional[float] = None) -> Tuple[List[int], List[int]]:
        """One failure-detection sweep. Returns (lost machine resource
        ids, failed task ids). Lost machines are marked LOST and
        deregistered (evicting their tasks back to runnable); silent
        RUNNING tasks are failed via HandleTaskFailure."""
        now = now if now is not None else self.clock()
        lost_machines: List[int] = []
        failed_tasks: List[int] = []

        # Machines: registered roots' machine children with stale beats.
        for rid, rs in self.scheduler.resource_map.items():
            rd = rs.descriptor
            if rd.type.name != "MACHINE":
                continue
            hb = rs.last_heartbeat
            if not hb:
                continue  # never heartbeated: not monitored
            if now - hb > self.machine_timeout_s and rd.state != ResourceState.LOST:
                rd.state = ResourceState.LOST
                lost_machines.append(rid)

        for rid in lost_machines:
            rs = self.scheduler.resource_map.find(rid)
            if rs is not None and rs.topology_node is not None:
                self.scheduler.deregister_resource(rs.topology_node)

        # Tasks: RUNNING with stale beats (only tasks that ever beat).
        for tid, td in self.scheduler.task_map.items():
            if td.state != TaskState.RUNNING or td.last_heartbeat_time == 0:
                continue
            if td.uid not in self.scheduler.task_bindings:
                continue  # already unbound by a machine loss above
            if now - td.last_heartbeat_time / 1e9 > self.task_timeout_s:
                failed_tasks.append(tid)

        for tid in failed_tasks:
            td = self.scheduler.task_map.find(tid)
            self.scheduler.handle_task_failure(td)
        return lost_machines, failed_tasks
