"""Heartbeat-driven failure detection.

The reference stores heartbeats (ResourceStatus.LastHeartbeat,
resourcestatus.go:26; TaskDescriptor.last_heartbeat_*, task_desc.proto:
46-47) and defines ResourceState LOST (resource_desc.proto:22) but
ships no checker — machine loss must be driven externally through
DeregisterResource (flowscheduler/scheduler.go:162-210). This monitor
closes the loop: heartbeats in, expiry sweep, and the reference's own
reaction machinery out (deregister for lost machines, HandleTaskFailure
for silent tasks).
"""

from __future__ import annotations

import threading
import time
import warnings
from typing import Callable, List, Optional, Tuple

from ..data import ResourceState, TaskState
from ..scheduler import FlowScheduler
from ..utils import resource_id_from_string


class HeartbeatMonitor:
    def __init__(
        self,
        scheduler: FlowScheduler,
        machine_timeout_s: float = 30.0,
        task_timeout_s: float = 60.0,
        clock: Optional[Callable[[], float]] = None,
    ) -> None:
        self.scheduler = scheduler
        self.machine_timeout_s = machine_timeout_s
        self.task_timeout_s = task_timeout_s
        self.clock = clock or time.monotonic
        #: heartbeats that arrived for entities we no longer track (a
        #: LOST machine beating again after deregister, a retired task):
        #: ignored, not fatal — re-admission goes through registration,
        #: never through a stray heartbeat resurrecting pruned state.
        self.stale_heartbeats = 0

    # -- heartbeat ingestion ----------------------------------------------

    def record_machine_heartbeat(self, resource_id: int, now: Optional[float] = None) -> bool:
        """Record a machine heartbeat. Returns False (and counts it as
        stale) when the resource is unknown — e.g. a machine that went
        LOST, was deregistered, and then resumed beating; it must
        re-register to rejoin, a heartbeat alone cannot resurrect it."""
        rs = self.scheduler.resource_map.find(resource_id)
        if rs is None:
            self.stale_heartbeats += 1
            return False
        rs.last_heartbeat = now if now is not None else self.clock()
        return True

    def record_task_heartbeat(self, task_id: int, now: Optional[float] = None) -> bool:
        """Record a task heartbeat; False (stale) for unknown tasks."""
        td = self.scheduler.task_map.find(task_id)
        if td is None:
            self.stale_heartbeats += 1
            return False
        # 0 is the proto's never-heartbeated sentinel (task_desc.proto
        # int default), so a genuine beat at t=0 is clamped to 1 ns.
        td.last_heartbeat_time = max(1, int((now if now is not None else self.clock()) * 1e9))
        return True

    # -- expiry sweep ------------------------------------------------------

    def check(self, now: Optional[float] = None) -> Tuple[List[int], List[int]]:
        """One failure-detection sweep. Returns (lost machine resource
        ids, failed task ids). Lost machines are marked LOST and
        deregistered (evicting their tasks back to runnable); silent
        RUNNING tasks are failed via HandleTaskFailure."""
        now = now if now is not None else self.clock()
        lost_machines: List[int] = []
        failed_tasks: List[int] = []

        # Machines: registered roots' machine children with stale beats.
        for rid, rs in self.scheduler.resource_map.items():
            rd = rs.descriptor
            if rd.type.name != "MACHINE":
                continue
            hb = rs.last_heartbeat
            if hb is None:
                continue  # never heartbeated: not monitored
            if now - hb > self.machine_timeout_s and rd.state != ResourceState.LOST:
                rd.state = ResourceState.LOST
                lost_machines.append(rid)

        for rid in lost_machines:
            rs = self.scheduler.resource_map.find(rid)
            if rs is not None and rs.topology_node is not None:
                self.scheduler.deregister_resource(rs.topology_node)

        # Tasks: RUNNING with stale beats (only tasks that ever beat).
        for tid, td in self.scheduler.task_map.items():
            if td.state != TaskState.RUNNING or td.last_heartbeat_time == 0:
                continue
            if td.uid not in self.scheduler.task_bindings:
                continue  # already unbound by a machine loss above
            if now - td.last_heartbeat_time / 1e9 > self.task_timeout_s:
                failed_tasks.append(tid)

        for tid in failed_tasks:
            td = self.scheduler.task_map.find(tid)
            self.scheduler.handle_task_failure(td)
        return lost_machines, failed_tasks


class RoundWatchdog:
    """A per-round deadline watchdog for the scheduler service loop.

    A Python round cannot be preempted mid-solve, so the watchdog does
    the two things that *are* possible: warn from a timer thread the
    moment the deadline passes (observable even if the round never
    returns — the operator's signal that the loop is wedged, not idle),
    and expose ``fired``/``misses`` so the service can record the miss
    in the round trace and treat it as a degradation signal.

    Use as a context manager around the round body; ``deadline_s <= 0``
    disables it.
    """

    def __init__(self, deadline_s: float = 0.0) -> None:
        self.deadline_s = deadline_s
        self.fired = False
        self.misses = 0
        self._timer: Optional[threading.Timer] = None
        self._t0 = 0.0
        # fired/misses are touched by the timer thread and (on a
        # boundary finish) __exit__'s wall-clock check; the lock keeps
        # a miss from being counted twice or read before it lands
        self._lock = threading.Lock()

    def _mark_miss(self) -> bool:
        with self._lock:
            if self.fired:
                return False
            self.fired = True
            self.misses += 1
            return True

    def _expire(self) -> None:
        if self._mark_miss():
            warnings.warn(
                f"scheduling round exceeded its {self.deadline_s:.3f}s deadline "
                "(solver wedged or cluster oversized for the budget)",
                RuntimeWarning,
                stacklevel=2,
            )

    def __enter__(self) -> "RoundWatchdog":
        self.fired = False
        if self.deadline_s > 0:
            self._t0 = time.monotonic()
            self._timer = threading.Timer(self.deadline_s, self._expire)
            self._timer.daemon = True
            self._timer.start()
        return self

    def __exit__(self, *exc) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
            # A round finishing right at the deadline races cancel()
            # against the already-dispatched timer callback: the wall
            # clock, not the callback's scheduling luck, decides — so
            # `fired` is settled before the caller reads it.
            if time.monotonic() - self._t0 >= self.deadline_s:
                self._mark_miss()
