"""Checkpoint / resume.

The reference has none: all state is in-memory and restart means a cold
rebuild from the API server's world view (SURVEY §5). The rebuild keeps
that reconstructibility property AND makes it a feature:

- FlowScheduler checkpoints are the *host descriptors only* (topology
  roots, jobs/tasks, bindings) — exactly the world state an API server
  would hold. Restore replays them through the normal event API
  (register_resource / add_job / placement pinning), so the restored
  graph is rebuilt by the same code paths production uses, never by
  poking internals.
- BulkCluster checkpoints are the flat device-shaped arrays themselves,
  written as npz: restore is a buffer upload, the natural device-state
  checkpoint for the array path.
"""

from __future__ import annotations

import json
import pickle
from typing import Dict, Optional, Tuple

import numpy as np

from ..data import JobState, TaskState
from ..scheduler import FlowScheduler
from ..utils import JobMap, ResourceMap, ResourceStatus, TaskMap, resource_id_from_string

CHECKPOINT_VERSION = 1
#: warm-restore manifest (the ".wal" companion): version of the framed
#: record stream save_warm_manifest writes
WARM_MANIFEST_VERSION = 1


class CheckpointError(RuntimeError):
    """Base for checkpoint load failures; subclasses are DISTINCT so a
    damaged sidecar, a missing companion, and a version mismatch each
    surface as their own actionable error (not one opaque crash)."""


class CheckpointDamaged(CheckpointError):
    """Truncated / garbage checkpoint bytes (unpicklable sidecar, torn
    write): the file exists but cannot be trusted."""


class CheckpointMissing(CheckpointError):
    """A required companion file of the checkpoint set is absent."""


class CheckpointVersionError(CheckpointError, ValueError):
    """The checkpoint was written by an incompatible version.
    ValueError subclass for pre-r14 callers that caught the bare
    ValueError the old version check raised."""
#: device checkpoints: version 2 = __meta_json__ typed meta (r4+);
#: version 1 = the pre-r4 sorted-int64 __meta_keys__/__meta__ pair.
#: Writers stamp 2; the loader accepts both. Bumped so a pre-r4 reader
#: opening a new file fails with its intended unsupported-version
#: message instead of an opaque KeyError('__meta_keys__').
DEVICE_CHECKPOINT_VERSION = 2


# ---------------------------------------------------------------------------
# FlowScheduler (event-path) checkpoints
# ---------------------------------------------------------------------------


def atomic_pickle(state, path: str) -> None:
    """Pickle to a temp file and rename into place: a crash mid-write
    must leave the PREVIOUS checkpoint intact, not a truncated file
    where the last good one used to be (same discipline as the warm
    manifest's integrity.write_records)."""
    import os

    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(state, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def save_scheduler(scheduler: FlowScheduler, path: str) -> None:
    """Snapshot the world state: topology roots, jobs (task trees ride
    along via root_task.spawned), and task→PU bindings."""
    jobs = {jid: jd for jid, jd in scheduler.job_map.items()}
    state = {
        "version": CHECKPOINT_VERSION,
        "coordinator": scheduler.resource_topology,
        "jobs": jobs,
        "bindings": dict(scheduler.task_bindings),
        "max_tasks_per_pu": scheduler.gm.max_tasks_per_pu,
    }
    atomic_pickle(state, path)


def restore_scheduler(
    path: str,
    cost_model_factory=None,
    backend=None,
    device_resident: bool = False,
) -> Tuple[FlowScheduler, ResourceMap, JobMap, TaskMap]:
    """Rebuild a scheduler from a checkpoint by replaying the event API.

    Placements are restored by pinning each bound task through the
    normal placement path, so bindings, resource stats, and graph state
    all agree — the same invariant a live scheduler maintains.
    """
    with open(path, "rb") as f:
        state = pickle.load(f)
    if state["version"] != CHECKPOINT_VERSION:
        raise ValueError(f"unsupported checkpoint version {state['version']}")

    resource_map = ResourceMap()
    job_map = JobMap()
    task_map = TaskMap()
    coordinator = state["coordinator"]

    def register_subtree(rtnd):
        rid = resource_id_from_string(rtnd.resource_desc.uuid)
        resource_map.insert(
            rid, ResourceStatus(descriptor=rtnd.resource_desc, topology_node=rtnd)
        )
        for ch in rtnd.children:
            register_subtree(ch)

    register_subtree(coordinator)
    # Clear runtime aggregates the replay will rebuild.
    for _, rs in resource_map.items():
        rs.descriptor.current_running_tasks = []
        rs.descriptor.num_running_tasks_below = 0
        rs.descriptor.num_slots_below = 0

    scheduler = FlowScheduler(
        resource_map,
        job_map,
        task_map,
        coordinator,
        max_tasks_per_pu=state["max_tasks_per_pu"],
        cost_model_factory=cost_model_factory,
        backend=backend,
        device_resident=device_resident,
    )
    # Each machine subtree under the coordinator goes through the normal
    # registration path (the constructor already registered the root).
    for machine in coordinator.children:
        scheduler.register_resource(machine)

    # Jobs + tasks. Previously-running tasks are reset to RUNNABLE so the
    # graph build creates their nodes; their recorded placements are then
    # re-pinned below (flipping them back to RUNNING).
    for jid, jd in state["jobs"].items():
        job_map.insert(jid, jd)
        stack = [jd.root_task] if jd.root_task else []
        while stack:
            td = stack.pop()
            task_map.insert(td.uid, td)
            stack.extend(td.spawned)
            if td.uid in state["bindings"] and td.state == TaskState.RUNNING:
                # CREATED (not RUNNABLE) so _compute_runnable_tasks_for_job
                # promotes it and registers it in the runnable set.
                td.state = TaskState.CREATED
                td.scheduled_to_resource = ""
        if jd.state not in (JobState.COMPLETED, JobState.FAILED, JobState.ABORTED):
            scheduler.add_job(jd)

    # Build task nodes WITHOUT a solve (no phantom placements), then
    # re-pin the recorded bindings through the normal placement path.
    jds = [
        jd
        for jd in scheduler.jobs_to_schedule.values()
        if scheduler._compute_runnable_tasks_for_job(jd)
    ]
    if jds:
        scheduler.gm.compute_topology_statistics(scheduler.gm.sink_node)
        scheduler.gm.add_or_update_job_nodes(jds)
    for task_id, pu_rid in state["bindings"].items():
        td = task_map.find(task_id)
        rs = resource_map.find(pu_rid)
        if td is None or rs is None:
            continue
        scheduler.handle_task_placement(td, rs.descriptor)
    return scheduler, resource_map, job_map, task_map


# ---------------------------------------------------------------------------
# BulkCluster (array-path) checkpoints
# ---------------------------------------------------------------------------

_BULK_ARRAYS = (
    "src", "dst", "cap", "cost", "excess", "node_type",
    "task_live", "task_job", "task_class", "task_pu",
    "pu_running", "machine_census", "machine_enabled",
)


def save_bulk_checkpoint(cluster, path: str) -> None:
    """Write the flat arrays + geometry to npz (device-state snapshot)."""
    meta = np.array(
        [cluster.M, cluster.P, cluster.S, cluster.J, cluster.C,
         cluster.unsched_cost, cluster.ec_cost, cluster.task_cap],
        dtype=np.int64,  # kschedlint: host-only (checkpoint wire format)
    )
    arrays = {name: getattr(cluster, name) for name in _BULK_ARRAYS}
    np.savez_compressed(path, __meta__=meta, **arrays)


def load_bulk_checkpoint(
    path: str, backend, machine_cost_fn=None, class_cost_fn=None
) -> "BulkCluster":
    """Rebuild a BulkCluster around checkpointed arrays. Cost callbacks
    are code, not data — pass the same machine_cost_fn/class_cost_fn the
    saved cluster used or its per-round cost refresh stays frozen."""
    from ..scheduler.bulk import BulkCluster

    data = np.load(path)
    M, P, S, J, C, unsched_cost, ec_cost, task_cap = data["__meta__"]
    cluster = BulkCluster(
        num_machines=int(M),
        pus_per_machine=int(P),
        slots_per_pu=int(S),
        num_jobs=int(J),
        backend=backend,
        unsched_cost=int(unsched_cost),
        ec_cost=int(ec_cost),
        machine_cost_fn=machine_cost_fn,
        class_cost_fn=class_cost_fn,
        num_task_classes=int(C),
        task_capacity=int(task_cap),
    )
    for name in _BULK_ARRAYS:
        getattr(cluster, name)[...] = data[name]
    # Rebuild the per-job free-row pools from task_live (single pass,
    # descending rows to match the constructor's pop order).
    cluster._job_free = [[] for _ in range(cluster.J)]
    for r in range(cluster.task_cap - 1, -1, -1):
        if not cluster.task_live[r]:
            cluster._job_free[r % cluster.J].append(r)
    return cluster


# ---------------------------------------------------------------------------
# DeviceBulkCluster (device-path) checkpoints
# ---------------------------------------------------------------------------

#: DeviceClusterState fields, in NamedTuple order
_DEVICE_STATE = (
    "live", "cls", "job", "pu", "pu_running", "machine_enabled", "grp",
)
#: GroupSpec fields (group mode only), prefixed g_ in the npz
_DEVICE_GROUPS = ("cls", "job", "e", "u", "pref_w")


def save_device_checkpoint(cluster, path: str) -> None:
    """Snapshot a DeviceBulkCluster: geometry + solver knobs + the full
    DeviceClusterState (placements, occupancy, membership, groups) and,
    in group mode, the GroupSpec arrays. One bulk device->host fetch —
    do this outside any timed region (docs/NOTES.md: the first fetch
    permanently degrades later dispatch latency on tunneled TPUs)."""
    meta = {
        "version": DEVICE_CHECKPOINT_VERSION,
        "num_machines": cluster.M,
        "pus_per_machine": cluster.P,
        "slots_per_pu": cluster.S,
        "num_jobs": cluster.J,
        "num_task_classes": cluster.C,
        "task_capacity": cluster.Tcap,
        "unsched_cost": cluster.unsched_cost,
        "ec_cost": cluster.ec_cost,
        "supersteps": cluster.supersteps,
        "decode_width": -1 if cluster.decode_width is None else cluster.decode_width,
        "alpha": cluster.alpha,
        "preemption": int(cluster.preemption),
        "continuation_discount": cluster.continuation_discount,
        "preempt_every": cluster.preempt_every,
        "preempt_drift": cluster.preempt_drift,
        "preempt_global_every": cluster.preempt_global_every,
        "preempt_scope_tau": cluster.preempt_scope_tau,
        "preempt_scoped_width": cluster.preempt_scoped_width,
        "preempt_incr_budget": cluster.preempt_incr_budget,
        "track_realized_cost": int(cluster.track_realized_cost),
        "num_groups": cluster.G if cluster.grouped else 0,
        # the full compaction ladder (a JSON list; int in pre-r4 saves)
        "active_groups_cap": list(cluster.active_groups_caps),
        "two_stage_eps0": cluster.two_stage_eps0,
        "refine_waves": cluster.refine_waves,
        "per_job": int(cluster.per_job),
    }
    arrays = {
        f"s_{name}": np.asarray(v)
        for name, v in cluster.fetch_state().items()
    }
    if cluster.hybrid_preempt:
        # the stability-aware carry: census at the last full re-solve
        # and rounds since — restoring it resumes the exact cadence
        # instead of conservatively re-firing a full round (fetched as
        # ONE extra transfer, keeping save near the one-bulk-fetch
        # discipline above)
        import jax

        hyb_census, hyb_k, hyb_kg = jax.device_get(
            (cluster._hyb_census, cluster._hyb_k, cluster._hyb_kg)
        )
        arrays["hyb_census"] = np.asarray(hyb_census)
        meta["hyb_k"] = int(hyb_k)
        meta["hyb_kg"] = int(hyb_kg)
    if cluster.grouped:
        got = {k: np.asarray(v) for k, v in cluster.groups._asdict().items()}
        arrays.update({f"g_{name}": got[name] for name in _DEVICE_GROUPS})
    if cluster.per_job:
        arrays["job_unsched_cost"] = np.asarray(cluster.job_unsched_cost)
    # meta rides as JSON, not a single int64 array: a future float knob
    # (fractional discount, alpha) must keep its type on round-trip
    # instead of truncating silently
    np.savez_compressed(
        path,
        __kind__=np.array("device_bulk"),
        __meta_json__=np.array(json.dumps(meta)),
        **arrays,
    )


def load_device_checkpoint(path: str, class_cost_fn=None):
    """Rebuild a DeviceBulkCluster from a device checkpoint. The cost
    callback is code, not data — pass the same class_cost_fn the saved
    cluster used (its identity shapes the compiled round programs)."""
    import jax.numpy as jnp

    from ..scheduler.device_bulk import DeviceBulkCluster, DeviceClusterState

    data = np.load(path)
    if "__kind__" not in data or str(data["__kind__"]) != "device_bulk":
        raise ValueError(
            f"{path} is not a device_bulk checkpoint (wrong kind or a "
            "bulk/npz checkpoint — use load_bulk_checkpoint for those)"
        )
    if "__meta_json__" in data:
        meta = json.loads(str(data["__meta_json__"]))
    else:  # pre-r4 checkpoints: all-int meta in a single int64 array
        meta = {
            str(k): int(v)
            for k, v in zip(data["__meta_keys__"], data["__meta__"])
        }
    if meta["version"] not in (1, DEVICE_CHECKPOINT_VERSION):
        raise ValueError(f"unsupported checkpoint version {meta['version']}")
    cluster = DeviceBulkCluster(
        num_machines=meta["num_machines"],
        pus_per_machine=meta["pus_per_machine"],
        slots_per_pu=meta["slots_per_pu"],
        num_jobs=meta["num_jobs"],
        num_task_classes=meta["num_task_classes"],
        task_capacity=meta["task_capacity"],
        unsched_cost=meta["unsched_cost"],
        ec_cost=meta["ec_cost"],
        class_cost_fn=class_cost_fn,
        supersteps=meta["supersteps"],
        decode_width=None if meta["decode_width"] < 0 else meta["decode_width"],
        alpha=meta["alpha"],
        job_unsched_cost=(
            data["job_unsched_cost"] if meta["per_job"] else None
        ),
        preemption=bool(meta["preemption"]),
        continuation_discount=meta["continuation_discount"],
        preempt_every=meta.get("preempt_every", 1),
        preempt_drift=meta.get("preempt_drift", 0),
        preempt_global_every=meta.get("preempt_global_every", 0),
        preempt_scope_tau=meta.get("preempt_scope_tau", 1),
        # explicit None test: a saved width of 0 is a legal (if
        # degenerate) configuration and must round-trip as 0, not None
        preempt_scoped_width=(
            None
            if meta.get("preempt_scoped_width") is None
            or meta["preempt_scoped_width"] < 0
            else meta["preempt_scoped_width"]
        ),
        preempt_incr_budget=meta.get("preempt_incr_budget"),
        track_realized_cost=bool(meta.get("track_realized_cost", 0)),
        num_groups=meta["num_groups"],
        active_groups_cap=meta["active_groups_cap"],
        refine_waves=meta["refine_waves"],
        two_stage_eps0=meta.get("two_stage_eps0", "one"),
    )
    cluster.state = DeviceClusterState(
        **{name: jnp.asarray(data[f"s_{name}"]) for name in _DEVICE_STATE}
    )
    if cluster.grouped:
        cluster.set_groups(
            **{name: data[f"g_{name}"] for name in _DEVICE_GROUPS}
        )
    if cluster.hybrid_preempt and "hyb_census" in data:
        cluster._hyb_census = jnp.asarray(data["hyb_census"])
        cluster._hyb_k = jnp.int32(meta.get("hyb_k", cluster.preempt_every - 1))
        cluster._hyb_kg = jnp.int32(
            meta.get("hyb_kg", max(cluster.preempt_global_every - 1, 0))
        )
    return cluster


# ---------------------------------------------------------------------------
# Warm-restore manifest (journal WAL + device-state manifest)
# ---------------------------------------------------------------------------
#
# The event-replay checkpoint above rebuilds only HOST scheduler state:
# a kill-and-restore lands back on the cold full_build path (fresh node
# ids, host argsort, full problem+plan upload, cold solver) and
# forfeits the delta-sized warm band. The warm manifest closes that
# gap: it snapshots the scheduler CORE (graph manager, flow graph,
# journal state, cost model, maps — one pickle, so shared descriptor
# identity survives), the DeviceGraphState + SlotPlanState geometry
# (slot table, regions, high-water marks, tail pool), and the solver's
# carried warm flow/potentials/endpoints. load_warm_manifest replays
# the records into a rebuilt scheduler whose device mirror is primed
# OUTSIDE any round, so the first post-restore round ships only that
# round's delta (plan_sync `delta`, upload `delta`) and the first
# solve is already warm — bit-identical to the never-killed process.
#
# The manifest rides the WAL record framing (runtime/integrity.py):
# seq-numbered, CRC'd records, so dropped/duplicated records and torn
# writes are detected as DISTINCT corruption kinds and the caller can
# contain them by falling back to the cold event replay.

#: scheduler attributes excluded from the core pickle (rebuilt fresh:
#: the solver holds the backend/ladder and live device buffers)
_SCHED_CORE_EXCLUDE = ("solver", "_round_in_flight")


def find_jax_solver(backend):
    """The JaxSolver whose warm state a manifest carries, if the
    configured rung is one (a DegradingSolver is unwrapped to its
    primary)."""
    from ..solver.jax_solver import JaxSolver
    from .degrade import DegradingSolver

    if isinstance(backend, DegradingSolver):
        backend = backend.primary
    return backend if isinstance(backend, JaxSolver) else None


def save_warm_manifest(scheduler, path: str, meta: Optional[dict] = None) -> None:
    """Write the warm-restore manifest for a FlowScheduler (see the
    section comment). Call at a round boundary with no round in flight
    and pending bindings flushed — SchedulerService.save_checkpoint
    guarantees both."""
    from .integrity import write_records

    sol = scheduler.solver
    core = {
        k: v for k, v in scheduler.__dict__.items() if k not in _SCHED_CORE_EXCLUDE
    }
    warm = None
    jaxs = find_jax_solver(sol.backend)
    if jaxs is not None:
        warm = jaxs.export_warm_state()
    payload = {
        "scheduler": core,
        "device_state": sol.state,
        "started": sol._started,
        "incremental": sol.incremental,
    }
    records = [
        ("meta", json.dumps(
            {"version": WARM_MANIFEST_VERSION, **(meta or {})}
        ).encode()),
        ("core", pickle.dumps(payload)),
        ("warm", pickle.dumps(warm)),
    ]
    write_records(path, records)


def load_warm_manifest(
    path: str,
    backend=None,
    device_resident: bool = False,
) -> Tuple:
    """Rebuild a FlowScheduler (+ maps) from a warm manifest and prime
    its device mirror. Returns ((scheduler, resource_map, job_map,
    task_map), meta). Raises `integrity.WALCorrupted` on a damaged
    stream and CheckpointVersionError on a version mismatch — callers
    contain both by falling back to restore_scheduler's cold replay."""
    from ..graph.device_export import _STATE_UIDS, DeviceResidentState
    from ..scheduler.flow_scheduler import FlowScheduler
    from ..solver.cpu_ref import ReferenceSolver
    from ..solver.placement import PlacementSolver
    from .integrity import read_records

    recs = dict(read_records(path))
    if not {"meta", "core", "warm"} <= set(recs):
        missing = {"meta", "core", "warm"} - set(recs)
        raise CheckpointDamaged(
            f"warm manifest {path} is missing record(s) {sorted(missing)}"
        )
    meta = json.loads(recs["meta"])
    if meta.get("version") != WARM_MANIFEST_VERSION:
        raise CheckpointVersionError(
            f"unsupported warm manifest version {meta.get('version')} "
            f"(this build writes {WARM_MANIFEST_VERSION}); re-checkpoint "
            "from a matching build or restore cold from the .sched replay"
        )
    payload = pickle.loads(recs["core"])
    warm = pickle.loads(recs["warm"])

    scheduler = FlowScheduler.__new__(FlowScheduler)
    scheduler.__dict__.update(payload["scheduler"])
    scheduler._round_in_flight = None
    st = payload["device_state"]
    # the uid feeds plan_key identity; a fresh process must never let a
    # LATER DeviceGraphState collide with the restored one's key
    old_uid = st._uid
    st._uid = next(_STATE_UIDS)
    # the pickled problem cache carries a plan_key built on the old
    # uid; drop it so the next materialize re-keys on the new one
    st._cache = None
    st._cache_nodes_ok = False
    st._cache_arcs_ok = False
    sol = PlacementSolver(
        scheduler.gm,
        backend if backend is not None else ReferenceSolver(),
        device_resident=device_resident,
    )
    sol.state = st
    sol.resident = DeviceResidentState(st) if device_resident else None
    sol._started = payload["started"]
    sol.incremental = payload["incremental"]
    scheduler.solver = sol
    if warm is not None:
        jaxs = find_jax_solver(sol.backend)
        if jaxs is not None:
            key = warm.get("key_solved")
            if key is not None and len(key) and key[0] == old_uid:
                key = (st._uid,) + tuple(key[1:])
            jaxs.import_warm_state(warm, key_solved=key, resident=device_resident)
    if sol.resident is not None:
        # prime the mirror NOW (full upload + plan tensor ship happen
        # at restore time, outside any round), so the first
        # post-restore round's refresh is delta-sized
        sol.resident.refresh()
    return (
        (scheduler, scheduler.resource_map, scheduler.job_map, scheduler.task_map),
        meta,
    )
