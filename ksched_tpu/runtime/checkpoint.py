"""Checkpoint / resume.

The reference has none: all state is in-memory and restart means a cold
rebuild from the API server's world view (SURVEY §5). The rebuild keeps
that reconstructibility property AND makes it a feature:

- FlowScheduler checkpoints are the *host descriptors only* (topology
  roots, jobs/tasks, bindings) — exactly the world state an API server
  would hold. Restore replays them through the normal event API
  (register_resource / add_job / placement pinning), so the restored
  graph is rebuilt by the same code paths production uses, never by
  poking internals.
- BulkCluster checkpoints are the flat device-shaped arrays themselves,
  written as npz: restore is a buffer upload, the natural device-state
  checkpoint for the array path.
"""

from __future__ import annotations

import pickle
from typing import Dict, Optional, Tuple

import numpy as np

from ..data import JobState, TaskState
from ..scheduler import FlowScheduler
from ..utils import JobMap, ResourceMap, ResourceStatus, TaskMap, resource_id_from_string

CHECKPOINT_VERSION = 1


# ---------------------------------------------------------------------------
# FlowScheduler (event-path) checkpoints
# ---------------------------------------------------------------------------


def save_scheduler(scheduler: FlowScheduler, path: str) -> None:
    """Snapshot the world state: topology roots, jobs (task trees ride
    along via root_task.spawned), and task→PU bindings."""
    jobs = {jid: jd for jid, jd in scheduler.job_map.items()}
    state = {
        "version": CHECKPOINT_VERSION,
        "coordinator": scheduler.resource_topology,
        "jobs": jobs,
        "bindings": dict(scheduler.task_bindings),
        "max_tasks_per_pu": scheduler.gm.max_tasks_per_pu,
    }
    with open(path, "wb") as f:
        pickle.dump(state, f)


def restore_scheduler(
    path: str,
    cost_model_factory=None,
    backend=None,
) -> Tuple[FlowScheduler, ResourceMap, JobMap, TaskMap]:
    """Rebuild a scheduler from a checkpoint by replaying the event API.

    Placements are restored by pinning each bound task through the
    normal placement path, so bindings, resource stats, and graph state
    all agree — the same invariant a live scheduler maintains.
    """
    with open(path, "rb") as f:
        state = pickle.load(f)
    if state["version"] != CHECKPOINT_VERSION:
        raise ValueError(f"unsupported checkpoint version {state['version']}")

    resource_map = ResourceMap()
    job_map = JobMap()
    task_map = TaskMap()
    coordinator = state["coordinator"]

    def register_subtree(rtnd):
        rid = resource_id_from_string(rtnd.resource_desc.uuid)
        resource_map.insert(
            rid, ResourceStatus(descriptor=rtnd.resource_desc, topology_node=rtnd)
        )
        for ch in rtnd.children:
            register_subtree(ch)

    register_subtree(coordinator)
    # Clear runtime aggregates the replay will rebuild.
    for _, rs in resource_map.items():
        rs.descriptor.current_running_tasks = []
        rs.descriptor.num_running_tasks_below = 0
        rs.descriptor.num_slots_below = 0

    scheduler = FlowScheduler(
        resource_map,
        job_map,
        task_map,
        coordinator,
        max_tasks_per_pu=state["max_tasks_per_pu"],
        cost_model_factory=cost_model_factory,
        backend=backend,
    )
    # Each machine subtree under the coordinator goes through the normal
    # registration path (the constructor already registered the root).
    for machine in coordinator.children:
        scheduler.register_resource(machine)

    # Jobs + tasks. Previously-running tasks are reset to RUNNABLE so the
    # graph build creates their nodes; their recorded placements are then
    # re-pinned below (flipping them back to RUNNING).
    for jid, jd in state["jobs"].items():
        job_map.insert(jid, jd)
        stack = [jd.root_task] if jd.root_task else []
        while stack:
            td = stack.pop()
            task_map.insert(td.uid, td)
            stack.extend(td.spawned)
            if td.uid in state["bindings"] and td.state == TaskState.RUNNING:
                # CREATED (not RUNNABLE) so _compute_runnable_tasks_for_job
                # promotes it and registers it in the runnable set.
                td.state = TaskState.CREATED
                td.scheduled_to_resource = ""
        if jd.state not in (JobState.COMPLETED, JobState.FAILED, JobState.ABORTED):
            scheduler.add_job(jd)

    # Build task nodes WITHOUT a solve (no phantom placements), then
    # re-pin the recorded bindings through the normal placement path.
    jds = [
        jd
        for jd in scheduler.jobs_to_schedule.values()
        if scheduler._compute_runnable_tasks_for_job(jd)
    ]
    if jds:
        scheduler.gm.compute_topology_statistics(scheduler.gm.sink_node)
        scheduler.gm.add_or_update_job_nodes(jds)
    for task_id, pu_rid in state["bindings"].items():
        td = task_map.find(task_id)
        rs = resource_map.find(pu_rid)
        if td is None or rs is None:
            continue
        scheduler.handle_task_placement(td, rs.descriptor)
    return scheduler, resource_map, job_map, task_map


# ---------------------------------------------------------------------------
# BulkCluster (array-path) checkpoints
# ---------------------------------------------------------------------------

_BULK_ARRAYS = (
    "src", "dst", "cap", "cost", "excess", "node_type",
    "task_live", "task_job", "task_class", "task_pu",
    "pu_running", "machine_census", "machine_enabled",
)


def save_bulk_checkpoint(cluster, path: str) -> None:
    """Write the flat arrays + geometry to npz (device-state snapshot)."""
    meta = np.array(
        [cluster.M, cluster.P, cluster.S, cluster.J, cluster.C,
         cluster.unsched_cost, cluster.ec_cost, cluster.task_cap],
        dtype=np.int64,
    )
    arrays = {name: getattr(cluster, name) for name in _BULK_ARRAYS}
    np.savez_compressed(path, __meta__=meta, **arrays)


def load_bulk_checkpoint(
    path: str, backend, machine_cost_fn=None, class_cost_fn=None
) -> "BulkCluster":
    """Rebuild a BulkCluster around checkpointed arrays. Cost callbacks
    are code, not data — pass the same machine_cost_fn/class_cost_fn the
    saved cluster used or its per-round cost refresh stays frozen."""
    from ..scheduler.bulk import BulkCluster

    data = np.load(path)
    M, P, S, J, C, unsched_cost, ec_cost, task_cap = data["__meta__"]
    cluster = BulkCluster(
        num_machines=int(M),
        pus_per_machine=int(P),
        slots_per_pu=int(S),
        num_jobs=int(J),
        backend=backend,
        unsched_cost=int(unsched_cost),
        ec_cost=int(ec_cost),
        machine_cost_fn=machine_cost_fn,
        class_cost_fn=class_cost_fn,
        num_task_classes=int(C),
        task_capacity=int(task_cap),
    )
    for name in _BULK_ARRAYS:
        getattr(cluster, name)[...] = data[name]
    # Rebuild the per-job free-row pools from task_live (single pass,
    # descending rows to match the constructor's pop order).
    cluster._job_free = [[] for _ in range(cluster.J)]
    for r in range(cluster.task_cap - 1, -1, -1):
        if not cluster.task_live[r]:
            cluster._job_free[r % cluster.J].append(r)
    return cluster
