"""State integrity: device-state fingerprints, divergence repair, WAL.

The r11-r12 speedups made correctness structurally fragile: placements
depend on a long-lived chain of donated in-place scatters against
persistent device buffers (graph/device_export.delta_apply_fn,
graph/slot_plan.plan_apply_fn) that nothing audited after the initial
upload. This module closes that gap with three pieces:

- **Fingerprints** — order-independent weighted checksums of every
  persistent device buffer (problem arrays, slot-plan tensors, the
  carried warm flow), computed ON DEVICE by one scatter-free jit'd
  program per buffer family and compared against bit-exact host twins
  derived from the journal-maintained host arrays (the source of
  truth). The weights are odd, so any single-bit flip of any element
  changes the checksum — a wrong scatter, a stale plan row, or a
  bit-flipped buffer is caught the round it happens. The fingerprint
  programs are pinned by the jaxpr contracts (scatter-free, 32-bit,
  pow2-bucket hash-stable); the delta/plan scatter programs themselves
  are UNTOUCHED, so the r12 off-hash pins hold byte-identically.

- **Divergence repair ladder** — `StateAuditor.repair` escalates:
  re-scatter exactly the diverged rows (through the existing delta
  program) → full problem + plan tensor re-upload; the caller
  (solver/placement.py) holds the final `full_build` rung (which also
  rebuilds the plan layout and resets solver warm state), and the
  degradation ladder's NOOP round backstops even that. Both auditor
  rungs restore the exact pre-corruption buffers, so a repaired
  round's placements are bit-identical to a clean-state solve. Every audit, divergence, and repair is counted
  (`ksched_state_audits_total{result}`,
  `ksched_state_repairs_total{rung}`) and every divergence deposits a
  structured `state_divergence` event on the soltel stall ring that
  flight dumps embed.

- **WAL record framing** — checkpoint manifests (runtime/checkpoint.py
  `save_warm_manifest`) are written as a sequence of seq-numbered,
  CRC-framed records. `read_records` detects dropped records (seq
  gap), duplicated records (seq dup), torn writes (truncation), and
  bit rot (CRC) as distinct `WALCorrupted` kinds — the corruption
  fault classes `runtime/chaos.py` injects (`corrupt_wal_file`) and
  `SchedulerService.restore` contains by falling back to cold event
  replay.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from collections import Counter
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..obs.metrics import get_registry

#: fingerprint weight recurrence constants (Knuth multiplicative hash,
#: expressed as wrapped int32 so host uint32 math and device int32 math
#: produce the same bit patterns)
_FP_MUL = -1640531535  # 2654435761 mod 2**32
_FP_ADD = -1640531527  # 0x9E3779B9 mod 2**32

#: problem-buffer fingerprint order (DeviceResidentState.d_*)
FP_STATE_ARRAYS = ("excess", "src", "dst", "cap", "cost")
#: plan-tensor fingerprint order (DeviceResidentState.d_p_* mirror)
FP_PLAN_ARRAYS = (
    "p_arc", "p_sign", "p_src", "p_dst", "inv_order",
    "seg_start", "is_start", "node_first", "node_last", "node_nonempty",
)

#: mismatching indices carried on an IntegrityError / divergence event
DIFF_BOUND = 8


class IntegrityError(AssertionError):
    """Structured state-integrity failure: which array diverged, and a
    BOUNDED diff summary (first-`DIFF_BOUND` mismatching indices with
    expected vs found values) instead of a bare assert. An
    AssertionError subclass so pre-existing bare-assert consumers
    (tests, debug harnesses) keep catching it."""

    def __init__(
        self,
        message: str,
        array: str = "",
        indices: Optional[Sequence[int]] = None,
        expected: Optional[Sequence[int]] = None,
        found: Optional[Sequence[int]] = None,
    ) -> None:
        super().__init__(message)
        self.array = array
        self.indices = list(indices or [])[:DIFF_BOUND]
        self.expected = list(expected or [])[:DIFF_BOUND]
        self.found = list(found or [])[:DIFF_BOUND]

    def to_dict(self) -> dict:
        return {
            "array": self.array,
            "indices": [int(i) for i in self.indices],
            "expected": [int(v) for v in self.expected],
            "found": [int(v) for v in self.found],
            "detail": str(self),
        }


def bounded_diff(name: str, found: np.ndarray, expected: np.ndarray) -> IntegrityError:
    """An IntegrityError for one diverged array, carrying the first
    DIFF_BOUND mismatching indices."""
    got = np.asarray(found)
    want = np.asarray(expected)
    if got.shape != want.shape:
        return IntegrityError(
            f"{name}: shape {got.shape} != expected {want.shape}", array=name
        )
    bad = np.nonzero(got != want)[0]
    head = bad[:DIFF_BOUND]
    return IntegrityError(
        f"{name} diverged at {len(bad)} row(s); first {len(head)}: "
        f"idx={head.tolist()} found={got[head].tolist()} "
        f"expected={want[head].tolist()}",
        array=name,
        indices=head.tolist(),
        expected=want[head].tolist(),
        found=got[head].tolist(),
    )


# ---------------------------------------------------------------------------
# fingerprints: device programs + bit-exact host twins
# ---------------------------------------------------------------------------


_WEIGHTS: Dict[int, np.ndarray] = {}


def host_weights(n: int) -> np.ndarray:
    """uint32 weight vector w[i] = i*MUL + ADD (mod 2**32); odd for
    every i, so a single-bit flip of any element always moves the
    weighted sum."""
    cached = _WEIGHTS.get(n)
    if cached is not None:
        return cached
    i = np.arange(n, dtype=np.uint64)  # kschedlint: host-only (host checksum twin; device side is wrapped int32)
    w = (i * np.uint64(2654435761) + np.uint64(0x9E3779B9)) & 0xFFFFFFFF  # kschedlint: host-only (host checksum twin)
    # forced odd: the recurrence alone yields EVEN weights at odd i
    # (odd*odd + odd), and an even weight with k trailing zero bits
    # makes flips of the top k bits invisible mod 2**32 (caught by the
    # 512-round corruption soak: w[15] % 8 == 0 swallowed a bit-29
    # flip). With w odd, w * 2**b != 0 mod 2**32 for every b < 32.
    out = (w | np.uint64(1)).astype(np.uint32)  # kschedlint: host-only (host checksum twin)
    # cached per length (a handful of pow2 buckets live at once): the
    # audit calls this for 15 buffer families every audited round
    if len(_WEIGHTS) > 64:
        _WEIGHTS.clear()
    _WEIGHTS[n] = out
    return out


def host_fingerprint(arr: np.ndarray) -> int:
    """The host twin of the device checksum: sum(v[i]*w[i]) mod 2**32
    over the int32 bit patterns of `arr` (bool/int64 inputs cast the
    same way the device mirror upload casts them)."""
    v = np.ascontiguousarray(np.asarray(arr).astype(np.int32)).view(np.uint32)
    w = host_weights(len(v))
    prod = (v.astype(np.uint64) * w.astype(np.uint64)) & 0xFFFFFFFF  # kschedlint: host-only (host checksum twin)
    return int(np.sum(prod, dtype=np.uint64) & 0xFFFFFFFF)  # kschedlint: host-only (host checksum twin)


def _device_fp1(v):
    """Traced per-buffer checksum: identical arithmetic to
    host_fingerprint in wrapped int32."""
    import jax.numpy as jnp
    from jax import lax

    n = v.shape[0]
    i = lax.iota(jnp.int32, n)
    # | 1 matches host_weights: every weight odd, so no single-bit
    # flip can vanish mod 2**32
    w = (i * jnp.int32(_FP_MUL) + jnp.int32(_FP_ADD)) | jnp.int32(1)
    return jnp.sum(v.astype(jnp.int32) * w)


_FP_STATE = None


def state_fingerprint_fn():
    """Scatter-free jit'd checksums of the five persistent problem
    buffers, in FP_STATE_ARRAYS order -> int32[5]. Pinned by the jaxpr
    contracts (no scatters, 32-bit, pow2-bucket hash-stable)."""
    global _FP_STATE
    if _FP_STATE is None:
        import jax
        import jax.numpy as jnp

        @jax.jit  # kschedlint: program=state_fingerprint
        def _fp_state(excess, src, dst, cap, cost):
            return jnp.stack(
                [_device_fp1(x) for x in (excess, src, dst, cap, cost)]
            )

        _FP_STATE = _fp_state
    return _FP_STATE


_FP_PLAN = None


def plan_fingerprint_fn():
    """Scatter-free jit'd checksums of the ten slot-plan tensors, in
    FP_PLAN_ARRAYS order -> int32[10]."""
    global _FP_PLAN
    if _FP_PLAN is None:
        import jax
        import jax.numpy as jnp

        @jax.jit  # kschedlint: program=plan_fingerprint
        def _fp_plan(*tensors):
            return jnp.stack([_device_fp1(x) for x in tensors])

        _FP_PLAN = _fp_plan
    return _FP_PLAN


def device_fingerprints(buffers) -> np.ndarray:
    """Fetch one uint32 checksum per buffer (int32 bit pattern viewed
    unsigned, matching host_fingerprint)."""
    if len(buffers) == len(FP_STATE_ARRAYS):
        fps = state_fingerprint_fn()(*buffers)
    else:
        fps = plan_fingerprint_fn()(*buffers)
    return np.asarray(fps).astype(np.int32).view(np.uint32)


# ---------------------------------------------------------------------------
# seeded device corruption (the chaos poison scatter)
# ---------------------------------------------------------------------------

_CORRUPT = None


def corrupt_fn():
    """The chaos-only poison scatter: flip one bit of one element of a
    device buffer in place. Deliberately NOT a production program (no
    scatter exemption needed — it exists to prove the fingerprints
    catch exactly this class of fault)."""
    global _CORRUPT
    if _CORRUPT is None:
        import jax
        import jax.numpy as jnp

        @jax.jit  # kschedlint: program=corrupt_flip
        def _flip(buf, idx, bit):
            return buf.at[idx].set(buf[idx] ^ (jnp.int32(1) << bit))

        _CORRUPT = _flip
    return _CORRUPT


def apply_device_corruption(resident, spec: Dict) -> None:
    """Apply one injected device-buffer bit flip to a
    DeviceResidentState mirror. `spec` is FaultInjector.
    device_corruption()'s draw: {"array", "index", "bit"}; plan tensors
    are addressed as "p_<name>". The caller must rebind any outstanding
    problem handle afterwards (the flip produces a NEW buffer)."""
    import jax.numpy as jnp

    name = spec["array"]
    attr = {
        "p_arc": "d_p_arc", "p_sign": "d_p_sign",
        "p_src": "d_p_src", "p_dst": "d_p_dst",
    }.get(name, "d_" + name)
    buf = getattr(resident, attr, None)
    if buf is None:
        return  # mirror not built for that family yet: flip has no target
    shape = buf.shape
    if len(shape) > 1:  # sharded [D, Es] plan tensor: flip ONE element
        idx = int(spec["index"]) % int(buf.size)
        flat = buf.reshape(-1)
        new = corrupt_fn()(
            flat, jnp.int32(idx), jnp.int32(int(spec["bit"]) % 31)
        ).reshape(shape)
    else:
        idx = int(spec["index"]) % int(shape[0])
        new = corrupt_fn()(buf, jnp.int32(idx), jnp.int32(int(spec["bit"]) % 31))
    setattr(resident, attr, new)


# ---------------------------------------------------------------------------
# the auditor + divergence repair ladder
# ---------------------------------------------------------------------------


class StateAuditor:
    """Cross-checks a DeviceResidentState mirror against the host
    journal-maintained arrays (the source of truth) via fingerprints,
    and repairs divergence through an escalating ladder.

    Must run at the post-refresh point of a round (host and mirror are
    in sync by construction there); graph/slot-plan mutations between
    refreshes legitimately put the mirror behind and are not audited.
    """

    #: repair rungs this auditor owns, cheapest first; the caller
    #: (solver/placement.py) escalates to full_build when all fail.
    #: No separate plan-rebuild rung: the fingerprints compare device
    #: against HOST truth, so "reupload" makes the mirror exact by
    #: construction — host-side plan damage is a different detector's
    #: job (SlotPlanState.check_invariants) and is healed by the
    #: full_build escalation, which invalidates and rebuilds the plan
    #: layout from the graph.
    RUNGS = ("rescatter", "reupload")

    def __init__(self, resident) -> None:
        self.resident = resident
        self.counts: Counter = Counter()
        # ---- host-twin fingerprint caches ----------------------------
        # At audit_every=1 a naive audit recomputes O(n_cap + m_cap +
        # entry_cap) host checksums every round, re-adding the
        # O(problem-size) host term the delta-sized rounds removed.
        # Problem arrays are never mutated in place (problem() copies
        # per re-materialized group), so identity-keyed caching makes
        # the per-round host cost O(changed groups); plan tensors ARE
        # mutated in place, so their cache keys on (layout_gen,
        # value_version) — bumped by every mutation batch.
        self._fp_state_cache: Dict[str, Tuple] = {}  # name -> (array ref, fp)
        self._fp_plan_cache: Optional[Tuple] = None  # (key, fps list)
        self._fp_warm_cache: Optional[Tuple] = None  # (array ref, fp)
        reg = get_registry()
        self._m_audits = reg.counter(
            "ksched_state_audits_total",
            "device-state integrity audits, by result",
            labelnames=("result",),
        )
        self._m_repairs = reg.counter(
            "ksched_state_repairs_total",
            "divergence repairs, by ladder rung that healed the state",
            labelnames=("rung",),
        )
        self._m_diverged = reg.counter(
            "ksched_state_divergence_total",
            "device buffers observed diverged from the host truth",
            labelnames=("array",),
        )

    # -- expectations ------------------------------------------------------

    def expected_state(self) -> Dict[str, np.ndarray]:
        problem = self.resident.state.problem()
        return {
            "excess": problem.excess.astype(np.int32),
            "src": problem.src,
            "dst": problem.dst,
            "cap": problem.cap,
            "cost": problem.cost.astype(np.int32),
        }

    def _plan_in_sync(self) -> bool:
        plan = self.resident.state.plan
        r = self.resident
        return (
            plan is not None
            and plan.enabled
            and not plan.needs_rebuild
            and r._plan_gen == plan.layout_gen
            and r._plan_ver == plan.value_version
            and not plan.has_pending
        )

    def expected_plan(self) -> Dict[str, np.ndarray]:
        plan = self.resident.state.plan
        return {name: getattr(plan, name) for name in FP_PLAN_ARRAYS}

    # -- audit -------------------------------------------------------------

    def audit(self, warm_flow=None, warm_expected=None) -> List[str]:
        """Fingerprint-compare every in-sync device buffer family
        against its host twin; returns the diverged array names
        (empty = clean). `warm_flow`/`warm_expected` optionally audit
        a solver's carried device flow against its host copy."""
        diverged = self._compare(warm_flow, warm_expected)
        self.counts["audits"] += 1
        if diverged:
            self.counts["divergences"] += 1
            self._m_audits.labels(result="divergence").inc()
            for name in diverged:
                self._m_diverged.labels(array=name).inc()
            self._note_event(diverged)
        else:
            self._m_audits.labels(result="ok").inc()
        return diverged

    def _compare(self, warm_flow=None, warm_expected=None) -> List[str]:
        """The raw fingerprint comparison, counting nothing — repair's
        per-rung re-verification uses this so rung retries can't
        inflate the audit/divergence metrics or duplicate the soltel
        event."""
        r = self.resident
        diverged: List[str] = []
        if r.d_excess is not None:
            dev = device_fingerprints(
                tuple(getattr(r, "d_" + n) for n in FP_STATE_ARRAYS)
            )
            problem = r.state.problem()
            for i, name in enumerate(FP_STATE_ARRAYS):
                arr = getattr(problem, name)
                ref, fp = self._fp_state_cache.get(name, (None, -1))
                if ref is not arr:  # group re-materialized since
                    fp = host_fingerprint(arr)
                    self._fp_state_cache[name] = (arr, fp)
                if int(dev[i]) != fp:
                    diverged.append(name)
        if self._plan_in_sync():
            plan = r.state.plan
            # the mirror owns the program choice: the sharded mirror
            # psums per-shard partials with global-index weights, so
            # both modes compare against the SAME host twins
            dev = r.plan_fingerprints()
            key = (plan.layout_gen, plan.value_version)
            if self._fp_plan_cache is None or self._fp_plan_cache[0] != key:
                self._fp_plan_cache = (
                    key,
                    [
                        host_fingerprint(getattr(plan, name))
                        for name in FP_PLAN_ARRAYS
                    ],
                )
            fps = self._fp_plan_cache[1]
            for i, name in enumerate(FP_PLAN_ARRAYS):
                if int(dev[i]) != fps[i]:
                    diverged.append(name)
        if (
            warm_flow is not None
            and warm_expected is not None
            and warm_flow.shape[0] == len(warm_expected)
        ):
            got = int(np.asarray(_one_fp(warm_flow)).view(np.uint32))
            if self._fp_warm_cache is None or self._fp_warm_cache[0] is not warm_expected:
                self._fp_warm_cache = (warm_expected, host_fingerprint(warm_expected))
            if got != self._fp_warm_cache[1]:
                diverged.append("warm_flow")
        return diverged

    def diffs(self, diverged: List[str]) -> List[IntegrityError]:
        """Bounded per-array diffs for a divergence (fetches the
        diverged buffers; repair-path only)."""
        r = self.resident
        host = self.expected_state()
        plan_host = self.expected_plan() if self._plan_in_sync() else {}
        out = []
        attr = {
            "inv_order": "d_inv", "seg_start": "d_seg",
            "is_start": "d_isstart", "node_first": "d_first",
            "node_last": "d_last", "node_nonempty": "d_nonempty",
        }
        for name in diverged:
            if name == "warm_flow":
                out.append(IntegrityError("warm_flow diverged", array=name))
                continue
            want = host.get(name)
            if want is None:
                want = plan_host.get(name)
                dev = getattr(r, attr.get(name, "d_" + name))
            else:
                dev = getattr(r, "d_" + name)
            got = np.asarray(dev).astype(np.int32)
            if got.ndim > 1:  # sharded [D, Es] stacking of the [E] tensor
                got = got.reshape(-1)
            out.append(bounded_diff(name, got, want.astype(np.int32)))
        return out

    def _note_event(self, diverged: List[str]) -> None:
        from ..obs import soltel

        soltel.note_stall(
            {
                "kind": "state_divergence",
                "arrays": list(diverged),
                "detail": (
                    "device mirror diverged from the host journal truth: "
                    + ", ".join(diverged)
                ),
                "diffs": [e.to_dict() for e in self.diffs(diverged)],
            }
        )

    # -- repair ladder -----------------------------------------------------

    def repair(self, diverged: List[str]) -> str:
        """Escalate through the repair rungs until a re-verification
        (counting nothing — rung retries must not inflate the audit
        metrics) comes back clean; returns the rung that healed the
        state. Raises IntegrityError when every rung fails OR when the
        divergence includes state these rungs cannot reach (the warm
        flow lives on the solver, not the mirror) — the caller then
        owns the full_build escalation, which also drops solver warm
        state via backend.reset()."""
        if "warm_flow" in diverged:
            raise IntegrityError(
                "carried warm flow diverged: no mirror rung can repair "
                "solver-owned state; escalate to full_build (which "
                "resets the solver's warm carry)",
                array="warm_flow",
            )
        plan_dirty = any(n in FP_PLAN_ARRAYS for n in diverged)
        for rung in self.RUNGS:
            if rung == "rescatter" and plan_dirty:
                continue  # row-level rescatter covers problem arrays only
            getattr(self, "_repair_" + rung)(diverged)
            if not self._compare():
                self.counts[f"repair_{rung}"] += 1
                self._m_repairs.labels(rung=rung).inc()
                return rung
        raise IntegrityError(
            "divergence repair ladder exhausted "
            f"(arrays: {', '.join(diverged)}); escalate to full_build",
            array=",".join(diverged),
        )

    def _repair_rescatter(self, diverged: List[str]) -> None:
        """Re-scatter exactly the diverged rows through the existing
        delta program (O(diff), the cheapest rung)."""
        from ..graph.device_export import delta_apply_fn
        import jax.numpy as jnp

        r = self.resident
        host = self.expected_state()
        slots: set = set()
        nodes: set = set()
        for name in diverged:
            dev = np.asarray(getattr(r, "d_" + name))
            bad = np.nonzero(dev != host[name])[0]
            (nodes if name == "excess" else slots).update(int(i) for i in bad)
        arc_rec = r._pack_arcs(np.sort(np.fromiter(slots, np.int32, len(slots))))
        node_rec = r._pack_nodes(np.sort(np.fromiter(nodes, np.int32, len(nodes))))
        (r.d_excess, r.d_src, r.d_dst, r.d_cap, r.d_cost) = delta_apply_fn()(
            r.d_excess, r.d_src, r.d_dst, r.d_cap, r.d_cost,
            jnp.asarray(arc_rec), jnp.asarray(node_rec),
        )
        r._scaled = None

    def _repair_reupload(self, diverged: List[str]) -> None:
        """Full problem re-upload + full plan tensor re-upload from the
        host truth (exact values: placement parity preserved)."""
        r = self.resident
        r._full_upload(r.state.problem(), arcs_too=True)
        r._scaled = None
        if r.state.plan is not None and r.state.plan.enabled:
            r._plan_gen = -1  # force the rebuild-upload path
            r._sync_plan()



_FP_ONE = None


def _one_fp(buf):
    """Single-buffer checksum (the warm-flow audit), cached like the
    other fingerprint programs — a per-call jax.jit wrapper would
    re-trace every audit."""
    global _FP_ONE
    if _FP_ONE is None:
        import jax

        _FP_ONE = jax.jit(_device_fp1)  # kschedlint: program=buffer_fingerprint
    return _FP_ONE(buf)


# ---------------------------------------------------------------------------
# WAL record framing (checkpoint manifests; see runtime/checkpoint.py)
# ---------------------------------------------------------------------------

WAL_MAGIC = b"KSWAL1\n"


class WALCorrupted(RuntimeError):
    """A WAL/manifest stream failed validation. `kind` is one of
    "bad_magic", "truncated", "crc", "seq_gap", "seq_dup" — torn
    writes, dropped records, and duplicated records are DISTINCT,
    so chaos tests can assert the detector classifies each fault."""

    def __init__(self, kind: str, detail: str) -> None:
        super().__init__(f"WAL corrupted ({kind}): {detail}")
        self.kind = kind


def write_records(path: str, records: List[Tuple[str, bytes]]) -> None:
    """Write `(kind, payload)` records as a seq-numbered, CRC-framed
    stream. Written to a temp file and renamed, so a crash mid-write
    leaves either the old manifest or none (a partial new one is only
    reachable through injected torn-write chaos)."""
    tmp = path + ".tmp"
    framed = list(records) + [
        # end-of-stream footer: without it, dropping the FINAL record
        # would read back as a clean shorter stream
        ("__end__", json.dumps({"count": len(records)}).encode()),
    ]
    with open(tmp, "wb") as f:
        f.write(WAL_MAGIC)
        for seq, (kind, payload) in enumerate(framed):
            hdr = json.dumps(
                {"seq": seq, "kind": kind, "len": len(payload),
                 "crc": zlib.crc32(payload)}
            ).encode()
            f.write(struct.pack("<I", len(hdr)))
            f.write(hdr)
            f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_records(path: str) -> List[Tuple[str, bytes]]:
    """Read and VALIDATE a record stream; raises WALCorrupted with a
    distinct kind for each corruption class."""
    with open(path, "rb") as f:
        data = f.read()
    if not data.startswith(WAL_MAGIC):
        raise WALCorrupted("bad_magic", f"{path} is not a ksched WAL/manifest")
    off = len(WAL_MAGIC)
    out: List[Tuple[str, bytes]] = []
    expected_seq = 0
    while off < len(data):
        if off + 4 > len(data):
            raise WALCorrupted("truncated", f"torn frame header at byte {off}")
        (hlen,) = struct.unpack_from("<I", data, off)
        off += 4
        if off + hlen > len(data):
            raise WALCorrupted("truncated", f"torn record header at byte {off}")
        try:
            hdr = json.loads(data[off:off + hlen])
        except ValueError as e:
            raise WALCorrupted("crc", f"unparseable record header: {e}") from e
        off += hlen
        plen = int(hdr["len"])
        if off + plen > len(data):
            raise WALCorrupted(
                "truncated",
                f"record {hdr.get('seq')} payload torn "
                f"({len(data) - off}/{plen} bytes)",
            )
        payload = data[off:off + plen]
        off += plen
        if zlib.crc32(payload) != int(hdr["crc"]):
            raise WALCorrupted("crc", f"record {hdr.get('seq')} failed its CRC")
        seq = int(hdr["seq"])
        if seq < expected_seq:
            raise WALCorrupted("seq_dup", f"record seq {seq} delivered twice")
        if seq > expected_seq:
            raise WALCorrupted(
                "seq_gap", f"record seq {expected_seq} missing (next is {seq})"
            )
        expected_seq += 1
        out.append((str(hdr["kind"]), payload))
    if not out or out[-1][0] != "__end__":
        raise WALCorrupted(
            "truncated", "end-of-stream footer missing (torn tail write)"
        )
    footer = json.loads(out.pop()[1])
    if int(footer.get("count", -1)) != len(out):
        raise WALCorrupted(
            "seq_gap",
            f"footer promises {footer.get('count')} records, stream holds {len(out)}",
        )
    return out


def _raw_frames(data: bytes) -> List[bytes]:
    """Split a stream into raw frame byte strings WITHOUT validation
    (the corruption injector's view)."""
    off = len(WAL_MAGIC)
    frames = []
    while off + 4 <= len(data):
        (hlen,) = struct.unpack_from("<I", data, off)
        end = off + 4 + hlen
        if end > len(data):
            break
        hdr = json.loads(data[off + 4:end])
        end += int(hdr["len"])
        frames.append(data[off:min(end, len(data))])
        off = end
    return frames


def corrupt_wal_file(path: str, mode: str, rng) -> None:
    """Deterministically damage a WAL/manifest file in place — the
    chaos fault classes for checkpoint integrity. `mode`:

    - "wal_drop": remove one middle record (seq gap);
    - "wal_dup": deliver one record twice (seq dup);
    - "wal_torn": truncate the file inside the final record (the torn
      checkpoint write).
    """
    with open(path, "rb") as f:
        data = f.read()
    frames = _raw_frames(data)
    if not frames:
        with open(path, "wb") as f:
            f.write(data[: max(len(data) // 2, 1)])
        return
    if mode == "wal_torn":
        cut = len(data) - 1 - int(rng.integers(0, max(len(frames[-1]) - 1, 1)))
        with open(path, "wb") as f:
            f.write(data[:cut])
        return
    i = int(rng.integers(0, len(frames)))
    if mode == "wal_drop":
        frames.pop(i)
    elif mode == "wal_dup":
        frames.insert(i, frames[i])
    else:
        raise ValueError(f"unknown WAL corruption mode {mode!r}")
    with open(path, "wb") as f:
        f.write(WAL_MAGIC)
        for fr in frames:
            f.write(fr)


# Level-3 registry ownership (ksched_tpu/analysis/program_registry.py)
from ..analysis.program_registry import declare_programs as _declare_programs

_declare_programs(
    __name__,
    "state_fingerprint", "plan_fingerprint", "buffer_fingerprint",
    "corrupt_flip",
)
