"""The solver degradation ladder.

The reference's loop dies with its solver: any solver failure (real
non-convergence, an overflow, a poisoned cost input) kills the round
and the process. Production schedulers degrade instead (Firmament runs
a fallback scheduler when the flow solver misbehaves): here the ladder
tries the configured backend, then steps down through cheaper/safer
rungs (scan-CSR JAX solver, the exact `cpu_ref` oracle), and only when
*every* rung fails raises `LadderExhausted` — which the scheduler
service catches and turns into a NOOP round that keeps the previous
assignments instead of crashing.

The ladder also hosts the chaos seam: a `FaultInjector` (see chaos.py)
can schedule per-rung faults — forced non-convergence, a backend
exception, NaN'd cost inputs — which exercise exactly the paths real
faults take.
"""

from __future__ import annotations

import warnings
from typing import Callable, List, Optional, Tuple

from ..graph.device_export import FlowProblem
from ..obs.metrics import get_registry
from ..solver.base import FlowResult, FlowSolver
from .chaos import ChaosBackendError, FaultInjector, poison_costs

from .integrity import IntegrityError

#: failures a rung may raise that the ladder absorbs: non-convergence /
#: infeasibility (RuntimeError), scaled-cost or potential overflow
#: (OverflowError et al.), rejected inputs (ValueError), and state-
#: integrity failures (IntegrityError — an AssertionError subclass, so
#: it must be named explicitly): the divergence response ladder
#: (runtime/integrity.py) repairs in place, but if a repair itself
#: raises through a solve, the rung steps down and the NOOP round is
#: the documented last rung of the divergence ladder. Anything else
#: (KeyboardInterrupt, MemoryError, bugs raising TypeError) propagates.
DEGRADABLE_ERRORS = (RuntimeError, ValueError, ArithmeticError, IntegrityError)


class LadderExhausted(RuntimeError):
    """Every rung of the degradation ladder failed this round.

    `reasons` carries one STRUCTURED reason per failed rung
    (obs/soltel.failure_reason): the stall detector's verdict with the
    final supersteps of telemetry when the failure was a genuine
    non-convergence, a classified error otherwise — what the flight
    recorder dumps instead of a bare timeout string."""

    def __init__(
        self,
        failures: List[Tuple[str, BaseException]],
        reasons: Optional[List[dict]] = None,
    ) -> None:
        self.failures = failures
        self.reasons = reasons or []
        detail = "; ".join(f"{name}: {err}" for name, err in failures)
        super().__init__(f"all solver rungs failed: {detail}")


class DegradingSolver(FlowSolver):
    """A FlowSolver that tries rungs in order until one converges.

    ``rungs`` is a list of (name, backend_or_factory); factories are
    called lazily on first use so fallback backends (and their jax
    imports/compilations) cost nothing until a fault actually occurs.
    Synchronous on purpose: the ladder must observe the failure before
    the round's deltas are decoded, so it exposes only ``solve`` and
    the placement driver runs it inside the dispatch phase.
    """

    def __init__(
        self,
        rungs: List[Tuple[str, object]],
        injector: Optional[FaultInjector] = None,
    ) -> None:
        if not rungs:
            raise ValueError("degradation ladder needs at least one rung")
        self._rungs: List[Tuple[str, object]] = list(rungs)
        self.injector = injector
        self.degradations_total = 0
        self.last_degradations = 0
        self.last_rung = -1
        self.last_rung_name: Optional[str] = None
        #: structured reasons (obs/soltel.failure_reason) for the rungs
        #: that failed during the LAST solve, in failure order
        self.last_failure_reasons: List[dict] = []
        # obs handles resolve at construction time (scoped_registry works)
        reg = get_registry()
        self._m_degradations = reg.counter(
            "ksched_degradations_total",
            "solver rungs stepped down, by the rung that failed",
            labelnames=("rung",),
        )
        self._m_exhausted = reg.counter(
            "ksched_ladder_exhausted_total",
            "rounds on which every solver rung failed (NOOP rounds)",
        )
        self._m_rung = reg.gauge(
            "ksched_solver_rung",
            "ladder rung that produced the last solve (-1 = none yet)",
        )
        self._m_rung.set(self.last_rung)  # -1 until the first solve lands

    # -- rung access -------------------------------------------------------

    def rung_names(self) -> List[str]:
        return [name for name, _ in self._rungs]

    def _backend(self, i: int) -> FlowSolver:
        name, b = self._rungs[i]
        if not isinstance(b, FlowSolver) and callable(b):
            b = b()
            if not isinstance(b, FlowSolver):
                raise TypeError(f"rung {name!r} factory returned {type(b).__name__}")
            self._rungs[i] = (name, b)
        return b

    @property
    def primary(self) -> FlowSolver:
        """The configured (first-rung) backend."""
        return self._backend(0)

    # -- FlowSolver --------------------------------------------------------

    def _begin_solve(self) -> List[Tuple[str, BaseException]]:
        self.last_degradations = 0
        self.last_rung = -1
        self.last_rung_name = None
        self.last_failure_reasons = []
        self.last_telemetry = None
        return []

    def _rung_problem(self, i: int, name: str, problem: FlowProblem) -> FlowProblem:
        """Apply this rung's scheduled chaos fault (if any) — raising
        for exception/nonconvergence faults, poisoning for nan_cost."""
        fault = self.injector.solver_fault(i) if self.injector else None
        if fault == "exception":
            raise ChaosBackendError(f"chaos: injected backend exception ({name})")
        if fault == "nonconverge":
            raise RuntimeError(f"chaos: forced non-convergence ({name})")
        if fault == "nan_cost":
            return poison_costs(problem)
        return problem

    def _note_rung_failure(
        self,
        i: int,
        name: str,
        e: BaseException,
        failures: List[Tuple[str, BaseException]],
    ) -> None:
        from ..obs import soltel

        failures.append((name, e))
        # structured reason instead of a bare timeout: the stall
        # detector's verdict (+ the final supersteps of telemetry)
        # lands in the soltel ring that every flight dump embeds, and
        # rides LadderExhausted.reasons
        reason = soltel.failure_reason(name, e)
        self.last_failure_reasons.append(
            soltel.note_stall(reason, getattr(e, "telemetry", None))
        )
        self.degradations_total += 1
        self.last_degradations += 1
        self._m_degradations.labels(rung=name).inc()
        nxt = self._rungs[i + 1][0] if i + 1 < len(self._rungs) else None
        warnings.warn(
            f"solver rung {name!r} failed "
            f"({reason.get('kind', 'error')}: {e}); "
            + (f"degrading to {nxt!r}" if nxt else "ladder exhausted"),
            RuntimeWarning,
            stacklevel=3,
        )

    def _finish_rung(self, i: int, name: str) -> None:
        self.last_rung = i
        self.last_rung_name = name
        self._m_rung.set(i)

    def _solve_from(
        self,
        start: int,
        problem: FlowProblem,
        failures: List[Tuple[str, BaseException]],
    ) -> FlowResult:
        for i in range(start, len(self._rungs)):
            name = self._rungs[i][0]
            try:
                p = self._rung_problem(i, name, problem)
                # solve_traced: each rung attempt — including a failing
                # one — is a nested backend_solve span in the trace
                result = self._backend(i).solve_traced(p)
            except DEGRADABLE_ERRORS as e:
                self._note_rung_failure(i, name, e, failures)
                continue
            self._finish_rung(i, name)
            return result
        self._m_exhausted.inc()
        raise LadderExhausted(failures, reasons=list(self.last_failure_reasons))

    def solve(self, problem: FlowProblem) -> FlowResult:
        return self._solve_from(0, problem, self._begin_solve())

    # -- pipelined dispatch ------------------------------------------------

    def solve_async(self, problem: FlowProblem):
        """Dispatch the CONFIGURED rung without synchronizing, so a
        pipelined round can overlap host work with the in-flight solve.
        Any rung failure — at dispatch or at complete() — degrades
        through the remaining rungs SYNCHRONOUSLY inside complete():
        the pipelined loop falls back to the synchronous path on a rung
        failure rather than attempting to re-pipeline a degraded round.
        Fault draws, degradation counters, and the failure-reason ring
        behave exactly as in solve() (same per-round injector plan,
        same rung order)."""
        failures = self._begin_solve()
        name = self._rungs[0][0]
        try:
            p = self._rung_problem(0, name, problem)
            b = self._backend(0)
            if hasattr(b, "solve_async"):
                return (problem, "pending", b.solve_async(p), failures)
            return (problem, "done", b.solve_traced(p), failures)
        except DEGRADABLE_ERRORS as e:
            self._note_rung_failure(0, name, e, failures)
            return (problem, "failed", None, failures)

    def complete(self, token) -> FlowResult:
        """Synchronize a solve_async dispatch; on failure, degrade
        through the remaining rungs synchronously."""
        problem, kind, payload, failures = token
        if kind == "done":
            self._finish_rung(0, self._rungs[0][0])
            return payload
        if kind == "pending":
            name = self._rungs[0][0]
            b = self._backend(0)
            try:
                result = b.complete(payload)
            except DEGRADABLE_ERRORS as e:
                self._note_rung_failure(0, name, e, failures)
            else:
                self._finish_rung(0, name)
                # async completions bypass solve_traced, so the caller
                # (solver/placement.py) publishes solver-interior
                # telemetry from last_telemetry — surface the rung's
                self.last_telemetry = getattr(b, "last_telemetry", None)
                return result
        return self._solve_from(1, problem, failures)

    def reset(self) -> None:
        # only instantiated rungs carry warm state worth dropping
        for _, b in self._rungs:
            if isinstance(b, FlowSolver):
                b.reset()

    # -- trace plumbing ----------------------------------------------------

    @property
    def last_iterations(self) -> int:
        """Solver effort of the rung that actually produced the round
        (RoundTracer reads this through the placement driver)."""
        if self.last_rung < 0:
            return 0
        b = self._rungs[self.last_rung][1]
        return getattr(b, "last_iterations", 0) or getattr(b, "last_supersteps", 0)


def build_degradation_ladder(
    configured: FlowSolver,
    configured_name: str = "configured",
    injector: Optional[FaultInjector] = None,
    make_backend: Optional[Callable[[str], FlowSolver]] = None,
) -> DegradingSolver:
    """configured backend → scan-CSR JAX solver → cpu_ref oracle.

    Rungs already covered by the configured backend are skipped (a
    configured "jax" does not get a second jax rung). Fallback rungs are
    lazy factories: no jax import or compile until a degradation fires.
    """
    if make_backend is None:
        from ..solver.select import make_backend as make_backend_default

        make_backend = make_backend_default
    rungs: List[Tuple[str, object]] = [(configured_name, configured)]
    cls = type(configured).__name__
    if cls not in ("JaxSolver",) and configured_name != "jax":
        rungs.append(("jax", lambda: make_backend("jax")))
    if cls not in ("ReferenceSolver",) and configured_name != "ref":
        rungs.append(("cpu_ref", lambda: make_backend("ref")))
    return DegradingSolver(rungs, injector=injector)
