"""Seeded, deterministic fault injection (the chaos harness).

Firmament and Borg both stress that cluster schedulers live or die by
how they ride out control-plane blips, silent machines, and solver
failures — and the only way to *test* that is to inject those faults on
a reproducible schedule. Everything here is driven by independent
`numpy` RNG streams spawned from one seed, so the same seed produces
the same fault schedule, fault for fault, across runs:

- `ChaosPolicy` — the knob set (probabilities, durations, kinds);
- `FaultInjector` — draws the schedule and counts every injected fault
  (the soak asserts these totals against the per-round `RoundRecord`
  counters, so no fault can go unobserved);
- `ChaosClusterAPI` — wraps any `ClusterAPI` with control-plane faults
  that stay deterministic under a single-threaded driver: API outages
  (batches suppressed, events held back), dropped binding POSTs (the
  pod re-surfaces, as a real watch would re-list it);
- HTTP-shaped faults (`http_fault`) for `cluster/fake_apiserver.py`'s
  hermetic fault hook: 5xx, hangs, latency spikes over real sockets.

Solver faults (forced non-convergence, backend exceptions, NaN'd cost
inputs) are consumed by `runtime/degrade.py`'s degradation ladder via
`solver_fault(rung)`.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..cluster.api import Binding, ClusterAPI, NodeEvent, PodEvent
from ..obs.metrics import get_registry

#: solver fault kinds the injector can schedule (see degrade.py)
SOLVER_FAULT_KINDS = ("nonconverge", "exception", "nan_cost")


class ChaosBackendError(RuntimeError):
    """The injected stand-in for an arbitrary backend exception."""


@dataclass(frozen=True)
class ChaosPolicy:
    """Fault-schedule knobs. All probabilities default to 0 (inert).

    Per-round draws: `api_outage_prob` starts a control-plane outage
    lasting `api_outage_rounds` (min, max) rounds; `machine_flap_prob`
    (per machine per round) silences a machine's heartbeats for
    `machine_flap_rounds` rounds; `solver_fault_prob` faults the
    configured backend rung with a kind from `solver_fault_kinds`, and
    `solver_total_outage_prob` faults *every* rung (forcing a NOOP
    round). Per-event draws: `binding_drop_prob` on each binding POST;
    `http_error_prob` / `http_hang_prob` / `http_latency_prob` on each
    HTTP request through the fake API server's fault hook.
    """

    seed: int = 0
    # control-plane outages (whole rounds of empty batches)
    api_outage_prob: float = 0.0
    api_outage_rounds: Tuple[int, int] = (1, 3)
    # per-request HTTP faults (fake_apiserver hook)
    http_error_prob: float = 0.0
    http_hang_prob: float = 0.0
    http_latency_prob: float = 0.0
    http_latency_s: Tuple[float, float] = (0.02, 0.1)
    http_hang_s: float = 1.0
    # binding-POST drops
    binding_drop_prob: float = 0.0
    # machine heartbeat flaps
    machine_flap_prob: float = 0.0
    machine_flap_rounds: Tuple[int, int] = (2, 5)
    # solver faults
    solver_fault_prob: float = 0.0
    solver_fault_kinds: Tuple[str, ...] = SOLVER_FAULT_KINDS
    solver_total_outage_prob: float = 0.0
    # state-corruption faults (runtime/integrity.py): a per-solve draw
    # flips one bit of one persistent device buffer via a seeded poison
    # scatter — the fault class the fingerprint audit must catch the
    # round it happens
    device_corrupt_prob: float = 0.0
    device_corrupt_arrays: Tuple[str, ...] = (
        "excess", "src", "dst", "cap", "cost", "p_sign",
    )
    # checkpoint-corruption faults: at each kill-and-restore the soak
    # draws one of wal_drop / wal_dup / wal_torn (dropped WAL record,
    # duplicated record, torn checkpoint write) against the warm
    # manifest; restore must DETECT it and fall back to cold replay
    wal_corrupt_prob: float = 0.0
    wal_corrupt_kinds: Tuple[str, ...] = ("wal_drop", "wal_dup", "wal_torn")

    def __post_init__(self) -> None:
        bad = [k for k in self.solver_fault_kinds if k not in SOLVER_FAULT_KINDS]
        if bad:
            raise ValueError(
                f"unknown solver fault kinds {bad}; want a subset of "
                f"{SOLVER_FAULT_KINDS}"
            )


class FaultInjector:
    """Draws the fault schedule from independent per-domain RNG streams
    and counts every fault actually injected.

    Separate streams per fault domain (outages, bindings, solver,
    flaps, HTTP) keep the schedule deterministic even when one domain's
    consumption rate varies — e.g. HTTP request counts depend on
    wall-clock poll timing, but that cannot perturb the solver-fault or
    flap schedule. `begin_round` advances round-granular draws;
    per-event draws happen at the injection site. `quiesce()` stops all
    new faults (the soak's cooldown, so dropped bindings settle before
    final-state comparison).
    """

    def __init__(self, policy: ChaosPolicy) -> None:
        self.policy = policy
        # streams 0-4 predate the corruption domains; spawn keys are
        # sequential, so appending streams keeps every pre-existing
        # fixed-seed fault schedule bit-identical
        streams = np.random.SeedSequence(policy.seed).spawn(7)
        self._rng_outage = np.random.default_rng(streams[0])
        self._rng_bind = np.random.default_rng(streams[1])
        self._rng_solver = np.random.default_rng(streams[2])
        self._rng_flap = np.random.default_rng(streams[3])
        self._rng_http = np.random.default_rng(streams[4])
        self._rng_corrupt = np.random.default_rng(streams[5])
        self._rng_wal = np.random.default_rng(streams[6])
        self.counters: Counter = Counter()
        # live twin of `counters` on the obs registry: the obs smoke
        # reconciles this against the tracer's per-round attribution
        # (handles resolve at construction time; scoped_registry works)
        self._m_injected = get_registry().counter(
            "ksched_chaos_injected_total",
            "faults injected by the chaos harness, by kind",
            labelnames=("kind",),
        )
        self.round_index = -1
        self._outage_rounds_left = 0
        #: this round's solver plan: {} | {rung 0: kind} | {all rungs: kind}
        self._solver_plan: Dict[int, str] = {}
        self._solver_plan_all = False
        self._flaps: Dict[int, int] = {}  # machine key -> silent rounds left
        self._quiesced = False

    def _count(self, kind: str, n: int = 1) -> None:
        """Count one injected fault, in both accounting surfaces: the
        deterministic Counter (soak determinism asserts compare it
        bit-for-bit) and the live metrics registry."""
        self.counters[kind] += n
        self._m_injected.labels(kind=kind).inc(n)

    # -- lifecycle ---------------------------------------------------------

    def quiesce(self) -> None:
        """Stop injecting: active outages/flaps end, no new draws fire."""
        self._quiesced = True
        self._outage_rounds_left = 0
        self._solver_plan = {}
        self._flaps.clear()

    def begin_round(self, round_index: int) -> None:
        """Advance round-granular schedules (outage windows, the solver
        fault plan). Call once per scheduler round, before polling."""
        self.round_index = round_index
        if self._outage_rounds_left > 0:
            self._outage_rounds_left -= 1
        self._solver_plan = {}
        self._solver_plan_all = False
        if self._quiesced:
            return
        p = self.policy
        if (
            self._outage_rounds_left == 0
            and p.api_outage_prob > 0
            and self._rng_outage.random() < p.api_outage_prob
        ):
            lo, hi = p.api_outage_rounds
            self._outage_rounds_left = int(self._rng_outage.integers(lo, hi + 1))
        if p.solver_total_outage_prob > 0 and (
            self._rng_solver.random() < p.solver_total_outage_prob
        ):
            kind = str(self._rng_solver.choice(p.solver_fault_kinds))
            self._solver_plan_all = True
            self._solver_plan = {0: kind}
        elif p.solver_fault_prob > 0 and (
            self._rng_solver.random() < p.solver_fault_prob
        ):
            self._solver_plan = {0: str(self._rng_solver.choice(p.solver_fault_kinds))}

    # -- control-plane faults ---------------------------------------------

    def outage_active(self) -> bool:
        return self._outage_rounds_left > 0

    def note_outage_round(self) -> None:
        """Count one suppressed batch poll (called by ChaosClusterAPI)."""
        self._count("api_outage_round")

    def drop_binding(self) -> bool:
        if self._quiesced or self.policy.binding_drop_prob <= 0:
            return False
        if self._rng_bind.random() < self.policy.binding_drop_prob:
            self._count("binding_drop")
            return True
        return False

    # -- machine heartbeat flaps ------------------------------------------

    def machine_silent(self, machine_key: int) -> bool:
        """Whether this machine's heartbeat is suppressed this round.
        Call once per machine per round (the draw advances per call)."""
        left = self._flaps.get(machine_key, 0)
        if left > 0:
            self._flaps[machine_key] = left - 1
            self._count("machine_flap_round")
            return True
        if self._quiesced or self.policy.machine_flap_prob <= 0:
            return False
        if self._rng_flap.random() < self.policy.machine_flap_prob:
            lo, hi = self.policy.machine_flap_rounds
            self._flaps[machine_key] = int(self._rng_flap.integers(lo, hi + 1)) - 1
            self._count("machine_flap")
            self._count("machine_flap_round")
            return True
        return False

    # -- solver faults (consumed by the degradation ladder) ---------------

    def solver_fault(self, rung_index: int) -> Optional[str]:
        """The fault kind scheduled for this rung this round, or None.
        Counted at injection time, so un-consulted plans (e.g. rounds
        with no solve) never inflate the totals."""
        if self._solver_plan_all:
            kind = self._solver_plan.get(0)
        else:
            kind = self._solver_plan.get(rung_index)
        if kind is not None:
            self._count(f"solver_{kind}")
        return kind

    # -- state-corruption faults (runtime/integrity.py) -------------------

    def device_corruption(
        self, n_cap: int, m_cap: int, available=None
    ) -> Optional[dict]:
        """One per-solve device-buffer bit-flip draw: None, or
        {"array", "index", "bit"} for integrity.apply_device_corruption.
        Node-space arrays index within n_cap, arc/plan-space within
        m_cap (the applier re-mods against the live buffer extent, so
        plan tensors sized 2*m_cap stay in range). ``available`` narrows
        the targets to buffers that exist right now (the plan mirror is
        built lazily) — availability is state-driven and deterministic,
        so the schedule stays reproducible. Counted as
        `device_bit_flip` at injection time; a draw with no live target
        injects (and counts) nothing."""
        if self._quiesced or self.policy.device_corrupt_prob <= 0:
            return None
        if self._rng_corrupt.random() >= self.policy.device_corrupt_prob:
            return None
        arrays = tuple(
            a for a in self.policy.device_corrupt_arrays
            if available is None or a in available
        )
        if not arrays:
            return None
        name = str(arrays[int(self._rng_corrupt.integers(0, len(arrays)))])
        extent = n_cap if name == "excess" else m_cap
        spec = {
            "array": name,
            "index": int(self._rng_corrupt.integers(0, max(extent, 1))),
            "bit": int(self._rng_corrupt.integers(0, 31)),
        }
        self._count("device_bit_flip")
        return spec

    def checkpoint_corruption(self) -> Optional[Tuple[str, int]]:
        """One per-checkpoint WAL corruption draw: None, or
        (kind, seed) where kind is wal_drop/wal_dup/wal_torn and seed
        feeds integrity.corrupt_wal_file's deterministic byte choice.
        Counted by kind at injection time."""
        if self._quiesced or self.policy.wal_corrupt_prob <= 0:
            return None
        if self._rng_wal.random() >= self.policy.wal_corrupt_prob:
            return None
        kinds = self.policy.wal_corrupt_kinds
        kind = str(kinds[int(self._rng_wal.integers(0, len(kinds)))])
        self._count(kind)
        return kind, int(self._rng_wal.integers(0, 1 << 31))

    # -- HTTP faults (the fake API server hook) ---------------------------

    def http_fault(self, route: str) -> Optional[dict]:
        """Per-request fault draw for the hermetic API server. Returns
        None or {"kind": "error"|"hang"|"latency", ...}. The side-door
        /_test routes are never faulted (the test driver must always be
        able to steer)."""
        if self._quiesced or route.startswith("_test"):
            return None
        p = self.policy
        r = self._rng_http.random()
        if r < p.http_error_prob:
            self._count("http_error")
            return {"kind": "error", "code": 503}
        r -= p.http_error_prob
        if r < p.http_hang_prob:
            self._count("http_hang")
            return {"kind": "hang", "seconds": p.http_hang_s}
        r -= p.http_hang_prob
        if r < p.http_latency_prob:
            lo, hi = p.http_latency_s
            self._count("http_latency")
            return {
                "kind": "latency",
                "seconds": float(lo + (hi - lo) * self._rng_http.random()),
            }
        return None

    # -- accounting --------------------------------------------------------

    def snapshot(self) -> Dict[str, int]:
        return dict(self.counters)


def delta_counters(before: Dict[str, int], after: Dict[str, int]) -> Dict[str, int]:
    """after - before, keeping only keys that moved (for RoundRecord).
    Counters are monotone, so Counter subtraction (positive-only) is it."""
    return dict(Counter(after) - Counter(before))


def poison_costs(problem):
    """A copy of the FlowProblem with NaN'd cost inputs — the chaos
    stand-in for a cost model emitting garbage. Backends must *reject*
    this (non-finite validation) rather than solve on wrapped-int
    nonsense; every backend shares solver/base.check_finite_costs."""
    cost = np.asarray(problem.cost, dtype=np.float64).copy()
    if len(cost):
        cost[len(cost) // 2] = np.nan
    return dataclasses.replace(problem, cost=cost)


class ChaosClusterAPI(ClusterAPI):
    """A fault-injecting decorator over any ClusterAPI.

    Deterministic under a single-threaded driver (the chaos soak):
    during an injected API outage, batch polls return empty without
    draining — queued events are delivered when the outage ends,
    exactly as informers re-list after an API-server blip. A dropped
    binding POST re-surfaces its pod on the next batch (the pending
    listing would still show it), so the service's re-deliver/re-post
    machinery is exercised end to end.
    """

    def __init__(self, inner: ClusterAPI, injector: FaultInjector) -> None:
        self.inner = inner
        self.injector = injector
        self._last_event: Dict[str, PodEvent] = {}
        self._resurfaced: List[PodEvent] = []
        self.counters: Counter = Counter()

    # -- producer passthrough ---------------------------------------------

    def submit_pod(self, pod: PodEvent) -> None:
        self.inner.submit_pod(pod)

    def submit_node(self, node: NodeEvent) -> None:
        self.inner.submit_node(node)

    # -- consumer side -----------------------------------------------------

    def get_pod_batch(self, timeout_s: float) -> List[PodEvent]:
        # Blocking contract: "[] only on close" — an injected outage
        # must NOT surface as an empty batch here, or a blocking
        # consumer (e.g. the --one-shot main path) would misread a
        # 1-3 round outage as shutdown. Outage suppression lives in
        # poll_pod_batch, the hardened loop's closed-vs-outage path.
        if self._resurfaced:
            # Already-deliverable pods must not wait behind the inner
            # blocking call (which only wakes on a brand-new pod or
            # close — starving them, and on close dropping them).
            out, self._resurfaced = self._resurfaced, []
            return out
        return self._with_resurfaced(self.inner.get_pod_batch(timeout_s))

    def poll_pod_batch(self, timeout_s: float) -> List[PodEvent]:
        if self.injector.outage_active():
            self.injector.note_outage_round()
            return []
        return self._with_resurfaced(self.inner.poll_pod_batch(timeout_s))

    def _with_resurfaced(self, batch: List[PodEvent]) -> List[PodEvent]:
        for pod in batch:
            self._last_event[pod.pod_id] = pod
        if self._resurfaced:
            batch = self._resurfaced + batch
            self._resurfaced = []
        return batch

    def get_node_batch(self, timeout_s: float) -> List[NodeEvent]:
        return self.inner.get_node_batch(timeout_s)

    def assign_bindings(self, bindings: List[Binding]) -> None:
        kept = []
        for b in bindings:
            if self.injector.drop_binding():
                # the POST "failed": the pod is still pending server-side
                # and re-enters the next batch; the service must re-post
                event = self._last_event.get(b.pod_id, PodEvent(pod_id=b.pod_id))
                self._resurfaced.append(event)
                self.counters["binding_reposts_pending"] += 1
            else:
                kept.append(b)
        if kept:
            self.inner.assign_bindings(kept)

    def close(self) -> None:
        self.inner.close()

    def is_closed(self) -> bool:
        return self.inner.is_closed()

    def bindings(self):
        return self.inner.bindings()

    def stats(self) -> Dict[str, int]:
        return dict(self.counters)
