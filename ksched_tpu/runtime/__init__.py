"""Runtime auxiliary subsystems: failure detection, checkpoint/resume,
round tracing.

The reference carries the *fields* for all three (heartbeats on
ResourceStatus/TaskDescriptor, ResourceState LOST, ad hoc round timing)
but implements none of them (SURVEY §5). Here they are first-class.
"""

from .checkpoint import (
    load_bulk_checkpoint,
    load_device_checkpoint,
    restore_scheduler,
    save_bulk_checkpoint,
    save_device_checkpoint,
    save_scheduler,
)
from .failure import HeartbeatMonitor
from .trace import RoundTracer

__all__ = [
    "HeartbeatMonitor",
    "RoundTracer",
    "load_bulk_checkpoint",
    "load_device_checkpoint",
    "restore_scheduler",
    "save_bulk_checkpoint",
    "save_device_checkpoint",
    "save_scheduler",
]
