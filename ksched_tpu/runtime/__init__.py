"""Runtime auxiliary subsystems: failure detection, checkpoint/resume,
round tracing, fault injection (chaos), and solver degradation.

The reference carries the *fields* for the first three (heartbeats on
ResourceStatus/TaskDescriptor, ResourceState LOST, ad hoc round timing)
but implements none of them (SURVEY §5). Here they are first-class —
and the chaos harness (chaos.py) plus the degradation ladder
(degrade.py) make the failure paths deterministic to exercise.
"""

from .chaos import (
    ChaosBackendError,
    ChaosClusterAPI,
    ChaosPolicy,
    FaultInjector,
)
from .checkpoint import (
    load_bulk_checkpoint,
    load_device_checkpoint,
    restore_scheduler,
    save_bulk_checkpoint,
    save_device_checkpoint,
    save_scheduler,
)
from .degrade import DegradingSolver, LadderExhausted, build_degradation_ladder
from .failure import HeartbeatMonitor, RoundWatchdog
from .trace import RoundTracer

__all__ = [
    "ChaosBackendError",
    "ChaosClusterAPI",
    "ChaosPolicy",
    "DegradingSolver",
    "FaultInjector",
    "HeartbeatMonitor",
    "LadderExhausted",
    "RoundTracer",
    "RoundWatchdog",
    "build_degradation_ladder",
    "load_bulk_checkpoint",
    "load_device_checkpoint",
    "restore_scheduler",
    "save_bulk_checkpoint",
    "save_device_checkpoint",
    "save_scheduler",
]
