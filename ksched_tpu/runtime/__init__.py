"""Runtime auxiliary subsystems: failure detection, checkpoint/resume,
round tracing, fault injection (chaos), and solver degradation.

The reference carries the *fields* for the first three (heartbeats on
ResourceStatus/TaskDescriptor, ResourceState LOST, ad hoc round timing)
but implements none of them (SURVEY §5). Here they are first-class —
and the chaos harness (chaos.py) plus the degradation ladder
(degrade.py) make the failure paths deterministic to exercise.
"""

from .chaos import (
    ChaosBackendError,
    ChaosClusterAPI,
    ChaosPolicy,
    FaultInjector,
)
from .checkpoint import (
    CheckpointDamaged,
    CheckpointError,
    CheckpointMissing,
    CheckpointVersionError,
    load_bulk_checkpoint,
    load_device_checkpoint,
    load_warm_manifest,
    restore_scheduler,
    save_bulk_checkpoint,
    save_device_checkpoint,
    save_scheduler,
    save_warm_manifest,
)
from .degrade import DegradingSolver, LadderExhausted, build_degradation_ladder
from .integrity import IntegrityError, StateAuditor, WALCorrupted
from .failure import HeartbeatMonitor, RoundWatchdog
from .trace import RoundTracer

__all__ = [
    "ChaosBackendError",
    "ChaosClusterAPI",
    "ChaosPolicy",
    "CheckpointDamaged",
    "CheckpointError",
    "CheckpointMissing",
    "CheckpointVersionError",
    "DegradingSolver",
    "FaultInjector",
    "HeartbeatMonitor",
    "IntegrityError",
    "LadderExhausted",
    "RoundTracer",
    "RoundWatchdog",
    "StateAuditor",
    "WALCorrupted",
    "build_degradation_ladder",
    "load_bulk_checkpoint",
    "load_device_checkpoint",
    "load_warm_manifest",
    "restore_scheduler",
    "save_bulk_checkpoint",
    "save_device_checkpoint",
    "save_scheduler",
    "save_warm_manifest",
]
